#!/usr/bin/env python
"""Service throughput flood: jobs/sec at fixed tail latency, fused vs
unfused — the ISSUE 6 success metric.

The north star is thousands of SMALL concurrent mines, so the number
that matters is not single-job wall but how many jobs/sec the service
sustains and what the p99 submitter sees.  This harness floods an
in-process ``Master`` (the real admission queue, worker pool, devcache
and engines — everything but the HTTP framing, which overload_smoke
already exercises) with N small mixed-priority TSR mines over a pool of
distinct datasets, twice:

- **unfused**: cross-job fusion off — every job plans and dispatches
  its own launches (the pre-ISSUE-6 service);
- **fused**: the service/fusion.py broker on, at the production window
  defaults — concurrent jobs' candidate waves co-schedule into shared
  super-batched launches.

and reports jobs/sec, p50/p99 client-observed latency (median of 3
timed floods per mode — this box is shared, single walls are noise),
total device launches, and STRICT per-job parity (every fused job's
rule set must be byte-identical to its unfused run — fusion is a
scheduling change, not a semantics change).  A third,
timing-independent phase lines jobs up in a held window and asserts
the launch actually fused cross-job.

Two speedup numbers, deliberately separate:

- ``speedup_jobs_per_sec``: measured CPU wall ratio.  The CPU backend
  executes concurrent unfused launches IN PARALLEL across host cores,
  so launch consolidation is structurally underrewarded here — this
  number is honest but hardware-pessimistic.
- ``modeled_device_dispatch``: the broker's actual launches/traffic vs
  its tallied solo alternative (``alt_solo_*``), priced by the
  committed KERNELS.json cost model (``estimate_seconds``) where a
  device launch costs DISPATCH_SEC — the bill a serial accelerator
  pays.  This is the repo's own EWMA-calibrated arithmetic, the same
  terms the fusion decision itself trades off.

Wall-clock numbers are REPORTED, never compared (bench_smoke's rule:
walls are machine truths, not commitments); the committed
``BENCH_THROUGHPUT.json`` pins the structural expectations — parity,
cross-job fusion observed, modeled device-dispatch speedup >= 2, no
degrades/sheds — that must hold on any machine.  ``--update`` rewrites
it.  ``--jobs N`` / ``--workers K`` override the flood size for
hardware runs.

Usage: scripts/throughput_smoke.sh [--update]   (pins JAX_PLATFORMS=cpu,
hard timeout like overload_smoke)
"""

from __future__ import annotations

import json
import os
import sys
import time

EXPECT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_THROUGHPUT.json")

# structural fields diffed against the committed expectations (walls and
# ratios are reported alongside but never compared).  slo_consistent
# (ISSUE 9): the service's own /admin/slo sliding-window p99 must agree
# with this harness's offline client-observed p99 to within an order of
# magnitude — the structural claim that the observable SLO layer
# measures the same thing the bench does, not a wall comparison.
# usage_conserved (ISSUE 19): the fused flood re-runs with [usage] on
# and the per-tenant attribution counters must move by EXACTLY the
# broker's own launch/traffic deltas — the conservation invariant over
# fused waves, the forced cross-job window and degraded re-dispatches.
COMPARED = ("jobs", "parity", "forced_cross_job", "modeled_2x",
            "degraded", "sheds", "failures", "slo_consistent",
            "usage_conserved")

# --mix tenants (ISSUE 13): the elastic-control-plane success metric —
# a 2-replica fleet with weighted-fair admission, one flooding tenant
# and two background tenants (equal weights).  Structural guards: each
# background tenant's served-jobs/s >= 0.5x its weight-fair share of
# the fleet's throughput AND its p99 within 2x of its solo run (+0.25s
# additive slack — walls are noisy, the guard catches starvation, not
# jitter); a forced scale-down mid-flood drains one replica with ZERO
# lost or duplicated jobs and byte-exact per-dataset parity.
TENANTS_COMPARED = ("tenants_jobs", "tenants_parity",
                    "tenants_fair_share_ok", "tenants_p99_ok",
                    "tenants_drain_ok")

# --mix zipf (ISSUE 12): the result-reuse tier's success metric — a
# realistic zipf-distributed request mix (hot datasets + dominated
# parameter variants), cold vs cached, with structural guards: per-
# request parity against the cold baseline, cache-hit ratio >= 0.5,
# served-jobs/s speedup >= 2x, and NO cold-mine p99 regression (cold
# requests in cached mode stay within a generous 3x envelope of the
# baseline p99 — walls are noisy on shared boxes, the guard catches
# order-of-magnitude admission-path regressions, not jitter).
# zipf_usage_conserved (ISSUE 19): every launch deposited while [usage]
# is on lands in exactly one finished job's settled usage block (served
# and coalesced requests bill zero), and the cached phase credits
# avoided device-seconds off the hot set.
ZIPF_COMPARED = ("zipf_jobs", "zipf_parity", "zipf_hit_ratio_ok",
                 "zipf_speedup_2x", "no_p99_regression_cold",
                 "zipf_usage_conserved")

# --mix engines (ISSUE 15): the SPAM-engine + planner success metric —
# the same pattern-mine flood run per engine route (SPADE_TPU vs
# SPAM_TPU) over a DENSE dataset pool, plus an AUTO flood over a mixed
# dense+sparse pool.  Structural guards: byte parity per dataset across
# every route, AUTO routes every dense job to SPAM_TPU and every
# sparse job to SPADE_TPU (never SPAM below the calibrated crossover),
# zero sheds/failures.  Walls (jobs/s per engine) are reported next to
# them, never compared — and the existing default/zipf/tenants rows
# are untouched (this mix only ADDS keys).
ENGINES_COMPARED = ("engines_jobs", "engines_parity", "engines_auto_ok",
                    "engines_failures", "engines_sheds")

# --mix hybrid (ISSUE 16): the density-adaptive vertical store's success
# metric — the SAME mixed-density SPAM flood run three times with the
# planner's per-item representation routing pinned differently
# ([planner] representation = auto | bitmap | idlist) at a crossover
# that actually splits the alphabet.  Structural guards: byte-exact
# per-dataset parity across ALL THREE representation modes (the dEclat
# identity sup = parent - |diffset| and the id-list join are exact, not
# approximate), the auto flood genuinely ran a HYBRID store (rep_dense
# > 0 AND rep_idlist > 0, with diffset nodes + pair launches observed)
# while the pins ran uniform stores, zero sheds/failures.  Walls
# (jobs/s per mode, hybrid-vs-best-fixed ratio) are reported next to
# the guards, never compared — CPU walls on a shared box say nothing
# about the TPU writeback the fused prune kernel saves.
HYBRID_COMPARED = ("hybrid_jobs", "hybrid_parity", "hybrid_store_ok",
                   "hybrid_failures", "hybrid_sheds")

# --mix predict (ISSUE 17): the prediction-serving-plane success metric
# — a concurrent /predict flood (with background train jobs mining at
# the same time: the mixed read+write shape the read plane exists for)
# run twice, micro-batch window ON (same-artifact requests fuse into
# scoring waves) vs OFF (every request launches solo).  Structural
# guards: byte parity of EVERY flood response against the brute-force
# host oracle over the served rule set, modeled device-dispatch
# predictions/s fused >= 2x unfused (actual wave/launch counts from
# the timed floods priced by the committed DISPATCH_SEC cost-model
# constant — the same arbiter as the mining mix's ``modeled_2x``: on
# this CPU backend concurrent solo launches execute in parallel across
# host cores, so the WALL ratio structurally underrewards launch
# consolidation), a genuinely fused (>= 2 request) wave observed in
# every timed fused flood, zero failures.  Walls (predictions/s, p99)
# are reported next to the guards, never compared — re-measure on
# hardware per ROADMAP item 5.
PREDICT_COMPARED = ("predict_requests", "predict_parity",
                    "predict_fused_2x", "predict_fused_waves_ok",
                    "predict_failures")

N_JOBS = int(os.environ.get("SPARKFSM_TP_JOBS", "48"))
N_WORKERS = int(os.environ.get("SPARKFSM_TP_WORKERS", "8"))
N_RUNS = int(os.environ.get("SPARKFSM_TP_RUNS", "3"))
N_SEQ = 90
N_DATASETS = 8
PRIORITIES = ("high", "normal", "low")
DEADLINE_S = 300.0


def _datasets():
    from spark_fsm_tpu.data.synth import synthetic_db

    # one geometry (n_sequences equal -> one fusion shape key), distinct
    # contents: the flood is many DIFFERENT small mines, not one cached
    return [synthetic_db(seed=100 + i, n_sequences=N_SEQ, n_items=9,
                         mean_itemsets=3.0, mean_itemset_size=1.2)
            for i in range(N_DATASETS)]


def _flood(dbs, n_jobs, workers, label):
    """Submit n_jobs mixed-priority TSR mines and poll them home;
    returns (rows keyed by uid, summary)."""
    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.service.actors import Master
    from spark_fsm_tpu.service.model import ServiceRequest
    from spark_fsm_tpu.service.store import ResultStore

    store = ResultStore()
    master = Master(store=store, miner_workers=workers)
    spmf = [format_spmf(db) for db in dbs]
    try:
        t0 = time.monotonic()
        t_submit, done = {}, {}
        sheds = failures = 0
        for i in range(n_jobs):
            req = ServiceRequest("fsm", "train", {
                "algorithm": "TSR_TPU", "source": "INLINE",
                "sequences": spmf[i % len(dbs)], "k": "6",
                "minconf": "0.4", "max_side": "2",
                # client-supplied uid: uuid4 reads the OS entropy pool,
                # which on starved container hosts costs ~5 ms a call —
                # 48 of those serialized at submit time would throttle
                # the offered load the flood exists to create
                "uid": f"tp-{label}-{i}",
                "priority": PRIORITIES[i % len(PRIORITIES)]})
            resp = master.handle(req)
            if resp.status == "failure":
                sheds += 1
                continue
            t_submit[resp.data["uid"]] = (time.monotonic(), i % len(dbs))
        deadline = time.monotonic() + DEADLINE_S
        while t_submit.keys() - done.keys() and time.monotonic() < deadline:
            for uid in list(t_submit.keys() - done.keys()):
                st = store.status(uid)
                if st in ("finished", "failure"):
                    done[uid] = (time.monotonic(), st)
                    if st == "failure":
                        failures += 1
            time.sleep(0.002)
        pending = t_submit.keys() - done.keys()
        if pending:
            raise TimeoutError(
                f"{label}: {len(pending)} jobs never finished")
        wall = time.monotonic() - t0
        lats = sorted(done[u][0] - t_submit[u][0] for u in done)
        q = lambda p: lats[min(len(lats) - 1, int(p * (len(lats) - 1)))]
        rows = {}
        for uid, (_, db_i) in t_submit.items():
            rows[uid] = (db_i, store.rules(uid))
        summary = {
            "jobs": len(done), "wall_s": round(wall, 3),
            "jobs_per_sec": round(len(done) / wall, 2),
            "p50_s": round(q(0.50), 4), "p99_s": round(q(0.99), 4),
            "sheds": sheds, "failures": failures,
        }
        return rows, summary
    finally:
        master.shutdown()


def _forced_window(dbs, n_held: int = 4):
    """Timing-independent fusion proof: ``n_held`` jobs lined up in a
    HELD window must resolve through at least one shared cross-job
    launch with per-job parity (the flood above fuses
    opportunistically, which is the point — but CI needs one
    deterministic cross-job launch)."""
    import threading

    from spark_fsm_tpu.data.vertical import build_vertical
    from spark_fsm_tpu.models.tsr import TsrTPU
    from spark_fsm_tpu.service import fusion as FZ
    from spark_fsm_tpu.utils.canonical import rules_text

    mk = lambda db: TsrTPU(build_vertical(db, min_item_support=1), 6,
                           0.4, max_side=2)
    want = [rules_text(mk(db).mine()) for db in dbs[:n_held]]
    b = FZ.broker()
    before = b.stats["cross_job_launches"]
    b.hold()
    out = {}
    ts = [threading.Thread(target=lambda k=k, db=db: out.setdefault(
        k, mk(db).mine())) for k, db in enumerate(dbs[:n_held])]
    for t in ts:
        t.start()
    deadline = time.monotonic() + 60.0
    while b.pending() < n_held and time.monotonic() < deadline:
        time.sleep(0.005)
    held = b.pending()
    b.release()
    for t in ts:
        t.join(120.0)
    assert not any(t.is_alive() for t in ts), "forced-window mine wedged"
    parity = [rules_text(out[k]) == want[k] for k in range(n_held)]
    return {"held_waves": held, "parity": all(parity),
            "cross_job_launches": b.stats["cross_job_launches"] - before}


ZIPF_JOBS = int(os.environ.get("SPARKFSM_TP_ZIPF_JOBS", "64"))


def _zipf_stream(n_jobs, n_datasets, seed=7):
    """Deterministic zipf-distributed request stream: dataset i drawn
    with weight 1/(i+1) (hot heads, long tail), parameters drawn from a
    variant pool where the base (k=8) dominates the rest — repeats of
    the base coalesce or exact-hit, the weaker variants serve
    dominated once the base has run."""
    import random

    rng = random.Random(seed)
    weights = [1.0 / (i + 1) for i in range(n_datasets)]
    variants = [(8, "0.4"), (8, "0.4"), (5, "0.4"), (3, "0.5")]
    return [(rng.choices(range(n_datasets), weights)[0],
             *rng.choice(variants)) for _ in range(n_jobs)]


def _zipf_flood(dbs, stream, workers, label):
    """Run the request stream through a fresh Master; returns
    (per-request rows, summary).  Rows carry the request key, the
    canonical rules text, and how the request was satisfied (cold /
    exact / dominated / coalesced)."""
    import json as _json

    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.service.actors import Master
    from spark_fsm_tpu.service.model import (ServiceRequest,
                                             deserialize_rules)
    from spark_fsm_tpu.service.store import ResultStore
    from spark_fsm_tpu.utils.canonical import rules_text

    store = ResultStore()
    master = Master(store=store, miner_workers=workers)
    spmf = [format_spmf(db) for db in dbs]
    try:
        t0 = time.monotonic()
        t_submit, done = {}, {}
        keys = {}
        for i, (db_i, k, minconf) in enumerate(stream):
            uid = f"zp-{label}-{i}"
            resp = master.handle(ServiceRequest("fsm", "train", {
                "algorithm": "TSR_TPU", "source": "INLINE",
                "sequences": spmf[db_i], "k": str(k),
                "minconf": minconf, "max_side": "2", "uid": uid}))
            if resp.status == "failure":
                raise RuntimeError(f"zipf submit failed: {resp.data}")
            t_submit[uid] = time.monotonic()
            keys[uid] = (db_i, k, minconf)
        deadline = time.monotonic() + DEADLINE_S
        while t_submit.keys() - done.keys() and time.monotonic() < deadline:
            for uid in list(t_submit.keys() - done.keys()):
                st = store.status(uid)
                if st in ("finished", "failure"):
                    done[uid] = (time.monotonic(), st)
            time.sleep(0.002)
        pending = t_submit.keys() - done.keys()
        if pending:
            raise TimeoutError(f"zipf-{label}: {len(pending)} jobs "
                               f"never finished")
        failures = sum(1 for _, st in done.values() if st == "failure")
        wall = time.monotonic() - t0
        rows = {}
        cold_lats, all_lats = [], []
        served = coalesced = 0
        usage_billed = 0  # launches attributed across THIS flood's jobs
        for uid in done:
            stats = _json.loads(store.get(f"fsm:stats:{uid}") or "{}")
            if stats.get("coalesced_into"):
                how = "coalesced"
                coalesced += 1
                served += 1
            elif stats.get("served_from_cache"):
                how = stats["served_from_cache"]
                served += 1
            else:
                how = "cold"
            lat = done[uid][0] - t_submit[uid]
            all_lats.append(lat)
            if how == "cold":
                cold_lats.append(lat)
                # only COLD jobs were billed: a served/coalesced row's
                # stats blob carries the cached leader's usage block
                # (its historical cost), not a fresh deposit
                usage_billed += int(
                    (stats.get("usage") or {}).get("launches", 0))
            rows[uid] = (keys[uid],
                         rules_text(deserialize_rules(store.rules(uid))),
                         how)
        q = lambda xs, p: sorted(xs)[
            min(len(xs) - 1, int(p * (len(xs) - 1)))] if xs else None
        summary = {
            "jobs": len(done), "wall_s": round(wall, 3),
            "jobs_per_sec": round(len(done) / wall, 2),
            "p99_s": round(q(all_lats, 0.99), 4),
            "cold_jobs": len(cold_lats),
            "p99_cold_s": (None if not cold_lats
                           else round(q(cold_lats, 0.99), 4)),
            "served": served, "coalesced": coalesced,
            "failures": failures, "usage_launches": usage_billed,
        }
        return rows, summary
    finally:
        master.shutdown()


def main_zipf(update: bool, n_jobs: int, workers: int) -> int:
    """--mix zipf: the result-reuse success metric (ROADMAP item 2)."""
    from spark_fsm_tpu import config as cfgmod
    from spark_fsm_tpu.ops import ragged_batch as RB
    from spark_fsm_tpu.utils import jitcache

    RB.set_overhead_calibration(False)
    jitcache.enable_compile_counter()
    dbs = _datasets()
    stream = _zipf_stream(n_jobs, len(dbs))

    # compile-warm the cold path (same arbiter as the fusion flood)
    for i in range(6):
        before = jitcache.compile_counts()["count"]
        _zipf_flood(dbs, stream, workers, f"warm-{i}")
        if jitcache.compile_counts()["count"] == before:
            break

    def med(runs, key):
        vals = sorted(r[key] for r in runs)
        return vals[len(vals) // 2]

    # both timed phases run with [usage] on (ISSUE 19): the reuse tier's
    # conservation claim is that every deposited launch lands in exactly
    # one finished job's settled usage block — served/coalesced requests
    # bill ZERO launches and the cached phase credits avoided-cost
    # priced from the cached entry's recorded usage instead
    from spark_fsm_tpu.service import usage as UM

    old_cfg = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config({"usage": {"enabled": True}}))
    u_launches0 = UM._LAUNCHES.total()
    u_avoided0 = UM._AVOIDED.total()
    try:
        cold_runs, cold_rows = [], {}
        for i in range(N_RUNS):
            rows, s = _zipf_flood(dbs, stream, workers, f"cold-{i}")
            cold_rows.update(rows)
            cold_runs.append(s)

        cfgmod.set_config(cfgmod.parse_config(
            {"usage": {"enabled": True},
             "rescache": {"enabled": True}}))
        cached_runs, cached_rows = [], {}
        for i in range(N_RUNS):
            rows, s = _zipf_flood(dbs, stream, workers, f"cached-{i}")
            cached_rows.update(rows)
            cached_runs.append(s)
    finally:
        UM.uninstall()
        cfgmod.set_config(old_cfg)
    u_launches1 = UM._LAUNCHES.total()
    u_avoided1 = UM._AVOIDED.total()

    billed = sum(r["usage_launches"] for r in cold_runs + cached_runs)
    zipf_usage = {
        "billed_launches": billed,
        "counter_launches": int(u_launches1 - u_launches0),
        "avoided_device_seconds": round(u_avoided1 - u_avoided0, 6),
    }
    zipf_usage_conserved = (
        billed == zipf_usage["counter_launches"]
        and zipf_usage["avoided_device_seconds"] > 0)

    # per-request parity: every cached/coalesced/dominated/cold answer
    # must be byte-identical (canonical text) to the cold baseline's
    # answer for the same (dataset, k, minconf)
    want = {}
    for key, text, _ in cold_rows.values():
        want.setdefault(key, text)
    parity = all(len({t for k2, t, _ in cold_rows.values() if k2 == key})
                 == 1 for key in want)
    for key, text, _ in cached_rows.values():
        parity = parity and want.get(key) == text

    cold_jps = med(cold_runs, "jobs_per_sec")
    cached_jps = med(cached_runs, "jobs_per_sec")
    total = sum(r["jobs"] for r in cached_runs)
    served = sum(r["served"] for r in cached_runs)
    coalesced = sum(r["coalesced"] for r in cached_runs)
    hit_ratio = round(served / max(1, total), 3)
    coalesce_ratio = round(coalesced / max(1, total), 3)
    p99_cold_base = med(cold_runs, "p99_s")
    cold_p99s = [r["p99_cold_s"] for r in cached_runs
                 if r["p99_cold_s"] is not None]
    p99_cold_cached = (sorted(cold_p99s)[len(cold_p99s) // 2]
                      if cold_p99s else None)
    no_regress = (p99_cold_cached is None
                  or p99_cold_cached <= 3.0 * p99_cold_base + 0.05)
    speedup = round(cached_jps / max(1e-9, cold_jps), 2)

    out = {
        "zipf_jobs": n_jobs, "workers": workers,
        "zipf_parity": parity,
        "zipf_hit_ratio_ok": hit_ratio >= 0.5,
        "zipf_speedup_2x": speedup >= 2.0,
        "no_p99_regression_cold": bool(no_regress),
        "zipf_usage_conserved": bool(zipf_usage_conserved),
        "zipf": {
            "usage": zipf_usage,
            "cold": {"jobs_per_sec": cold_jps,
                     "p99_s": p99_cold_base,
                     "runs": [r["jobs_per_sec"] for r in cold_runs]},
            "cached": {"jobs_per_sec": cached_jps,
                       "p99_cold_s": p99_cold_cached,
                       "runs": [r["jobs_per_sec"] for r in cached_runs],
                       "failures": sum(r["failures"]
                                       for r in cached_runs)},
            "speedup_jobs_per_sec": speedup,
            "cache_hit_ratio": hit_ratio,
            "coalesce_ratio": coalesce_ratio,
            "served": served, "coalesced": coalesced, "total": total,
        },
    }
    print(json.dumps(out, indent=2))

    try:
        with open(EXPECT_PATH) as fh:
            expect = json.load(fh)
    except OSError:
        expect = {}
    if update:
        expect.update({k: out[k] for k in ZIPF_COMPARED})
        with open(EXPECT_PATH, "w") as fh:
            json.dump(expect, fh, indent=2)
            fh.write("\n")
        print(f"bench_throughput: zipf expectations written -> "
              f"{EXPECT_PATH}")
        return 0
    bad = [k for k in ZIPF_COMPARED if out.get(k) != expect.get(k)]
    if bad:
        for k in bad:
            print(f"bench_throughput[zipf]: MISMATCH {k}: got "
                  f"{out.get(k)!r}, expected {expect.get(k)!r}",
                  file=sys.stderr)
        return 1
    print(f"bench_throughput[zipf]: OK (cached {cached_jps} jobs/s vs "
          f"cold {cold_jps} jobs/s, hit ratio {hit_ratio}, coalesce "
          f"ratio {coalesce_ratio}, cold p99 {p99_cold_cached}s vs "
          f"baseline {p99_cold_base}s — walls reported, guards "
          f"structural)")
    return 0


ENGINES_JOBS = int(os.environ.get("SPARKFSM_TP_ENG_JOBS", "24"))


def _engines_datasets():
    """Dense pool (above the density crossover) + sparse pool (below
    it — the ONE sub-crossover shape, data/synth.sub_crossover_db).
    One geometry per pool."""
    from spark_fsm_tpu.data.synth import sub_crossover_db, synthetic_db

    dense = [synthetic_db(seed=300 + i, n_sequences=90, n_items=9,
                          mean_itemsets=3.0, mean_itemset_size=1.2)
             for i in range(4)]
    sparse = [sub_crossover_db(offset=17 * k) for k in range(2)]
    return dense, sparse


def _engines_flood(plan, workers, label):
    """Run a [(algorithm, db_key, db, support)] plan through a fresh
    Master; returns (rows keyed by uid -> (db_key, patterns-json,
    planner_engine), summary)."""
    import json as _json

    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.service.actors import Master
    from spark_fsm_tpu.service.model import ServiceRequest
    from spark_fsm_tpu.service.store import ResultStore

    store = ResultStore()
    master = Master(store=store, miner_workers=workers)
    spmf = {}
    try:
        t0 = time.monotonic()
        t_submit, done, meta = {}, {}, {}
        sheds = failures = 0
        for i, (algo, db_key, db, support) in enumerate(plan):
            if db_key not in spmf:
                spmf[db_key] = format_spmf(db)
            uid = f"eng-{label}-{i}"
            resp = master.handle(ServiceRequest("fsm", "train", {
                "algorithm": algo, "source": "INLINE",
                "sequences": spmf[db_key], "support": support,
                "uid": uid}))
            if resp.status == "failure":
                sheds += 1
                continue
            t_submit[uid] = time.monotonic()
            meta[uid] = db_key
        deadline = time.monotonic() + DEADLINE_S
        while t_submit.keys() - done.keys() and time.monotonic() < deadline:
            for uid in list(t_submit.keys() - done.keys()):
                st = store.status(uid)
                if st in ("finished", "failure"):
                    done[uid] = (time.monotonic(), st)
                    if st == "failure":
                        failures += 1
            time.sleep(0.002)
        pending = t_submit.keys() - done.keys()
        if pending:
            raise TimeoutError(
                f"engines-{label}: {len(pending)} jobs never finished")
        wall = time.monotonic() - t0
        rows = {}
        for uid, db_key in meta.items():
            stats = _json.loads(store.get(f"fsm:stats:{uid}") or "{}")
            rows[uid] = (db_key, store.patterns(uid),
                         stats.get("planner_engine"),
                         {k: stats.get(k) for k in
                          ("representation", "rep_dense", "rep_idlist",
                           "diffset_nodes", "pair_launches",
                           "wave_survivors", "waves")})
        lats = sorted(done[u][0] - t_submit[u] for u in done)
        q = lambda p: lats[min(len(lats) - 1, int(p * (len(lats) - 1)))]
        summary = {"jobs": len(done), "wall_s": round(wall, 3),
                   "jobs_per_sec": round(len(done) / wall, 2),
                   "p50_s": round(q(0.50), 4),
                   "p99_s": round(q(0.99), 4),
                   "sheds": sheds, "failures": failures}
        return rows, summary
    finally:
        master.shutdown()


def main_engines(update: bool, n_jobs: int, workers: int) -> int:
    """--mix engines: the ISSUE 15 SPAM-engine + planner metric."""
    from spark_fsm_tpu.ops import ragged_batch as RB
    from spark_fsm_tpu.utils import jitcache

    RB.set_overhead_calibration(False)
    jitcache.enable_compile_counter()
    dense, sparse = _engines_datasets()

    def dense_plan(algo):
        return [(algo, f"d{i % len(dense)}", dense[i % len(dense)],
                 "0.08") for i in range(n_jobs)]

    auto_plan = []
    for i in range(n_jobs):
        if i % 3 == 2:  # every third AUTO job is a sparse shape
            k = i % len(sparse)
            auto_plan.append(("AUTO", f"s{k}", sparse[k], "2"))
        else:
            k = i % len(dense)
            auto_plan.append(("AUTO", f"d{k}", dense[k], "0.08"))

    # compile-warm every route to stability (same arbiter as the other
    # mixes: a timed phase must not pay fresh XLA compiles)
    for i in range(6):
        before = jitcache.compile_counts()["count"]
        _engines_flood(dense_plan("SPADE_TPU"), workers, f"w-spade-{i}")
        _engines_flood(dense_plan("SPAM_TPU"), workers, f"w-spam-{i}")
        _engines_flood(auto_plan, workers, f"w-auto-{i}")
        if jitcache.compile_counts()["count"] == before:
            break

    def med(runs):
        vals = sorted(r["jobs_per_sec"] for r in runs)
        return vals[len(vals) // 2]

    rows_all = {}
    per_engine = {}
    sheds = failures = 0
    for algo in ("SPADE_TPU", "SPAM_TPU"):
        runs = []
        for i in range(N_RUNS):
            rows, s = _engines_flood(dense_plan(algo), workers,
                                     f"{algo}-{i}")
            rows_all.update(rows)
            runs.append(s)
            sheds += s["sheds"]; failures += s["failures"]
        per_engine[algo] = {
            "jobs_per_sec": med(runs),
            "p99_s": sorted(r["p99_s"] for r in runs)[len(runs) // 2],
            "runs_jobs_per_sec": [r["jobs_per_sec"] for r in runs]}

    auto_rows, auto_sum = _engines_flood(auto_plan, workers, "auto")
    rows_all.update(auto_rows)
    sheds += auto_sum["sheds"]; failures += auto_sum["failures"]

    # parity: one byte-exact pattern set per dataset key across EVERY
    # engine route (explicit SPADE, explicit SPAM, AUTO both ways)
    by_key = {}
    for db_key, pats, _, _ in rows_all.values():
        by_key.setdefault(db_key, set()).add(pats)
    parity = all(len(v) == 1 for v in by_key.values())

    # AUTO routing: dense keys -> SPAM_TPU, sparse keys -> SPADE_TPU
    # ("AUTO never picks SPAM below the calibrated density crossover")
    routed = {"dense": set(), "sparse": set()}
    for db_key, _, eng, _ in auto_rows.values():
        routed["dense" if db_key.startswith("d") else "sparse"].add(eng)
    auto_ok = (routed["dense"] == {"SPAM_TPU"}
               and routed["sparse"] == {"SPADE_TPU"})

    out = {
        "engines_jobs": n_jobs, "workers": workers,
        "engines_parity": parity,
        "engines_auto_ok": auto_ok,
        "engines_failures": failures,
        "engines_sheds": sheds,
        "engines": {
            **per_engine,
            "spam_speedup_dense": round(
                per_engine["SPAM_TPU"]["jobs_per_sec"]
                / max(1e-9, per_engine["SPADE_TPU"]["jobs_per_sec"]), 2),
            "auto": {"jobs_per_sec": auto_sum["jobs_per_sec"],
                     "p99_s": auto_sum["p99_s"],
                     "routed": {k: sorted(x for x in v if x)
                                for k, v in routed.items()}},
        },
    }
    print(json.dumps(out, indent=2))

    try:
        with open(EXPECT_PATH) as fh:
            expect = json.load(fh)
    except OSError:
        expect = {}
    if update:
        expect.update({k: out[k] for k in ENGINES_COMPARED})
        with open(EXPECT_PATH, "w") as fh:
            json.dump(expect, fh, indent=2)
            fh.write("\n")
        print(f"bench_throughput: engines expectations written -> "
              f"{EXPECT_PATH}")
        return 0
    bad = [k for k in ENGINES_COMPARED if out.get(k) != expect.get(k)]
    if bad:
        for k in bad:
            print(f"bench_throughput[engines]: MISMATCH {k}: got "
                  f"{out.get(k)!r}, expected {expect.get(k)!r}",
                  file=sys.stderr)
        return 1
    print(f"bench_throughput[engines]: OK (dense flood: SPAM "
          f"{per_engine['SPAM_TPU']['jobs_per_sec']} jobs/s vs SPADE "
          f"{per_engine['SPADE_TPU']['jobs_per_sec']} jobs/s "
          f"({out['engines']['spam_speedup_dense']}x); AUTO routed "
          f"dense->SPAM_TPU, sparse->SPADE_TPU with byte parity — "
          f"walls reported, guards structural)")
    return 0


HYBRID_JOBS = int(os.environ.get("SPARKFSM_TP_HYB_JOBS", "24"))
# the crossover the whole mix runs at: high enough that the zipf tail
# of _hybrid_datasets lands below it (id-lists) while the hot head
# stays above (bitmaps).  All three modes share it so the comparison
# is representation-only.
HYBRID_CROSSOVER = 0.5


def _hybrid_datasets():
    """Mixed-density pool: a steep zipf alphabet gives each DB a few
    ~full-density head items and a long sub-crossover tail — the shape
    the hybrid store exists for (uniform pins waste pool rows on the
    tail or wave lanes on the head)."""
    from spark_fsm_tpu.data.synth import synthetic_db

    return [synthetic_db(seed=400 + i, n_sequences=90, n_items=24,
                         mean_itemsets=4.0, mean_itemset_size=1.3,
                         zipf_s=2.2)
            for i in range(4)]


def main_hybrid(update: bool, n_jobs: int, workers: int) -> int:
    """--mix hybrid: the ISSUE 16 density-adaptive store metric."""
    from spark_fsm_tpu import config as C
    from spark_fsm_tpu.ops import ragged_batch as RB
    from spark_fsm_tpu.utils import jitcache

    RB.set_overhead_calibration(False)
    jitcache.enable_compile_counter()
    dbs = _hybrid_datasets()
    plan = [("SPAM_TPU", f"m{i % len(dbs)}", dbs[i % len(dbs)], "0.08")
            for i in range(n_jobs)]

    def set_planner(rep):
        # process-global planner pin, exactly the operator knob
        # ([planner] representation) docs/OPERATIONS.md describes —
        # the flood exercises the deployed path, not a bench backdoor
        C.set_config(C.parse_config({"planner": {
            "representation": rep,
            "density_crossover": HYBRID_CROSSOVER}}))

    def med(runs, field="jobs_per_sec"):
        vals = sorted(r[field] for r in runs)
        return vals[len(vals) // 2]

    rows_all, per_mode, mode_stats = {}, {}, {}
    sheds = failures = 0
    try:
        for rep in ("auto", "bitmap", "idlist"):
            set_planner(rep)
            for i in range(6):  # compile-warm this mode to stability
                before = jitcache.compile_counts()["count"]
                _engines_flood(plan, workers, f"w-{rep}-{i}")
                if jitcache.compile_counts()["count"] == before:
                    break
            runs = []
            for i in range(N_RUNS):
                rows, s = _engines_flood(plan, workers, f"{rep}-{i}")
                rows_all.update(rows)
                if rep not in mode_stats:
                    mode_stats[rep] = next(iter(rows.values()))[3]
                runs.append(s)
                sheds += s["sheds"]; failures += s["failures"]
            per_mode[rep] = {
                "jobs_per_sec": med(runs),
                "p99_s": med(runs, "p99_s"),
                "runs_jobs_per_sec": [r["jobs_per_sec"] for r in runs]}
    finally:
        C.set_config(C.parse_config({}))  # restore process defaults

    # parity: one byte-exact pattern set per dataset across ALL THREE
    # representation modes — the store is a layout choice, never a
    # result choice
    by_key = {}
    for db_key, pats, _, _ in rows_all.values():
        by_key.setdefault(db_key, set()).add(pats)
    parity = all(len(v) == 1 for v in by_key.values())

    # the auto flood must have run a genuinely HYBRID store (both
    # representations live in one mine, diffsets + pair launches
    # observed) while each pin ran uniform
    au, bm, il = (mode_stats.get(k, {}) for k in
                  ("auto", "bitmap", "idlist"))
    store_ok = bool(
        (au.get("rep_dense") or 0) > 0 and (au.get("rep_idlist") or 0) > 0
        and (au.get("pair_launches") or 0) > 0
        and (au.get("diffset_nodes") or 0) > 0
        and (bm.get("rep_idlist") or 0) == 0
        and (il.get("rep_dense") or 0) == 0)

    best_fixed = max(per_mode["bitmap"]["jobs_per_sec"],
                     per_mode["idlist"]["jobs_per_sec"])
    out = {
        "hybrid_jobs": n_jobs, "workers": workers,
        "hybrid_parity": parity,
        "hybrid_store_ok": store_ok,
        "hybrid_failures": failures,
        "hybrid_sheds": sheds,
        "hybrid": {
            **per_mode,
            "crossover": HYBRID_CROSSOVER,
            "auto_stats": au,
            "speedup_vs_best_fixed": round(
                per_mode["auto"]["jobs_per_sec"] / max(1e-9, best_fixed),
                2)},
    }
    print(json.dumps(out, indent=2))

    try:
        with open(EXPECT_PATH) as fh:
            expect = json.load(fh)
    except OSError:
        expect = {}
    if update:
        expect.update({k: out[k] for k in HYBRID_COMPARED})
        with open(EXPECT_PATH, "w") as fh:
            json.dump(expect, fh, indent=2)
            fh.write("\n")
        print(f"bench_throughput: hybrid expectations written -> "
              f"{EXPECT_PATH}")
        return 0
    bad = [k for k in HYBRID_COMPARED if out.get(k) != expect.get(k)]
    if bad:
        for k in bad:
            print(f"bench_throughput[hybrid]: MISMATCH {k}: got "
                  f"{out.get(k)!r}, expected {expect.get(k)!r}",
                  file=sys.stderr)
        return 1
    print(f"bench_throughput[hybrid]: OK (mixed-density flood: hybrid "
          f"{per_mode['auto']['jobs_per_sec']} jobs/s vs best fixed "
          f"{best_fixed} jobs/s "
          f"({out['hybrid']['speedup_vs_best_fixed']}x); byte parity "
          f"across auto/bitmap/idlist; auto store split "
          f"{au.get('rep_dense')} dense / {au.get('rep_idlist')} "
          f"id-list with {au.get('diffset_nodes')} diffset nodes — "
          f"walls reported, guards structural)")
    return 0


TEN_WORKERS = int(os.environ.get("SPARKFSM_TP_TEN_WORKERS", "2"))
TEN_FLOOD = int(os.environ.get("SPARKFSM_TP_TEN_FLOOD", "36"))
TEN_BG = int(os.environ.get("SPARKFSM_TP_TEN_BG", "8"))


def _tenant_fleet(workers):
    """2-replica in-process fleet on one shared store: real lease
    managers with REAL heartbeat threads (steal is the transport the
    drain phase rides), fairness from the active process config."""
    from spark_fsm_tpu.service.actors import Master
    from spark_fsm_tpu.service.lease import LeaseManager
    from spark_fsm_tpu.service.store import ResultStore

    store = ResultStore()
    mgrs = [LeaseManager(store, replica_id=f"bench-{i}",
                         lease_ttl_s=6.0, heartbeat_s=0.25)
            for i in range(2)]
    masters = [Master(store=store, miner_workers=workers, lease_mgr=m)
               for m in mgrs]
    return store, masters


def _tenant_run(dbs, plan, workers, label, drain_b_after_submit=False):
    """Run a (tenant, db_i) submission plan through a fresh 2-replica
    fleet; returns (rows, summary).  ``drain_b_after_submit`` drives
    the forced scale-down: replica B drains right after the submits
    land and its backlog must finish on A via the steal protocol."""
    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.service.model import ServiceRequest

    store, masters = _tenant_fleet(workers)
    spmf = [format_spmf(db) for db in dbs]
    drain_report = {}
    try:
        t0 = time.monotonic()
        t_submit, done, meta = {}, {}, {}
        sheds = 0
        for i, (tenant, db_i) in enumerate(plan):
            uid = f"tn-{label}-{i}"
            target = masters[1] if drain_b_after_submit \
                else masters[i % 2]
            resp = target.handle(ServiceRequest("fsm", "train", {
                "algorithm": "TSR_TPU", "source": "INLINE",
                "sequences": spmf[db_i], "k": "6", "minconf": "0.4",
                "max_side": "2", "uid": uid, "tenant": tenant}))
            if resp.status == "failure":
                sheds += 1
                continue
            t_submit[uid] = time.monotonic()
            meta[uid] = (tenant, db_i)
        if drain_b_after_submit:
            drain_report = masters[1].miner.drain(
                timeout_s=120.0, reason="bench forced scale-down")
        deadline = time.monotonic() + DEADLINE_S
        failures = 0
        while t_submit.keys() - done.keys() \
                and time.monotonic() < deadline:
            for uid in list(t_submit.keys() - done.keys()):
                st = store.status(uid)
                if st in ("finished", "failure"):
                    done[uid] = (time.monotonic(), st)
                    if st == "failure":
                        failures += 1
            time.sleep(0.002)
        pending = t_submit.keys() - done.keys()
        if pending:
            raise TimeoutError(
                f"tenants-{label}: {len(pending)} jobs never finished")
        wall = time.monotonic() - t0
        rows, by_tenant = {}, {}
        for uid, (tenant, db_i) in meta.items():
            rows[uid] = (db_i, store.rules(uid))
            by_tenant.setdefault(tenant, []).append(
                (t_submit[uid], done[uid][0]))
        q = lambda xs, p: sorted(xs)[
            min(len(xs) - 1, int(p * (len(xs) - 1)))]
        tenants = {}
        for tenant, spans in by_tenant.items():
            lats = [d - s for s, d in spans]
            # the tenant's goodput window: first submit to ITS last
            # finish — the rate the fair-share guard compares
            span_wall = max(d for _, d in spans) - min(
                s for s, _ in spans)
            tenants[tenant] = {
                "jobs": len(spans),
                "jobs_per_sec": round(
                    len(spans) / max(1e-9, span_wall), 3),
                "p50_s": round(q(lats, 0.50), 4),
                "p99_s": round(q(lats, 0.99), 4)}
        summary = {"jobs": len(done), "wall_s": round(wall, 3),
                   "jobs_per_sec": round(len(done) / wall, 2),
                   "tenants": tenants, "sheds": sheds,
                   "failures": failures}
        if drain_report:
            summary["drain"] = drain_report
        return rows, summary
    finally:
        for m in masters:
            m.shutdown()


def main_tenants(update: bool, workers: int) -> int:
    """--mix tenants: the ISSUE 13 fairness + scale-down metric."""
    from spark_fsm_tpu import config as cfgmod
    from spark_fsm_tpu.ops import ragged_batch as RB
    from spark_fsm_tpu.utils import jitcache

    RB.set_overhead_calibration(False)
    jitcache.enable_compile_counter()
    dbs = _datasets()

    bg_plan = [(t, (i * 2 + k) % N_DATASETS)
               for i in range(TEN_BG)
               for k, t in enumerate(("bg1", "bg2"))]
    flood_plan = [("flood", i % N_DATASETS) for i in range(TEN_FLOOD)]
    mixed_plan = flood_plan + bg_plan  # flood lands FIRST: FIFO would
    # queue every background job behind the whole flood

    old_cfg = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config(
        {"fairness": {"enabled": True}}))
    try:
        # compile-warm to stability (the same arbiter as the other
        # mixes: a timed phase must not pay fresh XLA compiles)
        for i in range(6):
            before = jitcache.compile_counts()["count"]
            _tenant_run(dbs, mixed_plan, workers, f"warm-{i}")
            if jitcache.compile_counts()["count"] == before:
                break

        def med(runs, pick):
            vals = sorted(pick(r) for r in runs)
            return vals[len(vals) // 2]

        solo_runs, mixed_runs = [], []
        rows_all = {}
        for i in range(N_RUNS):
            rows, s = _tenant_run(dbs, bg_plan, workers, f"solo-{i}")
            rows_all.update(rows)
            solo_runs.append(s)
        for i in range(N_RUNS):
            rows, s = _tenant_run(dbs, mixed_plan, workers,
                                  f"mixed-{i}")
            rows_all.update(rows)
            mixed_runs.append(s)

        # forced scale-down: everything lands on B, B drains at once,
        # A must steal the backlog — zero lost, zero duplicated
        drain_rows, drain_sum = _tenant_run(
            dbs, mixed_plan[:12], workers, "drain",
            drain_b_after_submit=True)
        rows_all.update(drain_rows)

        # per-dataset parity across every phase/tenant/replica: one
        # byte-exact rule set per dataset index
        by_db = {}
        for db_i, rules in rows_all.values():
            by_db.setdefault(db_i, set()).add(rules)
        parity = all(len(v) == 1 for v in by_db.values())

        total_jps = med(mixed_runs, lambda r: r["jobs_per_sec"])
        fair_ok, p99_ok = True, True
        bg_report = {}
        for t in ("bg1", "bg2"):
            mixed_jps = med(mixed_runs,
                            lambda r: r["tenants"][t]["jobs_per_sec"])
            solo_p99 = med(solo_runs,
                           lambda r: r["tenants"][t]["p99_s"])
            mixed_p99 = med(mixed_runs,
                            lambda r: r["tenants"][t]["p99_s"])
            # equal weights, three backlogged tenants: fair share is a
            # third of the fleet's served rate
            fair_share = total_jps / 3.0
            fair_ok = fair_ok and mixed_jps >= 0.5 * fair_share
            p99_ok = p99_ok and mixed_p99 <= 2.0 * solo_p99 + 0.25
            bg_report[t] = {
                "mixed_jobs_per_sec": mixed_jps,
                "fair_share_jobs_per_sec": round(fair_share, 3),
                "solo_p99_s": solo_p99, "mixed_p99_s": mixed_p99}

        drain = drain_sum.get("drain", {})
        drain_ok = (drain_sum["failures"] == 0
                    and drain_sum["sheds"] == 0
                    and drain_sum["jobs"] == 12
                    and drain.get("left_for_recovery", 1) == 0
                    and drain.get("stolen_by_peers", 0) >= 1
                    and parity)

        out = {
            "tenants_jobs": len(mixed_plan), "workers": workers,
            "tenants_parity": parity,
            "tenants_fair_share_ok": bool(fair_ok),
            "tenants_p99_ok": bool(p99_ok),
            "tenants_drain_ok": bool(drain_ok),
            "tenants": {
                "total_jobs_per_sec": total_jps,
                "background": bg_report,
                "flood_p99_s": med(
                    mixed_runs,
                    lambda r: r["tenants"]["flood"]["p99_s"]),
                "mixed_runs_jobs_per_sec": [
                    r["jobs_per_sec"] for r in mixed_runs],
                "drain": {**drain,
                          "jobs": drain_sum["jobs"],
                          "failures": drain_sum["failures"]},
            },
        }
    finally:
        cfgmod.set_config(old_cfg)
    print(json.dumps(out, indent=2))

    try:
        with open(EXPECT_PATH) as fh:
            expect = json.load(fh)
    except OSError:
        expect = {}
    if update:
        expect.update({k: out[k] for k in TENANTS_COMPARED})
        with open(EXPECT_PATH, "w") as fh:
            json.dump(expect, fh, indent=2)
            fh.write("\n")
        print(f"bench_throughput: tenants expectations written -> "
              f"{EXPECT_PATH}")
        return 0
    bad = [k for k in TENANTS_COMPARED if out.get(k) != expect.get(k)]
    if bad:
        for k in bad:
            print(f"bench_throughput[tenants]: MISMATCH {k}: got "
                  f"{out.get(k)!r}, expected {expect.get(k)!r}",
                  file=sys.stderr)
        return 1
    print(f"bench_throughput[tenants]: OK (fleet "
          f"{out['tenants']['total_jobs_per_sec']} jobs/s; background "
          f"tenants at >= 0.5x fair share with p99 within 2x of solo; "
          f"forced scale-down stole "
          f"{drain.get('stolen_by_peers')} jobs with zero "
          f"lost/duplicated — walls reported, guards structural)")
    return 0


PREDICT_REQS = int(os.environ.get("SPARKFSM_TP_PREDICT_REQS", "192"))
PREDICT_THREADS = int(os.environ.get("SPARKFSM_TP_PREDICT_THREADS", "8"))
PREDICT_TRAINS = int(os.environ.get("SPARKFSM_TP_PREDICT_TRAINS", "4"))
PREDICT_M = 5
# prefixes the flood rotates through: varied rows so waves are not
# degenerate (identical queries would hide a row-demux bug), all short
# enough to land inside the configured depth_floor geometry
PREDICT_PREFIXES = ("", "1", "2", "1,2", "3", "1,3", "2,4", "1,2,3")


def _predict_plan(uids, n_reqs, threads):
    """Deterministic flood plan: consecutive blocks of ``threads``
    entries share a uid, so the lock-stepped flood threads (thread t
    walks plan[t::threads]) rendezvous on ONE artifact per round — the
    shape micro-batching exists for.  No ``high`` entries: a high
    joiner makes its window due immediately (that is its job), which
    would turn the fused flood into a solo-launch measurement."""
    plan = []
    for i in range(n_reqs):
        plan.append((uids[(i // threads) % len(uids)],
                     PREDICT_PREFIXES[i % len(PREDICT_PREFIXES)],
                     PREDICT_M,
                     ("normal", "low")[i % 2]))
    return plan


def _predict_flood(master, plan, threads, label):
    """Run the plan through ``threads`` lock-stepped submitters;
    returns (responses aligned with plan, summary)."""
    import threading

    from spark_fsm_tpu.service.model import ServiceRequest

    n = len(plan)
    results = [None] * n
    lats = [0.0] * n

    def run(t):
        for i in range(t, n, threads):
            uid, items, m, pr = plan[i]
            req = ServiceRequest("fsm", "predict", {
                "uid": uid, "items": items, "m": str(m), "priority": pr})
            s = time.monotonic()
            results[i] = master.handle(req)
            lats[i] = time.monotonic() - s

    ts = [threading.Thread(target=run, args=(t,)) for t in range(threads)]
    t0 = time.monotonic()
    for th in ts:
        th.start()
    for th in ts:
        th.join(DEADLINE_S)
    wall = time.monotonic() - t0
    assert not any(th.is_alive() for th in ts), f"{label}: flood wedged"
    slats = sorted(lats)
    q = lambda p: slats[min(n - 1, int(p * (n - 1)))]
    fused_jobs = failures = 0
    for r in results:
        if r is None or r.status != "finished":
            failures += 1
            continue
        if json.loads(r.data["stats"])["fused"]:
            fused_jobs += 1
    return results, {
        "requests": n, "wall_s": round(wall, 3),
        "predictions_per_sec": round(n / wall, 2),
        "p50_ms": round(q(0.50) * 1000.0, 3),
        "p99_ms": round(q(0.99) * 1000.0, 3),
        "fused_jobs": fused_jobs, "failures": failures,
    }


def _predict_parity(results, plan, rules_by_uid):
    """Every flood response byte-identical (canonical JSON) to the
    brute-force host oracle over that uid's rule set."""
    from spark_fsm_tpu.ops import rule_trie

    ok = True
    for (uid, items, m, _), r in zip(plan, results):
        if r is None or r.status != "finished":
            continue
        got = json.loads(r.data["predictions"])
        prefix = sorted({int(i) for i in items.split(",") if i})
        want = rule_trie.predict_host(rules_by_uid[uid], prefix, m)
        if (json.dumps(got, sort_keys=True)
                != json.dumps(want, sort_keys=True)):
            ok = False
    return ok


def _await_uids(store, uids, label):
    deadline = time.monotonic() + DEADLINE_S
    pend = set(uids)
    while pend and time.monotonic() < deadline:
        for u in list(pend):
            st = store.status(u)
            if st == "failure":
                raise RuntimeError(f"{label}: train {u} failed")
            if st == "finished":
                pend.discard(u)
        time.sleep(0.005)
    if pend:
        raise TimeoutError(f"{label}: {len(pend)} trains never finished")


def main_predict(update: bool, n_reqs: int, threads: int) -> int:
    """--mix predict: the ISSUE 17 prediction-serving-plane metric."""
    from spark_fsm_tpu import config as cfgmod
    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.data.synth import synthetic_db
    from spark_fsm_tpu.service import model as smodel
    from spark_fsm_tpu.service.actors import Master
    from spark_fsm_tpu.service.model import ServiceRequest
    from spark_fsm_tpu.service.store import ResultStore
    from spark_fsm_tpu.utils import jitcache

    jitcache.enable_compile_counter()
    # small lanes_floor on purpose: the flood's rule sets are tiny, so
    # per-launch EXEC is small and per-launch DISPATCH (the fixed cost
    # micro-batching amortizes) is what the walls measure — the serving
    # analogue of the mining broker's launch-consolidation bet.  The
    # production floor stays at the config default (1024).
    fused_cfg = {"predict": {"window_ms": 2.0, "max_wave": threads,
                             "lanes_floor": 256, "depth_floor": 8,
                             "topm": PREDICT_M}}
    unfused_cfg = {"predict": {"window_ms": 0.0, "max_wave": 1,
                               "lanes_floor": 256, "depth_floor": 8,
                               "topm": PREDICT_M}}
    cfgmod.set_config(cfgmod.parse_config(fused_cfg))
    store = ResultStore()
    master = Master(store=store, miner_workers=N_WORKERS)
    try:
        # serve set: the rule artifacts the flood predicts against
        dbs = _datasets()[:4]
        uids = []
        for i, db in enumerate(dbs):
            uid = f"tp-pred-{i}"
            resp = master.handle(ServiceRequest("fsm", "train", {
                "algorithm": "TSR_TPU", "source": "INLINE",
                "sequences": format_spmf(db), "k": "6",
                "minconf": "0.4", "max_side": "2",
                "uid": uid, "priority": "normal"}))
            assert resp.status != "failure", resp.data
            uids.append(uid)
        _await_uids(store, uids, "serve-set")
        rules_by_uid = {u: smodel.deserialize_rules(store.rules(u))
                        for u in uids}

        plan = _predict_plan(uids, n_reqs, threads)
        touch = [(u, "1", PREDICT_M, "normal") for u in uids]

        # background trains that mine DURING each timed flood — the
        # mixed read+write shape the read plane must hold its walls
        # under.  Same dataset geometry as the serve set so the mining
        # path stays on already-compiled shapes.
        bg_spmf = [format_spmf(synthetic_db(
            seed=200 + i, n_sequences=N_SEQ, n_items=9,
            mean_itemsets=3.0, mean_itemset_size=1.2))
            for i in range(PREDICT_TRAINS)]

        def submit_bg(label):
            bgu = []
            for i, text in enumerate(bg_spmf):
                uid = f"tp-pred-bg-{label}-{i}"
                resp = master.handle(ServiceRequest("fsm", "train", {
                    "algorithm": "TSR_TPU", "source": "INLINE",
                    "sequences": text, "k": "6", "minconf": "0.4",
                    "max_side": "2", "uid": uid, "priority": "low"}))
                if resp.status != "failure":
                    bgu.append(uid)
            return bgu

        _await_uids(store, submit_bg("warm"), "bg-warm")

        # compile-warm both modes to stability (the shared arbiter: a
        # timed phase must not pay fresh XLA compiles)
        for i in range(6):
            before = jitcache.compile_counts()["count"]
            cfgmod.set_config(cfgmod.parse_config(fused_cfg))
            _predict_flood(master, touch, 1, f"touch-fused-{i}")
            _predict_flood(master, plan, threads, f"warm-fused-{i}")
            cfgmod.set_config(cfgmod.parse_config(unfused_cfg))
            _predict_flood(master, touch, 1, f"touch-unfused-{i}")
            _predict_flood(master, plan, threads, f"warm-unfused-{i}")
            if jitcache.compile_counts()["count"] == before:
                break

        parity = True
        failures = fused_jobs_total = 0
        per_mode, deltas = {}, {}
        for mode, cfg in (("fused", fused_cfg), ("unfused", unfused_cfg)):
            cfgmod.set_config(cfgmod.parse_config(cfg))
            # pre-touch: set_config swapped in a fresh artifact cache;
            # rebuild outside the timed window
            _predict_flood(master, touch, 1, f"touch-{mode}")
            s0 = master.predictor.stats()
            runs = []
            for i in range(N_RUNS):
                bgu = submit_bg(f"{mode}-{i}")
                results, s = _predict_flood(master, plan, threads,
                                            f"{mode}-{i}")
                _await_uids(store, bgu, f"bg-{mode}-{i}")
                parity = parity and _predict_parity(results, plan,
                                                    rules_by_uid)
                failures += s["failures"]
                if mode == "fused":
                    fused_jobs_total += s["fused_jobs"]
                runs.append(s)
            s1 = master.predictor.stats()
            # the broker's own launch accounting over the timed floods
            # only (touch/warm excluded by the snapshot bracket)
            deltas[mode] = {k: s1[k] - s0[k] for k in
                           ("waves", "fused_jobs", "solo_jobs", "exec_s")}
            vals = sorted(r["predictions_per_sec"] for r in runs)
            per_mode[mode] = {
                "predictions_per_sec": vals[len(vals) // 2],
                "p50_ms": sorted(r["p50_ms"] for r in runs)[len(runs) // 2],
                "p99_ms": sorted(r["p99_ms"] for r in runs)[len(runs) // 2],
                "fused_share": round(
                    sum(r["fused_jobs"] for r in runs)
                    / max(1, sum(r["requests"] for r in runs)), 3),
                "launches": deltas[mode]["waves"],
                "runs_predictions_per_sec":
                    [r["predictions_per_sec"] for r in runs]}

        # modeled device dispatch (the mining mix's modeled_2x arbiter
        # applied to the read path): each mode's ACTUAL launch count
        # priced at the committed per-dispatch constant, plus the
        # measured scoring walls (row-independent kernel: both modes
        # score the same rows, so exec is a shared term, not a lever).
        # On a serial accelerator this ratio IS the device-time saving;
        # on this CPU backend it is a model (see module docstring).
        from spark_fsm_tpu.ops import ragged_batch as RB
        alt_solo = deltas["fused"]["fused_jobs"] + deltas["fused"]["solo_jobs"]
        modeled_fused_s = (deltas["fused"]["waves"] * RB.DISPATCH_SEC
                           + deltas["fused"]["exec_s"])
        modeled_solo_s = (alt_solo * RB.DISPATCH_SEC
                          + deltas["unfused"]["exec_s"])
        modeled = {
            "launches": deltas["fused"]["waves"],
            "alt_solo_launches": alt_solo,
            "modeled_fused_s": round(modeled_fused_s, 4),
            "modeled_solo_s": round(modeled_solo_s, 4),
            "speedup": round(
                modeled_solo_s / max(1e-9, modeled_fused_s), 2),
        }

        fused_pps = per_mode["fused"]["predictions_per_sec"]
        unfused_pps = per_mode["unfused"]["predictions_per_sec"]
        out = {
            "predict_requests": n_reqs,
            "predict_threads": threads,
            "predict_parity": parity,
            "predict_fused_2x": modeled["speedup"] >= 2.0,
            # >= one genuinely fused (>= 2 request) wave per timed
            # fused flood on average — the micro-batch path actually
            # engaged, not just the window code being present
            "predict_fused_waves_ok": fused_jobs_total >= 2 * N_RUNS,
            "predict_failures": failures,
            "predict": {
                **per_mode,
                "wall_speedup_predictions_per_sec": round(
                    fused_pps / max(1e-9, unfused_pps), 2),
                "modeled_device_dispatch": modeled,
                "background_trains_per_flood": PREDICT_TRAINS,
            },
        }
    finally:
        master.shutdown()
        cfgmod.set_config(cfgmod.parse_config({}))
    print(json.dumps(out, indent=2))

    try:
        with open(EXPECT_PATH) as fh:
            expect = json.load(fh)
    except OSError:
        expect = {}
    if update:
        expect.update({k: out[k] for k in PREDICT_COMPARED})
        with open(EXPECT_PATH, "w") as fh:
            json.dump(expect, fh, indent=2)
            fh.write("\n")
        print(f"bench_throughput: predict expectations written -> "
              f"{EXPECT_PATH}")
        return 0
    bad = [k for k in PREDICT_COMPARED if out.get(k) != expect.get(k)]
    if bad:
        for k in bad:
            print(f"bench_throughput[predict]: MISMATCH {k}: got "
                  f"{out.get(k)!r}, expected {expect.get(k)!r}",
                  file=sys.stderr)
        return 1
    print(f"bench_throughput[predict]: OK (fused {fused_pps} "
          f"predictions/s vs unfused {unfused_pps} predictions/s under "
          f"background mining; modeled device-dispatch speedup "
          f"{out['predict']['modeled_device_dispatch']['speedup']}x over "
          f"{out['predict']['modeled_device_dispatch']['alt_solo_launches']} "
          f"solo launches, byte parity vs the host oracle on every "
          f"response — walls reported, guards structural)")
    return 0


def main() -> int:
    update = "--update" in sys.argv[1:]
    args = [a for a in sys.argv[1:] if a != "--update"]
    mix = None
    if "--mix" in args:
        mix = args[args.index("--mix") + 1]
        if mix not in ("zipf", "tenants", "engines", "hybrid", "predict"):
            sys.exit(f"unknown --mix {mix!r} "
                     f"(have: zipf, tenants, engines, hybrid, predict)")
    n_jobs, workers = N_JOBS, N_WORKERS
    if "--jobs" in args:
        n_jobs = int(args[args.index("--jobs") + 1])
    if "--workers" in args:
        workers = int(args[args.index("--workers") + 1])
    if mix == "zipf":
        return main_zipf(update,
                         ZIPF_JOBS if "--jobs" not in args else n_jobs,
                         workers)
    if mix == "tenants":
        return main_tenants(
            update,
            TEN_WORKERS if "--workers" not in args else workers)
    if mix == "engines":
        return main_engines(
            update,
            ENGINES_JOBS if "--jobs" not in args else n_jobs,
            workers)
    if mix == "hybrid":
        return main_hybrid(
            update,
            HYBRID_JOBS if "--jobs" not in args else n_jobs,
            workers)
    if mix == "predict":
        return main_predict(
            update,
            PREDICT_REQS if "--jobs" not in args else n_jobs,
            PREDICT_THREADS if "--workers" not in args else workers)

    from spark_fsm_tpu import config as cfgmod
    from spark_fsm_tpu.ops import ragged_batch as RB
    from spark_fsm_tpu.service import fusion as FZ

    # committed cost-model constants: the structural outcome must be
    # machine-independent (same pin as bench_smoke)
    RB.set_overhead_calibration(False)

    dbs = _datasets()

    # warm each mode to its COMPILE-STABLE state before timing it: the
    # flood measures DISPATCH throughput, and a timed phase that pays
    # fresh XLA compiles measures the compiler instead (exactly the
    # stall prewarm's solo + fused ladders exist to prevent in the live
    # service).  Untimed floods repeat until one completes with zero
    # fresh backend compiles — the jitcache counter is the arbiter, the
    # same one the prewarm drift test pins.
    from spark_fsm_tpu.utils import jitcache

    jitcache.enable_compile_counter()

    def warm_to_stable(label: str, cap: int = 8) -> int:
        for i in range(cap):
            before = jitcache.compile_counts()["count"]
            _flood(dbs, n_jobs, workers, f"warm-{label}-{i}")
            if jitcache.compile_counts()["count"] == before:
                return i + 1
        return cap

    def timed(label: str):
        """N_RUNS floods; the reported row is the jobs/sec MEDIAN run
        (this box is shared — a single wall is noise), sheds/failures
        summed across all runs (structural, must be zero regardless)."""
        rows_all, summaries = {}, []
        for i in range(N_RUNS):
            rows, s = _flood(dbs, n_jobs, workers, f"{label}-{i}")
            rows_all.update(rows)
            summaries.append(s)
        ranked = sorted(summaries, key=lambda s: s["jobs_per_sec"])
        med = dict(ranked[len(ranked) // 2])
        med["runs_jobs_per_sec"] = [s["jobs_per_sec"] for s in summaries]
        med["sheds"] = sum(s["sheds"] for s in summaries)
        med["failures"] = sum(s["failures"] for s in summaries)
        return rows_all, med

    warm = {"unfused_floods": warm_to_stable("unfused")}
    rows_u, unfused = timed("unfused")

    # the fused phase doubles as the usage-attribution conservation
    # drill (ISSUE 19): with [usage] on, every broker dispatch over the
    # span — opportunistic fused waves, the forced cross-job window AND
    # any degraded solo re-dispatches — must be split across exactly the
    # jobs that rode it, so the per-tenant fsm_usage_* counters move by
    # EXACTLY what the broker's own launch/traffic tallies move by
    from spark_fsm_tpu.service import usage as UM

    old_cfg = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config({"usage": {"enabled": True}}))
    FZ.configure(cfgmod.FusionConfig(enabled=True))
    try:
        warm["fused_floods"] = warm_to_stable("fused")
        b0 = dict(FZ.broker().stats)  # modeled-ratio baseline: timed
        # fused work only, not the warm floods
        u0 = (UM._LAUNCHES.total(), UM._TRAFFIC.total())
        rows_f, fused = timed("fused")
        # modeled-ratio snapshot BEFORE the forced window: its held
        # group fuses at the best possible ratio by construction and
        # must not pad the opportunistic floods' modeled speedup (the
        # final `broker`/`degraded` report still covers it)
        b_timed = dict(FZ.broker().stats)
        forced = _forced_window(dbs)
        broker = dict(FZ.broker().stats)
        u1 = (UM._LAUNCHES.total(), UM._TRAFFIC.total())
    finally:
        FZ.configure(None)
        UM.uninstall()
        cfgmod.set_config(old_cfg)

    usage_report = {
        "billed_launches": u1[0] - u0[0],
        "broker_launches": broker["launches"] - b0["launches"],
        "billed_traffic_units": u1[1] - u0[1],
        "broker_traffic_units": (broker["traffic_units"]
                                 - b0["traffic_units"]),
    }
    usage_conserved = (
        usage_report["billed_launches"] == usage_report["broker_launches"]
        and usage_report["billed_traffic_units"]
        == usage_report["broker_traffic_units"])

    # the broker's device-dispatch accounting, priced by the committed
    # cost model: what the timed fused work actually launched vs the
    # tallied per-job alternative.  On a serial accelerator this ratio
    # IS the device-time saving; on this CPU backend it is a model
    # (see module docstring).
    d = {k: b_timed[k] - b0[k] for k in b_timed}
    modeled_solo_s = RB.estimate_seconds(
        d["alt_solo_units"], d["alt_solo_launches"], N_SEQ, 1)
    modeled_fused_s = RB.estimate_seconds(
        d["traffic_units"], d["launches"], N_SEQ, 1)
    modeled = {
        "launches": d["launches"],
        "alt_solo_launches": d["alt_solo_launches"],
        "traffic_units": d["traffic_units"],
        "alt_solo_units": d["alt_solo_units"],
        "modeled_fused_s": round(modeled_fused_s, 4),
        "modeled_solo_s": round(modeled_solo_s, 4),
        "speedup": round(modeled_solo_s / max(1e-9, modeled_fused_s), 2),
    }

    # service-side SLO vs the harness's offline measurement (ISSUE 9):
    # every flood above ran through the real Miner, so its finishes fed
    # /admin/slo's sliding windows.  Loose per-priority agreement —
    # the SLO e2e p99 must land within an order of magnitude of the
    # client-observed p50..p99 envelope across the timed modes (the
    # window also holds warm-flood samples; this is a consistency claim,
    # not a wall comparison).
    from spark_fsm_tpu.service import obsplane

    slo = obsplane.slo_snapshot()
    lo = 0.1 * min(unfused["p50_s"], fused["p50_s"])
    hi = 10.0 * max(unfused["p99_s"], fused["p99_s"])
    slo_rows = {}
    slo_ok = True
    for prio in obsplane.PRIORITIES:
        row = slo["priorities"][prio]["e2e"]
        slo_rows[prio] = row
        if row.get("count", 0) < 1:
            slo_ok = False  # every priority class was flooded
        elif not (lo <= row["p99"] <= hi):
            slo_ok = False

    # strict per-job parity: same dataset -> byte-identical rules, fused
    # or not (uids differ; compare via each row's dataset index)
    by_db_u = {}
    for _, (db_i, rules) in rows_u.items():
        by_db_u.setdefault(db_i, set()).add(rules)
    parity = all(len(v) == 1 for v in by_db_u.values())
    for _, (db_i, rules) in rows_f.items():
        parity = parity and {rules} == by_db_u[db_i]

    out = {
        "jobs": n_jobs, "workers": workers, "warm": warm,
        "unfused": unfused, "fused": fused,
        "speedup_jobs_per_sec": round(
            fused["jobs_per_sec"] / max(1e-9, unfused["jobs_per_sec"]), 2),
        "modeled_device_dispatch": modeled,
        "modeled_2x": modeled["speedup"] >= 2.0,
        "parity": parity,
        "forced_cross_job": forced["cross_job_launches"] >= 1,
        "forced_window": forced,
        "slo_consistent": slo_ok,
        "slo": {"window_s": slo["window_s"],
                "bounds_s": [round(lo, 4), round(hi, 4)],
                "e2e": slo_rows},
        "usage_conserved": bool(usage_conserved),
        "usage": usage_report,
        "broker": broker,
        "degraded": broker["degraded"],
        "sheds": unfused["sheds"] + fused["sheds"],
        "failures": unfused["failures"] + fused["failures"],
    }
    print(json.dumps(out, indent=2))

    if update:
        expect = {k: out[k] for k in COMPARED}
        with open(EXPECT_PATH, "w") as fh:
            json.dump(expect, fh, indent=2)
            fh.write("\n")
        print(f"bench_throughput: expectations rewritten -> {EXPECT_PATH}")
        return 0
    try:
        with open(EXPECT_PATH) as fh:
            expect = json.load(fh)
    except OSError:
        sys.exit(f"bench_throughput: no committed expectations at "
                 f"{EXPECT_PATH} (run with --update once)")
    bad = [k for k in COMPARED if out.get(k) != expect.get(k)]
    if bad:
        for k in bad:
            print(f"bench_throughput: MISMATCH {k}: "
                  f"got {out.get(k)!r}, expected {expect.get(k)!r}",
                  file=sys.stderr)
        return 1
    print("bench_throughput: structural expectations OK "
          f"(fused {fused['jobs_per_sec']} jobs/s vs unfused "
          f"{unfused['jobs_per_sec']} jobs/s, p99 {fused['p99_s']}s vs "
          f"{unfused['p99_s']}s — walls reported, never compared; "
          f"modeled device-dispatch speedup {modeled['speedup']}x over "
          f"{modeled['alt_solo_launches']} solo launches)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
