#!/usr/bin/env python
"""RUN_SLOW evidence harness -> SLOWTESTS.json (VERDICT r4 #2).

The RUN_SLOW-gated tests (mid-scale 8-mesh parity at >=10k candidates,
full-scale TSR) are exactly the capability evidence CI skips — and an
un-run test is not evidence.  This harness runs them with RUN_SLOW=1,
parses the junit report into per-test rows (id, wall, outcome), merges
the stats sidecar the tests append (candidate counts, pattern counts),
and commits the result as SLOWTESTS.json so every round carries a green
run's provenance, not just the tests' existence.

Selection: the two RUN_SLOW files the evidence demand names.  The
interpret-Pallas mesh variant in test_incremental.py is deliberately
NOT selected — 8 interpreted shards serialized on a 1-core box overrun
XLA's 40s collective rendezvous deadline and ABORT the process (see its
skip reason), which would take the whole evidence run down with it.

Usage: python slowtests.py   (takes tens of CPU-minutes on a 1-core box)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import xml.etree.ElementTree as ET

FILES = ["tests/test_midscale_multichip.py", "tests/test_tsr.py"]


def main() -> None:
    root = os.path.dirname(os.path.abspath(__file__))
    junit = tempfile.NamedTemporaryFile(suffix=".xml", delete=False).name
    stats_path = tempfile.NamedTemporaryFile(suffix=".jsonl",
                                             delete=False).name
    env = dict(os.environ, RUN_SLOW="1", SLOWTESTS_STATS=stats_path)
    # weak 1-core boxes: shrink the midscale SEQUENCE axis (the fused/
    # queue engines' dense per-wave pair matrices are CPU-bound there);
    # candidate width — the evidence — barely moves (see the fixture)
    if (os.cpu_count() or 1) <= 2:
        env.setdefault("MIDSCALE_SCALE", "0.35")
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", *FILES, "-q",
         f"--junit-xml={junit}"],
        cwd=root, env=env, capture_output=True, text=True)
    wall = time.monotonic() - t0

    tests = []
    counts = {"passed": 0, "failed": 0, "skipped": 0, "errors": 0}
    try:
        for case in ET.parse(junit).getroot().iter("testcase"):
            outcome = "passed"
            for child in case:
                if child.tag in ("failure", "error"):
                    outcome = "failed" if child.tag == "failure" else "errors"
                elif child.tag == "skipped":
                    outcome = "skipped"
            counts[outcome] += 1
            tests.append({
                "id": f"{case.get('classname')}::{case.get('name')}",
                "wall_s": round(float(case.get("time", 0)), 2),
                "outcome": outcome,
            })
    except ET.ParseError:
        pass

    stats_rows = []
    try:
        with open(stats_path) as fh:
            stats_rows = [json.loads(line) for line in fh if line.strip()]
    except OSError:
        pass
    by_test = {r.pop("test"): r for r in stats_rows}
    for t in tests:
        name = t["id"].rsplit("::", 1)[-1]
        if name in by_test:
            t["stats"] = by_test[name]

    out = {
        "ts": round(time.time(), 1),
        "cmd": f"RUN_SLOW=1 pytest {' '.join(FILES)} -q",
        "host_cores": os.cpu_count(),
        "pytest_wall_s": round(wall, 1),
        "exit_code": proc.returncode,
        "all_passed": proc.returncode == 0 and counts["failed"] == 0
        and counts["errors"] == 0,
        "counts": counts,
        "tests": tests,
        "tail": proc.stdout.strip().splitlines()[-3:],
        # an XLA abort (SIGABRT) reports on stderr, not stdout — keep
        # enough of it to diagnose a dead run from the artifact alone
        "stderr_tail": proc.stderr.strip().splitlines()[-6:],
    }
    path = os.path.join(root, "SLOWTESTS.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, path)
    print(json.dumps({k: out[k] for k in
                      ("all_passed", "counts", "pytest_wall_s")}))
    for fn in (junit, stats_path):
        try:
            os.unlink(fn)
        except OSError:
            pass
    sys.exit(proc.returncode)


if __name__ == "__main__":
    main()
