#!/usr/bin/env python
"""Eval-config benchmark suite — all five BASELINE.md configs on one chip.

``bench.py`` remains the driver's single-line headline harness (config #0,
the north-star workload); this suite exercises the OTHER eval configs the
reference is judged on, each scaled so the whole suite fits interactive
wall-clock, each with parity attested against the CPU oracle:

  1. SPADE on BMS-WebView-1-shaped   (minsup 1%), single chip
  2. SPADE on MSNBC-shaped           (minsup 0.5%), seq-axis mesh path
  3. TSR top-k rules on Kosarak-shaped (k=100, minconf=0.5), device engine
  4. cSPADE on Gazelle-shaped        (maxgap=2, maxwindow=5)
  5. streaming incremental SPADE     (sliding window, per-window parity)

Prints one JSON line per config and writes the collected results to
``BENCH_SUITE.json`` (with platform + timestamp) unless BENCH_SUITE_OUT=0.
Scale knobs: BENCH_SUITE_SCALE (default 0.2) multiplies every dataset's
size so a full-size run is one env var away — EXCEPT config 1, which runs
at ``min(1, scale*5)`` (full size by default; its oracle check is cheap,
see the config-1 comment).

The real public datasets are unreachable (zero-egress sandbox); the seeded
synthetic generators in data/synth.py match each dataset's documented
shape, and the metric strings say so.
"""

import json
import os
import sys
import time

from spark_fsm_tpu.utils.probe import tpu_probe


def main() -> None:
    from spark_fsm_tpu.utils.jitcache import enable_compile_cache
    enable_compile_cache()  # compiles persist across runs (cold-start win)
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        reason = "JAX_PLATFORMS=cpu requested"
    else:
        reason = tpu_probe(float(os.environ.get("BENCH_TPU_WAIT", "60")))
    import jax
    if reason:
        print(f"bench_suite: CPU fallback — {reason}", file=sys.stderr)
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    from spark_fsm_tpu.data.synth import (
        bms_webview1_like, gazelle_like, kosarak_like, msnbc_like)
    from spark_fsm_tpu.data.vertical import abs_minsup
    from spark_fsm_tpu.models.oracle import mine_cspade, mine_spade
    from spark_fsm_tpu.models.spade_constrained import mine_cspade_tpu
    from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu
    from spark_fsm_tpu.models.tsr import mine_tsr_cpu, mine_tsr_tpu
    from spark_fsm_tpu.parallel.mesh import make_mesh
    from spark_fsm_tpu.streaming.window import WindowMiner
    from spark_fsm_tpu.utils.canonical import patterns_text, rules_text

    scale = float(os.environ.get("BENCH_SUITE_SCALE", "0.2"))
    platform = jax.devices()[0].platform
    results = []

    def record(config, name, fn, oracle_fn, text_fn, warm=True, db=None,
               stats=None):
        if db is not None and not db:
            print(json.dumps({"config": config, "skipped":
                              f"scale {scale} yields an empty database"}),
                  flush=True)
            return
        t0 = time.perf_counter()
        got = fn()
        cold = time.perf_counter() - t0
        wall = cold
        if warm:  # steady state: compiles cached from the cold run
            if stats is not None:
                stats.clear()  # keep only the measured pass's stats
            t0 = time.perf_counter()
            got = fn()
            wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        want = oracle_fn()
        oracle_wall = time.perf_counter() - t0
        row = {
            "config": config,
            "metric": name,
            "results": len(got),
            "wall_s": round(wall, 3),
            "cold_wall_s": round(cold, 3),
            "oracle_wall_s": round(oracle_wall, 3),
            "speedup_vs_oracle": round(oracle_wall / wall, 2) if wall else 0.0,
            "parity": text_fn(got) == text_fn(want),
            "platform": platform,
        }
        if stats is not None:
            # engine route diagnostics: which engine actually ran (fused vs
            # classic DFS), whether a static cap pushed it back to classic,
            # and whether a kernel fault downgraded Pallas mid-mine.
            # `route` only exists for engines that HAVE a routing decision
            # (mine_spade_tpu always records `fused`; TSR/cSPADE have no
            # fused engine, so emitting "classic" for them would imply a
            # decision that was never made)
            if "fused" in stats:
                # record the actual engine (obs.engine_route) so a queue
                # regression is distinguishable from a dense one
                from spark_fsm_tpu.utils.obs import engine_route
                row["route"] = engine_route(stats)
            for key in ("fused_overflow", "fused_skipped", "kernel_launches",
                        "store_cache_hit"):
                if stats.get(key) is not None:
                    row[key] = stats[key]
            # mid-mine Pallas downgrades: SPADE records "pallas_fallback",
            # TSR one key per failed km bucket ("pallas_fallback_km2") —
            # match by prefix so neither engine's faults go unreported
            for key, val in stats.items():
                if key.startswith("pallas_fallback"):
                    row[key] = val
        results.append(row)
        print(json.dumps(row), flush=True)

    # 1. SPADE, BMS-WebView-1-shaped, minsup 1% — run at FULL size (the
    # actual eval config).  What the reduced-scale knob buys elsewhere is
    # a cheap CPU-oracle parity check; config 1's full-size oracle is
    # sub-second (48 patterns at 1%), so full size costs nothing here,
    # while configs 2-5 keep the knob because THEIR oracle checks grow
    # into minutes at full size.  scale*5 < 1 still shrinks config 1.
    s1 = min(1.0, scale * 5)
    db1 = bms_webview1_like(scale=s1)
    ms1 = abs_minsup(0.01, len(db1))
    st1: dict = {}
    # through the SERVICE-DEFAULT path incl. the device-store cache
    # (service/devcache.py): the warm pass is a repeat mine over
    # identical data, so it reuses the HBM store + compiled engine —
    # store_cache_hit in the row attests which side was measured
    from spark_fsm_tpu.service.devcache import spade_engine_cache
    record(1, f"SPADE synthetic BMS-WebView-1-shaped x{s1:g} minsup=1%",
           lambda: spade_engine_cache.mine(db1, ms1, stats_out=st1),
           lambda: mine_spade(db1, ms1), patterns_text, db=db1, stats=st1)

    # 2. SPADE, MSNBC-shaped, minsup 0.5%, through the mesh (shard_map+psum)
    # path — on a 1-chip box this still exercises the sharded program.
    db2 = msnbc_like(scale=scale * 0.5)  # msnbc is ~1M seqs; halve again
    ms2 = abs_minsup(0.005, len(db2))
    mesh = make_mesh(len(jax.devices()))
    st2: dict = {}
    record(2, f"SPADE synthetic MSNBC-shaped mesh({mesh.devices.size}) minsup=0.5%",
           lambda: mine_spade_tpu(db2, ms2, mesh=mesh, stats_out=st2),
           lambda: mine_spade(db2, ms2), patterns_text, db=db2, stats=st2)

    # 3. TSR top-k rules, Kosarak-shaped
    db3 = kosarak_like(scale=scale * 0.5)
    st3: dict = {}
    record(3, "TSR_TPU synthetic Kosarak-shaped k=100 minconf=0.5",
           lambda: mine_tsr_tpu(db3, 100, 0.5, max_side=2, stats_out=st3),
           lambda: mine_tsr_cpu(db3, 100, 0.5, max_side=2), rules_text,
           warm=False, db=db3, stats=st3)  # minutes-long: one run, cold == wall

    # 4. cSPADE, Gazelle-shaped, maxgap=2 maxwindow=5
    db4 = gazelle_like(scale=scale)
    ms4 = abs_minsup(0.005, len(db4))
    st4: dict = {}
    record(4, f"cSPADE synthetic Gazelle-shaped maxgap=2 maxwindow=5 minsup=0.5%",
           lambda: mine_cspade_tpu(db4, ms4, maxgap=2, maxwindow=5,
                                   stats_out=st4),
           lambda: mine_cspade(db4, ms4, maxgap=2, maxwindow=5), patterns_text,
           db=db4, stats=st4)

    # 5. streaming incremental SPADE: sliding window over micro-batches,
    # parity of EVERY window state vs a fresh oracle mine of that window
    db5 = bms_webview1_like(scale=scale, seed=9)
    if not db5:
        print(json.dumps({"config": 5, "skipped":
                          f"scale {scale} yields an empty database"}),
              flush=True)
    else:
        n_batches = min(6, len(db5))  # tiny scales: one sequence per batch
        per = len(db5) // n_batches
        batches = [
            db5[i * per: (i + 1) * per if i < n_batches - 1 else len(db5)]
            for i in range(n_batches)]  # remainder rides the last batch
        stream_parity = True

        def run_stream(check_parity):
            nonlocal stream_parity
            wm = WindowMiner(0.02, max_batches=3)
            wall = 0.0
            for batch in batches:
                t0 = time.perf_counter()
                got = wm.push(batch)
                wall += time.perf_counter() - t0  # pushes only — the
                if check_parity:  # per-window oracle mines are the CHECK,
                    window_db = wm.window.sequences()  # not the workload
                    want = mine_spade(window_db, wm.minsup_abs())
                    stream_parity &= patterns_text(got) == patterns_text(want)
            return wm, wall

        # same cold/warm split as configs 1-4: the first pass pays the
        # window-shape compiles, the second (fresh miner, same shapes)
        # measures steady-state push cost
        wm, cold = run_stream(check_parity=True)
        wm, wall = run_stream(check_parity=False)
        row = {
            "config": 5,
            "metric": (f"streaming SPADE sliding-window({n_batches} "
                       f"micro-batches, keep 3) minsup=2%"),
            "results": len(wm.patterns),
            "wall_s": round(wall, 3),
            "cold_wall_s": round(cold, 3),
            "pushes": wm.stats["pushes"],
            "parity": stream_parity,  # every window state vs fresh oracle
            "platform": platform,
        }
        results.append(row)
        print(json.dumps(row), flush=True)

    if os.environ.get("BENCH_SUITE_OUT") != "0":
        out = {
            "scale": scale,
            "ts": round(time.time(), 1),
            "platform": platform,
            "all_parity": all(r["parity"] for r in results),
            "config1_scale": s1,
            "note": ((f"configs 2-5 run at reduced scale (full-size oracle "
                      f"parity checks cost minutes); config 1 ran at scale "
                      f"{s1:g}"
                      + (" — the actual full-size eval config.  Its "
                         "workload is tiny (2 levels, ~1.8k candidates), "
                         "so on THIS tunneled single chip the device mine "
                         "is transfer/latency-bound, not compute-bound: "
                         "measured tunnel floor ~0.1 s per roundtrip and "
                         "~10-16 MB/s host<->device, so the per-mine token "
                         "upload (~2.4 MB) plus two roundtrips costs "
                         "~0.3 s before any mining happens, while the CPU "
                         "oracle pays none of it (~0.25 s total).  The "
                         "fused route (engaged, see route field) closes "
                         "most of the gap (~0.35 s); on a production "
                         "local-PCIe TPU host the same fixed costs are "
                         "~1 ms and the device wins outright.  The device "
                         "win grows with workload — see configs 2-4"
                         if s1 == 1.0 else "")
                      + " (headline: see BASELINE.json published). "
                      "cold_wall_s includes XLA compiles whenever the "
                      "persistent compile cache has no entry for the "
                      "current kernel shapes — any engine/kernel change "
                      "recompiles once")),
            "configs": results,
        }
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_SUITE.json")
        with open(path, "w") as fh:
            json.dump(out, fh, indent=2)
            fh.write("\n")


if __name__ == "__main__":
    sys.exit(main())
