"""Full-scale spot-check harness (BASELINE.md configs at REAL dataset size).

`bench_suite.py` runs all five eval configs at reduced scale so every run
can attest oracle parity (full-size oracle mines take minutes to hours);
this harness runs the configs at scale=1.0 WITHOUT the oracle to prove
the engines handle the real sizes — the memory plans, shape bucketing,
and launch sizing, not just the algorithmic speedups.  Parity at full
scale is still guaranteed transitively: the engines are byte-identical to
the oracles at every tested scale and contain no scale-dependent branches
that change WHAT is enumerated (only HOW wide the launches are) — and
config 2's `--parity` runs the one full-size oracle that IS feasible.

Each config prints one JSON line; unless BENCH_SCALE_OUT=0 the collected
lines are also written to ``BENCH_SCALE.json`` (the committed artifact —
every full-scale number quoted in README/OPERATIONS must trace to it).
Synthetic data uses the vectorized generators (`fast=True`, see
data/synth.py — a full Kosarak draw takes seconds instead of ~35 min).

Configs: 2 (full MSNBC SPADE, mesh path), 3 (full Kosarak TSR,
max_side=2), 3d (same but the service DEFAULT — unlimited rule sides,
routed to the RESIDENT-FRONTIER path since ISSUE 7), 3r (3d with
resident routing pinned off — the host-loop reference the 3d collapse
is measured against), 4 (full Gazelle cSPADE, maxgap=2/maxwindow=5),
5 (full-scale sliding window on the INCREMENTAL service-default route:
per-push walls + repair counters), 5r (same stream on the re-mine
fallback: window-scaled walls + the compiled-shape count that proves
shape_buckets bounds recompiles).

Usage: python bench_scale.py [--parity] [2 3 3d 3r 4 5 5r]  (default:
all; --parity additionally runs the full-size oracle where feasible —
configs 2 and 4, and per-push window oracles for 5 — attesting
byte-identical pattern sets; 3/3d/3r have no feasible full-size oracle)
"""

from __future__ import annotations

import json
import os
import sys
import time

from spark_fsm_tpu.utils.obs import engine_route as _route


def config2(parity: bool = False) -> dict:
    """SPADE over the full MSNBC-shaped DB (990k seqs, mesh path).

    ``parity``: also run the NumPy oracle on the full DB (~1 min) and
    attest byte-identical pattern sets at real size — the only eval
    config whose oracle is feasible at scale=1.0.
    """
    import jax

    from spark_fsm_tpu.data.synth import msnbc_like
    from spark_fsm_tpu.data.vertical import abs_minsup
    from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu
    from spark_fsm_tpu.parallel.mesh import make_mesh

    t0 = time.monotonic()
    db = msnbc_like(scale=1.0, fast=True)
    t1 = time.monotonic()
    ms = abs_minsup(0.005, len(db))
    mesh = make_mesh(len(jax.devices()))
    stats: dict = {}
    cold0 = time.monotonic()
    pats = mine_spade_tpu(db, ms, mesh=mesh, stats_out=stats)
    cold1 = time.monotonic()
    stats = {}
    warm0 = time.monotonic()
    pats2 = mine_spade_tpu(db, ms, mesh=mesh, stats_out=stats)
    warm1 = time.monotonic()
    assert pats == pats2
    out = {
        "config": "2", "scale": 1.0,
        "metric": "SPADE synthetic MSNBC-shaped FULL (990k seqs) "
                  f"mesh({mesh.devices.size}) minsup=0.5%",
        "sequences": len(db), "patterns": len(pats),
        "datagen_s": round(t1 - t0, 2),
        "cold_wall_s": round(cold1 - cold0, 2),
        "wall_s": round(warm1 - warm0, 2),
        "route": _route(stats),
        "fused_overflow": bool(stats.get("fused_overflow")),
        "platform": jax.default_backend(),
    }
    if parity:
        from spark_fsm_tpu.models.oracle import mine_spade
        from spark_fsm_tpu.utils.canonical import patterns_text

        o0 = time.monotonic()
        want = mine_spade(db, ms)
        o1 = time.monotonic()
        out["oracle_wall_s"] = round(o1 - o0, 2)
        out["parity"] = patterns_text(pats) == patterns_text(want)
        out["speedup_vs_oracle"] = round(out["oracle_wall_s"]
                                         / max(out["wall_s"], 1e-9), 2)
    return out


def _tsr(max_side, tag: str, note: str, resident: str = "auto") -> dict:
    """TSR top-k over the full Kosarak-shaped DB (990k seqs, 39.6k items)."""
    import jax

    from spark_fsm_tpu.data.synth import kosarak_like
    from spark_fsm_tpu.data.vertical import build_vertical
    from spark_fsm_tpu.models.tsr import TsrTPU

    t0 = time.monotonic()
    db = kosarak_like(scale=1.0, fast=True)
    t1 = time.monotonic()
    vdb = build_vertical(db, min_item_support=1)
    t2 = time.monotonic()
    eng = TsrTPU(vdb, 100, 0.5, max_side=max_side, resident=resident)
    t3 = time.monotonic()
    rules = eng.mine()
    t4 = time.monotonic()
    out = {
        "config": tag, "scale": 1.0,
        "metric": "TSR_TPU synthetic Kosarak-shaped FULL "
                  f"(990k x 39.6k) k=100 minconf=0.5 {note}",
        "sequences": vdb.n_sequences, "items": vdb.n_items,
        "rules": len(rules),
        "datagen_s": round(t1 - t0, 2),
        "vertical_build_s": round(t2 - t1, 2),
        "wall_s": round(t4 - t3, 2),
        "evaluated": eng.stats["evaluated"],
        "kernel_launches": eng.stats["kernel_launches"],
        "platform": jax.default_backend(),
    }
    # per-km decomposition (models/tsr.py per-bucket counters): padded
    # width x km is the kernel's per-candidate traffic unit, so these
    # separate candidate-mix cost (irreducible) from launch packing
    per_km = {k: v for k, v in sorted(eng.stats.items())
              if k.startswith(("evaluated_km", "launches_km", "width_km",
                               "borrowed_km"))}
    if per_km:
        out["per_km"] = per_km
    # super-batch / pruning counters (ops/ragged_batch.py + the TSR
    # conf-bound pruning): the engine maintains traffic_units itself
    # (width x geometry-km summed over launches, jnp path included)
    out["traffic_units"] = eng.stats.get("traffic_units")
    out["superbatches"] = eng.stats.get("superbatches", 0)
    out["pruned_conf"] = eng.stats.get("pruned_conf", 0)
    out["pruned_conf_chains"] = eng.stats.get("pruned_conf_chains", 0)
    # resident-frontier counters (ops/resident_frontier.py): present
    # only when the planner routed (part of) the mine on-device —
    # the 3d-vs-3r decomposition reads straight off these
    from spark_fsm_tpu.models.tsr import resident_counters

    out.update(resident_counters(eng.stats))
    return out


def config3() -> dict:
    return _tsr(2, "3", "max_side=2")


def config3d() -> dict:
    # the honest default-path number: the service leaves rule sides
    # UNCAPPED unless the request sets max_side (docs/OPERATIONS.md
    # knob); since ISSUE 7 the planner routes this shape to the
    # RESIDENT-FRONTIER path (whole km-ladders in one dispatch), so
    # this row carries the resident counters
    return _tsr(None, "3d", "max_side unlimited (service default)")


def config3r() -> dict:
    # the host-loop REFERENCE for 3d: same workload with resident
    # routing pinned off — the expand/readback/re-plan loop the
    # resident path replaces, kept runnable so hardware sessions can
    # measure the 3d-vs-3r collapse side by side
    return _tsr(None, "3r", "max_side unlimited, resident=never "
                "(host-loop reference)", resident="never")


def config4(parity: bool = False) -> dict:
    """cSPADE over the full Gazelle-shaped DB (59k seqs), maxgap/maxwindow.

    ``parity``: also run the NumPy cSPADE oracle at full size (minutes —
    the engine's 35 s scale-0.2 oracle extrapolates to low single
    digits) and attest byte-identical constrained pattern sets.
    """
    import jax

    from spark_fsm_tpu.data.synth import gazelle_like
    from spark_fsm_tpu.data.vertical import abs_minsup
    from spark_fsm_tpu.models.spade_constrained import mine_cspade_tpu

    t0 = time.monotonic()
    db = gazelle_like(scale=1.0, fast=True)
    t1 = time.monotonic()
    ms = abs_minsup(0.005, len(db))
    stats: dict = {}
    cold0 = time.monotonic()
    pats = mine_cspade_tpu(db, ms, maxgap=2, maxwindow=5, stats_out=stats)
    cold1 = time.monotonic()
    warm0 = time.monotonic()
    pats2 = mine_cspade_tpu(db, ms, maxgap=2, maxwindow=5)
    warm1 = time.monotonic()
    assert pats == pats2
    out = {
        "config": "4", "scale": 1.0,
        "metric": "cSPADE synthetic Gazelle-shaped FULL (59k seqs) "
                  "maxgap=2 maxwindow=5 minsup=0.5%",
        "sequences": len(db), "patterns": len(pats),
        "datagen_s": round(t1 - t0, 2),
        "cold_wall_s": round(cold1 - cold0, 2),
        "wall_s": round(warm1 - warm0, 2),
        "kernel_launches": stats.get("kernel_launches"),
        "platform": jax.default_backend(),
    }
    if parity:
        from spark_fsm_tpu.models.oracle import mine_cspade
        from spark_fsm_tpu.utils.canonical import patterns_text

        o0 = time.monotonic()
        want = mine_cspade(db, ms, maxgap=2, maxwindow=5)
        o1 = time.monotonic()
        out["oracle_wall_s"] = round(o1 - o0, 2)
        out["parity"] = patterns_text(pats) == patterns_text(want)
        out["speedup_vs_oracle"] = round(out["oracle_wall_s"]
                                         / max(out["wall_s"], 1e-9), 2)
    return out


def _stream_batches():
    from spark_fsm_tpu.data.synth import msnbc_like

    t0 = time.monotonic()
    db = msnbc_like(scale=1.0, fast=True)
    t1 = time.monotonic()
    n_push, keep = 10, 5
    per = len(db) // n_push
    batches = [db[i * per: (i + 1) * per if i < n_push - 1 else len(db)]
               for i in range(n_push)]
    return batches, n_push, keep, per, round(t1 - t0, 2)


def config5(parity: bool = False) -> dict:
    """Full-scale streaming, SERVICE-DEFAULT route: true incremental
    mining (streaming/incremental.py — count the arriving batch, evict
    by subtraction, border repair).  10 MSNBC-shaped micro-batches
    (~99k seqs each), keep 5.  The point of the row: steady-state push
    wall scales with the BATCH, not the 495k-seq window (config 5r is
    the re-mine comparison), and the repair counters prove steady pushes
    ride the sweep.

    ``parity``: per-push full-window oracle mines (~10 x ~1 min) attest
    the incremental state byte-identical to a fresh mine at real size.
    """
    import jax

    from spark_fsm_tpu.streaming.incremental import IncrementalWindowMiner

    batches, n_push, keep, per, datagen_s = _stream_batches()
    wm = IncrementalWindowMiner(0.005, max_batches=keep)
    walls, repaired, phases, parities = [], [], [], []
    snaps = []  # per-push (window, minsup, patterns) for DEFERRED parity
    for batch in batches:
        before = wm.stats["repaired_nodes"]
        p0 = time.monotonic()
        wm.push(batch)
        walls.append(round(time.monotonic() - p0, 2))
        repaired.append(wm.stats["repaired_nodes"] - before)
        # the miner's own phase breakdown (tokens/sweep/repair/prune) —
        # committed per push so wall spikes are attributable from the
        # artifact (VERDICT r4 weak #3: a 27 s push that repaired 127
        # nodes needs its time accounted, not hand-waved to contention)
        phases.append(wm.stats.get("phase_s"))
        if parity:
            # snapshot now, mine the oracle AFTER the loop: an in-loop
            # oracle (~1 min of CPU grind per push) contends with the
            # next push's host phases and corrupts the committed walls
            # (measured: ~9 s token-phase spikes from exactly this).
            # Batches are frozen shallow copies, so the 5 references
            # ARE the window content — no per-push O(window) flatten
            snaps.append((wm.window.batches(), wm.minsup_abs(),
                          list(wm.patterns)))
    if parity:
        from spark_fsm_tpu.models.oracle import mine_spade
        from spark_fsm_tpu.utils.canonical import patterns_text

        for win_batches, ms, pats in snaps:
            seqs = [s for b in win_batches for s in b]
            want = mine_spade(seqs, ms)
            parities.append(patterns_text(pats) == patterns_text(want))
    out = {
        "config": "5", "scale": 1.0,
        "metric": f"streaming SPADE sliding-window FULL ({n_push} "
                  f"MSNBC-shaped micro-batches of ~{per // 1000}k seqs, "
                  f"keep {keep}) minsup=0.5% — INCREMENTAL (service "
                  "default)",
        "datagen_s": datagen_s,
        "pushes": n_push,
        "window_sequences": wm.window.n_sequences,
        "patterns": len(wm.patterns),
        "per_push_wall_s": walls,
        "per_push_phase_s": phases,
        "steady_push_wall_s": round(
            sorted(walls[keep:])[len(walls[keep:]) // 2], 2),
        "route": wm.stats["route"],
        "repaired_nodes_per_push": repaired,
        "tracked_nodes": wm.stats["tracked_nodes"],
        "border_nodes": wm.stats["border_nodes"],
        "sweep_candidates": wm.stats["sweep_candidates"],
        "platform": jax.default_backend(),
    }
    if parity:
        out["parity"] = all(parities)
        out["parity_per_push"] = parities
    return out


def config5r() -> dict:
    """Full-scale streaming, RE-MINE fallback route (streaming/window.py
    with incremental pinned off — the pre-incremental baseline and the
    path constrained/TSR windows still use).  Same batches as config 5;
    per-push walls scale with the window, and the distinct compiled-shape
    count proves shape_buckets bounds recompiles."""
    import jax

    from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu
    from spark_fsm_tpu.streaming.window import WindowMiner

    batches, n_push, keep, per, datagen_s = _stream_batches()
    shape_keys = set()
    push_stats: dict = {}

    def mine(window_db, minsup_abs):
        push_stats.clear()
        res = mine_spade_tpu(window_db, minsup_abs, shape_buckets=True,
                             stats_out=push_stats)
        if push_stats.get("shape_key"):
            shape_keys.add(push_stats["shape_key"])
        return res

    wm = WindowMiner(0.005, max_batches=keep, mine=mine)
    walls = []
    routes = []
    for batch in batches:
        p0 = time.monotonic()
        wm.push(batch)
        walls.append(round(time.monotonic() - p0, 2))
        routes.append(_route(push_stats))
    return {
        "config": "5r", "scale": 1.0,
        "metric": f"streaming SPADE sliding-window FULL ({n_push} "
                  f"MSNBC-shaped micro-batches of ~{per // 1000}k seqs, "
                  f"keep {keep}) minsup=0.5% — RE-MINE fallback",
        "datagen_s": datagen_s,
        "pushes": n_push,
        "window_sequences": wm.window.n_sequences,
        "patterns": len(wm.patterns),
        "per_push_wall_s": walls,
        "steady_push_wall_s": round(
            sorted(walls[keep:])[len(walls[keep:]) // 2], 2),
        "routes": routes,
        "distinct_compiled_shapes": len(shape_keys),
        "shape_keys": sorted(shape_keys),
        "platform": jax.default_backend(),
    }


def mesh_sweep() -> list:
    """Scaling-curve row set over 1/2/4/8 virtual CPU devices
    (``--mesh-sweep``): config-2/3 miniatures per device count, the TSR
    rows routed through the equivalence-class PARTITIONED 2-D mesh
    (parallel/partition.py) where the device count allows an outer
    axis.  Exports the partition counters — class imbalance ratio,
    threshold-exchange rounds, cross-partition bytes — so the curve
    shows the partitioned regime's collectives scaling with ROUNDS
    while the data-parallel psum path scales with launches.  Rows merge
    into BENCH_SCALE.json by config key like every other config; walls
    on virtual devices are shape checks, not performance claims (all
    eight "devices" timeshare this host's cores)."""
    import jax

    from spark_fsm_tpu.data.synth import kosarak_like, msnbc_like
    from spark_fsm_tpu.data.vertical import abs_minsup
    from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu
    from spark_fsm_tpu.models.tsr import mine_tsr_tpu
    from spark_fsm_tpu.parallel.mesh import make_mesh
    from spark_fsm_tpu.utils.canonical import rules_text

    # outer-axis split per device count: d=2 is partition-only (one
    # device per row = the engines' single-device path), d=4/8 are true
    # 2-D parts x seq arrangements
    parts_of = {1: 1, 2: 2, 4: 2, 8: 4}
    db2 = msnbc_like(scale=0.002, fast=True)
    ms = abs_minsup(0.005, len(db2))
    db3 = kosarak_like(scale=0.002, fast=True)
    rows = []
    ref_rules = None
    for d in (1, 2, 4, 8):
        if d > len(jax.devices()):
            break
        mesh = make_mesh(d) if d > 1 else None
        sstats: dict = {}
        t0 = time.monotonic()
        pats = mine_spade_tpu(db2, ms, mesh=mesh, stats_out=sstats)
        rows.append({
            "config": f"m2-d{d}", "devices": d,
            "metric": "mesh-sweep SPADE msnbc-miniature (data-parallel "
                      "seq shard, per-wave psum)",
            "patterns": len(pats), "route": _route(sstats),
            "wall_s": round(time.monotonic() - t0, 2),
            "platform": jax.default_backend(),
        })
        parts = parts_of[d]
        tstats: dict = {}
        t0 = time.monotonic()
        rules = mine_tsr_tpu(db3, 100, 0.5, max_side=2, mesh=mesh,
                             partition_parts=parts if parts > 1 else 0,
                             stats_out=tstats)
        if ref_rules is None:
            ref_rules = rules_text(rules)
        row = {
            "config": f"m3-d{d}", "devices": d, "parts": parts,
            "inner_devices": d // parts,
            "metric": "mesh-sweep TSR kosarak-miniature (equivalence-"
                      "class partitioned 2-D mesh)",
            "rules": len(rules),
            "parity_vs_d1": rules_text(rules) == ref_rules,
            "wall_s": round(time.monotonic() - t0, 2),
            "kernel_launches": tstats.get("kernel_launches"),
            "evaluated": tstats.get("evaluated"),
            "traffic_units": tstats.get("traffic_units"),
            "partition_imbalance": tstats.get("partition_imbalance"),
            "partition_exchanges": tstats.get("partition_exchanges", 0),
            "partition_cross_bytes": tstats.get("partition_cross_bytes",
                                                0),
            "deepening_rounds": tstats.get("deepening_rounds"),
            "platform": jax.default_backend(),
        }
        rows.append(row)
    return rows


def main() -> None:
    args = sys.argv[1:]
    if "--mesh-sweep" in args:
        # the sweep needs the 8 virtual CPU devices BEFORE the first
        # backend init; jax.config.update pins the platform past the
        # sandbox's ambient plugin env (see tests/conftest.py)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    from spark_fsm_tpu.utils.jitcache import enable_compile_cache

    enable_compile_cache()
    runners = {"2": config2, "3": config3, "3d": config3d,
               "3r": config3r, "4": config4, "5": config5,
               "5r": config5r}
    parity_capable = {"2", "4", "5"}  # feasible full-size oracles
    parity = "--parity" in args
    sweep = "--mesh-sweep" in args
    which = [a for a in args if a not in ("--parity", "--mesh-sweep")]
    if sweep:
        if which or parity:
            # refusing beats silently skipping: an operator combining
            # --mesh-sweep with config names would believe those rows
            # were re-measured when the sweep branch never ran them
            sys.exit("--mesh-sweep runs its own fixed row set and "
                     "cannot be combined with config names or --parity "
                     f"(got {sys.argv[1:]})")
        rows = mesh_sweep()
        for row in rows:
            print(json.dumps(row), flush=True)
        _write_rows(rows)
        return
    if not which:
        which = list(runners)
    if not set(which) <= set(runners):
        sys.exit(f"usage: python bench_scale.py [--parity] "
                 f"[{' '.join(runners)}]"
                 f" — full-scale spot-check configs (got {sys.argv[1:]})")
    if parity and not (set(which) & parity_capable):
        sys.exit("--parity needs at least one parity-capable config "
                 f"({sorted(parity_capable)}); configs 3/3d have no "
                 "feasible full-size oracle (219 s at scale 0.2)")
    rows = []
    for n in dict.fromkeys(which):  # de-dup, keep order
        kwargs = {"parity": parity} if n in parity_capable else {}
        row = runners[n](**kwargs)
        rows.append(row)
        print(json.dumps(row), flush=True)
    _write_rows(rows)


def _write_rows(rows) -> None:
    if os.environ.get("BENCH_SCALE_OUT") != "0":
        import jax

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_SCALE.json")
        # merge by config key: a partial run (e.g. `bench_scale.py 5`)
        # refreshes only its own rows — it must never clobber the other
        # configs' committed records (README/OPERATIONS trace to them)
        merged = {}
        try:
            with open(path) as fh:
                for r in json.load(fh).get("configs", []):
                    merged[str(r.get("config"))] = r
        except (OSError, ValueError):
            pass
        for r in rows:
            merged[str(r["config"])] = dict(r, ts=round(time.time(), 1))
        out = {
            "ts": round(time.time(), 1),
            "platform": jax.default_backend(),
            "note": ("full-scale spot checks on one chip via the tunneled "
                     "relay; synthetic shaped generators stand in for the "
                     "unreachable public datasets (zero-egress sandbox). "
                     "Walls on this shared host swing with contention — "
                     "see BASELINE.json published best/latest for the "
                     "measured spread on the headline workload.  Rows "
                     "merge by config key (partial runs refresh only "
                     "their own rows; per-row ts is the row's run)."),
            "configs": [merged[k] for k in sorted(merged)],
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(out, fh, indent=2)
            fh.write("\n")
        os.replace(tmp, path)


if __name__ == "__main__":
    main()
