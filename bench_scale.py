"""Full-scale spot-check harness (BASELINE.md configs at REAL dataset size).

`bench_suite.py` runs all five eval configs at reduced scale so every run
can attest oracle parity (full-size oracle mines take minutes to hours);
this harness runs selected configs at scale=1.0 WITHOUT the oracle to
prove the engines handle the real sizes — the memory plans, shape
bucketing, and launch sizing, not just the algorithmic speedups.  Parity
at full scale is still guaranteed transitively: the engines are
byte-identical to the oracles at every tested scale and contain no
scale-dependent branches that change WHAT is enumerated (only HOW wide
the launches are).

Each config prints one JSON line.  Synthetic data uses the vectorized
generators (`fast=True`, see data/synth.py — a full Kosarak draw takes
seconds instead of ~35 minutes).

Usage: python bench_scale.py [--parity] [2] [3]   (default: both configs;
--parity additionally runs the full-size oracle where feasible — config 2
only — and attests byte-identical pattern sets)
"""

from __future__ import annotations

import json
import sys
import time


def config2(parity: bool = False) -> dict:
    """SPADE over the full MSNBC-shaped DB (990k seqs, mesh path).

    ``parity``: also run the NumPy oracle on the full DB (~1 min) and
    attest byte-identical pattern sets at real size — the only eval
    config whose oracle is feasible at scale=1.0.
    """
    import jax

    from spark_fsm_tpu.data.synth import msnbc_like
    from spark_fsm_tpu.data.vertical import abs_minsup
    from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu
    from spark_fsm_tpu.parallel.mesh import make_mesh

    t0 = time.monotonic()
    db = msnbc_like(scale=1.0, fast=True)
    t1 = time.monotonic()
    ms = abs_minsup(0.005, len(db))
    mesh = make_mesh(len(jax.devices()))
    stats: dict = {}
    cold0 = time.monotonic()
    pats = mine_spade_tpu(db, ms, mesh=mesh, stats_out=stats)
    cold1 = time.monotonic()
    warm0 = time.monotonic()
    pats2 = mine_spade_tpu(db, ms, mesh=mesh)
    warm1 = time.monotonic()
    assert pats == pats2
    out = {
        "config": 2, "scale": 1.0,
        "metric": "SPADE synthetic MSNBC-shaped FULL (990k seqs) "
                  f"mesh({mesh.devices.size}) minsup=0.5%",
        "sequences": len(db), "patterns": len(pats),
        "datagen_s": round(t1 - t0, 2),
        "cold_wall_s": round(cold1 - cold0, 2),
        "wall_s": round(warm1 - warm0, 2),
        "fused": bool(stats.get("fused")),
        "platform": jax.default_backend(),
    }
    if parity:
        from spark_fsm_tpu.models.oracle import mine_spade
        from spark_fsm_tpu.utils.canonical import patterns_text

        o0 = time.monotonic()
        want = mine_spade(db, ms)
        o1 = time.monotonic()
        out["oracle_wall_s"] = round(o1 - o0, 2)
        out["parity"] = patterns_text(pats) == patterns_text(want)
        out["speedup_vs_oracle"] = round(out["oracle_wall_s"]
                                         / max(out["wall_s"], 1e-9), 2)
    return out


def config3() -> dict:
    """TSR top-k over the full Kosarak-shaped DB (990k seqs, 39.6k items)."""
    import jax

    from spark_fsm_tpu.data.synth import kosarak_like
    from spark_fsm_tpu.data.vertical import build_vertical
    from spark_fsm_tpu.models.tsr import TsrTPU

    t0 = time.monotonic()
    db = kosarak_like(scale=1.0, fast=True)
    t1 = time.monotonic()
    vdb = build_vertical(db, min_item_support=1)
    t2 = time.monotonic()
    eng = TsrTPU(vdb, 100, 0.5, max_side=2)
    t3 = time.monotonic()
    rules = eng.mine()
    t4 = time.monotonic()
    return {
        "config": 3, "scale": 1.0,
        "metric": "TSR_TPU synthetic Kosarak-shaped FULL "
                  "(990k x 39.6k) k=100 minconf=0.5",
        "sequences": vdb.n_sequences, "items": vdb.n_items,
        "rules": len(rules),
        "datagen_s": round(t1 - t0, 2),
        "vertical_build_s": round(t2 - t1, 2),
        "wall_s": round(t4 - t3, 2),
        "evaluated": eng.stats["evaluated"],
        "kernel_launches": eng.stats["kernel_launches"],
        "platform": jax.default_backend(),
    }


def main() -> None:
    from spark_fsm_tpu.utils.jitcache import enable_compile_cache

    enable_compile_cache()
    runners = {2: config2, 3: config3}
    args = sys.argv[1:]
    parity = "--parity" in args
    args = [a for a in args if a != "--parity"]
    try:
        which = {int(a) for a in args} or set(runners)
    except ValueError:
        which = set()
    if not which or not which <= set(runners):
        sys.exit(f"usage: python bench_scale.py [--parity] "
                 f"[{' '.join(map(str, sorted(runners)))}]"
                 f" — full-scale spot-check configs (got {sys.argv[1:]})")
    if parity and 2 not in which:
        sys.exit("--parity requires config 2 (the only config whose "
                 "full-size oracle is feasible); rerun with 2 included")
    for n in sorted(which):
        kwargs = {"parity": parity} if n == 2 else {}
        print(json.dumps(runners[n](**kwargs)), flush=True)


if __name__ == "__main__":
    main()
