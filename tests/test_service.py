"""Service lifecycle tests over the real HTTP surface.

Mirrors SURVEY.md sec 3 call stacks: register -> track -> train -> status
-> get, with the FILE and TRACKED sources, SPADE/TSR plugins, rule
filtering, and failure supervision.  Runs on the CPU backend (conftest).
"""

import json
import time
import urllib.request
import urllib.parse

import pytest

from spark_fsm_tpu.data.spmf import format_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.service.app import serve_background
from spark_fsm_tpu.service.model import deserialize_patterns, deserialize_rules
from spark_fsm_tpu.utils.canonical import patterns_text, sort_patterns


@pytest.fixture(scope="module")
def server():
    srv = serve_background()
    yield srv
    srv.master.shutdown()
    srv.shutdown()


def _post_port(port, endpoint, **params):
    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{port}{endpoint}"
    with urllib.request.urlopen(url, data=data, timeout=30) as resp:
        return json.loads(resp.read().decode())


def _post(server, endpoint, **params):
    return _post_port(server.server_port, endpoint, **params)


def _await_status_port(port, uid, want="finished", timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        resp = _post_port(port, f"/status/{uid}")
        if resp["status"] == want:
            return resp
        if resp["status"] == "failure":
            raise AssertionError(f"job failed: {resp}")
        time.sleep(0.05)
    raise AssertionError(f"timeout waiting for {want}")


def _await_status(server, uid, want="finished", timeout=60.0):
    return _await_status_port(server.server_port, uid, want, timeout)


def test_admin(server):
    assert _post(server, "/admin/ping")["status"] == "up"
    algos = _post(server, "/admin/algorithms")
    assert {"SPADE", "SPADE_TPU", "TSR", "TSR_TPU"} <= set(algos)


def test_train_get_file_source(server, tmp_path):
    db = synthetic_db(seed=5, n_sequences=220, n_items=12, mean_itemsets=4.0)
    path = tmp_path / "db.spmf"
    path.write_text(format_spmf(db))

    resp = _post(server, "/train", algorithm="SPADE_TPU", source="FILE",
                 path=str(path), support="0.05")
    assert resp["status"] == "started"
    uid = resp["data"]["uid"]
    _await_status(server, uid)

    got = _post(server, "/get/patterns", uid=uid)
    assert got["status"] == "finished"
    patterns = deserialize_patterns(got["data"]["patterns"])
    want = mine_spade(db, abs_minsup(0.05, len(db)))
    assert patterns_text(sort_patterns(patterns)) == patterns_text(want)


def test_train_inline_constrained(server):
    db = synthetic_db(seed=6, n_sequences=150, n_items=10, mean_itemsets=5.0)
    resp = _post(server, "/train", algorithm="SPADE_TPU", source="INLINE",
                 sequences=format_spmf(db), support="0.05",
                 maxgap="2", maxwindow="5")
    uid = resp["data"]["uid"]
    _await_status(server, uid)
    got = _post(server, "/get/patterns", uid=uid)
    from spark_fsm_tpu.models.oracle import mine_cspade
    want = mine_cspade(db, abs_minsup(0.05, len(db)), maxgap=2, maxwindow=5)
    patterns = deserialize_patterns(got["data"]["patterns"])
    assert patterns_text(sort_patterns(patterns)) == patterns_text(want)


def test_track_register_mine_lifecycle(server):
    # register a field spec (identity mapping), track a clickstream, mine
    # the tracked topic
    _post(server, "/register/clicks", site="site", user="user",
          timestamp="timestamp", item="item")
    events = [
        ("alice", 1, 3), ("alice", 2, 7), ("alice", 3, 3),
        ("bob", 1, 3), ("bob", 2, 7), ("bob", 3, 9),
        ("carol", 1, 3), ("carol", 2, 7),
    ]
    for user, ts, item in events:
        r = _post(server, "/track/clicks", site="shop", user=user,
                  timestamp=str(ts), item=str(item))
        assert r["status"] == "finished"

    resp = _post(server, "/train", algorithm="SPADE", source="TRACKED",
                 topic="clicks", support="3")
    uid = resp["data"]["uid"]
    _await_status(server, uid)
    got = _post(server, "/get/patterns", uid=uid)
    patterns = deserialize_patterns(got["data"]["patterns"])
    # <{3}>, <{7}>, <{3},{7}> occur in all 3 user sequences
    as_set = {(pat, sup) for pat, sup in patterns}
    assert (((3,),), 3) in as_set
    assert (((7,),), 3) in as_set
    assert (((3,), (7,)), 3) in as_set


def test_register_maps_arbitrary_field_names(server):
    # the registered spec maps roles onto NON-default event field names;
    # tracking and mining must consult it (SURVEY.md sec 2 Registrar row)
    _post(server, "/register/weblog", site="domain", user="visitor",
          timestamp="at", group="session", item="sku")

    # item role lives under 'sku' — an event missing it is rejected
    r = _post(server, "/track/weblog", domain="shop", visitor="x",
              at="1", session="1", other="y")
    assert r["status"] == "failure" and "sku" in r["data"]["error"]

    events = [
        ("ann", 1, 1, 3), ("ann", 2, 2, 7),
        ("ben", 1, 1, 3), ("ben", 2, 2, 7),
    ]
    for visitor, at, session, sku in events:
        r = _post(server, "/track/weblog", domain="shop", visitor=visitor,
                  at=str(at), session=str(session), sku=str(sku))
        assert r["status"] == "finished"

    resp = _post(server, "/train", algorithm="SPADE", source="TRACKED",
                 topic="weblog", support="2")
    uid = resp["data"]["uid"]
    _await_status(server, uid)
    got = _post(server, "/get/patterns", uid=uid)
    as_set = {(pat, sup) for pat, sup in
              deserialize_patterns(got["data"]["patterns"])}
    assert (((3,), (7,)), 2) in as_set


def test_tracked_groups_not_time_contiguous(server):
    # two groups interleaved in time still form exactly two itemsets,
    # ordered by each group's first timestamp (ADVICE round-1 finding)
    for at, session, sku in [(1, 10, 5), (2, 20, 6), (3, 10, 7), (4, 20, 8)]:
        _post(server, "/track/interleave", site="s", user="u",
              timestamp=str(at), group=str(session), item=str(sku))
    resp = _post(server, "/train", algorithm="SPADE", source="TRACKED",
                 topic="interleave", support="1")
    uid = resp["data"]["uid"]
    _await_status(server, uid)
    got = _post(server, "/get/patterns", uid=uid)
    as_set = {(pat, sup) for pat, sup in
              deserialize_patterns(got["data"]["patterns"])}
    # group 10 = {5,7} (first ts 1), group 20 = {6,8} (first ts 2)
    assert (((5, 7), (6, 8)), 1) in as_set
    assert (((5, 6),), 1) not in as_set  # no cross-group itemset


def test_uid_reuse_clears_stale_error(server):
    # a failed job leaves an error; re-training with the SAME uid must not
    # report the stale error once the new job finishes (ADVICE finding)
    uid = "reuse-me"
    resp = _post(server, "/train", uid=uid, algorithm="SPADE", source="FILE",
                 path="/nonexistent/file.spmf", support="0.5")
    deadline = time.time() + 30
    while time.time() < deadline:
        st = _post(server, f"/status/{uid}")
        if st["status"] == "failure":
            break
        time.sleep(0.05)
    else:
        raise AssertionError("failure status never surfaced")

    resp = _post(server, "/train", uid=uid, algorithm="SPADE",
                 source="INLINE", sequences="1 -1 2 -2\n1 -1 2 -2",
                 support="2")
    assert resp["data"]["uid"] == uid
    st = _await_status(server, uid)
    assert "error" not in st["data"], f"stale error leaked: {st}"
    got = _post(server, "/get/patterns", uid=uid)
    assert got["status"] == "finished"


def test_tsr_rules_and_filtering(server):
    db = synthetic_db(seed=8, n_sequences=120, n_items=8, mean_itemsets=4.0)
    resp = _post(server, "/train", algorithm="TSR_TPU", source="INLINE",
                 sequences=format_spmf(db), k="15", minconf="0.5",
                 max_side="2")
    uid = resp["data"]["uid"]
    _await_status(server, uid)
    got = _post(server, "/get/rules", uid=uid)
    rules = deserialize_rules(got["data"]["rules"])
    assert rules, "expected some rules"
    some_item = rules[0][0][0]
    filtered = _post(server, "/get/rules", uid=uid,
                     antecedent=str(some_item))
    frules = deserialize_rules(filtered["data"]["rules"])
    assert frules and all(some_item in r[0] for r in frules)
    assert len(frules) <= len(rules)

    # ranked next-item prediction: every candidate's best rule has its
    # antecedent contained in the observed items (a MULTI-item observed
    # set, so real subset matching runs), candidates exclude the
    # observed items, ordering is confidence-desc (support tie-break),
    # and each entry carries the exact sup/supx pair of its quoted rule
    import json as _json

    have = set(rules[0][0]) | {rules[-1][0][0]}
    items_arg = ",".join(map(str, sorted(have)))
    pred = _post(server, "/get/prediction", uid=uid, items=items_arg)
    assert pred["status"] == "finished", pred
    preds = _json.loads(pred["data"]["predictions"])
    assert preds, "expected at least one prediction"
    confs = [p["confidence"] for p in preds]
    assert confs == sorted(confs, reverse=True)
    rule_index = {(tuple(r[0]), tuple(r[1])): r for r in rules}
    for p in preds:
        assert p["item"] not in have
        assert set(p["antecedent"]) <= have
        assert p["item"] in p["consequent"]
        src_rule = rule_index[(tuple(p["antecedent"]), tuple(p["consequent"]))]
        assert (p["support"], p["antecedent_support"]) == (src_rule[2], src_rule[3])
        assert p["confidence"] == src_rule[2] / src_rule[3]
    # observed items with no matching rules -> empty prediction list,
    # still a finished response; missing items param -> failure
    none = _post(server, "/get/prediction", uid=uid, items="999999")
    assert none["status"] == "finished"
    assert _json.loads(none["data"]["predictions"]) == []
    bad = _post(server, "/get/prediction", uid=uid)
    assert bad["status"] == "failure"


def test_failure_supervision(server):
    # unknown algorithm rejected synchronously — as a STRUCTURED 400
    # listing the supported registry (ISSUE 15 satellite), not a 200
    # failure envelope
    import urllib.error

    from spark_fsm_tpu.service import plugins as _plugins

    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(server, "/train", algorithm="NOPE", source="INLINE",
              sequences="1 -2", support="0.5")
    assert ei.value.code == 400
    body = json.loads(ei.value.read().decode())
    assert body["status"] == "failure"
    assert "unknown algorithm" in body["data"]["error"]
    assert json.loads(body["data"]["supported"]) == \
        sorted(_plugins.ALGORITHMS)

    # bad source path fails asynchronously with status=failure + error
    resp = _post(server, "/train", algorithm="SPADE", source="FILE",
                 path="/nonexistent/file.spmf", support="0.5")
    uid = resp["data"]["uid"]
    deadline = time.time() + 30
    while time.time() < deadline:
        st = _post(server, f"/status/{uid}")
        if st["status"] == "failure":
            assert "error" in st["data"]
            break
        time.sleep(0.05)
    else:
        raise AssertionError("failure status never surfaced")

    # a source missing its required params surfaces a clear error
    resp = _post(server, "/train", algorithm="SPADE", source="ELASTIC",
                 support="0.5")
    uid = resp["data"]["uid"]
    deadline = time.time() + 30
    while time.time() < deadline:
        st = _post(server, f"/status/{uid}")
        if st["status"] == "failure":
            assert "ELASTIC source needs" in st["data"]["error"]
            break
        time.sleep(0.05)
    else:
        raise AssertionError("source-param failure never surfaced")


def test_unknown_uid_and_pending(server):
    resp = _post(server, "/status/deadbeef")
    assert resp["status"] == "failure"
    got = _post(server, "/get/patterns", uid="deadbeef")
    assert got["status"] == "failure"


def test_concurrent_jobs_multiple_workers():
    """Several train jobs in flight at once across 2 miner workers: every
    job finishes with its OWN results (no cross-job state bleed through
    the shared store or engines)."""
    from spark_fsm_tpu.service.actors import Master
    from spark_fsm_tpu.service.model import ServiceRequest
    from spark_fsm_tpu.service.store import ResultStore

    store = ResultStore()
    master = Master(store=store, miner_workers=2)
    try:
        uids = []
        for k in range(6):
            # each job mines a distinct item alphabet {10k+1, 10k+2}
            a, b = 10 * k + 1, 10 * k + 2
            seqs = f"{a} -1 {b} -2\n" * (k + 2)
            resp = master.handle(ServiceRequest("fsm", "train", {
                "algorithm": "SPADE", "source": "INLINE",
                "sequences": seqs, "support": "1.0"}))
            uids.append((resp.data["uid"], a, b, k + 2))
        deadline = time.time() + 60
        while time.time() < deadline:
            done = [store.status(u) for u, *_ in uids]
            if all(s in ("finished", "failure") for s in done):
                break
            time.sleep(0.02)
        for uid, a, b, n in uids:
            assert store.status(uid) == "finished", store.get(f"fsm:error:{uid}")
            patterns = json.loads(store.patterns(uid))
            assert {"support": n, "itemsets": [[a], [b]]} in patterns, \
                (uid, patterns)
    finally:
        master.shutdown()


def test_sigterm_drains_service_cleanly():
    # k8s/systemd stop: SIGTERM must drain like Ctrl-C — miners finish
    # their current job to a durable status, both servers close, process
    # exits 0 with the stop line printed (service/app.py main()).
    import os
    import pathlib
    import signal as _signal
    import subprocess
    import sys

    import socket

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # a REAL remote port so the drain also closes the actor-protocol
    # server (remote-port 0 would disable it and skip that branch)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    rport = s.getsockname()[1]
    s.close()
    child = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import sys\n"
        f"sys.argv = ['app', '--port', '0', '--remote-port', '{rport}']\n"
        "from spark_fsm_tpu.service.app import main\n"
        "main()\n"
    )
    proc = subprocess.Popen([sys.executable, "-c", child], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        # wait for the boot line (skipping earlier banner lines, e.g.
        # the integrity scrubber's), then exercise one request and stop
        line = ""
        for _ in range(8):
            line = proc.stdout.readline()
            if "spark_fsm_tpu service on http://" in line:
                break
        assert "spark_fsm_tpu service on http://" in line, line
        port = int(line.rsplit(":", 1)[1])
        # the remote server logs structured lines too — read until its
        # boot banner appears (bounded; reading a fixed count would block
        # on the pipe once the expected lines are exhausted)
        seen = []
        for _ in range(5):
            line2 = proc.stdout.readline()
            seen.append(line2)
            if "actor protocol" in line2:
                break
        assert any("actor protocol" in l for l in seen), seen
        resp = _post_port(port, "/train",
                          algorithm="SPADE", source="INLINE",
                          sequences="1 -1 2 -2\n1 -1 2 -2\n", support="0.5")
        uid = resp["data"]["uid"]
        _await_status_port(port, uid)
        proc.send_signal(_signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, f"rc={proc.returncode}\n{out}"
    assert "spark_fsm_tpu service stopped" in out, out


def test_submit_after_shutdown_fails_durably():
    # A request racing past the closed listeners (remote/actor path, or an
    # in-flight HTTP handler) and hitting Miner.submit() AFTER shutdown()
    # has enqueued the worker sentinels must land in a durable 'failure'
    # status — never sit 'started' forever on a queue no worker reads.
    from spark_fsm_tpu.service.actors import Miner
    from spark_fsm_tpu.service.model import ServiceRequest
    from spark_fsm_tpu.service.store import ResultStore

    store = ResultStore()
    miner = Miner(store, workers=1)
    miner.shutdown(join_timeout_s=10.0)
    miner.submit(ServiceRequest("fsm", "train", {
        "algorithm": "SPADE", "source": "INLINE",
        "sequences": "1 -1 2 -2\n", "support": "0.5", "uid": "late"}))
    assert store.status("late") == "failure"
    assert "shutting down" in (store.get("fsm:error:late") or "")


