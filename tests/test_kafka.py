"""Kafka adapter contract tests (streaming/kafka.py) against a fake
poll()-shaped consumer — the shape both kafka-python and a wrapped
confluent-kafka expose.  No broker or client library involved; what is
under test is the fetch contract PollConsumer relies on."""

import pytest

from spark_fsm_tpu.data.spmf import format_spmf, parse_spmf
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.streaming.consumer import PollConsumer
from spark_fsm_tpu.streaming.incremental import IncrementalWindowMiner
from spark_fsm_tpu.streaming.kafka import KafkaFetch
from spark_fsm_tpu.utils.canonical import patterns_text


class _Rec:
    def __init__(self, value):
        self.value = value


class _FakeConsumer:
    """kafka-python poll() shape: {partition: [records]} per call."""

    def __init__(self, polls):
        self._polls = list(polls)
        self.seen_timeouts = []

    def poll(self, timeout_ms=None):
        self.seen_timeouts.append(timeout_ms)
        return self._polls.pop(0) if self._polls else {}


def test_poll_concatenates_partitions_in_order():
    fake = _FakeConsumer([{
        "tp0": [_Rec(b"1 -2\n"), _Rec(b"2 -2\n")],
        "tp1": [_Rec("3 -1 4 -2\n")],        # str values pass through
    }])
    fetch = KafkaFetch(fake, timeout_ms=250)
    batch = fetch()
    assert batch == parse_spmf("1 -2\n2 -2\n3 -1 4 -2\n")
    assert fake.seen_timeouts == [250]
    assert fetch.stats == {"polls": 1, "records": 3, "bad_records": 0,
                           "dead_letters": []}


def test_empty_poll_and_empty_records_are_idle():
    fake = _FakeConsumer([{}, {"tp0": [_Rec(b"")]}])
    fetch = KafkaFetch(fake)
    assert fetch() is None          # broker had nothing
    assert fetch() is None          # records parsed to zero sequences
    assert fetch.stats["polls"] == 2


def test_multiline_record_values():
    fake = _FakeConsumer([{"tp0": [_Rec(b"1 -2\n2 -2\n1 2 -2\n")]}])
    assert KafkaFetch(fake)() == parse_spmf("1 -2\n2 -2\n1 2 -2\n")


def test_bad_record_raise_surfaces_to_supervision():
    fake = _FakeConsumer([{"tp0": [_Rec(b"not spmf")]}])
    fetch = KafkaFetch(fake)
    with pytest.raises(ValueError):
        fetch()
    # and PollConsumer turns that into a counted, non-fatal error
    fake2 = _FakeConsumer([{"tp0": [_Rec(b"garbage")]},
                           {"tp0": [_Rec(b"7 -2\n")]}])
    got = []
    pc = PollConsumer(KafkaFetch(fake2), got.append, poll_interval_s=0)
    pc.run(max_polls=2)
    assert pc.stats["errors"] == 1 and got == [parse_spmf("7 -2\n")]


def test_bad_record_skip_counts_and_keeps_good_ones():
    fake = _FakeConsumer([{"tp0": [_Rec(b"\xff\xfe bad utf8"),
                                   _Rec(b"5 -2\n"),
                                   _Rec(b"oops")]}])
    fetch = KafkaFetch(fake, on_bad="skip")
    assert fetch() == parse_spmf("5 -2\n")
    assert fetch.stats["bad_records"] == 2


class _OffsetRec(_Rec):
    def __init__(self, value, offset):
        super().__init__(value)
        self.offset = offset


def test_dead_letter_ring_diagnoses_poison_messages():
    """Undecodable payloads land in a bounded ring (last 16) with
    partition/offset (when the record exposes one), a TRUNCATED payload
    repr, and the error — so a poisoned topic names its producer and
    replay point instead of being a bare counter."""
    big = b"\xff" + b"x" * 500  # undecodable AND oversized
    fake = _FakeConsumer([{"tp3": [_OffsetRec(big, 41),
                                   _Rec(b"5 -2\n"),
                                   _Rec(b"oops")]}])
    fetch = KafkaFetch(fake, on_bad="skip")
    assert fetch() == parse_spmf("5 -2\n")
    ring = fetch.stats["dead_letters"]
    assert len(ring) == 2
    assert ring[0]["partition"] == "tp3" and ring[0]["offset"] == 41
    assert ring[0]["payload"].endswith("...(truncated)")
    assert len(ring[0]["payload"]) < 200
    assert "UnicodeDecodeError" in ring[0]["error"]
    assert ring[1]["offset"] is None  # record type without offsets
    assert "oops" in ring[1]["payload"]


def test_dead_letter_ring_is_bounded_and_recorded_on_raise():
    # raise mode records the poison record too (it is the one that took
    # the poll down — exactly what the operator needs to see)
    fake = _FakeConsumer([{"tp0": [_Rec(b"garbage")]}])
    fetch = KafkaFetch(fake)
    with pytest.raises(ValueError):
        fetch()
    assert len(fetch.stats["dead_letters"]) == 1

    # the ring keeps only the LAST 16 across polls
    polls = [{"tp0": [_Rec(f"bad {i}".encode())]} for i in range(20)]
    fetch2 = KafkaFetch(_FakeConsumer(polls), on_bad="skip")
    for _ in range(20):
        fetch2()
    ring = fetch2.stats["dead_letters"]
    assert len(ring) == 16
    assert "bad 19" in ring[-1]["payload"] and "bad 4" in ring[0]["payload"]
    assert fetch2.stats["bad_records"] == 20


def test_constructor_validation():
    with pytest.raises(TypeError, match="poll"):
        KafkaFetch(object())
    with pytest.raises(ValueError, match="on_bad"):
        KafkaFetch(_FakeConsumer([]), on_bad="ignore")


def test_end_to_end_kafka_to_incremental_window_parity():
    # the full seam: fake broker -> KafkaFetch -> PollConsumer ->
    # incremental window miner, with per-push oracle parity
    from spark_fsm_tpu.data.synth import synthetic_db

    dbs = [synthetic_db(seed=s, n_sequences=40, n_items=8,
                        mean_itemsets=2.5) for s in (1, 2, 3)]
    polls = [{"tp0": [_Rec(format_spmf(db).encode())]} for db in dbs]
    fake = _FakeConsumer(polls)
    wm = IncrementalWindowMiner(0.3, max_batches=2)
    parities = []

    def check(patterns):
        want = mine_spade(wm.window.sequences(), wm.minsup_abs())
        parities.append(patterns_text(patterns) == patterns_text(want))

    pc = PollConsumer(KafkaFetch(fake), wm.push, poll_interval_s=0,
                      on_result=check)
    pc.run(max_polls=4)  # 3 batches + 1 idle
    assert pc.stats["batches"] == 3
    assert parities == [True, True, True]
