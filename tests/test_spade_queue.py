"""Queue-fused sparse-frontier engine (models/spade_queue.py).

Parity anchor: the CPU oracle, byte-identical pattern sets (SURVEY.md
sec 4).  The queue engine reuses the dense fused engine's mask rules but
drives them through a device-resident FIFO ring, so the extra surface
under test is the ring discipline itself: slot reuse, wave splitting of
wide levels, root aliasing of item rows, and overflow detection.
"""

import numpy as np
import pytest

from spark_fsm_tpu.data.spmf import parse_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import build_vertical
from spark_fsm_tpu.models.oracle import mine_spade, mine_spade_vertical
from spark_fsm_tpu.models.spade_queue import (
    QueueCaps, QueueSpadeTPU, queue_eligible)
from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu
from spark_fsm_tpu.utils.canonical import patterns_text

ZAKI = "1 -1 2 -1 3 -2\n1 4 -1 3 -2\n1 -1 2 -1 3 4 -2\n1 3 -1 5 -2\n"
SMALL_CAPS = QueueCaps(nb=32, ring=512, c_cap=2048, r_cap=16384)


def _queue(db, minsup, **kw):
    vdb = build_vertical(db, min_item_support=minsup)
    eng = QueueSpadeTPU(vdb, minsup, caps=kw.pop("caps", SMALL_CAPS), **kw)
    return eng, eng.mine()


def test_parity_zaki():
    db = parse_spmf(ZAKI)
    eng, got = _queue(db, 2)
    assert got is not None
    assert patterns_text(got) == patterns_text(mine_spade(db, 2))
    assert eng.stats["kernel_launches"] == 1
    assert eng.stats["candidates"] > 0
    assert eng.stats["waves"] > 0


@pytest.mark.parametrize("seed,n,items,mi,misz,minsup,caps", [
    (7, 400, 40, 4.0, 1.6, 8, SMALL_CAPS),
    (9, 200, 25, 4.0, 2.5, 10, SMALL_CAPS),
    (21, 300, 60, 6.0, 1.3, 6, None),  # wide levels: default caps
])
def test_parity_synthetic(seed, n, items, mi, misz, minsup, caps):
    db = synthetic_db(seed=seed, n_sequences=n, n_items=items,
                      mean_itemsets=mi, mean_itemset_size=misz)
    _, got = _queue(db, minsup, caps=caps or QueueCaps())
    assert got is not None
    assert patterns_text(got) == patterns_text(mine_spade(db, minsup))


def test_wave_splitting_of_wide_levels():
    # nb far below the root count: every level is popped across several
    # waves, children enqueue behind remaining parents, and ring slots
    # recycle — the FIFO-specific machinery the dense engine doesn't have
    db = synthetic_db(seed=21, n_sequences=300, n_items=60,
                      mean_itemsets=6.0, mean_itemset_size=1.3)
    eng, got = _queue(db, 6, caps=QueueCaps(nb=16, ring=4096,
                                            c_cap=4096, r_cap=1 << 16))
    assert got is not None
    # far more waves than BFS levels proves the splitting actually ran
    assert eng.stats["waves"] > 8
    assert patterns_text(got) == patterns_text(mine_spade(db, 6))


def test_parity_multiword():
    # > 32 itemsets/sequence -> n_words > 1 exercises the word-minor
    # flat layout + carry chains inside the queue program (minsup 90
    # keeps the 2k-pattern set inside the caps; 60 is explosive)
    db = synthetic_db(seed=8, n_sequences=120, n_items=12,
                      mean_itemsets=40.0, mean_itemset_size=1.2)
    minsup = 90
    eng, got = _queue(db, minsup,
                      caps=QueueCaps(nb=64, ring=4096, c_cap=8192,
                                     r_cap=1 << 17))
    assert got is not None
    assert eng.n_words > 1
    assert patterns_text(got) == patterns_text(mine_spade(db, minsup))


def test_max_pattern_itemsets():
    db = synthetic_db(seed=9, n_sequences=200, n_items=25,
                      mean_itemsets=4.0, mean_itemset_size=2.5)
    vdb = build_vertical(db, min_item_support=10)
    eng = QueueSpadeTPU(vdb, 10, max_pattern_itemsets=2, caps=SMALL_CAPS)
    got = eng.mine()
    want = mine_spade_vertical(vdb, 10, max_pattern_itemsets=2)
    assert got is not None
    assert patterns_text(got) == patterns_text(want)


def test_overflow_returns_none_and_auto_falls_back():
    db = synthetic_db(seed=7, n_sequences=400, n_items=40,
                      mean_itemsets=4.0, mean_itemset_size=1.6)
    tiny = QueueCaps(nb=16, ring=32, c_cap=32, r_cap=64, i_max=8)
    eng, got = _queue(db, 8, caps=tiny)
    assert got is None and eng.stats.get("fused_overflow")
    stats = {}
    full = mine_spade_tpu(db, 8, stats_out=stats)
    assert patterns_text(full) == patterns_text(mine_spade(db, 8))


def test_ring_overflow_is_detected_not_corrupted():
    # a ring big enough for the roots but too small for the peak live
    # frontier must flag overflow (never silently overwrite live slots)
    db = synthetic_db(seed=13, n_sequences=60, n_items=40,
                      mean_itemsets=6.0, mean_itemset_size=2.0,
                      correlation=0.8)
    vdb = build_vertical(db, min_item_support=2)
    n_roots = sum(1 for s in vdb.item_supports if int(s) >= 2)
    tight = QueueCaps(nb=16, ring=max(64, ((n_roots + 15) // 16) * 16),
                      c_cap=4096, r_cap=1 << 16)
    eng = QueueSpadeTPU(vdb, 2, caps=tight)
    assert eng.mine() is None and eng.stats.get("fused_overflow")
    wide = QueueSpadeTPU(vdb, 2, caps=QueueCaps(nb=64, ring=16384,
                                                c_cap=8192, r_cap=1 << 17))
    got = wide.mine()
    assert got is not None
    assert patterns_text(got) == patterns_text(mine_spade(db, 2))


def test_eligibility():
    db = parse_spmf(ZAKI)
    vdb = build_vertical(db, min_item_support=2)
    assert queue_eligible(vdb)
    import jax
    from spark_fsm_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(len(jax.devices()))
    assert queue_eligible(vdb, mesh=mesh)

    class FakeVdb:
        n_items = vdb.n_items
        n_sequences = vdb.n_sequences
        n_words = vdb.n_words
    # huge stores exceed the allocation envelope (no traffic ceiling:
    # per-wave traffic tracks the actual frontier)
    big = FakeVdb()
    big.n_sequences = 300_000_000
    assert not queue_eligible(big)
    # Kosarak-scale alphabets belong to the classic engine
    wide = FakeVdb()
    wide.n_items = 5000
    assert not queue_eligible(wide)


def test_caps_for_budget_scale_with_memory():
    from spark_fsm_tpu.models.spade_queue import working_set_bytes

    row = 80_000 * 4  # headline-ish single-word row
    small = QueueCaps.for_budget(row, 384, 1 << 30)
    big = QueueCaps.for_budget(row, 384, 8 << 30)
    assert big.ring > small.ring
    # the sized caps actually FIT their budget (the one shared estimator
    # for_budget and queue_eligible both use)
    assert working_set_bytes(small, row, 384) <= 1 << 30
    assert working_set_bytes(big, row, 384) <= 8 << 30
    # and a budget too small for even the minimum ring still returns the
    # least-memory geometry (an explicit fused="queue" pin allocates the
    # smallest thing possible; queue_eligible refuses such workloads)
    tiny = QueueCaps.for_budget(row, 384, 1 << 20)
    assert tiny.ring == 256
    assert working_set_bytes(tiny, row, 384) > 1 << 20
    # nb rows must tile the Pallas P_TILE
    from spark_fsm_tpu.ops import pallas_support as PS
    assert (2 * small.nb) % PS.P_TILE == 0


def test_store_survives_repeat_mines():
    # steady-state re-mines reuse the store built in __init__: item rows
    # must be intact after a mine (the loop writes only ring slots)
    db = synthetic_db(seed=9, n_sequences=200, n_items=25,
                      mean_itemsets=4.0, mean_itemset_size=2.5)
    vdb = build_vertical(db, min_item_support=10)
    eng = QueueSpadeTPU(vdb, 10, caps=SMALL_CAPS)
    first = eng.mine()
    second = eng.mine()
    assert first is not None and second is not None
    assert patterns_text(first) == patterns_text(second)


def test_parity_mesh():
    import jax
    from spark_fsm_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(len(jax.devices()))
    db = synthetic_db(seed=7, n_sequences=400, n_items=40,
                      mean_itemsets=4.0, mean_itemset_size=1.6)
    vdb = build_vertical(db, min_item_support=8)
    eng = QueueSpadeTPU(vdb, 8, mesh=mesh, caps=SMALL_CAPS)
    got = eng.mine()
    assert got is not None
    assert patterns_text(got) == patterns_text(mine_spade(db, 8))


def test_empty_and_single():
    assert _queue(parse_spmf("1 -2\n1 -2\n"), 2)[1] == [(((1,),), 2)]
    _, got = _queue(parse_spmf("1 -2\n"), 2)
    assert got == []


def test_shape_buckets_reuse_compile():
    db1 = synthetic_db(seed=30, n_sequences=100, n_items=15,
                       mean_itemsets=3.0)
    db2 = synthetic_db(seed=31, n_sequences=120, n_items=15,
                       mean_itemsets=3.0)
    keys = set()
    for db, ms in ((db1, 5), (db2, 5)):
        vdb = build_vertical(db, min_item_support=ms)
        eng = QueueSpadeTPU(vdb, ms, caps=SMALL_CAPS, shape_buckets=True)
        got = eng.mine()
        assert got is not None
        assert patterns_text(got) == patterns_text(mine_spade(db, ms))
        assert eng.n_seq == 128  # both bucket to the same shape
        keys.add(eng.stats["shape_key"])
    assert len(keys) == 1


def test_traced_minsup_reuses_compile():
    # the same engine geometry mined at two minsups must share the
    # compiled program (minsup is a traced scalar, not a cache key)
    db = synthetic_db(seed=9, n_sequences=200, n_items=25,
                      mean_itemsets=4.0, mean_itemset_size=2.5)
    for ms in (10, 14):
        vdb = build_vertical(db, min_item_support=ms)
        eng = QueueSpadeTPU(vdb, ms, caps=SMALL_CAPS)
        got = eng.mine()
        assert patterns_text(got) == patterns_text(mine_spade(db, ms))
