import numpy as np
import pytest

from spark_fsm_tpu.ops import bitops_np as BN


@pytest.fixture(scope="module")
def jnp_mod():
    import jax.numpy as jnp
    return jnp


def rand_bitmaps(rng, shape):
    b = rng.integers(0, 2**32, size=shape, dtype=np.uint32)
    b &= rng.integers(0, 2**32, size=shape, dtype=np.uint32)
    return b


@pytest.mark.parametrize("shape", [(1,), (3,), (5, 4, 2), (2, 7, 3)])
def test_sext_matches_numpy(jnp_mod, shape):
    from spark_fsm_tpu.ops import bitops_jax as BJ
    rng = np.random.default_rng(0)
    b = rand_bitmaps(rng, shape)
    np.testing.assert_array_equal(np.asarray(BJ.sext_transform(jnp_mod.asarray(b))),
                                  BN.sext_transform(b))


def test_support_matches_numpy(jnp_mod):
    from spark_fsm_tpu.ops import bitops_jax as BJ
    rng = np.random.default_rng(1)
    b = rand_bitmaps(rng, (6, 10, 3))
    np.testing.assert_array_equal(np.asarray(BJ.support(jnp_mod.asarray(b))), BN.support(b))
    assert np.asarray(BJ.support(jnp_mod.zeros((4, 2), jnp_mod.uint32))) == 0


def test_join_select(jnp_mod):
    from spark_fsm_tpu.ops import bitops_jax as BJ
    rng = np.random.default_rng(2)
    p = rand_bitmaps(rng, (4, 6, 2))
    i = rand_bitmaps(rng, (4, 6, 2))
    iss = np.array([True, False, True, False])
    got = np.asarray(BJ.join(jnp_mod.asarray(p), jnp_mod.asarray(i), jnp_mod.asarray(iss)))
    want = np.where(iss[:, None, None], BN.sext_transform(p), p) & i
    np.testing.assert_array_equal(got, want)


def test_extend_helpers(jnp_mod):
    from spark_fsm_tpu.ops import bitops_jax as BJ
    rng = np.random.default_rng(3)
    p = rand_bitmaps(rng, (5, 2))
    i = rand_bitmaps(rng, (5, 2))
    np.testing.assert_array_equal(np.asarray(BJ.s_extend(jnp_mod.asarray(p), jnp_mod.asarray(i))),
                                  BN.s_extend(p, i))
    np.testing.assert_array_equal(np.asarray(BJ.i_extend(jnp_mod.asarray(p), jnp_mod.asarray(i))),
                                  BN.i_extend(p, i))


def test_tsr_primitives_match_numpy(jnp_mod):
    from spark_fsm_tpu.ops import bitops_jax as BJ
    rng = np.random.default_rng(6)
    b = rand_bitmaps(rng, (4, 5, 3))
    for np_fn, jx_fn in [(BN.prefix_or_incl, BJ.prefix_or_incl),
                         (BN.suffix_or_incl, BJ.suffix_or_incl),
                         (BN.shift_up_one, BJ.shift_up_one)]:
        np.testing.assert_array_equal(np.asarray(jx_fn(jnp_mod.asarray(b))), np_fn(b))


def test_popcount_tail_mask_match_numpy(jnp_mod):
    """ISSUE 15 satellite: the jax popcount/tail-mask/pack primitives
    are bit-exact mirrors of the numpy reference, including the
    sext-padding overcount fix."""
    from spark_fsm_tpu.ops import bitops_jax as BJ
    rng = np.random.default_rng(21)
    b = rand_bitmaps(rng, (5, 3))
    np.testing.assert_array_equal(
        np.asarray(BJ.popcount(jnp_mod.asarray(b))), BN.popcount(b))
    for n_valid in (0, 1, 31, 32, 40, 64, 95, 96):
        np.testing.assert_array_equal(
            np.asarray(BJ.tail_mask(n_valid, 3)), BN.tail_mask(n_valid, 3))
        np.testing.assert_array_equal(
            np.asarray(BJ.masked_popcount(jnp_mod.asarray(b), n_valid)),
            BN.masked_popcount(b, n_valid))
    # the observable sext bug, on the jax side
    t = BJ.sext_transform(jnp_mod.asarray(
        np.array([[np.uint32(1 << 3), np.uint32(0)]])))
    assert int(np.asarray(BJ.popcount(t)).sum()) == 60
    assert int(np.asarray(BJ.masked_popcount(t, 40))) == 36


def test_pack_and_support_popcount_match_numpy(jnp_mod):
    from spark_fsm_tpu.ops import bitops_jax as BJ
    rng = np.random.default_rng(22)
    for n_seq in (1, 31, 33, 45, 64):
        act = rng.random((3, n_seq)) < 0.5
        np.testing.assert_array_equal(
            np.asarray(BJ.pack_seq_bits(jnp_mod.asarray(act))),
            BN.pack_seq_bits(act))
    bm = rand_bitmaps(rng, (4, 45, 2))
    np.testing.assert_array_equal(
        np.asarray(BJ.support_popcount(jnp_mod.asarray(bm))),
        BN.support(bm))
