import numpy as np
import pytest

from spark_fsm_tpu.ops import bitops_np as BN


@pytest.fixture(scope="module")
def jnp_mod():
    import jax.numpy as jnp
    return jnp


def rand_bitmaps(rng, shape):
    b = rng.integers(0, 2**32, size=shape, dtype=np.uint32)
    b &= rng.integers(0, 2**32, size=shape, dtype=np.uint32)
    return b


@pytest.mark.parametrize("shape", [(1,), (3,), (5, 4, 2), (2, 7, 3)])
def test_sext_matches_numpy(jnp_mod, shape):
    from spark_fsm_tpu.ops import bitops_jax as BJ
    rng = np.random.default_rng(0)
    b = rand_bitmaps(rng, shape)
    np.testing.assert_array_equal(np.asarray(BJ.sext_transform(jnp_mod.asarray(b))),
                                  BN.sext_transform(b))


def test_support_matches_numpy(jnp_mod):
    from spark_fsm_tpu.ops import bitops_jax as BJ
    rng = np.random.default_rng(1)
    b = rand_bitmaps(rng, (6, 10, 3))
    np.testing.assert_array_equal(np.asarray(BJ.support(jnp_mod.asarray(b))), BN.support(b))
    assert np.asarray(BJ.support(jnp_mod.zeros((4, 2), jnp_mod.uint32))) == 0


def test_join_select(jnp_mod):
    from spark_fsm_tpu.ops import bitops_jax as BJ
    rng = np.random.default_rng(2)
    p = rand_bitmaps(rng, (4, 6, 2))
    i = rand_bitmaps(rng, (4, 6, 2))
    iss = np.array([True, False, True, False])
    got = np.asarray(BJ.join(jnp_mod.asarray(p), jnp_mod.asarray(i), jnp_mod.asarray(iss)))
    want = np.where(iss[:, None, None], BN.sext_transform(p), p) & i
    np.testing.assert_array_equal(got, want)


def test_extend_helpers(jnp_mod):
    from spark_fsm_tpu.ops import bitops_jax as BJ
    rng = np.random.default_rng(3)
    p = rand_bitmaps(rng, (5, 2))
    i = rand_bitmaps(rng, (5, 2))
    np.testing.assert_array_equal(np.asarray(BJ.s_extend(jnp_mod.asarray(p), jnp_mod.asarray(i))),
                                  BN.s_extend(p, i))
    np.testing.assert_array_equal(np.asarray(BJ.i_extend(jnp_mod.asarray(p), jnp_mod.asarray(i))),
                                  BN.i_extend(p, i))


def test_tsr_primitives_match_numpy(jnp_mod):
    from spark_fsm_tpu.ops import bitops_jax as BJ
    rng = np.random.default_rng(6)
    b = rand_bitmaps(rng, (4, 5, 3))
    for np_fn, jx_fn in [(BN.prefix_or_incl, BJ.prefix_or_incl),
                         (BN.suffix_or_incl, BJ.suffix_or_incl),
                         (BN.shift_up_one, BJ.shift_up_one)]:
        np.testing.assert_array_equal(np.asarray(jx_fn(jnp_mod.asarray(b))), np_fn(b))
