"""Engine-vs-oracle parity: the north-star property (byte-identical sets)."""

import numpy as np
import pytest

from spark_fsm_tpu.data.spmf import parse_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.models.spade_tpu import SpadeTPU, mine_spade_tpu
from spark_fsm_tpu.utils.canonical import diff_patterns, patterns_text
from tests.test_oracle import ZAKI_DB, random_db


def assert_parity(db, minsup, max_pattern_itemsets=None, **kw):
    a = mine_spade(db, minsup, max_pattern_itemsets=max_pattern_itemsets)
    b = mine_spade_tpu(db, minsup, max_pattern_itemsets=max_pattern_itemsets, **kw)
    assert patterns_text(a) == patterns_text(b), diff_patterns(a, b)
    return b


def test_parity_zaki():
    assert_parity(ZAKI_DB, 2)


@pytest.mark.parametrize("seed", range(5))
def test_parity_randomized(seed):
    rng = np.random.default_rng(seed)
    db = random_db(rng, n_seq=30, n_items=6, max_itemsets=5, max_set=3)
    assert_parity(db, 3)


def test_parity_synthetic():
    db = synthetic_db(seed=7, n_sequences=400, n_items=40, mean_itemsets=4.0,
                      mean_itemset_size=1.4)
    assert_parity(db, abs_minsup(0.02, len(db)))


def test_parity_multiword():
    # sequences long enough to span multiple uint32 words; dense long
    # sequences explode combinatorially, so cap pattern length and keep
    # minsup high — the point is exercising the multi-word carry chain
    db = synthetic_db(seed=8, n_sequences=120, n_items=12, mean_itemsets=40.0,
                      max_itemsets=80)
    assert_parity(db, abs_minsup(0.5, len(db)), max_pattern_itemsets=3)


def test_parity_tiny_pool_exercises_recompute():
    db = synthetic_db(seed=9, n_sequences=200, n_items=25, mean_itemsets=4.0,
                      mean_itemset_size=1.3)
    minsup = abs_minsup(0.03, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    a = mine_spade(db, minsup)
    # 64-slot pool with small batches forces slot reclaim + recompute
    eng = SpadeTPU(vdb, minsup, pool_bytes=1, node_batch=16, chunk=64,
                   recompute_chunk=8)
    assert eng.pool_slots <= 64  # floor budget: reclaim + recompute must engage
    b = eng.mine()
    assert patterns_text(a) == patterns_text(b), diff_patterns(a, b)
    assert eng.stats["recomputed_nodes"] > 0 or eng.stats["reclaimed_slots"] == 0


def test_parity_max_itemsets_cap():
    a = mine_spade(ZAKI_DB, 2, max_pattern_itemsets=2)
    b = mine_spade_tpu(ZAKI_DB, 2, max_pattern_itemsets=2)
    assert patterns_text(a) == patterns_text(b), diff_patterns(a, b)


def test_mesh_parity_8_devices():
    import jax
    from spark_fsm_tpu.parallel.mesh import make_mesh
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    mesh = make_mesh(8)
    db = synthetic_db(seed=10, n_sequences=330, n_items=30, mean_itemsets=4.0,
                      mean_itemset_size=1.3)  # 330 % 8 != 0 -> exercises padding
    minsup = abs_minsup(0.03, len(db))
    a = mine_spade(db, minsup)
    b = assert_parity(db, minsup, mesh=mesh)
    assert len(b) == len(a)


def test_mesh_parity_with_recompute():
    from spark_fsm_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(4)
    db = synthetic_db(seed=11, n_sequences=160, n_items=20, mean_itemsets=4.0)
    minsup = abs_minsup(0.05, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    eng = SpadeTPU(vdb, minsup, mesh=mesh, pool_bytes=1, node_batch=16, chunk=64)
    got = eng.mine()
    want = mine_spade(db, minsup)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


def test_empty_and_trivial():
    assert mine_spade_tpu(parse_spmf("1 -2\n2 -2\n"), 2) == []
    res = mine_spade_tpu(parse_spmf("1 -2\n1 -2\n"), 2)
    assert res == [(((1,),), 2)]


def test_launch_width_clamps_to_pool_budget():
    # Per-launch temps are [chunk, S*W]: a fixed chunk default that is
    # invisible at small S was a 7.5G materialize temp at 990k sequences
    # (full-scale MSNBC OOM).  The width must clamp so a launch's
    # candidate tensor stays within ~1/8 of the pool budget — overriding
    # even an explicitly passed chunk — while parity is unaffected.
    db = synthetic_db(seed=9, n_sequences=200, n_items=25, mean_itemsets=4.0,
                      mean_itemset_size=1.3)
    minsup = abs_minsup(0.03, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    slot_bytes = 200 * vdb.n_words * 4  # n_seq unpadded here (no mesh)
    eng = SpadeTPU(vdb, minsup, pool_bytes=slot_bytes * 512, chunk=4096)
    assert eng.chunk <= 64  # (512/8 = 64 slots' worth per launch)
    assert patterns_text(eng.mine()) == patterns_text(mine_spade(db, minsup))

    from spark_fsm_tpu.models.spade_constrained import ConstrainedSpadeTPU
    from spark_fsm_tpu.models.oracle import mine_cspade
    ceng = ConstrainedSpadeTPU(vdb, minsup, maxgap=2,
                               pool_bytes=1, chunk=4096)
    assert ceng.chunk <= 8
    assert patterns_text(ceng.mine()) == patterns_text(
        mine_cspade(db, minsup, maxgap=2))


def test_pallas_dispatch_fault_downgrades(monkeypatch):
    # A kernel fault at DISPATCH (lowering/compile failures surface on the
    # batch_supports call) must downgrade the engine to the jnp path for
    # the rest of the mine with a visible flag and byte-identical results
    # — mirror of tests/test_tsr.py's per-km downgrade test.
    import spark_fsm_tpu.models.spade_tpu as M

    def boom(*a, **k):
        raise RuntimeError("synthetic dispatch fault")

    monkeypatch.setattr(M.PS, "batch_supports", boom)
    db = synthetic_db(seed=13, n_sequences=200, n_items=25,
                      mean_itemsets=4.0, mean_itemset_size=1.3)
    minsup = abs_minsup(0.03, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    eng = SpadeTPU(vdb, minsup, use_pallas=True)
    got = eng.mine()
    assert eng.use_pallas is False
    assert "synthetic dispatch fault" in eng.stats["pallas_fallback"]
    want = mine_spade(db, minsup)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


def test_pallas_readback_fault_recounts_inflight_batches(monkeypatch):
    # TPU kernel RUNTIME faults surface at readback (np.asarray), not at
    # dispatch.  With pipeline_depth > 1 several Pallas-dispatched batches
    # are already in flight when the first fault lands; each must be
    # recounted on the jnp path (the `was_pallas` gating in _resolve) and
    # the final pattern set must be byte-identical.
    import spark_fsm_tpu.models.spade_tpu as M

    faults = []

    class FaultyArray:
        def copy_to_host_async(self):
            pass

        def __array__(self, *a, **k):
            faults.append(1)
            raise RuntimeError("synthetic readback fault")

    monkeypatch.setattr(M.PS, "batch_supports",
                        lambda *a, **k: FaultyArray())
    db = synthetic_db(seed=14, n_sequences=200, n_items=30,
                      mean_itemsets=4.0, mean_itemset_size=1.3)
    minsup = abs_minsup(0.03, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    # small node batches + deep pipeline: the root frontier alone fills
    # several in-flight Pallas batches before the first resolve faults
    eng = SpadeTPU(vdb, minsup, use_pallas=True, node_batch=4,
                   pipeline_depth=4)
    assert eng.node_batch == 4 and eng.pipeline_depth == 4
    got = eng.mine()
    assert eng.use_pallas is False
    assert "synthetic readback fault" in eng.stats["pallas_fallback"]
    # more than one in-flight Pallas batch hit the readback fault and
    # went through the recount path
    assert len(faults) >= 2
    want = mine_spade(db, minsup)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)
