"""Native tokenizer (data/_fasttok.c): parity with the numpy flatten.

The extension builds on demand into the user cache; when that fails
(no compiler, SPARKFSM_FASTTOK=0) every consumer falls back to the
numpy path — these tests pin that both paths produce byte-identical
token tables.
"""

import numpy as np
import pytest

from spark_fsm_tpu.data import fasttok
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import build_vertical


def test_flatten_parity():
    db = synthetic_db(seed=5, n_sequences=300, n_items=20,
                      mean_itemsets=4.0, mean_itemset_size=1.5)
    ft = fasttok.flatten(db)
    if ft is None:
        pytest.skip("native tokenizer unavailable in this environment")
    # compared against the REAL numpy fallback, not a copy of it
    want = fasttok.flatten_numpy(db)
    for got, exp in zip(ft, want):
        np.testing.assert_array_equal(got, exp)


def test_flatten_accepts_lists_and_rejects_garbage():
    if fasttok.flatten([((1,),)]) is None:
        pytest.skip("native tokenizer unavailable in this environment")
    # lists are sequences too (sources may build lists, not tuples)
    lengths, counts, items = fasttok.flatten([[[1, 2], [3]], [[2]]])
    assert lengths.tolist() == [2, 1]
    assert counts.tolist() == [2, 1, 1]
    assert items.tolist() == [1, 2, 3, 2]
    # non-integer items surface as an exception, not silent corruption
    with pytest.raises(TypeError):
        fasttok.flatten([((1, "x"),)])


def test_build_vertical_identical_with_and_without_native(monkeypatch):
    db = synthetic_db(seed=7, n_sequences=200, n_items=15,
                      mean_itemsets=3.0, mean_itemset_size=1.4)
    with_native = build_vertical(db, min_item_support=2)
    monkeypatch.setattr(fasttok, "_mod", None)
    monkeypatch.setattr(fasttok, "_tried", True)
    without = build_vertical(db, min_item_support=2)
    for attr in ("item_ids", "seq_lengths", "item_supports",
                 "tok_item", "tok_seq", "tok_word", "tok_mask"):
        np.testing.assert_array_equal(getattr(with_native, attr),
                                      getattr(without, attr))
