"""Fused whole-mine-on-device engine (models/spade_fused.py).

Parity anchor: the CPU oracle, byte-identical pattern sets (SURVEY.md
sec 4).  The fused engine's enumeration is mask-vectorized SPAM S/I
candidate lists, so any divergence from the oracle's list rules shows up
here as a set difference.
"""

import numpy as np
import pytest

from spark_fsm_tpu.data.spmf import parse_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import build_vertical
from spark_fsm_tpu.models.oracle import mine_spade, mine_spade_vertical
from spark_fsm_tpu.models.spade_fused import (
    FusedCaps, FusedSpadeTPU, fused_eligible)
from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu
from spark_fsm_tpu.utils.canonical import patterns_text

ZAKI = "1 -1 2 -1 3 -2\n1 4 -1 3 -2\n1 -1 2 -1 3 4 -2\n1 3 -1 5 -2\n"
SMALL_CAPS = FusedCaps(f_cap=256, c_cap=2048, r_cap=16384)


def _fused(db, minsup, **kw):
    vdb = build_vertical(db, min_item_support=minsup)
    eng = FusedSpadeTPU(vdb, minsup, caps=kw.pop("caps", SMALL_CAPS), **kw)
    return eng, eng.mine()


def test_parity_zaki():
    db = parse_spmf(ZAKI)
    eng, got = _fused(db, 2)
    assert got is not None
    assert patterns_text(got) == patterns_text(mine_spade(db, 2))
    assert eng.stats["kernel_launches"] == 1
    assert eng.stats["candidates"] > 0


@pytest.mark.parametrize("seed,n,items,mi,misz,minsup,caps", [
    (7, 400, 40, 4.0, 1.6, 8, SMALL_CAPS),
    (9, 200, 25, 4.0, 2.5, 10, SMALL_CAPS),
    (21, 300, 60, 6.0, 1.3, 6, None),  # wide levels: default caps
])
def test_parity_synthetic(seed, n, items, mi, misz, minsup, caps):
    db = synthetic_db(seed=seed, n_sequences=n, n_items=items,
                      mean_itemsets=mi, mean_itemset_size=misz)
    _, got = _fused(db, minsup, caps=caps or FusedCaps())
    assert got is not None
    assert patterns_text(got) == patterns_text(mine_spade(db, minsup))


def test_parity_multiword():
    # > 32 itemsets/sequence -> n_words > 1 exercises the word-minor
    # flat layout + carry chains inside the fused program
    db = synthetic_db(seed=8, n_sequences=120, n_items=12,
                      mean_itemsets=40.0, mean_itemset_size=1.2)
    minsup = 60  # dense fixture: keep the pattern set bounded
    _, got = _fused(db, minsup,
                    caps=FusedCaps(f_cap=1024, c_cap=8192, r_cap=1 << 16))
    if got is None:  # legitimately explosive at this minsup: nothing to test
        pytest.skip("fixture overflowed fused caps")
    assert patterns_text(got) == patterns_text(mine_spade(db, minsup))


def test_max_pattern_itemsets():
    db = synthetic_db(seed=9, n_sequences=200, n_items=25,
                      mean_itemsets=4.0, mean_itemset_size=2.5)
    vdb = build_vertical(db, min_item_support=10)
    eng = FusedSpadeTPU(vdb, 10, max_pattern_itemsets=2, caps=SMALL_CAPS)
    got = eng.mine()
    want = mine_spade_vertical(vdb, 10, max_pattern_itemsets=2)
    assert got is not None
    assert patterns_text(got) == patterns_text(want)


def test_overflow_returns_none_and_auto_falls_back():
    db = synthetic_db(seed=7, n_sequences=400, n_items=40,
                      mean_itemsets=4.0, mean_itemset_size=1.6)
    tiny = FusedCaps(f_cap=16, c_cap=32, r_cap=64, l_max=8)
    eng, got = _fused(db, 8, caps=tiny)
    assert got is None and eng.stats.get("fused_overflow")
    # the wrapper must still return the full, correct set via the
    # classic engine
    stats = {}
    full = mine_spade_tpu(db, 8, stats_out=stats)
    assert patterns_text(full) == patterns_text(mine_spade(db, 8))


def test_auto_routing_uses_fused_for_small_dbs():
    db = parse_spmf(ZAKI)
    stats = {}
    got = mine_spade_tpu(db, 2, stats_out=stats)
    # auto prefers the sparse-frontier queue engine (models/spade_queue)
    assert stats.get("fused") == "queue"
    assert patterns_text(got) == patterns_text(mine_spade(db, 2))
    # the dense engine stays reachable, pinned
    stats_d = {}
    got_d = mine_spade_tpu(db, 2, stats_out=stats_d, fused="dense")
    assert stats_d.get("fused") is True
    assert patterns_text(got_d) == patterns_text(got)
    # fused="never" pins the classic engine; the routing decision is
    # still recorded (False), so artifact consumers can distinguish
    # "routed classic" from "this algorithm has no routing"
    stats2 = {}
    got2 = mine_spade_tpu(db, 2, stats_out=stats2, fused="never")
    assert stats2["fused"] is False
    assert patterns_text(got2) == patterns_text(got)


def test_eligibility():
    db = parse_spmf(ZAKI)
    vdb = build_vertical(db, min_item_support=2)
    assert fused_eligible(vdb)
    import jax
    from spark_fsm_tpu.parallel.mesh import make_mesh
    # single-process mesh: eligible (validated path)
    mesh = make_mesh(len(jax.devices()))
    assert fused_eligible(vdb, mesh=mesh)
    # negative paths: the routing guards must reject...  (stubs suffice —
    # fused_eligible only reads n_items/n_sequences/n_words)
    class FakeVdb:
        n_items = vdb.n_items
        n_sequences = vdb.n_sequences
        n_words = vdb.n_words
    # ...databases whose dense per-level traffic exceeds the cutoff
    big = FakeVdb()
    big.n_sequences = 300_000_000
    assert not fused_eligible(big)
    # ...alphabets wider than the mask arrays support
    wide = FakeVdb()
    wide.n_items = 5000
    assert not fused_eligible(wide)
    # (multi-host meshes are eligible too — the mesh assert above covers
    # the routing; tests/test_multihost.py's 2-process fused_parity check
    # validates the actual multi-controller execution)


def test_mesh_scaled_caps_widen_the_frontier():
    # FusedCaps.for_mesh grows the frontier with the device count at
    # constant per-device traffic (the pair matrix shards its sequence
    # axis) — this is what keeps the headline BMS-WebView-2 frontier
    # (~2.6k nodes) fused on a v5e-8 where the single-chip 1024-node cap
    # overflows.
    import jax
    from spark_fsm_tpu.parallel.mesh import make_mesh

    assert FusedCaps.for_mesh(None).f_cap == 1024
    mesh = make_mesh(len(jax.devices()))
    caps = FusedCaps.for_mesh(mesh)
    assert caps.f_cap == min(8192, 1024 * mesh.devices.size)
    assert caps.c_cap == 8 * caps.f_cap  # emission cap tracks the frontier

    # The routing property itself, at test size: a dense low-minsup DB
    # whose frontier exceeds 1024 nodes overflows the single-chip caps
    # (mine() -> None, the classic-engine fallback signal) and completes
    # byte-identically to the oracle at the mesh-scale frontier width.
    db = synthetic_db(seed=13, n_sequences=60, n_items=40,
                      mean_itemsets=6.0, mean_itemset_size=2.0,
                      correlation=0.8)
    vdb = build_vertical(db, min_item_support=2)
    assert FusedSpadeTPU(vdb, 2, caps=FusedCaps(f_cap=1024)).mine() is None
    wide = FusedSpadeTPU(vdb, 2, caps=FusedCaps(f_cap=8192)).mine()
    assert patterns_text(wide) == patterns_text(mine_spade(db, 2))


def test_parity_mesh():
    import jax
    from spark_fsm_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(len(jax.devices()))
    db = synthetic_db(seed=7, n_sequences=400, n_items=40,
                      mean_itemsets=4.0, mean_itemset_size=1.6)
    vdb = build_vertical(db, min_item_support=8)
    eng = FusedSpadeTPU(vdb, 8, mesh=mesh, caps=SMALL_CAPS)
    got = eng.mine()
    assert got is not None
    assert patterns_text(got) == patterns_text(mine_spade(db, 8))


def test_empty_and_single():
    assert _fused(parse_spmf("1 -2\n1 -2\n"), 2)[1] == [
        (((1,),), 2)]
    _, got = _fused(parse_spmf("1 -2\n"), 2)
    assert got == []


def test_shape_buckets_reuse_compile():
    # two window-ish DBs with different sizes must land on one compiled
    # shape when bucketed (streaming re-mines per micro-batch)
    db1 = synthetic_db(seed=30, n_sequences=100, n_items=15,
                      mean_itemsets=3.0)
    db2 = synthetic_db(seed=31, n_sequences=120, n_items=15,
                      mean_itemsets=3.0)
    for db, ms in ((db1, 5), (db2, 5)):
        vdb = build_vertical(db, min_item_support=ms)
        eng = FusedSpadeTPU(vdb, ms, caps=SMALL_CAPS, shape_buckets=True)
        got = eng.mine()
        assert got is not None
        assert patterns_text(got) == patterns_text(mine_spade(db, ms))
        assert eng.n_seq == 128  # both bucket to the same shape


def test_fused_eligible_allocation_ceiling():
    # Traffic alone once routed a 99k-seq x 3-word streaming window into
    # the fused engine, whose PEAK ALLOCATION (store + prep stack + joins
    # + kernel-layout transposes live at once) then OOM'd the chip.
    # Eligibility must model allocation too, and must judge the pow2-
    # BUCKETED sequence axis when shape_buckets is on (streaming windows).
    from types import SimpleNamespace

    from spark_fsm_tpu.models.spade_fused import fused_eligible

    small = SimpleNamespace(n_items=17, n_sequences=5000, n_words=1)
    assert fused_eligible(small)

    # CPU budget fallback is 4 GiB; a 300k x 3-word store (2177 rows x
    # ~6.3 MB bucketed) is tens of GB — must be rejected
    big = SimpleNamespace(n_items=17, n_sequences=300_000, n_words=3)
    assert not fused_eligible(big, shape_buckets=True)
    assert not fused_eligible(big)

    # bucketing must be part of the judgment: a size whose UNbucketed
    # allocation fits but whose pow2 bucket does not
    import jax

    from spark_fsm_tpu.models._common import device_hbm_budget
    budget = 0.45 * device_hbm_budget(jax.devices()[0])
    # store+4*prep ~= (2177 + 4*2048) * row_bytes; pick n_seq so that
    # unbucketed row bytes fit but the next pow2 does not
    rows_factor = (128 + 2 * 1024 + 1) + 4 * (2 * 1024)
    n_fit = int(budget / rows_factor / 4 * 0.9)  # W=1, 90% of the edge
    edge = SimpleNamespace(n_items=17, n_sequences=n_fit, n_words=1)
    if fused_eligible(edge):  # traffic cap may reject first on tiny budgets
        assert not fused_eligible(edge, shape_buckets=True) or (
            # only if the pow2 bucket still fits (n_fit just under a pow2)
            2 ** (n_fit - 1).bit_length() * rows_factor * 4 <= budget)
