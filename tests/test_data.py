

def test_synthetic_db_fast_shape_and_determinism():
    """The vectorized generator matches the exact one's distribution family
    (not its bytes — different rng consumption) and is deterministic."""
    import numpy as np

    from spark_fsm_tpu.data.synth import synthetic_db, synthetic_db_fast

    slow = synthetic_db(7, 3000, 500, mean_itemsets=4.0, mean_itemset_size=1.3)
    fast = synthetic_db_fast(7, 3000, 500, mean_itemsets=4.0,
                             mean_itemset_size=1.3)
    assert fast == synthetic_db_fast(7, 3000, 500, mean_itemsets=4.0,
                                     mean_itemset_size=1.3)  # deterministic
    for db in (slow, fast):
        lens = np.array([len(s) for s in db])
        assert len(db) == 3000 and lens.min() >= 1
        items = [i for s in db for st in s for i in st]
        assert min(items) >= 1 and max(items) <= 500
    # same length distribution (both draw Poisson lengths first)
    assert abs(np.mean([len(s) for s in slow])
               - np.mean([len(s) for s in fast])) < 0.15
    # mineable: frequent patterns exist (working-set correlation works)
    from spark_fsm_tpu.data.vertical import abs_minsup
    from spark_fsm_tpu.models.oracle import mine_spade

    assert len(mine_spade(fast, abs_minsup(0.05, len(fast)))) > 5
