"""Protocol-level RedisResultStore tests against an in-process RESP server.

The reference's RedisSink/RedisCache talk to a real Redis (SURVEY.md
sec 2); the rebuild's store speaks RESP2 on the wire (service/resp.py).
These tests run a miniature Redis — a socket server implementing the six
commands the store uses — so the exact bytes the store would send to
production Redis are what's exercised here.
"""

import json
import socket
import threading

import pytest

from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.service.resp import RespClient, RespError, encode_command
from spark_fsm_tpu.service.store import RedisResultStore


class MiniRedis:
    """RESP2 server on a loopback socket implementing the command subset
    the store uses: SET[ PX ms][ NX]/GET/RPUSH/LRANGE/LPOP/LLEN/LTRIM/
    DEL/INCR/KEYS/SCAN/PEXPIRE/PTTL/TTL/PING.

    Key expiry (the lease layer's substrate) runs on ``self.clock``
    (default ``time.monotonic``) with Redis-style lazy purge, so lease
    tests can drive a virtual clock instead of sleeping out TTLs."""

    def __init__(self, clock=None):
        self.kv = {}
        self.lists = {}
        self.expiry = {}  # key -> clock() deadline
        self.clock = clock if clock is not None else \
            __import__("time").monotonic
        self.lock = threading.Lock()
        self.srv = socket.socket()
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(4)
        self.port = self.srv.getsockname()[1]
        self.commands_seen = []
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        buf = b""

        def read_line():
            nonlocal buf
            while b"\r\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf += chunk
            line, buf = buf.split(b"\r\n", 1)
            return line

        def read_exact(n):
            nonlocal buf
            while len(buf) < n + 2:
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf += chunk
            payload, buf = buf[:n], buf[n + 2:]
            return payload

        try:
            while True:
                line = read_line()
                assert line[:1] == b"*", line
                nargs = int(line[1:])
                args = []
                for _ in range(nargs):
                    hdr = read_line()
                    assert hdr[:1] == b"$", hdr
                    args.append(read_exact(int(hdr[1:])).decode())
                conn.sendall(self._dispatch(args))
        except (ConnectionError, OSError):
            conn.close()

    def _alive(self, key):
        """Lazy expiry purge (callers hold the lock)."""
        deadline = self.expiry.get(key)
        if deadline is not None and self.clock() >= deadline:
            self.expiry.pop(key, None)
            self.kv.pop(key, None)
            self.lists.pop(key, None)
            return False
        return key in self.kv or key in self.lists

    def _dispatch(self, args):
        cmd, rest = args[0].upper(), args[1:]
        self.commands_seen.append(cmd)
        with self.lock:
            if cmd == "PING":
                return b"+PONG\r\n"
            if cmd == "SET":
                px, nx = None, False
                opts = [o.upper() for o in rest[2:]]
                i = 0
                while i < len(opts):
                    if opts[i] == "PX":
                        px = int(rest[3 + i])
                        i += 2
                    elif opts[i] == "NX":
                        nx = True
                        i += 1
                    else:
                        return b"-ERR syntax error\r\n"
                if nx and self._alive(rest[0]):
                    return b"$-1\r\n"  # NX refused: Null reply
                self.kv[rest[0]] = rest[1]
                if px is not None:
                    self.expiry[rest[0]] = self.clock() + px / 1000.0
                else:
                    self.expiry.pop(rest[0], None)  # plain SET clears TTL
                return b"+OK\r\n"
            if cmd == "GET":
                self._alive(rest[0])
                v = self.kv.get(rest[0])
                if v is None:
                    return b"$-1\r\n"
                vb = v.encode()
                return b"$%d\r\n%s\r\n" % (len(vb), vb)
            if cmd == "PEXPIRE":
                if not self._alive(rest[0]):
                    return b":0\r\n"
                self.expiry[rest[0]] = self.clock() + int(rest[1]) / 1000.0
                return b":1\r\n"
            if cmd in ("PTTL", "TTL"):
                if not self._alive(rest[0]):
                    return b":-2\r\n"
                deadline = self.expiry.get(rest[0])
                if deadline is None:
                    return b":-1\r\n"
                left = max(0.0, deadline - self.clock())
                return b":%d\r\n" % int(left * 1000 if cmd == "PTTL"
                                        else round(left))
            if cmd == "RPUSH":
                lst = self.lists.setdefault(rest[0], [])
                lst.extend(rest[1:])
                return b":%d\r\n" % len(lst)
            if cmd == "LRANGE":
                lst = self.lists.get(rest[0], [])
                start, stop = int(rest[1]), int(rest[2])
                stop = len(lst) if stop == -1 else stop + 1
                out = [b"*%d\r\n" % len(lst[start:stop])]
                for v in lst[start:stop]:
                    vb = v.encode()
                    out.append(b"$%d\r\n%s\r\n" % (len(vb), vb))
                return b"".join(out)
            if cmd == "LPOP":
                lst = self.lists.get(rest[0], [])
                if not lst:
                    return b"$-1\r\n"
                vb = lst.pop(0).encode()
                return b"$%d\r\n%s\r\n" % (len(vb), vb)
            if cmd == "LLEN":
                return b":%d\r\n" % len(self.lists.get(rest[0], []))
            if cmd == "LTRIM":
                lst = self.lists.get(rest[0])
                if lst is not None:
                    start, stop = int(rest[1]), int(rest[2])
                    stop = len(lst) if stop == -1 else stop + 1
                    self.lists[rest[0]] = lst[start:stop]
                return b"+OK\r\n"
            if cmd == "DEL":
                n = 0
                for k in rest:
                    alive = self._alive(k)
                    self.expiry.pop(k, None)
                    n += ((self.kv.pop(k, None) is not None) +
                          (self.lists.pop(k, None) is not None)) if alive \
                        else 0
                return b":%d\r\n" % n
            if cmd == "INCR":
                self._alive(rest[0])
                v = int(self.kv.get(rest[0], "0")) + 1
                self.kv[rest[0]] = str(v)
                return b":%d\r\n" % v
            if cmd == "KEYS":
                # prefix globs only — all the store's journal/lease
                # scans need
                assert rest[0].endswith("*"), rest
                pre = rest[0][:-1]
                ks = sorted(k for k in list(self.kv) + list(self.lists)
                            if k.startswith(pre) and self._alive(k))
                out = [b"*%d\r\n" % len(ks)]
                for k in ks:
                    kb = k.encode()
                    out.append(b"$%d\r\n%s\r\n" % (len(kb), kb))
                return b"".join(out)
            if cmd == "SCAN":
                # cursor iteration: the cursor is OPAQUE to clients
                # (real Redis returns decimal bucket cursors; here it is
                # the last key of the previous batch — "0" starts AND
                # terminates in both, which is all RespClient.scan
                # relies on).  Keys alive for the whole iteration are
                # returned exactly once.
                cursor, match, count = rest[0], None, 10
                i = 1
                while i < len(rest):
                    opt = rest[i].upper()
                    if opt == "MATCH":
                        match = rest[i + 1]
                        i += 2
                    elif opt == "COUNT":
                        count = int(rest[i + 1])
                        i += 2
                    else:
                        return b"-ERR syntax error\r\n"
                pre = ""
                if match is not None:
                    assert match.endswith("*"), match  # prefix globs only
                    pre = match[:-1]
                ks = sorted(k for k in list(self.kv) + list(self.lists)
                            if k.startswith(pre) and self._alive(k))
                if cursor != "0":
                    import bisect
                    ks = ks[bisect.bisect_right(ks, cursor):]
                batch = ks[:max(1, count)]
                nxt = "0" if len(ks) <= len(batch) else batch[-1]
                nb = nxt.encode()
                out = [b"*2\r\n", b"$%d\r\n%s\r\n" % (len(nb), nb),
                       b"*%d\r\n" % len(batch)]
                for k in batch:
                    kb = k.encode()
                    out.append(b"$%d\r\n%s\r\n" % (len(kb), kb))
                return b"".join(out)
            return b"-ERR unknown command '%s'\r\n" % cmd.encode()

    def close(self):
        self.srv.close()


@pytest.fixture()
def mini_redis():
    server = MiniRedis()
    yield server
    server.close()


def test_encode_command_bytes():
    assert encode_command("SET", "k", "v") == \
        b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$1\r\nv\r\n"


def test_client_roundtrip(mini_redis):
    c = RespClient(port=mini_redis.port)
    assert c.ping()
    c.set("a", "hello\r\nworld")  # CRLF inside a bulk string survives
    assert c.get("a") == "hello\r\nworld"
    assert c.get("missing") is None
    assert c.rpush("l", "x") == 1
    assert c.rpush("l", "y") == 2
    assert c.lrange("l") == ["x", "y"]
    assert c.llen("l") == 2
    assert c.lpop("l") == "x"
    assert c.lrange("l") == ["y"]
    assert c.lpop("missing") is None
    assert c.incr("n") == 1
    assert c.incr("n") == 2
    assert c.delete("a") == 1
    assert c.get("a") is None
    with pytest.raises(RespError, match="unknown command"):
        c.command("FLUSHALL")
    c.close()


def test_store_contract_over_wire(mini_redis):
    store = RedisResultStore(port=mini_redis.port)
    # status registry
    store.add_status("u1", "started")
    store.add_status("u1", "finished")
    assert store.status("u1") == "finished"
    assert [s for _, s in store.status_log("u1")] == ["started", "finished"]
    # results
    store.add_patterns("u1", '[{"support": 3}]')
    assert store.patterns("u1") == '[{"support": 3}]'
    store.add_rules("u1", "[]")
    assert store.rules("u1") == "[]"
    # field specs + tracked events
    store.add_fields("t", '{"item": "sku"}')
    assert store.fields("t") == '{"item": "sku"}'
    store.track("t", '{"sku": 5}')
    assert store.tracked("t") == ['{"sku": 5}']
    # counters + job cleanup
    assert store.incr("fsm:metric:jobs_submitted") == 1
    store.clear_job("u1")
    assert store.patterns("u1") is None
    assert store.status("u1") == "finished"  # clear_job keeps nothing? no:
    # clear_job without keep_status_log drops the log but not the status key
    assert store.status_log("u1") == []
    # every op above went over the socket, not the in-proc fallback
    assert "SET" in mini_redis.commands_seen
    assert "RPUSH" in mini_redis.commands_seen
    assert "INCR" in mini_redis.commands_seen


def test_store_end_to_end_mine(mini_redis):
    """A full train job through the Master with Redis-backed persistence."""
    from spark_fsm_tpu.service.actors import Master

    store = RedisResultStore(port=mini_redis.port)
    master = Master(store=store)
    try:
        req = ServiceRequest("fsm", "train", {
            "algorithm": "SPADE", "source": "INLINE",
            "sequences": "1 -1 2 -2\n1 -1 2 -2\n2 -1 1 -2\n",
            "support": "0.5"})
        resp = master.handle(req)
        uid = resp.data["uid"]
        deadline = __import__("time").time() + 30
        while __import__("time").time() < deadline:
            if store.status(uid) in ("finished", "failure"):
                break
            __import__("time").sleep(0.02)
        assert store.status(uid) == "finished", store.get(f"fsm:error:{uid}")
        assert store.patterns(uid) is not None
        # the mined patterns live in the mini-redis dict, not process memory
        assert mini_redis.kv[f"fsm:pattern:{uid}"] == store.patterns(uid)
    finally:
        master.shutdown()


def test_journal_contract_over_wire(mini_redis):
    """The write-ahead job journal (ISSUE 5) round-trips over RESP: the
    intent record persists across clients (what restart recovery reads
    after a kill -9) and the KEYS scan finds exactly the journal keys."""
    store = RedisResultStore(port=mini_redis.port)
    store.journal_set("j1", '{"incarnation": "a"}')
    store.journal_set("j2", '{"incarnation": "b"}')
    store.set("fsm:status:j1", "started")  # not a journal key
    assert store.journal_uids() == ["j1", "j2"]
    assert store.journal_get("j1") == '{"incarnation": "a"}'
    # a SECOND client (the rebooted incarnation) sees the same intents
    store2 = RedisResultStore(port=mini_redis.port)
    assert store2.journal_uids() == ["j1", "j2"]
    store2.journal_clear("j1")
    assert store.journal_uids() == ["j2"]
    # the journal walk is cursor-based now (ISSUE 9 satellite): SCAN on
    # the wire, never the server-blocking KEYS
    assert "SCAN" in mini_redis.commands_seen
    assert "KEYS" not in mini_redis.commands_seen


def test_key_expiry_over_wire_with_virtual_clock():
    """The lease-layer verbs (SET PX NX / PEXPIRE / PTTL) round-trip over
    RESP against a VIRTUAL monotonic clock — hermetic: no sleeps, no real
    Redis, exactly the bytes a production Redis would see."""
    t = [0.0]
    server = MiniRedis(clock=lambda: t[0])
    try:
        c = RespClient(port=server.port)
        # NX acquisition: first writer wins, second is refused
        assert c.set_px("lease", "holder-a", 5000, nx=True) is True
        assert c.set_px("lease", "holder-b", 5000, nx=True) is False
        assert c.get("lease") == "holder-a"
        assert 0 < c.pttl("lease") <= 5000
        # renewal re-arms the TTL
        t[0] = 4.0
        assert c.pexpire("lease", 5000) is True
        t[0] = 8.0  # would be past the ORIGINAL deadline
        assert c.get("lease") == "holder-a"
        # expiry: the key lazily purges and NX succeeds again
        t[0] = 9.5
        assert c.get("lease") is None
        assert c.pttl("lease") == -2
        assert c.pexpire("lease", 1000) is False
        assert c.set_px("lease", "holder-b", 5000, nx=True) is True
        # plain SET clears the TTL (Redis semantics)
        c.set("lease", "holder-b2")
        assert c.pttl("lease") == -1
        t[0] = 100.0
        assert c.get("lease") == "holder-b2"
        # DEL reports whether the key was still alive — the exclusive
        # claim arbiter the steal protocol rides on
        assert c.set_px("claim", "x", 1000) is True
        assert c.delete("claim") == 1
        assert c.delete("claim") == 0
        c.close()
    finally:
        server.close()


def test_inproc_store_expiry_matches_wire_semantics():
    """The in-process ResultStore implements the same expiry contract
    (virtual clock), so lease tests are backend-agnostic."""
    from spark_fsm_tpu.service.store import ResultStore

    t = [0.0]
    s = ResultStore(clock=lambda: t[0])
    assert s.set_px("lease", "a", 2000, nx=True) is True
    assert s.set_px("lease", "b", 2000, nx=True) is False
    assert 0 < s.pttl("lease") <= 2000
    t[0] = 1.5
    assert s.pexpire("lease", 2000) is True
    t[0] = 3.0
    assert s.get("lease") == "a"  # renewed past the original deadline
    t[0] = 3.6
    assert s.get("lease") is None
    assert s.pttl("lease") == -2
    assert s.pexpire("lease", 500) is False
    assert s.set_px("lease", "b", 1000, nx=True) is True
    # expired keys drop out of prefix scans (heartbeat/lease liveness
    # reads go through keys())
    assert s.keys("lease") == ["lease"]
    t[0] = 5.0
    assert s.keys("lease") == []
    # plain SET clears a TTL; DEL arbitrates exclusively
    s.set_px("claim", "x", 1000)
    s.set("claim", "y")
    t[0] = 50.0
    assert s.get("claim") == "y"
    assert s.delete("claim") == 1
    assert s.delete("claim") == 0


def test_scan_walks_large_keyspace_incrementally(mini_redis):
    """The KEYS→SCAN satellite (ISSUE 9 / ROADMAP item 1 follow-up):
    a large synthetic keyspace is walked in bounded cursor batches —
    several SCAN round-trips, complete coverage, no KEYS command — and
    expired keys drop out mid-iteration like any other read."""
    store = RedisResultStore(port=mini_redis.port)
    want = {f"fsm:journal:j{i:05d}" for i in range(1200)}
    for k in sorted(want):
        store.set(k, "{}")
    store.set("fsm:status:unrelated", "x")
    mini_redis.commands_seen.clear()
    got = list(store.scan_iter("fsm:journal:", count=100))
    assert set(got) == want
    assert len(got) == len(want)  # stable keyspace: exactly-once
    n_scans = mini_redis.commands_seen.count("SCAN")
    assert n_scans >= 12, f"expected incremental batches, got {n_scans}"
    assert "KEYS" not in mini_redis.commands_seen
    # one bounded step caps its reply at COUNT
    cur, batch = store.scan_keys("fsm:journal:", "0", count=50)
    assert len(batch) == 50 and cur != "0"
    cur2, batch2 = store.scan_keys("fsm:journal:", cur, count=50)
    assert batch2[0] > batch[-1]  # resumes strictly after the cursor
    # journal_uids (the recovery pass) rides the same cursor walk
    mini_redis.commands_seen.clear()
    uids = store.journal_uids()
    assert len(uids) == 1200 and "SCAN" in mini_redis.commands_seen
    assert "KEYS" not in mini_redis.commands_seen


def test_inproc_scan_matches_wire_semantics():
    """The in-process store implements the same SCAN contract (opaque
    cursor, "0" terminates, lazy expiry drops keys) so lease tests are
    backend-agnostic."""
    from spark_fsm_tpu.service.store import ResultStore

    t = [0.0]
    s = ResultStore(clock=lambda: t[0])
    for i in range(25):
        s.set(f"fsm:replica:r{i:02d}", "{}")
    s.set_px("fsm:replica:dying", "{}", 1000)
    seen = []
    cursor = "0"
    steps = 0
    while True:
        cursor, batch = s.scan_keys("fsm:replica:", cursor, count=7)
        seen.extend(batch)
        steps += 1
        if cursor == "0":
            break
    assert steps >= 4
    assert len(seen) == len(set(seen)) == 26
    t[0] = 2.0  # the PX key expires: later scans skip it
    assert "fsm:replica:dying" not in list(s.scan_iter("fsm:replica:"))


def test_lease_walks_use_scan_not_keys(mini_redis):
    """The steal/heartbeat/recovery walks (service/lease.py) must drive
    SCAN over the wire — KEYS blocks the shared server once per replica
    per heartbeat tick, the exact storm the satellite retires."""
    from spark_fsm_tpu.service.lease import LeaseManager

    store = RedisResultStore(port=mini_redis.port)
    mgr = LeaseManager(store, replica_id="scan-a", lease_ttl_s=30,
                       heartbeat_s=0)
    peer = LeaseManager(store, replica_id="scan-b", lease_ttl_s=30,
                        heartbeat_s=0)
    class _IdleMiner:  # just enough Miner surface for the steal scan
        def idle_capacity(self):
            return 1

        def queue_size(self):
            return 0

    mgr._miner = _IdleMiner()
    peer._miner = None

    def fake_peer_record():
        peer.publish_heartbeat()
        # overwrite with a loaded-looking record so the steal scan
        # actually walks scan-b's admission namespace
        from spark_fsm_tpu.utils import envelope

        raw = json.loads(envelope.unwrap(
            store.peek("fsm:replica:scan-b"))[0])
        raw.update({"queued": 1, "steal": True})
        store.set_px("fsm:replica:scan-b",
                     envelope.wrap(json.dumps(raw)), 30000)

    fake_peer_record()
    store.set("fsm:admission:scan-b:job1", "1")
    mini_redis.commands_seen.clear()
    peers = mgr.peers()
    assert [p["replica"] for p in peers] == ["scan-b"]
    mgr.steal_once()  # walks the peer's admission namespace
    assert "SCAN" in mini_redis.commands_seen
    assert "KEYS" not in mini_redis.commands_seen


def test_store_fails_fast_when_down():
    with pytest.raises(OSError):
        RedisResultStore(port=1)  # nothing listens there


def test_client_resyncs_after_protocol_error():
    """A malformed reply poisons the connection; the next command gets a
    fresh socket instead of off-by-one replies from the stale stream."""
    from spark_fsm_tpu.service.resp import RespProtocolError

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(2)
    replies = [b",3.14\r\n", b"+PONG\r\n"]  # RESP3 double (unknown), then ok

    def serve_conn(conn):
        try:
            while True:
                if not conn.recv(65536):
                    return
                conn.sendall(replies.pop(0))
        except (OSError, IndexError):
            conn.close()

    def accept_loop():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            threading.Thread(target=serve_conn, args=(conn,),
                             daemon=True).start()

    threading.Thread(target=accept_loop, daemon=True).start()
    c = RespClient(port=srv.getsockname()[1])
    with pytest.raises(RespProtocolError):
        c.ping()
    assert c._sock is None  # poisoned
    assert c.ping()         # transparent reconnect on a fresh stream
    c.close()
    srv.close()
