"""ELASTIC and PIWIK sources — the reference's last two source seams,
exercised for real: the Elasticsearch client speaks the actual
search/scroll HTTP API against an in-process mini-ES (the bytes a
production cluster would receive), and the Piwik source reads the
ecommerce item log schema from a sqlite export."""

import json
import sqlite3
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.service.sources import (
    SourceError, elastic_source, piwik_source)
from spark_fsm_tpu.service.store import ResultStore


# ------------------------------------------------------------- mini ES

class MiniES(BaseHTTPRequestHandler):
    """Two-page scroll over a class-level document list."""

    docs: list = []
    page_size_seen: list = []
    scrolls: dict = {}
    short_pages: bool = False

    def log_message(self, fmt, *args):
        pass

    def do_POST(self):  # noqa: N802
        body = json.loads(self.rfile.read(
            int(self.headers.get("Content-Length") or 0)) or b"{}")
        if self.path.startswith("/_search/scroll"):
            sid = body["scroll_id"]
            offset = MiniES.scrolls.get(sid)
            if offset is None:
                self._send(404, {"error": "no such scroll"})
                return
            size = MiniES.scrolls["size"]
            if MiniES.short_pages:  # multi-shard behavior: short non-final
                size = 1            # pages mid-scroll
            hits = MiniES.docs[offset:offset + size]
            MiniES.scrolls[sid] = offset + len(hits)
            self._send(200, {"_scroll_id": sid,
                             "hits": {"hits": [{"_source": d} for d in hits]}})
            return
        # /{index}/_search?scroll=1m
        size = int(body.get("size", 10))
        MiniES.page_size_seen.append(size)
        MiniES.scrolls = {"s1": size, "size": size}
        hits = MiniES.docs[:size]
        MiniES.scrolls["s1"] = len(hits)
        self._send(200, {"_scroll_id": "s1",
                         "hits": {"hits": [{"_source": d} for d in hits]}})

    def _send(self, code, obj):
        payload = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


@pytest.fixture()
def mini_es():
    server = ThreadingHTTPServer(("127.0.0.1", 0), MiniES)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()
    server.server_close()


def test_elastic_scroll_and_field_spec(mini_es):
    # 5 docs, page size 2 -> initial search + 2 scroll pages
    MiniES.docs = [
        {"shop": "s", "visitor": "u1", "ts": 1, "basket": 1, "sku": 3},
        {"shop": "s", "visitor": "u1", "ts": 2, "basket": 2, "sku": 5},
        {"shop": "s", "visitor": "u2", "ts": 1, "basket": 3, "sku": 3},
        {"shop": "s", "visitor": "u2", "ts": 2, "basket": 4, "sku": 5},
        {"shop": "s", "visitor": "u2", "ts": 2, "basket": 4, "sku": 7},
    ]
    MiniES.page_size_seen = []
    store = ResultStore()
    store.add_fields("clicks", json.dumps({
        "site": "shop", "user": "visitor", "timestamp": "ts",
        "group": "basket", "item": "sku"}))
    db = elastic_source(ServiceRequest("fsm", "train", {
        "url": mini_es, "index": "events", "topic": "clicks",
        "page_size": "2"}), store)
    assert MiniES.page_size_seen == [2]
    assert db == [((3,), (5,)), ((3,), (5, 7))]


def test_elastic_short_scroll_pages_not_truncated(mini_es):
    """A scroll page with fewer than page_size hits is NOT the end of the
    scroll (multi-shard clusters do this); only an empty page is."""
    MiniES.docs = [
        {"site": "s", "user": "u", "timestamp": t, "group": t, "item": t + 1}
        for t in range(5)
    ]
    MiniES.short_pages = True
    try:
        db = elastic_source(ServiceRequest("fsm", "train", {
            "url": mini_es, "index": "events", "page_size": "2"}),
            ResultStore())
    finally:
        MiniES.short_pages = False
    # all 5 docs survive: one 2-hit search page + three 1-hit scroll pages
    assert db == [((1,), (2,), (3,), (4,), (5,))]


def test_elastic_errors(mini_es):
    store = ResultStore()
    with pytest.raises(SourceError, match="needs 'url'"):
        elastic_source(ServiceRequest("fsm", "train", {"index": "x"}), store)
    with pytest.raises(SourceError, match="invalid index"):
        elastic_source(ServiceRequest("fsm", "train", {
            "url": mini_es, "index": "a/b"}), store)
    MiniES.docs = []
    with pytest.raises(SourceError, match="matched no documents"):
        elastic_source(ServiceRequest("fsm", "train", {
            "url": mini_es, "index": "events"}), store)
    with pytest.raises(SourceError, match="failed"):
        elastic_source(ServiceRequest("fsm", "train", {
            "url": "http://127.0.0.1:1", "index": "events"}), store)


# -------------------------------------------------------------- piwik

@pytest.fixture()
def piwik_db(tmp_path):
    path = str(tmp_path / "piwik.sqlite")
    conn = sqlite3.connect(path)
    conn.execute("""CREATE TABLE piwik_log_conversion_item (
        idsite INTEGER, idvisitor TEXT, server_time TEXT,
        idorder INTEGER, idaction_sku INTEGER)""")
    rows = [
        # visitor A: order 1 {3}, later order 2 {5}
        (1, "A", "2024-01-01 10:00:00", 1, 3),
        (1, "A", "2024-01-02 10:00:00", 2, 5),
        # visitor B: one order with two items
        (1, "B", "2024-01-01 11:00:00", 3, 3),
        (1, "B", "2024-01-01 11:00:00", 3, 7),
        # another site, filtered out by idsite=1
        (2, "C", "2024-01-01 12:00:00", 4, 9),
    ]
    conn.executemany(
        "INSERT INTO piwik_log_conversion_item VALUES (?,?,?,?,?)", rows)
    conn.commit()
    conn.close()
    return path


def test_piwik_purchase_sequences(piwik_db):
    store = ResultStore()
    db = piwik_source(ServiceRequest("fsm", "train", {
        "db": piwik_db, "idsite": "1"}), store)
    assert db == [((3,), (5,)), ((3, 7),)]
    # no filter: site 2's visitor appears too
    db_all = piwik_source(ServiceRequest("fsm", "train",
                                         {"db": piwik_db}), store)
    assert ((9,),) in db_all and len(db_all) == 3


def test_piwik_epoch_timestamps(tmp_path):
    path = str(tmp_path / "p2.sqlite")
    conn = sqlite3.connect(path)
    conn.execute("""CREATE TABLE piwik_log_conversion_item (
        idsite INTEGER, idvisitor TEXT, server_time INTEGER,
        idorder INTEGER, idaction_sku INTEGER)""")
    conn.executemany(
        "INSERT INTO piwik_log_conversion_item VALUES (?,?,?,?,?)",
        [(1, "A", 200, 2, 5), (1, "A", 100, 1, 3)])
    conn.commit()
    conn.close()
    db = piwik_source(ServiceRequest("fsm", "train", {"db": path}),
                      ResultStore())
    assert db == [((3,), (5,))]  # epoch ints order the itemsets


def test_piwik_mixed_timestamp_types(tmp_path):
    """Small integers must stay epochs: sqlite's strftime('%s', N) would
    read them as Julian day numbers (giving huge NEGATIVE epochs), so a
    column mixing ints and DATETIME strings must dispatch on typeof."""
    path = str(tmp_path / "p4.sqlite")
    conn = sqlite3.connect(path)
    conn.execute("""CREATE TABLE piwik_log_conversion_item (
        idsite INTEGER, idvisitor TEXT, server_time,
        idorder INTEGER, idaction_sku INTEGER)""")
    conn.executemany(
        "INSERT INTO piwik_log_conversion_item VALUES (?,?,?,?,?)",
        [(1, "A", 2000000, 2, 5),                      # small int epoch
         (1, "A", "1970-01-01 00:00:01", 1, 3),        # epoch 1, earlier
         # TEXT-affinity numeric epoch (CSV imports store everything as
         # text): must parse as 3000000, not NULL->0
         (1, "A", "3000000", 3, 9)])
    conn.commit()
    conn.close()
    db = piwik_source(ServiceRequest("fsm", "train", {"db": path}),
                      ResultStore())
    assert db == [((3,), (5,), (9,))]  # int row did NOT collapse to a huge
    #                  negative epoch, text-numeric row ordered last


def test_piwik_varchar_order_ids(tmp_path):
    """Real Piwik/Matomo idorder is a varchar (site-defined order ids);
    non-numeric ids must group itemsets, not crash."""
    path = str(tmp_path / "p3.sqlite")
    conn = sqlite3.connect(path)
    conn.execute("""CREATE TABLE piwik_log_conversion_item (
        idsite INTEGER, idvisitor TEXT, server_time TEXT,
        idorder TEXT, idaction_sku INTEGER)""")
    conn.executemany(
        "INSERT INTO piwik_log_conversion_item VALUES (?,?,?,?,?)",
        [(1, "A", "2024-01-01 10:00:00", "ORD-1001", 3),
         (1, "A", "2024-01-01 10:00:00", "ORD-1001", 7),
         (1, "A", "2024-01-02 10:00:00", "ORD-1002", 5)])
    conn.commit()
    conn.close()
    db = piwik_source(ServiceRequest("fsm", "train", {"db": path}),
                      ResultStore())
    assert db == [((3, 7), (5,))]


def test_piwik_errors(tmp_path):
    with pytest.raises(SourceError, match="needs a 'db'"):
        piwik_source(ServiceRequest("fsm", "train", {}), ResultStore())
    with pytest.raises(SourceError, match="cannot open"):
        piwik_source(ServiceRequest("fsm", "train",
                                    {"db": str(tmp_path / "nope.sqlite")}),
                     ResultStore())