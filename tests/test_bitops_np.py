import numpy as np

from spark_fsm_tpu.ops import bitops_np as B


def bits(*positions, n_words=1):
    out = np.zeros(n_words, dtype=np.uint32)
    for p in positions:
        out[p // 32] |= np.uint32(1 << (p % 32))
    return out


def naive_sext(b):
    """Bit-by-bit reference for the postfix mask."""
    n = b.shape[-1] * 32
    get = lambda p: (b[p // 32] >> (p % 32)) & 1
    out = np.zeros_like(b)
    for p in range(n):
        if any(get(q) for q in range(p)):
            out[p // 32] |= np.uint32(1 << (p % 32))
    return out


def test_sext_simple():
    b = bits(2)
    assert B.sext_transform(b).tolist() == [(0xFFFFFFFF << 3) & 0xFFFFFFFF]


def test_sext_zero():
    assert B.sext_transform(bits()).tolist() == [0]


def test_sext_first_bit_only_counts():
    # bits at 1 and 5 -> mask = everything strictly after 1
    got = B.sext_transform(bits(1, 5))
    assert got.tolist() == [(0xFFFFFFFF << 2) & 0xFFFFFFFF]


def test_sext_multiword_carry():
    b = bits(33, n_words=3)
    got = B.sext_transform(b)
    assert got[0] == 0
    assert got[1] == (0xFFFFFFFF << 2) & 0xFFFFFFFF
    assert got[2] == 0xFFFFFFFF
    b2 = bits(0, n_words=2)
    got2 = B.sext_transform(b2)
    assert got2[0] == 0xFFFFFFFE and got2[1] == 0xFFFFFFFF


def test_sext_random_vs_naive():
    rng = np.random.default_rng(0)
    for _ in range(50):
        b = rng.integers(0, 2**32, size=3, dtype=np.uint32)
        # sparsify so first-set-bit positions vary
        b &= rng.integers(0, 2**32, size=3, dtype=np.uint32)
        b &= rng.integers(0, 2**32, size=3, dtype=np.uint32)
        np.testing.assert_array_equal(B.sext_transform(b), naive_sext(b))


def test_sext_batched_shape():
    rng = np.random.default_rng(1)
    b = rng.integers(0, 2**32, size=(4, 5, 2), dtype=np.uint32)
    got = B.sext_transform(b)
    for i in range(4):
        for j in range(5):
            np.testing.assert_array_equal(got[i, j], B.sext_transform(b[i, j]))


def test_extensions_and_support():
    # seq0: prefix at pos 1, item at pos 3 -> s-ext hits, i-ext misses
    prefix = np.stack([bits(1), bits(2)])
    item = np.stack([bits(3), bits(2)])
    s = B.s_extend(prefix, item)
    assert s[0].tolist() == bits(3).tolist()
    assert s[1].tolist() == [0]
    i = B.i_extend(prefix, item)
    assert i[0].tolist() == [0]
    assert i[1].tolist() == bits(2).tolist()
    assert B.support(s) == 1 and B.support(i) == 1
    assert B.support(np.zeros((3, 2), np.uint32)) == 0


def test_first_set_positions():
    b = np.stack([bits(0, n_words=2), bits(37, 40, n_words=2), bits(n_words=2)])
    assert B.first_set_positions(b).tolist() == [0, 37, 64]


def naive_prefix_or(b):
    n = b.shape[-1] * 32
    get = lambda p: (b[p // 32] >> (p % 32)) & 1
    out = np.zeros_like(b)
    for p in range(n):
        if any(get(q) for q in range(p + 1)):
            out[p // 32] |= np.uint32(1 << (p % 32))
    return out


def naive_suffix_or(b):
    n = b.shape[-1] * 32
    get = lambda p: (b[p // 32] >> (p % 32)) & 1
    out = np.zeros_like(b)
    for p in range(n):
        if any(get(q) for q in range(p, n)):
            out[p // 32] |= np.uint32(1 << (p % 32))
    return out


def test_prefix_suffix_or_random_vs_naive():
    rng = np.random.default_rng(5)
    for _ in range(30):
        b = rng.integers(0, 2**32, size=3, dtype=np.uint32)
        b &= rng.integers(0, 2**32, size=3, dtype=np.uint32)
        b &= rng.integers(0, 2**32, size=3, dtype=np.uint32)
        np.testing.assert_array_equal(B.prefix_or_incl(b), naive_prefix_or(b))
        np.testing.assert_array_equal(B.suffix_or_incl(b), naive_suffix_or(b))


def test_shift_up_one():
    b = bits(0, 31, 40, n_words=2)
    got = B.shift_up_one(b)
    assert got.tolist() == bits(1, 32, 41, n_words=2).tolist()
    # top bit falls off the end
    top = bits(63, n_words=2)
    assert B.shift_up_one(top).tolist() == [0, 0]


# ------------------------- popcount / tail-word masking (ISSUE 15 satellite)


def test_popcount_matches_bin_count():
    rng = np.random.default_rng(11)
    w = rng.integers(0, 2**32, size=(7, 3), dtype=np.uint32)
    want = np.vectorize(lambda x: bin(int(x)).count("1"))(w)
    np.testing.assert_array_equal(B.popcount(w), want)


def test_tail_mask_edges():
    assert B.tail_mask(64, 2).tolist() == [0xFFFFFFFF, 0xFFFFFFFF]
    assert B.tail_mask(40, 2).tolist() == [0xFFFFFFFF, 0xFF]
    assert B.tail_mask(32, 2).tolist() == [0xFFFFFFFF, 0]
    assert B.tail_mask(1, 2).tolist() == [1, 0]
    assert B.tail_mask(0, 2).tolist() == [0, 0]
    # n_valid past the word span saturates
    assert B.tail_mask(100, 2).tolist() == [0xFFFFFFFF, 0xFFFFFFFF]


def test_masked_popcount_ignores_sext_padding_bits():
    """THE observable bug: the SPAM s-extension shift saturates every
    bit above the first occurrence — including tail-word padding
    positions past the true capacity — so an unmasked popcount
    overcounts by the padding width."""
    n_valid = 40  # 2 words, 24 padding bits in the tail word
    b = bits(3, n_words=2)  # first occurrence at position 3
    t = B.sext_transform(b)
    naive = int(B.popcount(t).sum())
    masked = int(B.masked_popcount(t, n_valid))
    assert naive == 60          # 64 - 4: every bit after 3, pads included
    assert masked == 36         # 40 - 4: valid positions only
    assert naive - masked == 24  # exactly the padding width


def test_pack_seq_bits_non_word_multiple_sequence_count():
    """Packed-sequence-word support: a sequence count that is not a
    multiple of the word width gets an explicit all-zero tail pad, so
    popcount(packed) == the true alive count."""
    rng = np.random.default_rng(12)
    for n_seq in (1, 31, 32, 33, 45, 64, 95):
        act = rng.random((4, n_seq)) < 0.5
        packed = B.pack_seq_bits(act)
        assert packed.shape == (4, -(-n_seq // 32))
        np.testing.assert_array_equal(
            B.popcount(packed).sum(axis=-1), act.sum(axis=-1))


def test_support_popcount_matches_support():
    rng = np.random.default_rng(13)
    bm = rng.integers(0, 2**32, size=(6, 45, 2), dtype=np.uint32)
    bm &= rng.integers(0, 2**32, size=(6, 45, 2), dtype=np.uint32)
    np.testing.assert_array_equal(B.support_popcount(bm), B.support(bm))
    # all-zero and all-ones extremes
    assert B.support_popcount(np.zeros((3, 2), np.uint32)) == 0
    assert B.support_popcount(np.full((1, 33, 1), 7, np.uint32)) == 33
