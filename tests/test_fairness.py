"""Weighted-fair multi-tenant admission (ISSUE 13,
service/fairness.py): DRR service order, per-tenant occupancy caps
with bucket-derived Retry-After, the bounded tenant vocabulary, and
the flood-tenant starvation drill.

The acceptance contract: fairness layers UNDER the strict priority
classes (a high job from any tenant beats every normal job), a
flooding tenant sheds 429s with ITS OWN refill-derived Retry-After
while other tenants' goodput holds at their weight-fair share, and
the disabled path leaves the queue byte-for-byte FIFO."""

import threading
import time

import pytest

from spark_fsm_tpu import config as cfgmod
from spark_fsm_tpu.service import fairness, sources
from spark_fsm_tpu.service.actors import (AdmissionQueue, AdmissionShed,
                                          Master, Miner)
from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.service.store import ResultStore

DRILL_TIMEOUT_S = 120.0


def _cfg(**fair):
    fair.setdefault("enabled", True)
    return cfgmod.parse_config({"fairness": fair})


@pytest.fixture
def fairness_on(request):
    """Boot config with fairness enabled (+ optional marker-style
    overrides via indirect param); restored after."""
    old = cfgmod.get_config()
    overrides = getattr(request, "param", {})
    cfgmod.set_config(_cfg(**overrides))
    yield cfgmod.get_config()
    cfgmod.set_config(old)


def _req(uid, **extra):
    data = {"algorithm": "SPADE", "source": "INLINE",
            "sequences": "1 -1 2 -2\n1 -1 2 -2\n", "support": "1.0",
            "uid": uid}
    data.update({k: str(v) for k, v in extra.items()})
    return ServiceRequest("fsm", "train", data)


def _wait(store, uid, timeout=DRILL_TIMEOUT_S):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = store.status(uid)
        if st in ("finished", "failure"):
            return st
        time.sleep(0.01)
    raise TimeoutError(f"job {uid} reached no terminal status")


def _queue(weights=None, depth=0, **fair):
    cfg = _cfg(weights=weights or {}, **fair)
    return AdmissionQueue(depth,
                          fair=fairness.TenantScheduler(cfg.fairness))


def _fill(q, tenant, n, priority="normal", prefix=None):
    for i in range(n):
        ok, *_ = q.try_reserve(priority, tenant)
        assert ok
        q.put(_req(f"{prefix or tenant}{i}"), priority, tenant)


# ----------------------------------------------------------- DRR mechanics


def test_drr_interleaves_equal_weights_round_robin():
    q = _queue()
    _fill(q, "a", 4)
    _fill(q, "b", 4)
    order = [q.get().uid[0] for _ in range(8)]
    # one job per tenant per round: strict alternation
    assert order == list("abababab")


def test_drr_serves_proportionally_to_weights():
    q = _queue(weights={"gold": 2.0, "free": 1.0})
    _fill(q, "gold", 8)
    _fill(q, "free", 8)
    first9 = [q.get().uid for _ in range(9)]
    n_gold = sum(1 for u in first9 if u.startswith("gold"))
    # 2:1 service ratio over three rounds of 3
    assert n_gold == 6, first9


def test_drr_idle_tenant_banked_credit_does_not_starve():
    q = _queue()
    _fill(q, "a", 6)
    # serve a few of a's jobs while b is idle
    for _ in range(3):
        assert q.get().uid.startswith("a")
    # b arrives late: it gets its fair share from NOW, not a banked
    # backlog of quanta for the rounds it sat out
    _fill(q, "b", 3)
    order = [q.get().uid[0] for _ in range(6)]
    assert order.count("b") == 3
    assert order[:2] != ["b", "b"], order


def test_priority_classes_stay_strict_above_fairness():
    q = _queue()
    _fill(q, "a", 3, priority="normal")
    _fill(q, "b", 1, priority="high", prefix="hi-b")
    # the high-class job wins regardless of tenant round-robin state
    assert q.get().uid == "hi-b0"


def test_remove_uid_and_pop_all_keep_tenant_accounting():
    q = _queue()
    _fill(q, "a", 3)
    _fill(q, "b", 2)
    assert q.remove("a1") is not None
    assert q.tenant_depths() == {"a": 2, "b": 2}
    rest = q.pop_all()
    assert len(rest) == 4
    assert q.tenant_depths() == {}
    assert q.size() == 0


# ------------------------------------------------- caps, sheds, Retry-After


def test_tenant_cap_sheds_with_tenant_counts():
    q = _queue(tenant_depth=2, depth=100)
    _fill(q, "flood", 2)
    ok, queued, ahead, scope = q.try_reserve("normal", "flood")
    assert (ok, scope) == (False, "tenant")
    assert queued == 2  # the TENANT's occupancy, not the global depth
    # other tenants are untouched by the flood's cap
    ok, *_ , scope = q.try_reserve("normal", "quiet")
    assert ok and scope == ""


def test_global_bound_still_binds_under_fairness():
    q = _queue(tenant_depth=0, depth=2)
    _fill(q, "a", 2)
    ok, queued, ahead, scope = q.try_reserve("normal", "b")
    assert (ok, scope) == (False, "queue")
    assert queued == 2


def test_reserve_abort_returns_tenant_token():
    q = _queue(tenant_depth=1)
    ok, *_ = q.try_reserve("normal", "a")
    assert ok
    ok, *_, scope = q.try_reserve("normal", "a")
    assert not ok and scope == "tenant"
    q.abort("a")
    ok, *_, scope = q.try_reserve("normal", "a")
    assert ok and scope == ""
    q.abort("a")


def test_retry_after_tracks_tenant_share():
    sched = fairness.TenantScheduler(
        _cfg(weights={"gold": 4.0, "free": 1.0}).fairness)
    # same backlog, same service rate: the low-weight tenant waits
    # proportionally longer because its bucket refills at its share
    slow = sched.retry_after_s("free", 10, per_job_s=2.0, workers=2,
                               active=["gold", "free"])
    fast = sched.retry_after_s("gold", 10, per_job_s=2.0, workers=2,
                               active=["gold", "free"])
    assert slow > fast >= 1
    assert slow >= 4 * fast / 2  # 4x share, integer ceil slack


def test_miner_tenant_shed_is_429_with_own_retry(fairness_on,
                                                 monkeypatch):
    cfgmod.set_config(_cfg(tenant_depth=1))
    gate = threading.Event()
    entered = threading.Event()
    real = sources.get_db

    def gated(req, store):
        entered.set()
        assert gate.wait(DRILL_TIMEOUT_S)
        return real(req, store)

    monkeypatch.setattr(sources, "get_db", gated)
    store = ResultStore()
    miner = Miner(store, workers=1)
    try:
        miner.submit(_req("f0", tenant="flood"))  # runs (gated)
        # f0 must have LEFT the queue (its token returned) before the
        # cap=1 arithmetic below is deterministic
        assert entered.wait(DRILL_TIMEOUT_S)
        miner.submit(_req("f1", tenant="flood"))  # queued: cap reached
        with pytest.raises(AdmissionShed) as exc:
            miner.submit(_req("f2", tenant="flood"))
        assert "tenant 'flood'" in str(exc.value)
        assert exc.value.retry_after_s >= 1
        # the shed left zero trace of the uid
        assert store.status("f2") is None
        assert store.journal_get("f2") is None
        # a different tenant still admits — the cap is per tenant
        miner.submit(_req("q0", tenant="quiet"))
    finally:
        gate.set()
        for uid in ("f0", "f1", "q0"):
            _wait(store, uid)
        miner.shutdown()


def test_bounded_tenant_vocabulary(fairness_on):
    cfgmod.set_config(_cfg(max_tenants=2))  # "default" + one more
    store = ResultStore()
    miner = Miner(store, workers=1)
    try:
        miner.submit(_req("a0", tenant="alpha"))
        resp_exc = None
        try:
            miner.submit(_req("b0", tenant="beta"))
        except ValueError as exc:
            resp_exc = exc
        assert resp_exc is not None and "vocabulary full" in str(resp_exc)
        assert store.status("b0") is None  # refused before any write
        with pytest.raises(ValueError, match="invalid tenant"):
            miner.submit(_req("c0", tenant="bad tenant!"))
        # the registered tenant and the default stay usable
        miner.submit(_req("a1", tenant="alpha"))
        miner.submit(_req("d0"))
    finally:
        for uid in ("a0", "a1", "d0"):
            _wait(store, uid)
        miner.shutdown()


# ------------------------------------------------------- starvation drill


def test_flood_tenant_cannot_starve_background_tenant(fairness_on,
                                                      monkeypatch):
    """The ISSUE 13 fairness drill, hermetic: a flooding tenant's
    backlog is interleaved 1:1 with the background tenant's (equal
    weights), so the background tenant's k jobs all finish within ~2x
    its weight-fair share of the service slots — instead of queueing
    behind the whole flood as FIFO would."""
    gate = threading.Event()
    order = []
    real = sources.get_db

    def tracking(req, store):
        if req.uid == "hold":
            assert gate.wait(DRILL_TIMEOUT_S)
        else:
            order.append(req.uid)
        return real(req, store)

    monkeypatch.setattr(sources, "get_db", tracking)
    store = ResultStore()
    miner = Miner(store, workers=1)
    try:
        # occupy the single worker so the whole mix queues up first
        miner.submit(_req("hold", tenant="flood"))
        for i in range(12):
            miner.submit(_req(f"fl{i}", tenant="flood"))
        for i in range(4):
            miner.submit(_req(f"bg{i}", tenant="bg"))
        gate.set()
        for i in range(4):
            _wait(store, f"bg{i}")
        # fair share with equal weights = every other slot: bg's 4 jobs
        # must all have STARTED within the first 2*4 = 8 service slots
        # (+1 slack for the round the flood leads)
        started_before_last_bg = order.index("bg3") + 1
        assert started_before_last_bg <= 9, order
    finally:
        gate.set()
        for i in range(12):
            _wait(store, f"fl{i}")
        _wait(store, "hold")
        miner.shutdown()


def test_disabled_path_is_fifo_and_ignores_tenant():
    q = AdmissionQueue(0)  # no scheduler: the pre-ISSUE-13 queue
    for i in range(4):
        ok, _, _, scope = q.try_reserve("normal",
                                        "t%d" % (i % 2))
        assert ok and scope == ""
        q.put(_req(f"j{i}"), "normal")
    assert [q.get().uid for _ in range(4)] == ["j0", "j1", "j2", "j3"]
    assert q.tenant_depths() == {}


def test_heartbeat_piggybacks_tenant_depths_and_drain_state(
        fairness_on):
    from spark_fsm_tpu.service.lease import LeaseManager

    store = ResultStore()
    mgr = LeaseManager(store, replica_id="rep-t", heartbeat_s=0)
    miner = Miner(store, workers=1, lease_mgr=mgr)
    try:
        gate_req = _req("slowhb", tenant="gold")
        # no gating needed: just check the snapshot fields exist
        mgr.publish_heartbeat()
        import json as _json

        from spark_fsm_tpu.utils import envelope as _env

        rec = _json.loads(_env.unwrap(store.peek("fsm:replica:rep-t"))[0])
        assert rec["draining"] is False
        assert rec["tenants"] == {}
        assert rec["fps"] == []
        mgr.set_draining(True)
        rec = _json.loads(_env.unwrap(store.peek("fsm:replica:rep-t"))[0])
        assert rec["draining"] is True and rec["free"] == 0
        assert gate_req is not None
    finally:
        miner.shutdown()


def test_fairness_config_validation():
    with pytest.raises(cfgmod.ConfigError, match="tenant_depth"):
        cfgmod.parse_config({"fairness": {"tenant_depth": -1}})
    with pytest.raises(cfgmod.ConfigError, match="max_tenants"):
        cfgmod.parse_config({"fairness": {"max_tenants": 0}})
    with pytest.raises(cfgmod.ConfigError, match="default_weight"):
        cfgmod.parse_config({"fairness": {"default_weight": 0}})
    with pytest.raises(cfgmod.ConfigError, match="weight"):
        cfgmod.parse_config(
            {"fairness": {"weights": {"t": -2.0}}})
    with pytest.raises(cfgmod.ConfigError, match="weight"):
        cfgmod.parse_config(
            {"fairness": {"weights": {"t": "not-a-number"}}})
