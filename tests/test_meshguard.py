"""Degraded-topology survival drills (ISSUE 20, service/meshguard.py).

The contracts under test:

- HEALTH STATE MACHINE: device-shaped failures walk a partition row
  healthy -> suspect -> dead (``dead_after`` trips); non-device
  exceptions never move health; suspect rows heal on success; dead rows
  never heal passively; every death bumps the topology epoch; the
  heartbeat gossip merge is monotone (max epoch, union dead) so order
  cannot matter.
- DEGRADED RE-PLAN: ``replan_surviving`` re-homes ONLY the dead rows'
  classes (LPT over recorded class costs) — survivors keep theirs —
  and ``adopters_for`` maps each dead part to a deterministic surviving
  adopter.
- IN-FLIGHT ADOPTION PARITY: killing a partition row mid-mine on the
  8-virtual-device 2x4 mesh re-homes its slice onto the survivor and
  the merged result stays byte-identical to the healthy run.
- STALE-EPOCH FENCE: launches planned against a pre-death epoch are
  REFUSED (StaleTopology) at the engine dispatch and the fusion broker
  entry — never silently run on dead silicon.
- CRASH-LOOP QUARANTINE: a poison job that kills every holder is
  adopted exactly ``[cluster] max_adoptions`` times across a 2-miner
  fleet, then settles as a durable ``POISON:`` terminal with an
  ``fsm:quarantine:{uid}`` record; resubmits 409 until the record is
  released, after which the job completes clean.
- CORRUPT-INTENT SETTLE: an undecodable journal intent quarantines AND
  settles as a durable failure (outcome="corrupt") — no forever-pending
  uid.
"""

import json
import time

import numpy as np
import pytest

from spark_fsm_tpu.config import MeshguardConfig
from spark_fsm_tpu.data.spmf import format_spmf
from spark_fsm_tpu.data.synth import kosarak_like, synthetic_db
from spark_fsm_tpu.parallel import partition as PN
from spark_fsm_tpu.parallel.mesh import make_mesh
from spark_fsm_tpu.service import integrity, meshguard as MG
from spark_fsm_tpu.service.actors import Master, recover_orphans
from spark_fsm_tpu.service.lease import LeaseManager
from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.utils import faults, obs
from spark_fsm_tpu.utils.canonical import rules_text

DRILL_TIMEOUT_S = 120.0


def _req(uid, **extra):
    # SPADE_TPU: the plain-CPU plugin ignores the checkpoint object, and
    # the poison drill's crash fires INSIDE checkpoint.save
    data = {"algorithm": "SPADE_TPU", "source": "INLINE",
            "sequences": format_spmf(synthetic_db(
                seed=17, n_sequences=120, n_items=10, mean_itemsets=3.0,
                mean_itemset_size=1.3)),
            "support": "0.1", "uid": uid}
    data.update(extra)
    return ServiceRequest("fsm", "train", data)


def _await(cond, what, timeout=DRILL_TIMEOUT_S):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise TimeoutError(f"never happened: {what}")


# ------------------------------------------------- health state machine


def test_meshguard_health_state_machine_and_gossip():
    g = MG.MeshGuard(dead_after=2)
    assert g.state_of(0) == MG.HEALTHY
    # non-device exceptions never move health: None = caller re-raises
    assert g.note_row_fault(0, ValueError("store blip")) is None
    assert g.state_of(0) == MG.HEALTHY
    assert g.note_row_fault(
        0, faults.FaultInjected("injected fault")) == MG.SUSPECT
    g.note_row_ok(0)  # a suspect row heals on success
    assert g.state_of(0) == MG.HEALTHY
    assert g.current_epoch() == 0
    assert g.note_row_fault(0, None) == MG.SUSPECT
    assert g.note_row_fault(0, None) == MG.DEAD  # dead_after=2 trips
    assert g.current_epoch() == 1  # every death is an epoch
    g.note_row_ok(0)  # dead rows never heal passively
    assert g.state_of(0) == MG.DEAD
    assert g.dead_rows() == frozenset({0})
    # gossip merge is monotone (max epoch, union dead): order-free
    h = MG.MeshGuard(dead_after=2)
    h.merge_peer(g.heartbeat_payload())
    assert h.state_of(0) == MG.DEAD and h.current_epoch() == 1
    h.merge_peer({"epoch": 0, "dead": []})  # a stale peer view: no-op
    assert h.state_of(0) == MG.DEAD and h.current_epoch() == 1
    h.merge_peer(None)  # solo replicas advertise None
    h.merge_peer({"epoch": "garbage"})  # bitrot tolerated
    assert h.current_epoch() == 1


def test_probe_trips_and_fences_row():
    g = MG.MeshGuard(dead_after=1)
    g.register_rows({0: (), 1: ()})
    assert g.probe() == {0: MG.HEALTHY, 1: MG.HEALTHY}
    faults.arm("device.dispatch", every=1, match="part1")
    try:
        out = g.probe()
    finally:
        faults.disarm()
    assert out == {0: MG.HEALTHY, 1: MG.DEAD}  # dead_after=1 fences
    assert g.current_epoch() == 1
    assert g.probe()[1] == MG.DEAD  # dead rows are not re-probed


# ------------------------------------------------------ degraded re-plan


def test_replan_surviving_keeps_survivors_and_lpt_rebalances():
    rng = np.random.default_rng(11)
    ids = rng.choice(100000, size=400, replace=False)
    sups = rng.integers(1, 1000, size=400)
    plan = PN.plan_partitions(ids, sups, 4, 64, record=False)
    new = PN.replan_surviving(plan, [1, 3])
    assert (new.n_parts, new.n_classes) == (plan.n_parts, plan.n_classes)
    for c in range(plan.n_classes):
        if int(plan.owner[c]) in (0, 2):  # survivors keep their classes
            assert int(new.owner[c]) == int(plan.owner[c])
        else:  # orphaned classes land on SOME survivor
            assert int(new.owner[c]) in (0, 2)
    # dead partitions end empty; total cost is conserved
    assert float(new.part_costs[1]) == 0.0
    assert float(new.part_costs[3]) == 0.0
    assert np.isclose(new.part_costs.sum(), plan.part_costs.sum())
    # LPT keeps the 2-survivor split bounded
    assert new.part_costs[[0, 2]].max() < 0.8 * new.part_costs.sum()
    # deterministic: every process derives the identical re-plan
    again = PN.replan_surviving(plan, [3, 1])
    assert (again.owner == new.owner).all()
    assert PN.replan_surviving(plan, []) is plan
    with pytest.raises(ValueError):
        PN.replan_surviving(plan, [0, 1, 2, 3])


def test_adopters_for_is_deterministic_lpt():
    rng = np.random.default_rng(3)
    ids = rng.choice(100000, size=300, replace=False)
    sups = rng.integers(1, 1000, size=300)
    plan = PN.plan_partitions(ids, sups, 4, 64, record=False)
    ad = PN.adopters_for(plan, [1, 2])
    assert set(ad) == {1, 2}
    assert set(ad.values()) <= {0, 3}
    # both survivors share the orphaned slices (LPT: the two dead
    # parts' loads spread, they do not both pile onto one survivor)
    assert len(set(ad.values())) == 2
    assert PN.adopters_for(plan, [2, 1]) == ad
    with pytest.raises(ValueError):
        PN.adopters_for(plan, [0, 1, 2, 3])


# ------------------------------------------------- stale-topology fence


def test_stale_epoch_refused_at_engine_and_broker():
    g = MG.install(MeshguardConfig(enabled=True, dead_after=1))
    try:
        assert MG.current_epoch() == 0
        MG.check_epoch(0)  # planned == current: passes
        MG.check_epoch(None)  # pre-plane launches always pass
        g.mark_dead(0)
        with pytest.raises(MG.StaleTopology) as ei:
            MG.check_epoch(0)
        assert ei.value.planned == 0 and ei.value.current == 1
        MG.check_epoch(1)  # re-planned launches pass again
        # broker entry: a stale unfusable wave is REFUSED (StaleTopology
        # propagates), never degraded onto dead silicon
        from spark_fsm_tpu.service import fusion
        with pytest.raises(MG.StaleTopology):
            fusion.dispatch_wave(object(), lambda: None, topology_epoch=0)
    finally:
        MG.reset()


# ------------------------------------- in-flight adoption (kill a row)


def test_tsr_partitioned_row_death_adoption_parity():
    """Chaos drill: on the 8-virtual-device 2x4 mesh, a device-shaped
    fault kills partition row 0 mid-mine (dead_after=1); its slice is
    adopted by the surviving row and the merged rules stay
    byte-identical to the healthy single-device run."""
    from spark_fsm_tpu.models.tsr import mine_tsr_tpu

    db = kosarak_like(scale=0.002, fast=True)
    want = rules_text(mine_tsr_tpu(db, 100, 0.5, max_side=2))
    g = MG.install(MeshguardConfig(enabled=True, dead_after=1))
    try:
        faults.arm("device.dispatch", every=1, times=1, match="part0")
        got = mine_tsr_tpu(db, 100, 0.5, max_side=2, mesh=make_mesh(8),
                           partition_parts=2)
        assert rules_text(got) == want
        assert g.dead_rows() == frozenset({0})
        assert g.current_epoch() >= 1
        # unlabelled counters snapshot to a bare float
        assert obs.REGISTRY.snapshot()["fsm_mesh_replans_total"] >= 1
    finally:
        faults.disarm()
        MG.reset()


# ---------------------------------------------- corrupt-intent recovery


def test_recover_orphans_corrupt_intent_settles_durably():
    store = ResultStore()
    store.set("fsm:journal:rot-1", "definitely { not json")
    master = Master(store=store, miner_workers=0)
    try:
        report = recover_orphans(master)
    finally:
        master.shutdown()
    assert report["quarantined"] == ["rot-1"]
    # quarantined AND settled: the client polling rot-1 sees a terminal
    assert store.status("rot-1") == "failure"
    assert "corrupt" in (store.get("fsm:error:rot-1") or "")
    assert store.peek("fsm:journal:rot-1") is None  # moved
    assert store.peek("fsm:quarantine:rot-1") is not None
    snap = obs.REGISTRY.snapshot()["fsm_recovery_jobs_total"]
    assert snap.get("outcome=corrupt", 0) >= 1


# -------------------------------------------- quarantine ledger (unit)


def test_quarantine_ledger_only_poison_blocks():
    store = ResultStore()
    MG.poison_record(store, "u-poison", reason="budget", adoptions=3)
    rec = MG.poisoned(store, "u-poison")
    assert rec["adoptions"] == 3 and rec["surface"] == "poison"
    # idempotent: re-settling neither rewrites nor recounts
    MG.poison_record(store, "u-poison", reason="other", adoptions=9)
    assert MG.poisoned(store, "u-poison")["reason"] == "budget"
    # an ISSUE 18 integrity quarantine (surface "journal") must NOT
    # block re-admission — only crash-loop poison does
    integrity.quarantine(store, "fsm:journal:u-bitrot", "raw??", "journal")
    assert MG.poisoned(store, "u-bitrot") is None
    rows = MG.quarantine_list(store)
    assert {r.get("surface") for r in rows} == {"poison", "journal"}
    assert MG.quarantine_release(store, "nope") is False  # the 404 case
    assert MG.quarantine_release(store, "u-poison") is True
    assert MG.poisoned(store, "u-poison") is None


# ------------------------------------------------- steal bumps adoptions


def test_steal_bumps_adoption_counter():
    t = [0.0]
    store = ResultStore(clock=lambda: t[0])
    mgr = LeaseManager(store, replica_id="thief", lease_ttl_s=10.0,
                       heartbeat_s=0, clock=lambda: t[0])
    calls = {}

    class FakeMiner:
        def note_adoption(self, uid, count):
            calls["adoption"] = (uid, count)

        def submit(self, req):
            calls["submitted"] = req.uid

    mgr.start(FakeMiner())
    store.journal_set("s1", json.dumps(
        {"uid": "s1", "adoptions": 1, "ts": 1.0,
         "request": {"uid": "s1"}}))
    store.set("fsm:admission:victim:s1", "1")
    assert mgr._steal_one("fsm:admission:victim:s1", "s1", "victim")
    assert calls["submitted"] == "s1"
    assert calls["adoption"] == ("s1", 2)  # parsed 1, staged 2


# --------------------------------- crash-loop quarantine (2-miner fleet)


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_poison_job_quarantined_after_max_adoptions():
    """The acceptance drill: a poison job (every holder crashes at its
    first checkpoint save) is adopted exactly ``max_adoptions`` (3)
    times across a 2-miner fleet, then settles as a durable ``POISON:``
    terminal; resubmission 409s until ``/admin/quarantine`` releases
    the record, after which the job completes clean."""

    class _Crash(KeyboardInterrupt):
        """BaseException: kills the worker thread like a process crash
        — Miner._loop's supervision catches only Exception, so the
        journal intent and lease survive untouched."""

    uid = "poison-drill"
    t = [0.0]
    store = ResultStore(clock=lambda: t[0])
    mk = lambda rid: LeaseManager(store, replica_id=rid, lease_ttl_s=5.0,
                                  heartbeat_s=0, clock=lambda: t[0])
    # each crash permanently consumes one worker THREAD (the point of
    # the drill: real crashed processes); 3 per miner leaves a survivor
    # on rep-b for the post-release clean run
    master_a = Master(store=store, miner_workers=3, lease_mgr=mk("rep-a"))
    master_b = Master(store=store, miner_workers=3, lease_mgr=mk("rep-b"))

    def crashes():
        # injection counters are CUMULATIVE across disarm (they survive
        # for post-mortems), so measure relative to the suite's baseline
        return (faults.counters().get("checkpoint.save",
                                      {}).get("injected", 0) - base)

    base = faults.counters().get("checkpoint.save", {}).get("injected", 0)
    try:
        faults.arm("checkpoint.save", every=1, match=uid, exc=_Crash)
        master_a.miner.submit(_req(uid, checkpoint="1",
                                   checkpoint_every_s="0"))
        _await(lambda: crashes() >= 1, "first holder crash")
        # each recovery must run on the NON-holding replica (the
        # holder's own incarnation tag reads as live to itself)
        for n, master in enumerate((master_b, master_a, master_b),
                                   start=1):
            t[0] += 10.0  # the dead holder's lease expires
            report = recover_orphans(master)
            assert report["resumed"] == [uid], f"adoption {n}: {report}"
            assert json.loads(
                store.journal_get(uid))["adoptions"] == n
            _await(lambda n=n: crashes() >= n + 1,
                   f"holder crash after adoption {n}")
        # adoption budget (default max_adoptions=3) exhausted: the next
        # recovery settles POISON instead of adopting a 4th time
        t[0] += 10.0
        report = recover_orphans(master_a)
        assert report["failed"] == [uid] and report["resumed"] == []
        assert store.status(uid) == "failure"
        assert (store.get(f"fsm:error:{uid}") or "").startswith("POISON:")
        assert store.journal_get(uid) is None  # settled, not pending
        rec = MG.poisoned(store, uid)
        assert rec is not None and rec["adoptions"] == 3
        # resubmission is REFUSED with the 409 conflict mapping
        resp = master_b.handle(_req(uid, checkpoint="1"))
        assert resp.data.get("http_status") == "409"
        assert "quarantine" in resp.data.get("error", "")
        snap = obs.REGISTRY.snapshot()["fsm_quarantine_jobs_total"]
        assert snap.get("outcome=poisoned", 0) >= 1
        assert snap.get("outcome=refused", 0) >= 1
        # operator release: the record clears, the fault is gone (the
        # poison dataset was "fixed"), and the resubmit completes clean
        assert MG.quarantine_release(store, uid) is True
        faults.disarm()
        master_b.miner.submit(_req(uid, checkpoint="1"))
        _await(lambda: store.status(uid) in ("finished", "failure"),
               "released job terminal")
        assert store.status(uid) == "finished"
    finally:
        faults.disarm()
        master_b.shutdown()
        master_a.shutdown()
