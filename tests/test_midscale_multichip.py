"""Mid-scale multichip parity (RUN_SLOW): non-toy widths on the 8-mesh.

The CI-sized mesh tests (test_spade_tpu/test_spade_queue/test_multihost)
run hundreds of sequences and <1k candidates, so shard-degenerate edge
cases — empty shards after padding, psum at real frontier widths,
mesh-scaled caps — are only ever exercised at toy width.  This module
mines a BMS-WebView-1-shaped DB (~59.6k sequences, 8 virtual CPU
devices, tens of thousands of candidates) through every sharded engine
and requires byte-identical parity with the CPU oracle.

Minutes-long (CPU mesh + full-size oracle): gated behind RUN_SLOW=1,
same convention as tests/test_tsr.py's full-scale run.

The queue and fused engines additionally carry the ``veryslow`` marker:
their whole-mine ``lax.while_loop`` programs run INTERPRETED on the
virtual CPU mesh, so the wall is dominated by compile + interpretation,
not by the parity check the test exists for — measured 169.9 s (queue)
vs 4.36 s (classic) for the same DB and candidate width (SLOWTESTS.json,
round 5), which made the RUN_SLOW suite ~43 min of mostly queue/fused
compile.  On real TPU hardware the same engines are the FASTEST route
(BENCH_r05: queue engine, 0.43 s steady), so the cost is an artifact of
the emulation substrate, not the engines.  Keep them in RUN_SLOW
evidence runs (slowtests.py); deselect with ``-m 'not veryslow'`` when
iterating locally.
"""

import json
import os

import pytest

pytestmark = pytest.mark.skipif(
    not os.environ.get("RUN_SLOW"),
    reason="minutes-long mid-scale mesh run; set RUN_SLOW=1")


def _record(test: str, **kv) -> None:
    """Append measured evidence (candidate counts etc.) for the
    SLOWTESTS.json harness (slowtests.py); no-op outside it."""
    path = os.environ.get("SLOWTESTS_STATS")
    if path:
        with open(path, "a") as fh:
            fh.write(json.dumps({"test": test, **kv}) + "\n")


@pytest.fixture(scope="module")
def midscale():
    import jax

    from spark_fsm_tpu.data.synth import bms_webview1_like
    from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical
    from spark_fsm_tpu.models.oracle import mine_spade
    from spark_fsm_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(len(jax.devices()))
    assert mesh.devices.size == 8
    # fast=True: the vectorized generator (the pure-Python one takes
    # tens of minutes at this size on a weak box — data/synth.py note);
    # parity is vs the oracle on the SAME db, so which generator drew it
    # is irrelevant.  MIDSCALE_SCALE shrinks the SEQUENCE axis for weak
    # evidence boxes (slowtests.py sets 0.35 on a 1-core host, where the
    # fused/queue engines' dense per-wave pair matrices are CPU-bound);
    # the candidate WIDTH — the thing this module exists to exercise —
    # barely moves with it (measured: 30.7k candidates at scale 1.0,
    # 37.6k at 0.35; the >= 10k assertions below still bind).
    scale = float(os.environ.get("MIDSCALE_SCALE", "1.0"))
    db = bms_webview1_like(scale=scale, fast=True)
    minsup = abs_minsup(0.002, len(db))  # ~0.2%: tens of thousands of
    # candidates — the non-toy width this module exists to exercise
    vdb = build_vertical(db, min_item_support=minsup)
    want = mine_spade(db, minsup)
    return mesh, db, vdb, minsup, want


def test_classic_engine_midscale_mesh(midscale):
    from spark_fsm_tpu.models.spade_tpu import SpadeTPU
    from spark_fsm_tpu.utils.canonical import diff_patterns, patterns_text

    mesh, db, vdb, minsup, want = midscale
    eng = SpadeTPU(vdb, minsup, mesh=mesh)
    got = eng.mine()
    assert patterns_text(got) == patterns_text(want), \
        diff_patterns(want, got)
    # the point of mid-scale: candidate counts far beyond the CI fixtures
    assert eng.stats["candidates"] >= 10_000, eng.stats
    _record("test_classic_engine_midscale_mesh", sequences=len(db),
            devices=mesh.devices.size, candidates=eng.stats["candidates"],
            patterns=len(got))


@pytest.mark.veryslow
def test_queue_engine_midscale_mesh(midscale):
    from spark_fsm_tpu.models.spade_queue import QueueSpadeTPU
    from spark_fsm_tpu.utils.canonical import diff_patterns, patterns_text

    mesh, db, vdb, minsup, want = midscale
    eng = QueueSpadeTPU(vdb, minsup, mesh=mesh)
    got = eng.mine()
    assert got is not None, f"queue caps overflowed mid-scale: {eng.stats}"
    assert patterns_text(got) == patterns_text(want), \
        diff_patterns(want, got)
    assert eng.stats["candidates"] >= 10_000, eng.stats
    _record("test_queue_engine_midscale_mesh", sequences=len(db),
            devices=mesh.devices.size, candidates=eng.stats["candidates"],
            waves=eng.stats["waves"], patterns=len(got))


@pytest.mark.veryslow
def test_fused_engine_midscale_mesh(midscale):
    from spark_fsm_tpu.models.spade_fused import FusedCaps, FusedSpadeTPU
    from spark_fsm_tpu.utils.canonical import diff_patterns, patterns_text

    mesh, db, vdb, minsup, want = midscale
    eng = FusedSpadeTPU(vdb, minsup, mesh=mesh,
                        caps=FusedCaps.for_mesh(mesh))
    got = eng.mine()
    assert got is not None, f"fused caps overflowed mid-scale: {eng.stats}"
    assert patterns_text(got) == patterns_text(want), \
        diff_patterns(want, got)
    assert eng.stats["candidates"] >= 10_000, eng.stats


def test_constrained_engine_midscale_mesh(midscale):
    from spark_fsm_tpu.models.oracle import mine_cspade
    from spark_fsm_tpu.models.spade_constrained import mine_cspade_tpu
    from spark_fsm_tpu.utils.canonical import diff_patterns, patterns_text

    mesh, db, vdb, minsup, want = midscale
    stats: dict = {}
    got = mine_cspade_tpu(db, minsup, maxgap=2, maxwindow=5, mesh=mesh,
                          stats_out=stats)
    cwant = mine_cspade(db, minsup, maxgap=2, maxwindow=5)
    assert patterns_text(got) == patterns_text(cwant), \
        diff_patterns(cwant, got)


def test_tsr_engine_midscale_mesh(midscale):
    from spark_fsm_tpu.models.tsr import mine_tsr_cpu, mine_tsr_tpu
    from spark_fsm_tpu.utils.canonical import rules_text

    mesh, db, vdb, minsup, want = midscale
    stats: dict = {}
    got = mine_tsr_tpu(db, 50, 0.5, max_side=2, mesh=mesh, stats_out=stats)
    cwant = mine_tsr_cpu(db, 50, 0.5, max_side=2)
    assert rules_text(got) == rules_text(cwant)
    assert stats["evaluated"] >= 1_000, stats
