"""Incremental sliding-window miner (streaming/incremental.py).

The binding contract is the determinism clause of streaming/window.py:
after EVERY push the pattern set must be byte-identical to a fresh mine
of exactly the window's sequences — incrementality changes WHEN counting
happens (arriving batch only + border repair), never WHAT is mined.
These tests drive pushes through eviction, minsup drift, border
crossings in both directions, and late-appearing items, checking parity
against the CPU oracle after each push.
"""

import numpy as np
import pytest

from spark_fsm_tpu.data.spmf import parse_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.streaming.incremental import IncrementalWindowMiner
from spark_fsm_tpu.streaming.window import WindowMiner
from spark_fsm_tpu.utils.canonical import patterns_text


def _assert_parity(wm, extra=""):
    seqs = wm.window.sequences()
    want = mine_spade(seqs, wm.minsup_abs())
    assert patterns_text(wm.patterns) == patterns_text(want), \
        f"push {wm.stats['pushes']} diverged {extra}"


def _batches(seed, n_batches, per_batch, n_items=12, mean_itemsets=3.0,
             mean_itemset_size=1.5):
    rng = np.random.default_rng(seed)
    out = []
    for k in range(n_batches):
        out.append(synthetic_db(
            seed=int(rng.integers(1 << 30)), n_sequences=per_batch,
            n_items=n_items, mean_itemsets=mean_itemsets,
            mean_itemset_size=mean_itemset_size))
    return out


def test_parity_every_push_with_eviction():
    wm = IncrementalWindowMiner(0.2, max_batches=3)
    for batch in _batches(7, 7, 60):
        wm.push(batch)
        _assert_parity(wm)
    # eviction happened (7 pushes, keep 3)
    assert wm.window.evicted_batches == 4
    assert wm.stats["route"] == "incremental"


def test_steady_state_repairs_nothing():
    # identical batch distribution + absolute minsup: after warmup, the
    # border should not cross and pushes should not re-enumerate
    batches = _batches(11, 6, 80, n_items=8, mean_itemsets=2.5)
    wm = IncrementalWindowMiner(30, max_batches=3)  # absolute minsup
    repaired = []
    for batch in batches:
        before = wm.stats["repaired_nodes"]
        wm.push(batch)
        _assert_parity(wm)
        repaired.append(wm.stats["repaired_nodes"] - before)
    # the first pushes build the tree; later pushes should mostly ride
    # the sweep (this is the entire point of the incremental path)
    assert repaired[0] > 0
    assert sum(repaired[3:]) < sum(repaired[:3])


def test_minsup_drift_crosses_borders():
    # relative minsup + growing window: the absolute threshold rises
    # every push, pushing patterns out of F (downward crossings)
    wm = IncrementalWindowMiner(0.25, max_batches=None, max_sequences=None)
    for batch in _batches(13, 5, 50, n_items=10):
        wm.push(batch)
        _assert_parity(wm)


def test_late_appearing_item_becomes_frequent():
    # an item absent from early batches must enter F (and its subtree be
    # built by repair) when later batches make it frequent
    a = parse_spmf("1 -1 2 -2\n1 -2\n2 -1 1 -2\n")
    b = parse_spmf("9 -1 1 -2\n9 -2\n9 -1 9 -2\n")
    c = parse_spmf("9 -1 1 -2\n9 -1 2 -2\n9 -2\n")
    wm = IncrementalWindowMiner(2, max_batches=None)
    for batch in (a, b, c):
        wm.push(list(batch))
        _assert_parity(wm)
    assert any(p == ((9,),) for p, _ in wm.patterns)


def test_item_falls_out_and_returns():
    hot = parse_spmf("5 -1 6 -2\n5 -2\n5 -1 6 -2\n5 -2\n")
    cold = parse_spmf("1 -2\n2 -2\n1 -1 2 -2\n3 -2\n")
    wm = IncrementalWindowMiner(3, max_batches=2)
    for batch in (hot, cold, cold, hot, hot):
        wm.push(list(batch))
        _assert_parity(wm)


def test_multi_itemset_patterns_and_iext():
    # itemsets wider than one item exercise the i-extension candidate
    # rules through sweep AND repair
    for seed in (3, 4):
        wm = IncrementalWindowMiner(0.3, max_batches=2)
        for batch in _batches(seed, 4, 50, n_items=8,
                              mean_itemset_size=2.5):
            wm.push(batch)
            _assert_parity(wm)


def test_multiword_batches():
    # > 32 itemsets/sequence -> n_words > 1 in the batch stores.
    # min_support=0.85, NOT 0.5: 40-itemset sequences make the frequent
    # set explode combinatorially with support (0.5 tracked millions of
    # border nodes — 430 s of host tree bookkeeping on a 1-core box,
    # dominating the whole tier-1 wall).  The multiword contract —
    # 2-word batch stores, exact per-push parity — is identical at the
    # higher support with thousands of patterns instead of hundreds of
    # thousands.
    wm = IncrementalWindowMiner(0.85, max_batches=2)
    for batch in _batches(8, 3, 40, n_items=6, mean_itemsets=40.0,
                          mean_itemset_size=1.1):
        wm.push(batch)
        _assert_parity(wm)


def test_restored_window_is_swept_in_full():
    # the service restart path refills the window WITHOUT miner.push;
    # the next real push must sweep every unseen batch and converge
    batches = _batches(21, 3, 50)
    wm = IncrementalWindowMiner(0.2, max_batches=4)
    for b in batches[:2]:
        wm.window.push(b)  # refill, bypassing the miner
    wm.push(batches[2])
    _assert_parity(wm)
    assert wm.stats["swept_batches"] == 3


def test_matches_remine_miner_exactly():
    # same stream through the re-mine WindowMiner and the incremental
    # one: identical pattern sets at every push
    batches = _batches(17, 5, 60, n_items=10)
    inc = IncrementalWindowMiner(0.25, max_batches=3)
    rem = WindowMiner(0.25, max_batches=3)
    for batch in batches:
        got = inc.push(list(batch))
        want = rem.push(list(batch))
        assert patterns_text(got) == patterns_text(want)


def test_single_sequence_batches_and_empty_f1():
    wm = IncrementalWindowMiner(5, max_batches=2)
    wm.push(parse_spmf("1 -2\n"))
    assert wm.patterns == []  # nothing reaches minsup 5
    _assert_parity(wm)
    wm.push(parse_spmf("1 -2\n1 -2\n1 -2\n1 -2\n1 -2\n"))
    _assert_parity(wm)
    assert wm.patterns == [(((1,),), 6)]


def test_duplicate_batch_object_pushed_twice():
    # pushing the SAME list object twice must count as two window
    # entries (the miner copies on push; identity-keyed state would
    # otherwise collapse them and undercount supports)
    batch = _batches(23, 1, 50)[0]
    wm = IncrementalWindowMiner(0.3, max_batches=3)
    wm.push(batch)
    _assert_parity(wm)
    wm.push(batch)  # same object again
    assert wm.window.n_sequences == 2 * len(batch)
    _assert_parity(wm)
    # and the counted content is frozen against caller mutation
    batch.clear()
    wm.push(_batches(24, 1, 50)[0])
    _assert_parity(wm)


def _mesh8():
    from spark_fsm_tpu.parallel.mesh import make_mesh
    return make_mesh(8)


def test_mesh_parity_every_push_with_eviction():
    # VERDICT r4 #4: streaming and partitioning compose — each batch
    # store's sequence axis shards over the 8-device mesh (shard_map
    # sweep/fold + psum partial supports) with unchanged per-push parity
    wm = IncrementalWindowMiner(0.2, max_batches=3, mesh=_mesh8())
    for batch in _batches(7, 6, 60):
        wm.push(batch)
        _assert_parity(wm)
    assert wm.window.evicted_batches == 3
    assert wm.stats["route"] == "incremental"


def test_mesh_multiword():
    # >32 itemsets/sequence -> n_words > 1 batch stores on the mesh.
    # min_support=0.9, NOT 0.5: these 40-itemset sequences make the
    # frequent set explode combinatorially with support (0.5 tracks
    # ~2M border nodes / 317k patterns — ~4 min of pure host tree
    # bookkeeping on a 1-core box, which single-handedly blew the
    # tier-1 time budget).  The multiword-mesh contract under test —
    # sharded 2-word batch stores, psum parity per push — is identical
    # at 0.9 (~2.3k patterns), and the pattern-volume stress case lives
    # in the single-word mesh test above.
    wm = IncrementalWindowMiner(0.9, max_batches=2, mesh=_mesh8())
    for batch in _batches(8, 3, 30, n_items=6, mean_itemsets=40.0,
                          mean_itemset_size=1.1):
        wm.push(batch)
        _assert_parity(wm)


@pytest.mark.skipif(not __import__("os").environ.get("RUN_SLOW"),
                    reason="interpret-mode Pallas under an 8-way CPU mesh "
                           "serializes 8 interpreted shards per collective; "
                           "on a 1-core box that overruns XLA's 40s "
                           "rendezvous deadline and ABORTS the process "
                           "(the real-TPU path is the classic engine's "
                           "chip-validated _pallas_supports_fn)")
def test_mesh_multiword_pallas_interpret_slow():
    # use_pallas=True routes the sweep through the shard_map'd Pallas
    # launcher (interpret mode on the virtual CPU mesh)
    wm = IncrementalWindowMiner(0.5, max_batches=2, mesh=_mesh8(),
                                use_pallas=True)
    for batch in _batches(8, 2, 20, n_items=6, mean_itemsets=40.0,
                          mean_itemset_size=1.1):
        wm.push(batch)
        _assert_parity(wm)


def test_streamer_routes_incremental_under_mesh():
    # the service no longer gates incrementality on get_mesh() is None:
    # a meshed deployment's stream pushes keep batch-scaled cost, and
    # the route label proves it
    from spark_fsm_tpu import config
    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.service.actors import Master
    from spark_fsm_tpu.service.model import ServiceRequest
    from spark_fsm_tpu.service.store import ResultStore

    old = config.get_config()
    config.set_config(config.parse_config({"engine": {"mesh_devices": 8}}))
    master = None
    try:
        assert config.get_mesh() is not None
        store = ResultStore()
        master = Master(store=store)
        batches = _batches(29, 2, 40)
        for b in batches:
            resp = master.handle(ServiceRequest("fsm", "stream:mtopic", {
                "sequences": format_spmf(b), "support": "0.25",
                "max_batches": "3", "algorithm": "SPADE_TPU"}))
            assert resp.status == "finished", resp.data
        import json as _json
        stats = _json.loads(store.get("fsm:stats:stream:mtopic"))
        assert stats["route"] == "incremental"
        # parity of the served result set against the oracle
        miner = master.streamer._topics["mtopic"]["miner"]
        _assert_parity(miner)
    finally:
        config.set_config(old)
        if master is not None:
            master.shutdown()
