"""Result-reuse tier (ISSUE 12, service/resultcache.py): fingerprints,
dominance-serve parity, coalescing fan-out, recovery, eviction.

The acceptance contract: every cached / coalesced / dominated response
must be byte-identical (over the canonical text form, utils/canonical)
to a cold mine at the request's own parameters; deliberately
NON-dominated requests must MISS and mine cold; a killed leader leaves
follower journal entries the boot recovery pass settles — never a
stuck uid.
"""

import json
import threading
import time

import pytest

from spark_fsm_tpu import config as cfgmod
from spark_fsm_tpu.data.spmf import fingerprint_db, format_spmf, parse_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.models.oracle import mine_cspade, mine_spade
from spark_fsm_tpu.models.tsr import mine_tsr_cpu
from spark_fsm_tpu.service import resultcache, sources
from spark_fsm_tpu.service.actors import Master, recover_orphans
from spark_fsm_tpu.service.model import (ServiceRequest,
                                         deserialize_patterns,
                                         deserialize_rules)
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.utils.canonical import patterns_text, rules_text


@pytest.fixture
def rescache_on():
    """Boot config with the result-reuse tier enabled; restored after."""
    old = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config({"rescache": {"enabled": True}}))
    yield cfgmod.get_config()
    cfgmod.set_config(old)


@pytest.fixture
def blocky_source():
    """A registered source that blocks dataset load on an Event — the
    deterministic way to hold a leader in flight while followers
    attach."""
    gate = threading.Event()

    def blocky(req, store):
        assert gate.wait(60), "blocky gate never opened"
        return parse_spmf(req.param("sequences"))

    sources.register("BLOCKY", blocky)
    yield gate
    gate.set()
    sources.SOURCES.pop("BLOCKY", None)


def _db(seed=5, n=60):
    return synthetic_db(seed=seed, n_sequences=n, n_items=9,
                        mean_itemsets=3.0, mean_itemset_size=1.2)


def _submit(master, uid, text, **params):
    d = {"algorithm": "TSR_TPU", "source": "INLINE", "sequences": text,
         "k": "8", "minconf": "0.4", "max_side": "2", "uid": uid}
    d.update({k: str(v) for k, v in params.items()})
    resp = master.handle(ServiceRequest("fsm", "train", d))
    assert resp.status != "failure", resp.data
    return resp


def _wait(store, uid, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = store.status(uid)
        if st in ("finished", "failure"):
            return st
        time.sleep(0.01)
    raise TimeoutError(f"job {uid} reached no terminal status")


def _stats(store, uid):
    return json.loads(store.get(f"fsm:stats:{uid}") or "{}")


# ------------------------------------------------------------- fingerprints


def test_fingerprint_canonical_across_spellings():
    # itemsets dedup + sort in the parser, so spelling variants of the
    # same content converge on one fingerprint
    a = parse_spmf("1 3 -1 2 -1 2 4 -2\n5 -1 6 -2\n")
    b = parse_spmf("3 1 3 -1 2 -1 4 2 -2\n5 -1 6 -1 -2\n")
    assert fingerprint_db(a) == fingerprint_db(b)
    c = parse_spmf("1 3 -1 2 -1 2 4 -2\n5 -1 7 -2\n")
    assert fingerprint_db(a) != fingerprint_db(c)
    # itemset boundaries matter: <{1,2}> is not <{1},{2}>
    assert fingerprint_db(parse_spmf("1 2 -2\n")) != \
        fingerprint_db(parse_spmf("1 -1 2 -2\n"))


def test_disabled_by_default_no_instance():
    master = Master(store=ResultStore())
    try:
        assert master.miner._rescache is None
    finally:
        master.shutdown()


# ------------------------------------------------------- serving + parity


def test_exact_hit_and_dominated_tsr_parity(rescache_on):
    db = _db(seed=31)
    text = format_spmf(db)
    store = ResultStore()
    master = Master(store=store)
    try:
        _submit(master, "cold", text)
        assert _wait(store, "cold") == "finished"
        assert "served_from_cache" not in _stats(store, "cold")

        # identical request: exact hit, byte-identical canonical text
        _submit(master, "hit", text)
        assert _wait(store, "hit") == "finished"
        assert _stats(store, "hit")["served_from_cache"] == "exact"
        assert rules_text(deserialize_rules(store.rules("hit"))) == \
            rules_text(deserialize_rules(store.rules("cold")))

        # dominated: smaller k — must equal a cold mine at k=4
        _submit(master, "domk", text, k=4)
        assert _wait(store, "domk") == "finished"
        assert _stats(store, "domk")["served_from_cache"] == "dominated"
        oracle = rules_text(mine_tsr_cpu(db, 4, 0.4, max_side=2))
        assert rules_text(deserialize_rules(store.rules("domk"))) == oracle

        # stricter max_side at FULL k: the conservative predicate may
        # refuse (the side-filtered top-k could need support-pruned
        # rules) — served or cold, the answer must match the oracle
        _submit(master, "doms", text, k=8, max_side=1)
        assert _wait(store, "doms") == "finished"
        assert _stats(store, "doms").get("served_from_cache") in (
            None, "dominated")
        oracle = rules_text(mine_tsr_cpu(db, 8, 0.4, max_side=1))
        assert rules_text(deserialize_rules(store.rules("doms"))) == oracle

        # NON-dominated: larger k must MISS (mine cold) and still agree
        # with the oracle at k=12
        _submit(master, "bigk", text, k=12)
        assert _wait(store, "bigk") == "finished"
        assert "served_from_cache" not in _stats(store, "bigk")
        oracle = rules_text(mine_tsr_cpu(db, 12, 0.4, max_side=2))
        assert rules_text(deserialize_rules(store.rules("bigk"))) == oracle
    finally:
        master.shutdown()


def test_dominated_spade_minsup_parity_and_misses(rescache_on):
    db = _db(seed=37, n=80)
    text = format_spmf(db)
    store = ResultStore()
    master = Master(store=store)
    try:
        _submit(master, "cold", text, algorithm="SPADE_TPU", support=4,
                k="", minconf="", max_side="")
        assert _wait(store, "cold") == "finished"

        # higher minsup: filter of the cached set == cold mine
        _submit(master, "dom", text, algorithm="SPADE_TPU", support=8,
                k="", minconf="", max_side="")
        assert _wait(store, "dom") == "finished"
        assert _stats(store, "dom")["served_from_cache"] == "dominated"
        oracle = patterns_text(mine_spade(db, 8))
        assert patterns_text(
            deserialize_patterns(store.patterns("dom"))) == oracle

        # relative support resolving to a dominated absolute count
        _submit(master, "domrel", text, algorithm="SPADE_TPU",
                support=0.1, k="", minconf="", max_side="")
        assert _wait(store, "domrel") == "finished"
        assert _stats(store, "domrel")["served_from_cache"] == "dominated"
        oracle = patterns_text(mine_spade(db, 8))  # ceil(0.1*80) = 8
        assert patterns_text(
            deserialize_patterns(store.patterns("domrel"))) == oracle

        # NON-dominated: LOWER minsup must miss (cached run pruned)
        _submit(master, "low", text, algorithm="SPADE_TPU", support=2,
                k="", minconf="", max_side="")
        assert _wait(store, "low") == "finished"
        assert "served_from_cache" not in _stats(store, "low")
        assert patterns_text(
            deserialize_patterns(store.patterns("low"))) == \
            patterns_text(mine_spade(db, 2))

        # NON-dominated: stricter maxgap must miss — supports change
        # under constraints, filtering cannot reproduce them
        _submit(master, "gap", text, algorithm="SPADE_TPU", support=4,
                maxgap=1, k="", minconf="", max_side="")
        assert _wait(store, "gap") == "finished"
        assert "served_from_cache" not in _stats(store, "gap")
        assert patterns_text(
            deserialize_patterns(store.patterns("gap"))) == \
            patterns_text(mine_cspade(db, 4, maxgap=1, maxwindow=None))
    finally:
        master.shutdown()


def test_rules_dominance_threshold_guard_unit():
    """The TSR predicate's conservative core: a higher minconf is served
    only when the re-derived tie-inclusive threshold stays >= the
    cached run's own s_k — otherwise support-pruned rules could enter
    the weaker top-k and the serve must refuse."""
    ent = {
        "algo": "TSR_TPU", "kind": "rules",
        "params": {"algo": "TSR_TPU", "kind": "rules", "k": 2,
                   "minconf": 0.4, "max_side": None},
        "n_sequences": 20, "uid": "u",
        # A(sup 10, conf .5), B(sup 9, conf .5) — cached top-2 at .4;
        # an unseen rule C(sup 8, conf .9) was support-pruned (s_k0=9)
        "payload": json.dumps([
            {"antecedent": [1], "consequent": [2], "support": 10,
             "antecedent_support": 20},
            {"antecedent": [3], "consequent": [4], "support": 9,
             "antecedent_support": 18},
        ]),
    }

    def want(k, minconf, max_side=None):
        return {"algo": "TSR_TPU", "kind": "rules", "k": k,
                "minconf": minconf, "max_side": max_side}

    # same k, higher minconf: filtered set is empty at .8 — but the
    # cached run was NOT exhaustive (len == k), so the full qualifying
    # set at .8 was never materialized: REFUSE
    assert resultcache._servable(ent, want(2, 0.8)) is None
    # k=1 at the same minconf: s_k1 = 10 >= s_k0 = 9 — servable
    payload, mode, n = resultcache._servable(ent, want(1, 0.4))
    assert mode == "dominated" and n == 1
    assert deserialize_rules(payload)[0][2] == 10
    # k=1 at minconf .5: both rules qualify, s_k1 = 10 >= 9 — servable
    payload, mode, n = resultcache._servable(ent, want(1, 0.5))
    assert mode == "dominated" and n == 1
    # exact match serves verbatim
    payload, mode, n = resultcache._servable(ent, want(2, 0.4))
    assert mode == "exact" and payload == ent["payload"]
    # larger k always misses
    assert resultcache._servable(ent, want(3, 0.4)) is None
    # lower minconf always misses
    assert resultcache._servable(ent, want(2, 0.3)) is None

    # EXHAUSTIVE cached run (found < k rules): any smaller-or-equal k
    # and higher minconf is servable — nothing was support-pruned
    ent_ex = dict(ent)
    ent_ex["params"] = dict(ent["params"], k=5)
    payload, mode, n = resultcache._servable(ent_ex, want(5, 0.5))
    assert mode == "dominated" and n == 2
    payload, mode, n = resultcache._servable(ent_ex, want(2, 0.8))
    assert mode == "dominated" and n == 0

    # stricter max_side: servable when the side-filtered set still
    # clears the cached threshold (both cached rules have singleton
    # sides, so the filter drops nothing and s_k1 = s_k0)
    payload, mode, n = resultcache._servable(ent, want(2, 0.4,
                                                       max_side=1))
    assert mode == "dominated" and n == 2
    # looser side bound than cached always misses (unexplored rules)
    ent_side = dict(ent)
    ent_side["params"] = dict(ent["params"], max_side=1)
    assert resultcache._servable(ent_side, want(1, 0.4)) is None
    assert resultcache._servable(ent_side, want(1, 0.4,
                                                max_side=2)) is None


# ------------------------------------------------------------- coalescing


def test_coalescing_fanout(rescache_on, blocky_source):
    db = _db(seed=41)
    text = format_spmf(db)
    store = ResultStore()
    master = Master(store=store, miner_workers=1)
    try:
        # the blocker pins the single worker inside its dataset load,
        # so the leader stays QUEUED while followers attach
        _submit(master, "blk", format_spmf(_db(seed=42)),
                source="BLOCKY")
        _submit(master, "L", text)
        _submit(master, "F1", text)
        _submit(master, "F2", text)
        st = master.miner._rescache.stats()
        assert st["inflight_followers"] == 2, st
        # each follower is journaled while in flight (crash recovery)
        for uid in ("F1", "F2"):
            entry = json.loads(store.journal_get(uid))
            assert entry["coalesced_into"] == "L"
        blocky_source.set()
        for uid in ("blk", "L", "F1", "F2"):
            assert _wait(store, uid) == "finished", uid
        # fan-out delivery: byte-identical payloads, own stats/journal
        assert store.rules("F1") == store.rules("L")
        assert store.rules("F2") == store.rules("L")
        for uid in ("F1", "F2"):
            assert _stats(store, uid)["coalesced_into"] == "L"
            assert store.journal_get(uid) is None
    finally:
        master.shutdown()


def test_leader_cancel_redispatches_followers(rescache_on, blocky_source):
    db = _db(seed=43)
    text = format_spmf(db)
    store = ResultStore()
    master = Master(store=store, miner_workers=1)
    try:
        _submit(master, "blk", format_spmf(_db(seed=44)),
                source="BLOCKY")
        _submit(master, "L", text)
        _submit(master, "F", text)
        assert master.miner._rescache.stats()["inflight_followers"] == 1
        # cancel the LEADER while queued: its client's abort must not
        # take the follower down — F re-dispatches as a cold mine
        assert master.cancel("L") == "queued"
        blocky_source.set()
        assert _wait(store, "blk") == "finished"
        assert _wait(store, "L") == "failure"
        assert "CANCELLED" in store.get("fsm:error:L")
        assert _wait(store, "F") == "finished"
        oracle = rules_text(mine_tsr_cpu(db, 8, 0.4, max_side=2))
        assert rules_text(deserialize_rules(store.rules("F"))) == oracle
        assert store.journal_get("F") is None
    finally:
        master.shutdown()


def test_cancelled_follower_not_revived_by_leader_teardown(
        rescache_on, blocky_source):
    """A follower whose OWN cancel was acknowledged must settle as
    CANCELLED when its leader aborts — the cold re-dispatch path must
    not resurrect it with a fresh control entry."""
    db = _db(seed=47)
    text = format_spmf(db)
    store = ResultStore()
    master = Master(store=store, miner_workers=1)
    try:
        _submit(master, "blk", format_spmf(_db(seed=48)),
                source="BLOCKY")
        _submit(master, "L", text)
        _submit(master, "F", text)
        assert master.cancel("F") == "queued"  # follower's own cancel
        assert master.cancel("L") == "queued"  # then the leader aborts
        blocky_source.set()
        assert _wait(store, "blk") == "finished"
        assert _wait(store, "L") == "failure"
        assert _wait(store, "F") == "failure"
        assert "CANCELLED" in store.get("fsm:error:F")
        assert store.journal_get("F") is None
    finally:
        master.shutdown()


def test_follower_recovery_after_kill():
    """kill -9 of the process mid-coalesce: the follower's journal
    entry (written at attach) is all recovery needs — the boot pass
    settles it durably, never a stuck uid."""
    store = ResultStore()
    req = {"algorithm": "TSR_TPU", "source": "INLINE",
           "sequences": "1 -1 2 -2\n", "k": "4", "minconf": "0.4"}
    for uid, extra in (("dead-L", {}),
                       ("dead-F", {"coalesced_into": "dead-L"})):
        store.journal_set(uid, json.dumps({
            "uid": uid, "incarnation": "dead-incarnation",
            "replica": None, "ts": time.time(), "checkpoint": False,
            "priority": "normal", "request": dict(req, uid=uid),
            **extra}))
        store.add_status(uid, "started")
    master = Master(store=store)
    try:
        report = recover_orphans(master)
        assert set(report["failed"]) == {"dead-L", "dead-F"}
        for uid in ("dead-L", "dead-F"):
            assert store.status(uid) == "failure"
            assert "interrupted by restart" in store.get(f"fsm:error:{uid}")
            assert store.journal_get(uid) is None
    finally:
        master.shutdown()


# ------------------------------------------------------- knobs + eviction


def test_lru_eviction_by_byte_budget():
    old = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config(
        {"rescache": {"enabled": True, "max_bytes": 1}}))
    try:
        store = ResultStore()
        master = Master(store=store)
        try:
            before = resultcache._EVICTIONS.total()
            _submit(master, "a", format_spmf(_db(seed=51, n=30)), k=4)
            assert _wait(store, "a") == "finished"
            # a 1-byte budget evicts every entry it stores
            assert store.keys("fsm:rescache:") == []
            assert resultcache._EVICTIONS.total() > before
            # and the SAME request now misses — mines cold, still green
            _submit(master, "b", format_spmf(_db(seed=51, n=30)), k=4)
            assert _wait(store, "b") == "finished"
            assert "served_from_cache" not in _stats(store, "b")
        finally:
            master.shutdown()
    finally:
        cfgmod.set_config(old)


def test_dominance_and_coalesce_flags_off():
    old = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config(
        {"rescache": {"enabled": True, "dominance": False,
                      "coalesce": False}}))
    try:
        store = ResultStore()
        master = Master(store=store)
        try:
            text = format_spmf(_db(seed=53, n=30))
            _submit(master, "a", text, k=4)
            assert _wait(store, "a") == "finished"
            _submit(master, "b", text, k=4)
            assert _wait(store, "b") == "finished"
            # both layers off: identical request mines cold
            assert "served_from_cache" not in _stats(store, "b")
            assert store.rules("a") == store.rules("b")
        finally:
            master.shutdown()
    finally:
        cfgmod.set_config(old)


def test_cluster_mode_serve_and_coalesce(rescache_on, blocky_source):
    """Followers and serves hold their own fenced leases in cluster
    mode; everything still settles and the journal namespace drains."""
    old = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config({
        "rescache": {"enabled": True},
        "cluster": {"enabled": True, "replica_id": "rc-test",
                    "lease_ttl_s": 30.0}}))
    try:
        store = ResultStore()
        master = Master(store=store, miner_workers=1)
        try:
            text = format_spmf(_db(seed=61, n=40))
            _submit(master, "blk", format_spmf(_db(seed=62, n=40)),
                    source="BLOCKY")
            _submit(master, "L", text)
            _submit(master, "F", text)
            blocky_source.set()
            for uid in ("blk", "L", "F"):
                assert _wait(store, uid) == "finished", uid
            assert _stats(store, "F")["coalesced_into"] == "L"
            _submit(master, "hit", text)
            assert _wait(store, "hit") == "finished"
            assert _stats(store, "hit")["served_from_cache"] == "exact"
            assert store.keys("fsm:journal:") == []
            assert master.miner._lease.held_uids() == []
        finally:
            master.shutdown()
    finally:
        cfgmod.set_config(old)


# --------------------------------------- FILE fingerprints (ISSUE 13, 2b)


def _submit_file(master, uid, path, **params):
    d = {"algorithm": "TSR_TPU", "source": "FILE", "path": str(path),
         "k": "8", "minconf": "0.4", "max_side": "2", "uid": uid}
    d.update({k: str(v) for k, v in params.items()})
    resp = master.handle(ServiceRequest("fsm", "train", d))
    assert resp.status != "failure", resp.data
    return resp


def test_file_validator_unlocks_admission_fp_and_dominance(
        rescache_on, tmp_path):
    """An immutable FILE artifact fp-resolves at admission after its
    first load (validator-gated learned mapping), so later FILE
    requests exact-hit AND dominated-serve — the unlock ROADMAP 2b
    names (FILE used to coalesce only)."""
    from spark_fsm_tpu.data.spmf import file_validator

    db = _db(seed=70)
    path = tmp_path / "data.spmf"
    path.write_text(format_spmf(db))
    v1 = file_validator(str(path))
    assert v1 == file_validator(str(path))  # deterministic witness
    store = ResultStore()
    master = Master(store=store, miner_workers=1)
    try:
        _submit_file(master, "cold", path)
        assert _wait(store, "cold") == "finished"
        assert "served_from_cache" not in _stats(store, "cold")
        base = rules_text(deserialize_rules(store.rules("cold")))
        # exact hit: same path, untouched file
        _submit_file(master, "hit", path)
        assert _wait(store, "hit") == "finished"
        assert _stats(store, "hit")["served_from_cache"] == "exact"
        assert rules_text(deserialize_rules(store.rules("hit"))) == base
        # dominance serving now works for the FILE spelling too
        _submit_file(master, "dom", path, k=5)
        assert _wait(store, "dom") == "finished"
        assert _stats(store, "dom")["served_from_cache"] == "dominated"
        want = rules_text(mine_tsr_cpu(db, 5, 0.4, max_side=2))
        assert rules_text(
            deserialize_rules(store.rules("dom"))) == want
    finally:
        master.shutdown()


def test_file_validator_mismatch_falls_back_to_cold_mine(
        rescache_on, tmp_path):
    """The pinned fallback: a path whose content changed under the
    learned mapping must NOT serve the stale entry — the validator
    mismatch routes it down the mutable (cold) path, and the fresh
    load re-learns the mapping for the new bytes."""
    db1, db2 = _db(seed=71), _db(seed=72, n=50)
    path = tmp_path / "mut.spmf"
    path.write_text(format_spmf(db1))
    store = ResultStore()
    master = Master(store=store, miner_workers=1)
    try:
        _submit_file(master, "one", path)
        assert _wait(store, "one") == "finished"
        _submit_file(master, "one-hit", path)
        assert _wait(store, "one-hit") == "finished"
        assert _stats(store, "one-hit")["served_from_cache"] == "exact"
        # rewrite the file IN PLACE: same path, different content
        path.write_text(format_spmf(db2))
        _submit_file(master, "two", path)
        assert _wait(store, "two") == "finished"
        # not served from the stale entry — a cold mine of the NEW data
        assert "served_from_cache" not in _stats(store, "two")
        want2 = rules_text(mine_tsr_cpu(db2, 8, 0.4, max_side=2))
        assert rules_text(
            deserialize_rules(store.rules("two"))) == want2
        # the mapping re-learned: the new content now exact-hits
        _submit_file(master, "two-hit", path)
        assert _wait(store, "two-hit") == "finished"
        assert _stats(store, "two-hit")["served_from_cache"] == "exact"
    finally:
        master.shutdown()


# ------------------------------------ cross-replica coalesce hint (2c)


def test_peer_inflight_hint_sheds_with_steal_path_retry(
        rescache_on, monkeypatch):
    """A local miss whose dataset fingerprint is in flight on a PEER
    sheds with 429 + a ~2-heartbeat Retry-After instead of admitting a
    duplicate cold mine; after the peer publishes its entry the retry
    exact-hits.  Hint only — nothing attaches across replicas."""
    import threading

    from spark_fsm_tpu.service.lease import LeaseManager
    from spark_fsm_tpu.utils import obs as obsmod

    store = ResultStore()
    mk = lambda rid: LeaseManager(store, replica_id=rid,
                                  lease_ttl_s=30.0, heartbeat_s=0)
    mgr_a, mgr_b = mk("rc-a"), mk("rc-b")
    master_a = Master(store=store, miner_workers=1, lease_mgr=mgr_a)
    master_b = Master(store=store, miner_workers=1, lease_mgr=mgr_b)
    gate = threading.Event()
    entered = threading.Event()
    real = sources.get_db

    def gated(req, store_):
        if req.uid == "L":
            entered.set()
            assert gate.wait(60)
        return real(req, store_)

    monkeypatch.setattr(sources, "get_db", gated)
    text = format_spmf(_db(seed=80, n=40))
    hints0 = obsmod.REGISTRY.snapshot()["fsm_rescache_peer_hints_total"]
    try:
        _submit(master_a, "L", text)
        assert entered.wait(60)
        mgr_a.publish_heartbeat()  # advertises L's in-flight fp
        assert master_a.miner.inflight_fps() != []
        # refresh B's peer cache past any earlier (pre-heartbeat) scan
        # a metrics collector may have cached — in production the cache
        # ages out within one heartbeat; tests don't wait
        assert [p["replica"] for p in mgr_b.peers()] == ["rc-a"]
        resp = master_b.handle(ServiceRequest("fsm", "train", {
            "algorithm": "TSR_TPU", "source": "INLINE",
            "sequences": text, "k": "8", "minconf": "0.4",
            "max_side": "2", "uid": "dup"}))
        assert resp.data.get("http_status") == "429", resp.data
        assert int(resp.data["retry_after_s"]) >= 1
        assert "peer replica" in resp.data["error"]
        # hint only: zero store trace of the shed uid
        assert store.status("dup") is None
        assert store.journal_get("dup") is None
        assert obsmod.REGISTRY.snapshot()[
            "fsm_rescache_peer_hints_total"] == hints0 + 1
        gate.set()
        assert _wait(store, "L") == "finished"
        # the client's retry hits the entry the peer published
        _submit(master_b, "dup", text)
        assert _wait(store, "dup") == "finished"
        assert _stats(store, "dup")["served_from_cache"] == "exact"
    finally:
        gate.set()
        master_b.shutdown()
        master_a.shutdown()


def test_crash_between_entry_and_sidecar_heals_on_next_boot(rescache_on):
    """kill -9 between the cache-entry write and its LRU-sidecar write
    (the entry is written FIRST by design): the next boot's scrubber
    verifies the orphan entry and re-derives its sidecar from the
    entry's own bytes — the entry then serves normally, zero duplicated
    results (ISSUE 18 satellite)."""
    from spark_fsm_tpu.service import integrity
    from spark_fsm_tpu.utils import envelope

    text = format_spmf(_db(seed=61))
    store = ResultStore()
    master = Master(store=store, miner_workers=1)
    try:
        _submit(master, "warm", text)
        assert _wait(store, "warm") == "finished"
    finally:
        master.shutdown()
    [ekey] = store.keys("fsm:rescache:")
    skey = resultcache.sidecar_key_for(ekey)
    assert store.peek(skey) is not None
    store.delete(skey)  # the crash residue: entry landed, sidecar not
    scr = integrity.Scrubber(store, scrub_every_s=0.0, batch=256)
    tally = scr.scrub()
    assert tally["repaired"] == 1 and tally["quarantined"] == 0
    ent_payload = envelope.unwrap(store.peek(ekey))[0]
    side = json.loads(envelope.unwrap(store.peek(skey))[0])
    assert side["digest"] == json.loads(ent_payload)["digest"]
    assert side["bytes"] == len(ent_payload)
    # the healed entry SERVES the same request — and serves the SAME
    # rules the warm mine produced, nothing duplicated or rebuilt
    master = Master(store=store, miner_workers=1)
    try:
        _submit(master, "served", text)
        assert _wait(store, "served") == "finished"
        assert _stats(store, "served")["served_from_cache"] == "exact"
        assert rules_text(deserialize_rules(store.rules("served"))) == \
            rules_text(deserialize_rules(store.rules("warm")))
    finally:
        master.shutdown()
