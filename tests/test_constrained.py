"""maxgap/maxwindow constrained mining: ops, oracle, engine parity."""

import numpy as np
import pytest

from spark_fsm_tpu.data.spmf import parse_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical
from spark_fsm_tpu.models.oracle import (
    brute_force_mine_constrained, contains_constrained, mine_cspade, mine_spade)
from spark_fsm_tpu.models.spade_constrained import (
    ConstrainedSpadeTPU, mine_cspade_tpu)
from spark_fsm_tpu.ops import maxstart_np as MS
from spark_fsm_tpu.utils.canonical import diff_patterns, patterns_text
from tests.test_oracle import ZAKI_DB, random_db


# ------------------------------------------------------------------- ops

def test_expand_bits():
    w = np.array([0b101, 0b1], dtype=np.uint32)
    got = MS.expand_bits(w)
    assert got.shape == (64,)
    assert got[0] and not got[1] and got[2] and got[32]
    assert got.sum() == 3


def test_root_state():
    w = np.array([0b1010], dtype=np.uint32)
    m = MS.root_state(w)
    assert m[1] == 1 and m[3] == 3 and m[0] == -1


def test_prev_max_unbounded():
    m = np.array([-1, 2, -1, 5, -1], dtype=np.int16)
    got = MS.prev_max(np.pad(m, (0, 27), constant_values=-1), None)
    assert got[0] == -1 and got[1] == -1 and got[2] == 2
    assert got[3] == 2 and got[4] == 5


def test_prev_max_gap():
    m = np.array([3, -1, -1, -1, 7], dtype=np.int16)
    padded = np.pad(m, (0, 27), constant_values=-1)
    g1 = MS.prev_max(padded, 1)
    assert g1[1] == 3 and g1[2] == -1 and g1[5] == 7
    g3 = MS.prev_max(padded, 3)
    assert g3[3] == 3 and g3[4] == -1  # pos 4 - gap 3 reaches pos 1..3 only


def test_support_window():
    # ends at 5 with start 2: span 3
    m = np.full((1, 32), -1, np.int16)
    m[0, 5] = 2
    assert MS.support(m, None) == 1
    assert MS.support(m, 3) == 1
    assert MS.support(m, 2) == 0


def test_jax_ops_match_numpy():
    import jax.numpy as jnp
    from spark_fsm_tpu.ops import maxstart_jax as MJ
    rng = np.random.default_rng(0)
    words = rng.integers(0, 2**32, size=(4, 6, 2), dtype=np.uint32)
    m = rng.integers(-1, 50, size=(4, 6, 64)).astype(np.int16)
    np.testing.assert_array_equal(np.asarray(MJ.expand_bits(jnp.asarray(words))),
                                  MS.expand_bits(words))
    for g in (None, 1, 3, 100):
        np.testing.assert_array_equal(np.asarray(MJ.prev_max(jnp.asarray(m), g)),
                                      MS.prev_max(m, g))
    for w in (None, 0, 5, 63):
        np.testing.assert_array_equal(np.asarray(MJ.support(jnp.asarray(m), w)),
                                      MS.support(m, w))
    np.testing.assert_array_equal(
        np.asarray(MJ.s_extend(jnp.asarray(m), jnp.asarray(words), 2)),
        MS.s_extend(m, words, 2))
    np.testing.assert_array_equal(
        np.asarray(MJ.i_extend(jnp.asarray(m), jnp.asarray(words))),
        MS.i_extend(m, words))


# ----------------------------------------------------------- containment

def test_contains_constrained():
    seq = ((1,), (2,), (3,), (1, 4))
    assert contains_constrained(seq, ((1,), (3,)))
    assert not contains_constrained(seq, ((1,), (3,)), maxgap=1)
    assert contains_constrained(seq, ((1,), (3,)), maxgap=2)
    assert contains_constrained(seq, ((2,), (3,), (4,)), maxgap=1, maxwindow=2)
    assert not contains_constrained(seq, ((1,), (4,)), maxwindow=2)
    assert contains_constrained(seq, ((1,), (4,)), maxwindow=3)
    # backtracking case: greedy first match of {1} at 0 fails the gap, the
    # occurrence at 3 cannot work either, but (2)->(1,4) needs the later 1
    assert contains_constrained(seq, ((2,), (1,)), maxgap=2)


# ------------------------------------------------------- oracle parity

CONFIGS = [(None, None), (1, None), (2, None), (None, 2), (2, 3), (1, 2)]


@pytest.mark.parametrize("maxgap,maxwindow", CONFIGS)
def test_cspade_oracle_vs_brute_force(maxgap, maxwindow):
    rng = np.random.default_rng(42)
    db = random_db(rng, n_seq=14, n_items=5, max_itemsets=5, max_set=2)
    a = mine_cspade(db, 3, maxgap=maxgap, maxwindow=maxwindow)
    b = brute_force_mine_constrained(db, 3, maxgap=maxgap, maxwindow=maxwindow,
                                     max_pattern_itemsets=6, max_itemset_size=4)
    assert patterns_text(a) == patterns_text(b), diff_patterns(a, b)


def test_cspade_unconstrained_equals_spade():
    a = mine_cspade(ZAKI_DB, 2)
    b = mine_spade(ZAKI_DB, 2)
    assert patterns_text(a) == patterns_text(b), diff_patterns(a, b)


# -------------------------------------------------------- engine parity

@pytest.mark.parametrize("maxgap,maxwindow", CONFIGS)
def test_engine_vs_oracle(maxgap, maxwindow):
    rng = np.random.default_rng(7)
    db = random_db(rng, n_seq=25, n_items=6, max_itemsets=6, max_set=2)
    a = mine_cspade(db, 3, maxgap=maxgap, maxwindow=maxwindow)
    b = mine_cspade_tpu(db, 3, maxgap=maxgap, maxwindow=maxwindow)
    assert patterns_text(a) == patterns_text(b), diff_patterns(a, b)


def test_engine_synthetic_gazelle_like():
    db = synthetic_db(seed=30, n_sequences=300, n_items=40, mean_itemsets=5.0,
                      mean_itemset_size=1.3)
    minsup = abs_minsup(0.03, len(db))
    a = mine_cspade(db, minsup, maxgap=2, maxwindow=5)
    b = mine_cspade_tpu(db, minsup, maxgap=2, maxwindow=5)
    assert patterns_text(a) == patterns_text(b), diff_patterns(a, b)


def test_engine_tiny_pool_recompute():
    db = synthetic_db(seed=31, n_sequences=150, n_items=20, mean_itemsets=5.0)
    minsup = abs_minsup(0.05, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    eng = ConstrainedSpadeTPU(vdb, minsup, maxgap=3, maxwindow=6,
                              pool_bytes=1, node_batch=8, chunk=32,
                              recompute_chunk=4)
    # pool_bytes=1 clamps to the floor budget: a pool small enough that
    # slot reclaim + recompute-on-miss must engage
    assert eng.pool_slots <= 32
    got = eng.mine()
    want = mine_cspade(db, minsup, maxgap=3, maxwindow=6)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


def test_engine_mesh_parity():
    from spark_fsm_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(8)
    db = synthetic_db(seed=32, n_sequences=210, n_items=15, mean_itemsets=4.5)
    minsup = abs_minsup(0.05, len(db))
    got = mine_cspade_tpu(db, minsup, maxgap=2, maxwindow=4, mesh=mesh)
    want = mine_cspade(db, minsup, maxgap=2, maxwindow=4)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


def test_engine_int16_path():
    # sequences longer than 127 positions force the int16 state dtype
    db = synthetic_db(seed=33, n_sequences=60, n_items=10, mean_itemsets=100.0,
                      max_itemsets=150)
    minsup = abs_minsup(0.5, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    import jax.numpy as jnp
    eng = ConstrainedSpadeTPU(vdb, minsup, maxgap=1, maxwindow=3,
                              max_pattern_itemsets=3)
    assert eng.dtype == jnp.int16
    got = eng.mine()
    want = mine_cspade(db, minsup, maxgap=1, maxwindow=3, max_pattern_itemsets=3)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


def test_engine_shape_buckets_parity_and_reuse():
    # shape_buckets pow2-buckets the sequence axis and the item-row count
    # (streaming windows re-mine with drifting geometry): parity must be
    # unaffected, and two windows in the same buckets must compile to the
    # SAME geometry (equal shape_key) while exact shapes would differ.
    db = synthetic_db(seed=17, n_sequences=150, n_items=20,
                      mean_itemsets=4.0, mean_itemset_size=1.3)
    minsup = abs_minsup(0.05, len(db))
    want = mine_cspade(db, minsup, maxgap=2, maxwindow=5)
    s1 = {}
    got = mine_cspade_tpu(db, minsup, maxgap=2, maxwindow=5,
                          shape_buckets=True, stats_out=s1)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)
    assert ":s256" in s1["shape_key"], s1["shape_key"]  # 150 -> 256

    db2 = db[:140]  # different exact size, same pow2 bucket
    s2 = {}
    mine_cspade_tpu(db2, abs_minsup(0.05, len(db2)), maxgap=2, maxwindow=5,
                    shape_buckets=True, stats_out=s2)
    assert s1["shape_key"] == s2["shape_key"]
    s3 = {}
    mine_cspade_tpu(db2, abs_minsup(0.05, len(db2)), maxgap=2, maxwindow=5,
                    stats_out=s3)  # unbucketed: exact geometry
    assert ":s140" in s3["shape_key"], s3["shape_key"]


def test_stream_task_buckets_constrained_path():
    # the service plugin boundary applies shape_buckets to CONSTRAINED
    # streaming pushes too (mirror of the unconstrained test in
    # test_streaming.py)
    from spark_fsm_tpu.service import plugins
    from spark_fsm_tpu.service.model import ServiceRequest

    db = synthetic_db(seed=18, n_sequences=50, n_items=12,
                      mean_itemsets=4.0)
    data = {"algorithm": "SPADE_TPU", "support": "0.2", "maxgap": "2"}
    st: dict = {}
    plug = plugins.get_plugin(ServiceRequest("fsm", "stream", data))
    plug.extract(ServiceRequest("fsm", "stream", data), db, stats=st)
    assert st["shape_key"].startswith("cspade:s128w"), st["shape_key"]
