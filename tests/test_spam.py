"""SPAM bitmap mining engine (ISSUE 15, models/spam_bitmap.py +
ops/spam_bitops.py).

The acceptance pins: byte-identical output to the CPU oracle on the
pinned miniatures (direct and planner-routed, including through the
partition layer on the 8-virtual-device CPU mesh), checkpoint/resume
through the EXISTING frontier format — in both directions across
engines — and the tail-word-masked popcount support counting."""

import numpy as np
import pytest

from spark_fsm_tpu.data.synth import kosarak_like, synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical
from spark_fsm_tpu.models.oracle import brute_force_mine, mine_spade
from spark_fsm_tpu.models.spam_bitmap import (
    SpamBitmapTPU, mine_spam_cpu, mine_spam_tpu, spam_geometry)
from spark_fsm_tpu.utils.canonical import patterns_text


def _db_small():
    return synthetic_db(seed=7, n_sequences=60, n_items=10,
                        mean_itemsets=3.0, mean_itemset_size=1.3)


def _db_mid():
    return synthetic_db(seed=3, n_sequences=80, n_items=12,
                        mean_itemsets=4.0, mean_itemset_size=1.4)


def _db_kosarak():
    return kosarak_like(scale=0.0003, fast=True)


# ------------------------------------------------------------- oracle parity


def test_spam_cpu_matches_brute_force_tiny():
    db = [((1,), (2,), (1, 3)), ((1, 2), (3,)), ((2,), (1,), (3,)),
          ((1,), (3,))]
    want = sorted(brute_force_mine(db, 2))
    got = sorted(mine_spam_cpu(db, 2))
    assert got == want


@pytest.mark.parametrize("sup", [0.05, 0.1, 0.2])
def test_spam_cpu_matches_oracle(sup):
    db = _db_small()
    ms = abs_minsup(sup, len(db))
    assert patterns_text(mine_spam_cpu(db, ms)) == \
        patterns_text(mine_spade(db, ms))


@pytest.mark.parametrize("sup", [0.1, 0.2])
def test_spam_tpu_matches_oracle(sup):
    db = _db_mid()
    ms = abs_minsup(sup, len(db))
    stats = {}
    got = patterns_text(mine_spam_tpu(db, ms, stats_out=stats))
    assert got == patterns_text(mine_spade(db, ms))
    assert stats["engine"] == "spam"
    assert stats["waves"] >= 1
    # the wave pass's launch count is raggedness-independent: one
    # support launch per wave (prep/materialize add their own)
    assert stats["kernel_launches"] >= stats["waves"]
    assert stats["shape_key"].startswith("spam:")


def test_spam_tpu_kosarak_miniature_parity():
    db = _db_kosarak()
    ms = abs_minsup(0.03, len(db))
    assert patterns_text(mine_spam_tpu(db, ms)) == \
        patterns_text(mine_spade(db, ms))


def test_spam_max_pattern_itemsets_parity():
    db = _db_mid()
    ms = abs_minsup(0.1, len(db))
    from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu

    want = patterns_text(mine_spade_tpu(db, ms, max_pattern_itemsets=2,
                                        fused="never"))
    assert patterns_text(mine_spam_tpu(
        db, ms, max_pattern_itemsets=2)) == want
    assert patterns_text(mine_spam_cpu(
        db, ms, max_pattern_itemsets=2)) == want


def test_spam_tiny_node_batch_forces_many_waves():
    """Raggedness-independence under pressure: a 2-node batch produces
    many waves and recompute-on-miss traffic, same byte output."""
    db = _db_mid()
    ms = abs_minsup(0.1, len(db))
    vdb = build_vertical(db, min_item_support=ms)
    eng = SpamBitmapTPU(vdb, ms, node_batch=2, pipeline_depth=1)
    got = patterns_text(eng.mine())
    assert got == patterns_text(mine_spade(db, ms))
    assert eng.stats["waves"] > 5


def test_spam_empty_projection():
    db = [((1,),), ((2,),)]
    assert mine_spam_tpu(db, 2) == []
    assert mine_spam_cpu(db, 2) == []


# ---------------------------------------------------------- mesh + partition


def test_spam_mesh_parity():
    from spark_fsm_tpu.parallel.mesh import make_mesh

    db = _db_kosarak()
    ms = abs_minsup(0.03, len(db))
    want = patterns_text(mine_spade(db, ms))
    assert patterns_text(mine_spam_tpu(db, ms, mesh=make_mesh(8))) == want


def test_spam_partitioned_parity_8_device_mesh():
    """The acceptance's partition pin: the 2 x 4 parts x seq mesh route
    (class = DFS root item, exactly the SPADE partition classes) is
    byte-identical to the oracle."""
    from spark_fsm_tpu.parallel.mesh import make_mesh

    db = _db_kosarak()
    ms = abs_minsup(0.03, len(db))
    want = patterns_text(mine_spade(db, ms))
    stats = {}
    got = patterns_text(mine_spam_tpu(
        db, ms, mesh=make_mesh(8), partition_parts=2,
        partition_classes=16, stats_out=stats))
    assert got == want
    assert stats["partition_parts"] == 2
    assert stats["partition_imbalance"] >= 1.0


# ------------------------------------------------------- checkpoint/resume


def _mid_snapshot(eng_cls, vdb, ms, **kw):
    """Mine with per-wave checkpoints; return a MID-mine snapshot with
    the merged results list (the StoreCheckpoint.load contract)."""
    eng = eng_cls(vdb, ms, node_batch=2, pipeline_depth=1, **kw)
    snaps = []
    eng.mine(checkpoint_cb=snaps.append, checkpoint_every_s=0.0)
    assert len(snaps) >= 3
    mid_i = len(snaps) // 2
    merged = []
    for s in snaps[:mid_i + 1]:
        merged.extend(s["results"])
    mid = dict(snaps[mid_i])
    mid["results"] = merged
    return mid


def test_spam_checkpoint_resume_parity():
    db = _db_mid()
    ms = abs_minsup(0.1, len(db))
    vdb = build_vertical(db, min_item_support=ms)
    want = patterns_text(mine_spade(db, ms))
    mid = _mid_snapshot(SpamBitmapTPU, vdb, ms)
    eng = SpamBitmapTPU(vdb, ms)
    assert patterns_text(eng.mine(resume=mid)) == want
    assert eng.stats["resumed_nodes"] > 0


def test_spam_checkpoint_cross_engine_resume_both_ways():
    """The shared-frontier-format invariant: a SPAM snapshot resumes
    under the classic SPADE engine and vice versa — identical
    fingerprints, identical node shape, identical final bytes."""
    from spark_fsm_tpu.models.spade_tpu import SpadeTPU

    db = _db_mid()
    ms = abs_minsup(0.1, len(db))
    vdb = build_vertical(db, min_item_support=ms)
    want = patterns_text(mine_spade(db, ms))

    spam_mid = _mid_snapshot(SpamBitmapTPU, vdb, ms)
    assert patterns_text(SpadeTPU(vdb, ms).mine(resume=spam_mid)) == want

    spade_mid = _mid_snapshot(SpadeTPU, vdb, ms)
    assert patterns_text(
        SpamBitmapTPU(vdb, ms).mine(resume=spade_mid)) == want


def test_spam_stale_fingerprint_refused():
    db = _db_mid()
    ms = abs_minsup(0.1, len(db))
    vdb = build_vertical(db, min_item_support=ms)
    mid = _mid_snapshot(SpamBitmapTPU, vdb, ms)
    other = SpamBitmapTPU(vdb, ms + 1)
    with pytest.raises(ValueError, match="does not match"):
        other.mine(resume=mid)


# ------------------------------------------------------------------ geometry


def test_spam_geometry_bounds():
    g = spam_geometry(1000, 10, 1, node_batch=64,
                      pool_bytes=32 << 20)
    assert g["ni_pad"] % 64 == 0 and g["ni_pad"] >= 10
    assert g["node_batch"] >= 1
    assert g["total_rows"] == g["ni_pad"] + g["pool_slots"] + 1
    assert g["scratch"] == g["ni_pad"] + g["pool_slots"]
    # the wave-intermediate bound: 2*nb*tile rows of per-device slot
    # bytes fit in a quarter of the budget per in-flight wave
    spd = g["n_seq"] * 4
    assert (2 * g["node_batch"] * g["tile"] * spd
            * g["pipeline_depth"]) <= (32 << 20)


# --------------------------------------- hybrid store + diffsets (ISSUE 16)


def _db_mixed():
    """Steep-zipf miniature: a couple of ~full-density head items plus
    a long sparse tail — the shape whose alphabet a 0.5 crossover
    genuinely splits (pinned inside the hybrid tests below)."""
    return synthetic_db(seed=401, n_sequences=90, n_items=24,
                        mean_itemsets=4.0, mean_itemset_size=1.3,
                        zipf_s=2.2)


def test_spam_hybrid_matches_oracle():
    db = _db_mixed()
    ms = abs_minsup(0.08, len(db))
    want = patterns_text(mine_spade(db, ms))
    stats = {}
    got = patterns_text(mine_spam_tpu(db, ms, stats_out=stats,
                                      density_crossover=0.5))
    assert got == want
    # the store genuinely split and both evaluation paths ran
    assert stats["rep_dense"] > 0 and stats["rep_idlist"] > 0
    assert stats["pair_launches"] > 0
    assert stats["diffset_nodes"] > 0
    assert stats["wave_survivors"] > 0
    # hybrid mines publish the dense-pad-suffixed spelling of the SAME
    # key family (prefix-compatible with every spam: consumer)
    assert stats["shape_key"].startswith("spam:")
    assert f"d{64}" in stats["shape_key"]


@pytest.mark.parametrize("rep", ["bitmap", "idlist"])
def test_spam_representation_pin_parity(rep):
    """Operator pins force a UNIFORM store; bytes never change."""
    db = _db_mixed()
    ms = abs_minsup(0.08, len(db))
    want = patterns_text(mine_spade(db, ms))
    stats = {}
    got = patterns_text(mine_spam_tpu(db, ms, stats_out=stats,
                                      representation=rep))
    assert got == want
    assert stats["representation"] == rep
    if rep == "bitmap":
        assert stats["rep_idlist"] == 0 and stats["pair_launches"] == 0
    else:
        assert stats["rep_dense"] == 0 and stats["waves"] == 0


@pytest.mark.parametrize("dd", [0, 1, None])
def test_spam_diffset_depth_sweep(dd):
    """The dEclat formulation is an exact identity: any diffset depth
    (0 disables it) produces the same bytes, and the accounting stat
    reflects the depth gate."""
    db = _db_mixed()
    ms = abs_minsup(0.08, len(db))
    want = patterns_text(mine_spade(db, ms))
    for mine in (mine_spam_tpu, mine_spam_cpu):
        stats = {}
        kw = {} if dd is None else {"diffset_depth": dd}
        assert patterns_text(mine(db, ms, stats_out=stats,
                                  density_crossover=0.5, **kw)) == want
        if dd == 0:
            assert stats["diffset_nodes"] == 0
        else:
            assert stats["diffset_nodes"] > 0


def test_spam_hybrid_mesh_parity():
    from spark_fsm_tpu.parallel.mesh import make_mesh

    db = _db_mixed()
    ms = abs_minsup(0.08, len(db))
    want = patterns_text(mine_spade(db, ms))
    stats = {}
    got = patterns_text(mine_spam_tpu(db, ms, mesh=make_mesh(8),
                                      density_crossover=0.5,
                                      stats_out=stats))
    assert got == want
    assert stats["rep_idlist"] > 0  # still hybrid under the mesh


def test_spam_hybrid_pallas_interpret_parity():
    """The fused Pallas wave path (interpret mode on CPU) is
    byte-identical through the full hybrid engine."""
    db = _db_mixed()
    ms = abs_minsup(0.08, len(db))
    want = patterns_text(mine_spade(db, ms))
    assert patterns_text(mine_spam_tpu(db, ms, density_crossover=0.5,
                                       use_pallas=True)) == want


def test_spam_checkpoint_cross_representation_resume():
    """Checkpoints are representation-INVARIANT: a snapshot taken under
    the bitmap pin resumes under the hybrid (auto) store and the
    id-list pin — same fingerprint, same final bytes.  The frontier
    format records WHAT to mine, never HOW the store holds it."""
    db = _db_mixed()
    ms = abs_minsup(0.08, len(db))
    vdb = build_vertical(db, min_item_support=ms)
    want = patterns_text(mine_spade(db, ms))
    mid = _mid_snapshot(SpamBitmapTPU, vdb, ms, representation="bitmap")
    for kw in ({"density_crossover": 0.5}, {"representation": "idlist"}):
        eng = SpamBitmapTPU(vdb, ms, **kw)
        assert patterns_text(eng.mine(resume=mid)) == want
        assert eng.stats["resumed_nodes"] > 0


def test_spam_service_engine_kwargs_route():
    """The plugin route honors [engine] pool_bytes/node_batch and sheds
    constraints with a clear error."""
    from spark_fsm_tpu.service import plugins
    from spark_fsm_tpu.service.model import ServiceRequest

    db = _db_small()
    req = ServiceRequest("fsm", "train", {
        "algorithm": "SPAM_TPU", "support": "0.1"})
    stats = {}
    got = plugins.get_plugin(req).extract(req, db, stats)
    assert patterns_text(got) == patterns_text(
        mine_spade(db, abs_minsup(0.1, len(db))))
    assert stats["engine"] == "spam"

    bad = ServiceRequest("fsm", "train", {
        "algorithm": "SPAM_TPU", "support": "0.1", "maxgap": "1"})
    with pytest.raises(ValueError, match="maxgap"):
        plugins.get_plugin(bad).extract(bad, db, {})
