"""Resource attribution & usage metering plane (service/usage.py, ISSUE 19).

Pins the tentpole's contracts at three altitudes:

- **apportionment unit**: split_integral is exact (sums to total),
  deterministic (largest remainder, lowest-index tie-break), and safe
  on degenerate weights;
- **conservation invariant**: under a forced cross-job fused window AND
  under a cost-model-rejected (degraded solo re-dispatch) window, the
  per-job attribution sums EXACTLY to the broker's own dispatch
  counters (launches and traffic units) — no work invented, none lost;
- **durability**: the accumulator rides the frontier checkpoint across
  kill -9/adoption (resume REPLACES, so the final ledger row bills the
  job ONCE), avoided-cost credits land per mode, and the DISABLED path
  is one module-global read (same pin as fusion.dispatch_wave).
"""

import threading
import time

import numpy as np
import pytest

from spark_fsm_tpu import config as cfgmod
from spark_fsm_tpu.service import fusion as FZ
from spark_fsm_tpu.service import obsplane
from spark_fsm_tpu.service import usage
from spark_fsm_tpu.service.actors import StoreCheckpoint
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.utils import jobctl, obs

DEADLINE_S = 60.0


@pytest.fixture(autouse=True)
def _usage_hygiene():
    """No meter or broker leaks across tests: the engines probe module
    globals, so a leaked install would silently bill every later
    dispatch in the session."""
    usage.uninstall()
    FZ.configure(None)
    yield
    b = FZ.broker()
    if b is not None:
        b.release()
        assert b.drain(10.0), "fusion broker still busy at test exit"
    FZ.configure(None)
    usage.uninstall()
    cfgmod.set_config(cfgmod.parse_config({}))


def _install(store=None):
    cfg = cfgmod.parse_config({"usage": {"enabled": True,
                                         "flush_every_s": 0.0}})
    cfgmod.set_config(cfg)
    m = usage.install(store if store is not None else ResultStore(), None)
    m.stop()  # deterministic flushes only (flush_now / tick)
    return m


def _job(uid, tenant="default"):
    ctl = jobctl.register(uid)
    ctl.tenant = tenant
    return ctl


# ----------------------------------------------------- apportionment unit


def test_split_integral_is_exact_and_deterministic():
    assert usage.split_integral(7, [3, 2, 2]) == [3, 2, 2]
    # one unit, plurality weight wins (lowest index breaks ties)
    assert usage.split_integral(1, [2, 1, 1]) == [1, 0, 0]
    assert usage.split_integral(1, [1, 1]) == [1, 0]
    # degenerate weights fall back to equal shares
    assert usage.split_integral(10, [0, 0]) == [5, 5]
    assert usage.split_integral(0, [5, 3]) == [0, 0]
    assert usage.split_integral(3, []) == []
    rng = np.random.default_rng(7)
    for _ in range(200):
        n = int(rng.integers(1, 9))
        total = int(rng.integers(0, 10_000))
        weights = [float(w) for w in rng.random(n)]
        out = usage.split_integral(total, weights)
        assert sum(out) == total, (total, weights, out)
        assert all(v >= 0 for v in out)


# -------------------------------------------------- conservation invariant
#
# Broker-level waves reuse test_fusion.py's table-lookup eval idiom: no
# device, no compile cost, but the broker runs its REAL planner, cost
# model, and (here) its real attribution demux.


def _table_eval(km):
    def fn(p1, s1, xy):
        t = np.asarray(p1)[:, 0].astype(np.int64)
        s = np.asarray(s1)[:, 0].astype(np.int64)
        xyn = np.asarray(xy)
        xs = np.where(xyn[:, 0] >= 0, t[np.maximum(xyn[:, 0], 0)], 0)
        ys = np.where(xyn[:, 1] >= 0, s[np.maximum(xyn[:, 1], 0)], 0)
        return np.stack([xs.sum(axis=1), ys.sum(axis=1)])
    return fn


def _wave(uid, *, base, m=8, cands=None, priority="normal", n_seq=64):
    p1 = (np.arange(m, dtype=np.uint32)[:, None] + np.uint32(base))
    s1 = p1 + np.uint32(100_000)
    cands = cands if cands is not None else [((0,), (1,)), ((2, 3), (4,))]
    pools = {}
    for r, (x, y) in enumerate(cands):
        side = max(len(x), len(y))
        km = 1
        while km < side:
            km *= 2
        pools.setdefault(km, []).append(r)
    return FZ.EvalWave(uid=uid, priority=priority, cands=cands,
                       pools=pools, p1=p1, s1=s1, eval_fn=_table_eval,
                       put=lambda x: x, cap=lambda km: 8192, lane=32,
                       n_seq=n_seq, n_words=1)


def _settled_sum(uids):
    total = {"launches": 0, "traffic_units": 0, "seconds": 0.0}
    for uid in uids:
        vec = usage.settle(uid)
        assert vec is not None, f"no attribution deposited for {uid}"
        total["launches"] += vec["launches"]
        total["traffic_units"] += vec["traffic_units"]
        total["seconds"] += vec["device_seconds_measured"]
    return total


def test_conservation_exact_under_cross_job_fusion():
    """THE invariant: a fused cross-job group's per-job attribution sums
    EXACTLY to the broker's own launch/traffic counters."""
    _install()
    b = FZ.FusionBroker(window_s=0.25, max_jobs=8, max_width=16384)
    b.hold()
    _job("cons-a", "acme")
    _job("cons-b", "globex")
    try:
        w1 = _wave("cons-a", base=1)
        w2 = _wave("cons-b", base=1000,
                   cands=[((1,), (0,)), ((4,), (2, 5)), ((6, 7), (3,))])
        b.submit(w1)
        b.submit(w2)
        b.release()
        w1.result()
        w2.result()
        assert b.stats["fused_groups"] == 1
        assert b.stats["cross_job_launches"] >= 1
        got = _settled_sum(["cons-a", "cons-b"])
        assert got["launches"] == b.stats["launches"]
        assert got["traffic_units"] == b.stats["traffic_units"]
        assert got["seconds"] > 0.0
    finally:
        jobctl.release("cons-a")
        jobctl.release("cons-b")


def test_conservation_exact_under_degraded_solo_dispatch():
    """A cost-model-REJECTED group dispatches per-job (the degraded
    path): each solo re-dispatch bills its own job, and the sum still
    equals the broker's counters exactly."""
    _install()
    b = FZ.FusionBroker(window_s=0.25, max_jobs=8, max_width=16384)
    b.hold()
    _job("deg-a", "acme")
    _job("deg-b", "globex")
    try:
        w1 = _wave("deg-a", base=1, m=8192, n_seq=990_000)
        w2 = _wave("deg-b", base=7, m=8192, n_seq=990_000)
        b.submit(w1)
        b.submit(w2)
        b.release()
        w1.result()
        w2.result()
        assert b.stats["rejected_groups"] == 1
        assert b.stats["solo_waves"] == 2
        va = usage.settle("deg-a")
        vb = usage.settle("deg-b")
        assert va["launches"] + vb["launches"] == b.stats["launches"]
        assert (va["traffic_units"] + vb["traffic_units"]
                == b.stats["traffic_units"])
        # each job billed for ITS OWN plan, not a half of the pair
        assert va["launches"] >= 1 and vb["launches"] >= 1
    finally:
        jobctl.release("deg-a")
        jobctl.release("deg-b")


def test_conservation_counters_match_tenant_rollup():
    """The zero-seeded fsm_usage_* counters move by exactly what the
    tenant rollups record — the cross-check usage_smoke reads off
    /metrics."""
    m = _install()
    obsplane.seed_tenant("acme")
    before = usage._LAUNCHES.total()
    _job("ctr-1", "acme")
    try:
        usage.deposit("ctr-1", launches=5, traffic_units=640,
                      seconds_measured=0.25)
        vec = usage.settle("ctr-1")
        assert usage._LAUNCHES.total() - before == vec["launches"] == 5
        rep = m.report()
        assert rep["tenants"]["acme"]["launches"] == 5
        assert rep["tenants"]["acme"]["traffic_units"] == 640
    finally:
        jobctl.release("ctr-1")


# ------------------------------------------------ kill -9 / adoption drill


def test_attribution_survives_checkpoint_adoption_no_double_billing():
    """The dead holder's deposits ride the frontier checkpoint; the
    adopter resumes them (REPLACE, not add), re-deposits its own work,
    and the final ledger row bills the job ONCE."""
    store = ResultStore()
    _install(store)
    uid = "adopt-1"
    _job(uid, "acme")
    obsplane.seed_tenant("acme")
    try:
        usage.deposit(uid, launches=4, traffic_units=400,
                      seconds_est=0.4, seconds_measured=0.5)
        ckpt = StoreCheckpoint(store, uid, every_s=0.0)
        ckpt.save({"stack": [1, 2], "fingerprint": "fp",
                   "results": [], "results_done": 0})
        # kill -9: the holder's live accumulator dies with the process.
        # The fenced-failure path would usage.drop() — same end state.
        usage.drop(uid)
        jobctl.release(uid)

        # adopter: fresh control entry, loads the frontier
        _job(uid, "acme")
        state = StoreCheckpoint(store, uid).load()
        assert state is not None
        assert "usage" not in state  # stripped before the engine sees it
        adopted = usage.job_view(uid)
        assert adopted is not None and adopted["launches"] == 4
        # the adopter re-mines PAST the checkpoint and deposits on top
        usage.deposit(uid, launches=2, traffic_units=100,
                      seconds_measured=0.1)
        vec = usage.settle(uid)
        assert vec["launches"] == 6 and vec["traffic_units"] == 500
        usage.flush_now()
        rows = usage.get().ledger_rows(store)
        row = rows["acme"]
        assert row["jobs"][uid]["launches"] == 6
        assert row["totals"]["launches"] == 6  # once, not 4 + 6

        # a LATER settle of the same uid (resubmit/adopt chain) REPLACES
        # the ledger entry — totals follow the newest vector
        _job(uid, "acme")
        usage.deposit(uid, launches=3, traffic_units=50)
        usage.settle(uid)
        usage.flush_now()
        row = usage.get().ledger_rows(store)["acme"]
        assert row["jobs"][uid]["launches"] == 3
        assert row["totals"]["launches"] == 3
    finally:
        jobctl.release(uid)


def test_fenced_holder_drops_without_settling():
    m = _install()
    _job("fence-1", "acme")
    try:
        usage.deposit("fence-1", launches=7, traffic_units=10)
        usage.drop("fence-1")
        assert usage.settle("fence-1") is None
        rep = m.report()
        assert rep["tenants"].get("acme", {}).get("launches", 0) == 0
    finally:
        jobctl.release("fence-1")


# ------------------------------------------------------------ avoided cost


def test_avoided_cost_credits_per_mode():
    m = _install()
    obsplane.seed_tenant("acme")
    before = usage._AVOIDED.total()
    for mode, secs in (("exact", 0.5), ("dominated", 0.25),
                       ("coalesced", 0.125)):
        usage.credit_avoided("acme", secs, mode)
    rep = m.report()
    assert rep["tenants"]["acme"]["avoided_device_seconds"] == \
        pytest.approx(0.875)
    assert usage._AVOIDED.total() - before == pytest.approx(0.875)
    # unknown tenants fold to default; negative credits clamp to zero
    usage.credit_avoided("nobody-registered-this", 0.5, "exact")
    usage.credit_avoided("acme", -1.0, "exact")
    rep = m.report()
    assert rep["tenants"]["default"]["avoided_device_seconds"] == \
        pytest.approx(0.5)
    assert rep["tenants"]["acme"]["avoided_device_seconds"] == \
        pytest.approx(0.875)


# ---------------------------------------------------------- disabled path


def test_disabled_path_is_one_global_read():
    """[usage] off (the default): every probe returns after one
    module-global read — no meter, no counter, no rollup touched."""
    assert usage.get() is None
    before = usage._LAUNCHES.total()
    usage.deposit("ghost", launches=5, traffic_units=100,
                  seconds_measured=1.0)
    usage.deposit_tenant("acme", launches=3)
    usage.credit_avoided("acme", 1.0, "exact")
    assert usage.settle("ghost") is None
    assert usage.job_view("ghost") is None
    assert usage.checkpoint_snapshot("ghost") is None
    usage.resume("ghost", {"launches": 9})
    usage.drop("ghost")
    usage.tick()
    assert usage.flush_now() == 0
    assert usage.report() == {"enabled": False}
    assert usage.stats() is None
    assert usage._LAUNCHES.total() == before
    # the fused-attribution demux early-returns before touching a wave
    FZ.FusionBroker._attribute_fused([], [], 0.0, 0.0)


def test_config_validation():
    with pytest.raises(ValueError):
        cfgmod.parse_config({"usage": {"window_s": 0}})
    with pytest.raises(ValueError):
        cfgmod.parse_config({"usage": {"flush_every_s": -1}})
    with pytest.raises(ValueError):
        cfgmod.parse_config({"usage": {"top_jobs": 0}})
    cfg = cfgmod.parse_config({"usage": {"enabled": True}})
    assert cfg.usage.enabled and cfg.usage.window_s == 300.0


# ------------------------------------------- per-family cost-model drift


def test_family_drift_isolated_from_global_ewma():
    """observe_costmodel_family moves ONLY the per-family EWMA — the
    global drift ratio and sample counter stay byte-identical (the
    bench_smoke pin); observe_costmodel(family=...) moves both."""
    # earlier suite tests mine real jobs and pre-seed these EWMAs —
    # clear the two families this test asserts exact first-sample
    # values for (the module dict is process-global, like the gauge)
    obs._family_ewma.pop("tsr-resident", None)
    obs._family_ewma.pop("tsr-eval", None)
    samples = obs._COSTMODEL_SAMPLES.total()
    global_drift = obs.costmodel_drift()
    obs.observe_costmodel_family("tsr-resident", 0.1, 0.3)
    assert obs._COSTMODEL_SAMPLES.total() == samples
    assert obs.costmodel_drift() == global_drift
    fam = obs.costmodel_family_drift()
    assert fam["tsr-resident"] == pytest.approx(3.0)
    # unknown families and non-positive predictions are dropped
    obs.observe_costmodel_family("not-a-family", 0.1, 0.2)
    obs.observe_costmodel_family("spam", 0.0, 0.2)
    assert "not-a-family" not in obs.costmodel_family_drift()
    # the combined entry point moves the global EWMA AND the family's
    obs.observe_costmodel(0.2, 0.2, family="tsr-eval")
    assert obs._COSTMODEL_SAMPLES.total() == samples + 1
    assert obs.costmodel_family_drift()["tsr-eval"] > 0.0
    for f in obs.COSTMODEL_FAMILIES:
        assert isinstance(f, str) and f


# ---------------------------------------------------------- read path


def test_jobless_deposit_folds_to_tenant_and_flushes():
    """Predict waves have no JobControl: deposit_tenant folds the cost
    straight into the tenant rollup, and the durable flush merges it
    append-only into the ledger totals + read_path sub-vector."""
    store = ResultStore()
    m = _install(store)
    obsplane.seed_tenant("acme")
    usage.deposit_tenant("acme", launches=1, traffic_units=256,
                         seconds_measured=0.01)
    usage.deposit_tenant("unregistered", launches=1)  # folds to default
    rep = m.report(store)
    assert rep["tenants"]["acme"]["launches"] == 1
    assert rep["tenants"]["default"]["launches"] == 1
    row = usage.get().ledger_rows(store)["acme"]
    assert row["totals"]["launches"] == 1
    assert row["read_path"]["traffic_units"] == 256
    # a second flush with no new work writes nothing
    assert usage.flush_now() == 0
