import numpy as np
import pytest

from spark_fsm_tpu.data.spmf import parse_spmf
from spark_fsm_tpu.data.vertical import (abs_minsup, build_vertical,
                                         idlist_join_support, rep_plan)


def test_bit_layout():
    db = parse_spmf("1 3 -1 2 -1 2 4 -2\n1 -1 2 -2\n")
    vdb = build_vertical(db)
    assert vdb.item_ids.tolist() == [1, 2, 3, 4]
    assert vdb.n_words == 1
    i = {it: k for k, it in enumerate(vdb.item_ids.tolist())}
    # seq 0: item 1 at pos 0; item 2 at pos 1 and 2; item 3 at pos 0; item 4 at pos 2
    assert vdb.bitmaps[i[1], 0, 0] == 0b001
    assert vdb.bitmaps[i[2], 0, 0] == 0b110
    assert vdb.bitmaps[i[3], 0, 0] == 0b001
    assert vdb.bitmaps[i[4], 0, 0] == 0b100
    # seq 1: item 1 at pos 0, item 2 at pos 1
    assert vdb.bitmaps[i[1], 1, 0] == 0b01
    assert vdb.bitmaps[i[2], 1, 0] == 0b10
    assert vdb.item_supports.tolist() == [2, 2, 1, 1]


def test_projection_keeps_positions():
    # item 9 is infrequent; dropping it must not shift item 2's position
    db = parse_spmf("9 -1 2 -2\n2 -1 2 -2\n")
    vdb = build_vertical(db, min_item_support=2)
    assert vdb.item_ids.tolist() == [2]
    assert vdb.bitmaps[0, 0, 0] == 0b10  # still position 1
    assert vdb.bitmaps[0, 1, 0] == 0b11


def test_multiword_positions():
    # a sequence with 40 itemsets puts bits into word 1
    seq = " -1 ".join(["7"] * 40) + " -2"
    vdb = build_vertical(parse_spmf(seq))
    assert vdb.n_words == 2
    assert vdb.bitmaps[0, 0, 0] == 0xFFFFFFFF
    assert vdb.bitmaps[0, 0, 1] == 0xFF


def test_sequence_padding():
    db = parse_spmf("1 -2\n")
    vdb = build_vertical(db, pad_sequences_to=8)
    assert vdb.n_sequences == 8
    assert vdb.bitmaps[:, 1:].sum() == 0
    assert vdb.seq_lengths.tolist() == [1, 0, 0, 0, 0, 0, 0, 0]


def test_word_multiple():
    vdb = build_vertical(parse_spmf("1 -2\n"), word_multiple=4)
    assert vdb.n_words == 4


def test_abs_minsup():
    assert abs_minsup(0.001, 77500) == 78
    assert abs_minsup(0.5, 3) == 2
    assert abs_minsup(0.0, 100) == 1


def test_nbytes():
    vdb = build_vertical(parse_spmf("1 -2\n"))
    assert vdb.nbytes() == 4


# ----------------------------------------- hybrid store (ISSUE 16)


def _mixed_vdb():
    from spark_fsm_tpu.data.synth import synthetic_db

    db = synthetic_db(seed=401, n_sequences=50, n_items=16,
                      mean_itemsets=4.0, mean_itemset_size=1.3,
                      zipf_s=2.2)
    return build_vertical(db, min_item_support=2)


def test_idlist_reconstructs_bitmap():
    """The id-list is the SAME vertical database in sparse form: every
    (seq, word, mask) token scatters back to exactly the item's dense
    bitmap row, and the lengths accessor matches the token table."""
    vdb = _mixed_vdb()
    lens = vdb.idlist_lengths()
    assert lens.sum() == vdb.tok_seq.size
    for i in range(vdb.n_items):
        ts, tw, tm = vdb.idlist(i)
        assert ts.size == lens[i]
        back = np.zeros((vdb.n_sequences, vdb.n_words), np.uint32)
        np.bitwise_or.at(back, (ts, tw), tm)
        assert np.array_equal(back, vdb.bitmaps[i])


def test_idlist_join_support_matches_dense_join():
    """The sparse join is byte-identical to the dense one for BOTH
    extension kinds, for every (prefix item, extension item) pair —
    the property that makes per-item representation routing a layout
    choice, never a result choice."""
    from spark_fsm_tpu.ops import bitops_np as B

    vdb = _mixed_vdb()
    for p in range(vdb.n_items):
        plain = vdb.bitmaps[p]
        sext = B.sext_transform(plain[None])[0]
        for i in range(vdb.n_items):
            for pref in (plain, sext):
                want = int(B.support_popcount((pref & vdb.bitmaps[i])[None])[0])
                assert idlist_join_support(pref, *vdb.idlist(i)) == want


def test_diffset_identity_exact():
    """sup(child) == sup(parent_row) - |diffset| exactly, for random
    parent/child pairs where the child is an AND-down of the parent
    (the only shape joins produce)."""
    from spark_fsm_tpu.ops import bitops_np as B

    rng = np.random.default_rng(5)
    parent = rng.integers(0, 2**32, (30, 7, 2), dtype=np.uint32)
    child = parent & rng.integers(0, 2**32, (30, 7, 2), dtype=np.uint32)
    direct = B.support_popcount(child)
    viadiff = B.support_from_diffset(B.support_popcount(parent),
                                     B.diffset_count(parent, child))
    assert np.array_equal(direct, viadiff)


def test_rep_plan_split_and_pins():
    sup = np.array([50, 10, 2, 0, 25])
    plan = rep_plan(sup, 50, crossover=0.3)
    assert plan.rep.tolist() == [True, False, False, False, True]
    assert (plan.n_dense, plan.n_sparse, plan.hybrid) == (2, 3, True)
    attrs = plan.as_attrs()
    assert attrs["representation"] == "auto"
    assert attrs["dense_items"] == 2 and attrs["idlist_items"] == 3
    assert attrs["max_item_density"] == 1.0

    assert rep_plan(sup, 50, crossover=0.3, pin="bitmap").rep.all()
    assert not rep_plan(sup, 50, crossover=0.3, pin="idlist").rep.any()
    with pytest.raises(ValueError, match="representation"):
        rep_plan(sup, 50, crossover=0.3, pin="spam")
