import numpy as np

from spark_fsm_tpu.data.spmf import parse_spmf
from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical


def test_bit_layout():
    db = parse_spmf("1 3 -1 2 -1 2 4 -2\n1 -1 2 -2\n")
    vdb = build_vertical(db)
    assert vdb.item_ids.tolist() == [1, 2, 3, 4]
    assert vdb.n_words == 1
    i = {it: k for k, it in enumerate(vdb.item_ids.tolist())}
    # seq 0: item 1 at pos 0; item 2 at pos 1 and 2; item 3 at pos 0; item 4 at pos 2
    assert vdb.bitmaps[i[1], 0, 0] == 0b001
    assert vdb.bitmaps[i[2], 0, 0] == 0b110
    assert vdb.bitmaps[i[3], 0, 0] == 0b001
    assert vdb.bitmaps[i[4], 0, 0] == 0b100
    # seq 1: item 1 at pos 0, item 2 at pos 1
    assert vdb.bitmaps[i[1], 1, 0] == 0b01
    assert vdb.bitmaps[i[2], 1, 0] == 0b10
    assert vdb.item_supports.tolist() == [2, 2, 1, 1]


def test_projection_keeps_positions():
    # item 9 is infrequent; dropping it must not shift item 2's position
    db = parse_spmf("9 -1 2 -2\n2 -1 2 -2\n")
    vdb = build_vertical(db, min_item_support=2)
    assert vdb.item_ids.tolist() == [2]
    assert vdb.bitmaps[0, 0, 0] == 0b10  # still position 1
    assert vdb.bitmaps[0, 1, 0] == 0b11


def test_multiword_positions():
    # a sequence with 40 itemsets puts bits into word 1
    seq = " -1 ".join(["7"] * 40) + " -2"
    vdb = build_vertical(parse_spmf(seq))
    assert vdb.n_words == 2
    assert vdb.bitmaps[0, 0, 0] == 0xFFFFFFFF
    assert vdb.bitmaps[0, 0, 1] == 0xFF


def test_sequence_padding():
    db = parse_spmf("1 -2\n")
    vdb = build_vertical(db, pad_sequences_to=8)
    assert vdb.n_sequences == 8
    assert vdb.bitmaps[:, 1:].sum() == 0
    assert vdb.seq_lengths.tolist() == [1, 0, 0, 0, 0, 0, 0, 0]


def test_word_multiple():
    vdb = build_vertical(parse_spmf("1 -2\n"), word_multiple=4)
    assert vdb.n_words == 4


def test_abs_minsup():
    assert abs_minsup(0.001, 77500) == 78
    assert abs_minsup(0.5, 3) == 2
    assert abs_minsup(0.0, 100) == 1


def test_nbytes():
    vdb = build_vertical(parse_spmf("1 -2\n"))
    assert vdb.nbytes() == 4
