"""Chaos suite: injected failure at EVERY registered fault site.

The acceptance contract (ISSUE 3): for each site in
``faults.KNOWN_SITES``, injection must produce either a clean
retry/degrade whose results MATCH the fault-free run (parity) or a
clean ``failure`` status — never a hang (scenarios run under a hard
deadline via the watchdog itself), never a torn snapshot accepted on
resume (tests/test_checkpoint.py covers the crash-timing half), never a
silent wrong answer.  ``test_every_registered_site_is_covered`` pins
the sweep to the registry, so adding a fault site without a chaos
scenario fails CI.

Deterministic: nth/every triggers plus the pinned seed
(``SPARKFSM_CHAOS_SEED``, exported by scripts/chaos_smoke.sh) for
probability-based specs.  Every scenario disarms via
``faults.injected`` / the autouse fixture — conftest asserts the
registry is clean at both session edges.
"""

import json
import os
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from spark_fsm_tpu import config as cfgmod
from spark_fsm_tpu.data.spmf import format_spmf, parse_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.models.spade_tpu import SpadeTPU
from spark_fsm_tpu.models.tsr import TsrTPU
from spark_fsm_tpu.ops import ragged_batch as RB
from spark_fsm_tpu.service.actors import Master, StoreCheckpoint
from spark_fsm_tpu.service.devcache import (
    SpadeEngineCache, cspade_engine_cache, spade_engine_cache,
    tsr_engine_cache)
from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.streaming.consumer import PollConsumer, consumer_health
from spark_fsm_tpu.streaming.kafka import KafkaFetch
from spark_fsm_tpu.utils import faults, watchdog
from spark_fsm_tpu.utils.canonical import (diff_patterns, patterns_text,
                                           rules_text)
from spark_fsm_tpu.utils.retry import (CircuitBreaker, RetryPolicy,
                                       retry_counters)

CHAOS_SEED = int(os.environ.get("SPARKFSM_CHAOS_SEED", "1299827"))
SCENARIO_DEADLINE_S = 300.0  # suite-enforced no-hang bound


def _bounded(fn):
    """Run a scenario under a hard deadline: a hang is a FAILURE with a
    named site, never a wedged CI job (dogfoods the watchdog runner)."""
    return watchdog.run_with_deadline(fn, SCENARIO_DEADLINE_S,
                                      site="chaos.suite")


# site -> scenario test names; the sweep test pins this to KNOWN_SITES
COVERED: dict = {}


def covers(*sites):
    def deco(fn):
        for s in sites:
            COVERED.setdefault(s, []).append(fn.__name__)
        return fn
    return deco


@pytest.fixture(autouse=True)
def _chaos_hygiene():
    """No injection, no watchdog policy, and closed breakers leak in or
    out of any scenario."""
    faults.disarm()
    watchdog.configure(slack=None)
    for cache in (spade_engine_cache, cspade_engine_cache,
                  tsr_engine_cache):
        cache.breaker.success()  # reset consecutive-failure streaks
    yield
    faults.disarm()
    watchdog.configure(slack=None)


def _db():
    return synthetic_db(seed=17, n_sequences=120, n_items=10,
                        mean_itemsets=3.0, mean_itemset_size=1.3)


def _rule_db():
    return synthetic_db(seed=23, n_sequences=40, n_items=7,
                        mean_itemsets=3.0, mean_itemset_size=1.2)


def _run_train(store, data, timeout=120.0):
    """Submit one train job through the real Master; returns (uid,
    terminal status) — polling bounded, so a hung job fails loudly."""
    master = Master(store=store)
    try:
        resp = master.handle(ServiceRequest("fsm", "train", dict(data)))
        assert resp.status != "failure", resp.data
        uid = resp.data["uid"]
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = store.status(uid)
            if st in ("finished", "failure"):
                return uid, st
            time.sleep(0.02)
        raise TimeoutError(f"job {uid} reached no terminal status")
    finally:
        master.shutdown()


def _stored_patterns(store, uid):
    from spark_fsm_tpu.service.model import deserialize_patterns

    return deserialize_patterns(store.patterns(uid))


# ---------------------------------------------------------------- registry


def test_every_registered_site_is_covered():
    """The sweep IS the registry: a new fault site must ship a chaos
    scenario or this fails."""
    assert set(COVERED) == set(faults.KNOWN_SITES), (
        f"uncovered: {set(faults.KNOWN_SITES) - set(COVERED)}, "
        f"unknown: {set(COVERED) - set(faults.KNOWN_SITES)}")


def test_registry_validates_arms():
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.arm("store.flush", nth=1)
    with pytest.raises(ValueError, match="exactly one"):
        faults.arm("store.set", nth=1, every=2)
    with pytest.raises(ValueError, match="delay_s"):
        faults.arm("store.set", nth=1, exc="none")
    assert faults.armed() == {}


def test_trigger_shapes_are_deterministic():
    calls = []
    with faults.injected("store.set", every=2, match="chaos-trigger"):
        for i in range(6):
            try:
                faults.fault_site("store.set", key=f"chaos-trigger-{i}")
                calls.append("ok")
            except faults.FaultInjected:
                calls.append("boom")
    assert calls == ["ok", "boom", "ok", "boom", "ok", "boom"]
    # seeded probability: two runs with the same seed fire identically
    outcomes = []
    for _ in range(2):
        hits = []
        with faults.injected("store.set", p=0.5, seed=CHAOS_SEED,
                             match="chaos-trigger"):
            for i in range(16):
                try:
                    faults.fault_site("store.set", key=f"chaos-trigger-{i}")
                    hits.append(0)
                except faults.FaultInjected:
                    hits.append(1)
        outcomes.append(hits)
    assert outcomes[0] == outcomes[1] and sum(outcomes[0]) > 0


# ------------------------------------------------------------- store I/O


@covers("store.set")
def test_store_set_fault_retried_during_checkpointed_job():
    """A transient store failure on a frontier write is absorbed by the
    checkpoint's bounded-backoff retry — the job finishes with parity,
    no failure status, and the retry is counted."""
    db = _db()
    store = ResultStore()
    with faults.injected("store.set", nth=1, match="fsm:frontier:"):
        uid, status = _bounded(lambda: _run_train(store, {
            "algorithm": "SPADE_TPU", "source": "INLINE",
            "sequences": format_spmf(db), "support": "0.1",
            "checkpoint": "1", "checkpoint_every_s": "0"}))
    assert status == "finished", store.get(f"fsm:error:{uid}")
    want = mine_spade(db, abs_minsup(0.1, len(db)))
    got = _stored_patterns(store, uid)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)
    assert retry_counters().get("store.checkpoint", {}).get("retries", 0) >= 1


@covers("store.rpush")
def test_store_rpush_fault_retried_mid_mine():
    """An injected failure on a checkpoint DELTA append retries inside
    save(); the mine neither fails nor loses results."""
    db = _db()
    minsup = abs_minsup(0.05, len(db))
    store = ResultStore()
    ckpt = StoreCheckpoint(store, "chaos-rpush", every_s=0.0)
    eng = SpadeTPU(build_vertical(db, min_item_support=minsup), minsup,
                   node_batch=4, pipeline_depth=2, pool_bytes=32 << 20)
    with faults.injected("store.rpush", nth=1,
                         match="fsm:frontier:results:chaos-rpush"):
        got = _bounded(lambda: eng.mine(checkpoint_cb=ckpt.save,
                                        checkpoint_every_s=0.0))
    want = mine_spade(db, minsup)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)
    state = ckpt.load()
    assert state is not None  # the healed/retried snapshot still loads
    assert retry_counters()["store.checkpoint"]["retries"] >= 1


@covers("store.get")
def test_store_get_fault_retried_on_resume_load():
    store = ResultStore()
    ckpt = StoreCheckpoint(store, "chaos-get")
    ckpt.save({"version": 1, "stack": [{"steps": [[0, 1]], "s": [], "i": []}],
               "results_done": 0, "results": [[[[1]], 3]]})
    with faults.injected("store.get", nth=1, match="fsm:frontier:chaos-get"):
        state = StoreCheckpoint(store, "chaos-get").load()
    assert state is not None and state["results"] == [[[[1]], 3]]
    assert retry_counters()["store.checkpoint"]["retries"] >= 1


# ---------------------------------------------------------- checkpoint.save


@covers("checkpoint.save")
def test_checkpoint_save_fault_job_still_finishes_with_parity():
    """A whole-save failure aborts that mine attempt; supervision (the
    devcache host-path fallback or the Miner retry) re-runs it and the
    job still lands 'finished' with the exact pattern set."""
    db = _db()
    store = ResultStore()
    with faults.injected("checkpoint.save", nth=1):
        uid, status = _bounded(lambda: _run_train(store, {
            "algorithm": "SPADE_TPU", "source": "INLINE",
            "sequences": format_spmf(db), "support": "0.1",
            "checkpoint": "1", "checkpoint_every_s": "0", "retries": "2"}))
    assert status == "finished", store.get(f"fsm:error:{uid}")
    want = mine_spade(db, abs_minsup(0.1, len(db)))
    got = _stored_patterns(store, uid)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


# -------------------------------------------------------------- kafka.poll


@covers("kafka.poll")
class TestKafkaPollFaults:
    class _Rec:
        def __init__(self, value):
            self.value = value

    class _Fake:
        def __init__(self, polls):
            self._polls = list(polls)

        def poll(self, timeout_ms=None):
            return self._polls.pop(0) if self._polls else {}

    def test_flaky_poll_backs_off_and_loses_nothing(self):
        dbs = [synthetic_db(seed=s, n_sequences=12, n_items=6,
                            mean_itemsets=2.0) for s in (1, 2, 3)]
        polls = [{"tp0": [self._Rec(format_spmf(db).encode())]}
                 for db in dbs]
        fetch = KafkaFetch(self._Fake(polls))
        got = []
        pc = PollConsumer(fetch, got.append, poll_interval_s=0)
        with faults.injected("kafka.poll", every=2):
            stats = _bounded(lambda: pc.run(max_polls=10))
        # every batch arrived exactly once, in order, despite the faults
        assert [len(b) for b in got] == [len(db) for db in dbs]
        assert got == dbs
        assert stats["errors"] >= 2  # the injected polls were counted
        assert stats["stopped"] == "max_polls"


# ---------------------------------------------------------- device.dispatch


@covers("device.dispatch")
def test_dispatch_fault_degrades_kernel_to_jnp_with_parity():
    """A failed kernel launch marks only its km geometry bad; the lanes
    re-pool onto the jnp path and the rule set is byte-identical."""
    db = _rule_db()
    want = TsrTPU(build_vertical(db, min_item_support=1), 8, 0.4,
                  max_side=2, use_pallas=True).mine()
    eng = TsrTPU(build_vertical(db, min_item_support=1), 8, 0.4,
                 max_side=2, use_pallas=True)
    with faults.injected("device.dispatch", nth=1, match="kernel"):
        got = _bounded(eng.mine)
    assert rules_text(got) == rules_text(want)
    assert any(k.startswith("pallas_fallback_km") for k in eng.stats), (
        eng.stats)


@covers("device.dispatch")
def test_dispatch_hang_fails_launch_via_watchdog():
    """A HUNG readback (injected delay, no exception) must not wedge the
    worker: the watchdog deadline — derived from the packer's own cost
    model x slack — fails the launch with WatchdogTimeout (the device is
    suspect, so the engine does NOT keep dispatching on it), supervision
    re-runs the job, and the retry returns the exact rules."""
    db = _rule_db()
    want = TsrTPU(build_vertical(db, min_item_support=1), 8, 0.4,
                  max_side=2, use_pallas=True).mine()
    wd0 = watchdog.stats()
    watchdog.configure(slack=100.0, floor_s=0.5)
    eng = TsrTPU(build_vertical(db, min_item_support=1), 8, 0.4,
                 max_side=2, use_pallas=True)
    # the hang is far longer than any legitimate work this mine does,
    # so the wall bound below proves the watchdog cut it off rather
    # than waiting it out
    with faults.injected("device.dispatch", nth=1, match="readback",
                         delay_s=90.0, exc="none"):
        t0 = time.monotonic()
        with pytest.raises(watchdog.WatchdogTimeout):
            _bounded(eng.mine)
        wall = time.monotonic() - t0
    wd = watchdog.stats()
    assert wd["timeouts"] >= wd0["timeouts"] + 1
    assert wd["leaked_threads"] >= wd0["leaked_threads"] + 1
    assert wall < 60.0  # the 90s hang was NOT waited out
    # the supervised retry (fault spent, watchdog still armed): parity
    got = _bounded(TsrTPU(build_vertical(db, min_item_support=1), 8, 0.4,
                          max_side=2, use_pallas=True).mine)
    assert rules_text(got) == rules_text(want)


@covers("device.dispatch")
def test_dispatch_fault_in_queue_mine_is_supervised():
    """An injected queue-engine dispatch failure surfaces through the
    service as retry-then-finish (or a clean failure) — never a hang or
    a wrong pattern set."""
    db = _db()
    store = ResultStore()
    with faults.injected("device.dispatch", nth=1, match="queue_launch"):
        uid, status = _bounded(lambda: _run_train(store, {
            "algorithm": "SPADE_TPU", "source": "INLINE",
            "sequences": format_spmf(db), "support": "0.1",
            "retries": "2"}))
    assert status == "finished", store.get(f"fsm:error:{uid}")
    want = mine_spade(db, abs_minsup(0.1, len(db)))
    got = _stored_patterns(store, uid)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


# --------------------------------------------------------------- device.oom


@covers("device.oom")
def test_oom_degradation_ladder_halves_width():
    """RESOURCE_EXHAUSTED on a launch re-plans it at half width (floor
    128 lanes) with identical results — the OOM never reaches the mine.
    """
    db = synthetic_db(seed=29, n_sequences=60, n_items=14,
                      mean_itemsets=3.0, mean_itemset_size=1.3)
    vdb = build_vertical(db, min_item_support=1)
    eng = TsrTPU(vdb, 10, 0.4, max_side=2, use_pallas=True)
    m = min(eng.item_cap, vdb.n_items)
    eng.chunk = eng._round_chunk(m)
    eng._round_m = m
    p1, s1 = eng._prep(m)
    cands = [((i,), (j,)) for i in range(m) for j in range(m) if i != j]
    assert len(cands) > 128, "need a launch wider than the ladder floor"
    width = RB.next_pow2(len(cands))
    launch = RB.Launch(1, width, list(range(len(cands))), [1] * len(cands))

    def dispatch():
        parts, cols = [], np.empty(len(cands), np.int64)
        eng._xy_bufs = []
        base = eng._dispatch_kernel_launch(p1, s1, cands, launch, parts,
                                           cols, 0)
        arr = np.asarray(parts[0] if len(parts) == 1
                         else jnp.concatenate(parts, axis=1))
        return base, arr[0, cols], arr[1, cols]

    _, sup0, supx0 = dispatch()  # fault-free baseline
    with faults.injected("device.oom", nth=1):
        base, sup, supx = _bounded(dispatch)
    assert eng.stats["degraded_launches"] == 1
    assert base == 2 * (width // 2)  # two half-width sub-launches
    np.testing.assert_array_equal(sup, sup0)
    np.testing.assert_array_equal(supx, supx0)


@covers("device.oom")
def test_oom_mid_mine_keeps_parity():
    db = _rule_db()
    want = TsrTPU(build_vertical(db, min_item_support=1), 8, 0.4,
                  max_side=2, use_pallas=True).mine()
    eng = TsrTPU(build_vertical(db, min_item_support=1), 8, 0.4,
                 max_side=2, use_pallas=True)
    with faults.injected("device.oom", nth=1):
        got = _bounded(eng.mine)
    assert rules_text(got) == rules_text(want)
    # either the ladder absorbed it (wide launch) or the generic
    # fallback re-pooled the lanes onto jnp (floor-width launch) —
    # both are clean degrades, and one of them must have happened
    assert (eng.stats.get("degraded_launches", 0) >= 1
            or any(k.startswith("pallas_fallback_km")
                   for k in eng.stats)), eng.stats


# ----------------------------------------------------------- prewarm.compile


@covers("prewarm.compile")
def test_prewarm_compile_fault_is_isolated_per_key():
    """One failing shape-key warm must not take down boot or the other
    keys: the report carries the error on exactly the injected key."""
    from spark_fsm_tpu.service import prewarm
    from spark_fsm_tpu.utils import shapes

    spec = shapes.WorkloadSpec(n_sequences=8, n_items=2, n_words=1)
    with faults.injected("prewarm.compile", nth=1):
        report = _bounded(lambda: prewarm.run(spec))
    rows = report["keys"]
    assert len(rows) >= 2
    errs = [r for r in rows if "error" in r]
    assert len(errs) == 1 and "injected fault" in errs[0]["error"], rows
    assert report["total_wall_s"] >= 0  # run() completed normally


# -------------------------------------------------------------- devcache.put


@covers("devcache.put")
def test_devcache_breaker_opens_then_half_open_probe_recovers():
    """Consecutive device-put failures open the breaker; while open,
    every mine takes the uncached HOST-PATH fallback (full parity, no
    device-put cost on the failing layer); after the cooldown a
    half-open probe closes it and caching resumes."""
    db = _db()
    minsup = abs_minsup(0.1, len(db))
    want = mine_spade(db, minsup)
    cache = SpadeEngineCache()
    cache.breaker = CircuitBreaker("chaos-devcache", threshold=2,
                                   cooldown_s=1.0)
    with faults.injected("devcache.put", every=1):
        # closed: failures propagate to job supervision and count
        for _ in range(2):
            with pytest.raises(faults.FaultInjected):
                cache.mine(db, minsup, stats_out={})
        assert cache.breaker.state() == "open"
        snap = cache.breaker.snapshot()
        assert snap["opens"] >= 1 and snap["failures"] >= 2
        # open: the host path serves the mine — parity, fault untouched
        stats: dict = {}
        got = _bounded(lambda: cache.mine(db, minsup, stats_out=stats))
        assert patterns_text(got) == patterns_text(want)
    assert cache.stats["breaker_fallbacks"] == 1
    # disarmed + cooled down: the half-open probe re-tries the cache,
    # succeeds, closes the breaker, and the NEXT mine is a cache hit
    time.sleep(1.05)
    stats = {}
    got = _bounded(lambda: cache.mine(db, minsup, stats_out=stats))
    assert patterns_text(got) == patterns_text(want)
    assert cache.breaker.state() == "closed"
    assert stats["store_cache_hit"] is False  # the probe built the entry
    stats = {}
    got = _bounded(lambda: cache.mine(db, minsup, stats_out=stats))
    assert patterns_text(got) == patterns_text(want)
    assert stats["store_cache_hit"] is True
    cache.clear()


def test_breaker_probe_expiry_recovers_from_dead_probe():
    """A half-open probe that never reports back (hung device, killed
    thread) must not wedge the breaker open forever: after another
    cooldown a NEW probe is allowed."""
    t = [0.0]
    br = CircuitBreaker("chaos-probe", threshold=1, cooldown_s=10.0,
                        clock=lambda: t[0])
    br.failure()
    assert br.state() == "open"
    t[0] = 10.0
    assert br.allow() is True    # the probe
    assert br.allow() is False   # concurrent callers keep falling back
    # the probe dies silently; one more cooldown re-arms probing
    t[0] = 20.0
    assert br.allow() is True
    br.success()
    assert br.state() == "closed"
    assert br.allow() is True


# ----------------------------------------------- consumer backoff + leaks


def test_consumer_error_backoff_grows_and_is_bounded():
    def fetch():
        raise RuntimeError("broker down")

    pc = PollConsumer(fetch, lambda b: None, poll_interval_s=0.01,
                      max_consecutive_errors=4, max_backoff_s=0.08)
    waits = []
    orig_wait = pc._stop.wait

    def spy_wait(t):
        waits.append(t)
        return orig_wait(0)

    pc._stop.wait = spy_wait
    stats = _bounded(lambda: pc.run(max_polls=10))
    assert stats["stopped"] == "errors" and stats["errors"] == 4
    # waits after errors 1..3 (error 4 trips the bound before waiting):
    # exponential growth, jitter only UPWARD (never undercuts the base
    # interval), hard-capped at max_backoff_s jitter included
    assert len(waits) == 3 and stats["backoff_waits"] == 3
    assert waits[0] >= 0.01  # never faster than the idle poll interval
    assert waits[0] < waits[-1] <= 0.08


def test_consumer_stop_counts_leaked_thread():
    release = threading.Event()

    def sink(batch):
        release.wait(20)

    pc = PollConsumer(lambda: parse_spmf("1 -2\n"), sink,
                      poll_interval_s=0)
    pc.start()
    deadline = time.time() + 10
    while pc.stats["polls"] < 1 and time.time() < deadline:
        time.sleep(0.01)
    base = consumer_health()["leaked_threads"]
    pc.stop(join_timeout_s=0.05)
    try:
        assert pc.stats["leaked_threads"] == 1
        assert consumer_health()["leaked_threads"] == base + 1
        # a second stop() on the SAME wedged thread counts nothing new
        pc.stop(join_timeout_s=0.05)
        assert pc.stats["leaked_threads"] == 1
        assert consumer_health()["leaked_threads"] == base + 1
    finally:
        release.set()  # let the wedged sink finish so the thread exits


# ------------------------------------------- admission + journal + deadline


def _submit_data(uid):
    return {"algorithm": "SPADE", "source": "INLINE",
            "sequences": "1 -1 2 -2\n1 -1 2 -2\n", "support": "1.0",
            "uid": uid}


@covers("service.admit")
def test_admit_fault_is_clean_synchronous_failure():
    """An injected admission failure surfaces as a clean failure
    envelope BEFORE any store write — no half-submitted job, no journal
    entry, no queue-slot leak (the disarmed resubmit runs normally)."""
    store = ResultStore()
    master = Master(store=store)
    try:
        with faults.injected("service.admit", nth=1):
            resp = master.handle(ServiceRequest(
                "fsm", "train", _submit_data("chaos-admit")))
        assert resp.status == "failure"
        assert "injected fault" in resp.data["error"]
        assert store.status("chaos-admit") is None
        assert store.journal_get("chaos-admit") is None
        # disarmed: the same submit admits and finishes
        uid, status = _bounded(lambda: _run_train(
            store, _submit_data("chaos-admit")))
        assert status == "finished", store.get(f"fsm:error:{uid}")
    finally:
        master.shutdown()


@covers("service.journal")
def test_journal_write_fault_fails_submit_without_slot_leak():
    """An injected journal-intent write failure rejects the submit
    cleanly (no stuck 'started' job) and RELEASES the reserved queue
    slot — proven by filling the queue to its exact bound afterwards."""
    from spark_fsm_tpu.service.actors import Miner

    store = ResultStore()
    miner = Miner(store, workers=1, queue_depth=2)
    try:
        with faults.injected("service.journal", nth=1):
            with pytest.raises(faults.FaultInjected):
                miner.submit(ServiceRequest(
                    "fsm", "train", _submit_data("chaos-journal")))
        assert store.status("chaos-journal") is None
        assert store.journal_get("chaos-journal") is None
        # the aborted submit must not have leaked its reservation (a
        # leak would permanently shrink the usable queue depth)
        assert miner._q._reserved == 0 and miner.queue_size() == 0
        # and disarmed submits admit + finish normally
        for i in range(2):
            miner.submit(ServiceRequest(
                "fsm", "train", _submit_data(f"chaos-fill{i}")))
        deadline = time.time() + 60
        while time.time() < deadline:
            if all(store.status(f"chaos-fill{i}") == "finished"
                   for i in range(2)):
                break
            time.sleep(0.02)
        for i in range(2):
            assert store.status(f"chaos-fill{i}") == "finished"
        # a submit that dies AFTER its journal intent landed (injected
        # status-write failure) must settle the intent on the way out —
        # a live-looking record would 409 every resubmit of the uid
        with faults.injected("store.set", nth=1,
                             match="fsm:status:chaos-late"):
            with pytest.raises(faults.FaultInjected):
                miner.submit(ServiceRequest(
                    "fsm", "train", _submit_data("chaos-late")))
        assert store.journal_get("chaos-late") is None
        miner.submit(ServiceRequest(  # no 409: the uid is free again
            "fsm", "train", _submit_data("chaos-late")))
        deadline = time.time() + 60
        while (store.status("chaos-late") != "finished"
               and time.time() < deadline):
            time.sleep(0.02)
        assert store.status("chaos-late") == "finished"
    finally:
        miner.shutdown()


def test_deadline_expiry_mid_mine_fails_fast_and_durable():
    """A deadline that expires BETWEEN device launches (the injected
    per-dispatch delay guarantees the first launch outlives it) aborts
    the mine at the next safe point: durable DEADLINE_EXCEEDED failure,
    no retry, the job-control entry released — never device time burned
    to completion, never a hang."""
    from spark_fsm_tpu.utils import jobctl

    db = _rule_db()
    store = ResultStore()
    with faults.injected("device.dispatch", every=1, delay_s=0.6,
                         exc="none", match="jnp"):
        uid, status = _bounded(lambda: _run_train(store, {
            "algorithm": "TSR_TPU", "source": "INLINE",
            "sequences": format_spmf(db), "k": "8", "minconf": "0.4",
            "max_side": "2", "deadline_s": "0.5", "retries": "3"}))
    assert status == "failure"
    err = store.get(f"fsm:error:{uid}") or ""
    assert err.startswith("DEADLINE_EXCEEDED"), err
    # terminal bookkeeping: journal settled, control entry gone, and the
    # abort did NOT consume the retry budget (jobs_retried untouched)
    assert store.journal_get(uid) is None
    assert jobctl.get(uid) is None
    assert int(store.get("fsm:metric:jobs_retried") or 0) == 0


# ------------------------------------------------------- fusion.dispatch


@covers("fusion.dispatch")
def test_fusion_dispatch_fault_degrades_group_to_solo_with_parity():
    """An injected broker failure at the fusion window DEGRADES to
    unfused per-job dispatch: both jobs finish with byte-identical rule
    sets and the degraded counter names the event — a wave is never
    lost (the ISSUE 6 failure posture for the whole broker)."""
    import threading

    from spark_fsm_tpu.models.tsr import TsrTPU
    from spark_fsm_tpu.service import fusion as FZ

    db_a, db_b = _rule_db(), synthetic_db(
        seed=29, n_sequences=40, n_items=7, mean_itemsets=3.0,
        mean_itemset_size=1.2)
    mk = lambda db: TsrTPU(build_vertical(db, min_item_support=1), 8,
                           0.4, max_side=2)
    want_a, want_b = mk(db_a).mine(), mk(db_b).mine()  # fusion off

    FZ.configure(cfgmod.FusionConfig(enabled=True, window_ms=250.0))
    b = FZ.broker()
    degraded0 = b.stats["degraded"]
    try:
        b.hold()
        out = {}
        ts = [threading.Thread(target=lambda k=k, db=db: out.setdefault(
            k, mk(db).mine())) for k, db in (("a", db_a), ("b", db_b))]
        with faults.injected("fusion.dispatch", nth=1, match="window"):
            for t in ts:
                t.start()
            deadline = time.time() + 60.0
            while b.pending() < 2 and time.time() < deadline:
                time.sleep(0.005)
            assert b.pending() >= 2
            b.release()
            for t in ts:
                t.join(120.0)
                assert not t.is_alive(), "degraded mine wedged"
    finally:
        b.release()
        assert b.drain(10.0)
        FZ.configure(None)
    assert rules_text(out["a"]) == rules_text(want_a)
    assert rules_text(out["b"]) == rules_text(want_b)
    assert b.stats["degraded"] > degraded0


@covers("fusion.dispatch")
def test_fusion_dispatch_fault_queue_wave_degrades_direct():
    """The queue engine's whole-mine wave routes through the broker's
    accounting surface only — an armed fusion.dispatch fault there must
    fall straight through to the direct dispatch with an identical
    pattern set (and count the degrade)."""
    from spark_fsm_tpu.models.spade_queue import QueueSpadeTPU
    from spark_fsm_tpu.service import fusion as FZ

    db = _db()
    vdb_want = build_vertical(db, min_item_support=6)
    want = QueueSpadeTPU(vdb_want, 6).mine()  # fusion off
    assert want is not None

    FZ.configure(cfgmod.FusionConfig(enabled=True))
    b = FZ.broker()
    degraded0 = b.stats["degraded"]
    try:
        with faults.injected("fusion.dispatch", nth=1, match="queue"):
            eng = QueueSpadeTPU(build_vertical(db, min_item_support=6), 6)
            got = _bounded(eng.mine)
    finally:
        FZ.configure(None)
    assert got is not None
    assert patterns_text(got) == patterns_text(want)
    assert b.stats["degraded"] > degraded0


@covers("device.dispatch")
def test_device_dispatch_fault_fires_on_fused_broker_path():
    """With fusion ON the broker's _execute IS the real jnp dispatch
    call site, so an armed device.dispatch drill must fire THERE (not
    vacuously pass because only the engine's direct path is guarded)
    and degrade to per-job dispatch with byte-identical rules."""
    import threading

    from spark_fsm_tpu.models.tsr import TsrTPU
    from spark_fsm_tpu.service import fusion as FZ

    db_a, db_b = _rule_db(), synthetic_db(
        seed=29, n_sequences=40, n_items=7, mean_itemsets=3.0,
        mean_itemset_size=1.2)
    mk = lambda db: TsrTPU(build_vertical(db, min_item_support=1), 8,
                           0.4, max_side=2)
    want_a, want_b = mk(db_a).mine(), mk(db_b).mine()  # fusion off

    FZ.configure(cfgmod.FusionConfig(enabled=True, window_ms=250.0))
    b = FZ.broker()
    fired0 = faults.counters().get("device.dispatch", {}).get("injected", 0)
    try:
        b.hold()
        out = {}
        ts = [threading.Thread(target=lambda k=k, db=db: out.setdefault(
            k, mk(db).mine())) for k, db in (("a", db_a), ("b", db_b))]
        with faults.injected("device.dispatch", nth=1, match="jnp"):
            for t in ts:
                t.start()
            deadline = time.time() + 60.0
            while b.pending() < 2 and time.time() < deadline:
                time.sleep(0.005)
            assert b.pending() >= 2
            b.release()
            for t in ts:
                t.join(120.0)
                assert not t.is_alive(), "degraded mine wedged"
    finally:
        b.release()
        assert b.drain(10.0)
        FZ.configure(None)
    assert faults.counters().get("device.dispatch", {}).get(
        "injected", 0) > fired0, \
        "drill was vacuous: no injection fired on the fused path"
    assert rules_text(out["a"]) == rules_text(want_a)
    assert rules_text(out["b"]) == rules_text(want_b)


# ------------------------------------------------------- admin endpoints


def _post_raw(port, endpoint, **params):
    import urllib.error
    import urllib.parse
    import urllib.request

    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{port}{endpoint}"
    try:
        with urllib.request.urlopen(url, data=data, timeout=30) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read().decode())


def test_admin_faults_gated_and_health_reports_subsystems():
    from spark_fsm_tpu.service.app import serve_background

    cfg0 = cfgmod.get_config()
    srv = serve_background()
    port = srv.server_port
    try:
        # default boot config: the chaos lab is REFUSED
        code, body = _post_raw(port, "/admin/faults", action="list")
        assert code == 403 and "fault injection disabled" in body["error"]

        # /admin/health is always on and names every subsystem
        code, health = _post_raw(port, "/admin/health")
        assert code == 200
        assert set(health) >= {"faults", "retry", "watchdog", "breakers",
                               "consumers", "jobs"}
        assert health["faults"]["enabled"] is False
        assert set(health["breakers"]) == {"store_cache", "cspade_cache",
                                           "tsr_cache"}
        assert "leaked_threads" in health["consumers"]
        assert "jobs_retried" in health["jobs"]

        # opted in at boot: arm/list/disarm round-trips
        cfg = cfgmod.Config()
        cfg.fault_injection = True
        cfgmod.set_config(cfg)
        code, body = _post_raw(port, "/admin/faults", action="arm",
                               site="store.get", nth="1",
                               match="chaos-admin")
        assert code == 200 and "store.get" in body["armed"]
        assert body["armed"]["store.get"]["nth"] == 1
        code, body = _post_raw(port, "/admin/faults", action="disarm",
                               site="store.get")
        assert code == 200 and body["armed"] == {}
        code, body = _post_raw(port, "/admin/faults", action="arm",
                               site="nope.nope", nth="1")
        assert code == 500 and "unknown fault site" in body["error"]
    finally:
        faults.disarm()
        cfgmod.set_config(cfg0)
        srv.master.shutdown()
        srv.shutdown()


# ------------------------------------------------------------- lease.*


def _lease_miner(store, rid, ttl=5.0, heartbeat_s=0.0, depth=8):
    from spark_fsm_tpu.service.actors import Miner
    from spark_fsm_tpu.service.lease import LeaseManager

    mgr = LeaseManager(store, replica_id=rid, lease_ttl_s=ttl,
                       heartbeat_s=heartbeat_s)
    return Miner(store, workers=1, queue_depth=depth, lease_mgr=mgr), mgr


@covers("lease.acquire")
def test_lease_acquire_fault_is_clean_503_with_zero_trace():
    """An injected lease-acquisition failure refuses the submit with a
    clean 503 envelope BEFORE any store write: no status, no journal,
    no lease key — and the disarmed resubmit admits and finishes."""
    from spark_fsm_tpu.service.lease import LeaseManager

    store = ResultStore()
    mgr = LeaseManager(store, replica_id="chaos-acq", lease_ttl_s=5.0,
                       heartbeat_s=0)
    master = Master(store=store, lease_mgr=mgr)
    try:
        with faults.injected("lease.acquire", nth=1):
            resp = master.handle(ServiceRequest(
                "fsm", "train", _submit_data("chaos-lease")))
        assert resp.status == "failure"
        assert resp.data["http_status"] == "503"
        assert "lease acquisition" in resp.data["error"]
        assert store.status("chaos-lease") is None
        assert store.journal_get("chaos-lease") is None
        assert store.peek("fsm:lease:chaos-lease") is None
        # no admission-slot leak, and the disarmed resubmit runs clean
        assert master.miner._q._reserved == 0
        resp = master.handle(ServiceRequest(
            "fsm", "train", _submit_data("chaos-lease")))
        assert resp.status == "started", resp.data
        deadline = time.time() + 60
        while (store.status("chaos-lease") != "finished"
               and time.time() < deadline):
            time.sleep(0.02)
        assert store.status("chaos-lease") == "finished"
    finally:
        master.shutdown()


@covers("lease.renew")
def test_lease_renew_fault_job_runs_until_ttl_then_self_fences():
    """Renewal failures are survivable while the TTL lives — the job
    KEEPS RUNNING — but once the TTL lapses un-renewed the heartbeat
    fences the job's control entry and it aborts at its next safe point
    with a durable terminal ``LEASE_LOST:`` failure (no retry, frontier
    kept, journal settled)."""
    from spark_fsm_tpu.service import sources
    from spark_fsm_tpu.utils import jobctl

    store = ResultStore()
    # REAL heartbeat cadence (ttl/3): the thread is the renewal path
    # under drill
    miner, mgr = _lease_miner(store, "chaos-renew", ttl=0.9,
                              heartbeat_s=None)
    gate = threading.Event()
    entered = threading.Event()
    real = sources.get_db

    def gated(req, store_):
        if req.uid == "chaos-held":
            entered.set()
            assert gate.wait(60), "gate never freed"
        return real(req, store_)

    sources.get_db = gated
    try:
        with faults.injected("lease.renew", every=1):
            miner.submit(ServiceRequest(
                "fsm", "train", _submit_data("chaos-held")))
            assert entered.wait(60)
            # the job RUNS while its renewals fail; once the TTL lapses
            # the heartbeat marks the control entry fenced
            ctl = jobctl.get("chaos-held")
            deadline = time.time() + 30
            while not ctl.lease_lost and time.time() < deadline:
                time.sleep(0.02)
            assert ctl.lease_lost, "heartbeat never fenced past-TTL job"
            gate.set()
            deadline = time.time() + 60
            while (store.status("chaos-held") != "failure"
                   and time.time() < deadline):
                time.sleep(0.02)
        assert store.status("chaos-held") == "failure"
        err = store.get("fsm:error:chaos-held") or ""
        assert err.startswith("LEASE_LOST"), err
        # terminal bookkeeping: settled durably (nobody adopted — the
        # settle path's atomic NX reacquire proved it), no retry burned
        assert store.journal_get("chaos-held") is None
        assert jobctl.get("chaos-held") is None
        assert int(store.get("fsm:metric:jobs_retried") or 0) == 0
        assert faults.counters()["lease.renew"]["injected"] >= 1
    finally:
        sources.get_db = real
        gate.set()
        miner.shutdown()


@covers("lease.steal")
def test_lease_steal_fault_leaves_job_with_victim():
    """An injected steal-claim failure aborts the theft cleanly: the
    admission marker and the victim's lease are untouched, the steal is
    counted as an error, and the job finishes ON THE VICTIM."""
    from spark_fsm_tpu.service import sources

    store = ResultStore()
    miner_a, mgr_a = _lease_miner(store, "chaos-victim", ttl=30.0)
    miner_b, mgr_b = _lease_miner(store, "chaos-thief", ttl=30.0)
    gate = threading.Event()
    entered = threading.Event()
    real = sources.get_db

    def gated(req, store_):
        if req.uid == "chaos-blocker":
            entered.set()
            assert gate.wait(60), "gate never freed"
        return real(req, store_)

    sources.get_db = gated
    try:
        miner_a.submit(ServiceRequest(
            "fsm", "train", _submit_data("chaos-blocker")))
        assert entered.wait(60)
        miner_a.submit(ServiceRequest(
            "fsm", "train", _submit_data("chaos-q1")))
        mgr_a.publish_heartbeat()
        with faults.injected("lease.steal", every=1):
            assert mgr_b.steal_once() == 0
        # nothing moved: marker intact, lease still the victim's, and
        # the failed attempt is visible in the counters
        assert store.keys("fsm:admission:chaos-victim:") == \
            ["fsm:admission:chaos-victim:chaos-q1"]
        assert json.loads(
            store.peek("fsm:lease:chaos-q1"))["replica"] == "chaos-victim"
        assert faults.counters()["lease.steal"]["injected"] >= 1
        gate.set()
        deadline = time.time() + 60
        while (store.status("chaos-q1") != "finished"
               and time.time() < deadline):
            time.sleep(0.02)
        assert store.status("chaos-q1") == "finished"  # victim ran it
        assert store.journal_uids() == []
    finally:
        sources.get_db = real
        gate.set()
        miner_a.shutdown()
        miner_b.shutdown()


# ---------------------------------------------------------- device.resident


def _resident_db():
    return synthetic_db(seed=29, n_sequences=90, n_items=9,
                        mean_itemsets=3.0, mean_itemset_size=1.2)


@covers("device.resident")
def test_resident_segment_fault_falls_back_to_host_with_parity():
    """A dispatch fault mid-km-ladder abandons the resident round to
    the classic host-driven path from its ORIGINAL state: the frontier
    regenerates exactly (roots or resume), nothing is lost, the rule
    set matches the fault-free run, and the fallback is counted."""
    from spark_fsm_tpu.models.tsr import mine_tsr_tpu

    db = _resident_db()
    want = mine_tsr_tpu(db, 20, 0.4, max_side=None, resident="never")
    s = {}
    with faults.injected("device.resident", nth=1, match="segment"):
        got = _bounded(lambda: mine_tsr_tpu(
            db, 20, 0.4, max_side=None, resident="always", stats_out=s))
    assert rules_text(got) == rules_text(want)
    assert s.get("resident_fallbacks", 0) == 1, s
    assert faults.counters()["device.resident"]["injected"] >= 1


@covers("device.resident")
def test_resident_records_readback_fault_falls_back_with_parity():
    """Same contract at the FINAL records readback: the round falls
    back to the host path instead of failing the job upward."""
    from spark_fsm_tpu.models.tsr import mine_tsr_tpu

    db = _resident_db()
    want = mine_tsr_tpu(db, 20, 0.4, max_side=None, resident="never")
    s = {}
    with faults.injected("device.resident", nth=1, match="records"):
        got = _bounded(lambda: mine_tsr_tpu(
            db, 20, 0.4, max_side=None, resident="always", stats_out=s))
    assert rules_text(got) == rules_text(want)
    assert s.get("resident_fallbacks", 0) == 1, s


@covers("device.resident")
def test_resident_kill_restart_resumes_persisted_frontier():
    """Kill-restart drill: a checkpointed resident mine persists
    segment-boundary frontier snapshots into the store; dying mid-round
    and rebooting a FRESH engine from StoreCheckpoint.load() RESUMES
    the persisted frontier (resumed_nodes > 0, still on the resident
    path) and finishes with exact parity — no lost candidates, no
    duplicated results."""
    from spark_fsm_tpu.models.tsr import mine_tsr_tpu

    # deep run-shaped DB: several resident segments, so a mid-round
    # snapshot has a live frontier
    rng = np.random.default_rng(37)
    db = [[[int(it)] for it in (list(range(8))
                                + rng.integers(8, 13, size=3).tolist())]
          for _ in range(40)]
    want = mine_tsr_tpu(db, 150, 0.3, max_side=None, resident="never")

    class Killed(Exception):
        pass

    store = ResultStore()
    ckpt = StoreCheckpoint(store, "chaos-resident", every_s=0.0)
    saves = []

    def cb(state):
        ckpt.save(state)
        saves.append(len(state["stack"]))
        if len(saves) == 2:
            raise Killed  # simulated process death AFTER persisting

    vdb = build_vertical(db, min_item_support=1)
    eng = TsrTPU(vdb, 150, 0.3, max_side=None, resident="always")
    with pytest.raises(Killed):
        _bounded(lambda: eng.mine(checkpoint_cb=cb,
                                  checkpoint_every_s=0.0))
    state = StoreCheckpoint(store, "chaos-resident", every_s=0.0).load()
    assert state is not None and state["stack"]

    eng2 = TsrTPU(build_vertical(db, min_item_support=1), 150, 0.3,
                  max_side=None, resident="always")
    got = _bounded(lambda: eng2.mine(resume=state))
    assert eng2.stats["resumed_nodes"] == len(state["stack"])
    assert eng2.stats.get("resident_rounds", 0) >= 1, eng2.stats
    assert rules_text(got) == rules_text(want)


# -------------------------------------------- result-reuse tier (ISSUE 12)


@covers("rescache.lookup")
def test_rescache_lookup_fault_degrades_to_cold_mine():
    """An injected failure in the reuse lookup must cost only the
    reuse: the request mines COLD with oracle parity, the submit never
    fails, and no uid is left live (zero stuck followers)."""
    old_cfg = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config({"rescache": {"enabled": True}}))
    try:
        db = _rule_db()
        data = {"algorithm": "TSR", "source": "INLINE",
                "sequences": format_spmf(db), "k": "5", "minconf": "0.4"}
        store = ResultStore()
        # prime the cache with one clean mine
        _, st = _bounded(lambda: _run_train(
            store, dict(data, uid="rcl-prime")))
        assert st == "finished"
        assert store.get("fsm:stats:rcl-prime") is not None
        with faults.injected("rescache.lookup", every=1):
            _, st = _bounded(lambda: _run_train(
                store, dict(data, uid="rcl-cold")))
        assert st == "finished"
        stats = json.loads(store.get("fsm:stats:rcl-cold"))
        # the lookup died, so the identical request mined cold ...
        assert "served_from_cache" not in stats
        # ... with byte-identical results and nothing left live
        assert store.rules("rcl-cold") == store.rules("rcl-prime")
        assert store.keys("fsm:journal:") == []
    finally:
        cfgmod.set_config(old_cfg)


@covers("rescache.store")
def test_rescache_store_fault_keeps_job_green():
    """An injected failure storing the cache entry (or learning the
    fingerprint) must leave the producing job GREEN — its results were
    already durable; only the reuse entry is lost, so the next
    identical request mines cold with parity."""
    old_cfg = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config({"rescache": {"enabled": True}}))
    try:
        db = _rule_db()
        data = {"algorithm": "TSR", "source": "INLINE",
                "sequences": format_spmf(db), "k": "5", "minconf": "0.4"}
        store = ResultStore()
        with faults.injected("rescache.store", every=1):
            _, st = _bounded(lambda: _run_train(
                store, dict(data, uid="rcs-a")))
        assert st == "finished"
        # the entry never landed: no rescache keys, and the repeat
        # request misses (mines cold) with identical output
        assert store.keys("fsm:rescache:") == []
        _, st = _bounded(lambda: _run_train(
            store, dict(data, uid="rcs-b")))
        assert st == "finished"
        assert store.rules("rcs-b") == store.rules("rcs-a")
        assert store.keys("fsm:journal:") == []
    finally:
        cfgmod.set_config(old_cfg)


@covers("storeguard.probe")
def test_storeguard_probe_fault_drives_down_then_recovers_clean():
    """An injected raise at the probe site IS a failed probe: it must
    drive the health machine to DOWN deterministically (writes spool,
    nothing lands), and disarming must heal — probe ok, spool replayed
    IN ORDER, state healthy, store exactly as if no outage happened."""
    from spark_fsm_tpu.service import storeguard as SG

    SG.uninstall()
    scfg = cfgmod.parse_config({"storeguard": {
        "enabled": True, "probe_every_s": 0, "down_after": 1}}).storeguard
    store = ResultStore()
    g = SG.StoreGuard(store, scfg=scfg)
    try:
        with faults.injected("storeguard.probe", every=1):
            assert g.probe_once() == "unreachable"
            assert g.state == SG.DOWN
            g.rpush("u1", "fsm:frontier:results:u1", "[1]")
            g.set("u1", "fsm:frontier:u1", '{"meta": 1}')
            assert store.peek("fsm:frontier:u1") is None
            assert g.spool_entries() == 2
            # probes keep failing while armed: still down, still spooled
            g.tick()
            assert g.state == SG.DOWN and g.spool_entries() == 2
        g.tick()  # disarmed: probe succeeds, spool replays in order
        assert g.state == SG.HEALTHY and g.drained()
        assert store.lrange("fsm:frontier:results:u1") == ["[1]"]
        assert store.peek("fsm:frontier:u1") == '{"meta": 1}'
    finally:
        SG.uninstall()


@covers("storeguard.replay")
def test_storeguard_replay_fault_degrades_terminal_never_corrupt():
    """Injection DURING spool replay must degrade to the current
    terminal-failure path — the job fences, its spool is dropped — and
    must NEVER leave a state a resume would accept as valid: the spool
    preserves delta-before-meta ordering, so an interrupted replay
    leaves either no meta (load refuses: fresh restart) or a healable
    torn tail, exactly the existing StoreCheckpoint contract."""
    from spark_fsm_tpu.service import storeguard as SG
    from spark_fsm_tpu.service.actors import StoreCheckpoint
    from spark_fsm_tpu.utils import jobctl

    SG.uninstall()
    scfg = cfgmod.parse_config({"storeguard": {
        "enabled": True, "probe_every_s": 0, "down_after": 1}}).storeguard
    store = ResultStore()
    g = SG.StoreGuard(store, scfg=scfg)
    ctl = jobctl.register("rpl-1")
    try:
        # drive DOWN deterministically via the probe site, then spool a
        # checkpoint-shaped write sequence (delta rpush, meta set LAST)
        with faults.injected("storeguard.probe", every=1):
            assert g.probe_once() == "unreachable"
        assert g.state == SG.DOWN
        g.rpush("rpl-1", "fsm:frontier:results:rpl-1", "[1, 2]")
        g.set("rpl-1", "fsm:frontier:rpl-1",
              json.dumps({"results_total": 2, "results_inline": [],
                          "stack": []}))
        assert g.spool_entries() == 2
        # the replay's SECOND write faults: the delta landed, the meta
        # did not — the spool is dropped, the job fenced
        with faults.injected("storeguard.replay", nth=2):
            g.tick()
        assert g.state == SG.HEALTHY and g.drained()
        assert ctl.lease_lost is True  # terminal at the next safe point
        assert store.peek("fsm:frontier:rpl-1") is None
        # never corrupt: a resume attempt REFUSES the metaless residue
        assert StoreCheckpoint(store, "rpl-1").load() is None
    finally:
        jobctl.release("rpl-1")
        SG.uninstall()


@covers("store.corrupt")
def test_bitrot_checkpoint_delta_heals_to_last_good_snapshot():
    """Bitrot on a checkpoint delta chunk (store.corrupt on the nth
    durable read): load() truncates to the last good snapshot embedded
    in the preceding chunk and RESUMES — the corruption costs only the
    work mined after that chunk, never a restart, never a torn resume
    (ISSUE 18)."""
    def scenario():
        store = ResultStore()
        ckpt = StoreCheckpoint(store, "rot-1", every_s=0.0)
        a, b, c = [[[[1]], 3]], [[[[1], [2]], 2]], [[[[2]], 2]]
        ckpt.save({"version": 1, "stack": [{"x": 1}], "results_done": 0,
                   "results": list(a)})
        ckpt.save({"version": 1, "stack": [{"x": 2}], "results_done": 1,
                   "results": list(b)})
        ckpt.save({"version": 1, "stack": [], "results_done": 2,
                   "results": list(c)})
        # nth=2 addresses the SECOND chunk of the lrange (byte-flip:
        # intact length, dead digest) — the newest delta rots at rest
        with faults.injected("store.corrupt", nth=2,
                             match="fsm:frontier:results:"):
            healed = ckpt.load()
        assert healed is not None, "corrupt delta must heal, not restart"
        assert healed["results"] == a + b  # truncated to chunk 1's snapshot
        assert healed["stack"] == [{"x": 2}]  # chunk 1's embedded frontier
        assert store.llen("fsm:frontier:results:rot-1") == 1
        # the damaged bytes are preserved for the post-mortem
        assert store.peek("fsm:quarantine:frontier:results:rot-1#1")
        # the heal is durable: a clean (disarmed) reload agrees
        again = ckpt.load()
        assert again["results"] == a + b
        # and the mine RESUMES: the next save extends the healed prefix
        ckpt.save({"version": 1, "stack": [], "results_done": 2,
                   "results": list(c)})
        assert ckpt.load()["results"] == a + b + c
    _bounded(scenario)


@covers("store.corrupt")
def test_bitrot_rescache_entry_quarantined_never_served():
    """Bitrot on a rescache entry (truncation this time): the verified
    read quarantines it and reports a miss — corrupt bytes are never
    served and never crash admission; the request falls through to a
    cold mine."""
    from spark_fsm_tpu.ops.rule_trie import rules_digest
    from spark_fsm_tpu.service import resultcache
    from spark_fsm_tpu.utils import envelope

    def scenario():
        store = ResultStore()
        payload = json.dumps([[[[1]], 5]])
        ent = json.dumps({"algo": "SPADE_TPU", "kind": "patterns",
                          "params": {}, "n_sequences": 10, "uid": "u-rot",
                          "digest": rules_digest(payload),
                          "ts": time.time(), "payload": payload})
        key = resultcache.entry_key("fp-rot", "SPADE_TPU")
        store.set(key, envelope.wrap(ent))
        resultcache.write_sidecar(store, key, json.loads(ent), len(ent))
        # sanity: the intact entry opens
        assert resultcache.open_entry(store, "fp-rot", "SPADE_TPU")
        # the next read rots (byte-flip: intact length, dead digest)
        with faults.injected("store.corrupt", nth=1,
                             match="fsm:rescache:"):
            assert resultcache.open_entry(
                store, "fp-rot", "SPADE_TPU") is None
        # quarantined + invalidated: entry AND sidecar gone, bytes kept
        assert store.peek(key) is None
        assert store.peek(resultcache.sidecar_key_for(key)) is None
        assert store.peek("fsm:quarantine:rescache:fp-rot:SPADE_TPU")
        # the miss is sticky-clean: a later (disarmed) lookup just misses
        assert resultcache.open_entry(store, "fp-rot", "SPADE_TPU") is None
    _bounded(scenario)
