"""Cluster observability plane (ISSUE 9): fenced trace spine, merged
cross-replica timelines, SLO accounting, cluster metric aggregation.

The satellite acceptance pins live here:

- FENCED TRACE WRITES: a split-brain stale holder's spine appends are
  refused, counted in ``fsm_lease_fence_rejections_total`` next to the
  prevented result double-commits, and the adopter's merged timeline
  contains no spans from the fenced epoch (tombstones block even
  post-settle stragglers);
- the merged timeline de-duplicates (replica, span_id) and orders by
  wall ts;
- SLO sliding-window quantiles are exact over a virtual clock;
- the cluster view aggregates heartbeat snapshots and the
  ``fsm_cluster_*`` collector exposes them as gauges.
"""

import json
import time

import pytest

from spark_fsm_tpu.service import obsplane
from spark_fsm_tpu.service.lease import LeaseManager
from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.utils import envelope, obs

DRILL_TIMEOUT_S = 120.0


@pytest.fixture(autouse=True)
def _plane_reset():
    """Leave no process-global plane/tracing/SLO state behind (the
    recorder, spine sink and sliding windows are all process-global)."""
    enabled0 = obs.tracing_enabled()
    yield
    obs.configure_tracing(enabled0, max_spans=512, max_jobs=16)
    obs.clear_traces()
    obsplane.uninstall()
    obsplane.clear_slo()


def _counter(name):
    snap = obs.REGISTRY.snapshot()[name]
    return sum(snap.values()) if isinstance(snap, dict) else snap


def _rig(ttl=10.0):
    t = [0.0]
    store = ResultStore(clock=lambda: t[0])
    mk = lambda rid: LeaseManager(store, replica_id=rid, lease_ttl_s=ttl,
                                  heartbeat_s=0, clock=lambda: t[0])
    return t, store, mk


def test_priority_vocabulary_matches_actors():
    from spark_fsm_tpu.service.actors import PRIORITIES

    assert obsplane.PRIORITIES == PRIORITIES


# ------------------------------------------------- fenced spine writes


def test_split_brain_spine_appends_are_fenced(tmp_path=None):
    """The satellite drill, hermetic: holder A flushes while live; B
    adopts after A's TTL; A's later flushes are REFUSED and counted;
    the merged timeline holds A's pre-fence spans + B's spans and
    NOTHING from A's fenced epoch."""
    t, store, mk = _rig(ttl=10.0)
    a, b = mk("rep-a"), mk("rep-b")
    plane_a = obsplane.TraceSpine(store, a)
    plane_b = obsplane.TraceSpine(store, b)

    a.acquire("drill")
    store.journal_set("drill", json.dumps({"replica": "rep-a"}))
    rejected0 = _counter("fsm_lease_fence_rejections_total")

    # live holder: the flush lands, tagged with A's token
    assert plane_a.flush("drill", [
        {"span_id": 1, "site": "lifecycle.admitted", "ts": 100.0},
        {"span_id": 2, "site": "queue.dispatch", "ts": 101.0}]) == "ok"

    # A sleeps through its TTL; B adopts (journal rewritten = adoption
    # semantics: the intent is B's now, so A cannot NX-reacquire)
    t[0] = 30.0
    store.journal_set("drill", json.dumps({"replica": "rep-b"}))
    assert b.adopt_expired("drill") is True

    # the stale epoch: A wakes and flushes — refused, counted, nothing
    # appended
    n_chunks = len(store.spine_chunks("drill"))
    assert plane_a.flush("drill", [
        {"span_id": 3, "site": "stale.mine", "ts": 130.0}]) == "fenced"
    assert len(store.spine_chunks("drill")) == n_chunks
    assert _counter("fsm_lease_fence_rejections_total") > rejected0

    # even after A's local settle forgets the lease, the tombstone
    # blocks the post-settle straggler flush
    a.forget("drill")
    assert plane_a.flush("drill", [
        {"span_id": 4, "site": "stale.settled", "ts": 131.0}]) == "fenced"

    # the adopter's flushes land under its (larger) token
    assert plane_b.flush("drill", [
        {"span_id": 1, "site": "lifecycle.adopted", "ts": 140.0},
        {"span_id": 2, "site": "job", "ts": 141.0}]) == "ok"

    merged = obsplane.merged_timeline(store, "drill")
    sites = [s["site"] for s in merged["spans"]]
    assert "lifecycle.admitted" in sites and "queue.dispatch" in sites
    assert "lifecycle.adopted" in sites and "job" in sites
    assert "stale.mine" not in sites and "stale.settled" not in sites
    assert merged["replicas"] == ["rep-a", "rep-b"]
    # ordered by wall ts, monotone
    ts = [s["ts"] for s in merged["spans"]]
    assert ts == sorted(ts)
    # B's spans carry B's strictly larger fencing token
    tok = {s["replica"]: s["token"] for s in merged["spans"]}
    assert tok["rep-b"] > tok["rep-a"]
    spine_counts = obs.REGISTRY.snapshot()["fsm_trace_spine_writes_total"]
    assert spine_counts["outcome=fenced"] >= 2
    assert spine_counts["outcome=ok"] >= 2


def test_spine_unleased_uid_writes_with_null_token():
    """Stream pushes and solo jobs never hold a lease: their flushes
    land with token null instead of being refused."""
    _, store, mk = _rig()
    plane = obsplane.TraceSpine(store, mk("rep-a"))
    assert plane.flush("stream:topic", [
        {"span_id": 9, "site": "stream.push", "ts": 1.0}]) == "ok"
    chunk = json.loads(envelope.unwrap(
        store.spine_chunks("stream:topic")[0])[0])
    assert chunk["token"] is None and chunk["replica"] == "rep-a"


def test_spine_retention_keeps_newest_chunks():
    _, store, mk = _rig()
    plane = obsplane.TraceSpine(store, mk("rep-a"), max_chunks=3)
    for i in range(7):
        assert plane.flush("u", [{"span_id": i, "site": "s",
                                  "ts": float(i)}]) == "ok"
    chunks = obsplane.spine_chunks(store, "u")
    assert len(chunks) == 3
    assert [c["spans"][0]["span_id"] for c in chunks] == [4, 5, 6]


def test_merged_timeline_dedupes_local_ring_against_spine():
    """The serving replica's local ring spans were themselves flushed:
    the merge must not show them twice."""
    _, store, mk = _rig()
    a = mk("rep-a")
    plane = obsplane.TraceSpine(store, a)
    spans = [{"span_id": 1, "site": "job.submit", "ts": 10.0},
             {"span_id": 2, "site": "job", "ts": 11.0}]
    assert plane.flush("u", spans) == "ok"
    local = {"trace_id": "u", "attrs": {"algorithm": "SPADE"},
             "dropped_spans": 0,
             "spans": spans + [{"span_id": 3, "site": "job.sink",
                                "ts": 12.0}]}
    merged = obsplane.merged_timeline(store, "u", local,
                                      replica_id="rep-a",
                                      boot_id=plane.boot_id)
    assert merged["n_spans"] == 3
    assert [s["span_id"] for s in merged["spans"]] == [1, 2, 3]
    assert merged["attrs"] == {"algorithm": "SPADE"}
    # a crash-RESTARTED incarnation re-counts span_ids from 1 under the
    # same (pinned) replica id: its distinct boot nonce keeps the merge
    # from swallowing the resumed spans as duplicates
    plane2 = obsplane.TraceSpine(store, a)  # fresh boot, same replica
    assert plane2.boot_id != plane.boot_id
    assert plane2.flush("u", [{"span_id": 1, "site": "job.resumed",
                               "ts": 20.0}]) == "ok"
    merged2 = obsplane.merged_timeline(store, "u")
    assert merged2["n_spans"] == 3  # 1,2 from boot 1 + 1 from boot 2
    assert "job.resumed" in [s["site"] for s in merged2["spans"]]


# ------------------------------------------------------------- SLO layer


def test_sliding_quantiles_window_and_exactness():
    t = [1000.0]
    sq = obs.SlidingQuantiles(window_s=60.0, max_samples=512,
                              clock=lambda: t[0])
    for i in range(100):
        sq.observe(i / 100.0, priority="high")
    s = sq.stats(priority="high")
    assert s["count"] == 100
    assert abs(s["p50"] - 0.5) < 0.02
    assert abs(s["p99"] - 0.98) < 0.02
    assert s["max"] == 0.99
    # outside the window everything ages out
    t[0] += 120.0
    assert sq.stats(priority="high") == {"count": 0}
    # a fresh burst only sees itself
    sq.observe(5.0, priority="high")
    assert sq.stats(priority="high")["count"] == 1
    assert sq.stats(priority="low") == {"count": 0}
    with pytest.raises(ValueError):
        obs.SlidingQuantiles(window_s=0)


def test_observe_job_feeds_histograms_and_slo_snapshot():
    obsplane.clear_slo()
    h0 = obs.REGISTRY.snapshot()["fsm_job_e2e_seconds"]
    key = "priority=high,tenant=default"
    obsplane.observe_job("high", 2.0, 0.5, 1.5)
    obsplane.observe_job("high", 4.0, 1.0, 3.0)
    snap = obsplane.slo_snapshot()
    row = snap["priorities"]["high"]
    assert row["e2e"]["count"] == 2 and row["e2e"]["p99"] == 4.0
    assert row["queue_wait"]["p50"] in (0.5, 1.0)
    assert row["exec"]["count"] == 2
    assert snap["priorities"]["low"]["e2e"] == {"count": 0}
    h1 = obs.REGISTRY.snapshot()["fsm_job_e2e_seconds"]
    assert h1[key]["count"] == h0[key]["count"] + 2
    # the label vocabulary is zero-seeded: 'low' scrapes as count 0,
    # not no-data (the no-orphan-series posture) — with the tenant
    # label riding along (ISSUE 14 satellite)
    assert "priority=low,tenant=default" in h1
    text = obs.REGISTRY.render_prometheus()
    assert 'fsm_job_time_to_adoption_seconds_count 0' in text \
        or 'fsm_job_time_to_adoption_seconds_count' in text


def test_tenant_label_and_per_tenant_slo_quantiles():
    """ISSUE 14 satellite: fsm_job_e2e_seconds carries a tenant label
    with a zero-seeded, BOUNDED vocabulary (fairness-registered
    tenants), and /admin/slo serves per-tenant e2e quantiles."""
    obsplane.clear_slo()
    obsplane.seed_tenant("gold")
    h = obs.REGISTRY.snapshot()["fsm_job_e2e_seconds"]
    for p in obsplane.PRIORITIES:
        assert f"priority={p},tenant=gold" in h  # seeded at 0
    obsplane.observe_job("normal", 3.0, 1.0, 2.0, tenant="gold")
    # an UNREGISTERED tenant folds into "default" — the label
    # cardinality stays bounded no matter what requests claim
    obsplane.observe_job("normal", 9.0, 1.0, 8.0, tenant="nope")
    h = obs.REGISTRY.snapshot()["fsm_job_e2e_seconds"]
    assert h["priority=normal,tenant=gold"]["count"] >= 1
    assert not any(",tenant=nope" in k for k in h)
    snap = obsplane.slo_snapshot()
    assert snap["tenants"]["gold"]["count"] == 1
    assert snap["tenants"]["gold"]["p99"] == 3.0
    assert snap["tenants"]["default"]["count"] == 1
    obsplane.clear_slo()


def test_slo_digest_compact_and_heartbeat_merge_shape():
    """The heartbeat's compact SLO digest: worst per-priority e2e p99
    + sample count; None/0 on an empty window."""
    obsplane.clear_slo()
    assert obsplane.slo_digest() == {"p99": None, "n": 0}
    obsplane.observe_job("high", 1.0, 0.1, 0.9)
    obsplane.observe_job("low", 7.0, 0.1, 6.9)
    d = obsplane.slo_digest()
    assert d["n"] == 2 and d["p99"] == 7.0  # the WORST priority's p99
    obsplane.clear_slo()


def test_adoption_and_steal_histograms_seeded_and_observable():
    before = obs.REGISTRY.snapshot()["fsm_job_time_to_adoption_seconds"]
    obsplane.observe_adoption(2.5)
    obsplane.observe_steal_latency(0.4)
    after = obs.REGISTRY.snapshot()
    assert after["fsm_job_time_to_adoption_seconds"]["all"]["count"] \
        == before["all"]["count"] + 1
    assert after["fsm_job_steal_latency_seconds"]["all"]["count"] >= 1


# ------------------------------------------------------- cluster plane


class _FakeMiner:
    def __init__(self, queued=0, running=0, workers=2, sheds=0.0,
                 ewma=None):
        self._q, self._r, self._w = queued, running, workers
        self._sheds, self._ewma = sheds, ewma

    def queue_size(self):
        return self._q

    def running_count(self):
        return self._r

    def worker_count(self):
        return self._w

    def idle_capacity(self):
        return max(0, self._w - self._r - self._q)

    def sheds_total(self):
        return self._sheds

    def wall_ewma(self):
        return self._ewma


def test_cluster_view_aggregates_heartbeat_snapshots():
    t, store, mk = _rig(ttl=10.0)
    a, b = mk("rep-a"), mk("rep-b")
    a._miner = _FakeMiner(queued=3, running=1, workers=2, sheds=5,
                          ewma=0.8)
    b._miner = _FakeMiner(queued=0, running=0, workers=4)
    b.acquire("held-job")
    a.publish_heartbeat()
    b.publish_heartbeat()
    view = a.cluster_view(max_age_s=0)  # 0 = always fresh scan
    assert view["totals"]["replicas"] == 2
    assert view["totals"]["queued"] == 3
    assert view["totals"]["running"] == 1
    assert view["totals"]["free"] == 4  # B's 4 idle workers
    assert view["totals"]["held"] == 1
    assert view["totals"]["sheds"] == 5
    assert view["totals"]["lease_churn"] >= 1  # B's acquire
    rows = {r["replica"]: r for r in view["replicas"]}
    assert rows["rep-a"]["self"] is True
    assert rows["rep-b"]["held"] == 1
    # the collector exposes the same totals as gauges
    fams = {name: rows_ for name, kind, help, rows_
            in obsplane._cluster_collector(a)()}
    assert fams["fsm_cluster_replicas"][0][1] == 2.0
    assert fams["fsm_cluster_queue_depth"][0][1] == 3.0
    assert fams["fsm_cluster_in_flight"][0][1] == 1.0
    assert fams["fsm_cluster_leases_held"][0][1] == 1.0
    # a dead replica's row ages out with its heartbeat record
    t[0] = 30.0
    view = b.cluster_view(max_age_s=0)
    assert view["totals"]["replicas"] == 1
    # shed_view: the compact 429 body
    sv = b.shed_view()
    assert sv["replicas"] == 1 and "peer_free" in sv


def test_shed_view_reports_peer_free_capacity():
    t, store, mk = _rig()
    a, b = mk("rep-a"), mk("rep-b")
    b._miner = _FakeMiner(workers=4)
    b.publish_heartbeat()
    a._peers_cache = (-1e18, [])  # force a fresh scan through the cache
    sv = a.shed_view()
    assert sv == {"replica": "rep-a", "replicas": 2, "peer_free": 4,
                  "peer_queued": 0}


# --------------------------------------------- end-to-end (solo cluster)


def test_miner_writes_lifecycle_spine_and_slo_end_to_end():
    """A cluster-mode Miner with tracing on: the job's lifecycle marks
    land on the durable spine through the fenced path, the merged
    timeline de-duplicates ring vs spine, and the SLO layer observes
    the finish — the obs_smoke story at test scale."""
    from spark_fsm_tpu.service.actors import Miner

    obs.configure_tracing(True, max_spans=512, max_jobs=8)
    obsplane.clear_slo()
    store = ResultStore()
    mgr = LeaseManager(store, replica_id="solo1", lease_ttl_s=30,
                       heartbeat_s=0)
    miner = Miner(store, workers=1, queue_depth=8, lease_mgr=mgr)
    try:
        miner.submit(ServiceRequest("fsm", "train", {
            "algorithm": "SPADE", "source": "INLINE",
            "sequences": "1 -1 2 -2\n1 -1 2 -2\n", "support": "1.0",
            "uid": "solo-job", "priority": "high"}))
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline:
            if store.status("solo-job") in ("finished", "failure"):
                break
            time.sleep(0.01)
        assert store.status("solo-job") == "finished", \
            store.get("fsm:error:solo-job")
        # give the post-release root-span flush a beat
        deadline = time.time() + 10.0
        while time.time() < deadline:
            chunks = obsplane.spine_chunks(store, "solo-job")
            sites = {s["site"] for c in chunks for s in c["spans"]}
            if "job" in sites:
                break
            time.sleep(0.01)
        assert chunks, "no spine chunks written"
        for want in ("job.submit", "lifecycle.admitted",
                     "lifecycle.started", "lifecycle.settled", "job"):
            assert want in sites, (want, sorted(sites))
        # every non-final chunk was written under the held lease's token
        tokens = [json.loads(envelope.unwrap(raw)[0])["token"]
                  for raw in store.spine_chunks("solo-job")]
        assert tokens[0] is not None
        merged = obsplane.merged_timeline(
            store, "solo-job", obs.trace_dump("solo-job"),
            replica_id="solo1", boot_id=obsplane.plane().boot_id)
        ids = [(s["replica"], s["span_id"]) for s in merged["spans"]]
        assert len(ids) == len(set(ids)), "merge duplicated spans"
        ts = [s["ts"] for s in merged["spans"]]
        assert ts == sorted(ts)
        snap = obsplane.slo_snapshot()["priorities"]["high"]
        assert snap["e2e"]["count"] >= 1
        assert snap["queue_wait"]["count"] >= 1
    finally:
        miner.shutdown()


def test_no_spine_flush_without_install():
    """Solo default: no plane installed — tracing works, nothing is
    buffered for a spine, flush_trace is a no-op global read."""
    obsplane.uninstall()
    obs.configure_tracing(True, max_spans=16, max_jobs=4)
    with obs.trace("plain-job"):
        with obs.span("step"):
            pass
    obs.flush_trace("plain-job")
    assert obs._recorder.take_pending("plain-job") == []
    assert obs.trace_dump("plain-job")["n_spans"] == 2
