"""Cross-job launch fusion (service/fusion.py, ISSUE 6).

Covers the tentpole's contracts at three altitudes:

- **broker unit** (synthetic waves, no device): bounded-window policy —
  a ``high`` wave never waits out the window behind low fill, the
  window closes on ``max_jobs``/``max_width``, the calibrated cost
  model refuses unprofitable groups (and the refused group still
  dispatches per-job, correctly);
- **engine parity** (real TSR mines): two concurrent jobs lined up in a
  held window fuse into shared launches and their rule sets are
  byte-identical to solo (fusion-off) runs AND to the brute-force
  oracle — the positional-demux correctness claim of docs/DESIGN.md;
- **service**: two /train jobs through a 2-worker Miner with fusion on
  finish with cross-job launches recorded in the /admin/stats block,
  and the DISABLED path is one module-global read (same pin as the
  fault registry and flight recorder).
"""

import threading
import time

import numpy as np
import pytest

from spark_fsm_tpu import config as cfgmod
from spark_fsm_tpu.data.spmf import format_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import build_vertical
from spark_fsm_tpu.models.tsr import TsrTPU, brute_force_rules
from spark_fsm_tpu.service import fusion as FZ
from spark_fsm_tpu.service.actors import Master
from spark_fsm_tpu.service.model import ServiceRequest, deserialize_rules
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.utils import jobctl
from spark_fsm_tpu.utils.canonical import rules_text

DEADLINE_S = 60.0


@pytest.fixture(autouse=True)
def _fusion_hygiene():
    """No broker policy leaks in or out of any test (the engines probe
    module globals, so a leaked enable would silently reroute every
    later TSR dispatch in the session)."""
    FZ.configure(None)
    yield
    b = FZ.broker()
    if b is not None:
        b.release()
        assert b.drain(10.0), "fusion broker still busy at test exit"
    FZ.configure(None)


def _enable(**kw):
    cfg = cfgmod.FusionConfig(enabled=True, **kw)
    FZ.configure(cfg)
    return FZ.broker()


# ------------------------------------------------------- synthetic waves
#
# Broker-level tests use table-lookup eval fns instead of device
# programs: p1/s1 are [m, 1] uint32 tables whose rows carry distinctive
# per-job values, and the eval returns each lane's gathered sums — so a
# demux error (a lane resolved to the wrong job) changes the numbers,
# exactly like a real support readback, with zero compile cost.


def _table_eval(km):
    def fn(p1, s1, xy):
        t = np.asarray(p1)[:, 0].astype(np.int64)
        s = np.asarray(s1)[:, 0].astype(np.int64)
        xyn = np.asarray(xy)
        xs = np.where(xyn[:, 0] >= 0, t[np.maximum(xyn[:, 0], 0)], 0)
        ys = np.where(xyn[:, 1] >= 0, s[np.maximum(xyn[:, 1], 0)], 0)
        return np.stack([xs.sum(axis=1), ys.sum(axis=1)])
    return fn


def _wave(uid, *, base, m=8, cands=None, priority="normal", n_seq=64):
    p1 = (np.arange(m, dtype=np.uint32)[:, None] + np.uint32(base))
    s1 = p1 + np.uint32(100_000)
    cands = cands if cands is not None else [((0,), (1,)), ((2, 3), (4,))]
    pools = {}
    for r, (x, y) in enumerate(cands):
        side = max(len(x), len(y))
        km = 1
        while km < side:
            km *= 2
        pools.setdefault(km, []).append(r)
    return FZ.EvalWave(uid=uid, priority=priority, cands=cands,
                       pools=pools, p1=p1, s1=s1, eval_fn=_table_eval,
                       put=lambda x: x, cap=lambda km: 8192, lane=32,
                       n_seq=n_seq, n_words=1)


def _expect(wave):
    t = wave.p1[:, 0].astype(np.int64)
    s = wave.s1[:, 0].astype(np.int64)
    sups = [sum(int(t[i]) for i in x) for x, _ in wave.cands]
    supxs = [sum(int(s[j]) for j in y) for _, y in wave.cands]
    return sups, supxs


def _check(wave):
    sups, supxs, report = wave.result()
    want_sup, want_supx = _expect(wave)
    assert sups.tolist() == want_sup
    assert supxs.tolist() == want_supx
    return report


# ------------------------------------------------------------ broker unit


def test_fused_group_demuxes_per_job():
    # NOTE on windows under hold(): the group's window clock starts at
    # first submit and keeps ticking while held, so held tests use a
    # SHORT window — release() then launches at (or just after) expiry
    b = FZ.FusionBroker(window_s=0.25, max_jobs=8, max_width=16384)
    b.hold()
    w1 = _wave("job-a", base=1)
    w2 = _wave("job-b", base=1000,
               cands=[((1,), (0,)), ((4,), (2, 5)), ((6, 7), (3,))])
    b.submit(w1)
    b.submit(w2)
    assert b.pending() == 2
    b.release()
    r1, r2 = _check(w1), _check(w2)
    # distinct preps, tiny m: fusing two underfilled waves beats two
    # dispatches, so the group fused into cross-job launches
    assert r1["fused_jobs"] == 2 and r2["fused_jobs"] == 2
    assert r1["cross_job_launches"] >= 1
    assert b.stats["fused_groups"] == 1
    assert b.stats["cross_job_launches"] >= 1


def test_high_priority_never_waits_out_the_window():
    b = FZ.FusionBroker(window_s=30.0, max_jobs=8, max_width=16384)
    lo = _wave("job-lo", base=1, priority="low")
    b.submit(lo)
    time.sleep(0.25)
    assert not lo.done, "a lone low wave must wait for the window"
    t0 = time.monotonic()
    hi = _wave("job-hi", base=500, priority="high")
    b.submit(hi)
    _check(hi)
    _check(lo)
    # the high wave closed the 30 s window immediately — and took the
    # pending low fill with it instead of leaving it behind
    assert time.monotonic() - t0 < 10.0
    assert b.stats["waves"] == 2


def test_window_closes_on_max_jobs_and_width():
    b = FZ.FusionBroker(window_s=30.0, max_jobs=2, max_width=16384)
    t0 = time.monotonic()
    b.submit(_wave("a", base=1))
    w2 = _wave("b", base=100)
    b.submit(w2)
    _check(w2)  # 2 waves == max_jobs: due immediately
    assert time.monotonic() - t0 < 10.0

    b2 = FZ.FusionBroker(window_s=30.0, max_jobs=8, max_width=64)
    t0 = time.monotonic()
    wide = _wave("c", base=1, m=256,
                 cands=[((i,), (i + 1,)) for i in range(0, 128, 2)])
    b2.submit(wide)
    _check(wide)  # 64 pending lanes >= max_width 64: due immediately
    assert time.monotonic() - t0 < 10.0


def test_cost_model_rejects_unprofitable_group():
    # two tiny candidate sets over LARGE distinct preps at the full
    # Kosarak sequence axis (where a saved dispatch is worth only ~64
    # lane units): the fused plan saves one dispatch but pays a prep
    # concat priced far above it — the broker must dispatch per-job
    # (still inside the window run)
    b = FZ.FusionBroker(window_s=0.25, max_jobs=8, max_width=16384)
    b.hold()
    w1 = _wave("big-a", base=1, m=8192, n_seq=990_000)
    w2 = _wave("big-b", base=7, m=8192, n_seq=990_000)
    b.submit(w1)
    b.submit(w2)
    b.release()
    r1, r2 = _check(w1), _check(w2)
    assert r1["fused_jobs"] == 1 and r2["fused_jobs"] == 1
    assert b.stats["rejected_groups"] == 1
    assert b.stats["fused_groups"] == 0
    assert b.stats["solo_waves"] == 2


def test_intra_job_waves_fuse_without_cross_job_label():
    # one job's pipelined waves share a prep AND a uid: they fuse (free
    # — no concat), but the launch must NOT read as cross-job
    b = FZ.FusionBroker(window_s=0.25, max_jobs=8, max_width=16384)
    b.hold()
    w1 = _wave("job-a", base=1)
    w2 = _wave("job-a", base=999,
               cands=[((5,), (6,))])  # base ignored: same-uid test keeps
    w2.p1, w2.s1 = w1.p1, w1.s1       # the SHARED prep of a real pipeline
    b.submit(w1)
    b.submit(w2)
    b.release()
    _check(w1)
    r2 = _check(w2)
    assert r2["fused_jobs"] == 2  # two waves co-planned...
    assert r2["cross_job_launches"] == 0  # ...but one job, one tag
    assert b.stats["cross_job_launches"] == 0


# ---------------------------------------------------------- engine parity


def _mk_db(seed):
    return synthetic_db(seed=seed, n_sequences=60, n_items=8,
                        mean_itemsets=3.0, mean_itemset_size=1.2)


def _mine(db, *, uid=None, stats=None, pipeline=None):
    eng = TsrTPU(build_vertical(db, min_item_support=1), 6, 0.4,
                 max_side=2)
    if pipeline is not None:
        eng.PIPELINE_DEPTH = pipeline  # instance override (tests only)
    if uid is None:
        rules = eng.mine()
    else:
        try:
            with jobctl.activate(jobctl.register(uid)):
                rules = eng.mine()
        finally:
            jobctl.release(uid)
    if stats is not None:
        stats.update(eng.stats)
    return rules


def test_cross_job_fused_parity_oracle():
    """THE tentpole contract: two concurrent jobs lined up in one held
    window fuse into shared launches, and each job's rule set is
    byte-identical to its solo run and to the brute-force oracle."""
    db_a, db_b = _mk_db(31), _mk_db(47)
    solo_a, solo_b = _mine(db_a), _mine(db_b)

    b = _enable(window_ms=200.0, max_jobs=8, max_width=16384)
    b.hold()
    out, stats = {}, {"a": {}, "b": {}}
    run = lambda k, db: out.setdefault(
        k, _mine(db, uid=f"job-{k}", stats=stats[k]))
    ts = [threading.Thread(target=run, args=("a", db_a)),
          threading.Thread(target=run, args=("b", db_b))]
    for t in ts:
        t.start()
    deadline = time.monotonic() + DEADLINE_S
    while b.pending() < 2 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert b.pending() >= 2, "both jobs' first waves should be in window"
    b.release()
    for t in ts:
        t.join(DEADLINE_S)
        assert not t.is_alive(), "fused mine did not finish"

    assert rules_text(out["a"]) == rules_text(solo_a)
    assert rules_text(out["b"]) == rules_text(solo_b)
    assert rules_text(solo_a) == rules_text(
        brute_force_rules(db_a, 6, 0.4, max_side=2))
    assert b.stats["cross_job_launches"] >= 1
    assert stats["a"].get("fusion_waves", 0) >= 1
    assert stats["b"].get("fusion_waves", 0) >= 1
    # launches the engines did NOT dispatch themselves: fused mines
    # count their broker waves, not the shared device launches
    assert stats["a"].get("fusion_fused_waves", 0) >= 1


def test_lone_wave_dispatches_like_direct_path():
    """A wave with no fusion peer must produce the same rule set and
    the same launch SHAPES the direct path plans (same packer, same
    caps) — fusion never penalizes an unfused job's plan."""
    db = _mk_db(53)
    stats_direct = {}
    eng = TsrTPU(build_vertical(db, min_item_support=1), 6, 0.4,
                 max_side=2)
    direct = eng.mine()
    stats_direct = eng.stats

    _enable(window_ms=1.0, max_jobs=8, max_width=16384)
    stats_fused = {}
    # pipeline depth 1 so each wave resolves before the next dispatches
    # — every wave is provably ALONE in its window, the exact "no
    # fusion peer" case under test
    fused = _mine(db, uid="lone", stats=stats_fused, pipeline=1)
    assert rules_text(fused) == rules_text(direct)
    # every dispatch became one solo broker wave planning the same
    # launch count the direct path did
    assert stats_fused["fusion_launches"] == stats_direct[
        "kernel_launches"] - 1  # minus the direct path's prep launch
    assert stats_fused.get("fusion_fused_waves", 0) == 0


# --------------------------------------------------------------- service


def test_service_cross_job_fusion_stats_and_parity():
    db_a, db_b = _mk_db(61), _mk_db(67)
    want_a, want_b = _mine(db_a), _mine(db_b)
    store = ResultStore()
    b = _enable(window_ms=250.0, max_jobs=8, max_width=16384)
    master = Master(store=store, miner_workers=2)
    try:
        b.hold()
        uids = {}
        for k, db in (("a", db_a), ("b", db_b)):
            resp = master.handle(ServiceRequest("fsm", "train", {
                "algorithm": "TSR_TPU", "source": "INLINE",
                "sequences": format_spmf(db), "k": "6", "minconf": "0.4",
                "max_side": "2", "priority": "normal"}))
            assert resp.status != "failure", resp.data
            uids[k] = resp.data["uid"]
        deadline = time.monotonic() + DEADLINE_S
        while b.pending() < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert b.pending() >= 2
        b.release()
        deadline = time.monotonic() + DEADLINE_S
        while time.monotonic() < deadline:
            if all(store.status(u) in ("finished", "failure")
                   for u in uids.values()):
                break
            time.sleep(0.02)
        assert store.status(uids["a"]) == "finished"
        assert store.status(uids["b"]) == "finished"
        got_a = deserialize_rules(store.rules(uids["a"]))
        got_b = deserialize_rules(store.rules(uids["b"]))
        assert rules_text(got_a) == rules_text(want_a)
        assert rules_text(got_b) == rules_text(want_b)
        assert b.stats["cross_job_launches"] >= 1
        from spark_fsm_tpu.service.app import _fusion_stats

        fs = _fusion_stats()
        assert fs["enabled"] and fs["cross_job_launches"] >= 1
    finally:
        master.shutdown()


def test_disabled_path_is_one_global_read():
    """Fusion off (the default): the engine probes return after one
    module-global read — no broker, no wave, no counter touched — and
    dispatch_wave passes the callable straight through."""
    assert not FZ.eval_enabled()
    assert FZ.submit_eval(cands=[], pools={}, p1=None, s1=None,
                          eval_fn=None, put=None, cap=None, lane=32,
                          n_seq=64, n_words=1) is None
    b = FZ.broker()
    before = dict(b.stats) if b is not None else None
    assert FZ.dispatch_wave("queue", lambda: 41 + 1) == 42
    if b is not None:
        assert b.stats == before
    # and a real mine's stats carry no fusion_* keys at all
    db = _mk_db(71)
    eng = TsrTPU(build_vertical(db, min_item_support=1), 6, 0.4,
                 max_side=2)
    eng.mine()
    assert not any(k.startswith("fusion") for k in eng.stats)


def test_resident_dispatch_bypasses_fusion_window():
    """Resident-frontier TSR dispatches (ops/resident_frontier.py) route
    through ``dispatch_wave`` for the broker's accounting/fault surface
    but must NEVER enter a fusion window: a single long-lived while_loop
    dispatch waiting for window fill would stall the mine for the whole
    window (and holding a window open would stall its riders).  With the
    broker enabled and a LONG window, a resident mine must finish far
    inside the window wall, count only solo waves (no fused groups),
    and keep exact parity with the fusion-off run."""
    db = synthetic_db(seed=61, n_sequences=90, n_items=9,
                      mean_itemsets=3.0, mean_itemset_size=1.2)
    from spark_fsm_tpu.models.tsr import mine_tsr_tpu

    want = mine_tsr_tpu(db, 20, 0.4, max_side=None, resident="never")
    b = _enable(window_ms=30_000.0, max_jobs=8, max_width=16384)
    # the broker is a process-global singleton whose stats accumulate
    # across tests: assert DELTAS over this mine only
    before = dict(b.stats)
    s = {}
    t0 = time.monotonic()
    got = mine_tsr_tpu(db, 20, 0.4, max_side=None, resident="always",
                       stats_out=s)
    wall = time.monotonic() - t0
    delta = {k: b.stats.get(k, 0) - before.get(k, 0)
             for k in set(b.stats) | set(before)}
    assert rules_text(got) == rules_text(want)
    assert s.get("resident") is True, s
    assert wall < 25.0, f"resident mine waited on the fusion window: {wall}"
    assert delta["solo_waves"] >= 1, delta
    assert delta["fused_groups"] == 0, delta
    assert delta["cross_job_launches"] == 0, delta
