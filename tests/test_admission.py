"""Overload, deadline/cancel, and crash-restart recovery drills (ISSUE 5).

The acceptance drills run in-process against the real Master/Miner with
a deterministically BLOCKED worker (sources.get_db monkeypatched to gate
on an Event), so queue occupancy is exact — no sleep-and-hope:

- overload: flooding ``queue_depth + k`` submits sheds exactly ``k``
  with AdmissionShed/HTTP 429 + Retry-After, zero store writes for the
  shed uids, and the queue-depth gauge returns to 0;
- priority classes drain high -> normal -> low;
- resubmitting a live uid is a 409 conflict, never a state wipe;
- a deadline spent entirely on queue wait aborts the job durably
  (DEADLINE_EXCEEDED) before the dataset is ever built; /admin/cancel
  aborts a queued or running job the same way (CANCELLED);
- shutdown drain under a FULL queue: every backlog job gets a durable
  failure + a cleared journal entry, sheds during the drain still 429;
- kill-restart: a checkpointed mine killed between frontier saves is
  resubmitted by the boot recovery pass and finishes with the exact
  oracle pattern set (zero duplicated results); a non-checkpointed
  orphan lands in a durable "interrupted by restart" failure.
"""

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from spark_fsm_tpu.data.spmf import format_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.service import sources
from spark_fsm_tpu.service.actors import (AdmissionShed, Master, Miner,
                                          StoreCheckpoint, UidConflict,
                                          recover_orphans)
from spark_fsm_tpu.service.model import ServiceRequest, deserialize_patterns
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.utils import jobctl
from spark_fsm_tpu.utils.canonical import patterns_text

DRILL_TIMEOUT_S = 120.0


def _req(uid, **extra):
    data = {"algorithm": "SPADE", "source": "INLINE",
            "sequences": "1 -1 2 -2\n1 -1 2 -2\n", "support": "1.0",
            "uid": uid}
    data.update(extra)
    return ServiceRequest("fsm", "train", data)


class _Gate:
    """Deterministic worker occupancy: get_db blocks for chosen uids
    until released; every uid that reaches get_db is recorded in order."""

    def __init__(self, monkeypatch, block_uids=()):
        self.release = threading.Event()
        self.entered = threading.Event()
        self.block_uids = set(block_uids)
        self.run_order = []
        real = sources.get_db

        def gated(req, store):
            self.run_order.append(req.uid)
            if req.uid in self.block_uids:
                self.entered.set()
                assert self.release.wait(DRILL_TIMEOUT_S), "gate never freed"
            return real(req, store)

        monkeypatch.setattr(sources, "get_db", gated)


def _await_terminal(store, uid, timeout=DRILL_TIMEOUT_S):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = store.status(uid)
        if st in ("finished", "failure"):
            return st
        time.sleep(0.01)
    raise TimeoutError(f"job {uid} reached no terminal status "
                       f"(now {store.status(uid)!r})")


def _gauge(name):
    return __import__("spark_fsm_tpu.utils.obs",
                      fromlist=["REGISTRY"]).REGISTRY.snapshot()[name]


# ----------------------------------------------------------------- overload


def test_flood_sheds_exactly_k_with_retry_after(monkeypatch):
    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"blocker"})
    miner = Miner(store, workers=1, queue_depth=2)
    try:
        miner.submit(_req("blocker"))
        assert gate.entered.wait(DRILL_TIMEOUT_S)  # worker occupied
        miner.submit(_req("q1"))
        miner.submit(_req("q2"))
        assert miner.queue_size() == 2
        assert _gauge("fsm_service_queue_depth") == 2
        sheds = []
        for i in range(3):
            with pytest.raises(AdmissionShed) as err:
                miner.submit(_req(f"shed{i}"))
            sheds.append(err.value)
        # Retry-After sanity: a positive bounded integer seconds hint
        assert all(1 <= s.retry_after_s <= 3600 for s in sheds)
        # a shed leaves ZERO trace of the uid — no status, no journal
        for i in range(3):
            assert store.status(f"shed{i}") is None
            assert store.journal_get(f"shed{i}") is None
        gate.release.set()
        for uid in ("blocker", "q1", "q2"):
            assert _await_terminal(store, uid) == "finished", \
                store.get(f"fsm:error:{uid}")
        # queue drained: gauge back to 0, journals settled
        assert miner.queue_size() == 0
        assert _gauge("fsm_service_queue_depth") == 0
        assert store.journal_uids() == []
    finally:
        gate.release.set()
        miner.shutdown()


def test_priority_classes_drain_high_first(monkeypatch):
    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"blocker"})
    miner = Miner(store, workers=1, queue_depth=16)
    try:
        miner.submit(_req("blocker"))
        assert gate.entered.wait(DRILL_TIMEOUT_S)
        miner.submit(_req("p-low", priority="low"))
        miner.submit(_req("p-norm"))  # default normal
        miner.submit(_req("p-high", priority="high"))
        gate.release.set()
        for uid in ("p-low", "p-norm", "p-high"):
            assert _await_terminal(store, uid) == "finished"
        assert gate.run_order == ["blocker", "p-high", "p-norm", "p-low"]
        with pytest.raises(ValueError, match="unknown priority"):
            miner.submit(_req("bad", priority="urgent"))
    finally:
        gate.release.set()
        miner.shutdown()


def test_unbounded_queue_depth_zero_never_sheds(monkeypatch):
    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"blocker"})
    miner = Miner(store, workers=1, queue_depth=0)
    try:
        miner.submit(_req("blocker"))
        assert gate.entered.wait(DRILL_TIMEOUT_S)
        for i in range(8):
            miner.submit(_req(f"j{i}"))  # no AdmissionShed
        assert miner.queue_size() == 8
        gate.release.set()
        for i in range(8):
            assert _await_terminal(store, f"j{i}") == "finished"
    finally:
        gate.release.set()
        miner.shutdown()


# ----------------------------------------------------------- uid conflicts


def test_resubmitting_live_uid_is_conflict_not_state_wipe(monkeypatch):
    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"dup"})
    miner = Miner(store, workers=1, queue_depth=8)
    try:
        miner.submit(_req("dup"))
        assert gate.entered.wait(DRILL_TIMEOUT_S)
        with pytest.raises(UidConflict):  # running
            miner.submit(_req("dup"))
        miner.submit(_req("queued-dup"))
        with pytest.raises(UidConflict):  # queued
            miner.submit(_req("queued-dup"))
        gate.release.set()
        assert _await_terminal(store, "dup") == "finished"
        assert _await_terminal(store, "queued-dup") == "finished"
        # terminal uid: resubmit is allowed again and re-runs cleanly
        miner.submit(_req("dup"))
        assert _await_terminal(store, "dup") == "finished"
    finally:
        gate.release.set()
        miner.shutdown()


# ------------------------------------------------------ deadlines + cancel


def test_deadline_spent_on_queue_wait_aborts_before_running(monkeypatch):
    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"blocker"})
    miner = Miner(store, workers=1, queue_depth=8)
    try:
        miner.submit(_req("blocker"))
        assert gate.entered.wait(DRILL_TIMEOUT_S)
        miner.submit(_req("late", deadline_s="0.05"))
        time.sleep(0.15)  # the budget burns entirely on queue wait
        gate.release.set()
        assert _await_terminal(store, "late") == "failure"
        err = store.get("fsm:error:late") or ""
        assert err.startswith("DEADLINE_EXCEEDED"), err
        assert "late" not in gate.run_order  # never built a dataset
        assert store.journal_get("late") is None
        assert jobctl.get("late") is None  # control entry released
    finally:
        gate.release.set()
        miner.shutdown()


def test_bad_deadline_and_priority_rejected_synchronously():
    store = ResultStore()
    miner = Miner(store, workers=1, queue_depth=8)
    try:
        with pytest.raises(ValueError, match="deadline_s"):
            miner.submit(_req("bad1", deadline_s="-3"))
        with pytest.raises(ValueError):
            miner.submit(_req("bad2", deadline_s="soon"))
        # nan parses as float but compares False to everything — it must
        # be rejected, not armed as a deadline that can never expire
        with pytest.raises(ValueError, match="finite"):
            miner.submit(_req("bad3", deadline_s="nan"))
        with pytest.raises(ValueError, match="finite"):
            miner.submit(_req("bad4", deadline_s="inf"))
        # nothing half-submitted
        for uid in ("bad1", "bad2", "bad3", "bad4"):
            assert store.status(uid) is None
    finally:
        miner.shutdown()


def test_cancel_running_and_queued_jobs(monkeypatch):
    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"run1"})
    miner = Miner(store, workers=1, queue_depth=8)
    try:
        miner.submit(_req("run1"))
        assert gate.entered.wait(DRILL_TIMEOUT_S)
        miner.submit(_req("q1"))
        assert jobctl.cancel("run1") == "running"
        assert jobctl.cancel("q1") == "queued"
        assert jobctl.cancel("nope") is None
        gate.release.set()
        # run1 aborts at the post-dataset safe point; q1 on dequeue
        assert _await_terminal(store, "run1") == "failure"
        assert (store.get("fsm:error:run1") or "").startswith("CANCELLED")
        assert _await_terminal(store, "q1") == "failure"
        assert (store.get("fsm:error:q1") or "").startswith("CANCELLED")
        assert "q1" not in gate.run_order  # cancelled before running
        assert store.journal_uids() == []
    finally:
        gate.release.set()
        miner.shutdown()


# --------------------------------------------------------- HTTP code paths


def _serve(master):
    from spark_fsm_tpu.service.app import make_server

    server = make_server(0, master=master)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="fsm-http-admission-test").start()
    return server


def _post_raw(port, endpoint, **params):
    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{port}{endpoint}"
    try:
        with urllib.request.urlopen(url, data=data, timeout=30) as resp:
            return resp.status, dict(resp.headers), \
                json.loads(resp.read().decode())
    except urllib.error.HTTPError as err:
        return err.code, dict(err.headers), json.loads(err.read().decode())


def test_http_429_retry_after_409_conflict_and_cancel(monkeypatch):
    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"web-block"})
    master = Master(store=store, queue_depth=1)
    server = _serve(master)
    port = server.server_port
    try:
        code, _, body = _post_raw(port, "/train", uid="web-block",
                                  algorithm="SPADE", source="INLINE",
                                  sequences="1 -1 2 -2\n", support="1.0")
        assert code == 200 and body["status"] == "started"
        assert gate.entered.wait(DRILL_TIMEOUT_S)
        code, _, body = _post_raw(port, "/train", uid="web-q1",
                                  algorithm="SPADE", source="INLINE",
                                  sequences="1 -1 2 -2\n", support="1.0")
        assert code == 200
        # queue (depth 1) is now full: shed with 429 + Retry-After
        code, headers, body = _post_raw(port, "/train", uid="web-shed",
                                        algorithm="SPADE", source="INLINE",
                                        sequences="1 -1 2 -2\n",
                                        support="1.0")
        assert code == 429, body
        assert body["status"] == "failure"
        assert "queue full" in body["data"]["error"]
        retry_after = int(headers.get("Retry-After"))
        assert retry_after >= 1
        assert body["data"]["retry_after_s"] == str(retry_after)
        # live uid: 409 conflict
        code, _, body = _post_raw(port, "/train", uid="web-block",
                                  algorithm="SPADE", source="INLINE",
                                  sequences="1 -1 2 -2\n", support="1.0")
        assert code == 409 and "live" in body["data"]["error"]
        # cancel over HTTP: running job, then unknown -> 404
        code, _, body = _post_raw(port, "/admin/cancel/web-block")
        assert code == 200 and body["was"] == "running"
        code, _, body = _post_raw(port, "/admin/cancel/web-nope")
        assert code == 404
        # cancelling the QUEUED job settles it immediately and returns
        # its admission slot: the next submit admits instead of shedding
        code, _, body = _post_raw(port, "/admin/cancel/web-q1")
        assert code == 200 and body["was"] == "queued"
        assert _await_terminal(store, "web-q1") == "failure"
        assert (store.get("fsm:error:web-q1") or "").startswith("CANCELLED")
        code, _, body = _post_raw(port, "/train", uid="web-q2",
                                  algorithm="SPADE", source="INLINE",
                                  sequences="1 -1 2 -2\n", support="1.0")
        assert code == 200 and body["status"] == "started", body
        gate.release.set()
        assert _await_terminal(store, "web-block") == "failure"
        assert (store.get("fsm:error:web-block") or "").startswith(
            "CANCELLED")
        assert _await_terminal(store, "web-q2") == "finished"
    finally:
        gate.release.set()
        master.shutdown()
        server.shutdown()


# -------------------------------------------------- shutdown drain (full q)


def test_shutdown_drain_under_full_queue_fails_backlog_durably(monkeypatch):
    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"blocker"})
    miner = Miner(store, workers=1, queue_depth=3)
    miner.submit(_req("blocker"))
    assert gate.entered.wait(DRILL_TIMEOUT_S)
    for i in range(3):
        miner.submit(_req(f"backlog{i}"))
    done = threading.Event()

    def drain():
        miner.shutdown(join_timeout_s=DRILL_TIMEOUT_S)
        done.set()

    threading.Thread(target=drain, daemon=True).start()
    # wait until the drain is underway (stopping flag set)
    deadline = time.time() + DRILL_TIMEOUT_S
    while not miner._stopping and time.time() < deadline:
        time.sleep(0.01)
    # sheds DURING the drain still answer 429 (queue is full), no hang
    with pytest.raises(AdmissionShed):
        miner.submit(_req("drain-shed"))
    assert store.status("drain-shed") is None
    gate.release.set()
    assert done.wait(DRILL_TIMEOUT_S), "shutdown drain hung"
    # the running job finished; every queued backlog job got a durable
    # failure and its journal entry was settled
    assert store.status("blocker") == "finished"
    for i in range(3):
        uid = f"backlog{i}"
        assert store.status(uid) == "failure"
        assert "shutting down" in (store.get(f"fsm:error:{uid}") or "")
        assert store.journal_get(uid) is None
    assert store.journal_uids() == []
    assert miner.queue_size() == 0


# ----------------------------------------------------- kill-restart drill


class _Kill(BaseException):
    """Simulated hard kill: BaseException so no supervision layer eats
    it — the store is left exactly as a SIGKILL would leave it."""


class _KillingCheckpoint:
    """StoreCheckpoint wrapper that 'kills the process' right after the
    first frontier save lands."""

    def __init__(self, inner, after_saves=1):
        self.inner = inner
        self.every_s = 0.0
        self.saves = 0
        self.after = after_saves

    def load(self):
        return self.inner.load()

    def save(self, state):
        self.inner.save(state)
        self.saves += 1
        if self.saves >= self.after:
            raise _Kill


def _orphan_checkpointed_job(store, uid, db_text):
    """Leave the store exactly as a kill -9 mid-mine would: journal
    intent from a dead incarnation, status 'started', a persisted
    frontier from the first checkpoint save, NO results."""
    from spark_fsm_tpu.data.spmf import parse_spmf
    from spark_fsm_tpu.service import plugins

    req_data = {"algorithm": "SPADE_TPU", "source": "INLINE",
                "sequences": db_text, "support": "0.1", "checkpoint": "1",
                "checkpoint_every_s": "0", "uid": uid}
    store.journal_set(uid, json.dumps({
        "uid": uid, "incarnation": "dead-incarnation", "ts": 0,
        "checkpoint": True, "priority": "normal", "request": req_data}))
    store.add_status(uid, "started")
    ckpt = _KillingCheckpoint(StoreCheckpoint(store, uid, every_s=0.0))
    req = ServiceRequest("fsm", "train", dict(req_data))
    db = parse_spmf(db_text)
    with pytest.raises(_Kill):
        plugins.get_plugin(req).extract(req, db, {}, checkpoint=ckpt)
    assert ckpt.saves >= 1
    assert store.get(f"fsm:frontier:{uid}") is not None
    assert store.patterns(uid) is None
    return req_data


def test_kill_restart_drill_resumes_checkpointed_and_fails_orphans():
    db = synthetic_db(seed=31, n_sequences=120, n_items=10,
                      mean_itemsets=3.0, mean_itemset_size=1.3)
    db_text = format_spmf(db)
    store = ResultStore()
    _orphan_checkpointed_job(store, "drill", db_text)
    # a non-checkpointed orphan (queued or mid-mine at the kill)
    store.journal_set("plain", json.dumps({
        "uid": "plain", "incarnation": "dead-incarnation", "ts": 0,
        "checkpoint": False, "priority": "normal",
        "request": {"algorithm": "SPADE", "source": "INLINE",
                    "sequences": "1 -1 2 -2\n", "support": "1.0",
                    "uid": "plain"}}))
    store.add_status("plain", "started")
    # an orphan whose crash hit between the terminal write and the
    # journal clear: already finished, journal just needs settling
    store.journal_set("settled", json.dumps({
        "uid": "settled", "incarnation": "dead-incarnation", "ts": 0,
        "checkpoint": False, "priority": "normal", "request": {}}))
    store.add_status("settled", "finished")

    master = Master(store=store)  # the rebooted incarnation
    try:
        report = recover_orphans(master)
        assert report["resumed"] == ["drill"]
        assert report["failed"] == ["plain"]
        assert report["cleared"] == ["settled"]
        # the resubmitted checkpointed mine resumes from its persisted
        # frontier and finishes with the EXACT oracle pattern set —
        # zero duplicated results
        assert _await_terminal(store, "drill") == "finished", \
            store.get("fsm:error:drill")
        got = deserialize_patterns(store.patterns("drill"))
        want = mine_spade(db, abs_minsup(0.1, len(db)))
        assert patterns_text(got) == patterns_text(want)
        # non-checkpointed orphan: durable, explicit failure
        assert store.status("plain") == "failure"
        assert "interrupted by restart" in (store.get("fsm:error:plain")
                                            or "")
        assert store.status("settled") == "finished"
        # every journal intent is settled after the drill
        assert store.journal_uids() == []
    finally:
        master.shutdown()


def test_recovery_is_idempotent_and_skips_live_jobs(monkeypatch):
    """A second recovery pass (double boot, or a sibling process racing)
    finds nothing: resubmitted jobs are LIVE in the new incarnation."""
    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"held"})
    master = Master(store=store)
    try:
        master.miner.submit(_req("held"))
        assert gate.entered.wait(DRILL_TIMEOUT_S)
        report = recover_orphans(master)
        assert report == {"resumed": [], "failed": [], "cleared": [],
                          "quarantined": []}
        assert store.status("held") == "started"  # untouched
        gate.release.set()
        assert _await_terminal(store, "held") == "finished"
    finally:
        gate.release.set()
        master.shutdown()
