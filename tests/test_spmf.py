import pytest

from spark_fsm_tpu.data.spmf import format_spmf, parse_spmf


def test_parse_basic():
    db = parse_spmf("1 3 -1 2 -1 2 4 -1 -2\n")
    assert db == [((1, 3), (2,), (2, 4))]


def test_parse_no_trailing_markers():
    assert parse_spmf("5 -1 6") == [((5,), (6,))]
    assert parse_spmf("5 -1 6 -2") == [((5,), (6,))]


def test_parse_skips_comments_and_blanks():
    text = "# header\n\n1 -1 2 -2\n% meta\n3 -2\n"
    assert parse_spmf(text) == [((1,), (2,)), ((3,),)]


def test_parse_normalizes_itemsets():
    # duplicates removed, items sorted within an itemset
    assert parse_spmf("3 1 3 -1 -2") == [((1, 3),)]


def test_parse_rejects_nonpositive():
    with pytest.raises(ValueError):
        parse_spmf("0 -1 -2")


def test_roundtrip():
    db = [((1, 3), (2,), (2, 4)), ((7,),)]
    assert parse_spmf(format_spmf(db)) == db


def test_format_exact_text():
    assert format_spmf([((1, 3), (2,))]) == "1 3 -1 2 -1 -2\n"
