"""Store-outage survival drills (ISSUE 14, service/storeguard.py).

Three layers:

- HERMETIC state-machine tests: a guard over a cuttable in-process
  store — transitions need probe confirmation, spools are bounded and
  replay in order, the replay gate refuses a spool whose lease was
  legitimately taken during the outage (the no-double-commit
  invariant, preserved verbatim).
- The PINNED OUTAGE DRILL (the ISSUE 14 acceptance): cut the store
  mid-checkpointed-mine → the job STALLS at a safe point (not a
  terminal failure); heal the store → the SAME replica resumes via the
  journal-gated NX reacquire and completes with oracle parity, zero
  duplicated results, spool fully drained.
- Admission posture: a DOWN store sheds 429 by default; with
  ``ephemeral_admission`` the submit is admitted loudly flagged
  no-journal and its results land via the spool replay.

The disabled path (``[storeguard]`` off, the default) builds no guard
objects — pinned here and byte-identical in scripts/bench_smoke.sh.
"""

import json
import time

import pytest

from spark_fsm_tpu import config as cfgmod
from spark_fsm_tpu.data.spmf import format_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.service import storeguard as SG
from spark_fsm_tpu.service.actors import AdmissionShed, Miner
from spark_fsm_tpu.service.lease import LeaseManager
from spark_fsm_tpu.service.model import ServiceRequest, deserialize_patterns
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.utils import faults, jobctl
from spark_fsm_tpu.utils.canonical import diff_patterns, patterns_text

DRILL_TIMEOUT_S = 180.0


class CuttableStore(ResultStore):
    """In-process store whose every service-facing verb can be CUT
    (raises ConnectionError — a transport failure, exactly what a
    blackholed Redis surfaces).  ``cut_on_set_prefix`` arms an
    automatic cut that engages right AFTER a key with that prefix
    lands — the deterministic mid-checkpointed-mine outage trigger."""

    def __init__(self, clock=None):
        super().__init__(clock=clock)
        self.cut = False
        self.cut_on_set_prefix = None

    def _gate(self):
        if self.cut:
            raise ConnectionError("injected store outage (cut)")

    def set(self, key, value):
        self._gate()
        super().set(key, value)
        pfx = self.cut_on_set_prefix
        if pfx and key.startswith(pfx):
            self.cut = True
            self.cut_on_set_prefix = None

    def get(self, key):
        self._gate()
        return super().get(key)

    def peek(self, key):
        self._gate()
        return super().peek(key)

    def rpush(self, key, value):
        self._gate()
        super().rpush(key, value)

    def delete(self, key):
        self._gate()
        return super().delete(key)

    def incr(self, key):
        self._gate()
        return super().incr(key)

    def set_px(self, key, value, px_ms, nx=False):
        self._gate()
        return super().set_px(key, value, px_ms, nx=nx)

    def pexpire(self, key, px_ms):
        self._gate()
        return super().pexpire(key, px_ms)

    def pttl(self, key):
        self._gate()
        return super().pttl(key)

    def llen(self, key):
        self._gate()
        return super().llen(key)

    def lrange(self, key):
        self._gate()
        return super().lrange(key)

    def scan_keys(self, prefix, cursor="0", count=512):
        self._gate()
        return super().scan_keys(prefix, cursor, count)

    def spine_append(self, uid, chunk_json):
        self._gate()
        super().spine_append(uid, chunk_json)

    def probe(self):
        self._gate()
        return True

    # raw reads for assertions while the store is CUT (the test is the
    # omniscient observer; the service under test cannot see these)
    def raw(self, key):
        return self._kv.get(key)


def _scfg(**kw):
    base = {"enabled": True, "probe_every_s": 0, "down_after": 2,
            "spool_max_entries": 512, "stall_max_s": 120.0}
    base.update(kw)
    return cfgmod.parse_config({"storeguard": base}).storeguard


@pytest.fixture(autouse=True)
def _guard_hygiene():
    SG.uninstall()
    yield
    SG.uninstall()


@pytest.fixture()
def storeguard_config():
    """Swap the active config to a [storeguard]-enabled one (manual
    probe ticks) and restore after."""
    old = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config({"storeguard": {
        "enabled": True, "probe_every_s": 0, "down_after": 1,
        "stall_max_s": 120.0}}))
    yield
    cfgmod.set_config(old)


# ------------------------------------------------------------ state machine


def test_down_requires_probe_confirmation_then_replays_in_order():
    store = CuttableStore()
    g = SG.StoreGuard(store, scfg=_scfg(down_after=2))
    # healthy direct write
    g.set("u1", "k0", "v0")
    assert store.raw("k0") == "v0" and g.state == SG.HEALTHY
    store.cut = True
    # first failure: flaky, still raising (no probe consulted yet)
    with pytest.raises(ConnectionError):
        g.set("u1", "k1", "v1")
    assert g.state == SG.FLAKY
    # second failure crosses down_after; the probe (also cut) confirms
    # DOWN — and the write is SPOOLED instead of raising
    g.set("u1", "k1", "v1")
    g.rpush("u1", "l1", "a")
    g.rpush("u1", "l1", "b")
    g.set("u2", "k2", "v2")
    assert g.state == SG.DOWN
    assert store.raw("k1") is None  # nothing landed
    assert g.spool_entries() == 4
    # heal: one tick probes OK, replays everything in order
    store.cut = False
    g.tick()
    assert g.state == SG.HEALTHY and g.drained()
    assert store.raw("k1") == "v1" and store.raw("k2") == "v2"
    assert store.lrange("l1") == ["a", "b"]


def test_store_that_answers_probe_is_sick_not_down():
    """Writes failing while the probe SUCCEEDS = the store is alive but
    erroring — the guard must keep the conservative posture (raise,
    fence), never spool."""
    store = CuttableStore()
    g = SG.StoreGuard(store, scfg=_scfg(down_after=1))

    real_set = CuttableStore.set
    calls = []

    def set_fails(self, key, value):
        calls.append(key)
        raise ConnectionError("write path broken")

    CuttableStore.set = set_fails
    try:
        with pytest.raises(ConnectionError):
            g.set("u1", "k1", "v1")  # probe passes -> NOT down
    finally:
        CuttableStore.set = real_set
    assert g.state == SG.FLAKY
    assert g.drained()
    # a later clean write heals flaky back to healthy
    g.set("u1", "k1", "v1")
    assert g.state == SG.HEALTHY


def test_non_transport_errors_never_enter_the_state_machine():
    store = CuttableStore()
    g = SG.StoreGuard(store, scfg=_scfg(down_after=1))

    real_set = CuttableStore.set

    def set_value_error(self, key, value):
        raise ValueError("bad payload")

    CuttableStore.set = set_value_error
    try:
        with pytest.raises(ValueError):
            g.set("u1", "k1", "v1")
    finally:
        CuttableStore.set = real_set
    assert g.state == SG.HEALTHY and g.drained()


def test_spool_bound_overflow_fences_the_job():
    store = CuttableStore()
    g = SG.StoreGuard(store, scfg=_scfg(down_after=1,
                                        spool_max_entries=3))
    ctl = jobctl.register("u-big")
    try:
        store.cut = True
        g.set("u-big", "k", "v")  # confirms DOWN via probe
        for i in range(3):
            g.set("u-big", f"k{i}", "v")
        # 4 entries > bound: the spool poisons, the job fences
        assert ctl.lease_lost is True
        assert g.spool_entries() == 0
        # later writes for the poisoned uid are dropped, not spooled
        g.set("u-big", "k9", "v")
        assert g.spool_entries() == 0
        # heal: the poisoned spool is dropped as refused, nothing lands
        store.cut = False
        g.tick()
        assert g.state == SG.HEALTHY
        assert store.raw("k0") is None and store.raw("k9") is None
    finally:
        jobctl.release("u-big")


def test_replay_gate_same_token_reacquire_and_adopted_refusal():
    """The invariant core: a spool whose lease expired UNCLAIMED with
    the journal intent still ours replays under the SAME token; a
    spool whose uid was adopted during the outage is REFUSED."""
    t = [0.0]
    store = CuttableStore(clock=lambda: t[0])
    mgr = LeaseManager(store, replica_id="sg-a", lease_ttl_s=5.0,
                       heartbeat_s=0, clock=lambda: t[0])
    g = SG.StoreGuard(store, lease_mgr=mgr, scfg=_scfg(down_after=1),
                      clock=lambda: t[0])
    mgr.attach_guard(g)
    tok = mgr.acquire("u1")
    store.journal_set("u1", json.dumps({"replica": "sg-a",
                                        "request": {"x": "1"}}))
    store.cut = True
    g.set("u1", "fsm:pattern:u1", "[1]")  # -> DOWN, spooled
    assert g.state == SG.DOWN
    # outage outlives the TTL: the store-side lease expires
    t[0] = 10.0
    store.cut = False
    g.tick()
    # journal still ours -> NX re-take under the SAME token, replayed
    assert g.drained() and store.raw("fsm:pattern:u1") == "[1]"
    assert json.loads(store.peek("fsm:lease:u1"))["token"] == tok
    mgr.release("u1")
    store.journal_clear("u1")

    # round 2: an adopter takes the uid during the outage
    tok2 = mgr.acquire("u2")
    store.journal_set("u2", json.dumps({"replica": "sg-a",
                                        "request": {"x": "1"}}))
    ctl = jobctl.register("u2")
    mgr.attach("u2", ctl)
    store.cut = True
    g.set("u2", "fsm:pattern:u2", "[stale]")
    assert g.state == SG.DOWN
    t[0] = 20.0  # lease expires store-side
    store.cut = False
    # the adopter: fresh (larger) token + journal rewritten
    adopter = LeaseManager(store, replica_id="sg-b", lease_ttl_s=5.0,
                           heartbeat_s=0, clock=lambda: t[0])
    assert adopter.adopt_expired("u2") is True
    store.journal_set("u2", json.dumps({"replica": "sg-b",
                                        "request": {"x": "1"}}))
    store.set("fsm:pattern:u2", "[adopter]")
    g.tick()
    # replay REFUSED: the stale spool never lands over the adopter's
    assert g.drained()
    assert store.peek("fsm:pattern:u2") == "[adopter]"
    assert ctl.lease_lost is True  # fenced -> terminal path
    assert json.loads(store.peek("fsm:lease:u2"))["token"] > tok2
    jobctl.release("u2")


def test_replay_released_job_cleans_its_reacquired_lease():
    """A job that SETTLED locally during the outage (release ran as a
    store-side no-op): the replay reacquires to land the writes, then
    cleans the lease key it re-took."""
    t = [0.0]
    store = CuttableStore(clock=lambda: t[0])
    mgr = LeaseManager(store, replica_id="sg-a", lease_ttl_s=5.0,
                       heartbeat_s=0, clock=lambda: t[0])
    g = SG.StoreGuard(store, lease_mgr=mgr, scfg=_scfg(down_after=1),
                      clock=lambda: t[0])
    mgr.attach_guard(g)
    mgr.acquire("u1")
    store.journal_set("u1", json.dumps({"replica": "sg-a"}))
    store.cut = True
    g.set("u1", "fsm:pattern:u1", "[1]")
    g.delete("u1", "fsm:journal:u1")
    mgr.release("u1")  # store-side no-op (cut); local record dropped
    t[0] = 10.0
    store.cut = False
    g.tick()
    assert g.drained()
    assert store.peek("fsm:pattern:u1") == "[1]"
    assert store.peek("fsm:journal:u1") is None
    assert store.peek("fsm:lease:u1") is None  # cleaned after replay


# -------------------------------------------------------------- admission


def test_outage_sheds_admission_by_default(storeguard_config):
    store = CuttableStore()
    miner = Miner(store, workers=1)
    try:
        g = miner._guard
        assert g is not None
        store.cut = True
        assert g.probe_once() == "unreachable" and g.is_down()
        req = ServiceRequest("fsm", "train", {
            "algorithm": "SPADE", "source": "INLINE",
            "sequences": "1 -1 2 -2\n", "support": "1.0",
            "uid": "shed-me"})
        with pytest.raises(AdmissionShed, match="store outage"):
            miner.submit(req)
        # zero trace of the uid anywhere (store cut, nothing spooled)
        assert g.drained()
    finally:
        store.cut = False
        miner.shutdown()


def test_ephemeral_admission_runs_no_journal_job_through_the_spool():
    old = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config({"storeguard": {
        "enabled": True, "probe_every_s": 0, "down_after": 1,
        "ephemeral_admission": True}}))
    store = CuttableStore()
    miner = Miner(store, workers=1)
    try:
        g = miner._guard
        store.cut = True
        assert g.probe_once() == "unreachable"
        req = ServiceRequest("fsm", "train", {
            "algorithm": "SPADE", "source": "INLINE",
            "sequences": "1 -1 2 -2\n1 -1 2 -2\n", "support": "1.0",
            "uid": "eph-1"})
        extras = miner.submit(req)
        assert extras == {"ephemeral": "1"}  # the LOUD flag
        # the job runs to completion locally while the store is cut
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline and jobctl.get("eph-1") is not None:
            time.sleep(0.02)
        assert jobctl.get("eph-1") is None, "ephemeral job never settled"
        assert store.raw("fsm:pattern:eph-1") is None  # not durable yet
        # no journal intent ever existed (spooled or otherwise)
        store.cut = False
        g.tick()
        assert g.drained()
        assert store.status("eph-1") == "finished"
        assert store.patterns("eph-1") is not None
        assert store.journal_get("eph-1") is None
    finally:
        store.cut = False
        miner.shutdown()
        cfgmod.set_config(old)


def test_disabled_path_builds_no_guard_objects():
    store = ResultStore()
    miner = Miner(store, workers=1)
    try:
        assert miner._guard is None
        assert SG.get() is None
    finally:
        miner.shutdown()


# ------------------------------------------------------- the outage drill


def test_outage_drill_stall_resume_parity_spool_drained(
        storeguard_config):
    """THE ISSUE 14 acceptance pin: black-hole the store mid-
    checkpointed-mine → the job pauses at a safe point (stalled, NOT
    terminally failed); restore the store → the same replica resumes
    through the journal-gated NX reacquire and completes with oracle
    parity, zero duplicated results, spool fully drained."""
    store = CuttableStore()
    mgr = LeaseManager(store, replica_id="drill-a", lease_ttl_s=0.5,
                       heartbeat_s=0)
    miner = Miner(store, workers=1, lease_mgr=mgr)
    g = miner._guard
    assert g is not None
    db = synthetic_db(seed=41, n_sequences=160, n_items=12,
                      mean_itemsets=3.0, mean_itemset_size=1.3)
    want = mine_spade(db, abs_minsup(0.05, len(db)))
    try:
        # slow every frontier save so the mine reliably spans the cut
        # (the same trick replica_smoke uses), and cut the store right
        # after the FIRST frontier snapshot lands
        with faults.injected("checkpoint.save", every=1, delay_s=0.3,
                             exc="none"):
            store.cut_on_set_prefix = "fsm:frontier:drill"
            miner.submit(ServiceRequest("fsm", "train", {
                "algorithm": "SPADE_TPU", "source": "INLINE",
                "sequences": format_spmf(db), "support": "0.05",
                "checkpoint": "1", "checkpoint_every_s": "0",
                "uid": "drill"}))
            ctl = jobctl.get("drill")
            assert ctl is not None
            # wait for the auto-cut (first checkpoint landed)
            deadline = time.time() + DRILL_TIMEOUT_S
            while time.time() < deadline and not store.cut:
                assert jobctl.get("drill") is not None, \
                    f"job settled before the cut: {store.raw('fsm:error:drill')}"
                time.sleep(0.02)
            assert store.cut, "the mine never wrote a first checkpoint"
            # pump lease heartbeats: the TTL lapses, the guard proves
            # the outage, the job STALLS at its next safe point
            deadline = time.time() + DRILL_TIMEOUT_S
            while time.time() < deadline and not ctl.stalled:
                mgr.tick()
                g.tick()
                assert not ctl.lease_lost, \
                    "outage fenced the job instead of stalling it"
                time.sleep(0.05)
            assert ctl.stalled, "job never stalled at a safe point"
            assert store.raw("fsm:status:drill") not in ("finished",
                                                         "failure")
            assert not g.drained() or g.state == SG.DOWN
            # heal: the probe notices, the spool replays under the SAME
            # token (journal-gated NX reacquire), the job resumes
            store.cut = False
            g.tick()
            mgr.tick()
        deadline = time.time() + DRILL_TIMEOUT_S
        status = None
        while time.time() < deadline:
            mgr.tick()
            try:
                status = store.status("drill")
            except ConnectionError:
                status = None
            if status in ("finished", "failure"):
                break
            time.sleep(0.05)
        assert status == "finished", (status,
                                      store.raw("fsm:error:drill"))
        got = deserialize_patterns(store.patterns("drill"))
        assert patterns_text(got) == patterns_text(want), \
            diff_patterns(want, got)
        # spool fully drained, bookkeeping settled, guard healthy
        assert g.drained() and g.state == SG.HEALTHY
        assert store.journal_get("drill") is None
        deadline = time.time() + 10.0
        while time.time() < deadline and \
                store.peek("fsm:lease:drill") is not None:
            time.sleep(0.05)
        assert store.peek("fsm:lease:drill") is None
    finally:
        store.cut = False
        miner.shutdown()


def test_stall_honors_cancel_and_deadline():
    """A stalled job is paused, not unkillable: cancel (and deadline)
    land through the same safe point the stall parks on."""
    ctl = jobctl.register("stall-1")
    try:
        jobctl.stall_entry(ctl)
        import threading
        woke = []

        def runner():
            try:
                jobctl.check_entry(ctl)
                woke.append("clean")
            except jobctl.JobCancelled:
                woke.append("cancelled")

        th = threading.Thread(target=runner, daemon=True)
        th.start()
        time.sleep(0.15)
        assert not woke, "check_entry returned while stalled"
        assert jobctl.cancel("stall-1") == "queued"
        th.join(5.0)
        assert woke == ["cancelled"]
    finally:
        jobctl.unstall_entry(ctl)
        jobctl.release("stall-1")


def test_stall_max_fences_conservatively():
    t = [0.0]
    store = CuttableStore(clock=lambda: t[0])
    g = SG.StoreGuard(store, scfg=_scfg(down_after=1, stall_max_s=30.0),
                      clock=lambda: t[0])
    ctl = jobctl.register("stall-2")
    try:
        store.cut = True
        assert g.probe_once() == "unreachable"
        assert g.stall_job(ctl, "stall-2") is True
        assert ctl.stalled and not ctl.lease_lost
        # the optimism budget runs out while the store is still down
        t[0] = 31.0
        g.tick()
        assert ctl.lease_lost and not ctl.stalled
    finally:
        store.cut = False
        jobctl.release("stall-2")


def test_storeguard_config_validation():
    with pytest.raises(cfgmod.ConfigError, match="down_after"):
        cfgmod.parse_config({"storeguard": {"down_after": 0}})
    with pytest.raises(cfgmod.ConfigError, match="spool_max_entries"):
        cfgmod.parse_config({"storeguard": {"spool_max_entries": 0}})
    with pytest.raises(cfgmod.ConfigError, match="stall_max_s"):
        cfgmod.parse_config({"storeguard": {"stall_max_s": -1}})
    with pytest.raises(cfgmod.ConfigError, match="probe_every_s"):
        cfgmod.parse_config({"storeguard": {"probe_every_s": -1}})
    with pytest.raises(cfgmod.ConfigError, match="timeout_s"):
        cfgmod.parse_config({"store": {"timeout_s": 0}})
    cfg = cfgmod.parse_config({"storeguard": {
        "enabled": True, "ephemeral_admission": True}})
    assert cfg.storeguard.enabled and cfg.storeguard.ephemeral_admission


def test_down_flaky_drift_still_replays_and_bounds_stalls():
    """Review findings (ISSUE 14): a DOWN -> flaky drift (store
    answers the probe but is sick) must neither strand the spool
    forever once the store truly heals, nor hold a stall past
    stall_max_s."""
    t = [0.0]
    store = CuttableStore(clock=lambda: t[0])
    g = SG.StoreGuard(store, scfg=_scfg(down_after=1, stall_max_s=30.0),
                      clock=lambda: t[0])
    ctl = jobctl.register("drift-1")
    try:
        store.cut = True
        g.set("drift-1", "k1", "v1")  # probe unreachable -> DOWN, spooled
        assert g.state == SG.DOWN and g.spool_entries() == 1
        assert g.stall_job(ctl, "drift-1") is True
        # the store comes back SICK: probe raises a non-transport error
        # -> DOWN drifts to FLAKY with the stale error streak intact
        store.cut = False
        real_probe = CuttableStore.probe
        CuttableStore.probe = lambda self: (_ for _ in ()).throw(
            RuntimeError("LOADING"))
        try:
            assert g.probe_once() == "error"
            assert g.state == SG.FLAKY
            # stall bound applies in FLAKY too: past it the job fences
            t[0] = 31.0
            g.tick()
            assert ctl.lease_lost and not ctl.stalled
        finally:
            CuttableStore.probe = real_probe
        # store now truly healthy: the pending spool must replay even
        # though the stale streak never saw a successful direct write
        g.tick()
        assert g.state == SG.HEALTHY and g.drained()
        assert store.raw("k1") == "v1"
    finally:
        jobctl.release("drift-1")


def test_ephemeral_replay_refused_when_uid_has_foreign_trace():
    """Review finding (ISSUE 14): a gate="none" (ephemeral) spool must
    NOT clobber a uid that acquired a durable trace elsewhere during
    the outage — a reused uid's durable run wins, the ephemeral spool
    is refused."""
    store = CuttableStore()
    mgr = LeaseManager(store, replica_id="eph-a", lease_ttl_s=30.0,
                       heartbeat_s=0)
    g = SG.StoreGuard(store, lease_mgr=mgr, scfg=_scfg(down_after=1))
    store.cut = True
    g.set("eph-x", "fsm:pattern:eph-x", "[ephemeral]", gate="none")
    assert g.state == SG.DOWN
    store.cut = False
    # during the outage a peer ran a DURABLE job under the same uid
    store.add_status("eph-x", "finished")
    store.set("fsm:pattern:eph-x", "[durable]")
    g.tick()
    assert g.drained()
    assert store.peek("fsm:pattern:eph-x") == "[durable]"
    # while a uid with NO trace anywhere replays fine
    store.cut = True
    g.set("eph-y", "fsm:pattern:eph-y", "[ephemeral]", gate="none")
    store.cut = False
    g.tick()
    assert store.peek("fsm:pattern:eph-y") == "[ephemeral]"


def test_refused_replay_still_sweeps_own_admission_marker():
    """Review finding (ISSUE 14): a refused spool drop must not leak
    this replica's TTL-less admission marker — the deferred marker DEL
    is swept best-effort even when everything else is refused."""
    t = [0.0]
    store = CuttableStore(clock=lambda: t[0])
    mgr = LeaseManager(store, replica_id="mk-a", lease_ttl_s=5.0,
                       heartbeat_s=0, clock=lambda: t[0])
    g = SG.StoreGuard(store, lease_mgr=mgr, scfg=_scfg(down_after=1),
                      clock=lambda: t[0])
    mgr.attach_guard(g)
    tok = mgr.acquire("mk-1")
    store.journal_set("mk-1", json.dumps({"replica": "mk-a",
                                          "request": {"x": "1"}}))
    mgr.publish_admission("mk-1")
    marker = "fsm:admission:mk-a:mk-1"
    assert store.peek(marker) is not None
    store.cut = True
    # the dequeue-during-outage path: marker DEL + result write spool
    g.delete("mk-1", marker)
    g.set("mk-1", "fsm:pattern:mk-1", "[stale]")
    assert g.state == SG.DOWN
    # outage outlives the TTL; an adopter takes the uid meanwhile
    t[0] = 10.0
    store.cut = False
    adopter = LeaseManager(store, replica_id="mk-b", lease_ttl_s=5.0,
                           heartbeat_s=0, clock=lambda: t[0])
    assert adopter.adopt_expired("mk-1") is True
    store.journal_set("mk-1", json.dumps({"replica": "mk-b",
                                          "request": {"x": "1"}}))
    g.tick()
    assert g.drained()
    assert store.peek("fsm:pattern:mk-1") is None  # refused, dropped
    assert store.peek(marker) is None  # ...but the marker was swept
    assert tok >= 1
