"""Lease-fenced multi-replica drills (ISSUE 8).

Two kinds of test here:

- HERMETIC protocol tests: managers + an in-process store share one
  VIRTUAL monotonic clock, so expiry/renewal/fencing timing is exact —
  no sleeps, no flakes.
- END-TO-END drills: two real ``Miner``s ("replicas") share one store
  in this process, with tiny REAL TTLs where wall time must actually
  pass (the split-brain fencing drill).  Heartbeats run in manual-tick
  mode (``heartbeat_s=0``) so every renewal/steal/recovery step is
  driven deterministically by the test.

The acceptance pins:

- fencing token: an expired-lease holder resuming mid-mine has its
  journal/result/checkpoint writes REJECTED and surfaces as a terminal
  ``LEASE_LOST:`` failure, with zero duplicated results vs the adopting
  replica's oracle-parity run;
- work stealing: an idle replica claims a loaded peer's queued jobs via
  the two-phase (marker DEL -> lease takeover) claim; the victim drops
  them at dequeue; each job runs exactly once;
- recovery only adopts orphans whose lease has EXPIRED — a live
  sibling's jobs are never resurrected (the PR 5 single-writer hazard);
- a shed submit's Retry-After points at the steal path when peers
  advertise free capacity.
"""

import json
import threading
import time

import pytest

from spark_fsm_tpu import config as cfgmod
from spark_fsm_tpu.data.spmf import format_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.service import sources
from spark_fsm_tpu.service.actors import (AdmissionShed, Miner,
                                          recover_orphans)
from spark_fsm_tpu.service.lease import LeaseHeld, LeaseManager
from spark_fsm_tpu.service.model import ServiceRequest, deserialize_patterns
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.utils import jobctl
from spark_fsm_tpu.utils.canonical import patterns_text

DRILL_TIMEOUT_S = 120.0


def _req(uid, **extra):
    data = {"algorithm": "SPADE", "source": "INLINE",
            "sequences": "1 -1 2 -2\n1 -1 2 -2\n", "support": "1.0",
            "uid": uid}
    data.update(extra)
    return ServiceRequest("fsm", "train", data)


def _await_terminal(store, uid, timeout=DRILL_TIMEOUT_S):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = store.status(uid)
        if st in ("finished", "failure"):
            return st
        time.sleep(0.01)
    raise TimeoutError(f"job {uid} reached no terminal status "
                       f"(now {store.status(uid)!r})")


class _Gate:
    """Deterministic worker occupancy (same shape as test_admission's),
    blocking only the FIRST run of each gated uid: the gate is process-
    global, and an adopted/stolen re-run of the same uid on the OTHER
    in-process replica must pass through freely."""

    def __init__(self, monkeypatch, block_uids=()):
        self.release = threading.Event()
        self.entered = threading.Event()
        self.block_uids = set(block_uids)
        self.run_order = []
        real = sources.get_db

        def gated(req, store):
            self.run_order.append(req.uid)
            if req.uid in self.block_uids:
                self.block_uids.discard(req.uid)
                self.entered.set()
                assert self.release.wait(DRILL_TIMEOUT_S), "gate never freed"
            return real(req, store)

        monkeypatch.setattr(sources, "get_db", gated)


# ------------------------------------------------- hermetic protocol tests


def _rig(ttl=10.0):
    """(store, clock-cell) sharing one virtual monotonic clock."""
    t = [0.0]
    store = ResultStore(clock=lambda: t[0])
    mk = lambda rid: LeaseManager(store, replica_id=rid, lease_ttl_s=ttl,
                                  heartbeat_s=0, clock=lambda: t[0])
    return t, store, mk


def test_acquire_is_exclusive_and_tokens_are_monotonic():
    t, store, mk = _rig()
    a, b = mk("rep-a"), mk("rep-b")
    tok_a = a.acquire("u1")
    with pytest.raises(LeaseHeld, match="rep-a"):
        b.acquire("u1")
    # re-entrant for the holder (the adoption/steal -> submit path)
    assert a.acquire("u1") == tok_a
    a.release("u1")
    assert store.peek("fsm:lease:u1") is None  # compare-and-delete hit
    tok_b = b.acquire("u1")
    assert tok_b > tok_a  # one INCR sequence: later holders supersede
    # expiry frees the uid without any release
    t[0] = 20.0
    tok_a2 = a.acquire("u1")
    assert tok_a2 > tok_b


def test_renewal_extends_and_expiry_allows_seamless_reacquire():
    t, store, mk = _rig(ttl=10.0)
    a = mk("rep-a")
    a.acquire("u1")
    # the journal intent a real submit writes right after acquiring —
    # the reacquire gate reads its replica stamp
    store.journal_set("u1", json.dumps({"replica": "rep-a"}))
    t[0] = 8.0
    a.renew_all()  # PEXPIRE re-arms: now valid to t=18
    t[0] = 15.0
    a.fence("u1")  # local fast path, still live
    # expired UNCLAIMED with the intent still ours: the fence's one
    # atomic NX reacquire continues the job seamlessly
    t[0] = 30.0
    a.fence("u1")
    assert json.loads(store.peek("fsm:lease:u1"))["replica"] == "rep-a"
    # but once the intent is DISOWNED (settled, or rewritten by an
    # adopter that has since finished and released), a free lease key is
    # no longer proof of ownership — the fence must refuse
    t[0] = 50.0
    store.journal_clear("u1")
    with pytest.raises(jobctl.JobLeaseLost):
        a.fence("u1")
    assert a.settle_for_failure("u1") is False


def test_fence_rejects_superseded_holder_and_settle_refuses_writes():
    t, store, mk = _rig(ttl=10.0)
    a, b = mk("rep-a"), mk("rep-b")
    a.acquire("u1")
    t[0] = 11.0  # a's lease lapses un-renewed
    assert b.adopt_expired("u1") is True  # the crash-failover path
    with pytest.raises(jobctl.JobLeaseLost):
        a.fence("u1")
    # the stale holder may not durably settle the uid either — the
    # adopter owns its keys now
    assert a.settle_for_failure("u1") is False
    # while the ADOPTER both fences and settles freely
    b.fence("u1")
    assert b.settle_for_failure("u1") is True


def test_adopt_requires_expired_lease_and_is_exclusive():
    t, store, mk = _rig(ttl=10.0)
    a, b, c = mk("rep-a"), mk("rep-b"), mk("rep-c")
    a.acquire("u1")
    assert b.adopt_expired("u1") is False  # live: never resurrected
    t[0] = 11.0
    # two replicas recovering concurrently: the NX acquire arbitrates
    assert b.adopt_expired("u1") is True
    assert c.adopt_expired("u1") is False


def test_steal_claim_is_exclusive_against_victim_dequeue():
    t, store, mk = _rig()
    a = mk("rep-a")
    a.acquire("q1")
    a.publish_admission("q1")
    # the thief's phase-1 claim and the victim's dequeue run the SAME
    # DEL — exactly one side ever sees 1
    assert store.delete(f"fsm:admission:rep-a:q1") == 1  # thief wins
    assert a.retract_admission("q1") is False            # victim drops


def test_heartbeat_records_expire_with_their_replica():
    t, store, mk = _rig(ttl=10.0)
    a, b = mk("rep-a"), mk("rep-b")
    a.publish_heartbeat()
    b.publish_heartbeat()
    assert [p["replica"] for p in a.peers()] == ["rep-b"]
    t[0] = 11.0  # b "crashes": no renewals — its record self-expires
    assert a.peers() == []


def test_cluster_config_parse_and_validation():
    cfg = cfgmod.parse_config({"cluster": {
        "enabled": True, "lease_ttl_s": 5, "heartbeat_s": 1,
        "steal": False, "replica_id": "r1"}})
    assert cfg.cluster.enabled and cfg.cluster.lease_ttl_s == 5.0
    assert cfg.cluster.steal is False
    mgr = LeaseManager.from_config(ResultStore(), cfg.cluster)
    assert mgr.replica_id == "r1" and mgr.lease_ttl_s == 5.0
    assert mgr.heartbeat_s == 1.0 and mgr.steal_enabled is False
    # defaults: heartbeat = ttl/3, recovery cadence = ttl
    mgr2 = LeaseManager.from_config(
        ResultStore(), cfgmod.parse_config(
            {"cluster": {"lease_ttl_s": 9}}).cluster)
    assert mgr2.heartbeat_s == 3.0 and mgr2.recover_every_s == 9.0
    with pytest.raises(cfgmod.ConfigError, match="lease_ttl_s"):
        cfgmod.parse_config({"cluster": {"lease_ttl_s": 0}})
    with pytest.raises(cfgmod.ConfigError, match="heartbeat_s"):
        cfgmod.parse_config({"cluster": {"lease_ttl_s": 2,
                                         "heartbeat_s": 3}})
    with pytest.raises(cfgmod.ConfigError, match="unknown key"):
        cfgmod.parse_config({"cluster": {"ttl": 1}})


# --------------------------------------------------- end-to-end drills


def _miner(store, rid, ttl=1.0, workers=1, depth=8):
    """A 'replica': Miner + manual-tick lease manager on a shared store."""
    mgr = LeaseManager(store, replica_id=rid, lease_ttl_s=ttl,
                       heartbeat_s=0)
    return Miner(store, workers=workers, queue_depth=depth,
                 lease_mgr=mgr), mgr


def test_fencing_token_split_brain_zero_duplicated_results(monkeypatch):
    """The ISSUE 8 acceptance drill, in-process: replica A stalls
    mid-mine past its TTL (no renewals — a GC pause / SIGSTOP), replica
    B adopts the orphan via recovery and completes it with oracle
    parity.  When A wakes and mines to completion, its result sink,
    checkpoint and journal writes are all FENCED: the store holds
    exactly B's run — zero duplicated results — and A's incarnation
    surfaces the terminal ``LEASE_LOST:`` failure locally without
    clobbering B's 'finished' status."""
    from spark_fsm_tpu.utils import obs

    db = synthetic_db(seed=47, n_sequences=120, n_items=10,
                      mean_itemsets=3.0, mean_itemset_size=1.3)
    data = {"algorithm": "SPADE_TPU", "source": "INLINE",
            "sequences": format_spmf(db), "support": "0.1",
            "checkpoint": "1", "checkpoint_every_s": "0", "uid": "drill"}
    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"drill"})
    miner_a, mgr_a = _miner(store, "rep-a", ttl=0.5)
    miner_b, mgr_b = _miner(store, "rep-b", ttl=0.5)
    rejected0 = obs.REGISTRY.snapshot()["fsm_lease_fence_rejections_total"]
    try:
        miner_a.submit(ServiceRequest("fsm", "train", dict(data)))
        assert gate.entered.wait(DRILL_TIMEOUT_S)  # A stalled mid-job
        assert store.peek("fsm:lease:drill") is not None
        time.sleep(0.7)  # A's TTL lapses un-renewed (manual-tick mode)

        # replica B's recovery pass adopts the expired orphan and
        # resumes it through B's own admission
        class _B:  # recover_orphans wants a Master-shaped object
            pass

        master_b = _B()
        master_b.store, master_b.miner = store, miner_b
        report = recover_orphans(master_b)
        assert report["resumed"] == ["drill"], report
        assert _await_terminal(store, "drill") == "finished"
        want = mine_spade(db, abs_minsup(0.1, len(db)))
        got = deserialize_patterns(store.patterns("drill"))
        assert patterns_text(got) == patterns_text(want)
        b_payload = store.patterns("drill")
        # B's terminal path settled journal AND lease
        assert store.journal_uids() == []
        assert store.peek("fsm:lease:drill") is None

        # now the STALE incarnation wakes: its very first durable-write
        # boundary (the post-dataset fence) must bounce — poll for the
        # rejection rather than for jobctl state (B already released
        # the shared uid's entry)
        lost0 = obs.REGISTRY.snapshot()["fsm_lease_lost_total"]
        gate.release.set()
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline:
            snap = obs.REGISTRY.snapshot()
            if snap["fsm_lease_fence_rejections_total"] > rejected0:
                break
            time.sleep(0.02)
        snap = obs.REGISTRY.snapshot()
        assert snap["fsm_lease_fence_rejections_total"] > rejected0
        assert snap["fsm_lease_lost_total"] >= lost0 + 1  # marked lost
        # give A's settle path a beat, then prove it wrote NOTHING
        time.sleep(0.3)
        # the store is EXACTLY B's run: same payload object, status
        # finished (A's failure write was fenced), B's journal settled
        assert store.status("drill") == "finished"
        assert store.patterns("drill") == b_payload
        assert store.journal_uids() == []
        got = deserialize_patterns(store.patterns("drill"))
        assert patterns_text(got) == patterns_text(want)
    finally:
        gate.release.set()
        miner_a.shutdown()
        miner_b.shutdown()


def test_work_stealing_idle_replica_drains_loaded_peer(monkeypatch):
    """Two-phase steal: B (idle) claims A's queued jobs after A's
    heartbeat advertises the load; A's worker drops them at dequeue
    (exactly-once), and every job finishes with the right owner."""
    from spark_fsm_tpu.utils import obs

    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"blocker"})
    miner_a, mgr_a = _miner(store, "rep-a", ttl=5.0)
    miner_b, mgr_b = _miner(store, "rep-b", ttl=5.0)
    try:
        miner_a.submit(_req("blocker"))
        assert gate.entered.wait(DRILL_TIMEOUT_S)
        miner_a.submit(_req("q1"))
        miner_a.submit(_req("q2"))
        assert miner_a.queue_size() == 2
        # manual ticks: A advertises its load, B steals
        mgr_a.publish_heartbeat()
        mgr_b.publish_heartbeat()
        assert mgr_b.peers()[0]["queued"] == 2
        stolen0 = obs.REGISTRY.snapshot()[
            "fsm_steal_attempts_total"].get("outcome=stolen", 0)
        assert mgr_b.steal_once() == 1  # B has 1 worker -> budget 1
        assert _await_terminal(store, "q1") == "finished"
        # q1 now belongs to B: its journal was rewritten under B's
        # incarnation during the steal resubmit, then settled by B's run
        assert obs.REGISTRY.snapshot()["fsm_steal_attempts_total"][
            "outcome=stolen"] == stolen0 + 1
        gate.release.set()
        for uid in ("blocker", "q2"):
            assert _await_terminal(store, uid) == "finished"
        # exactly-once: the stolen uid built ONE dataset total (on B) —
        # A's worker dropped its queued copy at dequeue instead of
        # re-running it
        deadline = time.time() + DRILL_TIMEOUT_S
        while store.keys("fsm:admission:") and time.time() < deadline:
            time.sleep(0.01)  # A's worker still draining its queue
        assert gate.run_order.count("q1") == 1
        assert store.journal_uids() == []
        assert store.keys("fsm:admission:") == []  # no marker leaks
    finally:
        gate.release.set()
        miner_a.shutdown()
        miner_b.shutdown()


def test_victim_dequeue_drops_stolen_job_exactly_once(monkeypatch):
    """The victim side of the claim: when the thief wins the marker DEL
    while the victim's worker is still busy, the victim's eventual
    dequeue must DROP the job (counted) — never run it a second time."""
    from spark_fsm_tpu.utils import obs

    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"blocker"})
    miner_a, mgr_a = _miner(store, "rep-a", ttl=5.0)
    miner_b, mgr_b = _miner(store, "rep-b", ttl=5.0)
    try:
        miner_a.submit(_req("blocker"))
        assert gate.entered.wait(DRILL_TIMEOUT_S)
        miner_a.submit(_req("steal-me"))
        mgr_a.publish_heartbeat()
        assert mgr_b.steal_once() == 1
        assert _await_terminal(store, "steal-me") == "finished"
        drops0 = obs.REGISTRY.snapshot()["fsm_steal_victim_drops_total"]
        gate.release.set()
        assert _await_terminal(store, "blocker") == "finished"
        # wait for A's worker to reach (and drop) the stolen dequeue
        deadline = time.time() + DRILL_TIMEOUT_S
        while (obs.REGISTRY.snapshot()["fsm_steal_victim_drops_total"]
               <= drops0 and time.time() < deadline):
            time.sleep(0.01)
        assert obs.REGISTRY.snapshot()["fsm_steal_victim_drops_total"] \
            == drops0 + 1
        # exactly once: B's run is the only dataset build the stolen uid
        # ever got — A dropped it at dequeue, it never re-ran
        assert gate.run_order.count("steal-me") == 1
        assert store.status("steal-me") == "finished"
    finally:
        gate.release.set()
        miner_a.shutdown()
        miner_b.shutdown()


def test_submit_conflicts_409_when_uid_leased_by_peer(monkeypatch):
    """Cross-replica 409: a uid live on replica A is refused on replica
    B with a UidConflict (the lease generalizes the incarnation
    check) — not silently re-run."""
    from spark_fsm_tpu.service.actors import UidConflict

    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"dup"})
    miner_a, _ = _miner(store, "rep-a", ttl=5.0)
    miner_b, _ = _miner(store, "rep-b", ttl=5.0)
    try:
        miner_a.submit(_req("dup"))
        assert gate.entered.wait(DRILL_TIMEOUT_S)
        with pytest.raises(UidConflict):
            miner_b.submit(_req("dup"))
        gate.release.set()
        assert _await_terminal(store, "dup") == "finished"
        # terminal: the lease is released, B may reuse the uid
        miner_b.submit(_req("dup"))
        assert _await_terminal(store, "dup") == "finished"
    finally:
        gate.release.set()
        miner_a.shutdown()
        miner_b.shutdown()


def test_retry_after_points_at_steal_path_when_peers_are_free(monkeypatch):
    """Satellite: a shed submit's Retry-After reads the CLUSTER, not the
    local EWMA pessimum — with an idle peer advertising free capacity
    the hint is ~two heartbeats; without one it falls back to the
    cost-model estimate."""
    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"blocker"})
    mgr_a = LeaseManager(store, replica_id="rep-a", lease_ttl_s=6.0,
                         heartbeat_s=0)
    miner_a = Miner(store, workers=1, queue_depth=1, lease_mgr=mgr_a)
    # manual-tick mode spawned no thread; give the estimator a real
    # cadence to price the steal path with (ttl/3)
    mgr_a.heartbeat_s = 2.0
    mgr_b = LeaseManager(store, replica_id="rep-b", lease_ttl_s=6.0,
                         heartbeat_s=0)
    miner_b = Miner(store, workers=2, queue_depth=8, lease_mgr=mgr_b)
    try:
        # fill A: one running, one queued — next submit sheds
        miner_a.submit(_req("blocker"))
        assert gate.entered.wait(DRILL_TIMEOUT_S)
        miner_a.submit(_req("q1"))
        # no peer heartbeat yet: the local estimator answers (seeded by
        # the cost model — typically large)
        with pytest.raises(AdmissionShed) as err:
            miner_a.submit(_req("shed-local"))
        local_hint = err.value.retry_after_s
        assert local_hint >= 1
        # B (2 idle workers) advertises free capacity: the hint must now
        # point at the steal path — ~two heartbeats (ttl/3 = 2s -> 4s).
        # The estimator reads the heartbeat-cadence peer CACHE (a shed
        # storm must not become a KEYS storm); refresh it the way a
        # live heartbeat tick would.
        mgr_b.publish_heartbeat()
        mgr_a.peers()
        with pytest.raises(AdmissionShed) as err:
            miner_a.submit(_req("shed-cluster"))
        import math

        assert err.value.retry_after_s == \
            max(1, math.ceil(2 * mgr_a.heartbeat_s)) == 4
    finally:
        gate.release.set()
        miner_a.shutdown()
        miner_b.shutdown()


def test_recovery_skips_live_sibling_jobs(monkeypatch):
    """The exact hazard PR 5 documented: replica B's recovery pass must
    NOT treat replica A's live (leased) jobs as dead orphans."""
    store = ResultStore()
    gate = _Gate(monkeypatch, block_uids={"held"})
    miner_a, _ = _miner(store, "rep-a", ttl=5.0)
    miner_b, _ = _miner(store, "rep-b", ttl=5.0)
    try:
        miner_a.submit(_req("held"))
        assert gate.entered.wait(DRILL_TIMEOUT_S)

        class _B:
            pass

        master_b = _B()
        master_b.store, master_b.miner = store, miner_b
        report = recover_orphans(master_b)
        assert report == {"resumed": [], "failed": [], "cleared": [],
                          "quarantined": []}
        assert store.status("held") == "started"  # untouched
        gate.release.set()
        assert _await_terminal(store, "held") == "finished"
    finally:
        gate.release.set()
        miner_a.shutdown()
        miner_b.shutdown()
