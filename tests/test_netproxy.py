"""Partition-chaos TCP proxy (utils/netproxy.py) — the storm harness's
network fault plane, proven against a local echo server and MiniRedis.

The modes under test are the storm harness's vocabulary: blackhole
(half-open partition — bytes swallowed, connection held), delay
(latency cliff), refuse (fast connection failure), reset (mid-stream
close), heal (clean recovery), and ASYMMETRY (two proxies to one
upstream, partitioned independently — the per-replica partition shape
scripts/storm_smoke.py drives)."""

import socket
import threading
import time

import pytest

from spark_fsm_tpu.utils.netproxy import NetProxy


@pytest.fixture()
def echo():
    """Line-oriented echo server on an ephemeral loopback port."""
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)

    def serve(conn):
        try:
            while True:
                chunk = conn.recv(65536)
                if not chunk:
                    return
                conn.sendall(chunk)
        except OSError:
            pass
        finally:
            conn.close()

    def accept():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            threading.Thread(target=serve, args=(conn,),
                             daemon=True).start()

    threading.Thread(target=accept, daemon=True).start()
    yield srv.getsockname()[1]
    srv.close()


def _connect(port, timeout=2.0):
    return socket.create_connection(("127.0.0.1", port), timeout=timeout)


def _roundtrip(sock, payload=b"ping\n"):
    sock.sendall(payload)
    return sock.recv(65536)


def test_passthrough_and_stats(echo):
    proxy = NetProxy("127.0.0.1", echo)
    try:
        s = _connect(proxy.port)
        assert _roundtrip(s, b"hello") == b"hello"
        assert _roundtrip(s, b"world") == b"world"
        # the pipe thread counts AFTER forwarding: poll briefly
        deadline = time.monotonic() + 2.0
        st = proxy.stats()
        while time.monotonic() < deadline and (
                st["bytes_up"] < 10 or st["bytes_down"] < 10):
            time.sleep(0.01)
            st = proxy.stats()
        assert st["connections"] == 1
        assert st["bytes_up"] == 10 and st["bytes_down"] == 10
        s.close()
    finally:
        proxy.close()


def test_blackhole_swallows_then_heal_restores(echo):
    proxy = NetProxy("127.0.0.1", echo)
    try:
        s = _connect(proxy.port, timeout=0.3)
        assert _roundtrip(s) == b"ping\n"
        proxy.blackhole(True)
        s.sendall(b"lost\n")
        with pytest.raises(socket.timeout):
            s.recv(65536)  # half-open: nothing comes back, no close
        assert proxy.stats()["swallowed_bytes"] >= 5
        proxy.heal()
        # the old stream swallowed bytes mid-conversation — a client
        # reconnects (exactly what RespClient does after a timeout)
        s.close()
        s2 = _connect(proxy.port, timeout=2.0)
        assert _roundtrip(s2, b"back\n") == b"back\n"
        s2.close()
    finally:
        proxy.close()


def test_delay_adds_latency(echo):
    proxy = NetProxy("127.0.0.1", echo)
    try:
        s = _connect(proxy.port, timeout=5.0)
        assert _roundtrip(s) == b"ping\n"
        proxy.delay(0.25)
        t0 = time.monotonic()
        assert _roundtrip(s) == b"ping\n"
        assert time.monotonic() - t0 >= 0.25
        proxy.heal()
        s.close()
    finally:
        proxy.close()


def test_refuse_and_reset(echo):
    proxy = NetProxy("127.0.0.1", echo)
    try:
        s = _connect(proxy.port)
        assert _roundtrip(s) == b"ping\n"
        # reset: the live stream dies NOW
        assert proxy.reset_all() >= 1
        with pytest.raises(OSError):
            if s.recv(65536) == b"":  # orderly close also counts
                raise ConnectionResetError
        s.close()
        # refuse: new connections die immediately
        proxy.refuse(True)
        s2 = _connect(proxy.port)
        s2.settimeout(2.0)
        assert s2.recv(65536) == b""  # closed on accept
        s2.close()
        proxy.heal()
        s3 = _connect(proxy.port)
        assert _roundtrip(s3) == b"ping\n"
        s3.close()
    finally:
        proxy.close()


def test_asymmetric_partition_two_proxies_one_upstream(echo):
    """The per-replica partition shape: A's proxy black-holed, B's
    clean — same upstream."""
    pa = NetProxy("127.0.0.1", echo)
    pb = NetProxy("127.0.0.1", echo)
    try:
        sa = _connect(pa.port, timeout=0.3)
        sb = _connect(pb.port, timeout=2.0)
        pa.blackhole(True)
        sa.sendall(b"a\n")
        with pytest.raises(socket.timeout):
            sa.recv(65536)
        assert _roundtrip(sb, b"b\n") == b"b\n"  # B unaffected
        sa.close()
        sb.close()
    finally:
        pa.close()
        pb.close()


def test_proxy_fronts_miniredis_for_resp_client(echo):
    """End-to-end with the real RESP client + MiniRedis: a blackhole
    surfaces as a transport timeout (what RedisResultStore hands the
    storeguard), and a healed proxy serves a fresh connection."""
    import sys

    sys.path.insert(0, "tests")
    from test_redis_store import MiniRedis

    from spark_fsm_tpu.service.resp import RespClient

    mini = MiniRedis()
    proxy = NetProxy("127.0.0.1", mini.port)
    try:
        c = RespClient(port=proxy.port, timeout=0.5)
        assert c.ping()
        c.set("k", "v")
        assert c.get("k") == "v"
        proxy.blackhole(True)
        with pytest.raises(OSError):
            c.get("k")
        proxy.heal()
        assert c.ping()  # transparent reconnect through the clean proxy
        assert c.get("k") == "v"
        c.close()
    finally:
        proxy.close()
        mini.close()
