"""TSR: oracle-vs-engine parity and rule-semantics unit tests."""

import numpy as np
import pytest

from spark_fsm_tpu.data.spmf import parse_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.models.tsr import (
    TsrTPU, brute_force_rules, conf_ok, mine_tsr_tpu, rule_counts_direct)
from spark_fsm_tpu.data.vertical import build_vertical
from spark_fsm_tpu.utils.canonical import rules_text
from tests.test_oracle import ZAKI_DB, random_db


def test_rule_counts_direct():
    db = parse_spmf("1 -1 2 -1 3 -2\n2 -1 1 -1 3 -2\n1 3 -2\n")
    # X={1}, Y={3}: seq0 first(1)=0 < last(3)=2 ok; seq1 first(1)=1 < 2 ok;
    # seq2 first(1)=0 = last(3)=0 -> not strictly before
    assert rule_counts_direct(db, (1,), (3,)) == (2, 3)
    # X={1,2} -> Y={3}: seq0 max(first)=1 < 2 ok; seq1 max(first)=1 < 2 ok
    assert rule_counts_direct(db, (1, 2), (3,)) == (2, 2)
    # same-itemset co-occurrence is NOT before
    assert rule_counts_direct(db, (1,), (1,))[0] == 0  # degenerate but defined


def test_conf_ok_exact():
    assert conf_ok(1, 2, 0.5)
    assert not conf_ok(49, 100, 0.5)
    assert conf_ok(2, 3, 0.5)
    assert not conf_ok(0, 0, 0.5)


def assert_rule_parity(db, k, minconf, max_side=2, **kw):
    want = brute_force_rules(db, k, minconf, max_side=max_side)
    got = mine_tsr_tpu(db, k, minconf, max_side=max_side, **kw)
    assert rules_text(got) == rules_text(want), (
        f"\n--- got ---\n{rules_text(got)}\n--- want ---\n{rules_text(want)}")
    return got


def test_parity_zaki():
    assert_rule_parity(ZAKI_DB, k=5, minconf=0.5)


def test_parity_zaki_high_conf():
    assert_rule_parity(ZAKI_DB, k=3, minconf=0.9)


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("k,minconf", [(5, 0.5), (10, 0.3)])
def test_parity_randomized(seed, k, minconf):
    rng = np.random.default_rng(100 + seed)
    db = random_db(rng, n_seq=25, n_items=6, max_itemsets=5, max_set=2)
    assert_rule_parity(db, k, minconf)


def test_parity_side3():
    rng = np.random.default_rng(7)
    db = random_db(rng, n_seq=20, n_items=5, max_itemsets=6, max_set=2)
    assert_rule_parity(db, k=8, minconf=0.4, max_side=3)


def test_parity_pallas_kernel_interpret():
    # The Pallas rule-support path end-to-end (interpret mode on CPU):
    # same rules as brute force, km=1 and km=2 buckets exercised.
    rng = np.random.default_rng(11)
    db = random_db(rng, n_seq=25, n_items=6, max_itemsets=5, max_set=2)
    got = assert_rule_parity(db, k=8, minconf=0.4, use_pallas=True)


def test_parity_pallas_kernel_multiword():
    # multiword DB (> 32 itemsets/sequence): the kernel's cross-word
    # shift_up_one carry chain under the engine
    db = [tuple((1 + (i * 7 + j) % 5,) for j in range(40))
          for i in range(12)]
    assert_rule_parity(db, k=6, minconf=0.3, use_pallas=True)


def test_pallas_bucket_downgrade_is_per_km(monkeypatch):
    # A failing km bucket must downgrade ONLY itself: other buckets keep
    # the kernel, the bad bucket reruns on the jnp path with its own
    # engine-layout prep and budget width, and the final rules are
    # byte-identical.
    import spark_fsm_tpu.models.tsr as T

    real = T._kernel_eval_fn

    def flaky(mesh, km, sb, interpret, single):
        if km == 2:
            raise RuntimeError("synthetic km=2 kernel fault")
        return real(mesh, km, sb, interpret, single)

    monkeypatch.setattr(T, "_kernel_eval_fn", flaky)
    rng = np.random.default_rng(21)
    db = random_db(rng, n_seq=25, n_items=6, max_itemsets=5, max_set=2)
    got = assert_rule_parity(db, k=8, minconf=0.4, use_pallas=True)
    # engine state is inside the wrapper; re-run with a visible engine
    from spark_fsm_tpu.data.vertical import build_vertical
    eng = TsrTPU(build_vertical(db, min_item_support=1), 8, 0.4,
                 max_side=2, use_pallas=True)
    eng.mine()
    assert eng._pallas_bad == {2}
    assert "pallas_fallback_km2" in eng.stats
    assert "pallas_fallback_km1" not in eng.stats  # km=1 kept the kernel


def test_parity_pallas_kernel_mesh():
    import jax
    from spark_fsm_tpu.parallel.mesh import make_mesh
    rng = np.random.default_rng(12)
    db = random_db(rng, n_seq=26, n_items=6, max_itemsets=5, max_set=2)
    mesh = make_mesh(len(jax.devices()))
    eng_kw = {"mesh": mesh, "use_pallas": True}
    assert_rule_parity(db, k=8, minconf=0.4, **eng_kw)


def test_iterative_deepening():
    # force tiny item_cap so the deepening loop must widen
    db = synthetic_db(seed=21, n_sequences=300, n_items=30, mean_itemsets=5.0)
    want = mine_tsr_tpu(db, 10, 0.5, max_side=2, item_cap=64)
    eng_db = build_vertical(db, min_item_support=1)
    eng = TsrTPU(eng_db, 10, 0.5, max_side=2, item_cap=2)
    got = eng.mine()
    assert eng.stats["deepening_rounds"] > 1
    assert rules_text(got) == rules_text(want)


def test_mesh_parity():
    from spark_fsm_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(8)
    rng = np.random.default_rng(9)
    db = random_db(rng, n_seq=27, n_items=6, max_itemsets=5, max_set=2)
    assert_rule_parity(db, 6, 0.5, mesh=mesh)


def test_tie_inclusive_topk():
    # two rules with identical support at the k-th slot must BOTH appear
    db = parse_spmf("1 -1 2 -2\n1 -1 3 -2\n1 -1 2 -2\n1 -1 3 -2\n")
    got = mine_tsr_tpu(db, 1, 0.0)
    sups = [r[2] for r in got]
    assert sups.count(max(sups)) >= 2


def test_empty():
    assert mine_tsr_tpu(parse_spmf("1 -2\n"), 5, 0.5) == []


def test_cpu_engine_parity():
    # TSR (CPU, TsrCPU) and TSR_TPU must be byte-identical — they share the
    # search; only the bitmap evaluation backend differs.
    from spark_fsm_tpu.models.tsr import mine_tsr_cpu

    rng = np.random.default_rng(17)
    for _ in range(4):
        db = random_db(rng, n_seq=24, n_items=7, max_itemsets=5, max_set=2)
        got_cpu = mine_tsr_cpu(db, 8, 0.4)
        got_tpu = mine_tsr_tpu(db, 8, 0.4)
        assert rules_text(got_cpu) == rules_text(got_tpu)


def test_no_dense_bitmap_materialization():
    # The Kosarak eval config (~41k items x ~990k seqs) only fits if the
    # engine builds bitmaps for the top-m items per deepening round; pulling
    # vdb.bitmaps (ALL items, dense) would be ~160 GB at full scale.
    db = synthetic_db(7, n_sequences=300, n_items=50, mean_itemsets=4.0)
    vdb = build_vertical(db, min_item_support=1)
    eng = TsrTPU(vdb, k=10, minconf=0.5, item_cap=8)
    eng.mine()
    assert vdb._bitmaps is None, "TsrTPU must not materialize vdb.bitmaps"
    assert eng.stats["deepening_rounds"] >= 1


def test_launch_width_narrows_with_side_bucket():
    # The eval kernel's live-temp footprint grows with km, so the
    # BUDGET-derived launch width must shrink by 1/km as the side-size
    # bucket grows — a km=4 launch at the km=1 width OOMs real HBM
    # (v5e: 27G on a 16G chip; see _dispatch_eval / _round_chunk_jnp).
    # A caller-pinned chunk is honored unchanged.
    db = synthetic_db(3, n_sequences=40, n_items=12, mean_itemsets=5.0)
    vdb = build_vertical(db, min_item_support=1)
    eng = TsrTPU(vdb, k=5, minconf=0.5)
    # pin the budget-derived width the 1/km memory caps divide
    eng.chunk = eng._jnp_raw = 512
    eng._chunk_user = None
    p1, s1 = eng._prep(vdb.n_items)
    cands = [((0,), (i % 3 + 1, 4, 5)) for i in range(512)]  # kmax=3 -> km=4
    before = eng.stats["kernel_launches"]
    handle = eng._dispatch_eval(p1, s1, cands)
    assert eng.stats["kernel_launches"] - before == 512 // (512 // 4)
    sups, supxs = eng._resolve_eval(handle, len(cands))
    assert len(sups) == len(cands)

    pinned = TsrTPU(vdb, k=5, minconf=0.5, chunk=512)
    p1, s1 = pinned._prep(vdb.n_items)
    before = pinned.stats["kernel_launches"]
    pinned._dispatch_eval(p1, s1, cands)
    assert pinned.stats["kernel_launches"] - before == 1  # pinned: one launch

    # Mixed batch: one side-3 candidate must NOT narrow the km=1
    # majority's launch — buckets dispatch separately (1 wide + 1 narrow
    # launch), and results come back in the original candidate order.
    mixed = [((i % 4,), (i % 3 + 5,)) for i in range(500)]
    mixed.insert(250, ((0,), (1, 4, 5)))
    before = eng.stats["kernel_launches"]
    handle = eng._dispatch_eval(p1, s1, mixed)
    assert eng.stats["kernel_launches"] - before == 2
    sups, supxs = eng._resolve_eval(handle, len(mixed))
    single = eng._resolve_eval(
        eng._dispatch_eval(p1, s1, [mixed[250]]), 1)
    assert sups[250] == single[0][0] and supxs[250] == single[1][0]


@pytest.mark.slow
@pytest.mark.skipif("not __import__('os').environ.get('RUN_SLOW')",
                    reason="minutes-long full-scale run; set RUN_SLOW=1")
def test_kosarak_scale_runnable():
    # BASELINE.md eval config #3 at 10% scale (~99k seqs, ~4.1k items):
    # proves the top-M memory plan mines a large-alphabet DB end to end.
    from spark_fsm_tpu.data.synth import kosarak_like

    db = kosarak_like(scale=0.1)
    rules = mine_tsr_tpu(db, k=100, minconf=0.5)
    assert len(rules) >= 100
    assert all(conf_ok(sup, supx, 0.5) for _, _, sup, supx in rules)


def test_shape_buckets_parity_and_reuse():
    # shape_buckets pow2-buckets the sequence axis and token-array lengths
    # (streaming rule windows drift every push): rule set must be
    # unaffected, and two windows in the same bucket must share the
    # compiled geometry (equal shape_key static part).
    rng = np.random.default_rng(61)
    db = random_db(rng, n_seq=60, n_items=6, max_itemsets=5, max_set=2)
    s1 = {}
    got = mine_tsr_tpu(db, 8, 0.4, max_side=2, shape_buckets=True,
                       stats_out=s1)
    want = brute_force_rules(db, 8, 0.4, max_side=2)
    assert rules_text(got) == rules_text(want)
    assert s1["shape_key"].startswith("tsr:s128"), s1["shape_key"]  # 60->128

    s2 = {}
    mine_tsr_tpu(db[:50], 8, 0.4, max_side=2, shape_buckets=True,
                 stats_out=s2)
    assert s1["shape_key"] == s2["shape_key"]
    s3 = {}
    mine_tsr_tpu(db[:50], 8, 0.4, max_side=2, stats_out=s3)
    assert s3["shape_key"].startswith("tsr:s50"), s3["shape_key"]


def test_stream_task_buckets_tsr_path():
    # the service plugin boundary buckets TSR streaming pushes too
    from spark_fsm_tpu.service import plugins
    from spark_fsm_tpu.service.model import ServiceRequest

    rng = np.random.default_rng(62)
    db = random_db(rng, n_seq=40, n_items=6, max_itemsets=5, max_set=2)
    data = {"algorithm": "TSR_TPU", "k": "5", "minconf": "0.4",
            "max_side": "2"}
    st: dict = {}
    plug = plugins.get_plugin(ServiceRequest("fsm", "stream", data))
    plug.extract(ServiceRequest("fsm", "stream", data), db, stats=st)
    assert st["shape_key"].startswith("tsr:s128"), st["shape_key"]


def test_pallas_readback_fault_recounts_batches(monkeypatch):
    # TPU kernel RUNTIME faults surface at np.asarray in _resolve_eval,
    # not at dispatch: the engine must downgrade to the jnp path, recount
    # the in-flight batch(es), and still produce the exact rule set —
    # mirror of test_spade_tpu's readback-fault test.  max_side=1 keeps
    # every candidate in the km=1 bucket (single part, no concat), so the
    # fault object survives dispatch and fails exactly at readback.
    import spark_fsm_tpu.models.tsr as T

    faults = []

    class FaultyArray:
        def copy_to_host_async(self):
            pass

        def __array__(self, *a, **k):
            faults.append(1)
            raise RuntimeError("synthetic readback fault")

    monkeypatch.setattr(T, "_kernel_eval_fn",
                        lambda *a, **k: lambda p1k, s1k, xy: FaultyArray())
    rng = np.random.default_rng(71)
    db = random_db(rng, n_seq=25, n_items=8, max_itemsets=5, max_set=2)
    want = brute_force_rules(db, 8, 0.4, max_side=1)
    # tiny pinned chunk: the frontier splits into several batches, so
    # PIPELINE_DEPTH(=3) kernel handles are in flight when the first
    # fault lands — each must be recounted (the used_kernel gating)
    eng = TsrTPU(build_vertical(db, min_item_support=1), 8, 0.4,
                 max_side=1, use_pallas=True, chunk=2)
    got = eng.mine()
    assert rules_text(got) == rules_text(want)
    assert eng.use_pallas is False
    assert "synthetic readback fault" in eng.stats["pallas_fallback"]
    # multiple in-flight kernel batches hit the fault and went through
    # the recount path, not just the first
    assert len(faults) >= 2, faults
    # exported stats must count ONLY the surviving jnp work: the faulted
    # handles' evaluations AND their kernel launches are discarded (both
    # downgrade paths share this contract), so the stats match a mine
    # that never touched the kernel path at all
    ref = TsrTPU(build_vertical(db, min_item_support=1), 8, 0.4,
                 max_side=1, use_pallas=False, chunk=2)
    assert rules_text(ref.mine()) == rules_text(want)
    assert eng.stats["evaluated"] == ref.stats["evaluated"]
    # +1: the downgrade's engine-layout prep rebuild is REAL work that
    # stays counted; the discarded kernel eval launches do not
    assert eng.stats["kernel_launches"] == ref.stats["kernel_launches"] + 1


# ------------------------------------------------------ resident frontier
# (ops/resident_frontier.py: whole km-ladders expanded in one dispatch)


def _deep_db(n_seq=50, run=10, extra=6, seed=7):
    """Every sequence holds the ordered run 0..run-1 plus a few noise
    items, so rules with run-length sides have FULL support — deep
    sides survive any top-k threshold, which forces over-km-ladder
    children that stay LIVE (the defer-buffer handoff path)."""
    rng = np.random.default_rng(seed)
    db = []
    for _ in range(n_seq):
        items = list(range(run)) + rng.integers(
            run, run + extra, size=3).tolist()
        db.append([[int(it)] for it in items])
    return db


def test_resident_param_validation():
    vdb = build_vertical(ZAKI_DB, min_item_support=1)
    with pytest.raises(ValueError, match="resident"):
        TsrTPU(vdb, 5, 0.5, resident="sometimes")
    assert TsrTPU(vdb, 5, 0.5, resident=True).resident == "always"
    assert TsrTPU(vdb, 5, 0.5, resident=False).resident == "never"


def test_resident_route_heuristic():
    """The 'auto' planner heuristic routes only DEEP single-device
    mines whose geometry fits the capacity model; 'never' always wins;
    structural ineligibility (k past the on-device top-k buffer)
    overrides even 'always'."""
    from spark_fsm_tpu.ops import resident_frontier as RF

    db = synthetic_db(seed=5, n_sequences=120, n_items=10,
                      mean_itemsets=3.0)
    vdb = build_vertical(db, min_item_support=1)
    m = vdb.n_items
    assert TsrTPU(vdb, 8, 0.5, max_side=None)._resident_route(m)
    assert TsrTPU(vdb, 8, 0.5, max_side=3)._resident_route(m)
    assert not TsrTPU(vdb, 8, 0.5, max_side=2)._resident_route(m)
    assert not TsrTPU(vdb, 8, 0.5, max_side=None,
                      resident="never")._resident_route(m)
    assert TsrTPU(vdb, 8, 0.5, max_side=2,
                  resident="always")._resident_route(m)
    assert not TsrTPU(vdb, RF.K_PAD + 1, 0.5, max_side=None,
                      resident="always")._resident_route(m)


@pytest.mark.parametrize("seed", range(3))
def test_resident_oracle_parity_unlimited(seed):
    """Resident path vs BRUTE FORCE on unlimited-side mines: the tiny
    alphabet makes full enumeration feasible, so this is true oracle
    parity for the deep search, not engine-vs-engine."""
    rng = np.random.default_rng(300 + seed)
    db = random_db(rng, n_seq=25, n_items=6, max_itemsets=5, max_set=2)
    want = brute_force_rules(db, 10, 0.4, max_side=6)
    got = mine_tsr_tpu(db, 10, 0.4, max_side=None, resident="always")
    assert rules_text(got) == rules_text(want)


def test_resident_deep_unlimited_parity_and_handoff():
    """Deep unlimited-max_side case: rules with sides past the km=4
    device ladder are LIVE top-k work here (every sequence shares an
    ordered 10-item run), so the resident round must defer them on
    device and hand the survivors to the host path — and the handoff
    must reproduce the host loop's exact rule set."""
    db = _deep_db()
    s_h, s_r = {}, {}
    want = mine_tsr_tpu(db, 300, 0.3, max_side=None, resident="never",
                        stats_out=s_h)
    got = mine_tsr_tpu(db, 300, 0.3, max_side=None, resident="always",
                       stats_out=s_r)
    assert rules_text(got) == rules_text(want)
    # the workload is genuinely deep (the host evaluates km8 lanes) and
    # the resident round genuinely deferred + handed off
    assert s_h.get("evaluated_km8", 0) > 0, s_h
    assert s_r.get("resident_deferred", 0) > 0, s_r
    assert s_r.get("resident_handoffs", 0) >= 1, s_r
    assert "resident_spills" not in s_r, s_r


def test_resident_overflow_spill_parity(monkeypatch):
    """Capacity-overflow spill protocol: with a deliberately tiny ring
    the frontier outgrows the device buffers mid-ladder; the wave
    commits nothing, the intact frontier spills into the host loop's
    own resume format, and the round finishes with exact parity."""
    from spark_fsm_tpu.ops import resident_frontier as RF

    db = synthetic_db(seed=42, n_sequences=200, n_items=14,
                      mean_itemsets=4.0, mean_itemset_size=1.3)
    want = mine_tsr_tpu(db, 40, 0.4, max_side=None, resident="never")
    monkeypatch.setattr(
        RF, "caps_for",
        lambda *a, **k: RF.ResidentCaps(nb=32, ring=128, r_cap=256,
                                        d_cap=32))
    s = {}
    got = mine_tsr_tpu(db, 40, 0.4, max_side=None, resident="always",
                       stats_out=s)
    assert rules_text(got) == rules_text(want)
    assert s.get("resident_spills", 0) >= 1, s


def test_resident_checkpoint_resume_parity():
    """A resident mine checkpoints at segment boundaries in the ONE
    frontier_state format; killing it mid-round and resuming a FRESH
    engine from the snapshot (which may carry deferred over-ladder
    entries) reproduces the exact rule set, still on the resident
    path."""
    db = _deep_db(n_seq=40, run=8, seed=11)
    want = mine_tsr_tpu(db, 150, 0.3, max_side=None, resident="never")

    class Crash(Exception):
        pass

    saved = []

    def cb(state):
        saved.append(state)
        if len(saved) == 2:
            raise Crash

    vdb = build_vertical(db, min_item_support=1)
    eng = TsrTPU(vdb, 150, 0.3, max_side=None, resident="always")
    with pytest.raises(Crash):
        eng.mine(checkpoint_cb=cb, checkpoint_every_s=0.0)
    assert len(saved) == 2
    import json as _json

    state = _json.loads(_json.dumps(saved[-1]))  # the StoreCheckpoint trip
    assert state["stack"], "crash happened after the frontier emptied"

    eng2 = TsrTPU(build_vertical(db, min_item_support=1), 150, 0.3,
                  max_side=None, resident="always")
    got = eng2.mine(resume=state)
    assert eng2.stats["resumed_nodes"] == len(state["stack"])
    assert eng2.stats.get("resident_rounds", 0) >= 1, eng2.stats
    assert rules_text(got) == rules_text(want)
