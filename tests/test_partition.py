"""Equivalence-class partitioned mining (parallel/partition.py) — the
2-D ``hosts x seq`` mesh route, exercised ON the forced-host 8-device
CPU mesh in ONE process (the conftest pins
``--xla_force_host_platform_device_count=8``).

The contracts under test, none of which may hide behind the
multiprocess-collectives skip (tests/test_multihost.py covers the real
DCN boundary as a ride-along):

- partition ROUTING: class hash stable and process-independent, LPT
  balance bounded, submesh rows disjoint;
- partition-aware candidate generation: every class enumerated by
  exactly one partition, zero-root partitions degrade to empty slices;
- THRESHOLD EXCHANGE: the conservative floor only tightens, stays a
  lower bound on the global s_k, and the cross-partition collective
  count scales with ROUNDS, never with launches (the per-wave
  full-mesh psum is gone from the partitioned path by construction —
  every engine's mesh is its own inner row);
- PARITY: byte-identical rules/patterns to the single-device route for
  the config-3/3d-shaped miniatures and the SPADE/cSPADE engines;
- CHECKPOINTS: composite snapshots carry per-partition frontiers in
  the engines' existing ``frontier_state`` format, resume through the
  real StoreCheckpoint, and a changed layout restarts fresh.
"""

import numpy as np
import pytest

from spark_fsm_tpu.data.synth import kosarak_like, synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical
from spark_fsm_tpu.models.oracle import mine_cspade, mine_spade
from spark_fsm_tpu.parallel import partition as PN
from spark_fsm_tpu.parallel.mesh import make_mesh
from spark_fsm_tpu.utils.canonical import patterns_text, rules_text


def _db(seed=33, n=300, items=40):
    return synthetic_db(seed=seed, n_sequences=n, n_items=items,
                        mean_itemsets=5.0, mean_itemset_size=1.4)


# ------------------------------------------------------------ plan layer


def test_class_hash_stable_and_complete():
    ids = np.arange(1, 2000, 7)
    a = PN.class_of(ids, 64)
    b = PN.class_of(ids, 64)
    assert (a == b).all()  # deterministic, seedless
    assert a.min() >= 0 and a.max() < 64
    # avalanche: consecutive ids must not cluster in one class
    assert len(np.unique(PN.class_of(np.arange(64), 64))) > 16


def test_plan_partitions_balance_and_ownership():
    rng = np.random.default_rng(7)
    ids = rng.choice(100000, size=500, replace=False)
    sups = rng.integers(1, 1000, size=500)
    plan = PN.plan_partitions(ids, sups, 4, 64)
    # every class owned exactly once, every partition index valid
    assert plan.owner.shape == (64,)
    assert set(np.unique(plan.owner)) <= set(range(4))
    # each item maps to exactly one partition; the map is a pure
    # function of the global id (process-independent ownership)
    own = plan.owner_of(ids)
    assert ((0 <= own) & (own < 4)).all()
    # LPT over 64 classes / 4 parts: imbalance well under the trivial
    # bound (a degenerate assignment would be ~4.0)
    assert 1.0 <= plan.imbalance_ratio < 1.5, plan.part_costs
    with pytest.raises(ValueError):
        PN.plan_partitions(ids, sups, 8, 4)  # classes < parts


def test_submeshes_rows_disjoint_2d():
    mesh = make_mesh(8)
    rows = PN.submeshes(mesh, 2)
    assert len(rows) == 2
    d0 = {d.id for d in rows[0].devices.flat}
    d1 = {d.id for d in rows[1].devices.flat}
    assert len(d0) == len(d1) == 4 and not (d0 & d1)
    # one-device rows of a REAL mesh stay one-device MESHES — the mesh
    # is what pins each partition's work to its own device (a None row
    # would land every partition on the default device)
    rows8 = PN.submeshes(mesh, 8)
    assert all(r is not None and r.devices.size == 1 for r in rows8)
    assert len({r.devices.flat[0].id for r in rows8}) == 8
    # no mesh = one local device: nothing to spread, bare path kept
    assert PN.submeshes(None, 4) == [None] * 4
    assert PN.submeshes(mesh, 1) == [mesh]
    with pytest.raises(ValueError):
        PN.submeshes(make_mesh(6), 4)  # 6 devices / 4 rows


def test_threshold_board_monotone_and_conservative():
    board = PN.ThresholdBoard(3, floor=1)
    assert board.floor() == 1
    board.merge([5, 9, 2])
    assert board.floor() == 2  # 3rd largest of {5,9,2}
    board.merge([7])
    assert board.floor() == 5  # {9,7,5}
    prev = board.floor()
    board.merge([1, 1, 1])  # below-floor merges never loosen it
    assert board.floor() == prev
    # conservative: always <= the true k-th largest over everything seen
    assert board.floor() <= sorted([5, 9, 2, 7, 1, 1, 1])[-3]


# -------------------------------------------------- TSR partitioned route


def test_tsr_partitioned_parity_and_collectives_config3():
    """Acceptance pin: on the 8-virtual-device CPU mesh the partitioned
    route (2 partitions x 4-device inner seq rows) produces
    byte-identical rules to the single-device route for the config-3
    miniature, and cross-partition collectives scale with ROUNDS, not
    launches."""
    db = kosarak_like(scale=0.002, fast=True)
    want = rules_text(_mine_tsr(db, max_side=2))
    stats: dict = {}
    got = _mine_tsr(db, max_side=2, mesh=make_mesh(8), partition_parts=2,
                    stats_out=stats)
    assert rules_text(got) == want
    # launch-budget-style pin: the ONLY cross-partition collective is
    # the per-round exchange — one per deepening round — while the
    # dispatch count is an order of magnitude beyond it (the per-wave
    # full-mesh psum would have been one PER LAUNCH)
    assert stats["partition_exchanges"] == stats["deepening_rounds"] == 1
    assert stats["kernel_launches"] > 4 * stats["partition_exchanges"]
    assert stats["partition_cross_bytes"] > 0
    assert stats["partition_parts"] == 2
    assert 1.0 <= stats["partition_imbalance"] < 2.0


def test_tsr_partitioned_parity_config3d():
    """Same acceptance pin for the 3d shape (unlimited rule sides, the
    service default)."""
    db = kosarak_like(scale=0.002, fast=True)
    want = rules_text(_mine_tsr(db, max_side=None))
    stats: dict = {}
    got = _mine_tsr(db, max_side=None, mesh=make_mesh(8),
                    partition_parts=2, stats_out=stats)
    assert rules_text(got) == want
    assert stats["partition_exchanges"] == stats["deepening_rounds"]


def test_tsr_partitioned_no_cross_partition_mesh():
    """Structural guarantee behind the collectives pin: every partition
    engine's mesh is its OWN inner row (or None) — no shard_map/psum in
    the partitioned path can span partitions, so per-wave traffic
    cannot cross the outer axis even by accident."""
    from spark_fsm_tpu.models.tsr import TsrPartitioned

    db = _db()
    vdb = build_vertical(db, min_item_support=1)
    mesh = make_mesh(8)
    orch = TsrPartitioned(vdb, 10, 0.4, mesh=mesh, parts=2, max_side=2)
    rows = PN.submeshes(mesh, 2)
    for p, eng in orch.engines.items():
        assert eng.mesh is not None
        got_ids = {d.id for d in eng.mesh.devices.flat}
        want_ids = {d.id for d in rows[p].devices.flat}
        assert got_ids == want_ids and len(got_ids) == 4


def test_tsr_partitioned_deepening_floor_exact():
    """Multi-round mine (item_cap far below the alphabet): the floor
    carries across rounds, exchanges stay one per round, and the merged
    output is byte-identical — the conservative-floor exactness
    argument exercised end to end."""
    db = _db()
    want = rules_text(_mine_tsr(db, k=10, minconf=0.4, max_side=2,
                                item_cap=8))
    stats: dict = {}
    got = _mine_tsr(db, k=10, minconf=0.4, max_side=2, item_cap=8,
                    partition_parts=2, stats_out=stats)
    assert rules_text(got) == want
    assert stats["deepening_rounds"] >= 2
    assert stats["partition_exchanges"] == stats["deepening_rounds"]


def test_tsr_partitioned_resident_eligible_rows():
    """parts == devices (inner row = one device -> mesh None): the
    per-part engines keep the single-device path's eligibility —
    unlimited-side parts may route RESIDENT — with exact parity."""
    db = _db(seed=34)
    want = rules_text(_mine_tsr(db, k=12, minconf=0.4, max_side=None))
    stats: dict = {}
    got = _mine_tsr(db, k=12, minconf=0.4, max_side=None,
                    partition_parts=4, stats_out=stats)
    assert rules_text(got) == want
    assert stats["partition_parts"] == 4


def test_tsr_partitioned_one_device_rows_pin_devices():
    """parts == devices over a REAL mesh: every partition runs on its
    OWN one-device mesh row (distinct devices — the fix for all
    partitions landing on the default device), with exact parity."""
    from spark_fsm_tpu.models.tsr import TsrPartitioned

    db = _db(seed=21, n=203, items=12)
    vdb = build_vertical(db, min_item_support=1)
    mesh = make_mesh(4)
    orch = TsrPartitioned(vdb, 15, 0.5, mesh=mesh, parts=4, max_side=2)
    dev_ids = set()
    for eng in orch.engines.values():
        assert eng.mesh is not None and eng.mesh.devices.size == 1
        dev_ids.add(eng.mesh.devices.flat[0].id)
    assert len(dev_ids) == 4
    got = orch.mine()
    want = _mine_tsr(db, k=15, minconf=0.5, max_side=2)
    assert rules_text(got) == rules_text(want)


def test_tsr_partition_owns_all_classes_once():
    """Candidate-generation completeness: over all partitions, every
    root is seeded exactly once (the union/disjointness the parity
    tests rely on, asserted directly)."""
    from spark_fsm_tpu.models.tsr import TsrTPU

    db = _db()
    vdb = build_vertical(db, min_item_support=1)
    plan = PN.plan_partitions(vdb.item_ids, vdb.item_supports, 3, 64)
    m = vdb.n_items
    masks = [TsrTPU(vdb, 5, 0.5, partition=(plan, p))._owned_mask(m)
             for p in range(3)]
    total = np.zeros(m, int)
    for mk in masks:
        total += mk.astype(int)
    assert (total == 1).all()


def test_tsr_partitioned_checkpoint_resume_and_layout_binding():
    """Composite checkpoints through the REAL StoreCheckpoint: resume
    from an early snapshot is byte-identical, per-part frontiers ride
    the engines' existing frontier_state format, and a changed
    partition layout restarts fresh instead of resuming another
    layout's slices."""
    from spark_fsm_tpu.service.actors import StoreCheckpoint
    from spark_fsm_tpu.service.store import ResultStore

    db = _db()
    want = rules_text(_mine_tsr(db, k=10, minconf=0.4, max_side=2,
                                item_cap=8))
    store = ResultStore()
    ckpt = StoreCheckpoint(store, "part-ckpt", every_s=0.0)
    full = _mine_tsr(db, k=10, minconf=0.4, max_side=2, item_cap=8,
                     partition_parts=2, checkpoint=ckpt)
    assert rules_text(full) == want
    saved = ckpt.load()
    assert saved is not None
    part = saved["partition"]
    assert set(part) == {"done", "active_part", "active_state"}
    for rows in part["done"].values():
        for x, y, sup, supx in rows:
            assert sup >= 1 and supx >= sup
    # truncate to an EARLY composite: keep only part 0's slice and
    # verify the resumed mine still matches byte-for-byte
    early = dict(saved)
    early["partition"] = {
        "done": {k: v for k, v in part["done"].items() if k == "0"},
        "active_part": None, "active_state": None}
    early["results"] = [r for r in part["done"].get("0", [])]
    ckpt.save(dict(early, results=list(early["results"]),
                   results_done=0))
    res = _mine_tsr(db, k=10, minconf=0.4, max_side=2, item_cap=8,
                    partition_parts=2, checkpoint=ckpt)
    assert rules_text(res) == want
    # layout change: classes differ -> fingerprint mismatch -> fresh
    res2 = _mine_tsr(db, k=10, minconf=0.4, max_side=2, item_cap=8,
                     partition_parts=2, partition_classes=32,
                     checkpoint=ckpt)
    assert rules_text(res2) == want


def test_tsr_partitioned_mid_part_frontier_resume():
    """A mid-part composite (active_part + engine frontier_state)
    resumes the ACTIVE part from its frontier, not from scratch."""
    saves = []

    class Cap:
        every_s = 0.0

        def load(self):
            return None

        def save(self, s):
            saves.append(s)

    db = _db()
    want = rules_text(_mine_tsr(db, k=10, minconf=0.4, max_side=2,
                                item_cap=8))
    _mine_tsr(db, k=10, minconf=0.4, max_side=2, item_cap=8,
              partition_parts=2, checkpoint=Cap())
    mids = [s for s in saves
            if s["partition"]["active_part"] is not None
            and s["partition"]["active_state"] is not None]
    assert mids, "no mid-part composite was ever saved"
    mid = mids[0]
    fs = mid["partition"]["active_state"]
    assert {"fingerprint", "m", "minsup", "stack",
            "results"} <= set(fs)  # the engines' OWN snapshot format

    class Fixed:
        every_s = 1e9

        def load(self):
            return mid

        def save(self, s):
            pass

    res = _mine_tsr(db, k=10, minconf=0.4, max_side=2, item_cap=8,
                    partition_parts=2, checkpoint=Fixed())
    assert rules_text(res) == want


def test_tsr_partition_zero_root_slice():
    """A partition owning no frequent class degrades to an empty slice
    (tiny alphabet over many partitions) — the union is still exact."""
    db = synthetic_db(seed=5, n_sequences=80, n_items=4,
                      mean_itemsets=3.0, mean_itemset_size=1.2)
    want = rules_text(_mine_tsr(db, k=5, minconf=0.3, max_side=2))
    got = _mine_tsr(db, k=5, minconf=0.3, max_side=2, partition_parts=4)
    assert rules_text(got) == want


def _mine_tsr(db, k=100, minconf=0.5, **kwargs):
    from spark_fsm_tpu.models.tsr import mine_tsr_tpu

    return mine_tsr_tpu(db, k, minconf, **kwargs)


# ------------------------------------------------ SPADE / cSPADE slices


def test_spade_partitioned_parity_queue_and_classic():
    from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu

    db = _db(seed=21, n=203, items=12)
    ms = abs_minsup(0.06, len(db))
    want = patterns_text(mine_spade(db, ms))
    for fused in ("auto", "never"):
        stats: dict = {}
        got = mine_spade_tpu(db, ms, partition_parts=2, fused=fused,
                             stats_out=stats)
        assert patterns_text(got) == want, fused
        assert stats["fused"] == "partitioned"
        assert stats["partition_exchanges"] == 1
    # 2-D: partition rows over the 8-device mesh
    stats2: dict = {}
    got2 = mine_spade_tpu(db, ms, mesh=make_mesh(8), partition_parts=2,
                          stats_out=stats2)
    assert patterns_text(got2) == want


def test_spade_partitioned_checkpoint_composite():
    from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu

    saves = []

    class Cap:
        every_s = 0.0

        def load(self):
            return None

        def save(self, s):
            saves.append(s)

    db = _db(seed=21, n=203, items=12)
    ms = abs_minsup(0.06, len(db))
    want = patterns_text(mine_spade(db, ms))
    got = mine_spade_tpu(db, ms, partition_parts=2, checkpoint=Cap())
    assert patterns_text(got) == want
    assert saves and "partition" in saves[-1]
    last = saves[-1]

    class Fixed:
        every_s = 1e9

        def load(self):
            return last

        def save(self, s):
            pass

    res = mine_spade_tpu(db, ms, partition_parts=2, checkpoint=Fixed())
    assert patterns_text(res) == want


def test_cspade_partitioned_parity():
    from spark_fsm_tpu.models.spade_constrained import mine_cspade_tpu

    db = _db(seed=21, n=203, items=12)
    ms = abs_minsup(0.06, len(db))
    want = patterns_text(mine_cspade(db, ms, maxgap=2, maxwindow=5))
    stats: dict = {}
    got = mine_cspade_tpu(db, ms, maxgap=2, maxwindow=5,
                          partition_parts=2, chunk=64, node_batch=8,
                          pool_bytes=1 << 20, stats_out=stats)
    assert patterns_text(got) == want
    assert stats["partition_parts"] == 2


# ------------------------------------------------------- metrics hygiene


def test_partition_metric_families_zero_seeded():
    """Every fsm_partition_* family renders on a fresh scrape with its
    label vocabulary seeded (the obs_smoke no-orphan contract applied
    to the new names)."""
    from spark_fsm_tpu.utils import obs

    text = obs.REGISTRY.render_prometheus()
    for fam in ("fsm_partition_plans_total",
                "fsm_partition_exchange_rounds_total",
                "fsm_partition_cross_bytes_total",
                "fsm_partition_imbalance_ratio",
                "fsm_partition_mines_total"):
        assert fam in text, f"family missing from scrape: {fam}"
    for algo in ("tsr", "spade", "cspade"):
        assert f'fsm_partition_mines_total{{algo="{algo}"}}' in text


def test_partition_config_resolution():
    from spark_fsm_tpu import config as cfgmod
    from spark_fsm_tpu.config import ConfigError, parse_config
    from spark_fsm_tpu.service.plugins import resolved_partition_parts

    old = cfgmod.get_config()
    try:
        cfgmod.set_config(parse_config({}))
        assert resolved_partition_parts() == 0  # disabled by default
        cfgmod.set_config(parse_config(
            {"partition": {"enabled": True, "parts": 4}}))
        assert resolved_partition_parts() == 4
        cfgmod.set_config(parse_config(
            {"partition": {"enabled": True},
             "engine": {"mesh_devices": 8}}))
        assert resolved_partition_parts() == 2  # auto: mesh >= 2 devs
        cfgmod.set_config(parse_config({"partition": {"enabled": True}}))
        assert resolved_partition_parts() == 0  # no mesh, one process
        # auto on an odd mesh: no even split exists — stay off rather
        # than 500 every request at submeshes()
        cfgmod.set_config(parse_config(
            {"partition": {"enabled": True},
             "engine": {"mesh_devices": 3}}))
        assert resolved_partition_parts() == 0
        # explicit parts that cannot split the topology degrade to
        # unpartitioned (logged) instead of failing every train
        cfgmod.set_config(parse_config(
            {"partition": {"enabled": True, "parts": 3},
             "engine": {"mesh_devices": 8}}))
        assert resolved_partition_parts() == 0
        with pytest.raises(ConfigError):
            parse_config({"partition": {"parts": -1}})
        with pytest.raises(ConfigError):
            parse_config({"partition": {"parts": 8, "classes": 4}})
    finally:
        cfgmod.set_config(old)
