"""Streaming incremental SPADE (SURVEY.md sec 2.5, eval config #5).

The binding property: after EVERY micro-batch push, the window's mined
pattern set is byte-identical to a fresh oracle mine of exactly the
window's sequences — the stream changes when mining happens, never what
is mined.
"""

import json
import time
import urllib.parse
import urllib.request

import pytest

from spark_fsm_tpu.data.spmf import format_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.streaming.window import SlidingWindow, WindowMiner
from spark_fsm_tpu.utils.canonical import patterns_text


def _batches(seed, n, size, n_items=10):
    db = synthetic_db(seed=seed, n_sequences=n * size, n_items=n_items,
                      mean_itemsets=4.0)
    return [db[i * size:(i + 1) * size] for i in range(n)]


# ---------------------------------------------------------------- window


def test_window_count_eviction():
    w = SlidingWindow(max_batches=2)
    b1, b2, b3 = _batches(seed=1, n=3, size=5)
    assert w.push(b1) == 0 and w.n_sequences == 5
    assert w.push(b2) == 0 and w.n_sequences == 10
    assert w.push(b3) == 1  # b1 evicted
    assert w.n_batches == 2 and w.n_sequences == 10
    assert w.sequences() == list(b2) + list(b3)
    assert w.evicted_batches == 1


def test_window_sequence_cap_eviction():
    w = SlidingWindow(max_sequences=12)
    b1, b2, b3 = _batches(seed=2, n=3, size=5)
    w.push(b1); w.push(b2)
    assert w.n_sequences == 10  # under cap, nothing evicted
    w.push(b3)
    assert w.n_sequences == 10 and w.n_batches == 2  # b1 evicted
    # a single oversized batch is kept (eviction never empties the window)
    w2 = SlidingWindow(max_sequences=3)
    w2.push(b1)
    assert w2.n_batches == 1 and w2.n_sequences == 5


def test_window_item_supports_match_rescan():
    w = SlidingWindow(max_batches=2)
    for b in _batches(seed=3, n=3, size=8):
        w.push(b)
        got = w.item_supports()
        want = {}
        for seq in w.sequences():
            for it in {i for s in seq for i in s}:
                want[it] = want.get(it, 0) + 1
        assert dict(got) == want


# ------------------------------------------------------- incremental mine


@pytest.mark.parametrize("rel_support", [0.2, 3.0])
def test_window_miner_parity_over_batches(rel_support):
    """Each of 4 pushes (with eviction after the 2nd) mines a pattern set
    byte-identical to a fresh oracle mine of the window's sequences."""
    miner = WindowMiner(rel_support, max_batches=2)
    for b in _batches(seed=4, n=4, size=20):
        got = miner.push(b)
        seqs = miner.window.sequences()
        minsup = (int(rel_support) if rel_support >= 1
                  else abs_minsup(rel_support, len(seqs)))
        want = mine_spade(seqs, minsup)
        assert patterns_text(got) == patterns_text(want)
    assert miner.window.evicted_batches == 2
    assert miner.stats["mines"] == 4


def test_window_miner_minsup_tracks_window_size():
    miner = WindowMiner(0.5, max_batches=3)
    miner.push(_batches(seed=5, n=1, size=10)[0])
    assert miner.minsup_abs() == 5
    miner.push(_batches(seed=6, n=1, size=30)[0])
    assert miner.minsup_abs() == 20  # 0.5 * 40


# ---------------------------------------------------------------- service


@pytest.fixture(scope="module")
def server():
    from spark_fsm_tpu.service.app import serve_background

    srv = serve_background()
    yield srv
    srv.master.shutdown()
    srv.shutdown()


def _post(server, endpoint, **params):
    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{server.server_port}{endpoint}"
    with urllib.request.urlopen(url, data=data, timeout=60) as resp:
        return json.loads(resp.read().decode())


def test_stream_service_lifecycle(server):
    from spark_fsm_tpu.service.model import deserialize_patterns
    from spark_fsm_tpu.utils.canonical import sort_patterns

    batches = _batches(seed=7, n=3, size=15)
    window = []
    for i, b in enumerate(batches):
        resp = _post(server, "/stream/clickwin", sequences=format_spmf(b),
                     support="0.2", max_batches="2", algorithm="SPADE_TPU")
        assert resp["status"] == "finished", resp
        window = (window + [b])[-2:]
        seqs = [s for bb in window for s in bb]
        assert resp["data"]["window_sequences"] == str(len(seqs))
        got = _post(server, "/get/patterns", uid="stream:clickwin")
        assert got["status"] == "finished"
        patterns = deserialize_patterns(got["data"]["patterns"])
        want = mine_spade(seqs, abs_minsup(0.2, len(seqs)))
        assert patterns_text(sort_patterns(patterns)) == patterns_text(want)
    # third push evicted the first batch
    assert resp["data"]["evicted_batches"] == "1"


def test_stream_routes_incremental_by_default(server):
    # plain single-device SPADE_TPU windows ride the true incremental
    # path; incremental=0 pins the re-mine fallback; constraints force it
    b = format_spmf(_batches(seed=9, n=1, size=12)[0])
    resp = _post(server, "/stream/increq", sequences=b, support="0.3",
                 max_batches="2", algorithm="SPADE_TPU")
    assert resp["status"] == "finished", resp
    st = _post(server, "/status/stream:increq")
    assert json.loads(st["data"]["stats"])["route"] == "incremental"

    resp = _post(server, "/stream/rmq", sequences=b, support="0.3",
                 max_batches="2", algorithm="SPADE_TPU", incremental="0")
    assert resp["status"] == "finished", resp
    st = _post(server, "/status/stream:rmq")
    assert json.loads(st["data"]["stats"])["route"] == "re-mine"

    resp = _post(server, "/stream/cstrq", sequences=b, support="0.3",
                 max_batches="2", algorithm="SPADE_TPU", maxgap="2")
    assert resp["status"] == "finished", resp
    st = _post(server, "/status/stream:cstrq")
    assert json.loads(st["data"]["stats"])["route"] == "re-mine"


def test_stream_constrained_and_rules(server):
    # constrained SPADE over a sliding window
    batches = _batches(seed=8, n=2, size=20)
    for b in batches:
        resp = _post(server, "/stream/cwin", sequences=format_spmf(b),
                     support="0.2", maxgap="2", max_batches="2",
                     algorithm="SPADE_TPU")
        assert resp["status"] == "finished", resp
    from spark_fsm_tpu.models.oracle import mine_cspade
    from spark_fsm_tpu.service.model import deserialize_patterns
    from spark_fsm_tpu.utils.canonical import sort_patterns

    seqs = [s for b in batches for s in b]
    got = _post(server, "/get/patterns", uid="stream:cwin")
    patterns = deserialize_patterns(got["data"]["patterns"])
    want = mine_cspade(seqs, abs_minsup(0.2, len(seqs)), maxgap=2)
    assert patterns_text(sort_patterns(patterns)) == patterns_text(want)

    # TSR rules over a sliding window reuse the same seam
    resp = _post(server, "/stream/rulewin", sequences=format_spmf(batches[0]),
                 algorithm="TSR_TPU", k="10", minconf="0.5", max_side="2")
    assert resp["status"] == "finished", resp
    got = _post(server, "/get/rules", uid="stream:rulewin")
    assert got["status"] == "finished"
    assert json.loads(got["data"]["rules"])


def test_stream_errors(server):
    resp = _post(server, "/stream/", sequences="1 -2")
    assert resp["status"] == "failure"
    resp = _post(server, "/stream/nobatch")
    assert resp["status"] == "failure"
    assert "sequences" in resp["data"]["error"]
    resp = _post(server, "/stream/badalgo", sequences="1 -2",
                 algorithm="NOPE")
    assert resp["status"] == "failure"
    # a zero-capacity window would evict every pushed batch and serve an
    # empty result set forever with status=finished — must be rejected
    resp = _post(server, "/stream/zerowin", sequences="1 -2",
                 max_batches="0")
    assert resp["status"] == "failure"
    assert "max_batches" in resp["data"]["error"]


def test_window_rejects_nonpositive_caps():
    import pytest

    with pytest.raises(ValueError, match="max_batches"):
        SlidingWindow(max_batches=0)
    with pytest.raises(ValueError, match="max_sequences"):
        SlidingWindow(max_sequences=-1)


def test_stream_window_survives_restart():
    """The window state is persisted: a new Master over the same store
    (simulating a service restart) continues the stream exactly — the
    post-restart push mines the true window, not a truncated one."""
    from spark_fsm_tpu.service.actors import Master
    from spark_fsm_tpu.service.model import (
        ServiceRequest, deserialize_patterns)
    from spark_fsm_tpu.service.store import ResultStore
    from spark_fsm_tpu.utils.canonical import sort_patterns

    store = ResultStore()
    batches = _batches(seed=11, n=4, size=12)

    def push(master, batch):
        return master.handle(ServiceRequest("fsm", "stream:rwin", {
            "sequences": format_spmf(batch), "support": "0.2",
            "max_batches": "2", "algorithm": "SPADE"}))

    m1 = Master(store=store)
    try:
        for b in batches[:3]:
            assert push(m1, b).status == "finished"
    finally:
        m1.shutdown()

    m2 = Master(store=store)  # restart: fresh process state, same store
    try:
        # served results are durable without any push
        patterns = deserialize_patterns(store.patterns("stream:rwin"))
        seqs = [s for bb in batches[1:3] for s in bb]
        want = mine_spade(seqs, abs_minsup(0.2, len(seqs)))
        assert patterns_text(sort_patterns(patterns)) == patterns_text(want)
        # the post-restart push slides the RESTORED window (batches 2,3 ->
        # 3,4), not an empty one
        resp = push(m2, batches[3])
        assert resp.status == "finished"
        assert resp.data["window_batches"] == "2"
        seqs = [s for bb in batches[2:4] for s in bb]
        assert resp.data["window_sequences"] == str(len(seqs))
        patterns = deserialize_patterns(store.patterns("stream:rwin"))
        want = mine_spade(seqs, abs_minsup(0.2, len(seqs)))
        assert patterns_text(sort_patterns(patterns)) == patterns_text(want)
        # cumulative counters survive the restart (4 pushes total, and the
        # restore's window refill did not inflate them)
        stats = json.loads(store.get("fsm:stats:stream:rwin"))
        assert stats["pushes"] == 4
        assert resp.data["evicted_batches"] == "2"
    finally:
        m2.shutdown()


def test_stream_persisted_window_tracks_failed_mine():
    """The window mutates before the mine runs, so a failed mine must
    still persist the appended batch — otherwise a restart restores a
    window diverged from the live one."""
    from spark_fsm_tpu.service import plugins
    from spark_fsm_tpu.service.actors import Master
    from spark_fsm_tpu.service.model import ServiceRequest
    from spark_fsm_tpu.service.store import ResultStore

    calls = {"n": 0}

    def extract(req, db, stats=None, checkpoint=None):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("mine blew up")
        return plugins._spade_cpu(req, db, stats)

    plugins.ALGORITHMS["FLAKY_STREAM"] = plugins.AlgorithmPlugin(
        "FLAKY_STREAM", "patterns", extract)
    store = ResultStore()
    master = Master(store=store)
    try:
        def push(seqs):
            return master.handle(ServiceRequest("fsm", "stream:fwin", {
                "sequences": seqs, "support": "0.5", "max_batches": "4",
                "algorithm": "FLAKY_STREAM"}))

        assert push("1 -1 2 -2\n").status == "finished"
        assert push("3 -1 2 -2\n").status == "failure"  # mine #2 raises
        persisted = store.lrange("fsm:stream:window:fwin")
        assert len(persisted) == 2  # failed mine's batch IS in the window
        # a restarted service restores the full 2-batch window
        master.streamer._topics.clear()
        resp = push("2 -1 1 -2\n")
        assert resp.status == "finished"
        assert resp.data["window_batches"] == "3"
        assert len(store.lrange("fsm:stream:window:fwin")) == 3
    finally:
        del plugins.ALGORITHMS["FLAKY_STREAM"]
        master.shutdown()


# -------------------------------------------------------- poll consumer


def _queue_fetch(q):
    """The broker stand-in: a non-blocking queue poll (the Kafka-consumer
    shape — None when nothing is available right now)."""
    import queue as _queue

    from spark_fsm_tpu.streaming.consumer import StopConsumer

    def fetch():
        try:
            item = q.get_nowait()
        except _queue.Empty:
            return None
        if item is StopConsumer:
            raise StopConsumer()
        return item

    return fetch


def test_poll_consumer_drains_queue_with_window_parity():
    import queue

    from spark_fsm_tpu.streaming.consumer import PollConsumer, StopConsumer

    batches = _batches(seed=31, n=4, size=8)
    q = queue.Queue()
    for b in batches:
        q.put(b)
    q.put(StopConsumer)

    wm = WindowMiner(0.2, max_batches=2,
                     mine=lambda db, ms: mine_spade(db, ms))
    seen = []
    pc = PollConsumer(_queue_fetch(q), wm.push, poll_interval_s=0,
                      on_result=seen.append)
    stats = pc.run()
    assert stats["stopped"] == "end_of_stream"
    assert stats["batches"] == 4
    assert stats["sequences"] == 32
    assert wm.stats["pushes"] == 4
    # the final window state is byte-identical to a fresh oracle mine of
    # exactly the window's sequences (the streaming determinism contract)
    want = mine_spade(wm.window.sequences(), wm.minsup_abs())
    assert patterns_text(wm.patterns) == patterns_text(want)
    # on_result saw every push's pattern set; the last one is current
    assert len(seen) == 4 and seen[-1] == wm.patterns


def test_poll_consumer_idle_and_empty_batches():
    import queue

    from spark_fsm_tpu.streaming.consumer import PollConsumer

    (batch,) = _batches(seed=32, n=1, size=6)
    q = queue.Queue()
    q.put([])      # empty batch = idle, never pushed (would evict data)
    q.put(batch)
    wm = WindowMiner(0.5, max_batches=3,
                     mine=lambda db, ms: mine_spade(db, ms))
    pc = PollConsumer(_queue_fetch(q), wm.push, poll_interval_s=0)
    stats = pc.run(max_polls=4)  # 1 empty + 1 batch + 2 idle polls
    assert stats["stopped"] == "max_polls"
    assert stats["batches"] == 1
    assert stats["idle_polls"] == 3
    assert wm.stats["pushes"] == 1


def test_poll_consumer_flaky_fetch_keeps_polling():
    from spark_fsm_tpu.streaming.consumer import PollConsumer

    (batch,) = _batches(seed=33, n=1, size=5)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("broker hiccup")
        return batch if calls["n"] == 3 else None

    wm = WindowMiner(0.5, max_batches=2,
                     mine=lambda db, ms: mine_spade(db, ms))
    errors = []
    pc = PollConsumer(flaky, wm.push, poll_interval_s=0,
                      on_error=errors.append)
    stats = pc.run(max_polls=4)
    assert stats["errors"] == 2 and len(errors) == 2
    assert isinstance(errors[0], ConnectionError)
    assert stats["batches"] == 1  # recovered and consumed the batch
    assert wm.stats["pushes"] == 1


def test_poll_consumer_error_bound_stops_loop():
    from spark_fsm_tpu.streaming.consumer import PollConsumer

    def broken():
        raise ConnectionError("broker down")

    pc = PollConsumer(broken, lambda b: None, poll_interval_s=0,
                      max_consecutive_errors=3)
    stats = pc.run()
    assert stats["stopped"] == "errors"
    assert stats["errors"] == 3


def test_poll_consumer_backpressure_pauses_and_resumes():
    """Watermark backpressure (ISSUE 5): the consumer stops touching the
    broker once the downstream queue hits the high watermark and resumes
    only after it drains to the low one — batches wait at the broker
    instead of being shed by the admission queue."""
    import queue

    from spark_fsm_tpu.streaming.consumer import (PollConsumer,
                                                  consumer_health)

    batches = _batches(seed=35, n=3, size=5)
    q = queue.Queue()
    for b in batches:
        q.put(b)
    # scripted downstream depth: fills to the high watermark, then drains
    depths = iter([0, 4, 4, 3, 1, 0, 0, 0, 0, 0])
    wm = WindowMiner(0.5, max_batches=3,
                     mine=lambda db, ms: mine_spade(db, ms))
    base = consumer_health()["backpressure_pauses"]
    pc = PollConsumer(_queue_fetch(q), wm.push, poll_interval_s=0,
                      queue_depth_fn=lambda: next(depths),
                      pause_at=4, resume_at=1)
    stats = pc.run(max_polls=10)
    # depth 0 -> one batch consumed; depth 4 pauses; depths 4/4/3 hold
    # the loop; depth 1 resumes; the remaining batches then drain
    assert stats["batches"] == 3
    assert stats["backpressure_pauses"] == 1
    assert stats["backpressure_resumes"] == 1
    assert stats["paused_polls"] == 3  # depths 4, 4, 3 held the loop
    assert consumer_health()["backpressure_pauses"] == base + 1
    # no batch was lost or reordered while paused
    want = mine_spade(wm.window.sequences(), wm.minsup_abs())
    assert patterns_text(wm.patterns) == patterns_text(want)


def test_poll_consumer_backpressure_depth_probe_fails_open():
    import queue

    from spark_fsm_tpu.streaming.consumer import PollConsumer

    (batch,) = _batches(seed=36, n=1, size=5)
    q = queue.Queue()
    q.put(batch)

    def broken_gauge():
        raise RuntimeError("stats endpoint down")

    wm = WindowMiner(0.5, max_batches=2,
                     mine=lambda db, ms: mine_spade(db, ms))
    errors = []
    pc = PollConsumer(_queue_fetch(q), wm.push, poll_interval_s=0,
                      on_error=errors.append,
                      queue_depth_fn=broken_gauge, pause_at=2, resume_at=0)
    stats = pc.run(max_polls=2)
    # the broken gauge is reported but polling continues (fail open):
    # the batch is consumed, nothing starves
    assert stats["batches"] == 1
    assert stats["errors"] >= 1 and errors
    assert stats["paused_polls"] == 0


def test_poll_consumer_backpressure_validation():
    from spark_fsm_tpu.streaming.consumer import PollConsumer

    with pytest.raises(ValueError, match="pause_at"):
        PollConsumer(lambda: None, lambda b: None,
                     queue_depth_fn=lambda: 0)
    with pytest.raises(ValueError, match="resume_at"):
        PollConsumer(lambda: None, lambda b: None,
                     queue_depth_fn=lambda: 0, pause_at=2, resume_at=2)
    with pytest.raises(ValueError, match="queue_depth_fn"):
        PollConsumer(lambda: None, lambda b: None, pause_at=2)


def test_poll_consumer_background_thread_stop():
    import queue

    from spark_fsm_tpu.streaming.consumer import PollConsumer

    batches = _batches(seed=34, n=2, size=5)
    q = queue.Queue()
    for b in batches:
        q.put(b)
    wm = WindowMiner(0.5, max_batches=2,
                     mine=lambda db, ms: mine_spade(db, ms))
    pc = PollConsumer(_queue_fetch(q), wm.push, poll_interval_s=0.01)
    pc.start()
    deadline = time.time() + 10
    while wm.stats["pushes"] < 2 and time.time() < deadline:
        time.sleep(0.01)
    pc.stop()
    assert wm.stats["pushes"] == 2
    assert pc.stats["stopped"] == "stop"
    # stopped loop stays stopped; start() is idempotent on a dead thread
    q.put(batches[0])
    pc.start(max_polls=2)
    deadline = time.time() + 10
    while pc.stats["batches"] < 3 and time.time() < deadline:
        time.sleep(0.01)
    pc.stop()
    assert pc.stats["batches"] == 3


def test_poll_consumer_feeds_service_stream(server):
    # The full Kafka-to-service shape: a PollConsumer drains an
    # in-process queue (the broker stand-in) and POSTs each micro-batch
    # to /stream/{topic}; the window's served pattern set after the drain
    # is byte-identical to a fresh oracle mine of the live window.
    import queue

    from spark_fsm_tpu.service.model import deserialize_patterns
    from spark_fsm_tpu.streaming.consumer import PollConsumer, StopConsumer
    from spark_fsm_tpu.utils.canonical import sort_patterns

    batches = _batches(seed=41, n=3, size=12)
    q = queue.Queue()
    for b in batches:
        q.put(b)
    q.put(StopConsumer)

    def sink(batch):
        resp = _post(server, "/stream/pollwin", sequences=format_spmf(batch),
                     support="0.2", max_batches="2", algorithm="SPADE_TPU")
        assert resp["status"] == "finished", resp
        return resp

    errors = []  # surface sink assertion failures with their server
    pc = PollConsumer(_queue_fetch(q), sink, poll_interval_s=0,  # response
                      on_error=errors.append)
    stats = pc.run()
    assert not errors, errors
    assert stats["stopped"] == "end_of_stream" and stats["batches"] == 3

    got = _post(server, "/get/patterns", uid="stream:pollwin")
    patterns = deserialize_patterns(got["data"]["patterns"])
    window = [s for b in batches[-2:] for s in b]  # keep 2 of 3
    want = mine_spade(window, abs_minsup(0.2, len(window)))
    assert patterns_text(sort_patterns(patterns)) == patterns_text(want)


def test_stream_task_buckets_device_shapes():
    # Streaming pushes through the SERVICE plugin boundary must bucket
    # the device shapes (the window drifts every micro-batch; without
    # bucketing every push recompiles the kernel chain), while a plain
    # train request keeps exact shapes.  shape_key encodes the compiled
    # geometry: pow2-bucketed seq axis for the stream task.
    from spark_fsm_tpu.service import plugins
    from spark_fsm_tpu.service.model import ServiceRequest

    db = _batches(seed=51, n=1, size=50)[0]  # 50 seqs -> bucket 128
    data = {"algorithm": "SPADE_TPU", "support": "0.2"}
    stats_stream: dict = {}
    plug = plugins.get_plugin(ServiceRequest("fsm", "stream", data))
    plug.extract(ServiceRequest("fsm", "stream", data), db,
                 stats=stats_stream)
    assert ":s128" in stats_stream["shape_key"], stats_stream["shape_key"]

    stats_train: dict = {}
    plug = plugins.get_plugin(ServiceRequest("fsm", "train", data))
    plug.extract(ServiceRequest("fsm", "train", data), db,
                 stats=stats_train)
    # CPU backend (conftest): an unbucketed 50-seq train mine compiles at
    # the exact size — strictly stronger than asserting "not bucketed"
    assert ":s50" in stats_train["shape_key"], stats_train["shape_key"]
