"""Worker process for the 2-process multi-host parity test.

Launched by tests/test_multihost.py with args ``<coordinator_port>
<process_id>`` and 4 virtual CPU devices per process: the two workers
rendezvous through jax.distributed, form one 8-device global mesh, and each
runs the identical SPMD mining loop — the rebuild's DCN story (SURVEY.md
sec 2.2 rows 3-4) exercised for real, not mocked.
"""

import sys


def main() -> None:
    port, pid = int(sys.argv[1]), int(sys.argv[2])
    import jax

    jax.config.update("jax_platforms", "cpu")
    from spark_fsm_tpu.parallel.multihost import (
        init_distributed, is_multiprocess, shutdown_distributed)

    init_distributed(coordinator_address=f"127.0.0.1:{port}",
                     num_processes=2, process_id=pid)
    assert is_multiprocess(), jax.process_count()
    assert len(jax.devices()) == 8, jax.devices()
    assert len(jax.local_devices()) == 4, jax.local_devices()

    from spark_fsm_tpu.data.synth import synthetic_db
    from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical
    from spark_fsm_tpu.models.oracle import mine_spade
    from spark_fsm_tpu.models.spade_tpu import SpadeTPU
    from spark_fsm_tpu.parallel.mesh import make_mesh
    from spark_fsm_tpu.utils.canonical import patterns_text

    mesh = make_mesh()  # all 8 devices across both processes
    db = synthetic_db(seed=21, n_sequences=203, n_items=12,
                      mean_itemsets=4.0, mean_itemset_size=1.3)
    minsup = abs_minsup(0.06, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    eng = SpadeTPU(vdb, minsup, mesh=mesh, node_batch=16,
                   pool_bytes=32 << 20)
    assert eng._multiproc
    got = eng.mine()
    want = mine_spade(db, minsup)
    ok = patterns_text(got) == patterns_text(want)

    # the Pallas pair-support path must survive multi-controller too
    # (per-shard kernel launch inside shard_map + psum; interpret mode on
    # the CPU backend, the same program a real multi-host TPU runs)
    eng_k = SpadeTPU(vdb, minsup, mesh=mesh, node_batch=16,
                     pool_bytes=64 << 20, use_pallas=True)
    assert eng_k.use_pallas and eng_k._multiproc
    got_k = eng_k.mine()
    ok_k = patterns_text(got_k) == patterns_text(want)
    assert "pallas_fallback" not in eng_k.stats, eng_k.stats

    # constrained + TSR engines ride the same multi-host mesh
    from spark_fsm_tpu.models.oracle import mine_cspade
    from spark_fsm_tpu.models.spade_constrained import mine_cspade_tpu
    from spark_fsm_tpu.models.tsr import mine_tsr_cpu, mine_tsr_tpu
    from spark_fsm_tpu.utils.canonical import rules_text

    cgot = mine_cspade_tpu(db, minsup, maxgap=2, maxwindow=5, mesh=mesh,
                           chunk=64, node_batch=8, pool_bytes=1 << 20)
    ok_c = patterns_text(cgot) == patterns_text(
        mine_cspade(db, minsup, maxgap=2, maxwindow=5))
    rgot = mine_tsr_tpu(db, 15, 0.5, max_side=2, mesh=mesh)
    rwant = rules_text(mine_tsr_cpu(db, 15, 0.5, max_side=2))
    ok_r = rules_text(rgot) == rwant
    # the Pallas rule-support kernel under multi-controller (interpret
    # mode on CPU — the same program a real multi-host TPU runs)
    rgot_k = mine_tsr_tpu(db, 15, 0.5, max_side=2, mesh=mesh,
                          use_pallas=True)
    ok_r = ok_r and rules_text(rgot_k) == rwant

    # the fused whole-mine-on-device engine under multi-controller: every
    # process runs the one compiled program on replicated frontier state
    # and reconstructs the identical record buffer
    from spark_fsm_tpu.models.spade_fused import FusedCaps, FusedSpadeTPU

    # use_pallas=True (interpret mode on CPU) so the kernel branch of the
    # fused program — what a real multi-host TPU runs — is the one tested,
    # mirroring eng_k above
    feng = FusedSpadeTPU(vdb, minsup, mesh=mesh, caps=FusedCaps(f_cap=256),
                         use_pallas=True)
    fgot = feng.mine()
    ok_f = fgot is not None and patterns_text(fgot) == patterns_text(want)

    # streaming over the same multi-host mesh (SURVEY.md sec 2.5 meets
    # sec 2.2): every process pushes the identical micro-batches; the
    # shape-bucketed window re-mines run the one compiled program per
    # bucket and every process computes the identical pattern set
    from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu
    from spark_fsm_tpu.streaming.window import WindowMiner

    wm = WindowMiner(0.1, max_batches=2,
                     mine=lambda d, ms: mine_spade_tpu(
                         d, ms, mesh=mesh, shape_buckets=True,
                         pool_bytes=32 << 20, node_batch=16))
    ok_s = True
    for lo in (0, 70, 140):
        wm.push(db[lo:lo + 70])
        wwant = mine_spade(wm.window.sequences(), wm.minsup_abs())
        ok_s &= patterns_text(wm.patterns) == patterns_text(wwant)

    # PARTITIONED route across the REAL process boundary: the 2-D
    # hosts x seq regime — each process enumerates ONLY its own
    # equivalence classes over its process-LOCAL 4-device inner row
    # (no per-wave collective crosses DCN), and the per-round exchange
    # (one tiny all-gather) restores the byte-identical global top-k.
    # This is the partition layer's actual deployment shape; the
    # single-process 8-device tier-1 coverage (tests/test_partition.py)
    # proves routing/balance/threshold logic, THIS proves the DCN seam.
    pstats = {}
    pgot = mine_tsr_tpu(db, 15, 0.5, max_side=2, mesh=mesh,
                        partition_parts=2, stats_out=pstats)
    ok_p = rules_text(pgot) == rwant
    ok_p = ok_p and pstats.get("partition_exchanges", 0) >= 1
    # each process mined exactly its one owned partition
    ok_p = ok_p and pstats.get("partition_owned") == [pid]

    print(f"MULTIHOST_OK pid={pid} patterns={len(got)} parity={ok} "
          f"pallas_parity={ok_k} cspade_parity={ok_c} tsr_parity={ok_r} "
          f"fused_parity={ok_f} stream_parity={ok_s} "
          f"partition_parity={ok_p}",
          flush=True)
    assert ok and ok_k and ok_c and ok_r and ok_f and ok_s and ok_p
    shutdown_distributed()


if __name__ == "__main__":
    main()
