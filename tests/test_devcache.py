"""Device-store cache for repeat /train mines (service/devcache.py)."""

import json
import urllib.parse
import urllib.request

import pytest

from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.service.devcache import SpadeEngineCache, db_fingerprint
from spark_fsm_tpu.utils.canonical import patterns_text


def _db(seed=5, n=120):
    return synthetic_db(seed=seed, n_sequences=n, n_items=12,
                        mean_itemsets=3.0)


def test_fingerprint_is_content_exact():
    a, b = _db(5), _db(5)
    assert db_fingerprint(a) == db_fingerprint(b)
    assert db_fingerprint(a) != db_fingerprint(_db(6))
    # any mutation — even one item of one sequence — must change the key
    c = [list(map(list, s)) for s in _db(5)]
    c[3][0][0] += 1
    assert db_fingerprint(c) != db_fingerprint(a)


def test_repeat_mine_hits_and_matches_oracle():
    cache = SpadeEngineCache()
    db = _db()
    want = mine_spade(db, 6)
    s1, s2 = {}, {}
    r1 = cache.mine(db, 6, stats_out=s1)
    r2 = cache.mine(db, 6, stats_out=s2)
    assert patterns_text(r1) == patterns_text(r2) == patterns_text(want)
    assert s1["store_cache_hit"] is False
    assert s2["store_cache_hit"] is True
    assert cache.stats["hits"] == 1 and cache.stats["misses"] == 1


def test_key_covers_minsup_and_data():
    cache = SpadeEngineCache()
    db = _db()
    cache.mine(db, 6, stats_out={})
    s = {}
    cache.mine(db, 8, stats_out=s)       # same data, new minsup: miss
    assert s["store_cache_hit"] is False
    s = {}
    cache.mine(_db(9), 6, stats_out=s)   # new data: miss
    assert s["store_cache_hit"] is False
    assert cache.stats["hits"] == 0
    # and each entry still answers correctly afterwards
    s = {}
    got = cache.mine(db, 8, stats_out=s)
    assert s["store_cache_hit"] is True
    assert patterns_text(got) == patterns_text(mine_spade(db, 8))


def test_budget_evicts_lru():
    cache = SpadeEngineCache(budget_bytes=1)  # nothing fits
    db = _db()
    s1, s2 = {}, {}
    cache.mine(db, 6, stats_out=s1)
    cache.mine(db, 6, stats_out=s2)
    assert s2["store_cache_hit"] is False  # too big to ever cache


def test_explicit_engine_kwargs_fall_through_uncached():
    cache = SpadeEngineCache()
    db = _db()
    s = {}
    got = cache.mine(db, 6, stats_out=s, chunk=64)
    assert "store_cache_hit" not in s
    assert patterns_text(got) == patterns_text(mine_spade(db, 6))
    assert not cache.stats["hits"] and not cache.stats["misses"]


def test_classic_fallback_engine_is_cached_too():
    # fused="never" pins classic in the wrapper; the cache's own routing
    # only caches queue/classic — force classic via queue overflow is
    # hard to stage, so pin through fused="queue" on an eligible DB and
    # verify the queue engine is reused (waves stat present on hit)
    cache = SpadeEngineCache()
    db = _db()
    s = {}
    cache.mine(db, 6, stats_out=s, fused="queue")
    s2 = {}
    cache.mine(db, 6, stats_out=s2, fused="queue")
    assert s2["store_cache_hit"] is True and s2.get("fused") == "queue"


@pytest.fixture()
def server():
    from spark_fsm_tpu.service.app import serve_background

    srv = serve_background()
    yield srv
    srv.master.shutdown()
    srv.shutdown()


def _post(server, endpoint, **params):
    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{server.server_port}{endpoint}"
    with urllib.request.urlopen(url, data=data, timeout=60) as resp:
        return json.loads(resp.read().decode())


def test_train_twice_hits_store_cache(server, tmp_path):
    import time

    from spark_fsm_tpu.data.spmf import format_spmf

    path = tmp_path / "repeat.spmf"
    path.write_text(format_spmf(_db()))

    def train(uid):
        r = _post(server, "/train", algorithm="SPADE_TPU", source="FILE",
                  path=str(path), support="6", uid=uid)
        assert r["status"] == "started", r
        for _ in range(100):
            st = _post(server, "/status/" + uid)
            if st["status"] in ("finished", "failure"):
                return st
            time.sleep(0.1)
        raise AssertionError("job did not finish")

    st1 = train("dc1")
    st2 = train("dc2")
    assert json.loads(st1["data"]["stats"])["store_cache_hit"] is False
    assert json.loads(st2["data"]["stats"])["store_cache_hit"] is True
    p1 = _post(server, "/get/patterns", uid="dc1")["data"]["patterns"]
    p2 = _post(server, "/get/patterns", uid="dc2")["data"]["patterns"]
    assert p1 == p2


def test_tsr_repeat_mine_hits_and_matches():
    # VERDICT r4 #7: TSR mines (the framework's longest) now reuse the
    # built engine on repeat /train — a hit skips vertical build + token
    # indexing and returns the identical rule set
    from spark_fsm_tpu.models.tsr import mine_tsr_cpu
    from spark_fsm_tpu.service.devcache import TsrEngineCache
    from spark_fsm_tpu.utils.canonical import rules_text

    cache = TsrEngineCache()
    db = _db()
    want = mine_tsr_cpu(db, 10, 0.4, max_side=2)
    s1, s2 = {}, {}
    r1 = cache.mine(db, 10, 0.4, max_side=2, stats_out=s1)
    r2 = cache.mine(db, 10, 0.4, max_side=2, stats_out=s2)
    assert rules_text(r1) == rules_text(r2) == rules_text(want)
    assert s1["store_cache_hit"] is False
    assert s2["store_cache_hit"] is True
    # a parameter change is a different engine: miss, not stale reuse
    s3: dict = {}
    cache.mine(db, 11, 0.4, max_side=2, stats_out=s3)
    assert s3["store_cache_hit"] is False
    assert cache.stats == {"hits": 1, "misses": 2, "busy_misses": 0,
                           "evictions": 0,  # both fit max_entries=2
                           "breaker_fallbacks": 0}
    # a third distinct engine exceeds max_entries: LRU (k=10) drops
    cache.mine(db, 12, 0.4, max_side=2)
    assert cache.stats["evictions"] == 1


def test_tsr_service_route_uses_cache():
    from spark_fsm_tpu.service import plugins
    from spark_fsm_tpu.service.devcache import tsr_engine_cache
    from spark_fsm_tpu.service.model import ServiceRequest

    tsr_engine_cache.clear()
    db = _db(seed=9)
    req = ServiceRequest("fsm", "train", {
        "algorithm": "TSR_TPU", "k": "5", "minconf": "0.3",
        "max_side": "2"})
    s1, s2 = {}, {}
    r1 = plugins.get_plugin(req).extract(req, db, s1)
    r2 = plugins.get_plugin(req).extract(req, db, s2)
    assert r1 == r2
    assert s1["store_cache_hit"] is False
    assert s2["store_cache_hit"] is True


def test_cspade_repeat_mine_hits_and_matches_oracle():
    # the cSPADE half of the repeat-/train story (ISSUE-1 tentpole):
    # the constrained engine keeps its item store + max-start pool
    # across mine() calls, so a repeat hit skips build + construction
    # and returns the byte-identical constrained pattern set
    from spark_fsm_tpu.models.oracle import mine_cspade
    from spark_fsm_tpu.service.devcache import CSpadeEngineCache

    cache = CSpadeEngineCache()
    db = _db(seed=21)
    want = mine_cspade(db, 6, maxgap=2, maxwindow=5)
    s1, s2 = {}, {}
    r1 = cache.mine(db, 6, maxgap=2, maxwindow=5, stats_out=s1)
    r2 = cache.mine(db, 6, maxgap=2, maxwindow=5, stats_out=s2)
    assert patterns_text(r1) == patterns_text(r2) == patterns_text(want)
    assert s1["store_cache_hit"] is False
    assert s2["store_cache_hit"] is True
    assert cache.stats["hits"] == 1 and cache.stats["misses"] == 1


def test_cspade_key_folds_constraints():
    # maxgap/maxwindow select different kernels AND different
    # enumerations — entries must never be shared across constraint
    # pairs, and each entry must keep answering its own pair correctly
    from spark_fsm_tpu.models.oracle import mine_cspade
    from spark_fsm_tpu.service.devcache import CSpadeEngineCache

    cache = CSpadeEngineCache()
    db = _db(seed=22)
    cache.mine(db, 6, maxgap=2, maxwindow=5, stats_out={})
    s = {}
    cache.mine(db, 6, maxgap=1, maxwindow=5, stats_out=s)
    assert s["store_cache_hit"] is False  # different maxgap: miss
    s = {}
    cache.mine(db, 6, maxgap=2, maxwindow=None, stats_out=s)
    assert s["store_cache_hit"] is False  # different maxwindow: miss
    assert cache.stats["hits"] == 0
    s = {}
    got = cache.mine(db, 6, maxgap=1, maxwindow=5, stats_out=s)
    assert s["store_cache_hit"] is True
    assert patterns_text(got) == patterns_text(
        mine_cspade(db, 6, maxgap=1, maxwindow=5))


def test_cspade_checkpoint_and_kwargs_fall_through():
    from spark_fsm_tpu.service.devcache import CSpadeEngineCache

    class Ckpt:
        every_s = 30.0

        def load(self):
            return None

        def save(self, state):
            pass

    cache = CSpadeEngineCache()
    db = _db(seed=23)
    s = {}
    cache.mine(db, 6, maxgap=2, stats_out=s, checkpoint=Ckpt())
    assert "store_cache_hit" not in s  # uncached wrapper path
    s = {}
    cache.mine(db, 6, maxgap=2, stats_out=s, chunk=64)
    assert "store_cache_hit" not in s
    assert not cache.stats["hits"] and not cache.stats["misses"]


def test_checkpointed_mine_reuses_cached_engine():
    """ISSUE-1 acceptance: a checkpoint-resumed mine checks out the
    cached engine and seeds it from the snapshot — the repeat pays
    neither upload nor build, and the resumed result set is exact."""
    from spark_fsm_tpu.data.vertical import abs_minsup
    from spark_fsm_tpu.service.devcache import SpadeEngineCache

    db = _db(seed=24, n=240)
    minsup = abs_minsup(0.05, len(db))
    cache = SpadeEngineCache()
    want = mine_spade(db, minsup)

    # 1. a plain mine populates the cache with the (queue) engine
    s0 = {}
    r0 = cache.mine(db, minsup, stats_out=s0)
    assert patterns_text(r0) == patterns_text(want)
    assert s0["store_cache_hit"] is False

    # 2. a checkpointed job crashes mid-mine, leaving a snapshot
    class Crash(Exception):
        pass

    class CrashingCkpt:
        every_s = 0.0

        def __init__(self):
            self.saved = []
            self.merged = []
            self.crash = True

        def load(self):
            if not self.saved:
                return None
            state = dict(self.saved[-1])
            state["results"] = list(self.merged)
            return state

        def save(self, state):
            assert state["results_done"] == len(self.merged)
            self.merged.extend(state.pop("results"))
            state["results"] = None  # guard: load() rebuilds it
            self.saved.append(state)
            if self.crash and len(self.saved) == 1:
                raise Crash

    ckpt = CrashingCkpt()
    with pytest.raises(Crash):
        cache.mine(db, minsup, stats_out={}, checkpoint=ckpt)
    assert ckpt.saved and ckpt.saved[-1]["stack"], \
        "crash happened after the frontier emptied — lower every_s"

    # 3. the retry resumes ON THE CACHED ENGINE from the snapshot
    ckpt.crash = False
    s2 = {}
    r2 = cache.mine(db, minsup, stats_out=s2, checkpoint=ckpt)
    assert s2["store_cache_hit"] is True, s2
    assert s2.get("resumed_nodes", 0) > 0, s2
    assert patterns_text(r2) == patterns_text(want)


def test_cspade_train_twice_hits_cache_visible_in_admin_stats(server):
    # ISSUE-1 acceptance: a repeat cSPADE /train (same data, same
    # maxgap/maxwindow, same minsup) is a cache hit visible both in the
    # job's own stats and in /admin/stats' cspade_cache counters
    import time

    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.service.devcache import cspade_engine_cache

    cspade_engine_cache.clear()
    hits0 = cspade_engine_cache.stats["hits"]
    db = _db(seed=25)

    def train(uid):
        r = _post(server, "/train", algorithm="SPADE_TPU", source="INLINE",
                  sequences=format_spmf(db), support="6",
                  maxgap="2", maxwindow="5", uid=uid)
        assert r["status"] == "started", r
        for _ in range(200):
            st = _post(server, "/status/" + uid)
            if st["status"] in ("finished", "failure"):
                assert st["status"] == "finished", st
                return st
            time.sleep(0.1)
        raise AssertionError("job did not finish")

    st1 = train("cs1")
    st2 = train("cs2")
    assert json.loads(st1["data"]["stats"])["store_cache_hit"] is False
    assert json.loads(st2["data"]["stats"])["store_cache_hit"] is True
    p1 = _post(server, "/get/patterns", uid="cs1")["data"]["patterns"]
    p2 = _post(server, "/get/patterns", uid="cs2")["data"]["patterns"]
    assert p1 == p2
    admin = _post(server, "/admin/stats")
    assert admin["cspade_cache"]["hits"] >= hits0 + 1, admin
