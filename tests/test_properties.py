"""Property-based tests (hypothesis): the invariants that hold for EVERY
database, not just the seeded fixtures.

Strategy sizes are kept small (the oracle is the per-example cost) and
example counts modest so the whole file stays interactive; the point is
randomized structural coverage — empty itemsets never exist, duplicate
items collapse, single-sequence DBs, all-identical sequences, etc. —
that seeded generators tend to miss.
"""

import pytest

hypothesis = pytest.importorskip(
    "hypothesis",  # optional test dep: see [project.optional-dependencies]
    reason="property tests need hypothesis (pip install .[test])")
from hypothesis import given, settings, strategies as st  # noqa: E402

from spark_fsm_tpu.data.spmf import format_spmf, parse_spmf
from spark_fsm_tpu.data.vertical import build_vertical
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu
from spark_fsm_tpu.models.tsr import brute_force_rules, mine_tsr_tpu
from spark_fsm_tpu.utils.canonical import (
    diff_patterns, patterns_text, rules_text)

# a SequenceDB: 1-12 sequences of 1-5 itemsets of 1-3 items from a small
# alphabet (small enough that the oracle is instant, rich enough to hit
# repeats, single-item sets, and duplicate sequences)
_itemset = st.frozensets(st.integers(1, 6), min_size=1, max_size=3)
_sequence = st.lists(_itemset, min_size=1, max_size=5).map(
    lambda s: tuple(tuple(sorted(i)) for i in s))
_db = st.lists(_sequence, min_size=1, max_size=12)


@settings(max_examples=40, deadline=None)
@given(_db)
def test_spmf_roundtrip(db):
    # format -> parse is the identity on canonical (sorted-itemset) DBs
    assert parse_spmf(format_spmf(db)) == [tuple(seq) for seq in db]


@settings(max_examples=25, deadline=None)
@given(_db, st.integers(1, 4))
def test_engine_parity_random(db, minsup):
    want = mine_spade(db, minsup)
    got = mine_spade_tpu(db, minsup)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


@settings(max_examples=25, deadline=None)
@given(_db, st.integers(1, 4))
def test_fused_vs_classic_random(db, minsup):
    # all three execution strategies must enumerate identically ("queue"
    # and "dense" pin one fused engine each — "always" would only reach
    # the dense engine on queue overflow, silently dropping its coverage)
    classic = mine_spade_tpu(db, minsup, fused="never")
    for mode in ("queue", "dense"):
        fused = mine_spade_tpu(db, minsup, fused=mode)
        assert patterns_text(classic) == patterns_text(fused), \
            (mode, diff_patterns(classic, fused))


@settings(max_examples=15, deadline=None)
@given(_db, st.sampled_from([0.3, 0.5, 0.8]))
def test_tsr_parity_random(db, minconf):
    want = brute_force_rules(db, 5, minconf, max_side=2)
    got = mine_tsr_tpu(db, 5, minconf, max_side=2)
    assert rules_text(got) == rules_text(want)


@settings(max_examples=25, deadline=None)
@given(_db)
def test_support_monotonicity(db):
    # anti-monotonicity: every pattern's support is <= the support of
    # each of its single-item patterns (a consequence the whole prune
    # logic relies on), and supports never exceed |DB|
    res = mine_spade(db, 1)
    singles = {p[0][0]: s for p, s in res if len(p) == 1 and len(p[0]) == 1}
    for pat, sup in res:
        assert 1 <= sup <= len(db)
        for itemset in pat:
            for it in itemset:
                assert sup <= singles[it]


@settings(max_examples=25, deadline=None)
@given(_db)
def test_vertical_build_supports_match_oracle_singles(db):
    # the vertical DB's per-item sequence supports equal the oracle's
    # 1-pattern supports (the projection the whole mine seeds from)
    vdb = build_vertical(db, min_item_support=1)
    singles = {p[0][0]: s for p, s in mine_spade(db, 1)
               if len(p) == 1 and len(p[0]) == 1}
    got = {int(vdb.item_ids[i]): int(vdb.item_supports[i])
           for i in range(vdb.n_items)}
    assert got == singles


@settings(max_examples=60, deadline=None)
@given(st.text(max_size=200))
def test_parser_total_on_arbitrary_text(text):
    # the service parses CLIENT-supplied text: for arbitrary input the
    # parser must either raise ValueError or return a well-formed DB —
    # never crash differently, hang, or return malformed structures
    try:
        db = parse_spmf(text)
    except ValueError:
        return
    for seq in db:
        assert isinstance(seq, tuple) and seq
        for itemset in seq:
            assert isinstance(itemset, tuple) and itemset
            assert list(itemset) == sorted(set(itemset))
            assert all(isinstance(i, int) and i > 0 for i in itemset)


@settings(max_examples=40, deadline=None)
@given(st.lists(st.integers(-5, 8), min_size=0, max_size=30))
def test_parser_total_on_numeric_token_soup(tokens):
    # all-numeric lines exercise the -1/-2 state machine itself (random
    # text rarely gets past int()): same totality property, plus the
    # round-trip holds for whatever the parser accepted
    line = " ".join(map(str, tokens))
    try:
        db = parse_spmf(line)
    except ValueError:
        return
    assert parse_spmf(format_spmf(db)) == db
