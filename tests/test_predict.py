"""Prediction serving plane (ISSUE 17): ops/rule_trie.py +
service/predictor.py.

The contract at three altitudes:

- **trie unit** (no service): the device trie's scores are
  BYTE-IDENTICAL to an independent brute-force oracle written here —
  over random rule sets with deliberate (confidence, support) ties,
  empty prefixes, no-match prefixes, and top-m truncation at the
  tie-break boundary.  "Byte-identical" is literal: the serialized
  JSON strings compare equal, floats included (docs/DESIGN.md explains
  why the integer-rank kernel makes that a construction, not a test of
  float luck).
- **engine parity**: /predict answers over all three engines' real
  outputs — TSR rules directly, SPADE/SPAM pattern sets through the
  prefix-closure rule derivation — match the oracle, and the TSR path
  additionally matches the live Questor ``get:prediction`` endpoint
  entry-for-entry (the /predict fast path is a drop-in).
- **wave fusion**: N prefixes scored as ONE fused wave are
  byte-identical to the same prefixes scored solo (positional
  disjointness), and a cached artifact is reused across requests.
"""

import json
import random

import pytest

from spark_fsm_tpu import config as cfgmod
from spark_fsm_tpu.data.spmf import format_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.ops import rule_trie
from spark_fsm_tpu.service.actors import Master
from spark_fsm_tpu.service.model import (ServiceRequest,
                                         deserialize_patterns,
                                         deserialize_rules)

DEADLINE_S = 90.0


# ------------------------------------------------------ independent oracle
#
# Deliberately re-derived from the Questor semantics (actors.py
# "prediction" subject), not imported from ops/rule_trie — a shared bug
# cannot hide in a shared implementation.


def oracle_predict(rules, prefix, m):
    have = set(prefix)
    best = {}
    for x, y, sup, supx in rules:
        if supx <= 0 or not set(x) <= have:
            continue
        conf = sup / supx
        for it in y:
            if it in have:
                continue
            cur = best.get(it)
            if cur is None or (conf, sup) > (cur[0], cur[1]):
                best[it] = (conf, sup, supx, x, y)
    ranked = sorted(best.items(),
                    key=lambda kv: (-kv[1][0], -kv[1][1], kv[0]))[:m]
    return [{"item": it, "confidence": conf, "support": sup,
             "antecedent_support": supx,
             "antecedent": list(x), "consequent": list(y)}
            for it, (conf, sup, supx, x, y) in ranked]


def device_predict(rules, prefix, m, **build_kw):
    build_kw.setdefault("depth_floor", 8)  # cover test prefixes longer
    # than the rule set's own antecedent depth (production sizes the
    # artifact from the prefix — service/predictor.py depth_need)
    trie = rule_trie.build_trie(rules, **build_kw)
    return rule_trie.score_wave(trie, [list(prefix)], m)[0]


def assert_bytes_equal(got, want, ctx=""):
    g = json.dumps(got, sort_keys=True)
    w = json.dumps(want, sort_keys=True)
    assert g == w, f"{ctx}: device\n{g}\n!= oracle\n{w}"


def random_rules(rng, n_rules, n_items, *, with_ties=True):
    """Random rule list; with_ties plants exact (sup, supx) collisions
    so the (confidence, support) comparison actually exercises the
    tie-break order."""
    rules = []
    for _ in range(n_rules):
        xlen = rng.randint(1, 3)
        x = tuple(sorted(rng.sample(range(n_items), xlen)))
        rest = [i for i in range(n_items) if i not in x]
        y = tuple(sorted(rng.sample(rest,
                                    rng.randint(1, min(2, len(rest))))))
        supx = rng.randint(1, 12)
        sup = rng.randint(1, supx)
        rules.append((x, y, sup, supx))
    if with_ties and len(rules) >= 4:
        # clone the support numbers of one rule onto another with a different
        # consequent: equal conf AND equal sup, the cross-item tie that
        # must fall through to ascending item id
        x, y, sup, supx = rules[0]
        rest = [i for i in range(n_items) if i not in x and i not in y]
        if rest:
            rules[1] = (x, (rest[0],), sup, supx)
        # and an equal-conf different-sup pair (2/4 == 3/6)
        rules[2] = (rules[2][0], rules[2][1], 2, 4)
        rules[3] = (rules[3][0], rules[3][1], 3, 6)
    return rules


# ------------------------------------------------------------- unit parity


def test_trie_parity_random():
    rng = random.Random(0xF5A)
    for trial in range(25):
        n_items = rng.randint(4, 12)
        rules = random_rules(rng, rng.randint(1, 30), n_items)
        trie = rule_trie.build_trie(rules, depth_floor=8)
        for m in (1, 3, 8):
            for _ in range(4):
                prefix = sorted(rng.sample(range(n_items),
                                           rng.randint(0, min(6, n_items))))
                got = rule_trie.score_wave(trie, [prefix], m)[0]
                assert_bytes_equal(got, oracle_predict(rules, prefix, m),
                                   f"trial={trial} m={m} prefix={prefix}")


def test_empty_prefix_matches_empty_antecedent_rules_only():
    rules = [((1,), (2,), 3, 4), ((), (5,), 2, 8), ((), (6,), 1, 2)]
    got = device_predict(rules, [], 8)
    want = oracle_predict(rules, [], 8)
    assert_bytes_equal(got, want)
    assert [e["item"] for e in got] == [6, 5]  # 0.5 > 0.25


def test_no_match_prefix_returns_empty():
    rules = [((1, 2), (3,), 3, 4), ((4,), (5,), 2, 8)]
    assert device_predict(rules, [9], 8) == []
    assert oracle_predict(rules, [9], 8) == []


def test_observed_items_never_predicted():
    rules = [((1,), (2, 3), 5, 5)]
    got = device_predict(rules, [1, 2], 8)
    assert_bytes_equal(got, oracle_predict(rules, [1, 2], 8))
    assert [e["item"] for e in got] == [3]


def test_topm_tiebreak_truncation():
    # three candidates with IDENTICAL (conf, sup): order is ascending
    # item id, and m=2 must keep exactly the two smallest
    rules = [((1,), (7,), 3, 6), ((1,), (5,), 3, 6), ((1,), (9,), 3, 6),
             # equal conf (1/2), lower sup: sorts after all three
             ((1,), (4,), 1, 2)]
    for m in (1, 2, 3, 8):
        got = device_predict(rules, [1], m)
        assert_bytes_equal(got, oracle_predict(rules, [1], m), f"m={m}")
    assert [e["item"] for e in device_predict(rules, [1], 3)] == [5, 7, 9]


def test_per_item_best_rule_selection_is_first_wins():
    # two rules vote for item 5 with identical (conf, sup) — the oracle
    # keeps the FIRST seen (strict > comparison), and the entry carries
    # that rule's antecedent, not the later equal-scoring one's
    rules = [((1,), (5,), 2, 4), ((2,), (5,), 2, 4)]
    got = device_predict(rules, [1, 2], 4)
    assert_bytes_equal(got, oracle_predict(rules, [1, 2], 4))
    assert got[0]["antecedent"] == [1]  # the first rule's


def test_wave_fusion_byte_invariant():
    rng = random.Random(7)
    rules = random_rules(rng, 40, 10)
    trie = rule_trie.build_trie(rules, depth_floor=8)
    prefixes = [sorted(rng.sample(range(10), rng.randint(0, 5)))
                for _ in range(7)]
    fused = rule_trie.score_wave(trie, prefixes, 5)
    for i, p in enumerate(prefixes):
        solo = rule_trie.score_wave(trie, [p], 5)[0]
        assert_bytes_equal(fused[i], solo, f"row {i}")
        assert_bytes_equal(fused[i], oracle_predict(rules, p, 5))


def test_floors_do_not_change_bytes():
    rng = random.Random(11)
    rules = random_rules(rng, 12, 8)
    for p in ([], [1], [2, 3]):
        tight = device_predict(rules, p, 6, depth_floor=8)
        padded = device_predict(rules, p, 6, lanes_floor=256,
                                depth_floor=16)
        assert_bytes_equal(padded, tight, f"prefix={p}")


def test_rules_from_patterns_prefix_closure():
    # pattern set: <(1)> sup 4, <(1)(2)> sup 3 -> rule (1)->(2) with
    # supx = 4 (the prefix's own support), sup = 3
    rules = rule_trie.rules_from_patterns(
        [(((1,),), 4), (((1,), (2,)), 3), (((1,), (1, 2)), 2)])
    assert ((1,), (2,), 3, 4) in rules
    # last itemset {1,2} minus antecedent items {1} -> consequent (2,)
    assert ((1,), (2,), 2, 4) in rules


# ---------------------------------------------------------- engine parity


@pytest.fixture(scope="module")
def service():
    cfg = cfgmod.parse_config({
        "predict": {"window_ms": 2.0, "lanes_floor": 64,
                    "depth_floor": 8, "max_wave": 4}})
    cfgmod.set_config(cfg)
    m = Master()
    yield m
    m.shutdown()
    cfgmod.set_config(cfgmod.parse_config({}))


def _train(master, algorithm, **extra):
    import time

    db = synthetic_db(seed=21, n_sequences=120, n_items=9,
                      mean_itemsets=4.0)
    req = ServiceRequest(service="fsm", task="train", data={
        "algorithm": algorithm, "source": "INLINE",
        "sequences": format_spmf(db), **extra})
    resp = master.handle(req)
    assert resp.status == "started", resp.data
    uid = resp.data["uid"]
    deadline = time.time() + DEADLINE_S
    while time.time() < deadline:
        s = master.handle(ServiceRequest(service="fsm", task="status",
                                         data={"uid": uid}))
        if s.status == "finished":
            return uid
        assert s.status != "failure", s.data
        time.sleep(0.05)
    raise AssertionError("train timeout")


def _predict(master, uid, items, m="8", **extra):
    resp = master.handle(ServiceRequest(
        service="fsm", task="predict",
        data={"uid": uid, "items": items, "m": m, **extra}))
    assert resp.status == "finished", resp.data
    return (json.loads(resp.data["predictions"]),
            json.loads(resp.data["stats"]))


def _engine_rules(master, uid):
    payload = master.store.rules(uid)
    if payload is not None:
        return deserialize_rules(payload)
    return rule_trie.rules_from_patterns(
        deserialize_patterns(master.store.patterns(uid)))


@pytest.mark.parametrize("algorithm,extra", [
    ("TSR_TPU", {"support": "0.1", "k": "25", "minconf": "0.2"}),
    ("SPADE_TPU", {"support": "0.1"}),
    ("SPAM_TPU", {"support": "0.1"}),
])
def test_predict_engine_parity(service, algorithm, extra):
    uid = _train(service, algorithm, **extra)
    rules = _engine_rules(service, uid)
    assert rules, f"{algorithm}: no rules to serve"
    for items in ("", "1", "1,2", "3,4,5", "99"):
        prefix = sorted({int(i) for i in items.split(",") if i})
        got, stats = _predict(service, uid, items)
        assert_bytes_equal(got, oracle_predict(rules, prefix, 8),
                           f"{algorithm} items={items!r}")
    assert stats["shape_key"].startswith("predict:f")


def test_predict_matches_questor_endpoint(service):
    # the rules-backed fast path is a drop-in for get:prediction —
    # entry-for-entry identical where both serve (Questor has no top-m
    # and requires a non-empty prefix)
    uid = _train(service, "TSR_TPU", support="0.1", k="25", minconf="0.2")
    for items in ("1", "1,2", "2,6"):
        q = service.handle(ServiceRequest(
            service="fsm", task="get:prediction",
            data={"uid": uid, "items": items}))
        assert q.status == "finished", q.data
        want = json.loads(q.data["predictions"])
        got, _ = _predict(service, uid, items, m=str(max(1, len(want))))
        assert_bytes_equal(got, want, f"items={items!r}")


def test_artifact_cache_reuse_and_staleness(service):
    from spark_fsm_tpu.service import predictor as P

    uid = _train(service, "TSR_TPU", support="0.1", k="25", minconf="0.2")
    _predict(service, uid, "1,2")
    hits0 = P._HITS.total()
    _, stats = _predict(service, uid, "1,2")
    assert P._HITS.total() > hits0  # same digest+geometry: no rebuild
    snap = service.predictor.stats()
    assert snap["cache"]["entries"] >= 1
    assert any(r["digest"] == stats["artifact_digest"]
               for r in snap["cache"]["resident"])


def test_predict_validation_errors(service):
    r = service.handle(ServiceRequest(service="fsm", task="predict",
                                      data={"uid": "nope", "items": "1"}))
    assert r.status == "failure"
    r = service.handle(ServiceRequest(service="fsm", task="predict",
                                      data={"items": "1"}))
    assert r.status == "failure"  # neither uid nor fingerprint
    uid = _train(service, "TSR_TPU", support="0.1", k="25", minconf="0.2")
    r = service.handle(ServiceRequest(service="fsm", task="predict",
                                      data={"uid": uid, "items": "a,b"}))
    assert r.status == "failure"


def test_predict_tenant_labeling(service):
    """ISSUE 19 satellite: the read path carries the fairness tenant —
    a KNOWN tenant labels the response stats, the histograms, and the
    per-tenant SLO split; an unregistered one folds to 'default' (the
    label vocabulary stays bounded by the fairness config)."""
    from spark_fsm_tpu.service import obsplane

    obsplane.seed_tenant("predict-acme")
    uid = _train(service, "TSR_TPU", support="0.1", k="25", minconf="0.2")
    _, stats = _predict(service, uid, "1,2", tenant="predict-acme")
    assert stats["tenant"] == "predict-acme"
    # unknown tenants fold to the default label, never mint a new one
    _, stats = _predict(service, uid, "1,2", tenant="nobody-configured")
    assert stats["tenant"] == "default"
    snap = obsplane.slo_snapshot()
    t = snap.get("predict_tenants", {}).get("predict-acme")
    assert t is not None and t.get("count", 0) >= 1
