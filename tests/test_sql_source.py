"""JDBC (SQL) source tests — the reference's JdbcSource seam on sqlite3.

SURVEY.md sec 2 "Sequence sources": rows -> role-mapped events -> grouped
sequences, sharing the field-spec semantics with the TRACKED source.
"""

import json
import sqlite3

import pytest

from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.service.sources import SourceError, get_db, jdbc_source
from spark_fsm_tpu.service.store import ResultStore


def _mkdb(path, rows, cols=("site", "user", "timestamp", "grp", "item")):
    conn = sqlite3.connect(path)
    conn.execute(f"CREATE TABLE clicks ({', '.join(cols)})")
    conn.executemany(
        f"INSERT INTO clicks VALUES ({', '.join('?' * len(cols))})", rows)
    conn.commit()
    conn.close()


def _req(**data):
    return ServiceRequest("fsm", "train", {k: str(v) for k, v in data.items()})


def test_table_with_registered_spec(tmp_path):
    path = str(tmp_path / "clicks.db")
    # two users; user A has groups 10 (items 1,3) then 20 (item 2)
    _mkdb(path, [
        ("s", "A", 100, 10, 1),
        ("s", "A", 105, 10, 3),
        ("s", "A", 200, 20, 2),
        ("s", "B", 50, 7, 4),
    ])
    store = ResultStore()
    # non-default column name 'grp' mapped onto the 'group' role
    store.add_fields("item", json.dumps({"group": "grp"}))
    db = jdbc_source(_req(db=path, table="clicks"), store)
    assert db == [((1, 3), (2,)), ((4,),)]


def test_query_and_url_form(tmp_path):
    path = str(tmp_path / "q.db")
    _mkdb(path, [("s", "A", 1, 1, 9), ("s", "A", 2, 2, 8)])
    store = ResultStore()
    store.add_fields("item", json.dumps({"group": "grp"}))
    db = get_db(_req(source="JDBC", url=f"sqlite:///{path}",
                     query="SELECT * FROM clicks WHERE item > 8"), store)
    assert db == [((9,),)]


def test_column_aliasing_in_query(tmp_path):
    """SQL aliases can do the role mapping instead of a registered spec."""
    path = str(tmp_path / "alias.db")
    conn = sqlite3.connect(path)
    conn.execute("CREATE TABLE ev (host, visitor, at, batch, sku)")
    conn.executemany("INSERT INTO ev VALUES (?,?,?,?,?)", [
        ("h", "v1", 1, 1, 5), ("h", "v1", 2, 2, 6)])
    conn.commit()
    conn.close()
    db = jdbc_source(_req(
        db=path,
        query="SELECT host AS site, visitor AS user, at AS timestamp, "
              "batch AS 'group', sku AS item FROM ev"), ResultStore())
    assert db == [((5,), (6,))]


def test_errors(tmp_path):
    store = ResultStore()
    with pytest.raises(SourceError, match="'db'"):
        jdbc_source(_req(table="clicks"), store)
    with pytest.raises(SourceError, match="'query' or 'table'"):
        jdbc_source(_req(db=str(tmp_path / "x.db")), store)
    with pytest.raises(SourceError, match="invalid table name"):
        jdbc_source(_req(db=str(tmp_path / "x.db"), table="a; DROP"), store)
    with pytest.raises(SourceError, match="cannot open"):
        jdbc_source(_req(db=str(tmp_path / "missing.db"), table="t"), store)
    with pytest.raises(SourceError, match="unsupported"):
        jdbc_source(_req(url="postgres://h/d", table="t"), store)

    path = str(tmp_path / "empty.db")
    _mkdb(path, [])
    with pytest.raises(SourceError, match="no rows"):
        jdbc_source(_req(db=path, table="clicks"), store)
    with pytest.raises(SourceError, match="query failed"):
        jdbc_source(_req(db=path, query="SELECT * FROM nope"), store)
    with pytest.raises(SourceError, match="no result set"):
        jdbc_source(_req(db=path, query="-- nothing"), store)

    # a read-only open must not create the file
    assert not (tmp_path / "missing.db").exists()


def test_missing_item_column(tmp_path):
    path = str(tmp_path / "noitem.db")
    conn = sqlite3.connect(path)
    conn.execute("CREATE TABLE t (site, user, timestamp)")
    conn.execute("INSERT INTO t VALUES ('s', 'u', 1)")
    conn.commit()
    conn.close()
    with pytest.raises(SourceError, match="'item' role"):
        jdbc_source(_req(db=path, table="t"), ResultStore())
