"""Multi-host seam: 2 real processes, one 8-device mesh, byte-exact parity.

The reference's multi-machine story is Spark RPC + Akka remoting; the
rebuild's is jax.distributed over DCN (SURVEY.md sec 2.2).  This test runs
it for real: two OS processes with 4 virtual CPU devices each rendezvous
through a coordination service on localhost, shard the sequence axis over
the joint mesh, and must both produce the oracle's exact pattern set.
"""

import os
import pathlib
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_mesh_parity():
    port = _free_port()
    worker = pathlib.Path(__file__).with_name("_multihost_worker.py")
    repo = str(pathlib.Path(__file__).resolve().parents[1])
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen([sys.executable, str(worker), str(port), str(i)],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any("Multiprocess computations aren't implemented on the CPU"
           in out for out in outs):
        # this jaxlib's CPU backend has no cross-process collective
        # support at all — the DCN wiring cannot be emulated here.  A
        # capability gap of the test substrate, not a regression: the
        # same code path is exercised on real pods (OPERATIONS.md
        # production re-verification checklist, multi-host row).
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} rc={p.returncode}\n{out}"
        assert "MULTIHOST_OK" in out and "parity=True" in out, out
        assert "pallas_parity=True" in out, out
        assert "cspade_parity=True" in out and "tsr_parity=True" in out, out
        assert "fused_parity=True" in out, out
        assert "stream_parity=True" in out, out
        # equivalence-class partitioned route across the real process
        # boundary (parallel/partition.py): each worker enumerates only
        # its own classes over its local inner row, one exchange per
        # round merges the byte-identical top-k
        assert "partition_parity=True" in out, out
