"""Warm-path subsystem: shape-key registry + AOT prewarm.

Two contracts (the ISSUE-1 tentpole):

1. **No registry drift** — after service-default mines (plain,
   constrained, TSR, and a streaming push), every runtime-recorded
   ``shape_key`` must be in the set ``utils/shapes.enumerate_shapes``
   pre-computed from the data geometry alone.  Enumeration and engine
   construction share the same geometry functions, so this test is the
   tripwire for anyone changing one side without the other.

2. **Prewarm completeness** — after ``service/prewarm.run`` over the
   declared envelope, the FIRST service-default mine and EVERY
   streaming push perform zero fresh XLA compiles (counted via the
   jax.monitoring backend-compile event), i.e. the 41.7 s cache-miss
   cold start and the config-5 mid-stream sweep stall are fully
   prepaid.  The driver runs ONCE per module (scope="module" fixture) —
   it is deliberately exhaustive, so re-running it per test would
   dominate the tier-1 wall.
"""

import json
import urllib.parse
import urllib.request

import pytest

from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import build_vertical
from spark_fsm_tpu.models.oracle import mine_cspade, mine_spade
from spark_fsm_tpu.utils import shapes
from spark_fsm_tpu.utils.canonical import patterns_text
from spark_fsm_tpu.utils.jitcache import compile_counts, enable_compile_counter

BATCH = 50  # streaming micro-batch size used throughout


def _db(seed=77, n=150):
    return synthetic_db(seed=seed, n_sequences=n, n_items=11,
                        mean_itemsets=3.0)


def test_key_formats_are_the_engine_spellings():
    # the key_* helpers ARE the engine spellings (one definition);
    # a format change here must be deliberate — tests and committed
    # artifacts (BENCH_SCALE shape_keys) parse these prefixes
    assert shapes.key_classic(128, 1, 530, 16, 64) == \
        "classic:s128w1r530nb16c64"
    assert shapes.key_queue(128, 1, 128, 512, 8192) == \
        "queue:s128w1ni128nb512r8192"
    assert shapes.key_cspade(128, 1, 12, 64, 32, 256, 2, 5, 8) == \
        "cspade:s128w1i12p64nb32c256g2x5d8"
    assert shapes.key_cspade(128, 1, 12, 64, 32, 256, None, None, 16) == \
        "cspade:s128w1i12p64nb32c256gnxnd16"
    assert shapes.key_sweep(128, 1, 256, 128) == "sweep:s128w1r256i128"
    assert shapes.key_tsr_eval(128, 1, 4, 256) == "tsr-eval:s128w1km4c256"
    assert shapes.key_tsr_part(2, 128, 1) == "tsr-part:p2s128w1"
    assert shapes.key_spam(128, 1, 530, 16, 64) == \
        "spam:s128w1r530nb16i64"
    # the hybrid key keeps the "spam:" prefix (same engine, same wave
    # program family) and appends ONLY the dense-pad axis
    assert shapes.key_spam_hybrid(128, 1, 530, 16, 64, 64) == \
        "spam:s128w1r530nb16i64d64"
    assert shapes.key_spam_pair(128, 1, 256) == "spam-pair:s128w1c256"
    # the prediction-serving scoring geometry (ops/rule_trie.py)
    assert shapes.key_predict(1024, 16, 8, 8) == "predict:f1024d16w8m8"


def test_enumeration_covers_runtime_keys_no_drift():
    """Drift test: plain + constrained + TSR mines and a streaming push
    record only keys the enumerator predicted from (sequences, items,
    words) — no mining involved in the prediction."""
    from spark_fsm_tpu.models.spade_constrained import mine_cspade_tpu
    from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu
    from spark_fsm_tpu.models.tsr import mine_tsr_tpu
    from spark_fsm_tpu.streaming.incremental import IncrementalWindowMiner

    db = _db()
    minsup = 6
    vdb = build_vertical(db, min_item_support=minsup)  # host-only: the
    # frequent projection width/word count come from a cheap data pass
    spec = shapes.WorkloadSpec(
        n_sequences=len(db), n_items=vdb.n_items, n_words=vdb.n_words,
        constraints=((2, 5),), tsr=True,
        stream_batch_sequences=BATCH,
        # the stream push below runs at a tiny minsup over a small
        # window, so its frequent width is the batch's full alphabet
        stream_items=build_vertical(db[:BATCH],
                                    min_item_support=1).n_items)
    enumerated = set(shapes.enumerate_shapes(spec))

    shapes.reset_recorded()
    mine_spade_tpu(db, minsup)
    mine_cspade_tpu(db, minsup, maxgap=2, maxwindow=5)
    mine_tsr_tpu(db, 8, 0.5, max_side=2)
    miner = IncrementalWindowMiner(0.1, max_batches=3)
    miner.push(db[:BATCH])
    miner.push(db[BATCH:2 * BATCH])
    assert miner.stats.get("shape_key", "").startswith("sweep:")

    missing = shapes.drift(enumerated)
    assert not missing, (
        f"runtime shape keys missing from the enumeration: {missing}\n"
        f"enumerated: {sorted(enumerated)}")


@pytest.fixture(scope="module")
def warmed():
    """ONE prewarm run over a combined batch + constrained + streaming
    envelope; the zero-compile tests below all assert against it."""
    from spark_fsm_tpu.service import prewarm

    assert enable_compile_counter(), \
        "jax.monitoring backend-compile event unavailable on this jax"
    db = _db(seed=78)
    minsup = 6
    vdb = build_vertical(db, min_item_support=minsup)
    spec = shapes.WorkloadSpec(
        n_sequences=len(db), n_items=vdb.n_items, n_words=vdb.n_words,
        constraints=((2, 5),), max_tokens=len(vdb.tok_item),
        stream_batch_sequences=BATCH,
        stream_items=build_vertical(db[:BATCH],
                                    min_item_support=1).n_items)
    report = prewarm.run(spec)
    assert not [r for r in report["keys"] if r.get("error")], report
    assert {r["kind"] for r in report["keys"]} >= {"classic", "queue",
                                                   "cspade", "sweep"}
    return db, minsup, report


def test_prewarm_then_first_mine_compiles_nothing(warmed):
    """The headline acceptance: after prewarm over the declared
    envelope, the first service-default mine (plain AND constrained)
    performs ZERO fresh XLA compiles — the whole cold-start bill was
    paid by the driver."""
    from spark_fsm_tpu.service.devcache import (
        CSpadeEngineCache, SpadeEngineCache)

    db, minsup, _ = warmed
    # fresh caches: the first mine must be a cache MISS (full build)
    # yet compile nothing — everything it runs was prewarmed
    c0 = compile_counts()
    s = {}
    got = SpadeEngineCache().mine(db, minsup, stats_out=s)
    c1 = compile_counts()
    assert s["store_cache_hit"] is False
    assert patterns_text(got) == patterns_text(mine_spade(db, minsup))
    assert c1["count"] - c0["count"] == 0, \
        f"first plain mine compiled {c1['count'] - c0['count']} programs"

    s2 = {}
    got2 = CSpadeEngineCache().mine(db, minsup, maxgap=2, maxwindow=5,
                                    stats_out=s2)
    c2 = compile_counts()
    assert patterns_text(got2) == patterns_text(
        mine_cspade(db, minsup, maxgap=2, maxwindow=5))
    assert c2["count"] - c1["count"] == 0, \
        f"first cSPADE mine compiled {c2['count'] - c1['count']} programs"


def test_prewarm_covers_streaming_pushes(warmed):
    """Config-5 stall contract at test scale: after prewarm with the
    streaming envelope, NO push (including the second-shape push 2, the
    12.85 s offender at full scale) compiles anything fresh."""
    from spark_fsm_tpu.streaming.incremental import IncrementalWindowMiner

    db, _, _ = warmed
    c0 = compile_counts()
    miner = IncrementalWindowMiner(0.1, max_batches=3, seq_floor=BATCH)
    for i in range(3):
        miner.push(db[i * BATCH:(i + 1) * BATCH])
    c1 = compile_counts()
    assert c1["count"] - c0["count"] == 0, \
        f"pushes compiled {c1['count'] - c0['count']} fresh programs"


def test_tsr_superbatch_keys_through_prewarm():
    """Super-batch geometry coverage (the ragged-batch ladder): the
    enumerator lists one ``tsr-eval`` key per (km, pow2 width), the
    prewarm driver compiles and RECORDS each one, and a post-prewarm
    engine dispatch at the declared geometry performs zero fresh
    compiles — the PR-1 guarantee extended to the new launch ladder.
    A pinned tsr_chunk throttles the ladder so this stays seconds-scale.
    """
    from spark_fsm_tpu.models.tsr import TsrTPU
    from spark_fsm_tpu.ops import ragged_batch as RB
    from spark_fsm_tpu.service import prewarm

    assert enable_compile_counter()
    db = _db(seed=81, n=90)
    vdb = build_vertical(db, min_item_support=1)
    spec = shapes.WorkloadSpec(n_sequences=len(db), n_items=vdb.n_items,
                               n_words=vdb.n_words, tsr=True)
    ekw = {"tsr_chunk": 256}
    targets = shapes.enumerate_shapes(spec, engine_kwargs=ekw)
    eval_keys = {k for k, t in targets.items() if t["kind"] == "tsr_eval"}
    ladder = RB.superbatch_geometries(32, 256)
    assert eval_keys == {shapes.key_tsr_eval(len(db), vdb.n_words, km, w)
                        for km, w in ladder}
    (tsr_t,) = [t for t in targets.values() if t["kind"] == "tsr"]
    assert tsr_t["superbatch"] == ladder

    shapes.reset_recorded()
    report = prewarm.run(spec, engine_kwargs=ekw)
    bad = [r for r in report["keys"] if r.get("error")]
    assert not bad, bad
    recorded = shapes.recorded()
    for key in eval_keys:
        assert key in recorded, (key, sorted(recorded))

    # zero-fresh-compile through a live dispatch at the warmed geometry:
    # prep compiles per token count (excluded by snapshotting after it),
    # but every eval-launch program must already be warm
    eng = TsrTPU(vdb, 8, 0.5, max_side=None, chunk=256)
    m = min(eng.item_cap, vdb.n_items)
    eng.chunk = eng._round_chunk(m)
    eng._round_m = m
    p1, s1 = eng._prep(m)
    c0 = compile_counts()
    cands = ([((0,), (j,)) for j in range(1, 9)]
             + [((0, 1), (2, 3)), ((0,), (1, 2, 3))])
    handle = eng._dispatch_eval(p1, s1, cands)
    sups, supxs = eng._resolve_eval(handle, len(cands))
    assert len(sups) == len(cands)
    c1 = compile_counts()
    assert c1["count"] - c0["count"] == 0, \
        f"eval dispatch compiled {c1['count'] - c0['count']} fresh programs"


@pytest.fixture()
def server():
    from spark_fsm_tpu.service.app import serve_background

    srv = serve_background()
    yield srv
    srv.master.shutdown()
    srv.shutdown()


def _post(server, endpoint, **params):
    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{server.server_port}{endpoint}"
    with urllib.request.urlopen(url, data=data, timeout=120) as resp:
        return json.loads(resp.read().decode())


def test_admin_prewarm_and_shapes_endpoints(server):
    """POST /admin/prewarm compiles the declared envelope and reports
    per-key walls; /admin/shapes diffs enumerated vs recorded keys; the
    per-key walls also surface in /admin/stats.  Tiny envelope — the
    exhaustive driver run is covered by the ``warmed`` fixture tests;
    this checks the HTTP surface."""
    db = _db(seed=80, n=60)
    vdb = build_vertical(db, min_item_support=6)
    report = _post(server, "/admin/prewarm",
                   sequences=str(len(db)), items=str(vdb.n_items),
                   words=str(vdb.n_words), max_tokens="64")
    assert report["keys"], report
    assert not [r for r in report["keys"] if r.get("error")], report
    for row in report["keys"]:
        assert set(row) >= {"shape_key", "kind", "wall_s",
                            "fresh_compiles"}

    listing = _post(server, "/admin/shapes")
    assert set(listing["enumerated"]) == {r["shape_key"]
                                          for r in report["keys"]}
    # every enumerated key was CONSTRUCTED during the prewarm itself,
    # so recorded covers the batch-engine keys (sweep keys come from
    # stream pushes)
    for key in listing["enumerated"]:
        assert key in listing["recorded"], (key, listing)

    stats = _post(server, "/admin/stats")
    assert stats["prewarm"] is not None
    assert stats["prewarm"]["keys"], stats
    assert stats["shape_keys_recorded"] >= len(listing["enumerated"])


def test_tsr_resident_keys_through_prewarm():
    """Resident-frontier ladder coverage (ISSUE 7): the enumerator
    lists one ``tsr-resident`` key per wave width (wide + late-wave
    narrow) with caps derived from the SAME budget model the engine's
    eligibility check uses, the prewarm driver compiles and records
    each one, and a post-prewarm resident round performs ZERO fresh
    compiles — the PR-1 guarantee extended to the whole-ladder
    while_loop programs."""
    from spark_fsm_tpu.models.tsr import TsrTPU
    from spark_fsm_tpu.ops import resident_frontier as RF
    from spark_fsm_tpu.service import prewarm

    assert enable_compile_counter()
    db = _db(seed=83, n=90)
    vdb = build_vertical(db, min_item_support=1)
    spec = shapes.WorkloadSpec(n_sequences=len(db), n_items=vdb.n_items,
                               n_words=vdb.n_words, tsr=True)
    ekw = {"tsr_chunk": 256}
    targets = shapes.enumerate_shapes(spec, engine_kwargs=ekw)
    res = {k: t for k, t in targets.items() if t["kind"] == "tsr_resident"}
    assert res, "no tsr-resident keys enumerated"
    # enumeration derives the caps the engine will construct
    import jax

    from spark_fsm_tpu.models._common import device_hbm_budget

    caps = RF.caps_for(len(db), vdb.n_words, vdb.n_items,
                       device_hbm_budget(jax.devices()[0]))
    want_keys = set(RF.resident_keys(len(db), vdb.n_words, vdb.n_items,
                                     caps))
    assert set(res) == want_keys, (sorted(res), sorted(want_keys))

    shapes.reset_recorded()
    report = prewarm.run(spec, engine_kwargs=ekw)
    bad = [r for r in report["keys"] if r.get("error")]
    assert not bad, bad
    recorded = shapes.recorded()
    for key in want_keys:
        assert key in recorded, (key, sorted(recorded))

    # zero-fresh-compile through a live resident round at the warmed
    # geometry (prep compiles per token count — excluded by
    # snapshotting after it, same as the superbatch test above)
    eng = TsrTPU(vdb, 8, 0.5, max_side=None, chunk=256,
                 resident="always")
    m = min(eng.item_cap, vdb.n_items)
    eng.chunk = eng._round_chunk(m)
    eng._round_m = m
    assert eng._resident_route(m)
    eng._prep_engine(m)
    c0 = compile_counts()
    res_rules, _s_k = eng._mine_resident(m, resume=None,
                                         checkpoint_cb=None, every_s=30.0)
    c1 = compile_counts()
    assert res_rules
    assert eng.stats.get("resident_segments", 0) >= 1
    assert c1["count"] - c0["count"] == 0, \
        f"resident round compiled {c1['count'] - c0['count']} fresh programs"


def test_tsr_partition_keys_through_prewarm():
    """Partitioned-ladder coverage (the ISSUE-10 tentpole's warm-path
    contract): the enumerator lists the ``tsr-part`` umbrella key plus
    the per-part INNER ``tsr``/``tsr-eval`` ladder at the submesh-row
    geometry, the prewarm driver walks EVERY row (compiled executables
    bind device assignments), and a post-prewarm partitioned dispatch
    at the warmed geometry performs zero fresh compiles."""
    from spark_fsm_tpu.models import tsr as tsr_mod
    from spark_fsm_tpu.models.tsr import TsrPartitioned
    from spark_fsm_tpu.parallel import partition as PN
    from spark_fsm_tpu.parallel.mesh import make_mesh
    from spark_fsm_tpu.service import prewarm

    assert enable_compile_counter()
    db = _db(seed=82, n=96)
    vdb = build_vertical(db, min_item_support=1)
    mesh = make_mesh(8)
    spec = shapes.WorkloadSpec(n_sequences=len(db), n_items=vdb.n_items,
                               n_words=vdb.n_words, tsr=True,
                               partition_parts=2)
    ekw = {"tsr_chunk": 256}
    targets = shapes.enumerate_shapes(spec, mesh=mesh, engine_kwargs=ekw)
    part_t = {k: t for k, t in targets.items() if t["kind"] == "tsr_part"}
    assert part_t, "no tsr-part key enumerated"
    inner = PN.submeshes(mesh, 2)[0]
    tgp = tsr_mod.tsr_geometry(len(db), vdb.n_words, mesh=inner)
    assert shapes.key_tsr_part(2, tgp["n_seq"], vdb.n_words) in part_t
    # the inner eval ladder is enumerated at the INNER padded seq axis
    assert shapes.key_tsr_eval(tgp["n_seq"], vdb.n_words, 1, 32) in targets

    shapes.reset_recorded()
    mines_before = PN._MINES.total()
    plans_before = PN._PLANS.total()
    report = prewarm.run(spec, mesh=mesh, engine_kwargs=ekw)
    bad = [r for r in report["keys"] if r.get("error")]
    assert not bad, bad
    recorded = shapes.recorded()
    assert shapes.key_tsr_part(2, tgp["n_seq"], vdb.n_words) in recorded
    # the warm mine must not masquerade as traffic: fsm_partition_*
    # business families stay untouched by prewarm (record_metrics=False)
    assert PN._MINES.total() == mines_before
    assert PN._PLANS.total() == plans_before

    # zero-fresh-compile through a live partitioned dispatch on BOTH
    # rows at the warmed geometry (prep snapshotted first, like the
    # superbatch pin — its scatter build keys on token counts)
    orch = TsrPartitioned(vdb, 8, 0.5, mesh=mesh, parts=2,
                          max_side=None, chunk=256)
    assert orch.stats["shape_key"] in shapes.recorded()
    for eng in orch.engines.values():
        m = min(eng.item_cap, vdb.n_items)
        eng.chunk = eng._round_chunk(m)
        eng._round_m = m
        eng._jnp_prep = None
        p1, s1 = eng._prep(m)
        c0 = compile_counts()
        cands = ([((0,), (j,)) for j in range(1, 9)]
                 + [((0, 1), (2, 3)), ((0,), (1, 2, 3))])
        handle = eng._dispatch_eval(p1, s1, cands)
        sups, _supxs = eng._resolve_eval(handle, len(cands))
        assert len(sups) == len(cands)
        c1 = compile_counts()
        assert c1["count"] - c0["count"] == 0, (
            f"partitioned eval dispatch compiled "
            f"{c1['count'] - c0['count']} fresh programs")


def test_predict_keys_through_prewarm():
    """Read-plane warm-path contract (the ISSUE-17 acceptance pin): the
    enumerator lists one ``predict`` key per pow2 wave bucket at the
    declared floor geometry, the prewarm driver compiles and records
    each rung, and a post-prewarm scoring wave at the warmed geometry —
    a DIFFERENT artifact, same shapes — performs zero fresh compiles."""
    from spark_fsm_tpu.ops import rule_trie
    from spark_fsm_tpu.service import prewarm

    assert enable_compile_counter()
    spec = shapes.WorkloadSpec(n_sequences=0, n_items=0,
                               predict_lanes=64, predict_depth=8,
                               predict_wave=4, predict_topm=4)
    enumerated = sorted(shapes.enumerate_shapes(spec))
    assert enumerated == [shapes.key_predict(64, 8, w, 4)
                          for w in (1, 2, 4)]

    shapes.reset_recorded()
    report = prewarm.run(spec)
    bad = [r for r in report["keys"] if r.get("error")]
    assert not bad, bad
    recorded = shapes.recorded()
    assert set(enumerated) <= set(recorded), (enumerated, recorded)

    # a live artifact padded to the same floors lands on the warmed
    # keys: every wave width in the ladder scores with ZERO fresh
    # compiles (the artifact's planes are data, not shape)
    rules = [((1,), (2,), 3, 4), ((2, 3), (5,), 2, 6),
             ((1, 2), (7,), 1, 3)]
    trie = rule_trie.build_trie(rules, lanes_floor=64, depth_floor=8)
    for prefixes in ([[1]], [[1], [2, 3]], [[1], [2, 3], [], [1, 2]]):
        c0 = compile_counts()
        out = rule_trie.score_wave(trie, prefixes, 4)
        c1 = compile_counts()
        assert len(out) == len(prefixes)
        assert c1["count"] - c0["count"] == 0, (
            f"post-prewarm predict wave (n={len(prefixes)}) compiled "
            f"{c1['count'] - c0['count']} fresh programs")
    assert not shapes.drift(enumerated)
