"""Actor-protocol TCP entry (service/remote.py): the reference's second,
Akka-remote-style API surface driven over a real socket — full train ->
status -> get lifecycle, registrar + tracker tasks, and framing robustness
(malformed requests must not kill the connection)."""

import json
import socket
import time

import pytest

from spark_fsm_tpu.service.actors import Master
from spark_fsm_tpu.service.remote import (
    RemoteClient, serve_remote_background)
from spark_fsm_tpu.service.store import ResultStore


@pytest.fixture()
def remote():
    master = Master(store=ResultStore())
    server = serve_remote_background(master)
    yield server
    server.shutdown()
    server.server_close()
    master.shutdown()


def _wait_finished(client, uid, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        resp = client.request("status", {"uid": uid})
        if resp["status"] in ("finished", "failure"):
            return resp
        time.sleep(0.02)
    raise TimeoutError("job did not finish")


def test_train_status_get_over_socket(remote):
    client = RemoteClient(port=remote.port)
    resp = client.request("train", {
        "algorithm": "SPADE", "source": "INLINE",
        "sequences": "1 -1 2 -2\n1 -1 2 -2\n2 -1 1 -2\n",
        "support": "0.5"})
    assert resp["status"] == "started", resp
    uid = resp["data"]["uid"]
    final = _wait_finished(client, uid)
    assert final["status"] == "finished", final
    got = client.request("get:patterns", {"uid": uid})
    patterns = json.loads(got["data"]["patterns"])
    assert {"support": 3, "itemsets": [[1]]} in patterns
    assert {"support": 2, "itemsets": [[1], [2]]} in patterns
    client.close()


def test_register_track_mine_over_socket(remote):
    client = RemoteClient(port=remote.port)
    # register a NON-default field mapping, then track events using it
    assert client.request("register:clicks", {
        "site": "shop", "user": "visitor", "timestamp": "ts",
        "group": "session", "item": "sku"})["status"] == "finished"
    rows = [
        ("u1", 1, 1, 7), ("u1", 2, 2, 8),
        ("u2", 1, 3, 7), ("u2", 2, 4, 8),
    ]
    for visitor, ts, session, sku in rows:
        assert client.request("track:clicks", {
            "shop": "main", "visitor": visitor, "ts": ts,
            "session": session, "sku": sku})["status"] == "finished"
    resp = client.request("train", {
        "algorithm": "SPADE", "source": "TRACKED", "topic": "clicks",
        "support": "0.9"})
    uid = resp["data"]["uid"]
    assert _wait_finished(client, uid)["status"] == "finished"
    got = client.request("get:patterns", {"uid": uid})
    patterns = json.loads(got["data"]["patterns"])
    assert {"support": 2, "itemsets": [[7], [8]]} in patterns
    client.close()


def test_malformed_requests_keep_connection(remote):
    raw = socket.create_connection(("127.0.0.1", remote.port), timeout=10)
    f = raw.makefile("rwb")
    # not JSON at all
    f.write(b"this is not json\n")
    f.flush()
    resp = json.loads(f.readline())
    assert resp["status"] == "failure" and "malformed" in resp["data"]["error"]
    # valid JSON, wrong shape (array / null data) must not kill the socket
    f.write(b"[1, 2, 3]\n")
    f.flush()
    assert json.loads(f.readline())["status"] == "failure"
    f.write(b'{"service": "fsm", "task": "status", "data": null}\n')
    f.flush()
    assert json.loads(f.readline())["status"] == "failure"
    # JSON but an unknown task -> failure envelope from the Master
    f.write(b'{"service": "fsm", "task": "frobnicate", "data": {}}\n')
    f.flush()
    assert json.loads(f.readline())["status"] == "failure"
    # connection still usable for a real request afterwards
    f.write(b'{"service": "fsm", "task": "status", "data": {"uid": "x"}}\n')
    f.flush()
    resp = json.loads(f.readline())
    assert resp["task"] == "status"
    raw.close()


def test_blank_lines_skipped_and_concurrent_clients(remote):
    c1 = RemoteClient(port=remote.port)
    c2 = RemoteClient(port=remote.port)
    # blank lines are keepalive no-ops
    c1._file.write(b"\n\n")
    c1._file.flush()
    assert c1.request("status", {"uid": "nope"})["task"] == "status"
    assert c2.request("status", {"uid": "nope"})["task"] == "status"
    c1.close()
    c2.close()


def test_oversized_line_drained_and_framing_kept(remote, monkeypatch):
    """A request line over MAX_LINE gets one failure envelope and the
    remainder of the line is drained — framing stays one-reply-per-line."""
    from spark_fsm_tpu.service import remote as remote_mod

    monkeypatch.setattr(remote_mod, "MAX_LINE", 1024)
    raw = socket.create_connection(("127.0.0.1", remote.port), timeout=10)
    f = raw.makefile("rwb")
    f.write(b'{"service": "fsm", "task": "status", "data": {"x": "'
            + b"A" * 5000 + b'"}}\n')
    f.flush()
    resp = json.loads(f.readline())
    assert resp["status"] == "failure" and "exceeds" in resp["data"]["error"]
    # exactly ONE reply for the oversized line; the next request pairs
    # with the next reply
    f.write(b'{"service": "fsm", "task": "status", "data": {"uid": "x"}}\n')
    f.flush()
    resp = json.loads(f.readline())
    assert resp["task"] == "status"
    raw.close()


def test_prediction_over_socket(remote):
    # the prediction subject rides the same task vocabulary over TCP
    client = RemoteClient(port=remote.port)
    resp = client.request("train", {
        "algorithm": "TSR", "source": "INLINE",
        "sequences": "1 -1 2 -2\n1 -1 2 -2\n1 -1 3 -2\n2 -1 3 -2\n",
        "k": "5", "minconf": "0.3", "max_side": "1"})
    uid = resp["data"]["uid"]
    assert _wait_finished(client, uid)["status"] == "finished"
    got = client.request("get:prediction", {"uid": uid, "items": "1"})
    assert got["status"] == "finished", got
    preds = json.loads(got["data"]["predictions"])
    assert preds and all(p["item"] != 1 and p["antecedent"] == [1]
                         for p in preds)
    # 1 -> 2 holds in 2 of 3 sequences containing 1
    top = {p["item"]: p for p in preds}
    assert top[2]["support"] == 2 and top[2]["antecedent_support"] == 3
