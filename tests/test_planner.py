"""Engine planner drills (ISSUE 15, service/planner.py).

Pins: the calibrated density-crossover table, AUTO routing on real
dataset shapes vs explicit overrides, the structured 400 for unknown
engines, the planner decision on the trace spine, result-cache hits
across engine routes, and pinned mode."""

import json
import time

import pytest

from spark_fsm_tpu import config as cfgmod
from spark_fsm_tpu.data.spmf import format_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import (DatasetStats, abs_minsup,
                                         dataset_stats)
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.service import planner, plugins
from spark_fsm_tpu.service.actors import Master
from spark_fsm_tpu.service.model import ServiceRequest, \
    deserialize_patterns
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.utils import obs
from spark_fsm_tpu.utils.canonical import patterns_text


def _dense_db():
    # alphabet 10, density ~0.3: well above the 0.02 crossover
    return synthetic_db(seed=7, n_sequences=60, n_items=10,
                        mean_itemsets=3.0, mean_itemset_size=1.3)


def _sparse_db():
    # the ONE sub-crossover shape (data/synth.sub_crossover_db): 400
    # items at support 2 over 200 sequences — density 0.01 < 0.02
    from spark_fsm_tpu.data.synth import sub_crossover_db

    return sub_crossover_db()


def _stats(density, alphabet=32):
    return DatasetStats(n_sequences=1000, n_itemsets=4000, n_tokens=5000,
                        alphabet=alphabet, max_len=8, avg_len=4.0,
                        n_words=1, density=density)


def _wait(store, uid, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = store.status(uid)
        if st in ("finished", "failure"):
            return st
        time.sleep(0.01)
    raise TimeoutError(uid)


# -------------------------------------------------------- crossover table


def test_density_crossover_table_pinned():
    """The calibrated routing table (docs/DESIGN.md "Engine planner"):
    density/alphabet/constraints -> engine, at the committed default
    crossover (0.02) and alphabet ceiling (512)."""
    pcfg = cfgmod.PlannerConfig()
    assert pcfg.density_crossover == 0.02
    assert pcfg.max_alphabet == 512
    table = [
        # (density, alphabet, constrained) -> engine
        ((0.30, 12, False), "SPAM_TPU"),
        ((0.076, 62, False), "SPAM_TPU"),   # measured kosarak@0.01 row
        ((0.023, 230, False), "SPAM_TPU"),  # measured: 1.6x over SPADE
        ((0.02, 512, False), "SPAM_TPU"),   # boundary: >= is SPAM
        ((0.019, 64, False), "SPADE_TPU"),  # below crossover: never SPAM
        ((0.0001, 8, False), "SPADE_TPU"),
        ((0.30, 513, False), "SPADE_TPU"),  # alphabet ceiling
        ((0.30, 12, True), "SPADE_TPU"),    # constraints exclude SPAM
    ]
    for (density, alphabet, constrained), want in table:
        d = planner.choose_patterns_engine(
            _stats(density, alphabet), pcfg, constrained=constrained)
        assert d.engine == want, (density, alphabet, constrained, d)
        assert d.kind == "patterns"
        assert d.reason


def test_dataset_stats_projection_density():
    db = _sparse_db()
    st = dataset_stats(db, min_item_support=2)
    assert st.alphabet == 402
    assert st.density < 0.02
    dense = dataset_stats(_dense_db(), min_item_support=1)
    assert dense.density > 0.05


# ------------------------------------------------------------ AUTO routing


def test_auto_routes_dense_to_spam_with_parity_and_stats():
    db = _dense_db()
    req = ServiceRequest("fsm", "train", {
        "algorithm": "AUTO", "support": "0.1"})
    plugin = plugins.get_plugin(req)
    assert plugin.name == "AUTO" and plugin.kind == "patterns"
    stats = {}
    got = plugin.extract(req, db, stats)
    assert stats["planner_engine"] == "SPAM_TPU"
    assert stats["planner_mode"] == "auto"
    assert "density" in stats["planner_reason"]
    assert stats["engine"] == "spam"  # the routed engine actually ran
    assert patterns_text(got) == patterns_text(
        mine_spade(db, abs_minsup(0.1, len(db))))


def test_auto_routes_sparse_to_spade_never_spam_below_crossover():
    db = _sparse_db()
    req = ServiceRequest("fsm", "train", {
        "algorithm": "AUTO", "support": "2"})
    stats = {}
    got = plugins.get_plugin(req).extract(req, db, stats)
    assert stats["planner_engine"] == "SPADE_TPU"
    assert stats["planner_density"] < 0.02
    assert patterns_text(got) == patterns_text(mine_spade(db, 2))


def test_auto_routes_constrained_to_spade():
    db = _dense_db()
    req = ServiceRequest("fsm", "train", {
        "algorithm": "AUTO", "support": "0.1", "maxgap": "2"})
    stats = {}
    plugins.get_plugin(req).extract(req, db, stats)
    assert stats["planner_engine"] == "SPADE_TPU"
    assert "maxgap" in stats["planner_reason"]


def test_auto_infers_rules_kind_and_routes_tsr():
    db = _dense_db()
    req = ServiceRequest("fsm", "train", {
        "algorithm": "AUTO", "support": "0.1", "k": "5",
        "minconf": "0.4"})
    plugin = plugins.get_plugin(req)
    assert plugin.kind == "rules"
    stats = {}
    rules = plugin.extract(req, db, stats)
    assert stats["planner_engine"] == "TSR_TPU"
    assert all(len(r) == 4 for r in rules)


def test_explicit_spam_honored_below_crossover():
    """Explicit algorithm= always wins: SPAM on a sub-crossover dataset
    runs SPAM (the planner only owns AUTO)."""
    db = _sparse_db()
    req = ServiceRequest("fsm", "train", {
        "algorithm": "SPAM_TPU", "support": "2"})
    stats = {}
    got = plugins.get_plugin(req).extract(req, db, stats)
    assert stats["engine"] == "spam"
    assert "planner_engine" not in stats
    assert patterns_text(got) == patterns_text(mine_spade(db, 2))


def test_pinned_mode_routes_auto_unconditionally():
    old = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config(
        {"planner": {"mode": "pinned", "pinned": "SPADE_TPU"}}))
    try:
        db = _dense_db()  # dense — auto mode would pick SPAM
        req = ServiceRequest("fsm", "train", {
            "algorithm": "AUTO", "support": "0.1"})
        stats = {}
        plugins.get_plugin(req).extract(req, db, stats)
        assert stats["planner_engine"] == "SPADE_TPU"
        assert stats["planner_mode"] == "pinned"
        # a rules request cannot be served by a patterns pin: the
        # kind-default fallback keeps the result kind intact
        req2 = ServiceRequest("fsm", "train", {
            "algorithm": "AUTO", "support": "0.1", "k": "3",
            "minconf": "0.4"})
        stats2 = {}
        plugins.get_plugin(req2).extract(req2, db, stats2)
        assert stats2["planner_engine"] == "TSR_TPU"
    finally:
        cfgmod.set_config(old)


def test_pinned_spam_constrained_falls_back_to_spade():
    """A SPAM soak (mode=pinned, pinned=SPAM_TPU) must not fail every
    constrained AUTO request: constraints fall back to SPADE_TPU, with
    the reason naming why."""
    old = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config(
        {"planner": {"mode": "pinned", "pinned": "SPAM_TPU"}}))
    try:
        db = _dense_db()
        req = ServiceRequest("fsm", "train", {
            "algorithm": "AUTO", "support": "0.1", "maxgap": "2"})
        stats = {}
        got = plugins.get_plugin(req).extract(req, db, stats)
        assert stats["planner_engine"] == "SPADE_TPU"
        assert "maxgap" in stats["planner_reason"]
        assert got  # the constrained mine actually ran
        # unconstrained AUTO under the same pin still soaks SPAM
        req2 = ServiceRequest("fsm", "train", {
            "algorithm": "AUTO", "support": "0.1"})
        stats2 = {}
        plugins.get_plugin(req2).extract(req2, db, stats2)
        assert stats2["planner_engine"] == "SPAM_TPU"
    finally:
        cfgmod.set_config(old)


def test_planner_config_validation():
    with pytest.raises(cfgmod.ConfigError, match="planner.mode"):
        cfgmod.parse_config({"planner": {"mode": "sometimes"}})
    with pytest.raises(cfgmod.ConfigError, match="planner.pinned"):
        cfgmod.parse_config({"planner": {"pinned": "AUTO"}})
    with pytest.raises(cfgmod.ConfigError, match="density_crossover"):
        cfgmod.parse_config({"planner": {"density_crossover": 1.5}})
    with pytest.raises(cfgmod.ConfigError, match="max_alphabet"):
        cfgmod.parse_config({"planner": {"max_alphabet": 0}})


# ------------------------------------------------- unknown algorithm -> 400


def test_unknown_algorithm_sheds_structured_400():
    store = ResultStore()
    master = Master(store=store, miner_workers=1)
    try:
        resp = master.handle(ServiceRequest("fsm", "train", {
            "algorithm": "SPQR", "source": "INLINE",
            "sequences": format_spmf(_dense_db()), "support": "0.1"}))
        assert resp.status == "failure"
        assert resp.data.get("http_status") == "400"
        supported = json.loads(resp.data["supported"])
        # derived from the live registry, not a docstring
        assert supported == sorted(plugins.ALGORITHMS)
        assert "SPAM_TPU" in supported and "AUTO" in supported
        assert "SPQR" in resp.data["error"]
        # zero store trace of the uid — the shed happened before
        # anything went async
        assert store.status(resp.data["uid"]) is None
    finally:
        master.shutdown()


def test_unknown_algorithm_maps_to_http_400():
    """Over the real HTTP surface: a bad engine name is a 400 with the
    structured body, not a 200 failure envelope."""
    import urllib.error
    import urllib.parse
    import urllib.request

    from spark_fsm_tpu.service.app import serve_background

    srv = serve_background()
    try:
        data = urllib.parse.urlencode({
            "algorithm": "NOPE", "source": "INLINE",
            "sequences": format_spmf(_dense_db()),
            "support": "0.1"}).encode()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.server_port}/train", data=data,
                timeout=30)
        assert ei.value.code == 400
        body = json.loads(ei.value.read().decode())
        assert json.loads(body["data"]["supported"]) == \
            sorted(plugins.ALGORITHMS)
    finally:
        srv.master.shutdown()
        srv.shutdown()


# --------------------------------------------------- trace spine + metrics


def test_planner_decision_lands_in_trace():
    old = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config(
        {"observability": {"trace": True}}))
    store = ResultStore()
    master = Master(store=store, miner_workers=1)
    try:
        resp = master.handle(ServiceRequest("fsm", "train", {
            "algorithm": "AUTO", "source": "INLINE",
            "sequences": format_spmf(_dense_db()), "support": "0.1",
            "uid": "planner-trace"}))
        assert resp.status == "started"
        assert _wait(store, "planner-trace") == "finished"
        dump = obs.trace_dump("planner-trace")
        assert dump is not None
        routes = [s for s in dump["spans"] if s["site"] == "planner.route"]
        assert len(routes) == 1
        attrs = routes[0]["attrs"]
        assert attrs["engine"] == "SPAM_TPU"
        assert attrs["mode"] == "auto"
        assert "reason" in attrs and "density" in attrs
    finally:
        master.shutdown()
        cfgmod.set_config(old)


def test_engine_selected_counter_seeded_and_counts():
    fam = obs.REGISTRY.snapshot().get("fsm_engine_selected_total", {})
    for eng in planner.CONCRETE_ENGINES:
        assert f"engine={eng}" in fam, eng
    store = ResultStore()
    master = Master(store=store, miner_workers=1)
    try:
        before = obs.REGISTRY.snapshot()["fsm_engine_selected_total"]
        master.handle(ServiceRequest("fsm", "train", {
            "algorithm": "AUTO", "source": "INLINE",
            "sequences": format_spmf(_dense_db()), "support": "0.1",
            "uid": "esel-auto"}))
        master.handle(ServiceRequest("fsm", "train", {
            "algorithm": "SPADE_TPU", "source": "INLINE",
            "sequences": format_spmf(_dense_db()), "support": "0.1",
            "uid": "esel-explicit"}))
        _wait(store, "esel-auto")
        _wait(store, "esel-explicit")
        after = obs.REGISTRY.snapshot()["fsm_engine_selected_total"]
        assert after["engine=SPAM_TPU"] == before["engine=SPAM_TPU"] + 1
        assert after["engine=SPADE_TPU"] == \
            before["engine=SPADE_TPU"] + 1
        assert "engine=AUTO" not in after  # AUTO counts as its target
    finally:
        master.shutdown()


# --------------------------------------- result-cache engine invariance


def test_effective_params_engine_invariant_families():
    base = {"support": "0.1"}
    keys = set()
    for algo in ("SPADE", "SPADE_TPU", "SPAM", "SPAM_TPU", "AUTO"):
        req = ServiceRequest("fsm", "train",
                             {"algorithm": algo, **base})
        p = plugins.effective_params(req, n_sequences=100)
        keys.add(json.dumps(p, sort_keys=True))
        assert p["algo"] == "SPADE_TPU"
    assert len(keys) == 1
    rules = {"k": "5", "minconf": "0.4"}
    for algo in ("TSR", "TSR_TPU", "AUTO"):
        req = ServiceRequest("fsm", "train",
                             {"algorithm": algo, **rules})
        assert plugins.effective_params(req)["algo"] == "TSR_TPU"


def test_rescache_hits_across_engine_routes():
    """ISSUE 15 composition invariant: an entry produced under one
    engine route serves the identical dataset+params under EVERY other
    route (exact hit), byte-identically."""
    old = cfgmod.get_config()
    cfgmod.set_config(cfgmod.parse_config({"rescache": {"enabled": True}}))
    store = ResultStore()
    master = Master(store=store, miner_workers=1)
    try:
        db = _dense_db()
        spmf = format_spmf(db)
        want = patterns_text(mine_spade(db, abs_minsup(0.1, len(db))))

        def run(uid, algo):
            resp = master.handle(ServiceRequest("fsm", "train", {
                "algorithm": algo, "source": "INLINE",
                "sequences": spmf, "support": "0.1", "uid": uid}))
            assert resp.status == "started", resp.data
            assert _wait(store, uid) == "finished"
            stats = json.loads(store.get(f"fsm:stats:{uid}") or "{}")
            pats = patterns_text(
                deserialize_patterns(store.patterns(uid)))
            assert pats == want, (uid, algo)
            return stats

        cold = run("rc-cold", "SPADE_TPU")
        assert not cold.get("served_from_cache")
        # different engine spelling, same dataset+params: exact hit
        hit_spam = run("rc-spam", "SPAM")
        assert hit_spam.get("served_from_cache") == "exact"
        hit_auto = run("rc-auto", "AUTO")
        assert hit_auto.get("served_from_cache") == "exact"
    finally:
        master.shutdown()
        cfgmod.set_config(old)
