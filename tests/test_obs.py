"""Observability layer: metrics registry + flight recorder (utils/obs).

Covers the tentpole contracts: histogram bucket-edge semantics,
ring-buffer eviction order, thread-safety under concurrent actor spans,
the disabled-cost pin (NO span allocation when tracing is off — the
same one-global-read posture as the fault registry), and the
acceptance-path trace: a TSR mine under an armed ``device.oom`` fault
dumps the launch span, its RESOURCE_EXHAUSTED event, the half-width
re-plan child spans, and predicted-vs-measured seconds per launch.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import build_vertical
from spark_fsm_tpu.models.tsr import TsrTPU
from spark_fsm_tpu.utils import faults, obs


@pytest.fixture(autouse=True)
def _tracing_reset():
    """Every test starts from tracing-off defaults and leaves no trace
    rings behind (the recorder is process-global)."""
    enabled0 = obs.tracing_enabled()
    yield
    obs.configure_tracing(enabled0, max_spans=512, max_jobs=16)
    obs.clear_traces()


# ------------------------------------------------------------ registry

def test_histogram_bucket_edges():
    """Edges are INCLUSIVE upper bounds (Prometheus le= semantics):
    a value exactly on an edge lands in that bucket, above the last
    edge lands only in +Inf, and bucket counts are cumulative."""
    h = obs.Histogram("fsm_test_edges_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.10001, 1.0, 10.0, 11.0):
        h.observe(v)
    by_le = {dict(key)["le"]: val
             for suffix, key, val in h.samples() if suffix == "_bucket"}
    assert by_le == {"0.1": 2,    # 0.05, 0.1 (edge inclusive)
                     "1": 4,      # + 0.10001, 1.0
                     "10": 5,     # + 10.0
                     "+Inf": 6}   # + 11.0
    counts = {s: v for s, key, v in h.samples() if s == "_count"}
    sums = {s: v for s, key, v in h.samples() if s == "_sum"}
    assert counts["_count"] == 6
    assert abs(sums["_sum"] - 22.25001) < 1e-9


def test_histogram_rejects_bad_edges():
    with pytest.raises(ValueError):
        obs.Histogram("fsm_test_bad_seconds", buckets=(1.0, 1.0))
    with pytest.raises(ValueError):
        obs.Histogram("fsm_test_bad2_seconds", buckets=())


def test_fresh_counter_emits_zero_sample():
    """A never-incremented counter must scrape as 0, not as a missing
    series — 'no data' and 'zero events' are different answers to an
    alert rule."""
    c = obs.REGISTRY.counter("fsm_test_untouched_total")
    assert ("", (), 0.0) in c.samples()
    assert "fsm_test_untouched_total 0" in obs.REGISTRY.render_prometheus()


def test_histogram_bucket_mismatch_raises():
    obs.REGISTRY.histogram("fsm_test_ladder_seconds", buckets=(0.5, 5.0))
    # same edges: get-or-create returns the existing instance
    obs.REGISTRY.histogram("fsm_test_ladder_seconds", buckets=(0.5, 5.0))
    with pytest.raises(ValueError):
        obs.REGISTRY.histogram("fsm_test_ladder_seconds", buckets=(1.0, 2.0))


def test_registry_enforces_naming_scheme():
    with pytest.raises(ValueError):
        obs.Counter("jobs_total")  # missing fsm_ prefix
    with pytest.raises(ValueError):
        obs.Counter("fsm_Bad_Case")
    with pytest.raises(ValueError):
        obs.REGISTRY.counter("fsm_trace_spans_total").inc(-1)  # decrease
    # kind mismatch on an existing name is a bug, not a silent re-make
    with pytest.raises(ValueError):
        obs.REGISTRY.gauge("fsm_trace_spans_total")


def test_collector_failure_does_not_break_scrape():
    obs.REGISTRY.register_collector("_test_boom",
                                    lambda: 1 / 0)
    try:
        text = obs.REGISTRY.render_prometheus()
        assert "fsm_trace_spans_total" in text
    finally:
        obs.REGISTRY.register_collector("_test_boom", lambda: [])


# ------------------------------------------------------ flight recorder

def test_ring_eviction_order():
    """The per-job ring keeps the LAST max_spans completed spans, in
    completion order, and counts what it dropped."""
    obs.configure_tracing(True, max_spans=3, max_jobs=4)
    with obs.trace("job-ring"):
        for i in range(6):
            with obs.span("step", i=i):
                pass
    # root span completes LAST, so the ring holds steps 4, 5, root
    dump = obs.trace_dump("job-ring")
    assert [s["site"] for s in dump["spans"]] == ["step", "step", "job"]
    assert [s.get("attrs", {}).get("i") for s in dump["spans"]][:2] == [4, 5]
    assert dump["dropped_spans"] == 4  # steps 0-3
    assert dump["n_spans"] == 3


def test_job_ring_eviction():
    obs.configure_tracing(True, max_spans=8, max_jobs=2)
    for uid in ("j1", "j2", "j3"):
        with obs.trace(uid):
            pass
    assert obs.trace_dump("j1") is None  # oldest evicted
    assert obs.trace_dump("j2") is not None
    assert obs.trace_dump("j3") is not None
    assert obs.last_trace_id() == "j3"


def test_thread_safety_concurrent_actor_spans():
    """N worker threads each trace their own job concurrently (the
    Miner-pool shape): every trace keeps exactly its own spans and the
    global counters add up — no lost updates, no cross-talk."""
    obs.configure_tracing(True, max_spans=200, max_jobs=16)
    n_threads, n_spans = 8, 50
    errors = []

    def work(k):
        try:
            with obs.trace(f"job-{k}"):
                for i in range(n_spans):
                    with obs.span("step", thread=k, i=i) as sp:
                        sp.event("tick", i=i)
        except Exception as exc:  # pragma: no cover - the assert is below
            errors.append(exc)

    threads = [threading.Thread(target=work, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for k in range(n_threads):
        dump = obs.trace_dump(f"job-{k}")
        steps = [s for s in dump["spans"] if s["site"] == "step"]
        assert len(steps) == n_spans
        assert all(s["attrs"]["thread"] == k for s in steps)
        assert dump["dropped_spans"] == 0


def test_disabled_cost_pin():
    """Tracing off: span() hands back ONE shared no-op singleton (no
    allocation, no clock read), trace_event is a no-op, and nothing
    reaches the recorder — the engine-side cost is a single
    module-global read, same as the fault registry's pin."""
    obs.configure_tracing(False)
    before = obs.recorder_stats()
    spans_metric0 = obs.REGISTRY.counter("fsm_trace_spans_total").snapshot()
    s1 = obs.span("tsr.launch", km=1, width=128)
    s2 = obs.span("tsr.readback")
    assert s1 is s2  # the singleton: zero per-probe allocation
    with s1 as sp:
        sp.event("never_recorded")
        sp.set(x=1)
    obs.trace_event("never_recorded")
    with obs.trace("ghost-job") as root:
        root.event("nope")
    assert obs.recorder_stats() == before
    assert obs.trace_dump("ghost-job") is None
    assert obs.REGISTRY.counter(
        "fsm_trace_spans_total").snapshot() == spans_metric0


def test_span_without_active_trace_is_noop():
    obs.configure_tracing(True, max_spans=16, max_jobs=4)
    # probe from a fresh thread: threads start with an empty context, so
    # no trace is active there even when the suite itself runs traced
    # (SPARKFSM_TRACE_TESTS wraps every test body in a trace)
    box = []
    t = threading.Thread(
        target=lambda: box.append(obs.span("orphan") is obs.span("orphan2")))
    t.start()
    t.join()
    assert box == [True]
    # explicit trace_id records even without a context trace
    obs._recorder.begin("explicit", {})
    with obs.span("pinned", trace_id="explicit"):
        pass
    assert [s["site"] for s in obs.trace_dump("explicit")["spans"]] \
        == ["pinned"]


def test_scrape_does_not_consume_chaos_triggers():
    """A /metrics scrape (or the snapshot embedded in /admin/stats and
    /admin/health) must never advance an armed store.get trigger: the
    jobs collector reads via the guard-free peek, so a pinned-seed
    chaos drill stays deterministic under concurrent scraping."""
    from spark_fsm_tpu.service.actors import Master

    m = Master()
    try:
        # delta, not absolute: the per-site counters are LIFETIME (they
        # survive disarm), so earlier chaos tests legitimately leave
        # nonzero store.get counts behind
        before = faults.counters().get("store.get", {"calls": 0,
                                                     "injected": 0})
        with faults.injected("store.get", nth=1):
            obs.REGISTRY.render_prometheus()
            obs.REGISTRY.snapshot()
            after = faults.counters().get("store.get", before)
        assert after.get("calls", 0) == before.get("calls", 0), (before,
                                                                 after)
        assert after.get("injected", 0) == before.get("injected", 0)
    finally:
        m.shutdown()


# ------------------------------------------------- acceptance: OOM trace

def test_oom_ladder_trace_dump():
    """A traced TSR mine under an armed device.oom fault dumps: the
    launch span carrying the RESOURCE_EXHAUSTED event, half-width
    re-plan CHILD spans nested under it, and predicted-vs-measured
    seconds on every launch span (the acceptance scenario at engine
    level; scripts/obs_smoke.sh drives the same story over the real
    /admin/trace HTTP surface)."""
    db = synthetic_db(seed=29, n_sequences=60, n_items=14,
                      mean_itemsets=3.0, mean_itemset_size=1.3)
    obs.configure_tracing(True, max_spans=4096, max_jobs=4)
    eng = TsrTPU(build_vertical(db, min_item_support=1), 10, 0.4,
                 max_side=2, use_pallas=True)
    with faults.injected("device.oom", nth=1):
        with obs.trace("oom-mine", algorithm="TSR_TPU"):
            eng.mine()
    assert eng.stats.get("degraded_launches", 0) >= 1
    dump = obs.trace_dump("oom-mine")
    spans = dump["spans"]
    oom = [s for s in spans
           for e in s.get("events", ())
           if e["name"] == "resource_exhausted"]
    assert oom, f"no RESOURCE_EXHAUSTED event in {sorted({s['site'] for s in spans})}"
    parent = oom[0]
    assert parent["site"] == "tsr.launch"
    assert "RESOURCE_EXHAUSTED" in [
        e for e in parent["events"] if e["name"] == "resource_exhausted"
    ][0]["error"]
    kids = [s for s in spans if s["parent_id"] == parent["span_id"]
            and s["site"] == "tsr.launch"]
    assert kids, "no half-width re-plan child spans"
    assert all(k["attrs"]["width"] == parent["attrs"]["width"] // 2
               for k in kids)
    launches = [s for s in spans if s["site"] == "tsr.launch"]
    assert all("predicted_s" in s["attrs"] and s["duration_s"] is not None
               for s in launches)
    readbacks = [s for s in spans if s["site"] == "tsr.readback"]
    assert readbacks and all("measured_s" in s["attrs"] for s in readbacks)
    # the residual gauge saw those dispatches
    assert obs.costmodel_drift() is not None


def test_span_launch_count_matches_engine_counter():
    """The bench_smoke cross-check invariant at test scale: span-derived
    launch count == the engine's kernel_launches counter, and tracing
    does not perturb the dispatch-shape counters."""
    db = synthetic_db(seed=7, n_sequences=50, n_items=12,
                      mean_itemsets=3.0, mean_itemset_size=1.3)
    base = TsrTPU(build_vertical(db, min_item_support=1), 10, 0.4,
                  max_side=2)
    want = base.mine()
    obs.configure_tracing(True, max_spans=1 << 14, max_jobs=4)
    eng = TsrTPU(build_vertical(db, min_item_support=1), 10, 0.4,
                 max_side=2)
    with obs.trace("xcheck"):
        got = eng.mine()
    assert got == want
    for key in ("kernel_launches", "evaluated", "traffic_units"):
        assert eng.stats[key] == base.stats[key]
    dump = obs.trace_dump("xcheck")
    n_spans = sum(1 for s in dump["spans"]
                  if s["site"] in ("tsr.launch", "tsr.prep"))
    assert n_spans == eng.stats["kernel_launches"]
    assert dump["dropped_spans"] == 0


# ------------------------------------------------------- HTTP endpoints

def test_metrics_endpoint_and_trace_404():
    """GET /metrics serves the registry regardless of tracing;
    /admin/trace/{uid} 404s while tracing is off (read-only, never an
    error path for the service)."""
    from spark_fsm_tpu.service.app import serve_background

    obs.configure_tracing(False)
    srv = serve_background()
    try:
        port = srv.server_port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        assert "# TYPE fsm_trace_spans_total counter" in text
        assert "fsm_fault_site_calls_total" in text
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/admin/trace/nope", timeout=30)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as exc:
            assert exc.code == 404
            assert "tracing disabled" in json.loads(
                exc.read().decode())["error"]
    finally:
        srv.master.shutdown()
        srv.shutdown()
