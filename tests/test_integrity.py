"""Durable-state integrity plane (ISSUE 18): envelope wire format,
per-surface verify-on-read degradation, the quarantine keyspace, and
the background scrubber.

The crash-TIMING halves of the story live next to their subsystems
(tests/test_checkpoint.py for torn saves, tests/test_resultcache.py for
the entry/sidecar write window); the chaos-injection half
(``store.corrupt``) lives in tests/test_chaos.py.  This file owns the
*byte-damage* semantics: what each surface does when stored bytes fail
their checksum.
"""

import json

import pytest

from spark_fsm_tpu import config as cfgmod
from spark_fsm_tpu.service import integrity, obsplane, resultcache
from spark_fsm_tpu.service.actors import (Master, StoreCheckpoint,
                                          recover_orphans)
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.utils import envelope


# ---------------------------------------------------------------- envelope


def _flip(value: str, at: int) -> str:
    return value[:at] + chr(ord(value[at]) ^ 0x01) + value[at + 1:]


def test_envelope_roundtrip_and_verdicts():
    payload = json.dumps({"k": [1, 2, 3], "täxt": "ünïcode ✓"})
    w = envelope.wrap(payload)
    assert envelope.is_enveloped(w)
    assert envelope.unwrap(w) == (payload, "ok")
    # legacy: anything not carrying the magic passes through unverified
    assert envelope.unwrap(payload) == (payload, "legacy")
    assert envelope.unwrap("") == ("", "legacy")
    assert envelope.unwrap(None) == (None, "missing")
    # byte-flip inside the payload: digest mismatch at intact length
    assert envelope.unwrap(_flip(w, len(w) - 3)) == (None, "corrupt")
    # flip inside the stored digest itself
    assert envelope.unwrap(_flip(w, 8)) == (None, "corrupt")
    # truncation: length mismatch
    assert envelope.unwrap(w[: len(w) // 2]) == (None, "corrupt")
    # an unknown schema version is corrupt, not legacy: the magic says
    # "enveloped", so failing to verify it must never read as a pass
    assert envelope.unwrap("FSME9" + w[5:]) == (None, "corrupt")
    # magic with a mangled header
    assert envelope.unwrap("FSME1:nonsense") == (None, "corrupt")


# ------------------------------------------------- checkpoint degradation


def test_corrupt_checkpoint_meta_restarts_fresh_loudly():
    store = ResultStore()
    ckpt = StoreCheckpoint(store, "cm-1", every_s=0.0)
    ckpt.save({"version": 1, "stack": [{"x": 1}], "results_done": 0,
               "results": [[[[1]], 3]]})
    ckpt.save({"version": 1, "stack": [], "results_done": 1,
               "results": [[[[2]], 2]]})
    meta_key = "fsm:frontier:cm-1"
    store.set(meta_key, _flip(store.get(meta_key), 80))
    assert ckpt.load() is None  # identity unverifiable: restart fresh
    # both keys dropped so the fresh mine starts clean...
    assert store.peek(meta_key) is None
    assert store.llen("fsm:frontier:results:cm-1") == 0
    # ...and the damaged bytes are preserved for the post-mortem
    assert store.peek("fsm:quarantine:frontier:cm-1") is not None


def test_legacy_checkpoint_loads_and_upgrades_on_next_save():
    """Pre-envelope checkpoints (bare JSON meta + bare delta chunks)
    still resume — no flag-day migration — and the next save rewrites
    the surface enveloped."""
    store = ResultStore()
    store.set("fsm:frontier:leg-1", json.dumps(
        {"version": 1, "stack": [], "results_total": 2,
         "results_inline": [[[[1]], 3]]}))
    store.rpush("fsm:frontier:results:leg-1", json.dumps([[[[2]], 2]]))
    ckpt = StoreCheckpoint(store, "leg-1", every_s=0.0)
    state = ckpt.load()
    assert state["results"] == [[[[1]], 3], [[[2]], 2]]
    ckpt.save({**state, "results_done": 2, "results": [[[[3]], 1]]})
    assert envelope.is_enveloped(store.get("fsm:frontier:leg-1"))
    assert envelope.is_enveloped(
        store.lrange("fsm:frontier:results:leg-1")[-1])
    assert ckpt.load()["results"] == [[[[1]], 3], [[[2]], 2], [[[3]], 1]]


# --------------------------------------------------- journal degradation


def test_recover_orphans_quarantines_poison_journal_and_continues():
    store = ResultStore()
    # a poison intent: bitrot ate the envelope mid-record
    store.set("fsm:journal:poison-1",
              _flip(envelope.wrap(json.dumps({"incarnation": "dead"})), 80))
    # a healthy already-terminal orphan AFTER it in scan order: recovery
    # must reach it (one bad record never wedges the pass)
    store.journal_set("zz-done", json.dumps({"incarnation": "dead"}))
    store.add_status("zz-done", "finished")
    master = Master(store=store)
    try:
        report = recover_orphans(master)
    finally:
        master.shutdown()
    assert report["quarantined"] == ["poison-1"]
    assert report["cleared"] == ["zz-done"]
    assert store.peek("fsm:journal:poison-1") is None  # moved
    qrec = envelope.unwrap(store.peek("fsm:quarantine:poison-1"))[0]
    assert json.loads(qrec)["surface"] == "journal"


def test_journal_get_returns_payload_and_raw_corruption():
    store = ResultStore()
    store.journal_set("u1", json.dumps({"replica": "a"}))
    assert json.loads(store.journal_get("u1")) == {"replica": "a"}
    store.set("fsm:journal:u1", _flip(store.get("fsm:journal:u1"), 75))
    raw = store.journal_get("u1")  # corrupt: RAW bytes, callers degrade
    with pytest.raises(ValueError):
        json.loads(raw)
    assert store.journal_get("nope") is None


# ----------------------------------------------------- spine degradation


def test_merged_timeline_skips_and_counts_corrupt_chunks():
    store = ResultStore()
    good = envelope.wrap(json.dumps(
        {"replica": "r1", "boot": "b1", "token": 1, "ts": 2.0,
         "spans": [{"span_id": 1, "site": "job", "ts": 2.0}]}))
    store.spine_append("u-spine", good)
    store.spine_append("u-spine", _flip(good, len(good) - 5))
    store.spine_append("u-spine", "not json at all {{")
    merged = obsplane.merged_timeline(store, "u-spine")
    assert merged["corrupt_chunks"] == 2
    assert merged["spine_chunks"] == 1
    assert [s["span_id"] for s in merged["spans"]] == [1]
    assert obsplane.last_activity_ts(store, "u-spine") == 2.0


# ---------------------------------------------------------------- scrubber


def _entry(payload_obj) -> str:
    from spark_fsm_tpu.ops.rule_trie import rules_digest

    payload = json.dumps(payload_obj)
    return json.dumps({"algo": "SPADE_TPU", "kind": "patterns",
                       "params": {}, "n_sequences": 5, "uid": "u-e",
                       "digest": rules_digest(payload), "ts": 1.0,
                       "payload": payload})


def test_scrubber_quarantines_at_rest_and_repairs_sidecars():
    store = ResultStore()
    # corrupt journal intent at rest
    store.set("fsm:journal:rot-j", _flip(envelope.wrap("{}"), 72))
    # intact rescache entry whose sidecar a crash window never wrote
    ekey = resultcache.entry_key("fp-ok", "SPADE_TPU")
    store.set(ekey, envelope.wrap(_entry([[[[1]], 4]])))
    # corrupt rescache entry (sidecar present and healthy-looking)
    bkey = resultcache.entry_key("fp-bad", "SPADE_TPU")
    wrapped = envelope.wrap(_entry([[[[2]], 4]]))
    store.set(bkey, wrapped[: len(wrapped) - 10])
    resultcache.write_sidecar(store, bkey, {"ts": 1.0}, 10)
    scr = integrity.Scrubber(store, scrub_every_s=0.0, batch=256)
    tally = scr.scrub()
    assert tally["corrupt"] >= 2 and tally["quarantined"] >= 2
    assert tally["repaired"] == 1
    # journal: quarantine-MOVED
    assert store.peek("fsm:journal:rot-j") is None
    assert store.peek("fsm:quarantine:rot-j") is not None
    # corrupt entry: moved, its sidecar dropped
    assert store.peek(bkey) is None
    assert store.peek(resultcache.sidecar_key_for(bkey)) is None
    # intact entry: sidecar re-derived with the entry's own age
    side = envelope.unwrap(
        store.peek(resultcache.sidecar_key_for(ekey)))[0]
    assert json.loads(side)["ts"] == 1.0
    # idempotent: a second pass finds the same damage, re-counts nothing
    q0 = integrity._QUARANTINED.total()
    scr.scrub()
    assert integrity._QUARANTINED.total() == q0


def test_scrubber_is_batch_bounded_with_cross_pass_cursor():
    """Ten rotten journal intents, batch 4: NO single pass exceeds its
    budget, and the cross-pass cursor still reaches every key — the
    scrub converges without ever becoming a scan storm."""
    store = ResultStore()
    for i in range(10):
        store.set(f"fsm:journal:u{i:02d}", _flip(envelope.wrap("{}"), 72))
    scr = integrity.Scrubber(store, scrub_every_s=0.0, batch=4)
    for _ in range(12):
        assert scr.scrub()["keys"] <= 4  # the batch bound, every pass
        if not store.scan_keys("fsm:journal:", "0", 64)[1]:
            break
    assert store.scan_keys("fsm:journal:", "0", 64)[1] == []
    assert len(list(store.scan_iter("fsm:quarantine:"))) == 10
    assert scr.passes >= 3  # 10 keys / batch 4: never one big scan


def test_report_lists_quarantine_and_counters():
    store = ResultStore()
    cfg = cfgmod.parse_config({"integrity": {"scrub_every_s": 7.5,
                                             "scrub_batch": 32}})
    integrity.configure(cfg.integrity)
    try:
        scr = integrity.install(store)
        assert scr is not None
        assert scr.scrub_every_s == 7.5 and scr.batch == 32
        integrity.quarantine(store, "fsm:journal:qq", "damaged-bytes",
                             "journal", move=True)
        rep = integrity.report(store)
        assert rep["enabled"] is True and rep["scrub_every_s"] == 7.5
        rows = {r.get("key"): r for r in rep["quarantine"]}
        assert rows["fsm:journal:qq"]["surface"] == "journal"
        assert rows["fsm:journal:qq"]["quarantine_key"] == \
            "fsm:quarantine:qq"
        for name in ("scans", "verified", "legacy", "corrupt",
                     "quarantined", "repaired"):
            assert name in rep["counters"]
    finally:
        integrity.uninstall()
        integrity.configure(cfgmod.Config().integrity)


def test_disabled_plane_installs_nothing_but_still_verifies():
    store = ResultStore()
    cfg = cfgmod.parse_config({"integrity": {"enabled": False}})
    integrity.configure(cfg.integrity)
    try:
        assert integrity.install(store) is None
        integrity.tick()  # no scrubber: a no-op, never a crash
        assert integrity.report(store)["enabled"] is False
        # verify-on-read is NOT the flag's to disable
        store.set("fsm:journal:u9", _flip(envelope.wrap("{}"), 72))
        raw = store.journal_get("u9")
        with pytest.raises(ValueError):
            json.loads(raw)
    finally:
        integrity.uninstall()
        integrity.configure(cfgmod.Config().integrity)


# ------------------------------------------------------------------ config


def test_integrity_config_parse_and_validation():
    cfg = cfgmod.parse_config({})
    assert cfg.integrity.enabled is True
    assert cfg.integrity.scrub_every_s == 60.0
    assert cfg.integrity.scrub_batch == 256
    with pytest.raises(ValueError):
        cfgmod.parse_config({"integrity": {"scrub_every_s": -1}})
    with pytest.raises(ValueError):
        cfgmod.parse_config({"integrity": {"scrub_batch": 0}})
