"""Fused extension-count-prune Pallas kernel (ISSUE 16,
ops/pallas_extend.py): interpret-mode parity with the numpy ops and the
jnp reference, the survivor-mask bit contract, and the grid/traffic
model pinned against the committed KERNELS.json entry.

The kernel itself is TPU-targeted; on the CPU test backend it runs
through the Pallas interpreter, which exercises identical index/block
logic (same arrangement as tests/test_pallas_support.py).
"""

import json
import os

import jax.numpy as jnp
import numpy as np

from spark_fsm_tpu.ops import pallas_extend as PE
from spark_fsm_tpu.ops.pallas_support import (
    I_TILE, P_TILE, S_BLOCK, seq_block)


def _rand_words(rng, *shape):
    # sparse-ish bitmaps
    return (rng.integers(0, 2**32, shape, dtype=np.uint32)
            & rng.integers(0, 2**32, shape, dtype=np.uint32)
            & rng.integers(0, 2**32, shape, dtype=np.uint32))


def _mask_bit(mask, p, i):
    return (int(mask[p, i // 32]) >> (i % 32)) & 1


def test_extend_count_prune_matches_numpy():
    rng = np.random.default_rng(0)
    P, NI, S = 2 * P_TILE, 21, S_BLOCK
    pt = _rand_words(rng, P, S)
    store = _rand_words(rng, I_TILE, S)
    store[NI:] = 0  # pad lanes are all-zero rows (the engine contract)
    want = np.array([[np.count_nonzero(pt[p] & store[i])
                      for i in range(NI)] for p in range(P)])
    thr = int(np.median(want))  # both sides of the threshold populated
    assert (want >= thr).any() and (want < thr).any()
    sup, mask = PE.extend_count_prune(
        jnp.asarray(pt)[:, None, :], jnp.asarray(store)[:, None, :],
        jnp.int32(thr), NI, interpret=True)
    sup, mask = np.asarray(sup), np.asarray(mask)
    ni = -(-NI // I_TILE) * I_TILE
    assert sup.shape == (P, ni) and mask.shape == (P, ni // 32)
    for p in range(P):
        for i in range(NI):
            w = want[p, i]
            # exact count where it survives, EXACTLY 0 where it dies
            assert sup[p, i] == (w if w >= thr else 0), (p, i)
            assert _mask_bit(mask, p, i) == int(w >= thr), (p, i)
    # pad lanes (all-zero item rows) never survive a thr >= 1
    assert not sup[:, NI:].any() and not np.asarray(
        [[_mask_bit(mask, p, i) for i in range(NI, ni)]
         for p in range(P)]).any()


def test_extend_count_prune_multiword():
    rng = np.random.default_rng(3)
    W = 3
    sb = seq_block(W)
    P, NI, S = P_TILE, 17, 2 * sb
    pt = _rand_words(rng, P, W, S)
    items = _rand_words(rng, I_TILE, W, S)
    want = np.array([[np.count_nonzero((pt[p] & items[i]).any(axis=0))
                      for i in range(NI)] for p in range(P)])
    thr = int(np.median(want))
    sup, mask = PE.extend_count_prune(
        jnp.asarray(pt), jnp.asarray(items), jnp.int32(thr), NI,
        s_block=sb, interpret=True)
    sup, mask = np.asarray(sup), np.asarray(mask)
    for p in range(P):
        for i in range(NI):
            w = want[p, i]
            assert sup[p, i] == (w if w >= thr else 0), (p, i)
            assert _mask_bit(mask, p, i) == int(w >= thr), (p, i)


def test_kernel_matches_jnp_reference_and_diffset_identity():
    """The kernel and ``extend_count_prune_jnp`` are byte-identical on
    the same inputs, and the reference's dEclat flag never changes the
    bytes (exact identity) — so the kernel needs no diffset leg."""
    rng = np.random.default_rng(7)
    W = 2
    sb = seq_block(W)
    P, NI, S = P_TILE, 33, sb
    pt = _rand_words(rng, P, W, S)
    items = _rand_words(rng, I_TILE, W, S)
    thr = 3
    sup_k, mask_k = PE.extend_count_prune(
        jnp.asarray(pt), jnp.asarray(items), jnp.int32(thr), NI,
        s_block=sb, interpret=True)
    for flag in (False, True):
        sup_r, mask_r = PE.extend_count_prune_jnp(
            jnp.asarray(pt.transpose(0, 2, 1)),
            jnp.asarray(items.transpose(0, 2, 1))[:NI],
            thr, jnp.full(P, flag))
        assert np.array_equal(np.asarray(sup_k)[:, :NI],
                              np.asarray(sup_r))
        ref_bits = np.asarray(mask_r)
        got_bits = np.asarray(mask_k)[:, :ref_bits.shape[1]]
        # the reference packs ceil(NI/32) words; the kernel's extra
        # pad-lane words must be dead
        tail = 32 - (NI % 32 or 32)
        keep = np.uint32(0xFFFFFFFF) >> np.uint32(tail)
        assert np.array_equal(got_bits[:, :-1], ref_bits[:, :-1])
        assert np.array_equal(got_bits[:, -1] & keep, ref_bits[:, -1])


def test_threshold_is_traced():
    """One compiled kernel serves every threshold: the same callable at
    thr=1 returns the full counts (every nonzero lane survives) and at
    a huge thr returns all-zero."""
    rng = np.random.default_rng(11)
    P, S = P_TILE, S_BLOCK
    pt = _rand_words(rng, P, S)
    store = _rand_words(rng, I_TILE, S)
    lo, _ = PE.extend_count_prune(
        jnp.asarray(pt)[:, None, :], jnp.asarray(store)[:, None, :],
        jnp.int32(1), 8, interpret=True)
    hi, hi_mask = PE.extend_count_prune(
        jnp.asarray(pt)[:, None, :], jnp.asarray(store)[:, None, :],
        jnp.int32(S + 1), 8, interpret=True)
    want = np.array([[np.count_nonzero(pt[p] & store[i])
                      for i in range(8)] for p in range(P)])
    assert np.array_equal(np.asarray(lo)[:, :8], want)
    assert not np.asarray(hi).any() and not np.asarray(hi_mask).any()


def test_grid_model_pins_committed_kernels_entry():
    """The committed KERNELS.json structural entry for the fused kernel
    derives from ``grid_model`` at the headline geometry — this is the
    drift tripwire between the model, the bench and the committed
    artifact."""
    gm = PE.grid_model(2048, 384, 1, 77824)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "KERNELS.json")
    with open(path) as fh:
        entry = [k for k in json.load(fh)["kernels"]
                 if k["kernel"].startswith("extend_count_prune")][0]
    assert entry["structural"] is True
    assert entry["traffic_model_bytes"] == gm["model_bytes"]
    assert entry["min_useful_bytes"] == gm["min_useful_bytes"]
    assert entry["vpu_model"]["total_vpu_ops"] == gm["vpu_ops"]
    assert entry["vpu_model"]["grid_steps"] == gm["grid_steps"]
    assert entry["vpu_model"]["ops_per_word"] == PE.EXTEND_VPU_OPS_PER_WORD
    assert entry["vpu_model"]["epilogue_ops_per_lane"] == \
        PE.EPILOGUE_VPU_OPS_PER_LANE
