"""Ragged super-batch packer (ops/ragged_batch.py) + engine integration.

Three layers:

1. planner units — the pow2 split/merge policy, the cost model's merge
   decisions, the lane/cap invariants, and exactly-once row coverage;
2. TSR parity — mixed-km super-batches through the engine's kernel
   (interpret) and jnp paths must reproduce the brute-force rule set,
   single-device and on the 8-way CPU mesh;
3. queue late waves — the narrow-phase drain must keep oracle parity
   (single-device and mesh) while actually running narrow waves.
"""

import numpy as np
import pytest

from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import build_vertical
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.models.spade_queue import QueueCaps, QueueSpadeTPU
from spark_fsm_tpu.models.tsr import TsrTPU, brute_force_rules
from spark_fsm_tpu.ops import ragged_batch as RB
from spark_fsm_tpu.utils.canonical import patterns_text, rules_text
from tests.test_oracle import random_db


# ------------------------------------------------------------- planner units


def _check_exactly_once(pools, launches):
    want = sorted(r for rows in pools.values() for r in rows)
    got = sorted(r for L in launches for r in L.rows)
    assert got == want
    for L in launches:
        assert len(L.rows) == len(L.kms) <= L.width
        assert L.km == max(L.kms)
        assert L.width & (L.width - 1) == 0  # pow2


def test_low_overhead_splits_full_pow2():
    pools = {1: list(range(5000))}
    launches = RB.plan_launches(pools, cap=lambda km: 2048, lane=128,
                                overhead=64)
    _check_exactly_once(pools, launches)
    # greedy full-fill splits; only the sub-pad tail stays padded
    assert [L.width for L in launches] == [2048, 2048, 512, 256, 128, 128]
    assert [len(L.rows) for L in launches] == [2048, 2048, 512, 256, 128, 8]


def test_high_overhead_collapses_to_cap_launches():
    pools = {1: list(range(5000))}
    launches = RB.plan_launches(pools, cap=lambda km: 2048, lane=128,
                                overhead=1 << 20)
    _check_exactly_once(pools, launches)
    # pad is free next to a dispatch: ceil(n / cap) launches
    assert [len(L.rows) for L in launches] == [2048, 2048, 904]
    assert launches[-1].width == 1024


def test_mixed_km_tails_merge_with_lane_tags():
    pools = {1: list(range(40)), 2: list(range(40, 70)),
             4: list(range(70, 90)), 8: list(range(90, 100))}
    launches = RB.plan_launches(pools, cap=lambda km: 8192, lane=128,
                                overhead=1 << 20)
    _check_exactly_once(pools, launches)
    assert len(launches) == 1
    (L,) = launches
    assert L.km == 8 and L.width == 128 and L.mixed
    assert L.borrowed == 90  # every lane below the km8 geometry
    assert sorted(set(L.kms)) == [1, 2, 4, 8]
    assert L.traffic_units == 128 * 8


def test_cost_model_refuses_expensive_merge():
    # a 900-candidate km1 tail must NOT ride a km8 geometry (8x its
    # traffic dwarfs one saved dispatch at full-scale overhead)
    pools = {1: list(range(900)), 8: list(range(900, 910))}
    launches = RB.plan_launches(pools, cap=lambda km: 8192, lane=128,
                                overhead=512)
    _check_exactly_once(pools, launches)
    assert len(launches) == 2
    assert launches[0].km == 8 and launches[0].width == 128
    assert launches[1].km == 1 and launches[1].width == 1024


def test_per_km_caps_respected():
    pools = {4: list(range(5000)), 1: list(range(5000, 5100))}
    launches = RB.plan_launches(pools, cap=lambda km: 8192 // km, lane=32,
                                overhead=1 << 20)
    _check_exactly_once(pools, launches)
    for L in launches:
        assert L.width <= 8192 // L.km


def test_overhead_and_quantum_anchors():
    # full-Kosarak axis: the measured anchors (KERNELS.json)
    assert 300 <= RB.overhead_units(990_000, 1) <= 700
    assert RB.dispatch_quantum_lanes(990_000, 1) == 8192
    # dryrun axis: a dispatch is worth ~10^5 pad lanes, the quantum
    # widens (clamped by the staleness bound)
    assert RB.overhead_units(2_000, 1) > 100_000
    assert RB.dispatch_quantum_lanes(2_000, 1) == 16384


def test_late_wave_nb():
    from spark_fsm_tpu.ops import pallas_support as PS

    assert RB.late_wave_nb(512, PS.P_TILE) == 64
    assert RB.late_wave_nb(512, PS.P_TILE) % PS.P_TILE == 0
    # ladder disables itself when the floor reaches nb
    assert RB.late_wave_nb(32, PS.P_TILE) == 32


def test_xy_stager_lifetime_and_fill():
    st = RB.XYStager()
    cands = [((1, 2), (3,)), ((4,), (5, 6, 7))]
    L = RB.Launch(4, 32, [0, 1], [2, 4])
    buf = st.take(L, cands)
    assert buf.shape == (32, 2, 4)
    assert buf[0, 0].tolist() == [1, 2, -1, -1]
    assert buf[1, 1].tolist() == [5, 6, 7, -1]
    assert (buf[2:] == -1).all()  # pad lanes
    buf2 = st.take(L, cands)
    assert buf2 is not buf  # outstanding buffers are never reissued
    st.release([buf])
    assert st.take(L, cands) is buf  # released buffers recycle


# ----------------------------------------------------------- TSR integration


def assert_rule_parity_eng(db, k, minconf, **kw):
    vdb = build_vertical(db, min_item_support=1)
    eng = TsrTPU(vdb, k, minconf, **kw)
    got = eng.mine()
    n_items = vdb.n_items
    want = brute_force_rules(db, k, minconf,
                             max_side=kw.get("max_side") or n_items)
    assert rules_text(got) == rules_text(want), (
        f"\n--- got ---\n{rules_text(got)}\n--- want ---\n{rules_text(want)}")
    return eng


def test_superbatch_parity_unlimited_sides_kernel():
    # unlimited sides exercise mixed-km launches through the Pallas
    # (interpret) kernel path — the 3d-shaped dispatch pattern.
    # resident="never": this test pins the HOST-loop packer (deep mines
    # otherwise auto-route to the resident-frontier path, ISSUE 7)
    rng = np.random.default_rng(31)
    db = random_db(rng, n_seq=25, n_items=6, max_itemsets=5, max_set=2)
    eng = assert_rule_parity_eng(db, 8, 0.4, max_side=None,
                                 use_pallas=True, resident="never")
    assert eng.stats["traffic_units"] > 0
    assert sum(v for k, v in eng.stats.items()
               if k.startswith("launches_km")) >= 1


def test_superbatch_parity_unlimited_sides_jnp():
    rng = np.random.default_rng(33)
    db = random_db(rng, n_seq=30, n_items=6, max_itemsets=6, max_set=2)
    eng = assert_rule_parity_eng(db, 10, 0.3, max_side=None,
                                 resident="never")
    # the merged-tail path actually ran: mixed-km super-batches exist
    assert eng.stats.get("superbatches", 0) >= 1
    assert eng.stats["traffic_units"] > 0


def test_superbatch_parity_mesh():
    import jax
    from spark_fsm_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(len(jax.devices()))
    rng = np.random.default_rng(35)
    db = random_db(rng, n_seq=26, n_items=6, max_itemsets=5, max_set=2)
    assert_rule_parity_eng(db, 8, 0.4, max_side=None, mesh=mesh,
                           use_pallas=True)


def test_conf_pruning_fires_and_keeps_parity():
    # a capped antecedent plus a high confidence floor makes conf-dead
    # right chains provably whole-subtree-dead: pruned_conf > 0 while
    # the rule set stays byte-identical to brute force
    rng = np.random.default_rng(37)
    db = random_db(rng, n_seq=40, n_items=8, max_itemsets=5, max_set=2)
    eng = assert_rule_parity_eng(db, 5, 0.8, max_side=1)
    assert (eng.stats["pruned_conf"] > 0
            or eng.stats.get("pruned_conf_chains", 0) > 0), eng.stats


# --------------------------------------------------------- queue late waves


def test_queue_late_wave_parity_and_counters():
    # default-caps engine (nb=512, nb_late=64) over a small DB: the
    # whole mine drains in narrow waves (roots < nb_late skip the wide
    # phase entirely) with oracle parity and one dispatch
    db = synthetic_db(seed=21, n_sequences=300, n_items=60,
                      mean_itemsets=6.0, mean_itemset_size=1.3)
    vdb = build_vertical(db, min_item_support=6)
    eng = QueueSpadeTPU(vdb, 6, caps=QueueCaps())
    assert eng._nb_late == 64
    got = eng.mine()
    assert got is not None
    assert patterns_text(got) == patterns_text(mine_spade(db, 6))
    assert eng.stats["kernel_launches"] == 1
    assert eng.stats["late_waves"] > 0
    assert eng.stats["late_waves"] <= eng.stats["waves"]


def test_queue_wide_then_late_phase():
    # more roots than nb_late: the wide phase runs first, the narrow
    # phase drains the tail — both counted, parity preserved
    db = synthetic_db(seed=13, n_sequences=200, n_items=90,
                      mean_itemsets=5.0, mean_itemset_size=1.3)
    vdb = build_vertical(db, min_item_support=2)
    n_roots = sum(1 for s in vdb.item_supports if int(s) >= 2)
    caps = QueueCaps(nb=512, ring=16384, c_cap=8192, r_cap=1 << 17)
    eng = QueueSpadeTPU(vdb, 2, caps=caps)
    assert n_roots > eng._nb_late, "fixture must exceed the late width"
    got = eng.mine()
    assert got is not None
    assert patterns_text(got) == patterns_text(mine_spade(db, 2))
    assert 0 < eng.stats["late_waves"] < eng.stats["waves"]


def test_queue_late_wave_parity_mesh():
    import jax
    from spark_fsm_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(len(jax.devices()))
    db = synthetic_db(seed=21, n_sequences=304, n_items=60,
                      mean_itemsets=6.0, mean_itemset_size=1.3)
    vdb = build_vertical(db, min_item_support=6)
    eng = QueueSpadeTPU(vdb, 6, mesh=mesh, caps=QueueCaps())
    got = eng.mine()
    assert got is not None
    assert patterns_text(got) == patterns_text(mine_spade(db, 6))
    assert eng.stats["late_waves"] > 0


def test_queue_segmented_late_switch_parity():
    # the host-side ladder: a checkpointed (segmented) mine switches to
    # the narrow program when the counters show a drained frontier;
    # pattern set byte-identical to the one-shot path
    db = synthetic_db(seed=13, n_sequences=200, n_items=90,
                      mean_itemsets=5.0, mean_itemset_size=1.3)
    vdb = build_vertical(db, min_item_support=2)
    caps = QueueCaps(nb=512, ring=16384, c_cap=8192, r_cap=1 << 17)
    eng = QueueSpadeTPU(vdb, 2, caps=caps)
    snaps = []
    got = eng.mine(checkpoint_cb=snaps.append, checkpoint_every_s=0.0,
                   seg_waves=4)
    assert got is not None
    assert patterns_text(got) == patterns_text(mine_spade(db, 2))
    assert eng.stats.get("late_waves", 0) > 0
    assert eng.stats["kernel_launches"] > 1  # actually segmented


def test_overhead_drift_recalibration(monkeypatch):
    """Plan-time overhead recalibration (ISSUE 6 satellite): the
    committed DISPATCH_SEC scales by the live cost-model drift EWMA —
    quantized to pow2 steps (plan stability), never below 1 (the
    measured anchor is a floor), clamped at the cap — and the
    launch-budget/bench pin (set_overhead_calibration(False), the
    conftest default for every test) restores the raw constant."""
    from spark_fsm_tpu.utils import obs

    try:
        RB.set_overhead_calibration(True)
        for drift, want in ((None, 1), (0.5, 1), (1.0, 1), (1.9, 1),
                            (2.0, 2), (3.9, 2), (4.0, 4), (7.2, 4),
                            (999.0, RB._DRIFT_FACTOR_CAP)):
            monkeypatch.setattr(obs, "costmodel_drift", lambda d=drift: d)
            assert RB.drift_factor() == want, drift
            assert RB.calibrated_dispatch_s() == RB.DISPATCH_SEC * want
            # the planner's default overhead resolves through the
            # calibrated constant...
            assert RB.overhead_units(990_000, 1) == RB.overhead_units(
                990_000, 1, dispatch_s=RB.DISPATCH_SEC * want)
        # ...and more overhead per launch can only merge MORE: the
        # drifted plan for two ragged tails never emits more launches
        pools = {1: list(range(40)), 2: list(range(40, 60))}
        drifted = RB.plan_launches(pools, cap=lambda km: 4096, lane=32)
        monkeypatch.setattr(obs, "costmodel_drift", lambda: 1.0)
        base = RB.plan_launches(pools, cap=lambda km: 4096, lane=32)
        assert len(drifted) <= len(base)
    finally:
        RB.set_overhead_calibration(False)
    assert RB.drift_factor() == 1  # the pin: raw committed constant
