"""Pallas TSR rule-support kernel: interpret-mode parity with numpy ops.

Same testing stance as tests/test_pallas_support.py — the interpreter
exercises the identical scalar-prefetch index maps, block revisiting, and
carry chains the TPU runs.
"""

import numpy as np
import jax.numpy as jnp

from spark_fsm_tpu.ops import bitops_np as BN
from spark_fsm_tpu.ops.pallas_tsr import C_LANES, rule_supports, seq_block


def _rand_words(rng, *shape):
    return (rng.integers(0, 2**32, shape, dtype=np.uint32)
            & rng.integers(0, 2**32, shape, dtype=np.uint32)
            & rng.integers(0, 2**32, shape, dtype=np.uint32))


def _reference(p1, s1, xy):
    """NumPy reference via ops/bitops_np on [.., seq, word] layout."""
    out = np.zeros((2, len(xy)), np.int32)
    for c, (xs, ys) in enumerate(xy):
        a = None
        for r in xs:
            if r < 0:
                continue
            row = p1[r].T[None]          # [1, S, W]
            a = row if a is None else (a & row)
        cc = None
        for r in ys:
            if r < 0:
                continue
            row = s1[r].T[None]
            cc = row if cc is None else (cc & row)
        out[0, c] = BN.support(BN.shift_up_one(a) & cc)[0]
        out[1, c] = BN.support(a)[0]
    return out


def _fold(arr):
    """[n, W, S] -> folded kernel layout with the all-ones pad row
    appended ([n+1, S/128, 128] single-word, [n+1, W, S/128, 128])."""
    pad = np.full((1,) + arr.shape[1:], 0xFFFFFFFF, np.uint32)
    k = np.concatenate([arr, pad], axis=0)
    n, W, S = k.shape
    if W == 1:
        return k.reshape(n, S // 128, 128)
    return k.reshape(n, W, S // 128, 128)


def _run_case(seed, W, km, n_rows=9, n_blocks=2):
    rng = np.random.default_rng(seed)
    sb = seq_block(W, 8 * 128)
    S = n_blocks * sb
    p1 = _rand_words(rng, n_rows, W, S)
    s1 = _rand_words(rng, n_rows, W, S)
    C = C_LANES
    xy = np.full((C, 2, km), -1, np.int32)
    for c in range(C):
        nx = rng.integers(1, km + 1)
        ny = rng.integers(1, km + 1)
        xy[c, 0, :nx] = rng.choice(n_rows, nx, replace=False)
        xy[c, 1, :ny] = rng.choice(n_rows, ny, replace=False)

    # explicit s_block: S = n_blocks * sb exercises the multi-seq-block
    # grid (the auto block would cover the whole S in one step)
    got = np.asarray(rule_supports(
        jnp.asarray(_fold(p1)), jnp.asarray(_fold(s1)), jnp.asarray(xy),
        km=km, s_block=sb, interpret=True))
    want = _reference(p1, s1, xy)
    np.testing.assert_array_equal(got, want)


def test_rule_supports_single_word_km1():
    _run_case(seed=0, W=1, km=1)


def test_rule_supports_single_word_km2():
    _run_case(seed=1, W=1, km=2)


def test_rule_supports_multiword_km2():
    # W=3 exercises the cross-word shift_up_one carry chain
    _run_case(seed=2, W=3, km=2)


def test_rule_supports_multiple_out_blocks():
    # C > C_LANES: the out block is revisited per 128 candidates
    rng = np.random.default_rng(5)
    W, km = 1, 1
    sb = seq_block(W, 8 * 128)
    p1 = _rand_words(rng, 5, W, sb)
    s1 = _rand_words(rng, 5, W, sb)
    C = 2 * C_LANES
    xy = np.stack([rng.integers(0, 5, (C, km)),
                   rng.integers(0, 5, (C, km))], axis=1).astype(np.int32)
    got = np.asarray(rule_supports(
        jnp.asarray(_fold(p1)), jnp.asarray(_fold(s1)),
        jnp.asarray(xy), km=km, interpret=True))
    want = _reference(p1, s1, xy)
    np.testing.assert_array_equal(got, want)
