"""Launch-count regression guard (dryrun-scale, tier-1).

BENCH_SCALE runs hours on real hardware, so a batching regression there
surfaces weeks late.  These tests pin ``kernel_launches``/``evaluated``
for deterministic dryrun-scale miniatures of the two workloads the
ragged super-batch layer (ops/ragged_batch.py) exists for:

- a config-3d-shaped TSR mine (Kosarak-shaped data, unlimited rule
  sides, service-default knobs) — the measured collapse on this
  miniature is 49 -> 10 launches (4.9x) against the pre-superbatch
  dispatch policy, with the rule set unchanged;
- a late-wave queue mine — one dispatch, with the drain running at the
  narrow late-wave geometry.

The pins are EXACT: the search is deterministic on the CPU backend
(tier-1 pins JAX_PLATFORMS=cpu), so any drift — up OR down — means the
dispatch policy changed and the committed expectations (also mirrored
in scripts/bench_smoke_expect.json) must be re-derived deliberately.
"""

import numpy as np

from spark_fsm_tpu.data.synth import kosarak_like, synthetic_db
from spark_fsm_tpu.data.vertical import build_vertical
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.models.spade_queue import QueueCaps, QueueSpadeTPU
from spark_fsm_tpu.models.tsr import TsrTPU
from spark_fsm_tpu.utils.canonical import patterns_text


def test_tsr_3d_shape_launch_budget():
    # config 3d HOST-LOOP reference at dryrun scale: ~2k Kosarak-shaped
    # sequences, 128 items, k=100, minconf=0.5, max_side UNSET.
    # resident="never" pins the classic host-driven loop — the pre-
    # residency reference row the resident pin below is measured
    # against (the bench_smoke "3r" row)
    db = kosarak_like(scale=0.002, fast=True)
    vdb = build_vertical(db, min_item_support=1)
    eng = TsrTPU(vdb, 100, 0.5, max_side=None, resident="never")
    rules = eng.mine()
    assert len(rules) == 100
    st = eng.stats
    # one prep + 9 planned eval launches (pre-superbatch policy: 49)
    assert st["kernel_launches"] == 10, st
    assert st["evaluated"] == 136072, st
    assert st["traffic_units"] == 409600, st
    # the km mix itself (candidate-generation drift also fails loudly)
    assert st["evaluated_km1"] == 16256, st
    assert st["evaluated_km2"] == 67918, st
    assert st["evaluated_km4"] == 51898, st
    assert "resident" not in st, st


def test_tsr_3d_resident_launch_budget():
    """Resident-frontier pin for the SAME 3d miniature on service-
    default knobs (resident='auto' must route it): the whole unlimited-
    side mine collapses to one prep + two while_loop segments — 3
    launches against the host loop's 10 and the capped config-3 shape's
    7 (the ISSUE-7 acceptance bound is <= 2x config 3 = 14).  The six
    over-km-ladder children are deferred on device and all die against
    the final top-k threshold (no host handoff, no spill), and the
    device search evaluates FEWER candidates than the host loop: the
    exact on-device top-k threshold rises wave-by-wave, where the host
    pipeline dispatches against a stale minsup."""
    db = kosarak_like(scale=0.002, fast=True)
    vdb = build_vertical(db, min_item_support=1)
    eng = TsrTPU(vdb, 100, 0.5, max_side=None)  # auto -> resident
    rules = eng.mine()
    assert len(rules) == 100
    st = eng.stats
    assert st.get("resident") is True, st
    assert st["kernel_launches"] == 3, st
    assert st["resident_segments"] == 2, st
    assert st["resident_waves"] == 283, st
    assert st["evaluated"] == 119066, st
    assert st["traffic_units"] == 531200, st
    assert st["resident_deferred"] == 6, st
    assert "resident_spills" not in st, st
    assert "resident_handoffs" not in st, st
    # oracle parity vs the pinned host loop above
    eng_h = TsrTPU(vdb, 100, 0.5, max_side=None, resident="never")
    assert eng_h.mine() == rules


def test_tsr_3_shape_launch_budget():
    # the max_side=2 comparison row (config 3 shape): same data, capped
    # sides — the km1/km2 workload the 3-vs-3d decomposition anchors on
    db = kosarak_like(scale=0.002, fast=True)
    vdb = build_vertical(db, min_item_support=1)
    eng = TsrTPU(vdb, 100, 0.5, max_side=2)
    rules = eng.mine()
    assert len(rules) == 103  # tie-inclusive top-100
    st = eng.stats
    assert st["kernel_launches"] == 7, st
    assert st["evaluated"] == 86936, st
    assert st["traffic_units"] == 163840, st


def test_queue_late_wave_budget():
    # late-wave queue miniature: frontier far below nb for most of the
    # drain — the whole mine stays ONE dispatch and the narrow phase
    # does the tail work
    db = synthetic_db(seed=21, n_sequences=300, n_items=60,
                      mean_itemsets=6.0, mean_itemset_size=1.3)
    vdb = build_vertical(db, min_item_support=6)
    eng = QueueSpadeTPU(vdb, 6, caps=QueueCaps())
    got = eng.mine()
    assert got is not None
    assert patterns_text(got) == patterns_text(mine_spade(db, 6))
    assert eng.stats["kernel_launches"] == 1
    assert eng.stats["waves"] > 0
    assert eng.stats["late_waves"] == eng.stats["waves"]  # all-narrow
    assert eng.stats["candidates"] > 0


def test_fused_cross_job_launch_budget():
    """Launch-budget pin for the FUSED path (ISSUE 6): a deterministic
    two-job window group — 120 ragged candidates from job A, 50 from
    job B, distinct preps, the committed cost-model constants (the
    conftest calibration pin) — must collapse into EXACTLY one shared
    cross-job launch (per-job plans: one launch each), at the pinned
    geometry.  Any drift means the fusion/packing policy changed and
    the expectations must be re-derived deliberately, like the solo
    pins above."""
    from spark_fsm_tpu.service import fusion as FZ
    from tests.test_fusion import _check, _wave

    b = FZ.FusionBroker(window_s=0.25, max_jobs=8, max_width=16384)
    b.hold()
    wa = _wave("job-a", base=1, m=256, n_seq=2000,
               cands=[((i % 100,), ((i + 1) % 100,)) for i in range(100)]
                     + [((i, i + 1), (i + 2,)) for i in range(20)])
    wb = _wave("job-b", base=5000, m=256, n_seq=2000,
               cands=[((i % 64,), ((i + 3) % 64,)) for i in range(40)]
                     + [((i, i + 1, i + 2), (i + 3,)) for i in range(10)])
    b.submit(wa)
    b.submit(wb)
    b.release()
    ra, rb = _check(wa), _check(wb)
    assert b.drain(10.0)
    # the per-job alternative is one launch EACH (the packer merges each
    # job's tails): fusion halves the dispatch count for this group
    from spark_fsm_tpu.ops import ragged_batch as RB

    for w in (wa, wb):
        solo = RB.plan_launches(w.pools, cap=w.cap, lane=w.lane,
                                overhead=RB.overhead_units(2000, 1),
                                record=False)
        assert len(solo) == 1
    assert ra == rb  # one shared launch: both riders see the same plan
    assert ra["fused_jobs"] == 2
    assert ra["launches"] == 1
    assert ra["cross_job_launches"] == 1
    assert ra["traffic_units"] == 1024  # km4 geometry x 256 lanes
    assert ra["m_pad"] == 512  # 2x 256-row preps, pow2 bucket
    # alt_solo_*: the unfused alternative was 2 launches of 256 lanes
    # (km1 x 256 and km4 x 64 tails pack to one merged launch each) —
    # the device-dispatch saving the broker's accounting reports
    assert b.stats == {
        "waves": 2, "fused_waves": 2, "solo_waves": 0, "launches": 1,
        "cross_job_launches": 1, "fused_groups": 1,
        "rejected_groups": 0, "degraded": 0, "traffic_units": 1024,
        "alt_solo_launches": 2, "alt_solo_units": 512}
