import numpy as np
import pytest

from spark_fsm_tpu.data.spmf import parse_spmf
from spark_fsm_tpu.models.oracle import brute_force_mine, contains, mine_spade
from spark_fsm_tpu.utils.canonical import patterns_text, diff_patterns

# Worked example in the style of Zaki's SPADE paper (SURVEY.md sec 4).
ZAKI_DB = parse_spmf(
    """
    1 3 -1 2 -1 2 4 -2
    1 -1 2 -2
    3 -1 2 4 -2
    1 3 -1 4 -2
    """
)


def test_contains():
    seq = ((1, 3), (2,), (2, 4))
    assert contains(seq, ((1,), (2,)))
    assert contains(seq, ((1, 3), (2, 4)))
    assert contains(seq, ((2,), (2,)))
    assert not contains(seq, ((2,), (1,)))
    assert not contains(seq, ((1, 2),))
    assert not contains(seq, ((2,), (2,), (2,)))


def test_zaki_fixture_spot_values():
    res = dict(mine_spade(ZAKI_DB, minsup_abs=2))
    assert res[((1,),)] == 3
    assert res[((2,),)] == 3
    assert res[((1, 3),)] == 2
    assert res[((1,), (2,))] == 2
    assert res[((3,), (2, 4))] == 2
    assert res[((3,), (4,))] == 3
    assert ((2,), (1,)) not in res
    assert ((1, 2),) not in res


def test_oracle_matches_brute_force_on_fixture():
    a = mine_spade(ZAKI_DB, minsup_abs=2)
    b = brute_force_mine(ZAKI_DB, minsup_abs=2, max_pattern_itemsets=8, max_itemset_size=4)
    assert patterns_text(a) == patterns_text(b), diff_patterns(a, b)


def random_db(rng, n_seq=12, n_items=5, max_itemsets=4, max_set=3):
    db = []
    for _ in range(n_seq):
        seq = []
        for _ in range(rng.integers(1, max_itemsets + 1)):
            k = int(rng.integers(1, max_set + 1))
            itemset = tuple(sorted(rng.choice(n_items, size=k, replace=False) + 1))
            seq.append(tuple(int(x) for x in itemset))
        db.append(tuple(seq))
    return db


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("minsup", [2, 4])
def test_oracle_matches_brute_force_randomized(seed, minsup):
    rng = np.random.default_rng(seed)
    db = random_db(rng)
    a = mine_spade(db, minsup_abs=minsup)
    b = brute_force_mine(db, minsup_abs=minsup, max_pattern_itemsets=8, max_itemset_size=5)
    assert patterns_text(a) == patterns_text(b), diff_patterns(a, b)


def test_max_pattern_itemsets_cap():
    res = mine_spade(ZAKI_DB, minsup_abs=2, max_pattern_itemsets=1)
    assert all(len(p) == 1 for p, _ in res)
    # i-extensions within the single itemset still allowed
    assert ((1, 3),) in dict(res)


def test_empty_result():
    assert mine_spade(parse_spmf("1 -2\n2 -2\n"), minsup_abs=2) == []
