"""Elastic control plane drills (ISSUE 13, service/autoscale.py +
Miner.drain).

Two kinds of test, the PR 8 pattern:

- HERMETIC controller tests: autoscalers + lease managers + an
  in-process store share one VIRTUAL monotonic clock, so leader
  election, hysteresis, cooldown and expiry are exact — no sleeps.
- END-TO-END drain drills: real ``Miner``s ("replicas") share one
  store; the drain protocol runs against real worker threads and the
  real steal/recovery machinery, driven by manual heartbeat ticks.

The acceptance pins: sustained load → ONE scale-up decision record;
load oscillating inside the hysteresis band → ZERO decisions; scale-
down picks the least-loaded replica and the victim drains — queue
stolen by peers, zero lost jobs, oracle parity; a thief dying mid-
drain heals via periodic recovery."""

import json
import threading
import time

import pytest

from spark_fsm_tpu import config as cfgmod
from spark_fsm_tpu.data.spmf import format_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.service import autoscale as AS
from spark_fsm_tpu.service import sources
from spark_fsm_tpu.service.actors import Master
from spark_fsm_tpu.service.lease import LeaseManager
from spark_fsm_tpu.service.model import ServiceRequest, \
    deserialize_patterns
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.utils import obs
from spark_fsm_tpu.utils.canonical import patterns_text

DRILL_TIMEOUT_S = 120.0


def _acfg(**kw):
    base = {"min_replicas": 1, "max_replicas": 8,
            "up_queue_per_worker": 2.0, "down_free_frac": 0.5,
            "hold_s": 10.0, "cooldown_s": 30.0, "leader_ttl_s": 3.0,
            "drain_timeout_s": 60.0}
    base.update(kw)
    return cfgmod.parse_config(
        {"autoscale": {"enabled": True, **base},
         "cluster": {"enabled": True}}).autoscale


class FakeMiner:
    """Duck-typed load source for controller-only tests."""

    def __init__(self, workers=2):
        self.q = 0
        self.r = 0
        self.w = workers
        self.adm = 0  # lifetime admissions (heartbeat "adm")
        self.draining = False
        self.drained_with = None

    def admitted_total(self):
        return self.adm

    def queue_size(self):
        return self.q

    def running_count(self):
        return self.r

    def worker_count(self):
        return self.w

    def idle_capacity(self):
        return max(0, self.w - self.r - self.q)

    def sheds_total(self):
        return 0

    def wall_ewma(self):
        return None

    def tenant_depths(self):
        return {}

    def inflight_fps(self):
        return []

    def drain(self, timeout_s=None, reason=""):
        self.draining = True
        self.drained_with = {"timeout_s": timeout_s, "reason": reason}
        return {"outcome": "clean", "reason": reason,
                "left_for_recovery": 0}


def _rig(n=2, **acfg_kw):
    """n (scaler, fake-miner, mgr) triples on one virtual-clock store."""
    t = [0.0]
    store = ResultStore(clock=lambda: t[0])
    out = []
    cfg = _acfg(**acfg_kw)
    for i in range(n):
        mgr = LeaseManager(store, replica_id=f"as-{i}",
                           lease_ttl_s=30.0, heartbeat_s=0,
                           clock=lambda: t[0])
        m = FakeMiner()
        mgr.start(m)
        sc = AS.Autoscaler(m, mgr, acfg=cfg, decide_every_s=0,
                           clock=lambda: t[0])
        out.append((sc, m, mgr))
    return t, store, out


def _decisions():
    fam = obs.REGISTRY.snapshot().get("fsm_autoscale_decisions_total", {})
    fam = fam if isinstance(fam, dict) else {}
    return {"up": fam.get("dir=up", 0), "down": fam.get("dir=down", 0)}


# ---------------------------------------------------------------- election


def test_exactly_one_leader_and_failover_after_ttl():
    t, store, rigs = _rig(2)
    (sc_a, _, _), (sc_b, _, _) = rigs
    sc_a.tick()
    sc_b.tick()
    rec = AS._open(store.peek(AS.LEADER_KEY))
    assert rec["replica"] == "as-0"
    assert sc_a.stats()["is_leader"] and not sc_b.stats()["is_leader"]
    # the leader dies (stops ticking); its lease expires on the store
    # clock and the survivor takes over with a larger token
    tok0 = rec["token"]
    t[0] = 10.0  # > leader_ttl_s
    sc_b.tick()
    rec = AS._open(store.peek(AS.LEADER_KEY))
    assert rec["replica"] == "as-1"
    assert rec["token"] > tok0


# --------------------------------------------------------------- decisions


def test_sustained_load_scales_up_once_after_hold():
    t, store, rigs = _rig(1, hold_s=10.0, cooldown_s=100.0)
    sc, m, mgr = rigs[0]
    d0 = _decisions()
    m.q = 10  # load 5.0/worker > 2.0
    sc.tick()  # signal starts holding at t=0
    assert store.peek(AS.DESIRED_KEY) is None  # hysteresis: not yet
    t[0] = 5.0
    sc.tick()
    assert store.peek(AS.DESIRED_KEY) is None
    t[0] = 10.0
    sc.tick()  # held for hold_s: decision fires
    rec = AS._open(store.peek(AS.DESIRED_KEY))
    assert rec["dir"] == "up" and rec["desired"] == 2 \
        and rec["replicas"] == 1
    assert rec["leader"] == "as-0" and rec["seq"] > 0
    assert "queued/worker" in rec["reason"]
    d1 = _decisions()
    assert d1["up"] == d0["up"] + 1
    # the decision log ring recorded it
    assert sc.decision_log()[-1]["seq"] == rec["seq"]
    # still loaded, but inside the cooldown: no second decision
    t[0] = 25.0
    sc.tick()
    assert _decisions()["up"] == d1["up"]


def test_oscillating_load_inside_the_band_never_decides():
    """The flap pin: load alternating above/below the up threshold
    faster than hold_s accumulates no hold time — zero decisions over
    many ticks."""
    t, store, rigs = _rig(1, hold_s=10.0)
    sc, m, mgr = rigs[0]
    d0 = _decisions()
    for i in range(40):
        m.q = 10 if i % 2 == 0 else 1  # load 5.0 / 0.5, band is 2.0
        t[0] += 4.0  # < hold_s between flips
        sc.tick()
    assert _decisions() == d0
    assert store.peek(AS.DESIRED_KEY) is None


def test_p99_signal_scales_up():
    from spark_fsm_tpu.service import obsplane

    t, store, rigs = _rig(1, up_p99_s=1.0, hold_s=0.0)
    sc, m, mgr = rigs[0]
    d0 = _decisions()
    obsplane.clear_slo()
    try:
        for _ in range(20):
            obsplane.observe_job("normal", 5.0, 1.0, 4.0)
        t[0] = 1.0
        sc.tick()
        rec = AS._open(store.peek(AS.DESIRED_KEY))
        assert rec["dir"] == "up" and "p99" in rec["reason"]
        assert _decisions()["up"] == d0["up"] + 1
    finally:
        obsplane.clear_slo()


def test_admission_rate_derivative_scales_up_predictively():
    """ISSUE 15 satellite (ROADMAP item 4 remainder): an ACCELERATING
    admission rate scales up before the queue builds — the EWMA'd
    rate derivative is the signal, guarded by the same hold_s
    hysteresis; a steady (even high) rate never fires it."""
    t, store, rigs = _rig(1, up_rate_derivative=0.5, hold_s=3.0,
                          cooldown_s=100.0)
    sc, m, mgr = rigs[0]
    d0 = _decisions()

    # steady rate first: +5 admissions per tick, derivative ~ 0
    for i in range(8):
        t[0] = float(i)
        m.adm += 5
        sc.tick()
    assert store.peek(AS.DESIRED_KEY) is None
    assert _decisions() == d0
    last = sc.stats()["last_eval"]
    assert last["adm_rate_ewma"] is not None
    assert abs(last["adm_deriv_ewma"] or 0.0) < 0.5

    # accelerating: rate grows every tick; queue stays EMPTY (the
    # whole point — this signal fires before queued/worker can)
    rate = 5
    fired_at = None
    for i in range(8, 20):
        t[0] = float(i)
        rate += 4
        m.adm += rate
        sc.tick()
        if store.peek(AS.DESIRED_KEY) is not None:
            fired_at = i
            break
    assert fired_at is not None
    rec = AS._open(store.peek(AS.DESIRED_KEY))
    assert rec["dir"] == "up"
    assert "rate" in rec["reason"] and "d(rate)/dt" in rec["reason"]
    assert _decisions()["up"] == d0["up"] + 1
    # hysteresis: the signal needed hold_s of continuous acceleration
    last = sc.stats()["last_eval"]
    assert last["queued"] == 0  # predictive, not reactive


def test_admission_rate_derivative_off_by_default():
    t, store, rigs = _rig(1, hold_s=0.0, cooldown_s=0.0)
    sc, m, mgr = rigs[0]
    d0 = _decisions()
    rate = 1
    for i in range(10):
        t[0] = float(i)
        rate *= 2
        m.adm += rate
        sc.tick()
    assert store.peek(AS.DESIRED_KEY) is None
    assert _decisions() == d0


def test_fleet_p99_merge_scales_up_from_a_peer_digest():
    """ISSUE 14 satellite: the up_p99 signal is the FLEET max of the
    heartbeat-piggybacked per-replica digests — an IDLE leader (empty
    local window) must still scale up when a peer's digest shows a
    saturated p99."""
    from spark_fsm_tpu.service import obsplane

    t, store, rigs = _rig(2, up_p99_s=1.0, hold_s=0.0)
    (sc_a, m_a, mgr_a), (sc_b, m_b, mgr_b) = rigs
    d0 = _decisions()
    obsplane.clear_slo()  # the leader's own window is EMPTY (idle)
    try:
        # the peer's heartbeat record carries a saturated digest (the
        # field publish_heartbeat now piggybacks from its local window;
        # stamped directly here so the leader's merge — not the peer's
        # in-process obsplane, which the two rigs share — is what's
        # under test)
        mgr_b.publish_heartbeat()
        rec = AS._open(store.peek("fsm:replica:as-1"))
        assert "slo" in rec  # the digest field rides every heartbeat
        rec["slo"] = {"p99": 6.5, "n": 40}
        store.set_px("fsm:replica:as-1", json.dumps(rec), 30000)
        t[0] = 1.0
        sc_a.tick()  # as-0 leads, local window empty — peer digest wins
        out = AS._open(store.peek(AS.DESIRED_KEY))
        assert out["dir"] == "up" and "p99" in out["reason"]
        assert sc_a.stats()["last_eval"]["p99_s"] == 6.5
        assert _decisions()["up"] == d0["up"] + 1
    finally:
        obsplane.clear_slo()


def test_scale_down_targets_least_loaded_and_respects_min():
    t, store, rigs = _rig(2, hold_s=5.0, min_replicas=1,
                          down_free_frac=0.5)
    (sc_a, m_a, mgr_a), (sc_b, m_b, mgr_b) = rigs
    # both replicas idle; B advertises itself via heartbeat so the
    # leader's cluster view sees two live rows
    m_a.r, m_b.r = 1, 0  # A busier: the victim must be B
    mgr_b.publish_heartbeat()
    d0 = _decisions()
    sc_a.tick()  # leader + signal start
    t[0] = 5.0
    mgr_b.publish_heartbeat()
    sc_a.tick()
    rec = AS._open(store.peek(AS.DESIRED_KEY))
    assert rec["dir"] == "down" and rec["desired"] == 1
    assert rec["victim"] == "as-1"
    assert _decisions()["down"] == d0["down"] + 1
    assert store.peek(AS.drain_key("as-1")) is not None
    # min_replicas floor: with one live replica left no further down
    # decision is possible (B claims its directive + reports drained)
    sc_b.tick()
    deadline = time.time() + 10.0
    while time.time() < deadline and not m_b.draining:
        time.sleep(0.01)
    assert m_b.draining
    assert m_b.drained_with["reason"]
    deadline = time.time() + 10.0
    while time.time() < deadline and \
            store.peek(AS.drained_key("as-1")) is None:
        time.sleep(0.01)
    assert store.peek(AS.drained_key("as-1")) is not None
    assert store.peek(AS.drain_key("as-1")) is None  # claimed via DEL


def test_no_scale_down_at_min_replicas():
    t, store, rigs = _rig(1, hold_s=0.0, min_replicas=1)
    sc, m, mgr = rigs[0]
    d0 = _decisions()
    t[0] = 100.0
    sc.tick()  # idle single replica: down signal blocked by the floor
    assert _decisions() == d0


def test_draining_replica_stops_evaluating():
    t, store, rigs = _rig(1)
    sc, m, mgr = rigs[0]
    m.draining = True
    m.q = 100
    t[0] = 100.0
    sc.tick()
    sc.tick()
    assert store.peek(AS.LEADER_KEY) is None  # never even ran election


def test_autoscale_config_validation():
    with pytest.raises(cfgmod.ConfigError, match="cluster"):
        cfgmod.parse_config({"autoscale": {"enabled": True}})
    with pytest.raises(cfgmod.ConfigError, match="max_replicas"):
        cfgmod.parse_config({"autoscale": {
            "min_replicas": 4, "max_replicas": 2}})
    with pytest.raises(cfgmod.ConfigError, match="down_free_frac"):
        cfgmod.parse_config({"autoscale": {"down_free_frac": 1.5}})
    with pytest.raises(cfgmod.ConfigError, match="leader_ttl_s"):
        cfgmod.parse_config({"autoscale": {"leader_ttl_s": 0}})
    with pytest.raises(cfgmod.ConfigError, match="up_queue_per_worker"):
        cfgmod.parse_config({"autoscale": {"up_queue_per_worker": 0}})


# ------------------------------------------------------------ drain drills


def _req(uid, **extra):
    data = {"algorithm": "SPADE", "source": "INLINE",
            "sequences": "1 -1 2 -2\n1 -1 2 -2\n", "support": "1.0",
            "uid": uid}
    data.update({k: str(v) for k, v in extra.items()})
    return ServiceRequest("fsm", "train", data)


def _await_terminal(store, uid, timeout=DRILL_TIMEOUT_S):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = store.status(uid)
        if st in ("finished", "failure"):
            return st
        time.sleep(0.01)
    raise TimeoutError(f"job {uid} reached no terminal status "
                       f"(now {store.status(uid)!r})")


def test_drain_under_full_queue_peers_steal_everything(monkeypatch):
    """The ISSUE 13 drain drill: replica A drains while holding one
    RUNNING job and four QUEUED ones.  Idle peer B steals the entire
    queue off A's admission namespace (the drain loop reaps the
    claimed markers — the paused queue cannot shrink itself), the
    running job finishes on A, the drain reports clean, and every job
    lands finished with oracle parity — zero lost, zero duplicated."""
    store = ResultStore()
    mk = lambda rid: LeaseManager(store, replica_id=rid,
                                  lease_ttl_s=30.0, heartbeat_s=0)
    mgr_a, mgr_b = mk("rep-a"), mk("rep-b")
    master_a = Master(store=store, miner_workers=1, lease_mgr=mgr_a)
    master_b = Master(store=store, miner_workers=1, lease_mgr=mgr_b)
    gate = threading.Event()
    entered = threading.Event()
    real = sources.get_db

    def gated(req, store_):
        if req.uid == "hold" and not entered.is_set():
            entered.set()
            assert gate.wait(DRILL_TIMEOUT_S)
        return real(req, store_)

    monkeypatch.setattr(sources, "get_db", gated)
    db = synthetic_db(seed=61, n_sequences=80, n_items=10,
                      mean_itemsets=3.0, mean_itemset_size=1.3)
    want = mine_spade(db, abs_minsup(0.1, len(db)))
    uids = [f"steal-me-{i}" for i in range(4)]
    drops0 = obs.REGISTRY.snapshot()["fsm_steal_victim_drops_total"]
    try:
        master_a.miner.submit(_req("hold"))
        assert entered.wait(DRILL_TIMEOUT_S)
        for uid in uids:
            master_a.miner.submit(_req(
                uid, algorithm="SPADE_TPU", sequences=format_spmf(db),
                support="0.1"))
        assert master_a.miner.queue_size() == 4
        report = {}
        th = threading.Thread(
            target=lambda: report.update(
                master_a.miner.drain(timeout_s=DRILL_TIMEOUT_S,
                                     reason="drill")))
        th.start()
        # B's heartbeat ticks: sees draining A with 4 queued, steals
        # one per tick as its single worker frees up
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline and master_a.miner.queue_size():
            mgr_b.tick()
            time.sleep(0.05)
        assert master_a.miner.queue_size() == 0, "B never emptied A"
        gate.set()  # the running job finishes on A
        th.join(DRILL_TIMEOUT_S)
        assert not th.is_alive(), "drain never returned"
        assert report["outcome"] == "clean", report
        assert report["stolen_by_peers"] == 4, report
        assert report["left_for_recovery"] == 0
        # zero lost: every job terminal-finished; stolen ones with
        # byte-exact oracle parity (zero duplicated results)
        for uid in uids + ["hold"]:
            assert _await_terminal(store, uid) == "finished"
        for uid in uids:
            got = deserialize_patterns(store.patterns(uid))
            assert patterns_text(got) == patterns_text(want)
        # the victim-side drop accounting moved through the drain reap
        drops = obs.REGISTRY.snapshot()["fsm_steal_victim_drops_total"]
        assert drops >= drops0 + 4
        # A sheds new submits while drained, pointing at the peers
        from spark_fsm_tpu.service.actors import AdmissionShed

        with pytest.raises(AdmissionShed, match="draining"):
            master_a.miner.submit(_req("late"))
        assert store.status("late") is None
        # bookkeeping: journals/markers/leases all settled
        assert store.journal_uids() == []
        assert store.keys("fsm:admission:") == []
    finally:
        gate.set()
        master_b.shutdown()
        master_a.shutdown()


def test_thief_death_mid_drain_heals_via_periodic_recovery():
    """A thief that claims a draining replica's marker and dies before
    resubmitting leaves a journal orphan under its own (now orphaned)
    lease; the drain times out, leaves the job adoptable, and the
    survivor's periodic recovery adopts + resumes it exactly once."""
    t = [0.0]
    store = ResultStore(clock=lambda: t[0])
    mk = lambda rid: LeaseManager(store, replica_id=rid,
                                  lease_ttl_s=30.0, heartbeat_s=0,
                                  clock=lambda: t[0])
    mgr_a, mgr_b = mk("rep-a"), mk("rep-b")
    # A has ZERO workers: its queued job can never start locally, so
    # the drill is deterministic without gating
    master_a = Master(store=store, miner_workers=0, lease_mgr=mgr_a)
    master_b = Master(store=store, miner_workers=1, lease_mgr=mgr_b)
    db = synthetic_db(seed=62, n_sequences=80, n_items=10,
                      mean_itemsets=3.0, mean_itemset_size=1.3)
    want = mine_spade(db, abs_minsup(0.1, len(db)))
    try:
        master_a.miner.submit(_req(
            "orphan", algorithm="SPADE_TPU", sequences=format_spmf(db),
            support="0.1", checkpoint="1", checkpoint_every_s="0"))
        # burn B's recovery cadence at t=0 so the NEXT fire needs the
        # clock advance below (deterministic ordering)
        mgr_b.tick()
        # --- the thief's partial claim, verbatim protocol steps, then
        # death: marker DEL'd, lease overwritten with a larger token,
        # journal NOT rewritten, no resubmit
        assert store.delete("fsm:admission:rep-a:orphan") == 1
        tok = int(store.incr("fsm:lease:token"))
        store.set_px("fsm:lease:orphan",
                     json.dumps({"replica": "rep-c", "token": tok}),
                     30_000)
        # from A's viewpoint the claim IS a steal (a claimed marker is
        # indistinguishable from a live thief), so the drain reaps the
        # entry and reports clean — the heal still happens below, via
        # recovery, exactly because the journal was never settled
        report = master_a.miner.drain(timeout_s=0.5, reason="drill")
        assert report["outcome"] == "clean"
        assert report["stolen_by_peers"] == 1
        assert report["left_for_recovery"] == 0
        assert store.journal_get("orphan") is not None
        assert store.status("orphan") == "started"  # not settled
        # dead thief's lease expires; B's periodic recovery adopts
        t[0] = 40.0
        mgr_b.tick()
        assert _await_terminal(store, "orphan") == "finished"
        got = deserialize_patterns(store.patterns("orphan"))
        assert patterns_text(got) == patterns_text(want)
        assert store.journal_uids() == []
        snap = obs.REGISTRY.snapshot()["fsm_recovery_jobs_total"]
        assert snap.get("outcome=resumed", 0) >= 1
    finally:
        master_b.shutdown()
        master_a.shutdown()


def test_drain_solo_settles_leftovers_durably():
    """Without a cluster nobody can adopt: a solo drain's leftovers
    get a durable failure (keep_frontier) instead of a stuck uid."""
    store = ResultStore()
    master = Master(store=store, miner_workers=0)
    try:
        master.miner.submit(_req("left0"))
        report = master.miner.drain(timeout_s=0.3, reason="drill")
        assert report["outcome"] == "timeout"
        assert store.status("left0") == "failure"
        assert "draining" in store.get("fsm:error:left0")
        assert store.journal_get("left0") is None
    finally:
        master.shutdown()
