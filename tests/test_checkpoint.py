"""Failure recovery: frontier checkpoint/resume + miner job retry.

SURVEY.md sec 5 failure-detection and checkpoint rows: the primary
contract stays results-persisted-at-job-end; these tests cover the
optional extras — a crashed long mine resuming from its persisted DFS
frontier, and the Miner re-running failed jobs like Spark re-executes
tasks.
"""

import json
import time

import pytest

from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.models.spade_tpu import SpadeTPU, mine_spade_tpu
from spark_fsm_tpu.service import plugins
from spark_fsm_tpu.service.actors import Master, StoreCheckpoint
from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.utils import envelope
from spark_fsm_tpu.utils.canonical import diff_patterns, patterns_text


def _db():
    return synthetic_db(seed=31, n_sequences=240, n_items=13,
                        mean_itemsets=4.0, mean_itemset_size=1.4)


def test_crash_resume_parity():
    """Kill a mine mid-DFS; a fresh engine resuming the last checkpoint
    must produce the exact full pattern set."""
    db = _db()
    minsup = abs_minsup(0.05, len(db))
    vdb = build_vertical(db, min_item_support=minsup)

    class Crash(Exception):
        pass

    saved = []
    merged = []  # checkpoints carry result DELTAS; a sink appends them

    def cb(state):
        assert state["results_done"] == len(merged)
        merged.extend(state["results"])
        saved.append(state)
        if len(saved) == 2:
            raise Crash  # simulated mid-mine death, after persisting

    eng = SpadeTPU(vdb, minsup, node_batch=4, pipeline_depth=2,
                   pool_bytes=32 << 20)
    with pytest.raises(Crash):
        eng.mine(checkpoint_cb=cb, checkpoint_every_s=0.0)
    assert len(saved) == 2
    # reconstruct the resume dict the way StoreCheckpoint.load does
    state = json.loads(json.dumps(
        {**saved[-1], "results": list(merged)}))
    assert state["stack"], "crash happened after the frontier emptied"

    eng2 = SpadeTPU(build_vertical(db, min_item_support=minsup), minsup,
                    node_batch=16, pool_bytes=32 << 20)
    got = eng2.mine(resume=state)
    assert eng2.stats["resumed_nodes"] == len(state["stack"])
    want = mine_spade(db, minsup)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


def test_resume_rejects_mismatched_fingerprint():
    db = _db()
    minsup = abs_minsup(0.05, len(db))
    eng = SpadeTPU(build_vertical(db, min_item_support=minsup), minsup)
    state = eng.frontier_state([], [])
    other = SpadeTPU(build_vertical(db, min_item_support=minsup + 3),
                     minsup + 3)
    with pytest.raises(ValueError, match="fingerprint|does not match"):
        other.mine(resume=state)
    # a changed length constraint changes the enumeration: also refused
    constrained = SpadeTPU(build_vertical(db, min_item_support=minsup),
                           minsup, max_pattern_itemsets=2)
    with pytest.raises(ValueError, match="fingerprint|does not match"):
        constrained.mine(resume=state)


def test_wrapper_ignores_stale_checkpoint():
    """mine_spade_tpu silently restarts fresh when the stored frontier was
    written against different data (e.g. a TRACKED source that grew)."""
    db = _db()
    minsup = abs_minsup(0.05, len(db))

    class FakeCkpt:
        every_s = 30.0

        def __init__(self, state):
            self.state = state

        def load(self):
            return self.state

        def save(self, state):
            self.state = state

    stale = SpadeTPU(build_vertical(db, min_item_support=minsup + 5),
                     minsup + 5).frontier_state([], [])
    got = mine_spade_tpu(db, minsup, checkpoint=FakeCkpt(stale))
    want = mine_spade(db, minsup)
    assert patterns_text(got) == patterns_text(want)


def test_store_checkpoint_rewrite_saves_are_atomic():
    """Full-rewrite saves (results_done=0 every time — TSR) must be one
    atomic meta SET: a delete-then-rewrite list would let a kill pair an
    old meta with a newer list of the SAME length (top-k rewrites
    routinely match lengths), which the count check cannot catch."""
    store = ResultStore()
    ckpt = StoreCheckpoint(store, "job2")
    ckpt.save({"version": 1, "stack": [[5, [0], [1], True]],
               "results_done": 0, "results": [[[0], [1], 5, 9]]})
    ckpt.save({"version": 1, "stack": [[4, [0], [2], True]],
               "results_done": 0, "results": [[[0], [2], 6, 9]]})
    assert store.lrange("fsm:frontier:results:job2") == []  # never listed
    state = ckpt.load()
    assert state["results"] == [[[0], [2], 6, 9]]  # exactly the last save
    assert state["stack"] == [[4, [0], [2], True]]

    # a NEW instance resuming this snapshot must carry the inline part
    # into its own append-mode saves (its meta overwrites the carrier)
    ckpt2 = StoreCheckpoint(store, "job2")
    assert ckpt2.load()["results"] == [[[0], [2], 6, 9]]
    ckpt2.save({"version": 1, "stack": [], "results_done": 1,
                "results": [[[9], [8], 2, 2]]})
    assert ckpt2.load()["results"] == [[[0], [2], 6, 9], [[9], [8], 2, 2]]


def test_store_checkpoint_roundtrip_and_job_clear():
    store = ResultStore()
    ckpt = StoreCheckpoint(store, "job1", every_s=5.0)
    assert ckpt.load() is None
    # two delta saves merge back into one results list on load
    ckpt.save({"version": 1, "stack": [{"steps": [[0, 1]], "s": [], "i": []}],
               "results_done": 0, "results": [[[[1]], 3]]})
    ckpt.save({"version": 1, "stack": [],
               "results_done": 1, "results": [[[[1], [2]], 2]]})
    state = ckpt.load()
    assert state["results"] == [[[[1]], 3], [[[1], [2]], 2]]
    assert state["stack"] == []
    # a trailing chunk the meta never saw (a save killed between its
    # delta rpush and its meta set) HEALS: load returns the meta's own
    # — last good — snapshot and trims the orphan tail from the store
    store.rpush("fsm:frontier:results:job1", json.dumps([[[[9]], 1]]))
    healed = ckpt.load()
    assert healed["results"] == [[[[1]], 3], [[[1], [2]], 2]]  # no [[9]]
    assert store.llen("fsm:frontier:results:job1") == 1  # tail trimmed
    # a list that cannot be reconciled at a chunk boundary is torn
    # beyond repair and refused outright
    store.rpush("fsm:frontier:results:job1",
                json.dumps([[[[8]], 1], [[[7]], 1]]))
    meta = json.loads(envelope.unwrap(store.get("fsm:frontier:job1"))[0])
    meta["results_total"] = 3  # mid-chunk divergence: 2 then 4, never 3
    store.set("fsm:frontier:job1", envelope.wrap(json.dumps(meta)))
    assert ckpt.load() is None
    ckpt.save({"version": 1, "stack": [], "results_done": 0, "results": []})
    assert ckpt.load()["results"] == []
    store.clear_job("job1")  # new job with the same uid drops the frontier
    assert ckpt.load() is None


@pytest.fixture()
def flaky_plugin():
    calls = {"n": 0}

    def extract(req, db, stats=None, checkpoint=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient device wobble")
        return plugins._spade_cpu(req, db, stats)

    plugins.ALGORITHMS["FLAKY"] = plugins.AlgorithmPlugin(
        "FLAKY", "patterns", extract)
    yield calls
    del plugins.ALGORITHMS["FLAKY"]


def _wait(store, uid, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if store.status(uid) in ("finished", "failure"):
            return store.status(uid)
        time.sleep(0.02)
    raise TimeoutError


def test_miner_retries_transient_failure(flaky_plugin):
    store = ResultStore()
    master = Master(store=store)
    try:
        resp = master.handle(ServiceRequest("fsm", "train", {
            "algorithm": "FLAKY", "source": "INLINE",
            "sequences": "1 -1 2 -2\n1 -1 2 -2\n", "support": "0.5",
            "retries": "1"}))
        uid = resp.data["uid"]
        assert _wait(store, uid) == "finished"
        assert flaky_plugin["n"] == 2  # failed once, retried, succeeded
        assert int(store.get("fsm:metric:jobs_retried") or 0) == 1
    finally:
        master.shutdown()


def test_miner_no_retry_when_disabled(flaky_plugin):
    store = ResultStore()
    master = Master(store=store)
    try:
        resp = master.handle(ServiceRequest("fsm", "train", {
            "algorithm": "FLAKY", "source": "INLINE",
            "sequences": "1 -1 2 -2\n", "support": "0.5", "retries": "0"}))
        uid = resp.data["uid"]
        assert _wait(store, uid) == "failure"
        assert flaky_plugin["n"] == 1
        assert "wobble" in (store.get(f"fsm:error:{uid}") or "")
    finally:
        master.shutdown()


def test_validation_error_not_retried():
    """Deterministic failures (bad params/source) skip the retry loop."""
    calls = {"n": 0}

    def extract(req, db, stats=None, checkpoint=None):
        calls["n"] += 1
        raise ValueError("support parameter is garbage")

    plugins.ALGORITHMS["BROKEN"] = plugins.AlgorithmPlugin(
        "BROKEN", "patterns", extract)
    store = ResultStore()
    master = Master(store=store)
    try:
        resp = master.handle(ServiceRequest("fsm", "train", {
            "algorithm": "BROKEN", "source": "INLINE",
            "sequences": "1 -2\n", "support": "0.5", "retries": "3"}))
        uid = resp.data["uid"]
        assert _wait(store, uid) == "failure"
        assert calls["n"] == 1  # no re-runs despite retries=3
        assert store.get("fsm:metric:jobs_retried") is None
    finally:
        del plugins.ALGORITHMS["BROKEN"]
        master.shutdown()


def test_service_checkpoint_plumbing():
    """A SPADE_TPU train job with checkpoint=1 writes frontier snapshots
    during the mine and clears them once results are durable."""
    store = ResultStore()
    master = Master(store=store)
    seen = {"frontier": False}
    orig_set = store.set

    def spy_set(key, value):
        if key.startswith("fsm:frontier:"):
            seen["frontier"] = True
        orig_set(key, value)

    store.set = spy_set
    try:
        db_lines = "\n".join(
            " -1 ".join(str(i) for i in seq_parts) + " -2"
            for seq_parts in [(1, 2, 3), (1, 2), (2, 3), (1, 3), (3, 2)]
            for _ in range(4))
        resp = master.handle(ServiceRequest("fsm", "train", {
            "algorithm": "SPADE_TPU", "source": "INLINE",
            "sequences": db_lines, "support": "0.2",
            "checkpoint": "1", "checkpoint_every_s": "0"}))
        uid = resp.data["uid"]
        assert _wait(store, uid) == "finished"
        assert seen["frontier"], "no frontier snapshot was ever written"
        assert store.get(f"fsm:frontier:{uid}") is None  # cleared at end
        assert store.patterns(uid) is not None
        # the checkpointed job kept the default (queue) engine — the
        # fused_skipped="checkpoint" degradation is gone (VERDICT r4 #3)
        stats = json.loads(store.get(f"fsm:stats:{uid}") or "{}")
        assert stats.get("fused") == "queue"
        assert "fused_skipped" not in stats
    finally:
        master.shutdown()


def test_constrained_crash_resume_parity():
    """Same crash/resume contract for the maxgap/maxwindow engine."""
    from spark_fsm_tpu.models.oracle import mine_cspade
    from spark_fsm_tpu.models.spade_constrained import ConstrainedSpadeTPU

    db = _db()
    minsup = abs_minsup(0.05, len(db))
    vdb = build_vertical(db, min_item_support=minsup)

    class Crash(Exception):
        pass

    saved, merged = [], []

    def cb(state):
        assert state["results_done"] == len(merged)
        merged.extend(state["results"])
        saved.append(state)
        if len(saved) == 2:
            raise Crash

    eng = ConstrainedSpadeTPU(vdb, minsup, maxgap=2, maxwindow=6,
                              node_batch=4, pipeline_depth=2,
                              pool_bytes=32 << 20)
    with pytest.raises(Crash):
        eng.mine(checkpoint_cb=cb, checkpoint_every_s=0.0)
    state = json.loads(json.dumps({**saved[-1], "results": list(merged)}))
    assert state["stack"], "crash happened after the frontier emptied"

    eng2 = ConstrainedSpadeTPU(build_vertical(db, min_item_support=minsup),
                               minsup, maxgap=2, maxwindow=6, node_batch=16,
                               pool_bytes=32 << 20)
    got = eng2.mine(resume=state)
    assert eng2.stats["resumed_nodes"] == len(state["stack"])
    want = mine_cspade(db, minsup, maxgap=2, maxwindow=6)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


def test_constrained_resume_rejects_changed_constraints():
    from spark_fsm_tpu.models.spade_constrained import ConstrainedSpadeTPU

    db = _db()
    minsup = abs_minsup(0.05, len(db))
    eng = ConstrainedSpadeTPU(build_vertical(db, min_item_support=minsup),
                              minsup, maxgap=2)
    state = eng.frontier_state([], [])
    other = ConstrainedSpadeTPU(build_vertical(db, min_item_support=minsup),
                                minsup, maxgap=3)
    with pytest.raises(ValueError, match="fingerprint|does not match"):
        other.mine(resume=state)


def test_tsr_crash_resume_parity():
    """Kill a TSR mine mid-round; a fresh engine resuming the last
    checkpoint must produce the exact top-k rule set.  TSR snapshots are
    FULL (results_done always 0): the accepted-rule set shrinks when the
    internal minsup rises, so deltas cannot represent it."""
    from spark_fsm_tpu.models.tsr import TsrTPU
    from spark_fsm_tpu.utils.canonical import rules_text

    db = _db()
    vdb = build_vertical(db, min_item_support=1)

    class Crash(Exception):
        pass

    saved = []

    def cb(state):
        assert state["results_done"] == 0
        saved.append(state)
        if len(saved) == 2:
            raise Crash

    # tiny pinned chunk -> many batches -> the every_s=0 callback fires
    # between them, well before the round's frontier drains
    eng = TsrTPU(vdb, k=10, minconf=0.4, max_side=2, chunk=16)
    with pytest.raises(Crash):
        eng.mine(checkpoint_cb=cb, checkpoint_every_s=0.0)
    assert len(saved) == 2
    state = json.loads(json.dumps(saved[-1]))
    assert state["stack"], "crash happened after the frontier emptied"

    eng2 = TsrTPU(build_vertical(db, min_item_support=1),
                  k=10, minconf=0.4, max_side=2)
    got = eng2.mine(resume=state)
    assert eng2.stats["resumed_nodes"] == len(state["stack"])
    want = TsrTPU(build_vertical(db, min_item_support=1),
                  k=10, minconf=0.4, max_side=2).mine()
    assert rules_text(got) == rules_text(want)


def test_tsr_resume_rejects_mismatched_fingerprint():
    from spark_fsm_tpu.models.tsr import TsrTPU

    db = _db()
    vdb = build_vertical(db, min_item_support=1)
    state = TsrTPU(vdb, k=10, minconf=0.5,
                   max_side=2).frontier_state([], [], m=4, minsup=1)
    for other in (TsrTPU(vdb, k=11, minconf=0.5, max_side=2),
                  TsrTPU(vdb, k=10, minconf=0.6, max_side=2),
                  TsrTPU(vdb, k=10, minconf=0.5, max_side=3)):
        with pytest.raises(ValueError, match="fingerprint|does not match"):
            other.mine(resume=state)


def test_tsr_service_checkpoint_plumbing():
    """A TSR_TPU train job with checkpoint=1 writes frontier snapshots and
    clears them once the rules are durable (checkpoint support is no
    longer SPADE-only)."""
    store = ResultStore()
    master = Master(store=store)
    seen = {"frontier": False}
    orig_set = store.set

    def spy_set(key, value):
        if key.startswith("fsm:frontier:"):
            seen["frontier"] = True
        orig_set(key, value)

    store.set = spy_set
    try:
        db_lines = "\n".join(
            " -1 ".join(str(i) for i in seq_parts) + " -2"
            for seq_parts in [(1, 2, 3), (1, 2), (2, 3), (1, 3), (3, 2)]
            for _ in range(4))
        resp = master.handle(ServiceRequest("fsm", "train", {
            "algorithm": "TSR_TPU", "source": "INLINE",
            "sequences": db_lines, "k": "5", "minconf": "0.3",
            "max_side": "2", "checkpoint": "1", "checkpoint_every_s": "0"}))
        uid = resp.data["uid"]
        assert _wait(store, uid) == "finished"
        assert seen["frontier"], "no frontier snapshot was ever written"
        assert store.get(f"fsm:frontier:{uid}") is None  # cleared at end
        assert store.rules(uid) is not None
        stats = json.loads(store.get(f"fsm:stats:{uid}") or "{}")
        assert "checkpoint_unsupported" not in stats
    finally:
        master.shutdown()


def _queue_caps():
    # small waves so the geometric segment schedule yields several
    # boundaries on this 240-sequence db (default nb=512 would finish
    # the whole mine in ~2 waves)
    from spark_fsm_tpu.models.spade_queue import QueueCaps
    return QueueCaps(nb=32, ring=2048, c_cap=512, m_cap=512)


def test_queue_crash_resume_parity():
    """Kill a checkpointed QUEUE mine mid-run; a fresh queue engine
    resuming the last snapshot must produce the exact full pattern set
    (VERDICT r4 #3: the default engine is resumable — no more
    fused_skipped="checkpoint" degradation)."""
    from spark_fsm_tpu.models.spade_queue import QueueSpadeTPU

    db = _db()
    minsup = abs_minsup(0.05, len(db))

    class Crash(Exception):
        pass

    saved, merged = [], []

    def cb(state):
        assert state["results_done"] == len(merged)
        merged.extend(state["results"])
        saved.append(state)
        if len(saved) == 2:
            raise Crash

    eng = QueueSpadeTPU(build_vertical(db, min_item_support=minsup),
                        minsup, caps=_queue_caps())
    with pytest.raises(Crash):
        eng.mine(checkpoint_cb=cb, checkpoint_every_s=0.0, seg_waves=1)
    state = json.loads(json.dumps({**saved[-1], "results": list(merged)}))
    assert state["stack"], "crash happened after the frontier emptied"

    eng2 = QueueSpadeTPU(build_vertical(db, min_item_support=minsup),
                         minsup, caps=_queue_caps())
    got = eng2.mine(resume=state)
    assert eng2.stats["resumed_nodes"] == len(state["stack"])
    want = mine_spade(db, minsup)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


def test_queue_classic_snapshots_interchange():
    """The queue engine writes snapshots in the classic engine's format
    with the same fingerprint, so each engine resumes the other's — the
    contract that lets a mid-mine cap overflow fall from queue to classic
    WITHOUT restarting the mine."""
    from spark_fsm_tpu.models.spade_queue import QueueSpadeTPU

    db = _db()
    minsup = abs_minsup(0.05, len(db))
    want = mine_spade(db, minsup)

    class Crash(Exception):
        pass

    # queue snapshot -> classic resume
    saved, merged = [], []

    def cb(state):
        merged.extend(state["results"])
        saved.append(state)
        if len(saved) == 2:
            raise Crash

    qeng = QueueSpadeTPU(build_vertical(db, min_item_support=minsup),
                         minsup, caps=_queue_caps())
    assert (qeng.frontier_fingerprint()
            == SpadeTPU(build_vertical(db, min_item_support=minsup),
                        minsup).frontier_fingerprint())
    with pytest.raises(Crash):
        qeng.mine(checkpoint_cb=cb, checkpoint_every_s=0.0, seg_waves=1)
    state = json.loads(json.dumps({**saved[-1], "results": list(merged)}))
    assert state["stack"]
    ceng = SpadeTPU(build_vertical(db, min_item_support=minsup), minsup,
                    pool_bytes=32 << 20)
    got = ceng.mine(resume=state)
    assert patterns_text(got) == patterns_text(want)

    # classic snapshot -> queue resume
    saved2, merged2 = [], []

    def cb2(state):
        merged2.extend(state["results"])
        saved2.append(state)
        if len(saved2) == 2:
            raise Crash

    ceng2 = SpadeTPU(build_vertical(db, min_item_support=minsup), minsup,
                     node_batch=4, pipeline_depth=2, pool_bytes=32 << 20)
    with pytest.raises(Crash):
        ceng2.mine(checkpoint_cb=cb2, checkpoint_every_s=0.0)
    state2 = json.loads(json.dumps({**saved2[-1], "results": list(merged2)}))
    assert state2["stack"]
    qeng2 = QueueSpadeTPU(build_vertical(db, min_item_support=minsup),
                          minsup, caps=_queue_caps())
    got2 = qeng2.mine(resume=state2)
    assert qeng2.stats["resumed_nodes"] == len(state2["stack"])
    assert patterns_text(got2) == patterns_text(want)


def test_checkpointed_wrapper_routes_queue():
    """mine_spade_tpu with a checkpoint keeps the queue route (stats
    prove it) instead of degrading to the classic engine."""
    db = _db()
    minsup = abs_minsup(0.05, len(db))

    class Ckpt:
        every_s = 0.0

        def __init__(self):
            self.saves = []

        def load(self):
            return None

        def save(self, state):
            self.saves.append(state)

    ck = Ckpt()
    stats = {}
    got = mine_spade_tpu(db, minsup, checkpoint=ck, stats_out=stats)
    assert stats.get("fused") == "queue"
    assert "fused_skipped" not in stats
    assert ck.saves, "no snapshot written despite every_s=0"
    want = mine_spade(db, minsup)
    assert patterns_text(got) == patterns_text(want)


def test_save_is_non_destructive_under_store_failure():
    """Regression (ISSUE 3 satellite): save used to pop results/
    results_done from the CALLER's dict, so a store failure mid-save
    mutilated the engine's state and a retried save wrote a wrong
    results_total.  Now save works on a shallow copy: after an injected
    store.set failure exhausts the retry budget, the caller's dict is
    untouched and the re-issued save persists the exact snapshot."""
    from spark_fsm_tpu.utils import faults
    from spark_fsm_tpu.utils.retry import RetryPolicy

    store = ResultStore()
    ckpt = StoreCheckpoint(store, "nd", retry=RetryPolicy(retries=0))
    state = {"version": 1, "stack": [{"steps": [[0, 1]], "s": [], "i": []}],
             "results_done": 0, "results": [[[[1]], 3], [[[2]], 2]]}
    snapshot = json.loads(json.dumps(state))
    with faults.injected("store.set", every=1, match="fsm:frontier:nd"):
        with pytest.raises(faults.FaultInjected):
            ckpt.save(state)
    assert state == snapshot, "save mutilated the caller's state dict"
    ckpt.save(state)  # the retried save (fault gone) writes it all
    assert state == snapshot
    loaded = ckpt.load()
    assert loaded["results"] == snapshot["results"]
    assert loaded["stack"] == snapshot["stack"]
    # and a follow-up DELTA save composes on top of the retried one
    state2 = {"version": 1, "stack": [], "results_done": 2,
              "results": [[[[3]], 1]]}
    ckpt.save(state2)
    assert ckpt.load()["results"] == snapshot["results"] + [[[[3]], 1]]


def test_kill_between_rpush_and_meta_set_resumes_previous_snapshot():
    """Crash-timing on the checkpoint path (ISSUE 3 satellite): a kill
    AFTER the delta rpush but BEFORE the meta set leaves an orphan chunk
    the meta never saw.  load() must refuse that torn snapshot — it
    serves the PREVIOUS good one (the meta's own), trimming the orphan —
    and a checkpointed retry resumes from it with no duplicated rules."""
    from spark_fsm_tpu.utils import faults
    from spark_fsm_tpu.utils.retry import RetryPolicy

    store = ResultStore()
    ckpt = StoreCheckpoint(store, "kill", retry=RetryPolicy(retries=0))
    ckpt.save({"version": 1,
               "stack": [{"steps": [[0, 1]], "s": [0], "i": []}],
               "results_done": 0, "results": [[[[1]], 3]]})
    good = ckpt.load()
    # the kill: rpush lands (no retry budget, meta set always fails)
    with faults.injected("store.set", every=1, match="fsm:frontier:kill"):
        with pytest.raises(faults.FaultInjected):
            ckpt.save({"version": 1, "stack": [], "results_done": 1,
                       "results": [[[[2]], 2]]})
    assert store.llen("fsm:frontier:results:kill") == 1  # orphan chunk
    fresh = StoreCheckpoint(store, "kill")
    state = fresh.load()
    assert state is not None, "previous good snapshot must still resume"
    assert state["results"] == good["results"]  # NOT the torn delta
    assert state["stack"] == good["stack"]
    assert store.llen("fsm:frontier:results:kill") == 0  # healed
    # the retried save now lands cleanly on the healed store: exactly
    # one copy of the delta — no duplicated results on the next resume
    fresh.save({"version": 1, "stack": [], "results_done": 1,
                "results": [[[[2]], 2]]})
    assert fresh.load()["results"] == [[[[1]], 3], [[[2]], 2]]


def test_mine_killed_mid_save_resumes_with_full_parity():
    """End-to-end crash timing: a SPADE mine whose SECOND checkpoint
    save is killed between the delta write and the meta write must
    resume from the FIRST snapshot and still produce the exact pattern
    set (no lost, no duplicated patterns)."""
    from spark_fsm_tpu.utils import faults
    from spark_fsm_tpu.utils.retry import RetryPolicy

    db = _db()
    minsup = abs_minsup(0.05, len(db))
    store = ResultStore()
    ckpt = StoreCheckpoint(store, "mkill", retry=RetryPolicy(retries=0))
    eng = SpadeTPU(build_vertical(db, min_item_support=minsup), minsup,
                   node_batch=4, pipeline_depth=2, pool_bytes=32 << 20)
    # fire on the SECOND frontier meta write: save 1 completes, save 2
    # has rpushed its delta when the meta set "kills the process"
    with faults.injected("store.set", nth=2, match="fsm:frontier:mkill"):
        with pytest.raises(faults.FaultInjected):
            eng.mine(checkpoint_cb=ckpt.save, checkpoint_every_s=0.0)
    state = StoreCheckpoint(store, "mkill").load()
    assert state is not None and state["stack"], (
        "previous good snapshot must resume")
    eng2 = SpadeTPU(build_vertical(db, min_item_support=minsup), minsup,
                    node_batch=16, pool_bytes=32 << 20)
    got = eng2.mine(resume=state)
    want = mine_spade(db, minsup)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


def test_checkpointed_queue_overflow_resumes_in_classic(monkeypatch):
    """A queue-engine cap overflow MID-checkpointed-mine must fall back
    to the classic engine AND resume from the queue engine's last
    snapshot (shared frontier format + fingerprint), not restart."""
    from spark_fsm_tpu.models import spade_queue

    # caps sized so wave 1 fits (snapshot lands at its boundary) and the
    # record buffer overflows on a later wave
    monkeypatch.setattr(
        spade_queue.QueueCaps, "for_budget",
        classmethod(lambda cls, *a, **k: spade_queue.QueueCaps(
            nb=16, ring=2048, c_cap=512, m_cap=512, r_cap=96)))
    db = _db()
    minsup = abs_minsup(0.05, len(db))
    store = ResultStore()
    ckpt = StoreCheckpoint(store, "qovf", every_s=0.0)
    stats: dict = {}
    got = mine_spade_tpu(db, minsup, checkpoint=ckpt, stats_out=stats)
    want = mine_spade(db, minsup)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)
    assert stats.get("fused_overflow") is True, stats
    # the classic fallback RESUMED the queue engine's snapshot: its
    # stack was non-empty, not a fresh root frontier restart
    assert stats.get("resumed_nodes", 0) > 0, stats
