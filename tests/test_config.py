"""Boot config (SURVEY.md sec 5 config row) + observability tests.

The reference boots from application.conf (Typesafe Config); the rebuild
boots from TOML/JSON.  Also covers the metrics surface the reference lacks
but SURVEY.md sec 5 requires: engine stats in /status, /admin/stats
counters, and jax.profiler trace capture around a mine.
"""

import json
import time
import urllib.parse
import urllib.request

import pytest

from spark_fsm_tpu import config as cfgmod
from spark_fsm_tpu.config import Config, ConfigError, load_config, parse_config
from spark_fsm_tpu.data.spmf import format_spmf
from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.service.app import serve_background, service_stats


# ---------------------------------------------------------------- parsing

def test_defaults():
    cfg = Config()
    assert cfg.service.port == 9000
    assert cfg.store.backend == "inproc"
    assert cfg.engine.mesh_devices == 0
    assert cfg.engine.pool_bytes is None
    assert cfg.profile_dir == ""


def test_load_toml(tmp_path):
    p = tmp_path / "fsm.toml"
    p.write_text(
        'profile_dir = "traces"\n'
        "[service]\nhost = \"0.0.0.0\"\nport = 9100\nminer_workers = 2\n"
        "[store]\nbackend = \"redis\"\nport = 6380\n"
        "[engine]\nmesh_devices = 8\npool_bytes = 1073741824\nnode_batch = 64\n"
    )
    cfg = load_config(str(p))
    assert cfg.service.host == "0.0.0.0"
    assert cfg.service.port == 9100
    assert cfg.service.miner_workers == 2
    assert cfg.store.backend == "redis"
    assert cfg.store.port == 6380
    assert cfg.engine.mesh_devices == 8
    assert cfg.engine.pool_bytes == 1 << 30
    assert cfg.engine.node_batch == 64
    assert cfg.profile_dir == "traces"


def test_load_json(tmp_path):
    p = tmp_path / "fsm.json"
    p.write_text(json.dumps({"service": {"port": 9200},
                             "engine": {"chunk": 128}}))
    cfg = load_config(str(p))
    assert cfg.service.port == 9200
    assert cfg.engine.chunk == 128


def test_unknown_keys_rejected():
    with pytest.raises(ConfigError, match="unknown key"):
        parse_config({"service": {"prot": 9000}})
    with pytest.raises(ConfigError, match="unknown top-level"):
        parse_config({"sevice": {"port": 9000}})
    with pytest.raises(ConfigError, match="backend"):
        parse_config({"store": {"backend": "memcached"}})
    # scalar where a table is required: clear error, not character soup
    with pytest.raises(ConfigError, match="must be a table"):
        parse_config({"service": "ab"})
    with pytest.raises(ConfigError, match="must be a table"):
        parse_config({"engine": 5})


def test_engine_kwargs_and_mesh():
    try:
        cfgmod.set_config(parse_config(
            {"engine": {"pool_bytes": 123, "mesh_devices": 8}}))
        assert cfgmod.engine_kwargs("pool_bytes", "node_batch") == {
            "pool_bytes": 123}
        mesh = cfgmod.get_mesh()
        assert mesh is not None and mesh.devices.size == 8
        assert cfgmod.get_mesh() is mesh  # cached
    finally:
        cfgmod.set_config(Config())
    assert cfgmod.get_mesh() is None


# ---------------------------------------------------------- observability

@pytest.fixture(scope="module")
def server():
    srv = serve_background()
    yield srv
    srv.master.shutdown()
    srv.shutdown()


def _post(server, endpoint, **params):
    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{server.server_port}{endpoint}"
    with urllib.request.urlopen(url, data=data, timeout=30) as resp:
        return json.loads(resp.read().decode())


def _await(server, uid, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        resp = _post(server, f"/status/{uid}")
        if resp["status"] in ("finished", "failure"):
            return resp
        time.sleep(0.05)
    raise AssertionError("timeout")


def _train(server, **extra):
    db = synthetic_db(seed=11, n_sequences=120, n_items=10, mean_itemsets=4.0)
    resp = _post(server, "/train", algorithm="SPADE_TPU", source="INLINE",
                 sequences=format_spmf(db), support="0.05", **extra)
    assert resp["status"] == "started"
    return resp["data"]["uid"]


def test_status_carries_engine_stats(server):
    uid = _train(server)
    resp = _await(server, uid)
    assert resp["status"] == "finished"
    stats = json.loads(resp["data"]["stats"])
    assert stats["algorithm"] == "SPADE_TPU"
    assert stats["sequences"] == 120
    assert stats["results"] == stats["patterns"] > 0
    assert stats["kernel_launches"] > 0
    assert stats["mine_s"] >= 0
    assert stats["results_per_s"] > 0


def test_admin_stats_counters(server):
    before = _post(server, "/admin/stats")
    uid = _train(server)
    assert _await(server, uid)["status"] == "finished"
    after = _post(server, "/admin/stats")
    assert after["jobs"]["jobs_submitted"] >= before["jobs"]["jobs_submitted"] + 1
    assert after["jobs"]["jobs_finished"] >= before["jobs"]["jobs_finished"] + 1
    assert after["backend"] == "cpu"  # conftest forces CPU in tests
    assert after["devices"] == 8
    assert "SPADE_TPU" in after["algorithms"]
    # direct call mirrors the endpoint
    assert service_stats(server.master)["jobs"] == after["jobs"]


def test_admin_config_roundtrip(server):
    cfg = _post(server, "/admin/config")
    assert cfg["service"]["port"] == 9000  # default config active
    assert cfg["store"]["backend"] == "inproc"


def test_failed_job_counted(server):
    resp = _post(server, "/train", algorithm="SPADE_TPU", source="FILE",
                 path="/nonexistent/file.spmf", support="0.05")
    uid = resp["data"]["uid"]
    resp = _await(server, uid)
    assert resp["status"] == "failure"
    after = _post(server, "/admin/stats")
    assert after["jobs"]["jobs_failed"] >= 1


def test_profile_trace_captured(server, tmp_path):
    trace_dir = tmp_path / "trace"
    uid = _train(server, profile=str(trace_dir))
    resp = _await(server, uid)
    assert resp["status"] == "finished"
    stats = json.loads(resp["data"]["stats"])
    assert stats["profile_trace"] == str(trace_dir)
    # jax.profiler writes a plugins/ or *.pb trace tree under the dir
    assert trace_dir.exists() and any(trace_dir.rglob("*"))


def test_profile_flag_without_config_dir_fails(server):
    uid = _train(server, profile="1")
    resp = _await(server, uid)
    assert resp["status"] == "failure"
    assert "profile_dir" in resp["data"]["error"]


def test_profile_false_spellings_disable(server):
    # JSON bodies coerce false -> "False"; none of these may trigger
    # profiling (which would fail here: no profile_dir configured)
    for value in ("False", "0", "off", "NO", ""):
        uid = _train(server, profile=value)
        assert _await(server, uid)["status"] == "finished", value


def test_stream_failure_counter_separate(server):
    # a bad first push fails config validation -> stream_failures, and
    # jobs_failed (batch jobs) must not absorb it
    before = _post(server, "/admin/stats")["jobs"]
    resp = _post(server, "/stream/cfg_bad_topic",
                 sequences="1 -1 -2", support="0.5", algorithm="NOPE")
    assert resp["status"] == "failure"
    after = _post(server, "/admin/stats")["jobs"]
    assert after["jobs_failed"] == before["jobs_failed"]
    assert after["stream_failures"] == before["stream_failures"] + 1


def test_fused_accepts_engine_pins():
    # the engine supports queue/dense pins (mine_spade_tpu); the boot
    # vocabulary must accept them — and still reject typos
    from spark_fsm_tpu.config import ConfigError, parse_config

    for v in ("auto", "always", "never", "queue", "dense"):
        assert parse_config({"engine": {"fused": v}}).engine.fused == v
    with pytest.raises(ConfigError, match="fused"):
        parse_config({"engine": {"fused": "qeue"}})
