"""Pallas pair-support kernel: interpret-mode parity with the numpy ops.

The kernel itself is TPU-targeted; on the CPU test backend it runs through
the Pallas interpreter, which exercises identical index/block logic
(SURVEY.md sec 4: distributed/device tests without device hardware).
"""

import numpy as np
import jax.numpy as jnp

from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.models.spade_tpu import SpadeTPU
from spark_fsm_tpu.ops import bitops_np as BN
from spark_fsm_tpu.ops.pallas_support import (
    I_TILE, P_TILE, S_BLOCK, batch_supports, pair_supports)
from spark_fsm_tpu.utils.canonical import diff_patterns, patterns_text


def _rand_words(rng, n, s):
    # sparse-ish single-word bitmaps
    return (rng.integers(0, 2**32, (n, s), dtype=np.uint32)
            & rng.integers(0, 2**32, (n, s), dtype=np.uint32)
            & rng.integers(0, 2**32, (n, s), dtype=np.uint32))


def test_pair_supports_matches_numpy():
    rng = np.random.default_rng(0)
    P, NI, S = 2 * P_TILE, 21, S_BLOCK
    pt = _rand_words(rng, P, S)
    store = _rand_words(rng, I_TILE, S)
    out = np.asarray(pair_supports(jnp.asarray(pt), jnp.asarray(store), NI,
                                   interpret=True))
    assert out.shape == (P, -(-NI // I_TILE) * I_TILE)
    for p in range(P):
        for i in range(NI):
            want = int(np.count_nonzero(pt[p] & store[i]))
            assert out[p, i] == want, (p, i, out[p, i], want)


def test_batch_supports_extraction():
    rng = np.random.default_rng(1)
    P, S = P_TILE, 2 * S_BLOCK
    pt = _rand_words(rng, P, S)[..., None]          # [P, S, 1] squeezed path
    store = _rand_words(rng, I_TILE, S)[..., None]
    pref = rng.integers(0, P, 50, dtype=np.int32)
    item = rng.integers(0, 20, 50, dtype=np.int32)
    sup = np.asarray(batch_supports(jnp.asarray(pt), jnp.asarray(store), 20,
                                    jnp.asarray(pref), jnp.asarray(item),
                                    interpret=True))
    for k in range(50):
        want = int(BN.support(pt[pref[k], :, :] & store[item[k], :, :]))
        assert sup[k] == want


def test_engine_pallas_parity_small():
    db = synthetic_db(seed=7, n_sequences=260, n_items=14, mean_itemsets=4.0,
                      mean_itemset_size=1.4)
    minsup = abs_minsup(0.05, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    eng = SpadeTPU(vdb, minsup, use_pallas=True, node_batch=16,
                   pool_bytes=64 << 20)
    assert eng.use_pallas and eng.n_seq % S_BLOCK == 0
    got = eng.mine()
    want = mine_spade(db, minsup)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)
