"""Pallas pair-support kernel: interpret-mode parity with the numpy ops.

The kernel itself is TPU-targeted; on the CPU test backend it runs through
the Pallas interpreter, which exercises identical index/block logic
(SURVEY.md sec 4: distributed/device tests without device hardware).
Covers single-word, multiword (W > 1), and the shard_map mesh path.
"""

import numpy as np
import jax.numpy as jnp

from spark_fsm_tpu.data.synth import synthetic_db
from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical
from spark_fsm_tpu.models.oracle import mine_spade
from spark_fsm_tpu.models.spade_tpu import SpadeTPU
from spark_fsm_tpu.ops import bitops_np as BN
from spark_fsm_tpu.ops.pallas_support import (
    I_TILE, P_TILE, S_BLOCK, batch_supports, pair_supports, seq_block)
from spark_fsm_tpu.parallel.mesh import make_mesh
from spark_fsm_tpu.utils.canonical import diff_patterns, patterns_text


def _rand_words(rng, *shape):
    # sparse-ish bitmaps
    return (rng.integers(0, 2**32, shape, dtype=np.uint32)
            & rng.integers(0, 2**32, shape, dtype=np.uint32)
            & rng.integers(0, 2**32, shape, dtype=np.uint32))


def test_pair_supports_matches_numpy():
    rng = np.random.default_rng(0)
    P, NI, S = 2 * P_TILE, 21, S_BLOCK
    pt = _rand_words(rng, P, S)
    store = _rand_words(rng, I_TILE, S)
    out = np.asarray(pair_supports(jnp.asarray(pt)[:, None, :],
                                   jnp.asarray(store)[:, None, :], NI,
                                   interpret=True))
    assert out.shape == (P, -(-NI // I_TILE) * I_TILE)
    for p in range(P):
        for i in range(NI):
            want = int(np.count_nonzero(pt[p] & store[i]))
            assert out[p, i] == want, (p, i, out[p, i], want)


def test_pair_supports_multiword():
    rng = np.random.default_rng(3)
    W = 3
    sb = seq_block(W)
    P, NI, S = P_TILE, 17, 2 * sb
    pt = _rand_words(rng, P, W, S)
    items = _rand_words(rng, I_TILE, W, S)
    out = np.asarray(pair_supports(jnp.asarray(pt), jnp.asarray(items), NI,
                                   s_block=sb, interpret=True))
    for p in range(P):
        for i in range(NI):
            # support = #seqs where ANY word of the AND is nonzero
            want = int(np.count_nonzero((pt[p] & items[i]).any(axis=0)))
            assert out[p, i] == want, (p, i, out[p, i], want)


def test_batch_supports_extraction():
    rng = np.random.default_rng(1)
    P, S = P_TILE, 2 * S_BLOCK
    pt = _rand_words(rng, P, S)[..., None]          # [P, S, 1] native layout
    store = _rand_words(rng, I_TILE, S)[..., None]
    pref = rng.integers(0, P, 50, dtype=np.int32)
    item = rng.integers(0, 20, 50, dtype=np.int32)
    sup = np.asarray(batch_supports(jnp.asarray(pt), jnp.asarray(store), 20,
                                    jnp.asarray(pref), jnp.asarray(item),
                                    interpret=True))
    for k in range(50):
        want = int(BN.support(pt[pref[k], :, :] & store[item[k], :, :]))
        assert sup[k] == want


def test_batch_supports_multiword_kernel_layout():
    rng = np.random.default_rng(2)
    W = 2
    sb = seq_block(W)
    P, S = P_TILE, sb
    pt = _rand_words(rng, P, S, W)                  # native [P, S, W]
    items_t = _rand_words(rng, I_TILE, W, S)        # kernel [T, W, S]
    pref = rng.integers(0, P, 40, dtype=np.int32)
    item = rng.integers(0, I_TILE, 40, dtype=np.int32)
    sup = np.asarray(batch_supports(
        jnp.asarray(pt), jnp.asarray(items_t), I_TILE,
        jnp.asarray(pref), jnp.asarray(item),
        items_kernel_layout=True, s_block=sb, interpret=True))
    for k in range(40):
        a = pt[pref[k]].T                           # [W, S]
        want = int(np.count_nonzero((a & items_t[item[k]]).any(axis=0)))
        assert sup[k] == want


def test_engine_pallas_parity_small():
    db = synthetic_db(seed=7, n_sequences=260, n_items=14, mean_itemsets=4.0,
                      mean_itemset_size=1.4)
    minsup = abs_minsup(0.05, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    eng = SpadeTPU(vdb, minsup, use_pallas=True, node_batch=16,
                   pool_bytes=64 << 20)
    assert eng.use_pallas and eng.n_seq % eng._s_block == 0
    got = eng.mine()
    want = mine_spade(db, minsup)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


def test_engine_pallas_parity_multiword():
    # mean_itemsets > 32 forces n_words >= 2 (multiword carry chains + the
    # transposed item block both in play)
    db = synthetic_db(seed=11, n_sequences=150, n_items=10, mean_itemsets=40.0,
                      mean_itemset_size=1.2, max_itemsets=90)
    minsup = abs_minsup(0.2, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    assert vdb.n_words > 1
    eng = SpadeTPU(vdb, minsup, use_pallas=True, node_batch=8,
                   pool_bytes=64 << 20, max_pattern_itemsets=4)
    assert eng.use_pallas and eng._items_t is not None
    got = eng.mine()
    want = mine_spade(db, minsup, max_pattern_itemsets=4)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


def test_engine_pallas_parity_mesh():
    db = synthetic_db(seed=13, n_sequences=300, n_items=12, mean_itemsets=4.0,
                      mean_itemset_size=1.3)
    minsup = abs_minsup(0.06, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    mesh = make_mesh(8)
    eng = SpadeTPU(vdb, minsup, mesh=mesh, use_pallas=True, node_batch=16,
                   pool_bytes=256 << 20)
    assert eng.use_pallas and eng.n_seq % (8 * eng._s_block) == 0
    got = eng.mine()
    want = mine_spade(db, minsup)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)


def test_engine_pallas_parity_mesh_multiword():
    db = synthetic_db(seed=17, n_sequences=120, n_items=9, mean_itemsets=38.0,
                      mean_itemset_size=1.2, max_itemsets=80)
    minsup = abs_minsup(0.25, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    assert vdb.n_words > 1
    mesh = make_mesh(8)
    eng = SpadeTPU(vdb, minsup, mesh=mesh, use_pallas=True, node_batch=8,
                   pool_bytes=256 << 20, max_pattern_itemsets=3)
    assert eng.use_pallas and eng._items_t is not None
    got = eng.mine()
    want = mine_spade(db, minsup, max_pattern_itemsets=3)
    assert patterns_text(got) == patterns_text(want), diff_patterns(want, got)
