"""Test env: force CPU backend with 8 virtual devices (SURVEY.md sec 4).

Must run before any ``import jax`` — pytest imports conftest first, so this
is the one place allowed to set the env.  The same sharded code runs
unchanged on a real TPU mesh; the driver's dryrun_multichip uses the same
mechanism.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
