"""Test env: force CPU backend with 8 virtual devices (SURVEY.md sec 4).

This sandbox boots every interpreter with an `axon` TPU plugin registered
via sitecustomize (PYTHONPATH=/root/.axon_site) and JAX_PLATFORMS=axon in
the ambient env, so plain env-var defaults are NOT enough: the axon hooks
re-route platform selection, and a second process touching the TPU tunnel
while another holds it hangs at backend init.  The reliable override is
``jax.config.update('jax_platforms', 'cpu')`` before the first backend
init (XLA_FLAGS is read at backend-client creation, so setting it here is
still early enough for the 8 virtual devices).

The same sharded code runs unchanged on a real TPU mesh; the driver's
dryrun_multichip uses the same mechanism.
"""

import os
import subprocess
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()

_COLLECTIVE_FLAG = "--xla_cpu_collective_call_terminate_timeout_seconds=1200"


def _xla_accepts(flag: str) -> bool:
    """Probe (in a throwaway process) whether this jaxlib's XLA knows
    ``flag``: XLA parse_flags_from_env FATALS the whole process on any
    unknown XLA_FLAGS entry, so appending an unsupported flag here
    would abort EVERY test run at first backend init — which is exactly
    what happened when the sandbox's jaxlib moved to a version without
    the collective-timeout flag (the 'seed tests failing' state)."""
    code = ("import os; os.environ['JAX_PLATFORMS']='cpu'; "
            "import jax; jax.config.update('jax_platforms','cpu'); "
            "jax.devices()")
    env = dict(os.environ, XLA_FLAGS=flag)
    try:
        return subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True,
                              timeout=120).returncode == 0
    except Exception:
        return False


if (os.environ.get("RUN_SLOW")
        and "--xla_cpu_collective_call_terminate_timeout_seconds" not in _flags
        and _xla_accepts(_COLLECTIVE_FLAG)):
    # XLA CPU ABORTS the whole process when an 8-way collective's
    # participants don't all arrive within 40s — on a 1-core box the 8
    # virtual devices timeshare one core, so a mid-scale mesh program
    # (RUN_SLOW) can genuinely need minutes to reach the rendezvous.
    # Raise the failure-detection deadline; a real deadlock still
    # terminates, just later.  Only needed for the RUN_SLOW mesh tests,
    # and only when this jaxlib actually knows the flag (see probe).
    _flags = (_flags + " " + _COLLECTIVE_FLAG)
os.environ["XLA_FLAGS"] = _flags

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from spark_fsm_tpu.utils.jitcache import enable_compile_cache  # noqa: E402

enable_compile_cache()  # persistent XLA cache: repeat suite runs skip compiles


def _assert_faults_disarmed(when: str) -> None:
    """The chaos suite's no-leak contract: an injection left armed would
    silently fail (or flake) every LATER test that touches its site —
    enforce a disarmed registry at both session edges so a leak names
    the offending site instead of poisoning unrelated tests."""
    from spark_fsm_tpu.utils import faults

    leftover = faults.armed()
    assert not leftover, (
        f"fault-injection registry armed at session {when}: "
        f"{sorted(leftover)} — a chaos test leaked its injection "
        f"(use faults.injected(...) or a try/finally disarm)")


# --------------------------------------------------------------------------
# Opt-in suite flight recording: SPARKFSM_TRACE_TESTS=1 enables the
# utils/obs flight recorder for the whole session (each test runs under
# its own trace via the autouse fixture below) and prints the 10
# slowest spans at session end — the straggler hunt for tier-1 runtime
# regressions.  Off by default: tier-1 keeps the one-global-read
# disabled path and tests that reconfigure tracing stay isolated.
# --------------------------------------------------------------------------

import heapq  # noqa: E402
import threading  # noqa: E402

import pytest  # noqa: E402

_TRACE_TESTS = bool(os.environ.get("SPARKFSM_TRACE_TESTS"))
_slowest: list = []  # min-heap of (duration_s, seq, site, trace_id)
_slow_seq = 0
_SLOW_KEEP = 10
_slow_lock = threading.Lock()


def _slow_sink(span) -> None:
    # spans complete on miner workers, HTTP handler threads, and the
    # obs thread-safety test's own pool — the shared heap needs a lock
    # (a corrupted heap would silently wrong the straggler report)
    global _slow_seq
    d = span.duration_s
    if d is None:
        return
    with _slow_lock:
        _slow_seq += 1
        item = (d, _slow_seq, span.site, span.trace_id)
        if len(_slowest) < _SLOW_KEEP:
            heapq.heappush(_slowest, item)
        else:
            heapq.heappushpop(_slowest, item)


@pytest.fixture(autouse=True)
def _pin_overhead_calibration():
    """Pin the ragged planner's per-launch overhead to the committed
    constant for every test: the live path recalibrates it from the
    process-global ``fsm_costmodel_drift_ratio`` EWMA (ops/ragged_batch
    ``drift_factor``), and on this CPU backend any earlier test's TSR
    readbacks would push that gauge far above 1 — silently rescaling
    every later test's launch plans and breaking the pinned launch-
    budget/bench counters in an order-dependent way.  Tests that cover
    the calibration itself opt back in around their own body."""
    from spark_fsm_tpu.ops import ragged_batch as RB

    RB.set_overhead_calibration(False)
    yield
    RB.set_overhead_calibration(False)


@pytest.fixture(autouse=True)
def _trace_test(request):
    """Under SPARKFSM_TRACE_TESTS=1 every test body runs inside its own
    trace, so engine/service spans land somewhere countable.  A no-op
    (tracing stays off, zero overhead) otherwise."""
    if not _TRACE_TESTS:
        yield
        return
    from spark_fsm_tpu.utils import obs

    # re-enable per test: any earlier test that called config.set_config
    # (whose ObservabilityConfig defaults to trace=False) or toggled
    # tracing directly disabled the recorder — without this, the
    # slowest-span report would silently cover only the tests before
    # the first such call
    obs.configure_tracing(True, max_spans=256, max_jobs=8)
    with obs.trace(f"test:{request.node.nodeid}"):
        yield


def pytest_sessionstart(session):
    _assert_faults_disarmed("start")
    if _TRACE_TESTS:
        from spark_fsm_tpu.utils import obs

        obs.configure_tracing(True, max_spans=256, max_jobs=8)
        obs.add_span_sink(_slow_sink)


def pytest_sessionfinish(session, exitstatus):
    _assert_faults_disarmed("end")
    if _TRACE_TESTS:
        from spark_fsm_tpu.utils import obs

        obs.remove_span_sink(_slow_sink)
        obs.configure_tracing(False)
        rep = sorted(_slowest, reverse=True)
        print("\n-- SPARKFSM_TRACE_TESTS: 10 slowest spans --")
        for d, _, site, trace_id in rep:
            print(f"  {d:9.3f}s  {site:<20} {trace_id}")
