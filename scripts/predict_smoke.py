#!/usr/bin/env python
"""Prediction-serving smoke: boot with ``[predict]`` on, train, prewarm
the scoring ladder, drive 3 concurrent ``/predict`` requests over HTTP,
assert ONE fused scoring wave + byte parity + live read-path telemetry.

The CI companion to rescache_smoke/obs_smoke for the serving plane
(ISSUE 17, service/predictor.py): it boots the real HTTP service with a
held-open micro-batch window (250 ms — generous so the three
concurrent posts deterministically land in one group), then

- mines a base TSR job so the store holds a finished rule set;
- ``POST /admin/prewarm`` with an empty MINING envelope (sequences=0)
  so only the ``predict:*`` ladder from the boot ``[predict]`` floors
  compiles — the read path's AOT contract;
- fires 3 concurrent ``/predict`` posts against the same uid: they
  must resolve through ONE fused (3-request) scoring wave, each
  response byte-identical to the brute-force host oracle over the
  served rules (and to the Questor ``/get/prediction`` slow path);
- asserts no ``predict:*`` key appears in ``/admin/shapes`` drift
  (zero live scoring compiles after prewarm), the fsm_predict_*
  families are live on /metrics with the drill's counts, the
  ``/admin/slo`` read-path block holds the three observations, and
  ``/admin/predictor`` + ``/admin/rescache``-style stats surfaces show
  the resident artifact.

Usage: scripts/predict_smoke.sh   (pins JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.parse
import urllib.request


def main() -> int:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from spark_fsm_tpu import config as cfgmod
    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.data.synth import synthetic_db
    from spark_fsm_tpu.ops import rule_trie
    from spark_fsm_tpu.service.app import serve_background
    from spark_fsm_tpu.service.model import deserialize_rules

    cfgmod.set_config(cfgmod.parse_config({
        "predict": {"window_ms": 250.0, "max_wave": 4, "topm": 4,
                    "lanes_floor": 64, "depth_floor": 8}}))
    srv = serve_background()
    port = srv.server_port

    def post(ep, **params):
        data = urllib.parse.urlencode(params).encode()
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{ep}",
                                    data=data, timeout=120) as r:
            return r.read().decode()

    failures = []
    try:
        db = synthetic_db(seed=81, n_sequences=80, n_items=10,
                          mean_itemsets=3.0, mean_itemset_size=1.3)
        resp = json.loads(post("/train", algorithm="TSR_TPU",
                               source="INLINE", sequences=format_spmf(db),
                               k="8", minconf="0.4", max_side="2",
                               uid="pr-base"))
        assert resp["status"] != "failure", resp
        deadline = time.time() + 240.0
        while time.time() < deadline:
            st = json.loads(post("/status/pr-base"))
            if st["status"] in ("finished", "failure"):
                break
            time.sleep(0.05)
        if st["status"] != "finished":
            failures.append(f"base train did not finish: {st}")

        # prewarm ONLY the predict ladder (mining envelope zeroed): the
        # boot [predict] floors imply predict:f64d8w{1,2,4}m4
        report = json.loads(post("/admin/prewarm", sequences="0",
                                 items="0", stream_batch_sequences="0",
                                 fusion_jobs="0", partition_parts="0",
                                 tsr="0"))
        pkeys = [k for k in report.get("enumerated", [])
                 if k.startswith("predict:")]
        if not pkeys:
            failures.append(f"prewarm enumerated no predict keys: "
                            f"{report.get('enumerated')}")

        # 3 concurrent predicts against the same artifact: the held
        # window must fuse them into ONE scoring wave
        queries = [("1,2", "normal"), ("2", "low"), ("3,4", "normal")]
        out = {}

        def fire(i, items, pr):
            out[i] = json.loads(post("/predict/pr-base", items=items,
                                     m="4", priority=pr))

        ts = [threading.Thread(target=fire, args=(i, q, p))
              for i, (q, p) in enumerate(queries)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60.0)
        if any(t.is_alive() for t in ts):
            failures.append("a /predict request wedged")

        rules = deserialize_rules(
            json.loads(post("/get/rules", uid="pr-base"))["data"]["rules"])
        fused_seen = 0
        for i, (items, _) in enumerate(queries):
            r = out.get(i)
            if r is None or r["status"] != "finished":
                failures.append(f"predict {i} failed: {r}")
                continue
            stats = json.loads(r["data"]["stats"])
            if stats.get("fused"):
                fused_seen += 1
            got = json.loads(r["data"]["predictions"])
            prefix = sorted({int(x) for x in items.split(",") if x})
            want = rule_trie.predict_host(rules, prefix, 4)
            if (json.dumps(got, sort_keys=True)
                    != json.dumps(want, sort_keys=True)):
                failures.append(f"predict {i} not byte-identical to the "
                                f"host oracle (items={items!r})")
            # the slow path must agree too: /predict is a drop-in fast
            # path for the Questor's /get/prediction
            q = json.loads(post("/get/prediction", uid="pr-base",
                                items=items, m="4"))
            slow = json.loads(q["data"]["predictions"])[:4]
            if (json.dumps(got, sort_keys=True)
                    != json.dumps(slow, sort_keys=True)):
                failures.append(f"predict {i} disagrees with "
                                f"/get/prediction (items={items!r})")
        if fused_seen < 3:
            failures.append(f"expected all 3 requests in one fused wave, "
                            f"only {fused_seen} report fused=true")

        # zero live scoring compiles after prewarm: no predict:* key in
        # the recorded-vs-enumerated drift (mining keys WILL drift here
        # — the train above ran against a zeroed mining envelope)
        shapes_rep = json.loads(post("/admin/shapes"))
        pdrift = [k for k in (shapes_rep.get("drift") or [])
                  if k.startswith("predict:")]
        if pdrift:
            failures.append(f"live predict compiles after prewarm: "
                            f"{pdrift}")

        # live metric families with the drill's counts
        text = post("/metrics")

        def total(fam, **labels):
            want = set(f'{k}="{v}"' for k, v in labels.items())
            vals = []
            for line in text.splitlines():
                if not line.startswith(fam):
                    continue
                rest = line[len(fam):]
                if rest[:1] not in (" ", "{"):
                    continue
                if want and not all(w in rest for w in want):
                    continue
                vals.append(float(line.rsplit(" ", 1)[1]))
            return sum(vals) if vals else None

        for fam, labels, floor in (
                ("fsm_predict_requests_total", {"outcome": "served"}, 3),
                ("fsm_predict_waves_total", {"mode": "fused"}, 1),
                ("fsm_predict_artifact_builds_total", {}, 1),
                ("fsm_predict_artifact_cache_misses_total", {}, 1),
                ("fsm_predict_e2e_seconds_count", {"priority": "normal"}, 2),
                ("fsm_predict_artifact_entries", {}, 1)):
            got = total(fam, **labels)
            if got is None:
                failures.append(f"/metrics missing family {fam} {labels}")
            elif got < floor:
                failures.append(f"{fam}{labels} = {got} < {floor}")

        # read-path SLO block live on /admin/slo
        slo = json.loads(post("/admin/slo"))
        pblock = slo.get("predict", {})
        n_obs = sum(pblock.get(p, {}).get("e2e", {}).get("count", 0)
                    for p in ("high", "normal", "low"))
        if n_obs < 3:
            failures.append(f"/admin/slo predict block holds {n_obs} < 3 "
                            f"observations: {pblock}")

        # resident artifact visible on the admin surface
        pstats = json.loads(post("/admin/predictor"))
        if not pstats.get("cache", {}).get("resident"):
            failures.append(f"/admin/predictor shows no resident "
                            f"artifact: {pstats}")
    finally:
        srv.master.shutdown()
        srv.shutdown()
        cfgmod.set_config(cfgmod.parse_config({}))
    if failures:
        print("predict_smoke: FAILED:", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    print("predict_smoke: 3 concurrent /predict requests fused into one "
          "scoring wave with byte parity vs the host oracle AND the "
          "Questor slow path, zero live predict compiles after prewarm, "
          "fsm_predict_* families + /admin/slo read-path block live")
    return 0


if __name__ == "__main__":
    sys.exit(main())
