#!/usr/bin/env python
"""Fleet-supervisor chaos drill (ISSUE 14 satellite, ROADMAP item 4):
kill scripts/fleet.py mid-scale-up, restart it, and assert the fleet
CONVERGES to the published desired count with zero lost or duplicated
jobs.

The drill:

1. one MiniRedis as the fleet bus; ``scripts/fleet.py --initial 2``
   boots two replicas ([cluster] enabled, [autoscale] enabled so the
   config validates — the desired record is written by THIS harness,
   standing in for the leader's decision);
2. submit jobs to the live replicas (mix of quick + checkpointed);
3. publish ``fsm:autoscale:desired = 3`` and wait for the supervisor
   to START supplying the third replica — then SIGKILL the supervisor
   MID-SCALE-UP (the third replica may be half-booted; the first two
   keep running as orphans);
4. restart ``fleet.py --initial 0`` (restart mode): it must read the
   live fleet from the ``fsm:replica:*`` heartbeats, supply only the
   DEFICIT, and converge to 3 live heartbeats — never a duplicate
   fleet next to the orphans;
5. invariants: every accepted job settled exactly once with oracle
   parity, zero journal/lease/marker leftovers.

Usage: scripts/fleet_smoke.sh   (pins JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import json
import os
import pathlib
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.parse
import urllib.request

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))

BOOT_TIMEOUT_S = 240.0
DRILL_TIMEOUT_S = 300.0


def log(msg):
    print(f"fleet_smoke: {msg}", flush=True)


def post(port, endpoint, timeout=60, **params):
    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{port}{endpoint}"
    try:
        with urllib.request.urlopen(url, data=data,
                                    timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read().decode())


_DRAINED = set()


def start_drain(proc):
    """Background-drain a supervisor's stdout pipe (idempotent): the
    children inherit it and keep logging, and a full 64KB buffer
    blocks a child mid-log-write — a wedge that reads as a lost job."""
    import threading

    if proc is None or proc.stdout is None or id(proc) in _DRAINED:
        return
    _DRAINED.add(id(proc))

    def _drain(stream):
        try:
            for _ in stream:
                pass
        except (OSError, ValueError):
            pass

    threading.Thread(target=_drain, args=(proc.stdout,),
                     daemon=True).start()


def start_fleet(cfg_path, env, initial):
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "scripts" / "fleet.py"),
         "--config", cfg_path, "--initial", str(initial),
         "--max", "4", "--poll", "0.5"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, bufsize=1)
    return proc


def drain_lines(proc, pids, ports, deadline):
    """Non-blockingly-ish read fleet stdout, harvesting child pids and
    replica HTTP ports (children inherit the supervisor's stdout)."""
    import select

    while time.time() < deadline:
        r, _, _ = select.select([proc.stdout], [], [], 0.1)
        if not r:
            return
        line = proc.stdout.readline()
        if not line:
            return
        m = re.search(r"booted replica #\d+ \(pid (\d+)\)", line)
        if m:
            pids.add(int(m.group(1)))
        m = re.search(r"service on http://[^:]+:(\d+)", line)
        if m:
            ports.append(int(m.group(1)))


def live_heartbeats(client):
    n, cursor = 0, "0"
    while True:
        cursor, batch = client.scan(cursor, match="fsm:replica:*",
                                    count=64)
        n += len(batch)
        if cursor == "0":
            return n


def main():
    from test_redis_store import MiniRedis

    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.data.synth import synthetic_db
    from spark_fsm_tpu.data.vertical import abs_minsup
    from spark_fsm_tpu.models.oracle import mine_spade
    from spark_fsm_tpu.service.model import deserialize_patterns
    from spark_fsm_tpu.service.resp import RespClient
    from spark_fsm_tpu.utils.canonical import patterns_text

    mini = MiniRedis()
    log(f"MiniRedis (fleet bus) on port {mini.port}")
    client = RespClient(port=mini.port)
    tmp = tempfile.mkdtemp(prefix="fleet_smoke_")
    cfg_path = os.path.join(tmp, "fleet.json")
    with open(cfg_path, "w") as fh:
        json.dump({
            "service": {"port": 0, "miner_workers": 1,
                        "queue_depth": 16},
            "store": {"backend": "redis", "host": "127.0.0.1",
                      "port": mini.port},
            "cluster": {"enabled": True, "lease_ttl_s": 2.0,
                        "recover_every_s": 0.5},
            # the controller is live but parked: this harness writes
            # the desired record itself (deterministic scale signal)
            "autoscale": {"enabled": True, "min_replicas": 1,
                          "max_replicas": 4, "hold_s": 3600.0,
                          "cooldown_s": 3600.0},
        }, fh)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    pids, ports = set(), []
    fleet1 = fleet2 = None
    try:
        fleet1 = start_fleet(cfg_path, env, initial=2)
        deadline = time.time() + BOOT_TIMEOUT_S
        while time.time() < deadline and (len(ports) < 2
                                          or live_heartbeats(client) < 2):
            drain_lines(fleet1, pids, ports, time.time() + 0.5)
        assert len(ports) >= 2 and live_heartbeats(client) >= 2, \
            f"initial fleet never came up (ports={ports})"
        log(f"initial fleet up: 2 replicas on ports {ports[:2]}")

        # live traffic: quick + checkpointed jobs with known oracles
        db = synthetic_db(seed=77, n_sequences=100, n_items=10,
                          mean_itemsets=2.5, mean_itemset_size=1.2)
        want = patterns_text(mine_spade(db, abs_minsup(0.1, len(db))))
        accepted = []
        for i, extra in enumerate([{}, {"checkpoint": "1",
                                        "checkpoint_every_s": "0"}, {}]):
            uid = f"fleet-job-{i}"
            code, body = post(ports[i % 2], "/train", uid=uid,
                              algorithm="SPADE_TPU", source="INLINE",
                              sequences=format_spmf(db), support="0.1",
                              **extra)
            assert code == 200 and body["status"] == "started", body
            accepted.append(uid)

        # the scale signal: desired = 3 (standing in for the leader)
        client.set("fsm:autoscale:desired", json.dumps(
            {"desired": 3, "dir": "up", "reason": "fleet_smoke drill",
             "leader": "harness", "seq": 1,
             "ts": round(time.time(), 3)}))
        log("published fsm:autoscale:desired = 3")

        # wait for the supervisor to START supplying replica #3, then
        # SIGKILL it mid-scale-up
        deadline = time.time() + BOOT_TIMEOUT_S
        while time.time() < deadline and len(pids) < 3:
            drain_lines(fleet1, pids, ports, time.time() + 0.5)
        assert len(pids) >= 3, "supervisor never started the 3rd replica"
        fleet1.send_signal(signal.SIGKILL)
        fleet1.wait(30)
        # the orphaned replicas keep logging into fleet1's pipe
        start_drain(fleet1)
        killed_at_hb = live_heartbeats(client)
        log(f"SIGKILLed the supervisor mid-scale-up "
            f"({killed_at_hb} heartbeats live at the kill; "
            f"{len(pids)} replicas spawned)")

        # the orphaned replicas keep running: the in-flight jobs keep
        # settling with nobody supervising
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline:
            sts = [client.get(f"fsm:status:{u}") for u in accepted]
            if all(s in ("finished", "failure") for s in sts):
                break
            time.sleep(0.25)
        assert all(s == "finished" for s in sts), sts
        log("all jobs settled on the orphaned replicas")

        # RESTART in converge mode: supply only the heartbeat deficit
        fleet2 = start_fleet(cfg_path, env, initial=0)
        deadline = time.time() + BOOT_TIMEOUT_S
        hb = 0
        while time.time() < deadline:
            drain_lines(fleet2, pids, ports, time.time() + 0.5)
            hb = live_heartbeats(client)
            if hb >= 3:
                break
        assert hb == 3, f"fleet never converged to 3 (heartbeats={hb})"
        # convergence is STABLE: no duplicate fleet spawns next to the
        # orphans (one extra poll period of grace, then recount)
        time.sleep(3.0)
        drain_lines(fleet2, pids, ports, time.time() + 0.5)
        hb = live_heartbeats(client)
        assert hb == 3, f"fleet over-provisioned after restart ({hb})"
        log(f"restarted supervisor converged the fleet to 3 replicas "
            f"({len(pids)} total boots across both supervisors)")

        # zero lost/duplicated jobs: one terminal entry each, parity
        for uid in accepted:
            entries = [e.partition(":")[2]
                       for e in client.lrange(f"fsm:status:log:{uid}")]
            terminals = [e for e in entries
                         if e in ("finished", "failure")]
            assert terminals == ["finished"], (uid, entries)
            got = patterns_text(deserialize_patterns(
                client.get(f"fsm:pattern:{uid}")))
            assert got == want, f"{uid}: parity violated"
        assert client.keys("fsm:journal:*") == []
        assert client.keys("fsm:admission:*") == []
        log("invariants ok: every job settled exactly once with "
            "parity, no journal/marker leftovers")
    finally:
        for proc in (fleet1, fleet2):
            if proc is None:
                continue
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
            # classic wait-with-full-pipe deadlock guard: the children
            # keep logging through the shutdown drain
            start_drain(proc)
        for proc in (fleet1, fleet2):
            if proc is not None:
                try:
                    proc.wait(90)
                except subprocess.TimeoutExpired:
                    proc.kill()
        # reap any replica the killed supervisor orphaned
        for pid in pids:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        mini.close()
    log("PASS")


if __name__ == "__main__":
    main()
