#!/usr/bin/env python
"""Bitrot drill: the ISSUE 18 integrity plane against the REAL service
across REAL process boundaries.

The CI companion to overload_smoke for the durable-state integrity
layer (utils/envelope.py + service/integrity.py).  It boots the HTTP
service as a subprocess over a MiniRedis store (the in-process RESP
server from tests/test_redis_store.py — the store must survive the
service's death), then plants byte damage in every surface the
envelope protects and asserts the per-surface degradation contract:

1. warms the result-reuse tier with a TSR mine (oracle-checked), then
   submits a long CHECKPOINTED mine and kill -9s the service once two
   delta chunks have persisted;
2. while the service is DEAD, corrupts the durable state the way real
   bitrot would: byte-flips the LAST checkpoint delta chunk, truncates
   the rescache entry mid-record, and plants a flipped journal intent
   under a poison uid;
3. reboots on the same store: boot recovery must quarantine the poison
   intent (``1 quarantined`` on the recovery line) and still resume the
   drill, which must heal to the last GOOD chunk and finish with the
   EXACT oracle pattern set — zero duplicated, zero missing results;
4. re-submits the warmed TSR request: the damaged entry must never be
   served — the service falls through to a cold re-mine (no
   ``served_from_cache`` stat) that again matches the oracle, and the
   rotten bytes land in the quarantine keyspace;
5. BACKGROUND SCRUBBER: plants one more rotten intent at rest and
   waits for the thread-cadence scrub to quarantine it with no read
   traffic at all;
6. asserts ``/admin/integrity`` lists the quarantine records with
   their surfaces and that the zero-seeded ``fsm_integrity_*`` metric
   families are live on /metrics.

Usage: scripts/bitrot_smoke.sh   (pins JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import json
import os
import pathlib
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))

BOOT_TIMEOUT_S = 180.0
DRILL_TIMEOUT_S = 300.0
SCRUB_EVERY_S = 0.5


def log(msg):
    print(f"bitrot_smoke: {msg}", flush=True)


def post(port, endpoint, **params):
    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{port}{endpoint}"
    try:
        with urllib.request.urlopen(url, data=data, timeout=60) as resp:
            return resp.status, dict(resp.headers), \
                json.loads(resp.read().decode())
    except urllib.error.HTTPError as err:
        return err.code, dict(err.headers), json.loads(err.read().decode())


def scrape(port, family):
    """Sum every sample of ``family`` in /metrics (labels collapsed)."""
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=60) as resp:
        text = resp.read().decode()
    total, seen = 0.0, False
    for line in text.splitlines():
        m = re.match(rf"^{re.escape(family)}(\{{[^}}]*\}})?\s+(\S+)$", line)
        if m:
            total += float(m.group(2))
            seen = True
    assert seen, f"{family} missing from /metrics"
    return total


def flip(value, at):
    """One bit of bitrot at ``at`` — the minimal real-world damage."""
    return value[:at] + chr(ord(value[at]) ^ 0x01) + value[at + 1:]


def boot_service(cfg_path, env):
    child = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import sys\n"
        f"sys.argv = ['app', '--config', {str(cfg_path)!r}]\n"
        "from spark_fsm_tpu.service.app import main\n"
        "main()\n"
    )
    proc = subprocess.Popen([sys.executable, "-c", child], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    port = None
    recovery_line = None
    scrubber_line = None
    deadline = time.time() + BOOT_TIMEOUT_S
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise AssertionError(
                f"service died at boot (rc={proc.poll()})")
        if line.startswith("restart recovery:"):
            recovery_line = line.strip()
        if line.startswith("integrity scrubber on"):
            scrubber_line = line.strip()
        if "spark_fsm_tpu service on http://" in line:
            port = int(line.rsplit(":", 1)[1])
            break
    assert port is not None, "no boot line within the timeout"
    # keep draining stdout so a chatty incarnation never blocks on a
    # full pipe while the drill below is busy elsewhere
    threading.Thread(target=lambda: proc.stdout.read(),
                     daemon=True).start()
    return proc, port, recovery_line, scrubber_line


def main():
    from test_redis_store import MiniRedis  # noqa: E402 (tests/ on path)

    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.data.synth import synthetic_db
    from spark_fsm_tpu.data.vertical import abs_minsup
    from spark_fsm_tpu.models.oracle import mine_spade
    from spark_fsm_tpu.models.tsr import mine_tsr_cpu
    from spark_fsm_tpu.service.model import (deserialize_patterns,
                                             deserialize_rules)
    from spark_fsm_tpu.service.resp import RespClient
    from spark_fsm_tpu.utils import envelope
    from spark_fsm_tpu.utils.canonical import (diff_patterns,
                                               patterns_text, rules_text)

    mini = MiniRedis()
    log(f"MiniRedis on port {mini.port}")
    client = RespClient(port=mini.port)

    tmp = tempfile.mkdtemp(prefix="bitrot_smoke_")
    cfg_path = os.path.join(tmp, "config.json")
    with open(cfg_path, "w") as fh:
        json.dump({
            "fault_injection": True,  # the per-save delay arms via HTTP
            "service": {"port": 0, "miner_workers": 1, "queue_depth": 8},
            "store": {"backend": "redis", "host": "127.0.0.1",
                      "port": mini.port},
            "rescache": {"enabled": True},
            "integrity": {"scrub_every_s": SCRUB_EVERY_S,
                          "scrub_batch": 128},
            # pin the queue engine so the checkpointed drill takes the
            # segmented path (frontier saves at every segment boundary)
            "engine": {"fused": "queue"},
        }, fh)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    proc, port, _, scrubber_line = boot_service(cfg_path, env)
    log(f"service A on port {port} (pid {proc.pid}); {scrubber_line}")
    assert scrubber_line is not None, "no scrubber banner at boot"
    assert "thread cadence" in scrubber_line, scrubber_line

    warm_db = synthetic_db(seed=31, n_sequences=60, n_items=9,
                           mean_itemsets=3.0, mean_itemset_size=1.2)
    warm_text = format_spmf(warm_db)
    warm_params = dict(algorithm="TSR_TPU", source="INLINE",
                       sequences=warm_text, k="8", minconf="0.4",
                       max_side="2")
    oracle_rules = rules_text(mine_tsr_cpu(warm_db, 8, 0.4, max_side=2))

    # deep enough that the queue engine crosses >= 3 segment boundaries
    # (saves land at waves 1, 5, 21 of ~54): two delta chunks persist
    # with a couple of segments still to mine after the last one
    drill_db = synthetic_db(seed=41, n_sequences=300, n_items=10,
                            mean_itemsets=6.0, mean_itemset_size=1.5)
    oracle_patterns = mine_spade(drill_db, abs_minsup(0.02, len(drill_db)))

    try:
        # ---- warm the rescache with an oracle-checked TSR mine
        code, _, body = post(port, "/train", uid="warm", **warm_params)
        assert code == 200 and body["status"] == "started", body
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline:
            _, _, body = post(port, "/status/warm")
            if body["status"] in ("finished", "failure"):
                break
            time.sleep(0.1)
        assert body["status"] == "finished", body
        _, _, body = post(port, "/get/rules", uid="warm")
        got = rules_text(deserialize_rules(body["data"]["rules"]))
        assert got == oracle_rules, "warm mine disagrees with the oracle"
        ekeys = client.keys("fsm:rescache:*")
        assert len(ekeys) == 1, f"expected one rescache entry: {ekeys}"
        ekey = ekeys[0]
        assert envelope.is_enveloped(client.get(ekey)), \
            "rescache entry not enveloped on write"
        log(f"rescache warmed (oracle parity, entry {ekey})")

        # ---- checkpointed drill: slow every frontier save by 1s so at
        # least two delta chunks persist before the kill
        code, _, _ = post(port, "/admin/faults", action="arm",
                          site="checkpoint.save", every="1",
                          delay_s="1.0", exc="none")
        assert code == 200, "chaos lab refused the arm"
        code, _, body = post(port, "/train", uid="drill",
                             algorithm="SPADE_TPU", source="INLINE",
                             sequences=format_spmf(drill_db),
                             support="0.02", checkpoint="1",
                             checkpoint_every_s="0")
        assert code == 200 and body["status"] == "started", body
        chunks_key = "fsm:frontier:results:drill"
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline:
            if client.llen(chunks_key) >= 2:
                break
            assert proc.poll() is None, "service A died early"
            time.sleep(0.1)
        assert client.llen(chunks_key) >= 2, "never saw 2 delta chunks"
        assert client.get("fsm:journal:drill"), "drill journal missing"
        proc.send_signal(signal.SIGKILL)
        proc.wait(30)
        log("killed service A mid-mine (2+ delta chunks persisted)")
    except BaseException:
        proc.kill()
        raise

    # ---- the service is DEAD: rot the durable state under it
    chunks = client.lrange(chunks_key)
    client.ltrim(chunks_key, 0, len(chunks) - 2)
    client.rpush(chunks_key, flip(chunks[-1], len(chunks[-1]) - 10))
    log(f"byte-flipped the last of {len(chunks)} checkpoint delta chunks")
    raw = client.get(ekey)
    client.set(ekey, raw[: len(raw) // 2])
    log("truncated the rescache entry mid-record")
    client.set("fsm:journal:poison-bitrot",
               flip(envelope.wrap(json.dumps({"incarnation": "ghost"})),
                    80))
    log("planted a flipped journal intent under uid poison-bitrot")

    # ---- reboot on the SAME store
    proc, port, recovery_line, _ = boot_service(cfg_path, env)
    log(f"service B on port {port} (pid {proc.pid}); {recovery_line}")
    try:
        assert recovery_line is not None, "no recovery line at reboot"
        assert "1 resumed" in recovery_line, recovery_line
        assert "1 quarantined" in recovery_line, recovery_line
        assert client.get("fsm:journal:poison-bitrot") is None, \
            "poison intent not moved out of the journal namespace"
        assert client.get("fsm:quarantine:poison-bitrot"), \
            "poison intent missing from the quarantine keyspace"

        # drill: healed to the last GOOD chunk, resumed, oracle parity
        deadline = time.time() + DRILL_TIMEOUT_S
        status = None
        while time.time() < deadline:
            _, _, body = post(port, "/status/drill")
            status = body["status"]
            if status in ("finished", "failure"):
                break
            time.sleep(0.25)
        assert status == "finished", (status, body)
        _, _, body = post(port, "/get/patterns", uid="drill")
        got = deserialize_patterns(body["data"]["patterns"])
        assert patterns_text(got) == patterns_text(oracle_patterns), \
            diff_patterns(oracle_patterns, got)
        qkeys = client.keys("fsm:quarantine:*")
        assert any("frontier:results:drill" in k for k in qkeys), \
            f"rotten delta chunk not quarantined: {qkeys}"
        log(f"checkpoint drill ok: resumed from the last good chunk, "
            f"{len(got)} patterns with oracle parity")

        # rescache: the rotten entry is NEVER served — cold re-mine
        # with oracle parity (the scrubber may beat the read to the
        # quarantine; either way the lookup must cleanly miss)
        code, _, body = post(port, "/train", uid="rehit", **warm_params)
        assert code == 200 and body["status"] == "started", body
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline:
            _, _, body = post(port, "/status/rehit")
            if body["status"] in ("finished", "failure"):
                break
            time.sleep(0.1)
        assert body["status"] == "finished", body
        stats = json.loads(client.get("fsm:stats:rehit") or "{}")
        assert "served_from_cache" not in stats, \
            f"rotten entry was served: {stats}"
        _, _, body = post(port, "/get/rules", uid="rehit")
        got = rules_text(deserialize_rules(body["data"]["rules"]))
        assert got == oracle_rules, "cold re-mine disagrees with oracle"
        qkey = "fsm:quarantine:" + ekey[len("fsm:"):]
        assert client.get(qkey), f"rotten entry not quarantined at {qkey}"
        log("rescache drill ok: rotten entry quarantined, cold re-mine "
            "matches the oracle")

        # background scrubber: damage at REST, zero read traffic
        client.set("fsm:journal:rot-at-rest",
                   flip(envelope.wrap(json.dumps({"incarnation": "x"})),
                        80))
        deadline = time.time() + 60.0
        while time.time() < deadline:
            if client.get("fsm:journal:rot-at-rest") is None and \
                    client.get("fsm:quarantine:rot-at-rest"):
                break
            time.sleep(0.1)
        assert client.get("fsm:journal:rot-at-rest") is None, \
            "scrubber never quarantined the at-rest damage"
        log("scrubber ok: at-rest damage quarantined with no reads")

        # /admin/integrity: records listed with surfaces + counters
        code, _, rep = post(port, "/admin/integrity")
        assert code == 200 and rep["enabled"] is True, rep
        assert rep["scrub_every_s"] == SCRUB_EVERY_S, rep
        surfaces = {r.get("surface") for r in rep["quarantine"]}
        assert {"journal", "rescache", "checkpoint"} <= surfaces, surfaces
        for name in ("scans", "verified", "legacy", "corrupt",
                     "quarantined", "repaired"):
            assert name in rep["counters"], rep["counters"]
        log(f"/admin/integrity ok: {len(rep['quarantine'])} quarantine "
            f"records across surfaces {sorted(surfaces)}")

        # metric families live (zero-seeded, so presence is guaranteed;
        # the drill pushed the interesting ones off zero)
        for fam in ("fsm_integrity_scans_total",
                    "fsm_integrity_verified_total",
                    "fsm_integrity_legacy_total",
                    "fsm_integrity_corrupt_total",
                    "fsm_integrity_quarantined_total",
                    "fsm_integrity_repaired_total"):
            scrape(port, fam)
        assert scrape(port, "fsm_integrity_scans_total") >= 1
        assert scrape(port, "fsm_integrity_verified_total") >= 1
        assert scrape(port, "fsm_integrity_quarantined_total") >= 2
        assert scrape(port, "fsm_recovery_jobs_total") >= 2
        log("metrics ok: fsm_integrity_* families live")
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(60)
        except subprocess.TimeoutExpired:
            proc.kill()
        mini.close()
    log("PASS")


if __name__ == "__main__":
    main()
