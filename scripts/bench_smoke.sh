#!/usr/bin/env bash
# Launch/traffic smoke — the seconds-scale companion to verify_t1.sh.
# Shaped miniatures of BENCH_SCALE configs 3/3d/5 on the CPU backend,
# diffing kernel_launches / evaluated / traffic_units against the
# committed scripts/bench_smoke_expect.json (walls reported, never
# compared).  Pass --update to rewrite the expectations after a
# deliberate dispatch-policy change.
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/bench_smoke.py "$@"
