#!/usr/bin/env python
"""Observability smoke: boot the service, mine, scrape, cross-check.

The CI companion to verify_t1.sh / bench_smoke.sh / chaos_smoke.sh for
the observability layer (utils/obs.py): it boots the real HTTP service
with tracing ON, runs one traced TSR mine end to end, and asserts

- ``GET /metrics`` parses as Prometheus text exposition (every
  non-comment line is ``name[{labels}] value``, every family has a
  TYPE line, histogram buckets are cumulative and end at +Inf);
- NO ORPHAN COUNTERS: every registered fault site (utils/faults
  KNOWN_SITES) has ``fsm_fault_site_calls_total{site=...}`` and
  ``fsm_fault_site_injected_total`` series, and every framework retry
  policy (utils/retry KNOWN_SITES) has ``fsm_retry_attempts_total``
  series — armed-but-unexported machinery is invisible exactly when a
  drill needs it, which is the failure mode this guard exists for;
- the job's ``/admin/trace/{uid}`` dump exists, carries the job root
  span + mine span, and every tsr launch span has predicted seconds
  next to its measured wall;
- CLUSTER OBSERVABILITY (ISSUE 9; the service boots with ``[cluster]``
  enabled on the in-proc store): the dump is the MERGED timeline with
  lifecycle marks (admitted/started/settled) from the durable spine,
  ``/admin/cluster`` aggregates the heartbeat snapshots,
  ``/admin/slo`` reports per-priority latency quantiles for the mine
  that just ran, and the new ``fsm_cluster_*`` / ``fsm_job_*`` /
  ``fsm_trace_spine_*`` families are present with their label
  vocabularies zero-seeded (no orphan series).

Usage: scripts/obs_smoke.sh   (pins JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import json
import re
import sys
import time
import urllib.parse
import urllib.request

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})?\s+(\S+)$')


def parse_prometheus(text: str) -> dict:
    """Minimal exposition-format parser: {family: {label-string: value}}
    with TYPE bookkeeping; raises ValueError on any malformed line."""
    families: dict = {}
    types: dict = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            types[name] = kind.strip()
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"/metrics line {lineno} malformed: {line!r}")
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        try:
            fv = float(value)  # accepts exponents, +Inf, NaN
        except ValueError:
            raise ValueError(
                f"/metrics line {lineno}: non-numeric value {value!r}")
        families.setdefault(name, {})[labels] = fv
    for fam in families:
        base = re.sub(r"_(bucket|count|sum)$", "", fam)
        if fam not in types and base not in types:
            raise ValueError(f"family {fam} has samples but no # TYPE line")
    return families


def check_histograms(families: dict) -> None:
    for fam, rows in families.items():
        if not fam.endswith("_bucket"):
            continue
        by_series: dict = {}
        for labels, value in rows.items():
            le = re.search(r'le="([^"]*)"', labels)
            if le is None:
                raise ValueError(f"{fam}{labels}: bucket without le=")
            rest = re.sub(r',?le="[^"]*"', "", labels)
            by_series.setdefault(rest, []).append(
                (float("inf") if le.group(1) == "+Inf" else float(le.group(1)),
                 value))
        for rest, pairs in by_series.items():
            pairs.sort()
            if pairs[-1][0] != float("inf"):
                raise ValueError(f"{fam}{rest}: no +Inf bucket")
            counts = [v for _, v in pairs]
            if counts != sorted(counts):
                raise ValueError(f"{fam}{rest}: buckets not cumulative")


def main() -> int:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from spark_fsm_tpu import config as cfgmod
    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.data.synth import synthetic_db
    from spark_fsm_tpu.service.app import serve_background
    from spark_fsm_tpu.utils import faults as faultsmod
    from spark_fsm_tpu.utils import retry as retrymod

    cfgmod.set_config(cfgmod.parse_config(
        {"observability": {"trace": True, "spine_flush_spans": 8},
         "cluster": {"enabled": True, "replica_id": "obs-smoke",
                     "lease_ttl_s": 5.0}}))
    srv = serve_background()
    port = srv.server_port

    def post(ep, **params):
        data = urllib.parse.urlencode(params).encode()
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{ep}",
                                    data=data, timeout=120) as r:
            return r.read().decode()

    failures = []
    try:
        db = synthetic_db(seed=11, n_sequences=50, n_items=12,
                          mean_itemsets=3.0, mean_itemset_size=1.3)
        resp = json.loads(post(
            "/train", algorithm="TSR_TPU", source="INLINE",
            sequences=format_spmf(db), support="0.1", k="10",
            minconf="0.4", max_side="2", uid="obs-smoke"))
        uid = resp["data"]["uid"]
        for _ in range(1200):
            st = json.loads(post(f"/status/{uid}"))
            if st["status"] in ("finished", "failure"):
                break
            time.sleep(0.1)
        if st["status"] != "finished":
            failures.append(f"mine did not finish: {st}")

        text = post("/metrics")
        families = parse_prometheus(text)
        check_histograms(families)

        # no orphan counters: every registered fault site + retry policy
        for fam in ("fsm_fault_site_calls_total",
                    "fsm_fault_site_injected_total"):
            got = {re.search(r'site="([^"]*)"', k).group(1)
                   for k in families.get(fam, {}) if 'site="' in k}
            missing = set(faultsmod.KNOWN_SITES) - got
            if missing:
                failures.append(f"{fam}: no series for fault site(s) "
                                f"{sorted(missing)}")
        got = {re.search(r'site="([^"]*)"', k).group(1)
               for k in families.get("fsm_retry_attempts_total", {})
               if 'site="' in k}
        missing = set(retrymod.KNOWN_SITES) - got
        if missing:
            failures.append("fsm_retry_attempts_total: no series for retry "
                            f"policy site(s) {sorted(missing)}")
        for fam in ("fsm_jobs_finished_total", "fsm_trace_spans_total",
                    "fsm_planner_launches_total", "fsm_store_op_seconds_count",
                    "fsm_watchdog_guarded_total", "fsm_breaker_state",
                    # ISSUE 9 families: cluster plane, SLO layer, spine
                    "fsm_cluster_replicas", "fsm_cluster_queue_depth",
                    "fsm_cluster_in_flight", "fsm_cluster_leases_held",
                    "fsm_cluster_lease_churn",
                    "fsm_job_e2e_seconds_count",
                    "fsm_job_queue_wait_seconds_count",
                    "fsm_job_exec_seconds_count",
                    "fsm_job_time_to_adoption_seconds_count",
                    "fsm_job_steal_latency_seconds_count",
                    "fsm_trace_spine_writes_total",
                    # ISSUE 10 families: equivalence-class partitioned
                    # mining (parallel/partition.py) — present (zero)
                    # even on an unpartitioned boot
                    "fsm_partition_plans_total",
                    "fsm_partition_exchange_rounds_total",
                    "fsm_partition_cross_bytes_total",
                    "fsm_partition_imbalance_ratio",
                    "fsm_partition_mines_total",
                    # ISSUE 12 families: result-reuse tier
                    # (service/resultcache.py) — present (zero) even
                    # on a boot with [rescache] disabled
                    "fsm_rescache_hits_total",
                    "fsm_rescache_misses_total",
                    "fsm_rescache_coalesced_total",
                    "fsm_rescache_dominated_serves_total",
                    "fsm_rescache_evictions_total",
                    "fsm_rescache_bytes_total",
                    "fsm_rescache_bytes",
                    "fsm_rescache_errors_total",
                    # ISSUE 13 families: elastic control plane
                    # (service/autoscale.py) + weighted-fair admission
                    # (service/fairness.py) — present (zero) even on a
                    # boot with [autoscale]/[fairness] disabled
                    "fsm_autoscale_leader",
                    "fsm_autoscale_desired_replicas",
                    "fsm_autoscale_evals_total",
                    "fsm_autoscale_decisions_total",
                    "fsm_autoscale_drain_directives_total",
                    "fsm_replica_drains_total",
                    "fsm_tenant_queue_depth",
                    "fsm_tenant_admitted_total",
                    "fsm_tenant_sheds_total",
                    "fsm_tenant_dequeued_total",
                    "fsm_rescache_peer_hints_total",
                    # ISSUE 14 families: store-outage survival
                    # (service/storeguard.py) — present (zero) even on
                    # a boot with [storeguard] disabled
                    "fsm_store_health_state",
                    "fsm_storeguard_transitions_total",
                    "fsm_storeguard_probes_total",
                    "fsm_storeguard_spooled_writes_total",
                    "fsm_storeguard_spool_entries",
                    "fsm_storeguard_replays_total",
                    "fsm_storeguard_replayed_writes_total",
                    "fsm_storeguard_dropped_writes_total",
                    "fsm_storeguard_stalls_total",
                    "fsm_storeguard_outage_sheds_total",
                    "fsm_storeguard_ephemeral_admissions_total",
                    # ISSUE 15 family: engine planner
                    # (service/planner.py) — present even when no AUTO
                    # request ever arrived
                    "fsm_engine_selected_total",
                    # ISSUE 17 families: prediction serving plane
                    # (service/predictor.py + ops/rule_trie.py) —
                    # present (zero) before any /predict ever arrives
                    "fsm_predict_requests_total",
                    "fsm_predict_waves_total",
                    "fsm_predict_wave_jobs_count",
                    "fsm_predict_artifact_builds_total",
                    "fsm_predict_artifact_stale_rebuilds_total",
                    "fsm_predict_artifact_evictions_total",
                    "fsm_predict_artifact_cache_hits_total",
                    "fsm_predict_artifact_cache_misses_total",
                    "fsm_predict_artifact_cache_hit_ratio",
                    "fsm_predict_fused_ratio",
                    "fsm_predict_artifact_entries",
                    "fsm_predict_artifact_bytes",
                    "fsm_predict_artifact_age_seconds",
                    "fsm_predict_e2e_seconds_count",
                    "fsm_predict_window_wait_seconds_count",
                    "fsm_predict_exec_seconds_count",
                    # ISSUE 18 families: durable-state integrity plane
                    # (service/integrity.py) — present (zero) before
                    # any corruption is ever seen
                    "fsm_integrity_scans_total",
                    "fsm_integrity_verified_total",
                    "fsm_integrity_legacy_total",
                    "fsm_integrity_corrupt_total",
                    "fsm_integrity_quarantined_total",
                    "fsm_integrity_repaired_total",
                    # ISSUE 19 families: resource attribution plane
                    # (service/usage.py) — present (zero) even on a
                    # boot with [usage] disabled
                    "fsm_usage_device_seconds_total",
                    "fsm_usage_launches_total",
                    "fsm_usage_traffic_units_total",
                    "fsm_usage_avoided_device_seconds_total",
                    "fsm_usage_flushes_total",
                    "fsm_costmodel_family_drift_ratio",
                    # ISSUE 20 families: degraded-topology survival
                    # (service/meshguard.py) — present (zero) even on
                    # a boot with [meshguard] disabled
                    "fsm_mesh_epoch",
                    "fsm_mesh_rows_dead",
                    "fsm_mesh_row_transitions_total",
                    "fsm_mesh_probes_total",
                    "fsm_mesh_replans_total",
                    "fsm_mesh_stale_epoch_refused_total",
                    "fsm_quarantine_jobs_total"):
            if fam not in families:
                failures.append(f"expected family missing: {fam}")

        # no orphan LABEL series either: the new vocabularies are
        # zero-seeded, so a fresh scrape shows every priority class and
        # every spine-write outcome at 0 instead of no-data
        for fam, label, want in (
                ("fsm_job_e2e_seconds_count", "priority",
                 {"high", "normal", "low"}),
                # the tenant label (ISSUE 14 satellite): the default
                # tenant is seeded from boot so per-tenant SLO series
                # exist before any fairness tenant registers
                ("fsm_job_e2e_seconds_count", "tenant", {"default"}),
                ("fsm_job_queue_wait_seconds_count", "priority",
                 {"high", "normal", "low"}),
                ("fsm_job_queue_wait_seconds_count", "tenant",
                 {"default"}),
                ("fsm_job_exec_seconds_count", "tenant", {"default"}),
                ("fsm_service_sheds_total", "priority",
                 {"high", "normal", "low"}),
                ("fsm_trace_spine_writes_total", "outcome",
                 {"ok", "fenced", "error", "spooled"}),
                ("fsm_partition_mines_total", "algo",
                 {"tsr", "spade", "cspade"}),
                ("fsm_rescache_errors_total", "op",
                 {"lookup", "store", "serve", "coalesce", "fanout"}),
                # ISSUE 14 vocabularies (service/storeguard.py)
                ("fsm_storeguard_probes_total", "outcome",
                 {"ok", "unreachable", "error"}),
                ("fsm_storeguard_replays_total", "outcome",
                 {"ok", "refused", "error"}),
                ("fsm_storeguard_stalls_total", "outcome",
                 {"entered", "resumed", "fenced"}),
                ("fsm_storeguard_transitions_total", "state",
                 {"healthy", "flaky", "down"}),
                # ISSUE 15 vocabulary: every routable engine is seeded
                # so "this engine never ran" reads as 0, not no-data
                ("fsm_engine_selected_total", "engine",
                 {"SPADE", "SPADE_TPU", "SPAM", "SPAM_TPU", "TSR",
                  "TSR_TPU"}),
                # ISSUE 17 vocabularies: read-path SLO priority classes
                # + wave fusion modes + request outcomes
                ("fsm_predict_e2e_seconds_count", "priority",
                 {"high", "normal", "low"}),
                ("fsm_predict_window_wait_seconds_count", "priority",
                 {"high", "normal", "low"}),
                ("fsm_predict_exec_seconds_count", "priority",
                 {"high", "normal", "low"}),
                ("fsm_predict_waves_total", "mode", {"fused", "solo"}),
                ("fsm_predict_requests_total", "outcome",
                 {"served", "failure", "no_rules"}),
                # ISSUE 18 vocabularies: every protected surface is
                # seeded on the verify counters, and boot recovery can
                # now end an intent in quarantine
                ("fsm_integrity_verified_total", "surface",
                 {"checkpoint", "journal", "rescache", "spine",
                  "lease"}),
                ("fsm_integrity_corrupt_total", "surface",
                 {"checkpoint", "journal", "rescache", "spine",
                  "lease"}),
                # ISSUE 20 grows the recovery vocabulary: an intent can
                # settle as bitrot ("corrupt") now, and the mesh /
                # crash-loop quarantine families seed their transitions
                ("fsm_recovery_jobs_total", "outcome",
                 {"cleared", "resumed", "failed", "quarantined",
                  "corrupt"}),
                ("fsm_mesh_row_transitions_total", "to",
                 {"healthy", "suspect", "dead"}),
                ("fsm_mesh_probes_total", "outcome", {"ok", "failed"}),
                ("fsm_quarantine_jobs_total", "outcome",
                 {"poisoned", "refused", "released"}),
                # ISSUE 19 vocabularies: the usage bill's tenant label
                # is seeded with the default tenant from boot, and the
                # per-family cost-model drift gauge seeds every
                # dispatch family — "never dispatched" reads as 0
                ("fsm_usage_device_seconds_total", "tenant",
                 {"default"}),
                ("fsm_usage_launches_total", "tenant", {"default"}),
                ("fsm_usage_traffic_units_total", "tenant",
                 {"default"}),
                ("fsm_usage_avoided_device_seconds_total", "tenant",
                 {"default"}),
                ("fsm_usage_flushes_total", "tenant", {"default"}),
                ("fsm_costmodel_family_drift_ratio", "family",
                 {"tsr-eval", "tsr-fused", "tsr-resident", "spam",
                  "predict"}),
                ("fsm_predict_e2e_seconds_count", "tenant",
                 {"default"})):
            got = {m.group(1) for k in families.get(fam, {})
                   for m in [re.search(rf'{label}="([^"]*)"', k)] if m}
            missing = want - got
            if missing:
                failures.append(f"{fam}: label vocabulary not seeded "
                                f"({label}={sorted(missing)})")

        dump = json.loads(post(f"/admin/trace/{uid}"))
        sites = [s["site"] for s in dump.get("spans", ())]
        for want in ("job", "job.mine", "tsr.dispatch", "tsr.readback",
                     # lifecycle marks ride the merged spine timeline
                     "lifecycle.admitted", "lifecycle.started",
                     "lifecycle.settled"):
            if want not in sites:
                failures.append(f"trace dump missing span site {want!r} "
                                f"(got {sorted(set(sites))})")
        if not dump.get("merged"):
            failures.append("cluster-mode trace dump is not the merged "
                            "spine timeline")
        for s in dump.get("spans", ()):
            if s["site"] == "tsr.launch" and (
                    "predicted_s" not in s.get("attrs", {})
                    or s.get("duration_s") is None):
                failures.append(f"launch span without predicted/measured "
                                f"seconds: {s}")

        # ---- /admin/cluster: aggregated heartbeat view from any replica
        cluster = json.loads(post("/admin/cluster"))
        if not cluster.get("enabled"):
            failures.append(f"/admin/cluster reports disabled: {cluster}")
        totals = cluster.get("totals", {})
        if totals.get("replicas", 0) < 1:
            failures.append(f"/admin/cluster sees no live replicas: "
                            f"{totals}")
        for key in ("queued", "running", "free", "held", "sheds",
                    "lease_churn"):
            if key not in totals:
                failures.append(f"/admin/cluster totals missing {key!r}")

        # ---- /admin/slo: the finished mine must appear in its
        # priority's sliding window with a full quantile row
        slo = json.loads(post("/admin/slo"))
        row = slo.get("priorities", {}).get("normal", {})
        e2e = row.get("e2e", {})
        if e2e.get("count", 0) < 1:
            failures.append(f"/admin/slo saw no finished job: {slo}")
        elif not all(k in e2e for k in ("p50", "p95", "p99")):
            failures.append(f"/admin/slo e2e row incomplete: {e2e}")
        qw = row.get("queue_wait", {})
        if qw.get("count", 0) < 1:
            failures.append(f"/admin/slo queue_wait missing: {row}")
    finally:
        srv.master.shutdown()
        srv.shutdown()
    if failures:
        print("obs_smoke: FAILED:", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    n = sum(len(v) for v in families.values())
    print(f"obs_smoke: /metrics parsed ({len(families)} families, "
          f"{n} samples), no orphan counters, trace dump complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
