#!/usr/bin/env bash
# Observability smoke — the /metrics + flight-recorder companion to
# verify_t1.sh / bench_smoke.sh / chaos_smoke.sh.  Boots the service
# with tracing on, mines once, then asserts GET /metrics parses as
# Prometheus text exposition, every registered fault site and retry
# policy has a matching fsm_* series (no orphan counters), and the
# job's /admin/trace dump carries the launch spans with predicted-vs-
# measured seconds.
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/obs_smoke.py "$@"
