#!/usr/bin/env python
"""Elastic control plane smoke (ISSUE 13): three real service
processes on one MiniRedis, driving the whole loop end to end.

The CI companion to replica_smoke for service/autoscale.py +
service/fairness.py:

1. boots replicas A, B, C with [cluster] + [fairness] + [autoscale]
   (min_replicas = 3 so the controller cannot scale the smoke's own
   fleet down from under it; the scale-DOWN path is forced in step 4);
2. FAIRNESS: a flooding tenant submits past its per-tenant cap on A —
   the overflow sheds 429 with a tenant-specific Retry-After while a
   trickle tenant's jobs admit, finish, and match the oracle (the
   flood cannot occupy the quiet tenant's slots);
3. SCALE-UP: a fleet-wide backlog (queued/worker past the threshold,
   held past the hysteresis window) makes the leader publish a
   desired-replica-count record — /admin/autoscale on any replica
   shows the decision with desired = replicas + 1;
4. FORCED SCALE-DOWN: /admin/drain?exit=1 on C while it holds queued
   jobs — C stops admitting, the survivors steal its backlog, C's
   process EXITS cleanly, every job finishes with byte-exact oracle
   parity (zero lost, zero duplicated), and the fleet view shrinks to
   two replicas;
5. asserts the fsm_autoscale_* / fsm_tenant_* / fsm_replica_drains_*
   metric families are live and every journal/lease/marker is settled.

Usage: scripts/autoscale_smoke.sh   (pins JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import json
import os
import pathlib
import re
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.parse
import urllib.request

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))

BOOT_TIMEOUT_S = 180.0
DRILL_TIMEOUT_S = 300.0


def log(msg):
    print(f"autoscale_smoke: {msg}", flush=True)


def post(port, endpoint, **params):
    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{port}{endpoint}"
    try:
        with urllib.request.urlopen(url, data=data, timeout=60) as resp:
            return resp.status, dict(resp.headers), \
                json.loads(resp.read().decode())
    except urllib.error.HTTPError as err:
        return err.code, dict(err.headers), json.loads(err.read().decode())


def scrape(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=60) as resp:
        return resp.read().decode()


def series_sum(text, family, label_filter=""):
    total, seen = 0.0, False
    for line in text.splitlines():
        m = re.match(rf"^{re.escape(family)}(\{{[^}}]*\}})?\s+(\S+)$", line)
        if m and label_filter in (m.group(1) or ""):
            total += float(m.group(2))
            seen = True
    assert seen, f"{family} missing from /metrics"
    return total


def boot_service(cfg_path, env, name):
    child = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import sys\n"
        f"sys.argv = ['app', '--config', {str(cfg_path)!r}]\n"
        "from spark_fsm_tpu.service.app import main\n"
        "main()\n"
    )
    proc = subprocess.Popen([sys.executable, "-c", child], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    port = replica = None
    deadline = time.time() + BOOT_TIMEOUT_S
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise AssertionError(
                f"replica {name} died at boot (rc={proc.poll()})")
        if line.startswith("cluster replica "):
            replica = line.split()[2]
        if "spark_fsm_tpu service on http://" in line:
            port = int(line.rsplit(":", 1)[1])
            break
    assert port is not None, f"no boot line from {name} within the timeout"
    assert replica is not None, f"no cluster-replica line from {name}"
    return proc, port, replica


def submit(port, uid, spmf_text, tenant, **extra):
    params = {"uid": uid, "algorithm": "SPADE_TPU", "source": "INLINE",
              "sequences": spmf_text, "support": "0.05",
              "tenant": tenant}
    params.update(extra)
    return post(port, "/train", **params)


def await_finished(port, uid, timeout=DRILL_TIMEOUT_S):
    deadline = time.time() + timeout
    status = None
    while time.time() < deadline:
        _, _, body = post(port, f"/status/{uid}")
        status = body.get("status")
        if status in ("finished", "failure"):
            return status, body
        time.sleep(0.1)
    raise AssertionError(f"{uid} never terminal (last {status!r})")


def main():
    from test_redis_store import MiniRedis  # noqa: E402 (tests/ on path)

    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.data.synth import synthetic_db
    from spark_fsm_tpu.data.vertical import abs_minsup
    from spark_fsm_tpu.models.oracle import mine_spade
    from spark_fsm_tpu.service.model import deserialize_patterns
    from spark_fsm_tpu.service.resp import RespClient
    from spark_fsm_tpu.utils.canonical import patterns_text

    mini = MiniRedis()
    log(f"MiniRedis on port {mini.port}")
    client = RespClient(port=mini.port)

    tmp = tempfile.mkdtemp(prefix="autoscale_smoke_")
    cfg_path = os.path.join(tmp, "config.json")
    with open(cfg_path, "w") as fh:
        json.dump({
            "service": {"port": 0, "miner_workers": 1,
                        "queue_depth": 64},
            "store": {"backend": "redis", "host": "127.0.0.1",
                      "port": mini.port},
            "cluster": {"enabled": True, "lease_ttl_s": 2.0,
                        "recover_every_s": 0.5},
            "observability": {"trace": True, "spine_flush_spans": 8},
            "fairness": {"enabled": True, "tenant_depth": 4},
            # min_replicas = live fleet: the controller may decide UP
            # but never drain the smoke's own replicas; the down path
            # is driven explicitly via /admin/drain below
            "autoscale": {"enabled": True, "min_replicas": 3,
                          "max_replicas": 4,
                          "up_queue_per_worker": 1.0,
                          "hold_s": 0.5, "cooldown_s": 2.0,
                          "decide_every_s": 0.25,
                          "leader_ttl_s": 1.0,
                          "drain_timeout_s": 120.0},
        }, fh)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    procs = {}
    proc_a, port_a, rep_a = boot_service(cfg_path, env, "A")
    procs["A"] = proc_a
    log(f"replica A {rep_a} on port {port_a} (pid {proc_a.pid})")
    proc_b, port_b, rep_b = boot_service(cfg_path, env, "B")
    procs["B"] = proc_b
    log(f"replica B {rep_b} on port {port_b} (pid {proc_b.pid})")
    proc_c, port_c, rep_c = boot_service(cfg_path, env, "C")
    procs["C"] = proc_c
    log(f"replica C {rep_c} on port {port_c} (pid {proc_c.pid})")
    ports = {rep_a: port_a, rep_b: port_b, rep_c: port_c}
    try:
        # wait for the fleet to fully form (every heartbeat visible)
        # before loading it — the leader's decisions are computed from
        # this view
        deadline = time.time() + 30.0
        while time.time() < deadline:
            _, _, cluster = post(port_a, "/admin/cluster")
            if cluster.get("totals", {}).get("replicas") == 3:
                break
            time.sleep(0.25)
        assert cluster["totals"]["replicas"] == 3, cluster
        db = synthetic_db(seed=71, n_sequences=200, n_items=12,
                          mean_itemsets=3.0, mean_itemset_size=1.3)
        text = format_spmf(db)
        want = patterns_text(mine_spade(db, abs_minsup(0.05, len(db))))

        # ---- 1. fairness: flood tenant past its cap on A; the quiet
        # tenant's trickle must admit and finish regardless
        admitted, sheds = [], 0
        for i in range(10):
            code, headers, body = submit(port_a, f"flood-{i}", text,
                                         "flood")
            if code == 429:
                sheds += 1
                err = body.get("data", {}).get("error", "")
                assert "tenant 'flood'" in err, body
                assert int(headers.get("Retry-After", "0")) >= 1
            else:
                assert code == 200 and body["status"] == "started", body
                admitted.append(f"flood-{i}")
        assert sheds >= 1, "flood tenant never hit its cap"
        quiet = []
        for i in range(2):
            code, _, body = submit(port_a, f"quiet-{i}", text, "quiet")
            assert code == 200 and body["status"] == "started", \
                (code, body)
            quiet.append(f"quiet-{i}")
        log(f"fairness ok: flood admitted {len(admitted)}, shed "
            f"{sheds} with tenant Retry-After; quiet tenant admitted "
            f"despite the flood")

        # ---- 2. scale-up decision under sustained fleet backlog
        extra = []
        for name, port in (("A", port_a), ("B", port_b), ("C", port_c)):
            for i in range(4):
                uid = f"load-{name}-{i}"
                code, _, body = submit(port, uid, text,
                                       f"bulk{name}")
                if code == 200 and body["status"] == "started":
                    extra.append(uid)
        decision = None
        deadline = time.time() + 60.0
        while time.time() < deadline and decision is None:
            for port in (port_a, port_b, port_c):
                _, _, a = post(port, "/admin/autoscale")
                if a.get("enabled") and a.get("desired") \
                        and a["desired"].get("dir") == "up":
                    decision = a["desired"]
                    break
            time.sleep(0.2)
        assert decision is not None, "no scale-up decision published"
        # desired = observed live replicas + 1; the observation may
        # predate the last heartbeat by one cache window, so pin the
        # RELATIVE contract and the bound, not an absolute count
        assert decision["desired"] == decision["replicas"] + 1, decision
        assert 2 <= decision["replicas"] <= 3 \
            and decision["desired"] <= 4, decision
        assert decision["leader"] in (rep_a, rep_b, rep_c)
        log(f"scale-up ok: leader {decision['leader']} published "
            f"desired={decision['desired']} ({decision['reason']!r})")

        # let the backlog drain before the scale-down phase
        for uid in admitted + quiet + extra:
            status, body = await_finished(port_b, uid)
            assert status == "finished", (uid, body)
        got = deserialize_patterns(
            post(port_b, "/get/patterns", uid="quiet-0")[2]["data"]
            ["patterns"])
        assert patterns_text(got) == want, "quiet tenant parity broke"
        log(f"backlog drained: {len(admitted + quiet + extra)} jobs "
            f"finished, quiet-tenant oracle parity holds")

        # ---- 3. forced scale-down: C drains with queued jobs; the
        # survivors steal them; C's process exits cleanly
        drill = []
        for i in range(4):
            code, _, body = submit(port_c, f"drain-{i}", text, "quiet",
                                   priority="low")
            assert code == 200 and body["status"] == "started", body
            drill.append(f"drain-{i}")
        code, _, body = post(port_c, "/admin/drain", exit="1")
        assert code == 200 and body["status"] == "draining", body
        rc = None
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline:
            rc = proc_c.poll()
            if rc is not None:
                break
            time.sleep(0.2)
        assert rc == 0, f"drained replica C exited rc={rc}"
        log(f"scale-down ok: C drained and exited rc=0")
        for uid in drill:
            status, body = await_finished(port_a, uid)
            assert status == "finished", (uid, body)
            got = deserialize_patterns(
                post(port_a, "/get/patterns", uid=uid)[2]["data"]
                ["patterns"])
            assert patterns_text(got) == want, f"{uid} parity broke"
        log("drain parity ok: every queued job finished on the "
            "survivors, byte-exact oracle parity, zero lost/duplicated")

        # the fleet view shrinks once C's heartbeat record expires
        deadline = time.time() + 30.0
        replicas = None
        while time.time() < deadline:
            _, _, cluster = post(port_a, "/admin/cluster")
            replicas = cluster.get("totals", {}).get("replicas")
            if replicas == 2:
                break
            time.sleep(0.25)
        assert replicas == 2, f"fleet view still shows {replicas}"

        # ---- 4. bookkeeping + live metric families
        assert client.keys("fsm:journal:*") == []
        assert client.keys("fsm:admission:*") == []
        text_a = scrape(port_a)
        for fam in ("fsm_autoscale_leader",
                    "fsm_autoscale_desired_replicas",
                    "fsm_autoscale_evals_total",
                    "fsm_autoscale_decisions_total",
                    "fsm_tenant_queue_depth",
                    "fsm_tenant_admitted_total",
                    "fsm_tenant_sheds_total",
                    "fsm_tenant_dequeued_total",
                    "fsm_replica_drains_total",
                    "fsm_rescache_peer_hints_total"):
            series_sum(text_a, fam)
        ups = series_sum(text_a, "fsm_autoscale_decisions_total",
                         'dir="up"')
        # A alone: every flood submit (and so every flood shed) landed
        # there; B/C only have a tenant="flood" series if they happened
        # to STEAL a flood job (tenants seed on first resolve) — a
        # cross-replica sum would flake on steal placement
        sheds_m = series_sum(text_a, "fsm_tenant_sheds_total",
                             'tenant="flood"')
        assert sheds_m >= sheds, "tenant shed counter missed the flood"
        log(f"metrics ok: fsm_autoscale_*/fsm_tenant_* families live "
            f"(up decisions on A's view: {int(ups)}, flood sheds "
            f"{int(sheds_m)})")
    finally:
        for name, proc in procs.items():
            if proc.poll() is None:
                proc.send_signal(__import__("signal").SIGTERM)
        for name, proc in procs.items():
            try:
                proc.wait(60)
            except subprocess.TimeoutExpired:
                proc.kill()
        mini.close()
    log("PASS")


if __name__ == "__main__":
    main()
