#!/usr/bin/env bash
# Multi-replica failover smoke (ISSUE 8): two real service processes on
# one MiniRedis — work stealing of queued jobs, kill -9 of the replica
# holding a checkpointed mine, lease-expiry adoption by the survivor
# with oracle parity, and settled journals/leases afterwards.
#
# Runs under a hard timeout: a wedged boot/adoption must fail the smoke,
# not hang CI.
cd "$(dirname "$0")/.."
set -o pipefail
timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/replica_smoke.py
rc=$?
if [ $rc -ne 0 ]; then
    echo "REPLICA_SMOKE_FAILED rc=$rc"
fi
exit $rc
