#!/usr/bin/env bash
# Usage-metering smoke — the ISSUE 19 companion to obs_smoke.sh and
# rescache_smoke.sh.  Boots the service with [usage] + [fusion] +
# [fairness] + [rescache] on, floods two tenants with TSR mines plus a
# rescache hot set, then asserts the per-tenant bill on /admin/usage
# (est + measured device-seconds, launches, durable ledger rows,
# avoided-cost on the hot tenant) and the conservation invariant:
# per-tenant fsm_usage_launches_total sums EXACTLY to the broker's
# dispatch counters on /metrics.
cd "$(dirname "$0")/.."
exec timeout -k 30 600 env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/usage_smoke.py "$@"
