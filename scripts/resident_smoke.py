#!/usr/bin/env python
"""Seconds-scale smoke of the resident-frontier TSR path (ISSUE 7).

Runs the config-3d miniature twice — resident routing on service
defaults (the planner must pick the resident path) and pinned off (the
host-loop reference) — and asserts:

- the PINNED resident dispatch shape: 3 kernel launches (one prep + two
  while_loop segments), the committed resident-wave/deferred counters,
  zero spills/handoffs (the whole ladder completes on device);
- exact rule parity between the two routes (the oracle-parity claim at
  smoke scale);
- the ``fsm_tsr_resident_*`` metric families advanced (the
  observability satellite's counter surface).

Counters are deterministic on the CPU backend (the shell pins
JAX_PLATFORMS=cpu), so every comparison is exact — a resident-routing
or wave-policy regression fails here in seconds instead of surfacing in
an hours-long hardware BENCH_SCALE session.

Usage: scripts/resident_smoke.sh
"""

from __future__ import annotations

import sys

# the committed resident dispatch shape of the 3d miniature (must match
# tests/test_launch_budget.py::test_tsr_3d_resident_launch_budget and
# the bench_smoke "3d" row)
EXPECT = {
    "kernel_launches": 3,
    "resident_segments": 2,
    "resident_waves": 283,
    "resident_deferred": 6,
    "evaluated": 119066,
    "traffic_units": 531200,
}


def main() -> int:
    from spark_fsm_tpu.data.synth import kosarak_like
    from spark_fsm_tpu.data.vertical import build_vertical
    from spark_fsm_tpu.models.tsr import TsrTPU
    from spark_fsm_tpu.ops import ragged_batch as RB
    from spark_fsm_tpu.utils import obs

    RB.set_overhead_calibration(False)
    db = kosarak_like(scale=0.002, fast=True)
    vdb = build_vertical(db, min_item_support=1)

    eng = TsrTPU(vdb, 100, 0.5, max_side=None)  # service default: auto
    rules = eng.mine()
    st = eng.stats
    failures = []
    if not st.get("resident"):
        failures.append("service-default 3d miniature did not route to "
                        "the resident path")
    for key, want in EXPECT.items():
        if st.get(key) != want:
            failures.append(f"{key} = {st.get(key)}, committed {want}")
    for key in ("resident_spills", "resident_handoffs",
                "resident_fallbacks"):
        if key in st:
            failures.append(f"unexpected {key} = {st[key]} (the miniature "
                            "ladder must complete on device)")

    host = TsrTPU(vdb, 100, 0.5, max_side=None, resident="never")
    if host.mine() != rules:
        failures.append("resident rule set differs from the host loop")

    # the metric families must have actually ADVANCED, not merely exist
    # (counters zero-seed at registration, so substring presence alone
    # would pass with the count_* calls deleted): parse each family's
    # unlabelled sample and require at least this process's mine
    metrics = obs.REGISTRY.render_prometheus()
    values = {}
    for line in metrics.splitlines():
        if line.startswith("fsm_tsr_resident_") and " " in line:
            name, _, val = line.rpartition(" ")
            try:
                values[name] = float(val)
            except ValueError:
                pass
    for fam, floor in (("fsm_tsr_resident_segments_total",
                        EXPECT["resident_segments"]),
                       ("fsm_tsr_resident_waves_total",
                        EXPECT["resident_waves"]),
                       ("fsm_tsr_resident_readback_bytes_total", 1)):
        if values.get(fam, 0) < floor:
            failures.append(f"metric {fam} = {values.get(fam)} did not "
                            f"advance to >= {floor}")

    if failures:
        print("resident_smoke: FAILED:", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    print(f"resident_smoke: resident 3d miniature matches the committed "
          f"dispatch shape ({st['kernel_launches']} launches, "
          f"{st['resident_waves']} waves, parity with the host loop)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
