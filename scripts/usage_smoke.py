#!/usr/bin/env python
"""Usage-metering smoke: 2-tenant flood with a rescache hot set, then
assert the per-tenant bill on /admin/usage and the conservation
invariant off /metrics.

The CI companion to obs_smoke/rescache_smoke for the resource
attribution plane (ISSUE 19, service/usage.py): it boots the real HTTP
service with [usage] + [fusion] + [fairness] + [rescache] on, then

- floods two fairness tenants (``acme``, ``globex``) with TSR mines —
  fusion on means every eval dispatch routes through the broker, whose
  launch counter is the conservation ground truth;
- re-submits acme's hot dataset after completion: an EXACT cache hit
  that must credit acme with AVOIDED device-seconds priced from the
  cached entry's recorded usage block;
- asserts /admin/usage serves both tenant rows (estimated + measured
  device-seconds, launches, traffic units, the durable ledger
  sub-block) with acme's avoided-cost > 0, every finished job carries
  a ``usage`` block in its /status stats, and the top-jobs table is
  populated;
- cross-checks CONSERVATION on /metrics: per-tenant
  fsm_usage_launches_total sums EXACTLY to fsm_fusion_launches_total,
  and per-tenant traffic units to the broker's tally — no work
  invented, none lost;
- asserts the per-family cost-model drift gauges and the fsm_usage_*
  families are live (zero-seeded vocabularies, flushes recorded).

Usage: scripts/usage_smoke.sh   (pins JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import json
import sys
import time
import urllib.parse
import urllib.request


def main() -> int:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from spark_fsm_tpu import config as cfgmod
    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.data.synth import synthetic_db
    from spark_fsm_tpu.service.app import serve_background

    cfgmod.set_config(cfgmod.parse_config({
        "usage": {"enabled": True, "flush_every_s": 0.0},
        "fusion": {"enabled": True, "window_ms": 30.0},
        "fairness": {"enabled": True,
                     "weights": {"acme": 2.0, "globex": 1.0}},
        "rescache": {"enabled": True},
    }))
    srv = serve_background()
    port = srv.server_port

    def post(ep, **params):
        data = urllib.parse.urlencode(params).encode()
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{ep}",
                                    data=data, timeout=120) as r:
            return r.read().decode()

    def train(uid, text, tenant, **params):
        d = {"algorithm": "TSR_TPU", "source": "INLINE",
             "sequences": text, "k": "8", "minconf": "0.4",
             "max_side": "2", "uid": uid, "tenant": tenant}
        d.update(params)
        resp = json.loads(post("/train", **d))
        assert resp["status"] != "failure", resp
        return resp

    def wait(uid, timeout=240.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = json.loads(post(f"/status/{uid}"))
            if st["status"] in ("finished", "failure"):
                return st
            time.sleep(0.05)
        raise TimeoutError(f"job {uid} never finished")

    def series(text, fam):
        """{label-string: value} for one metric family."""
        out = {}
        for line in text.splitlines():
            if line.startswith(fam + " "):
                out[""] = float(line.rsplit(" ", 1)[1])
            elif line.startswith(fam + "{"):
                labels = line[len(fam) + 1:line.index("}")]
                out[labels] = float(line.rsplit(" ", 1)[1])
        return out

    failures = []
    try:
        dbs = {uid: synthetic_db(seed=seed, n_sequences=70, n_items=9,
                                 mean_itemsets=3.0, mean_itemset_size=1.2)
               for uid, seed in (("acme-hot", 81), ("acme-b", 82),
                                 ("glx-a", 83), ("glx-b", 84))}
        plan = [("acme-hot", "acme"), ("acme-b", "acme"),
                ("glx-a", "globex"), ("glx-b", "globex")]
        for uid, tenant in plan:
            train(uid, format_spmf(dbs[uid]), tenant)
        stats_by_uid = {}
        for uid, _ in plan:
            st = wait(uid)
            if st["status"] != "finished":
                failures.append(f"{uid} did not finish: {st}")
            stats_by_uid[uid] = json.loads(
                st.get("data", {}).get("stats", "{}"))

        # every finished job carries its attribution vector
        for uid, stats in stats_by_uid.items():
            u = stats.get("usage")
            if not u or u.get("launches", 0) < 1:
                failures.append(f"{uid} /status stats missing a usage "
                                f"block with launches: {u}")

        # the hot re-submit: exact rescache hit -> avoided-cost credit
        train("acme-hit", format_spmf(dbs["acme-hot"]), "acme")
        st = wait("acme-hit")
        hs = json.loads(st.get("data", {}).get("stats", "{}"))
        if hs.get("served_from_cache") != "exact":
            failures.append(f"hot re-submit not an exact hit: {hs}")

        admin = json.loads(post("/admin/usage"))
        if not admin.get("enabled"):
            failures.append(f"/admin/usage not enabled: {admin}")
        tenants = admin.get("tenants", {})
        for t in ("acme", "globex"):
            row = tenants.get(t)
            if not row:
                failures.append(f"/admin/usage missing tenant {t}")
                continue
            for f in ("device_seconds_est", "device_seconds_measured",
                      "launches", "traffic_units"):
                if not row.get(f, 0) > 0:
                    failures.append(f"tenant {t} {f} not > 0: {row}")
            led = row.get("ledger")
            if not led or not led.get("totals"):
                failures.append(f"tenant {t} has no durable ledger row")
            elif led["totals"].get("launches") != row.get("launches"):
                failures.append(
                    f"tenant {t} ledger launches "
                    f"{led['totals'].get('launches')} != live rollup "
                    f"{row.get('launches')}")
        if not tenants.get("acme", {}).get("avoided_device_seconds", 0) > 0:
            failures.append("acme has no avoided-cost credit after the "
                            "exact hit")
        if not admin.get("top_jobs"):
            failures.append("/admin/usage top_jobs empty")
        if admin.get("totals", {}).get("launches") != \
                sum(r.get("launches", 0) for r in tenants.values()):
            failures.append("/admin/usage totals do not sum the tenant "
                            "rows")

        # ---- conservation: per-tenant attribution == dispatch counters
        mtext = post("/metrics")
        usage_launches = series(mtext, "fsm_usage_launches_total")
        fusion_launches = series(mtext, "fsm_fusion_launches_total")
        got = sum(usage_launches.values())
        want = sum(fusion_launches.values())
        if got != want:
            failures.append(f"CONSERVATION BROKEN: sum fsm_usage_"
                            f"launches_total = {got} != fsm_fusion_"
                            f"launches_total = {want}")
        fstats = json.loads(post("/admin/stats"))["fusion"]
        usage_traffic = sum(
            series(mtext, "fsm_usage_traffic_units_total").values())
        if usage_traffic != fstats.get("traffic_units"):
            failures.append(f"CONSERVATION BROKEN: usage traffic "
                            f"{usage_traffic} != broker traffic "
                            f"{fstats.get('traffic_units')}")

        # ---- metric families live, vocabularies zero-seeded
        for fam in ("fsm_usage_device_seconds_total",
                    "fsm_usage_launches_total",
                    "fsm_usage_traffic_units_total",
                    "fsm_usage_avoided_device_seconds_total",
                    "fsm_usage_flushes_total"):
            vals = series(mtext, fam)
            if not vals:
                failures.append(f"/metrics missing family {fam}")
                continue
            for t in ("default", "acme", "globex"):
                if not any(f'tenant="{t}"' in k for k in vals):
                    failures.append(f"{fam} missing tenant={t} series")
        if sum(series(mtext, "fsm_usage_flushes_total").values()) < 1:
            failures.append("no durable ledger flush recorded")
        fam_drift = series(mtext, "fsm_costmodel_family_drift_ratio")
        for f in ("tsr-eval", "tsr-fused", "tsr-resident", "spam",
                  "predict"):
            if not any(f'family="{f}"' in k for k in fam_drift):
                failures.append(f"fsm_costmodel_family_drift_ratio "
                                f"missing family={f}")
        if not any(v > 0 for k, v in fam_drift.items()
                   if 'family="tsr-eval"' in k
                   or 'family="tsr-fused"' in k):
            failures.append("no tsr dispatch family recorded a drift "
                            "sample")

        # zero stuck uids: every journal intent settled
        leftover = srv.master.store.keys("fsm:journal:")
        if leftover:
            failures.append(f"journal intents leaked: {leftover}")
    finally:
        srv.master.shutdown()
        srv.shutdown()
    if failures:
        print("usage_smoke: FAILED:", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    print("usage_smoke: 2-tenant flood billed per tenant, conservation "
          "exact vs dispatch counters, avoided-cost credited on the hot "
          "set, ledger + families live")
    return 0


if __name__ == "__main__":
    sys.exit(main())
