#!/usr/bin/env bash
# Fleet-supervisor chaos smoke (ISSUE 14 satellite / ROADMAP item 4):
# kill scripts/fleet.py mid-scale-up, restart it with --initial 0, and
# assert the fleet converges to the published desired count from the
# fsm:replica:* heartbeats — zero lost or duplicated jobs, no duplicate
# fleet booted next to the orphaned replicas.  Hard timeout so a wedged
# fleet fails loudly instead of hanging CI.
cd "$(dirname "$0")/.."
exec timeout -k 15 900 env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/fleet_smoke.py "$@"
