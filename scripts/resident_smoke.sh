#!/usr/bin/env bash
# Resident-frontier smoke — seconds-scale proof that the service-default
# 3d miniature routes to the resident path at the committed dispatch
# shape (launches/waves/deferred pinned) with host-loop parity.
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/resident_smoke.py "$@"
