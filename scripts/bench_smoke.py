#!/usr/bin/env python
"""Seconds-scale launch/traffic smoke of the BENCH_SCALE hot configs.

Runs shaped miniatures of configs 3 (full-Kosarak TSR, max_side=2),
3d (same, unlimited sides — the service default, routed to the
RESIDENT-FRONTIER path since ISSUE 7), 3r (3d with resident routing
pinned off — the host-loop reference) and 5 (incremental streaming)
and diffs the DISPATCH-SHAPE counters — ``kernel_launches``,
``evaluated``, ``traffic_units``, and the 3d row's resident-wave
counters — against the committed expectations in
``scripts/bench_smoke_expect.json``.  Walls are reported but never
compared: the point is that launch-packing / candidate-generation
regressions fail in seconds on any machine (CI, laptop) instead of
surfacing weeks later in an hours-long BENCH_SCALE session on real
hardware.  The TSR rows double as the dryrun-scale record of the
super-batch collapse (pre-superbatch policy on the same 3d miniature:
49 launches; committed: 10).

Counters are deterministic on the CPU backend, so the diff is EXACT.
``--update`` rewrites the expectations (do this only for a deliberate
dispatch-policy change, and say so in the commit).

Usage: scripts/bench_smoke.sh [--update]   (pins JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import json
import os
import sys
import time

EXPECT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "bench_smoke_expect.json")

COMPARED = ("kernel_launches", "evaluated", "traffic_units",
            "pruned_conf", "superbatches", "resident_rounds",
            "resident_segments", "resident_waves", "resident_deferred",
            "resident_spills", "resident_handoffs",
            "resident_fallbacks", "resident_readback_bytes")


def smoke_tsr(max_side, trace_id=None, resident="auto"):
    from spark_fsm_tpu.data.synth import kosarak_like
    from spark_fsm_tpu.data.vertical import build_vertical
    from spark_fsm_tpu.models.tsr import TsrTPU, resident_counters

    db = kosarak_like(scale=0.002, fast=True)
    vdb = build_vertical(db, min_item_support=1)
    t0 = time.monotonic()
    eng = TsrTPU(vdb, 100, 0.5, max_side=max_side, resident=resident)
    if trace_id is not None:
        from spark_fsm_tpu.utils import obs

        with obs.trace(trace_id, engine="tsr", max_side=max_side):
            rules = eng.mine()
    else:
        rules = eng.mine()
    out = {
        "kernel_launches": eng.stats["kernel_launches"],
        "evaluated": eng.stats["evaluated"],
        "traffic_units": eng.stats["traffic_units"],
        "rules": len(rules),
        "pruned_conf": eng.stats.get("pruned_conf", 0),
        "superbatches": eng.stats.get("superbatches", 0),
        "wall_s": round(time.monotonic() - t0, 2),
    }
    out.update(resident_counters(eng.stats))
    return out


def smoke_stream():
    from spark_fsm_tpu.data.synth import msnbc_like
    from spark_fsm_tpu.streaming.incremental import IncrementalWindowMiner

    db = msnbc_like(scale=0.002, fast=True)
    per = len(db) // 4
    t0 = time.monotonic()
    wm = IncrementalWindowMiner(0.02, max_batches=2)
    for i in range(4):
        wm.push(db[i * per:(i + 1) * per])
    return {
        "patterns": len(wm.patterns),
        "tracked_nodes": wm.stats["tracked_nodes"],
        "sweep_candidates": wm.stats["sweep_candidates"],
        "wall_s": round(time.monotonic() - t0, 2),
    }


def main() -> int:
    update = "--update" in sys.argv[1:]
    # pin the planner's per-launch overhead to the committed constant:
    # the live drift recalibration (ops/ragged_batch.drift_factor) is
    # machine-dependent by design, and these counters must be EXACT on
    # any machine
    from spark_fsm_tpu.ops import ragged_batch as RB

    RB.set_overhead_calibration(False)
    rows = {
        "3": smoke_tsr(2),
        "3d": smoke_tsr(None),  # service default -> resident path
        "3r": smoke_tsr(None, resident="never"),  # host-loop reference
        "5": smoke_stream(),
    }
    print(json.dumps(rows, indent=2))
    if update:
        with open(EXPECT_PATH, "w") as fh:
            json.dump(rows, fh, indent=2)
            fh.write("\n")
        print(f"bench_smoke: expectations rewritten -> {EXPECT_PATH}")
        return 0
    try:
        with open(EXPECT_PATH) as fh:
            expect = json.load(fh)
    except OSError:
        sys.exit(f"bench_smoke: no committed expectations at {EXPECT_PATH}"
                 " (run with --update once, then commit the file)")
    failures = []
    for cfg, row in rows.items():
        for key, want in expect.get(cfg, {}).items():
            if key == "wall_s" or key not in row:
                continue  # walls are machine-dependent; never compared
            if cfg == "5" and key not in ("patterns", "tracked_nodes",
                                          "sweep_candidates"):
                continue
            if cfg != "5" and key not in COMPARED + ("rules",):
                continue
            if row[key] != want:
                failures.append(f"config {cfg}: {key} = {row[key]}, "
                                f"committed {want}")
    if failures:
        print("bench_smoke: DISPATCH-SHAPE DRIFT (deliberate? re-run "
              "with --update and commit):", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    print("bench_smoke: all counters match the committed expectations")
    return xcheck_trace(rows["3"])


def xcheck_trace(untraced_row) -> int:
    """Cross-check guard: re-run the config-3 miniature WITH tracing and
    require (a) the launch count derived from flight-recorder spans to
    equal the engine's dispatch-shape counter (every kernel_launches
    increment — prep builds + planned launches — opens exactly one
    tsr.prep/tsr.launch span; silent instrumentation drift on either
    side breaks the equality), and (b) the traced run's dispatch
    counters to match the untraced row byte-for-byte (tracing must
    OBSERVE the dispatch policy, never perturb it)."""
    from spark_fsm_tpu.utils import obs

    obs.configure_tracing(True, max_spans=1 << 16, max_jobs=4)
    try:
        row = smoke_tsr(2, trace_id="bench:xcheck")
    finally:
        obs.configure_tracing(False)
    dump = obs.trace_dump("bench:xcheck")
    failures = []
    if dump is None or dump["dropped_spans"]:
        failures.append(f"trace missing or lossy: {dump and dump['dropped_spans']}")
    else:
        span_launches = sum(1 for s in dump["spans"]
                            if s["site"] in ("tsr.launch", "tsr.prep"))
        if span_launches != row["kernel_launches"]:
            failures.append(
                f"span-derived launch count {span_launches} != engine "
                f"kernel_launches {row['kernel_launches']}")
    for key in COMPARED + ("rules",):
        if key not in untraced_row and key not in row:
            continue  # e.g. resident_* keys on a host-loop row
        if row.get(key) != untraced_row.get(key):
            failures.append(f"traced run perturbed {key}: {row.get(key)} "
                            f"!= {untraced_row.get(key)}")
    if failures:
        print("bench_smoke: TRACE/COUNTER CROSS-CHECK FAILED:",
              file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    print("bench_smoke: trace-span launch count matches the dispatch "
          "counters (traced run byte-identical)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
