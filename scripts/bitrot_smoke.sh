#!/usr/bin/env bash
# Bitrot drill — the durable-state integrity companion to verify_t1.sh,
# overload_smoke.sh and chaos_smoke.sh.  Boots the real service over a
# MiniRedis store, kill -9s a checkpointed mine, then rots the durable
# bytes under the dead service (byte-flipped checkpoint delta, truncated
# rescache entry, flipped journal intent) and asserts the rebooted
# service heals to the last good chunk with oracle parity, cold re-mines
# the poisoned cache hit, quarantines every damaged record, and reports
# it all via /admin/integrity + fsm_integrity_* metrics.  See
# scripts/bitrot_smoke.py for the assertions.
cd "$(dirname "$0")/.."
# hard wall-clock bound: a service subprocess that wedges during boot
# blocks the driver in readline(), so the whole drill runs under timeout
exec timeout -k 30 840 env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/bitrot_smoke.py "$@"
