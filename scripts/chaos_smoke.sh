#!/usr/bin/env bash
# Chaos smoke — the fault-injection companion to verify_t1.sh and
# bench_smoke.sh.  Runs the chaos suite (tests/test_chaos.py: every
# registered fault site injected, each must yield retry/degrade-with-
# parity or a clean failure — never a hang, a torn-snapshot resume, or
# a silent wrong answer) with a PINNED injection seed so probability
# triggers fire identically in CI and on a laptop.  Override the seed
# with SPARKFSM_CHAOS_SEED to explore new schedules; a failure under a
# new seed is a real recovery bug, not flake.
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu SPARKFSM_CHAOS_SEED="${SPARKFSM_CHAOS_SEED:-1299827}" \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python -m pytest tests/test_chaos.py -q -p no:cacheprovider "$@"
