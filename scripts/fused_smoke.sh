#!/usr/bin/env bash
# Fused extension-count-prune + hybrid store smoke — seconds-scale
# proof that the fused kernel's CPU (jnp) reference is exact vs a numpy
# oracle (zeroed sub-threshold lanes, bit-exact survivor mask, dEclat
# diffset identity), that the Pallas kernel matches it byte-for-byte in
# interpret mode, and that every representation routing of a
# mixed-density mine is byte-identical to the SPADE oracle.
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/fused_smoke.py "$@"
