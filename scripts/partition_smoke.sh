#!/usr/bin/env bash
# Partitioned-mining smoke — the equivalence-class 2-D mesh companion
# to verify_t1.sh (parallel/partition.py).  Pinned 8-virtual-device
# partitioned kosarak miniature: byte parity with the single-device
# route, exchanges-per-round collectives pin, live fsm_partition_*
# metric families.
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/partition_smoke.py "$@"
