#!/usr/bin/env bash
# Degraded-topology smoke — the mesh-loss survival companion to
# verify_t1.sh (service/meshguard.py).  Pinned 8-virtual-device
# partitioned kosarak miniature with partition row 0 killed mid-round:
# adoption byte parity, epoch fence, poison-quarantine roundtrip, live
# fsm_mesh_* / fsm_quarantine_* metric families.
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/meshguard_smoke.py "$@"
