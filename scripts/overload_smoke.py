#!/usr/bin/env python
"""Overload + kill-restart smoke: the ISSUE 5 drills against the REAL
service across REAL process boundaries.

The CI companion to chaos_smoke.sh for the admission/recovery layer.
It boots the HTTP service as a subprocess with a TINY admission queue
(``queue_depth = 2``, one miner worker) over a MiniRedis store (the
in-process RESP server from tests/test_redis_store.py — the store must
survive the service's death), then:

1. submits a long CHECKPOINTED mine (the chaos lab arms a per-save
   delay so the drill job reliably outlives the orchestration below);
2. floods past capacity: 2 submits queue, 3 more must shed with HTTP
   429 + a sane integer ``Retry-After``, and ``/metrics`` must report
   ``fsm_service_sheds_total == 3`` with the queue-depth gauge at 2;
3. kill -9s the service between frontier saves;
4. reboots it on the same store and asserts the boot recovery pass
   resumes the checkpointed job from its journal + frontier (it must
   reach ``finished`` with results), gives both queued filler jobs a
   durable "interrupted by restart" failure, and settles every journal
   intent (the queue-depth gauge reads 0 again).

Usage: scripts/overload_smoke.sh   (pins JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import json
import os
import pathlib
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.parse
import urllib.request

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))

BOOT_TIMEOUT_S = 180.0
DRILL_TIMEOUT_S = 300.0


def log(msg):
    print(f"overload_smoke: {msg}", flush=True)


def post(port, endpoint, **params):
    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{port}{endpoint}"
    try:
        with urllib.request.urlopen(url, data=data, timeout=60) as resp:
            return resp.status, dict(resp.headers), \
                json.loads(resp.read().decode())
    except urllib.error.HTTPError as err:
        return err.code, dict(err.headers), json.loads(err.read().decode())


def scrape(port, family):
    """Sum every sample of ``family`` in /metrics (labels collapsed)."""
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=60) as resp:
        text = resp.read().decode()
    total, seen = 0.0, False
    for line in text.splitlines():
        m = re.match(rf"^{re.escape(family)}(\{{[^}}]*\}})?\s+(\S+)$", line)
        if m:
            total += float(m.group(2))
            seen = True
    assert seen, f"{family} missing from /metrics"
    return total


def boot_service(cfg_path, env):
    child = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import sys\n"
        f"sys.argv = ['app', '--config', {str(cfg_path)!r}]\n"
        "from spark_fsm_tpu.service.app import main\n"
        "main()\n"
    )
    proc = subprocess.Popen([sys.executable, "-c", child], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    port = None
    recovery_line = None
    deadline = time.time() + BOOT_TIMEOUT_S
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise AssertionError(
                f"service died at boot (rc={proc.poll()})")
        if line.startswith("restart recovery:"):
            recovery_line = line.strip()
        if "spark_fsm_tpu service on http://" in line:
            port = int(line.rsplit(":", 1)[1])
            break
    assert port is not None, "no boot line within the timeout"
    return proc, port, recovery_line


def main():
    from test_redis_store import MiniRedis  # noqa: E402 (tests/ on path)

    from spark_fsm_tpu.service.resp import RespClient

    mini = MiniRedis()
    log(f"MiniRedis on port {mini.port}")
    client = RespClient(port=mini.port)

    tmp = tempfile.mkdtemp(prefix="overload_smoke_")
    cfg_path = os.path.join(tmp, "config.json")
    with open(cfg_path, "w") as fh:
        json.dump({
            "fault_injection": True,  # the per-save delay arms via HTTP
            "service": {"port": 0, "miner_workers": 1, "queue_depth": 2},
            "store": {"backend": "redis", "host": "127.0.0.1",
                      "port": mini.port},
            # pin the queue engine so the checkpointed drill takes the
            # segmented path (frontier saves at every segment boundary)
            "engine": {"fused": "queue"},
        }, fh)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    proc, port, _ = boot_service(cfg_path, env)
    log(f"service A on port {port} (pid {proc.pid})")
    try:
        # slow every frontier save by 1s so the drill job reliably
        # outlives the flood + kill below (incarnation-local: dies with A)
        code, _, _ = post(port, "/admin/faults", action="arm",
                          site="checkpoint.save", every="1",
                          delay_s="1.0", exc="none")
        assert code == 200, "chaos lab refused the arm"

        from spark_fsm_tpu.data.spmf import format_spmf
        from spark_fsm_tpu.data.synth import synthetic_db

        db = synthetic_db(seed=41, n_sequences=200, n_items=12,
                          mean_itemsets=3.0, mean_itemset_size=1.3)
        code, _, body = post(port, "/train", uid="drill",
                             algorithm="SPADE_TPU", source="INLINE",
                             sequences=format_spmf(db), support="0.05",
                             checkpoint="1", checkpoint_every_s="0")
        assert code == 200 and body["status"] == "started", body

        # occupy the queue (depth 2) behind the running drill
        for uid in ("filler0", "filler1"):
            code, _, body = post(port, "/train", uid=uid,
                                 algorithm="SPADE", source="INLINE",
                                 sequences="1 -1 2 -2\n", support="1.0")
            assert code == 200 and body["status"] == "started", body
        assert scrape(port, "fsm_service_queue_depth") == 2

        # flood past capacity: exactly 3 sheds, each 429 + Retry-After
        for i in range(3):
            code, headers, body = post(port, "/train", uid=f"shed{i}",
                                       algorithm="SPADE", source="INLINE",
                                       sequences="1 -1 2 -2\n",
                                       support="1.0")
            assert code == 429, (code, body)
            retry_after = int(headers["Retry-After"])
            assert 1 <= retry_after <= 3600, retry_after
            assert "queue full" in body["data"]["error"], body
        assert scrape(port, "fsm_service_sheds_total") == 3
        # a shed left zero trace: the uid is unknown
        code, _, body = post(port, "/status/shed0")
        assert body["status"] == "failure", body
        log("overload drill ok: 3/3 sheds with 429 + Retry-After, "
            "queue gauge at bound")

        # wait for the first persisted frontier, then kill -9 the
        # service BETWEEN saves, mid-mine
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline:
            if client.get("fsm:frontier:drill"):
                break
            assert proc.poll() is None, "service A died early"
            time.sleep(0.1)
        assert client.get("fsm:frontier:drill"), "no frontier save seen"
        assert client.get("fsm:journal:drill"), "drill journal missing"
        proc.send_signal(signal.SIGKILL)
        proc.wait(30)
        log("killed service A mid-mine (frontier + journal persisted)")
    except BaseException:
        proc.kill()
        raise

    # reboot on the SAME store: the boot recovery pass must resume the
    # drill and durably fail the queued fillers
    proc, port, recovery_line = boot_service(cfg_path, env)
    log(f"service B on port {port} (pid {proc.pid}); {recovery_line}")
    try:
        assert recovery_line is not None, "no recovery line at reboot"
        assert "1 resumed" in recovery_line, recovery_line
        assert "2 failed durably" in recovery_line, recovery_line

        deadline = time.time() + DRILL_TIMEOUT_S
        status = None
        while time.time() < deadline:
            _, _, body = post(port, "/status/drill")
            status = body["status"]
            if status in ("finished", "failure"):
                break
            time.sleep(0.25)
        assert status == "finished", (status, body)
        _, _, body = post(port, "/get/patterns", uid="drill")
        assert body["status"] == "finished" and body["data"]["patterns"]
        for uid in ("filler0", "filler1"):
            _, _, body = post(port, f"/status/{uid}")
            assert body["status"] == "failure", (uid, body)
            assert "interrupted by restart" in body["data"]["error"], body
        # every journal intent settled; the queue gauge reads 0 again
        assert client.keys("fsm:journal:*") == []
        assert scrape(port, "fsm_service_queue_depth") == 0
        log("kill-restart drill ok: drill resumed via journal recovery "
            "and finished; orphans failed durably; journal settled")
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(60)
        except subprocess.TimeoutExpired:
            proc.kill()
        mini.close()
    log("PASS")


if __name__ == "__main__":
    main()
