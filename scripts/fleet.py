#!/usr/bin/env python
"""Fleet supervisor — the operator hook that ACTS on the autoscaler's
decisions (ISSUE 13, service/autoscale.py).

The control plane deliberately splits deciding from supplying: the
leader-elected controller inside the service publishes a
desired-replica-count record (``fsm:autoscale:desired``) and drain
directives; SOMETHING in the environment has to boot and reap
processes.  In production that something is a k8s HPA-style controller
or systemd template units (docs/OPERATIONS.md maps the records to
both); this script is the self-contained reference implementation —
enough to run an elastic fleet on one box:

- boots ``--initial`` replicas from one boot config (store must be
  ``redis`` — the shared journal/lease namespace IS the fleet bus);
- polls ``fsm:autoscale:desired`` and spawns replicas while the LIVE
  count is below the published desired (bounded by ``--max``).  Live =
  max(own alive children, un-expired ``fsm:replica:*`` heartbeat
  records): the heartbeat side makes a RESTARTED supervisor converge
  instead of re-booting a fleet that survived it — replicas orphaned
  by a supervisor kill keep running and keep heartbeating, so the new
  supervisor counts them and supplies only the deficit (a transient
  overshoot from a not-yet-heartbeating boot is reaped by the
  autoscaler's own scale-down);
- reaps exited children: a scale-down victim drains and exits on its
  own (the drain directive is between the leader and the victim — the
  supervisor never kills anything), and an exited replica below the
  desired count is replaced (crash supervision for free);
- SIGTERM/SIGINT forwards a clean drain-style stop to every child.

Usage:
    python scripts/fleet.py --config fleet.toml [--initial 2]
                            [--max 8] [--poll 1.0]

``--initial 0`` is the RESTART spelling: boot nothing up front, read
the live fleet from the heartbeats, supply only what the desired
record still wants.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import signal
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def log(msg):
    print(f"fleet: {msg}", flush=True)


def boot_replica(cfg_path: str, n: int) -> subprocess.Popen:
    child = (
        "import sys\n"
        f"sys.argv = ['app', '--config', {str(cfg_path)!r}]\n"
        "from spark_fsm_tpu.service.app import main\n"
        "main()\n"
    )
    proc = subprocess.Popen([sys.executable, "-c", child])
    log(f"booted replica #{n} (pid {proc.pid})")
    return proc


def main() -> int:
    ap = argparse.ArgumentParser(description="spark_fsm_tpu fleet "
                                             "supervisor")
    ap.add_argument("--config", required=True,
                    help="replica boot config (.toml/.json); needs "
                         "[store] backend=redis and [cluster]/"
                         "[autoscale] enabled")
    ap.add_argument("--initial", type=int, default=None,
                    help="replicas to boot at start (default: "
                         "[autoscale] min_replicas; 0 = restart mode — "
                         "converge from the live heartbeats only)")
    ap.add_argument("--max", type=int, default=None,
                    help="hard replica ceiling (default: [autoscale] "
                         "max_replicas)")
    ap.add_argument("--poll", type=float, default=1.0)
    args = ap.parse_args()

    from spark_fsm_tpu import config as cfgmod
    from spark_fsm_tpu.utils import envelope
    from spark_fsm_tpu.service.resp import RespClient

    cfg = cfgmod.load_config(args.config)
    if cfg.store.backend != "redis":
        sys.exit("fleet: the boot config must use [store] backend = "
                 "'redis' (the shared store is the fleet bus)")
    initial = args.initial if args.initial is not None \
        else max(1, cfg.autoscale.min_replicas)
    ceiling = args.max if args.max is not None \
        else max(initial or 1, cfg.autoscale.max_replicas)
    client = RespClient(host=cfg.store.host, port=cfg.store.port)

    def live_heartbeats() -> int:
        """Un-expired fsm:replica:* records — the whole fleet's live
        count, including replicas a previous (killed) supervisor
        orphaned.  Cursor SCAN, never KEYS (the fleet bus is shared)."""
        n, cursor = 0, "0"
        while True:
            cursor, batch = client.scan(cursor, match="fsm:replica:*",
                                        count=64)
            n += len(batch)
            if cursor == "0":
                return n

    children: list = []
    seq = 0
    stopping = []

    def _term(signum, frame):
        stopping.append(True)

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    for _ in range(initial):
        seq += 1
        children.append(boot_replica(args.config, seq))
    desired = max(initial, 1)
    log(f"supervising {initial} replicas (ceiling {ceiling}), acting "
        f"on fsm:autoscale:desired")
    try:
        while not stopping:
            time.sleep(args.poll)
            # reap exits (drained victims leave on their own)
            for proc in list(children):
                rc = proc.poll()
                if rc is not None:
                    log(f"replica pid {proc.pid} exited rc={rc}")
                    children.remove(proc)
            try:
                raw = client.get("fsm:autoscale:desired")
                if raw:
                    # the record is enveloped on the wire now —
                    # a corrupt one reads as absent (keep desired)
                    rec = json.loads(envelope.unwrap(raw)[0] or "{}")
                    want = int(rec.get("desired") or desired)
                    if want != desired:
                        log(f"desired-replica record: {want} "
                            f"(reason: {rec.get('reason')!r}, "
                            f"leader {rec.get('leader')!r})")
                    desired = want
            except Exception as exc:
                log(f"desired-record read failed: {exc}")
            # supply up to the published desired count; scale-DOWN is
            # the leader's drain directive + the victim's own exit —
            # never a supervisor kill.  Live = max(own children, fleet
            # heartbeats): a restarted supervisor counts the replicas
            # its predecessor orphaned instead of duplicating them.
            try:
                hb = live_heartbeats()
            except Exception as exc:
                log(f"heartbeat scan failed: {exc}")
                hb = 0
            # one boot per poll: a freshly spawned replica has no
            # heartbeat record until it finishes booting, and spawning
            # the whole deficit at once would double-count it next poll
            if (max(len(children), hb) < min(desired, ceiling)
                    and len(children) < ceiling):
                seq += 1
                children.append(boot_replica(args.config, seq))
    finally:
        log("stopping fleet")
        for proc in children:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = time.time() + 60.0
        for proc in children:
            try:
                proc.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()
    return 0


if __name__ == "__main__":
    sys.exit(main())
