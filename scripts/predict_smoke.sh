#!/usr/bin/env bash
# Prediction-serving smoke — the ISSUE 17 companion to rescache_smoke.sh
# and obs_smoke.sh.  Boots the service with [predict] on and a held-open
# micro-batch window, trains a rule set, prewarm-compiles the scoring
# ladder, then fires 3 concurrent /predict requests: ONE fused scoring
# wave, byte parity vs the host oracle and the Questor slow path, zero
# live predict compiles, live fsm_predict_* families + /admin/slo
# read-path quantiles.
cd "$(dirname "$0")/.."
exec timeout -k 30 600 env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/predict_smoke.py "$@"
