#!/usr/bin/env python
"""Multi-replica failover smoke: the ISSUE 8 drills against TWO real
service processes sharing one MiniRedis store.

The CI companion to overload_smoke for the lease layer
(service/lease.py), across REAL process boundaries:

1. boots replicas A and B ([cluster] enabled, lease_ttl_s = 2) on one
   MiniRedis; asserts they generated distinct replica ids;
2. submits a long CHECKPOINTED mine to A (the chaos lab arms a per-save
   delay on A so the drill reliably outlives the orchestration) plus
   two quick filler jobs that queue behind it;
3. WORK STEALING: idle B must claim the queued fillers off A's
   admission namespace and finish them (fsm_steal_* counters on B);
4. kill -9s A while it holds the checkpointed drill mid-mine
   (frontier + journal + a live lease persisted in the MiniRedis);
5. FAILOVER: B's periodic recovery must adopt the drill only after its
   lease EXPIRES, resume it from the persisted frontier, and finish
   with the EXACT oracle pattern set — zero duplicated results.  The
   failover bound is read from the SERVICE's own
   ``fsm_job_time_to_adoption_seconds`` histogram (ISSUE 9) and
   asserted against the lease-TTL-derived bound — not from shell
   wall-clock;
6. CLUSTER FLIGHT RECORDER (ISSUE 9): ``/admin/trace/drill`` served by
   the SURVIVOR must return one merged timeline whose spans come from
   BOTH replicas — admission + mine progress flushed by dead A through
   the fenced spine, adoption + completion from B — ordered by wall
   time; ``/admin/cluster`` aggregates both replicas while both live;
7. asserts every journal intent and lease is settled and the
   fsm_lease_*/fsm_steal_*/fsm_job_* metric families are live on B's
   /metrics.

The stale-incarnation fencing half of the acceptance (late writes
REJECTED) cannot be driven by kill -9 — a dead process writes nothing —
and is pinned in-process by tests/test_lease.py's split-brain drill.

Usage: scripts/replica_smoke.sh   (pins JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import json
import os
import pathlib
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.parse
import urllib.request

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))

BOOT_TIMEOUT_S = 180.0
DRILL_TIMEOUT_S = 300.0
LEASE_TTL_S = 2.0
RECOVER_EVERY_S = 0.5


def log(msg):
    print(f"replica_smoke: {msg}", flush=True)


def post(port, endpoint, **params):
    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{port}{endpoint}"
    try:
        with urllib.request.urlopen(url, data=data, timeout=60) as resp:
            return resp.status, dict(resp.headers), \
                json.loads(resp.read().decode())
    except urllib.error.HTTPError as err:
        return err.code, dict(err.headers), json.loads(err.read().decode())


def scrape(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=60) as resp:
        return resp.read().decode()


def series_sum(text, family, label_filter=""):
    """Sum samples of ``family`` whose label block contains the filter."""
    total, seen = 0.0, False
    for line in text.splitlines():
        m = re.match(rf"^{re.escape(family)}(\{{[^}}]*\}})?\s+(\S+)$", line)
        if m and label_filter in (m.group(1) or ""):
            total += float(m.group(2))
            seen = True
    assert seen, f"{family} missing from /metrics"
    return total


def boot_service(cfg_path, env, name):
    child = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import sys\n"
        f"sys.argv = ['app', '--config', {str(cfg_path)!r}]\n"
        "from spark_fsm_tpu.service.app import main\n"
        "main()\n"
    )
    proc = subprocess.Popen([sys.executable, "-c", child], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    port = replica = None
    deadline = time.time() + BOOT_TIMEOUT_S
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise AssertionError(
                f"replica {name} died at boot (rc={proc.poll()})")
        if line.startswith("cluster replica "):
            replica = line.split()[2]
        if "spark_fsm_tpu service on http://" in line:
            port = int(line.rsplit(":", 1)[1])
            break
    assert port is not None, f"no boot line from {name} within the timeout"
    assert replica is not None, f"no cluster-replica line from {name}"
    return proc, port, replica


def main():
    from test_redis_store import MiniRedis  # noqa: E402 (tests/ on path)

    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.data.synth import synthetic_db
    from spark_fsm_tpu.data.vertical import abs_minsup
    from spark_fsm_tpu.models.oracle import mine_spade
    from spark_fsm_tpu.service.resp import RespClient
    from spark_fsm_tpu.utils import envelope

    mini = MiniRedis()
    log(f"MiniRedis on port {mini.port}")
    client = RespClient(port=mini.port)

    tmp = tempfile.mkdtemp(prefix="replica_smoke_")
    cfg_path = os.path.join(tmp, "config.json")
    with open(cfg_path, "w") as fh:
        json.dump({
            "fault_injection": True,  # the per-save delay arms via HTTP
            "service": {"port": 0, "miner_workers": 1, "queue_depth": 8},
            "store": {"backend": "redis", "host": "127.0.0.1",
                      "port": mini.port},
            "cluster": {"enabled": True, "lease_ttl_s": LEASE_TTL_S,
                        "recover_every_s": RECOVER_EVERY_S},
            # cluster flight recorder: traced jobs flush their spans to
            # the durable spine (small threshold so A's mine progress
            # lands between checkpoints too)
            "observability": {"trace": True, "spine_flush_spans": 8},
            # pin the queue engine so the checkpointed drill takes the
            # segmented path (frontier saves at every segment boundary)
            "engine": {"fused": "queue"},
            # resource attribution (ISSUE 19): the bill must survive
            # the failover drill — flushed through the lease-heartbeat
            # fenced write path on this very fleet
            "usage": {"enabled": True, "flush_every_s": 0.0},
        }, fh)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    proc_a, port_a, rep_a = boot_service(cfg_path, env, "A")
    log(f"replica A {rep_a} on port {port_a} (pid {proc_a.pid})")
    proc_b, port_b, rep_b = boot_service(cfg_path, env, "B")
    log(f"replica B {rep_b} on port {port_b} (pid {proc_b.pid})")
    try:
        assert rep_a != rep_b, "replica ids must be unique per boot"

        # slow every frontier save on A by 1s so the drill job reliably
        # outlives the steal + kill phases (armed on A only)
        code, _, _ = post(port_a, "/admin/faults", action="arm",
                          site="checkpoint.save", every="1",
                          delay_s="1.0", exc="none")
        assert code == 200, "chaos lab refused the arm"

        db = synthetic_db(seed=41, n_sequences=200, n_items=12,
                          mean_itemsets=3.0, mean_itemset_size=1.3)
        want = mine_spade(db, abs_minsup(0.05, len(db)))
        code, _, body = post(port_a, "/train", uid="drill",
                             algorithm="SPADE_TPU", source="INLINE",
                             sequences=format_spmf(db), support="0.05",
                             checkpoint="1", checkpoint_every_s="0")
        assert code == 200 and body["status"] == "started", body

        # ---- work stealing: fillers queue behind the drill on A; idle
        # B must claim them off A's admission namespace
        for uid in ("filler0", "filler1"):
            code, _, body = post(port_a, "/train", uid=uid,
                                 algorithm="SPADE", source="INLINE",
                                 sequences="1 -1 2 -2\n", support="1.0")
            assert code == 200 and body["status"] == "started", body
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline:
            done = [post(port_b, f"/status/{u}")[2]["status"]
                    for u in ("filler0", "filler1")]
            if done == ["finished", "finished"]:
                break
            assert proc_a.poll() is None and proc_b.poll() is None
            time.sleep(0.1)
        assert done == ["finished", "finished"], done
        text_b = scrape(port_b)
        stolen = series_sum(text_b, "fsm_steal_attempts_total",
                            'outcome="stolen"')
        assert stolen >= 2, f"B stole {stolen} jobs, expected both fillers"
        drops = series_sum(scrape(port_a), "fsm_steal_victim_drops_total")
        # the thief's steal-latency histogram observed both claims
        steal_lat_n = series_sum(text_b,
                                 "fsm_job_steal_latency_seconds_count")
        assert steal_lat_n >= 2, \
            f"steal latency histogram saw {steal_lat_n} claims"
        log(f"steal ok: B stole {int(stolen)} queued fillers "
            f"(A dropped {int(drops)} at dequeue), both finished on B; "
            f"fsm_job_steal_latency_seconds observed {int(steal_lat_n)}")

        # ---- cluster plane: while BOTH replicas live, either serves
        # the aggregated heartbeat view
        code, _, cluster = post(port_b, "/admin/cluster")
        assert code == 200 and cluster.get("enabled"), cluster
        assert cluster["totals"]["replicas"] == 2, cluster["totals"]
        log(f"cluster view ok: /admin/cluster on B sees "
            f"{cluster['totals']['replicas']} replicas "
            f"(totals {cluster['totals']})")

        # ---- failover: kill A between frontier saves, mid-mine
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline:
            if client.get("fsm:frontier:drill"):
                break
            assert proc_a.poll() is None, "replica A died early"
            time.sleep(0.1)
        assert client.get("fsm:frontier:drill"), "no frontier save seen"
        assert client.get("fsm:journal:drill"), "drill journal missing"
        assert client.get("fsm:lease:drill"), "drill lease missing"
        proc_a.send_signal(signal.SIGKILL)
        proc_a.wait(30)
        t_kill = time.monotonic()
        log("killed replica A mid-mine (frontier + journal + live lease "
            "persisted)")

        # B may adopt only after the lease EXPIRES; bound = TTL + one
        # recovery tick + scheduling slack
        t_adopt = None
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline:
            raw = client.get("fsm:journal:drill")
            if raw is None:  # already adopted AND finished
                t_adopt = t_adopt or time.monotonic()
                break
            # journal intents are enveloped on the wire now —
            # unwrap before parsing (legacy bare JSON passes through)
            if json.loads(envelope.unwrap(raw)[0] or "{}")\
                    .get("replica") == rep_b:
                t_adopt = time.monotonic()
                break
            time.sleep(0.05)
        assert t_adopt is not None, "B never adopted the drill"
        adopt_wall = t_adopt - t_kill  # informational only — the
        # asserted number is the service's own histogram below
        # (ISSUE 9: time-to-adoption is OBSERVABLE, not shell-derived).
        # The histogram's reference point is A's last durable spine
        # flush (its last checkpoint), which predates the kill by up to
        # one slowed save — the bound allows for it.
        # the histogram is observed just AFTER the adoption resubmit
        # rewrites the journal (the signal the loop above watched) —
        # poll briefly rather than racing a single scrape against it
        n = s = 0.0
        deadline = time.time() + 30.0
        while time.time() < deadline:
            text = scrape(port_b)
            n = series_sum(text, "fsm_job_time_to_adoption_seconds_count")
            if n >= 1:
                s = series_sum(text,
                               "fsm_job_time_to_adoption_seconds_sum")
                break
            time.sleep(0.1)
        assert n >= 1, "B never observed fsm_job_time_to_adoption_seconds"
        observed = s / n
        bound = LEASE_TTL_S + RECOVER_EVERY_S + 5.0
        assert 0.0 < observed <= bound, \
            (f"histogram time-to-adoption {observed:.1f}s outside the "
             f"TTL-derived bound {bound:.1f}s")
        log(f"failover ok: B adopted the drill {adopt_wall:.1f}s after "
            f"the kill; fsm_job_time_to_adoption_seconds observed "
            f"{observed:.1f}s (bound {bound:.1f}s, lease ttl "
            f"{LEASE_TTL_S}s)")

        status = None
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline:
            _, _, body = post(port_b, "/status/drill")
            status = body["status"]
            if status in ("finished", "failure"):
                break
            time.sleep(0.25)
        assert status == "finished", (status, body)
        _, _, body = post(port_b, "/get/patterns", uid="drill")
        assert body["status"] == "finished"
        from spark_fsm_tpu.service.model import deserialize_patterns
        from spark_fsm_tpu.utils.canonical import (diff_patterns,
                                                   patterns_text)

        got = deserialize_patterns(body["data"]["patterns"])
        assert patterns_text(got) == patterns_text(want), \
            diff_patterns(want, got)
        log(f"oracle parity ok: {len(got)} patterns, zero duplicated "
            "results")

        # ---- cluster flight recorder: the SURVIVOR serves one merged
        # timeline holding the dead owner's admission/mine spans next
        # to its own adoption/completion spans, ordered by wall time
        code, _, merged = post(port_b, "/admin/trace/drill")
        assert code == 200, merged
        assert merged.get("merged"), "B served a local-only trace dump"
        spans = merged["spans"]
        reps = {s.get("replica") for s in spans}
        assert rep_a in reps and rep_b in reps, \
            f"merged timeline missing a replica: {reps}"
        sites_a = {s["site"] for s in spans if s.get("replica") == rep_a}
        sites_b = {s["site"] for s in spans if s.get("replica") == rep_b}
        assert "lifecycle.admitted" in sites_a, \
            f"no admission span from dead A (A sites: {sorted(sites_a)})"
        mine_sites = {"job.dataset", "queue.dispatch", "queue.segment",
                      "queue.readback", "checkpoint.save",
                      "lifecycle.checkpointed"}
        assert sites_a & mine_sites, \
            f"no mine-progress spans from dead A: {sorted(sites_a)}"
        assert "lifecycle.adopted" in sites_b, \
            f"no adoption span from B: {sorted(sites_b)}"
        assert {"lifecycle.settled", "job"} & sites_b, \
            f"no completion span from B: {sorted(sites_b)}"
        ts = [s.get("ts") or 0 for s in spans]
        assert ts == sorted(ts), "merged timeline not wall-monotonic"
        log(f"merged timeline ok: {len(spans)} spans from "
            f"{sorted(reps)} ({len(sites_a)} sites from dead A, "
            f"{len(sites_b)} from B), wall-ordered")

        # every journal intent + lease settled; metric families live
        assert client.keys("fsm:journal:*") == []
        assert client.get("fsm:lease:drill") is None
        assert client.keys("fsm:admission:*") == []
        text = scrape(port_b)
        for fam in ("fsm_lease_acquired_total", "fsm_lease_held",
                    "fsm_lease_fence_rejections_total",
                    "fsm_steal_attempts_total",
                    "fsm_replica_heartbeats_total",
                    "fsm_trace_spine_writes_total",
                    "fsm_job_e2e_seconds_count",
                    "fsm_cluster_replicas"):
            series_sum(text, fam)
        resumed = series_sum(text, "fsm_recovery_jobs_total",
                             'outcome="resumed"')
        assert resumed >= 1, "B's recovery counter never saw the adoption"
        log("bookkeeping ok: journals/leases/markers settled, "
            "fsm_lease_*/fsm_steal_* families live")

        # ---- attribution survives the fleet (ISSUE 19): a TSR mine on
        # the SURVIVOR is billed per launch, settled into its /status
        # stats, and flushed to the durable fsm:usage:{tenant} ledger
        # through the lease-fenced write path — billed exactly ONCE
        code, _, body = post(port_b, "/train", uid="bill-tsr",
                             algorithm="TSR_TPU", source="INLINE",
                             sequences="1 -1 2 -2\n2 -1 1 -2\n1 2 -1\n",
                             k="4", minconf="0.2", max_side="1")
        assert code == 200 and body["status"] == "started", body
        deadline = time.time() + DRILL_TIMEOUT_S
        while time.time() < deadline:
            _, _, body = post(port_b, "/status/bill-tsr")
            if body["status"] in ("finished", "failure"):
                break
            time.sleep(0.1)
        assert body["status"] == "finished", body
        ustats = json.loads(body.get("data", {}).get("stats", "{}"))
        uvec = ustats.get("usage") or {}
        assert uvec.get("launches", 0) >= 1, \
            f"bill-tsr /status stats carries no usage block: {ustats}"
        code, _, bill = post(port_b, "/admin/usage")
        assert code == 200 and bill.get("enabled"), bill
        row = bill.get("tenants", {}).get("default") or {}
        assert row.get("launches", 0) >= uvec["launches"], \
            f"/admin/usage default-tenant rollup below the job: {row}"
        raw = client.get("fsm:usage:default")
        assert raw is not None, "no durable usage ledger record"
        rec = json.loads(envelope.unwrap(raw)[0])
        led = rec.get("jobs", {}).get("bill-tsr")
        assert led is not None and \
            led.get("launches") == uvec["launches"], \
            (f"ledger bills bill-tsr {led} != settled vector {uvec} "
             f"(double- or under-billed)")
        log(f"attribution ok: bill-tsr billed {uvec['launches']} "
            f"launches / {uvec.get('traffic_units')} traffic units "
            f"once, durable ledger row matches the settled vector")
    finally:
        if proc_a.poll() is None:
            proc_a.kill()
        proc_b.send_signal(signal.SIGTERM)
        try:
            proc_b.wait(60)
        except subprocess.TimeoutExpired:
            proc_b.kill()
        mini.close()
    log("PASS")


if __name__ == "__main__":
    main()
