#!/usr/bin/env python
"""Fused extension-count-prune + hybrid vertical store smoke (ISSUE 16).

Seconds-scale CI proof of the density-adaptive store and the fused
kernel's CPU (jnp) reference semantics:

- the fused reference (``pallas_extend.extend_count_prune_jnp``)
  against an independent numpy oracle: supports are EXACT where
  >= thr and EXACTLY 0 below it (dying candidates never carry a
  count), the packed survivor mask is bit-for-bit ``sup >= thr``
  (LSB-first, tail bits zero), and the dEclat diffset spelling is
  byte-identical to the direct count (exact identity, per row);
- the production wave wrapper (``spam_bitops.wave_extend_prune_fn``)
  jnp path vs the Pallas kernel in interpret mode: byte-identical
  (sup AND mask) on the same inputs with mixed per-row diffset flags;
- end-to-end hybrid parity: a mixed-density miniature mined with the
  planner's auto routing, the bitmap pin, the id-list pin, the Pallas
  wave path and the CPU engine (with and without diffsets) — every
  variant byte-identical to the SPADE oracle, and the auto mine's
  stats prove a genuinely HYBRID store ran (dense + id-list items in
  one mine, diffset nodes and pair launches observed).

Usage: scripts/fused_smoke.sh   (pins JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import sys


def main() -> int:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from spark_fsm_tpu.data.synth import synthetic_db
    from spark_fsm_tpu.models.oracle import mine_spade
    from spark_fsm_tpu.models.spam_bitmap import (mine_spam_cpu,
                                                  mine_spam_tpu)
    from spark_fsm_tpu.ops import pallas_extend as PE
    from spark_fsm_tpu.ops import spam_bitops as SB
    from spark_fsm_tpu.utils.canonical import patterns_text

    failures = []
    rng = np.random.default_rng(0)

    # ---- 1. fused jnp reference vs an independent numpy oracle ------
    P, NI, S, W, thr = 10, 40, 12, 2, 5
    # sparse item rows spread supports across [0, S] so lanes straddle
    # the threshold in both directions
    q = np.linspace(0.05, 0.9, NI)
    p3 = rng.integers(0, 2**32, (P, S, W), dtype=np.uint32)
    p3 *= (rng.random((P, S, W)) < 0.6).astype(np.uint32)
    items3 = rng.integers(0, 2**32, (NI, S, W), dtype=np.uint32)
    items3 *= (rng.random((NI, S, W)) < q[:, None, None]).astype(np.uint32)

    joined = p3[:, None] & items3[None]                  # [P, NI, S, W]
    sup_full = (joined != 0).any(-1).sum(-1).astype(np.int32)

    ud = rng.random(P) < 0.5
    sup, mask = PE.extend_count_prune_jnp(
        jnp.asarray(p3), jnp.asarray(items3), thr, jnp.asarray(ud))
    sup, mask = np.asarray(sup), np.asarray(mask)
    above = sup_full >= thr
    if not np.array_equal(sup[above], sup_full[above]):
        failures.append("fused sup not exact above thr")
    if np.any(sup[~above] != 0):
        failures.append("sub-threshold lanes carried a nonzero count")
    bit = (mask[:, np.arange(NI) // 32]
           >> (np.arange(NI) % 32).astype(np.uint32)) & 1
    if not np.array_equal(bit.astype(bool), above):
        failures.append("survivor mask != (sup >= thr) bit-for-bit")
    tail = mask[:, -1] >> (NI % 32 or 32)
    if NI % 32 and np.any(tail):
        failures.append("mask tail bits beyond NI not zero")
    for flag in (False, True):   # diffset spelling: exact identity
        s2, m2 = PE.extend_count_prune_jnp(
            jnp.asarray(p3), jnp.asarray(items3), thr,
            jnp.full(P, flag))
        if not (np.array_equal(np.asarray(s2), sup)
                and np.array_equal(np.asarray(m2), mask)):
            failures.append(f"diffset identity broken (use_diff={flag})")

    # ---- 2. production wave wrapper: jnp path vs Pallas interpret ---
    nd_pad, Sw, Bn = 64, 16, 3
    pt = rng.integers(0, 2**32, (2 * Bn, Sw), dtype=np.uint32)
    store = rng.integers(0, 2**32, (nd_pad, Sw), dtype=np.uint32)
    store *= (rng.random((nd_pad, Sw)) < 0.3).astype(np.uint32)
    ud2 = rng.random(2 * Bn) < 0.5
    thr2 = jnp.int32(4)
    f_jnp = SB.wave_extend_prune_fn(None, 1, nd_pad, use_pallas=False)
    f_pal = SB.wave_extend_prune_fn(None, 1, nd_pad, use_pallas=True,
                                    s_block=Sw, interpret=True)
    a = f_jnp(jnp.asarray(pt), jnp.asarray(store), thr2, jnp.asarray(ud2))
    b = f_pal(jnp.asarray(pt), jnp.asarray(store), thr2, jnp.asarray(ud2))
    if not (np.array_equal(np.asarray(a[0]), np.asarray(b[0]))
            and np.array_equal(np.asarray(a[1]), np.asarray(b[1]))):
        failures.append("wave wrapper: jnp vs Pallas-interpret diverged")

    # ---- 3. end-to-end hybrid parity on a mixed-density miniature ---
    db = synthetic_db(seed=401, n_sequences=90, n_items=24,
                      mean_itemsets=4.0, mean_itemset_size=1.3,
                      zipf_s=2.2)
    minsup = max(1, round(0.08 * len(db)))
    want = patterns_text(mine_spade(db, minsup))
    auto_stats = {}
    variants = [
        ("tpu-auto", lambda s: mine_spam_tpu(
            db, minsup, stats_out=s, density_crossover=0.5)),
        ("tpu-bitmap", lambda s: mine_spam_tpu(
            db, minsup, stats_out=s, representation="bitmap")),
        ("tpu-idlist", lambda s: mine_spam_tpu(
            db, minsup, stats_out=s, representation="idlist")),
        ("tpu-pallas", lambda s: mine_spam_tpu(
            db, minsup, stats_out=s, density_crossover=0.5,
            use_pallas=True)),
        ("cpu-auto", lambda s: mine_spam_cpu(
            db, minsup, stats_out=s, density_crossover=0.5)),
        ("cpu-nodiff", lambda s: mine_spam_cpu(
            db, minsup, stats_out=s, density_crossover=0.5,
            diffset_depth=0)),
    ]
    for name, run in variants:
        stats = {}
        got = patterns_text(run(stats))
        if got != want:
            failures.append(f"{name}: NOT byte-identical to oracle")
        if name == "tpu-auto":
            auto_stats = stats

    if not (auto_stats.get("rep_dense", 0) > 0
            and auto_stats.get("rep_idlist", 0) > 0):
        failures.append(f"auto mine was not hybrid: {auto_stats}")
    if not auto_stats.get("diffset_nodes", 0) > 0:
        failures.append(f"no diffset nodes observed: {auto_stats}")
    if not auto_stats.get("pair_launches", 0) > 0:
        failures.append(f"no sparse pair launches observed: {auto_stats}")

    if failures:
        print("fused_smoke: FAILED:", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    print(f"fused_smoke: OK (fused jnp reference exact vs numpy oracle "
          f"with zeroed sub-threshold lanes + bit-exact survivor mask; "
          f"Pallas-interpret byte parity; hybrid mine "
          f"{auto_stats.get('rep_dense')} dense / "
          f"{auto_stats.get('rep_idlist')} id-list items, "
          f"{auto_stats.get('diffset_nodes')} diffset nodes, "
          f"{auto_stats.get('pair_launches')} pair launches, all "
          f"byte-identical to the SPADE oracle)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
