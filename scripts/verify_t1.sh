#!/usr/bin/env bash
# Tier-1 verify — the ONE blessed entry point for builders and CI.
# Wraps the ROADMAP.md "Tier-1 verify" command VERBATIM (pipefail,
# timeout, DOTS_PASSED echo); if the two ever differ, ROADMAP.md wins
# and this wrapper is the bug.
#
# --smokes additionally runs the smoke family after a green pytest run:
#   bench_smoke.sh       dispatch-shape counters vs committed expectations
#   chaos_smoke.sh       every fault site injected, pinned seed
#   obs_smoke.sh         /metrics + trace completeness over a live boot
#   overload_smoke.sh    429 shedding + kill-restart journal recovery
#   throughput_smoke.sh  fused-vs-unfused flood, per-job parity
#   resident_smoke.sh    resident-frontier 3d miniature, pinned waves +
#                        host-path parity
#   partition_smoke.sh   equivalence-class partitioned mine on the
#                        8-virtual-device 2-D mesh: byte parity with
#                        the single-device route + exchanges-per-round
#                        collectives pin + live fsm_partition_* families
#   replica_smoke.sh     2 replicas on one MiniRedis: work stealing,
#                        kill -9 failover with lease-expiry adoption +
#                        oracle parity
#   rescache_smoke.sh    result-reuse tier over HTTP: cache hit +
#                        in-flight coalesce + dominated serve, parity
#                        vs cold oracle, live fsm_rescache_* families
#   autoscale_smoke.sh   elastic control plane: 3 replicas on one
#                        MiniRedis — tenant-fair 429s, a leader
#                        scale-up decision, forced scale-down drain
#                        with steal + parity and a clean victim exit
#   storm_smoke.sh       store-outage survival: black-hole-the-store
#                        drill (stall -> same-replica resume, parity,
#                        spool drained) + one pinned-seed partition
#                        storm over a proxied 2-replica fleet with the
#                        jepsen-lite invariant checker
#   fleet_smoke.sh       kill scripts/fleet.py mid-scale-up, restart,
#                        converge to desired from heartbeats — zero
#                        lost/duplicated jobs
#   spam_smoke.sh        SPAM wave engine vs oracle parity on a dense
#                        AND a sparse miniature + AUTO planner routing
#                        drill (never SPAM below the crossover) +
#                        structured 400 + fsm_engine_selected_total
#   fused_smoke.sh       fused extension-count-prune reference vs
#                        numpy oracle (zeroed sub-threshold lanes,
#                        bit-exact survivor mask, diffset identity) +
#                        Pallas-interpret byte parity + hybrid-store
#                        mine parity across every representation pin
#   predict_smoke.sh     prediction serving plane: 3 concurrent
#                        /predict requests fused into one scoring
#                        wave, byte parity vs host oracle + Questor
#                        slow path, zero live compiles after prewarm,
#                        live fsm_predict_* + /admin/slo read block
#   bitrot_smoke.sh      durable-state integrity: rot the bytes under
#                        a dead service (checkpoint delta, rescache
#                        entry, journal intent) — last-good resume +
#                        oracle parity, cold re-mine, quarantine on
#                        /admin/integrity, live fsm_integrity_*
#   usage_smoke.sh       resource attribution plane: 2-tenant flood
#                        with a rescache hot set — per-tenant bill on
#                        /admin/usage, conservation invariant exact vs
#                        the dispatch counters, avoided-cost credited,
#                        durable ledger + fsm_usage_* families live
#   meshguard_smoke.sh   degraded-topology survival: partition row 0
#                        killed mid-round on the 8-virtual-device 2-D
#                        mesh — adoption byte parity, stale-epoch
#                        launch refused, poison-quarantine roundtrip,
#                        live fsm_mesh_* + fsm_quarantine_* families
cd "$(dirname "$0")/.."
set -o pipefail
SMOKES=0
if [ "${1:-}" = "--smokes" ]; then SMOKES=1; shift; fi
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
if [ $rc -eq 0 ] && [ $SMOKES -eq 1 ]; then
    for s in bench_smoke chaos_smoke obs_smoke overload_smoke \
             throughput_smoke resident_smoke partition_smoke \
             replica_smoke rescache_smoke autoscale_smoke \
             storm_smoke fleet_smoke spam_smoke fused_smoke \
             predict_smoke bitrot_smoke usage_smoke \
             meshguard_smoke; do
        echo "== scripts/$s.sh"
        "scripts/$s.sh" || { echo "SMOKE_FAILED=$s"; exit 1; }
    done
fi
exit $rc
