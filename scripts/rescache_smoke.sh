#!/usr/bin/env bash
# Result-reuse smoke — the ISSUE 12 companion to obs_smoke.sh and
# chaos_smoke.sh.  Boots the service with [rescache] enabled and one
# miner worker, drives a cache hit, an in-flight coalesce, and a
# dominated serve over HTTP, asserts byte-identical parity against a
# cold oracle, live fsm_rescache_* metric families, and a drained
# journal namespace (no stuck follower uids).
cd "$(dirname "$0")/.."
exec timeout -k 30 600 env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/rescache_smoke.py "$@"
