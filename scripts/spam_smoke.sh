#!/usr/bin/env bash
# SPAM engine + planner smoke — seconds-scale proof that the SPAM wave
# engine is byte-identical to the oracle on a dense AND a sparse
# miniature, that AUTO routes each shape to the right engine (never
# SPAM below the calibrated crossover), and that the structured-400 /
# fsm_engine_selected_total surfaces are live.
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/spam_smoke.py "$@"
