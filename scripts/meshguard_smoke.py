#!/usr/bin/env python
"""Degraded-topology smoke: kill a partition row mid-mine, keep parity.

The CI companion to verify_t1.sh for the mesh-loss survival plane
(service/meshguard.py + parallel/partition.replan_surviving +
models/tsr.TsrPartitioned adoption): on the forced-host 8-device CPU
mesh it runs the config-3 kosarak miniature through the PARTITIONED
route (2 partition rows x 4-device inner seq rows) while a
device-shaped injected fault kills row 0 mid-round, and asserts

- BYTE PARITY with the single-device route after the surviving row
  adopts the dead row's class slice (the degraded exact-merge
  contract);
- the guard fenced exactly row 0 (dead_after=1) and bumped the
  topology epoch — stale launches are refused, not silently degraded;
- a poison-filler crash-loop quarantine roundtrip: a synthetic
  exhausted-adoption-budget job settles a durable
  ``fsm:quarantine:{uid}`` record, blocks re-admission, counts a
  refusal, and releases clean via the /admin/quarantine verbs;
- the fsm_mesh_* / fsm_quarantine_* metric families are LIVE on a
  registry scrape with their label vocabularies seeded.

Usage: scripts/meshguard_smoke.sh   (pins JAX_PLATFORMS=cpu + 8 devs)
"""

from __future__ import annotations

import sys
import time


def main() -> int:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    from spark_fsm_tpu.config import MeshguardConfig
    from spark_fsm_tpu.data.synth import kosarak_like
    from spark_fsm_tpu.models.tsr import mine_tsr_tpu
    from spark_fsm_tpu.parallel.mesh import make_mesh
    from spark_fsm_tpu.service import meshguard
    from spark_fsm_tpu.service.store import ResultStore
    from spark_fsm_tpu.utils import faults, obs
    from spark_fsm_tpu.utils.canonical import rules_text

    failures = []
    db = kosarak_like(scale=0.002, fast=True)

    t0 = time.monotonic()
    want = rules_text(mine_tsr_tpu(db, 100, 0.5, max_side=2))
    solo_s = time.monotonic() - t0

    # ---- chaos drill: kill partition row 0 mid-mine, adopt, merge
    guard = meshguard.install(MeshguardConfig(enabled=True, dead_after=1))
    t0 = time.monotonic()
    try:
        faults.arm("device.dispatch", every=1, times=1, match="part0")
        got = rules_text(mine_tsr_tpu(db, 100, 0.5, max_side=2,
                                      mesh=make_mesh(8),
                                      partition_parts=2))
    finally:
        faults.disarm()
    drill_s = time.monotonic() - t0
    if got != want:
        failures.append("degraded mine differs from the single-device "
                        "route (adoption exact-merge contract broken)")
    if guard.dead_rows() != frozenset({0}):
        failures.append(f"guard fenced {set(guard.dead_rows())}, "
                        "expected exactly row 0 dead")
    epoch = guard.current_epoch()
    if epoch < 1:
        failures.append(f"topology epoch never bumped (epoch={epoch})")
    try:
        guard.check_epoch(epoch - 1)
        failures.append("stale pre-death epoch was NOT refused")
    except meshguard.StaleTopology:
        pass
    meshguard.reset()

    # ---- poison-filler quarantine roundtrip (no real crash loop: the
    # tier-1 drill in tests/test_meshguard.py owns that; this pins the
    # durable-record verbs an operator actually drives)
    store = ResultStore()
    uid = "meshguard-smoke-poison"
    meshguard.poison_record(store, uid, reason="adoption budget "
                            "exhausted: smoke filler", adoptions=3)
    if meshguard.poisoned(store, uid) is None:
        failures.append("poison record did not block re-admission")
    meshguard.note_refused(uid)
    listed = [r for r in meshguard.quarantine_list(store)
              if r.get("uid") == uid]
    if not listed:
        failures.append("poison record missing from /admin/quarantine "
                        "list surface")
    if not meshguard.quarantine_release(store, uid):
        failures.append("quarantine_release returned False for a live "
                        "record")
    if meshguard.poisoned(store, uid) is not None:
        failures.append("released uid still blocks re-admission")

    # ---- scrape: families live, vocabularies seeded
    text = obs.REGISTRY.render_prometheus()
    for fam in ("fsm_mesh_epoch", "fsm_mesh_rows_dead",
                "fsm_mesh_row_transitions_total", "fsm_mesh_probes_total",
                "fsm_mesh_replans_total",
                "fsm_mesh_stale_epoch_refused_total",
                "fsm_quarantine_jobs_total"):
        if fam not in text:
            failures.append(f"metric family missing from scrape: {fam}")
    for series in ('fsm_mesh_row_transitions_total{to="dead"}',
                   'fsm_mesh_probes_total{outcome="failed"}',
                   'fsm_quarantine_jobs_total{outcome="poisoned"}',
                   'fsm_quarantine_jobs_total{outcome="refused"}',
                   'fsm_quarantine_jobs_total{outcome="released"}'):
        if series not in text:
            failures.append(f"label vocabulary not seeded: {series}")

    if failures:
        print("meshguard_smoke: FAILED:", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    print(f"meshguard_smoke: row 0 killed mid-round and adopted — "
          f"degraded 2x4 mine byte-identical to the single-device route "
          f"(epoch {epoch}, stale launch refused; poison quarantine "
          f"roundtrip clean; walls solo {solo_s:.1f}s / degraded "
          f"{drill_s:.1f}s on timeshared virtual devices)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
