#!/usr/bin/env bash
# Elastic control plane smoke (ISSUE 13): three real service processes
# on one MiniRedis — tenant-fair shedding, a leader-published scale-up
# decision under sustained backlog, and a forced scale-down whose
# victim drains (queue stolen by the survivors, oracle parity) and
# exits cleanly.
#
# Runs under a hard timeout: a wedged boot/drain must fail the smoke,
# not hang CI.
cd "$(dirname "$0")/.."
set -o pipefail
timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/autoscale_smoke.py
rc=$?
if [ $rc -ne 0 ]; then
    echo "AUTOSCALE_SMOKE_FAILED rc=$rc"
fi
exit $rc
