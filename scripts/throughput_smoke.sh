#!/usr/bin/env bash
# Throughput smoke — the cross-job fusion companion to verify_t1.sh,
# bench_smoke.sh, chaos_smoke.sh, obs_smoke.sh and overload_smoke.sh.
# Floods an in-process Master with N small mixed-priority TSR mines
# over distinct datasets, twice (fusion off, then on at the production
# window defaults), reports jobs/sec + p50/p99 fused vs unfused, and
# diffs the STRUCTURAL outcome — per-job parity, a forced deterministic
# cross-job launch, zero degrades/sheds/failures — against the
# committed BENCH_THROUGHPUT.json (walls reported, never compared).
# Pass --update to rewrite the expectations after a deliberate fusion-
# policy change; --jobs N / --workers K resize the flood for hardware.
cd "$(dirname "$0")/.."
# hard wall-clock bound like overload_smoke: a wedged broker window
# would otherwise block the poll loop until the 300 s job deadline
timeout -k 30 840 env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python bench_throughput.py "$@" || exit 1
# the ISSUE 12 zipf mix: result-reuse tier cold-vs-cached with the
# pinned hit-ratio / speedup / no-cold-p99-regression guards
exec timeout -k 30 840 env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python bench_throughput.py --mix zipf "$@"
