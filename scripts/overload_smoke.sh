#!/usr/bin/env bash
# Overload + kill-restart smoke — the admission/recovery companion to
# verify_t1.sh, bench_smoke.sh, chaos_smoke.sh and obs_smoke.sh.  Boots
# the real service with a tiny [service] queue_depth over a MiniRedis
# store, floods past capacity (exactly k sheds with 429 + Retry-After,
# shed counters on /metrics), then kill -9s a checkpointed mine between
# frontier saves and asserts the rebooted service finishes it via
# write-ahead-journal recovery while non-checkpointed orphans land in a
# durable "interrupted by restart" failure.  See scripts/overload_smoke.py
# for the assertions.
cd "$(dirname "$0")/.."
# hard wall-clock bound: a service subprocess that wedges during boot
# blocks the driver in readline(), so the whole drill runs under timeout
exec timeout -k 30 840 env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/overload_smoke.py "$@"
