#!/usr/bin/env python
"""SPAM engine + planner smoke (ISSUE 15).

Seconds-scale CI proof of the new subsystem, on two dataset SHAPES:

- a DENSE miniature (small alphabet, high bitmap fill — above the
  calibrated density crossover) and a SPARSE one (wide low-support
  alphabet — below it);
- SPAM vs oracle parity on BOTH shapes, direct (``algorithm=SPAM_TPU``
  and the CPU ``SPAM`` plugin) through the real service admission
  path;
- the AUTO routing drill: the planner must route the dense shape to
  ``SPAM_TPU`` and the sparse shape to ``SPADE_TPU`` (never SPAM below
  the crossover), each with byte parity and the decision recorded in
  the job's trace;
- an unknown engine name sheds the structured 400 whose ``supported``
  list is the live registry;
- the ``fsm_engine_selected_total{engine=...}`` family is live with
  its full zero-seeded vocabulary (the obs_smoke no-orphan contract).

Usage: scripts/spam_smoke.sh   (pins JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from spark_fsm_tpu import config as cfgmod
    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.data.synth import sub_crossover_db, synthetic_db
    from spark_fsm_tpu.data.vertical import abs_minsup, dataset_stats
    from spark_fsm_tpu.models.oracle import mine_spade
    from spark_fsm_tpu.service import planner, plugins
    from spark_fsm_tpu.service.actors import Master
    from spark_fsm_tpu.service.model import (ServiceRequest,
                                             deserialize_patterns)
    from spark_fsm_tpu.service.store import ResultStore
    from spark_fsm_tpu.utils import obs
    from spark_fsm_tpu.utils.canonical import patterns_text

    cfgmod.set_config(cfgmod.parse_config(
        {"observability": {"trace": True}}))

    dense = synthetic_db(seed=7, n_sequences=60, n_items=10,
                         mean_itemsets=3.0, mean_itemset_size=1.3)
    # 400 items x support 2: density 0.01 < 0.02 (the ONE shared
    # sub-crossover shape — see its docstring)
    sparse = sub_crossover_db()

    failures = []
    store = ResultStore()
    master = Master(store=store, miner_workers=2)

    def run(uid, algo, db, support):
        resp = master.handle(ServiceRequest("fsm", "train", {
            "algorithm": algo, "source": "INLINE",
            "sequences": format_spmf(db), "support": support,
            "uid": uid}))
        if resp.status != "started":
            failures.append(f"{uid}: submit failed: {resp.data}")
            return None
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            st = store.status(uid)
            if st in ("finished", "failure"):
                break
            time.sleep(0.02)
        if store.status(uid) != "finished":
            failures.append(f"{uid}: did not finish "
                            f"({store.status(uid)}: "
                            f"{store.get(f'fsm:error:{uid}')})")
            return None
        stats = json.loads(store.get(f"fsm:stats:{uid}") or "{}")
        return patterns_text(deserialize_patterns(store.patterns(uid))), \
            stats

    try:
        # ---- shape sanity: the two miniatures straddle the crossover
        ms_dense = abs_minsup(0.1, len(dense))
        d_stats = dataset_stats(dense, min_item_support=ms_dense)
        s_stats = dataset_stats(sparse, min_item_support=2)
        x = cfgmod.get_config().planner.density_crossover
        if not (d_stats.density >= x > s_stats.density):
            failures.append(
                f"miniatures do not straddle the crossover {x}: dense "
                f"{d_stats.density}, sparse {s_stats.density}")

        want_dense = patterns_text(mine_spade(dense, ms_dense))
        want_sparse = patterns_text(mine_spade(sparse, 2))

        # ---- direct SPAM parity on both shapes, both plugin legs
        for uid, algo, db, sup, want in (
                ("spam-dense-tpu", "SPAM_TPU", dense, "0.1", want_dense),
                ("spam-dense-cpu", "SPAM", dense, "0.1", want_dense),
                ("spam-sparse-tpu", "SPAM_TPU", sparse, "2",
                 want_sparse)):
            out = run(uid, algo, db, sup)
            if out and out[0] != want:
                failures.append(f"{uid}: NOT byte-identical to oracle")
            if out and out[1].get("engine") not in ("spam", "spam-cpu"):
                failures.append(f"{uid}: wrong engine stats {out[1]}")

        # ---- AUTO routing drill on the two shapes
        out = run("auto-dense", "AUTO", dense, "0.1")
        if out:
            if out[0] != want_dense:
                failures.append("auto-dense: parity broken")
            if out[1].get("planner_engine") != "SPAM_TPU":
                failures.append(f"auto-dense routed "
                                f"{out[1].get('planner_engine')}, "
                                f"want SPAM_TPU")
        out = run("auto-sparse", "AUTO", sparse, "2")
        if out:
            if out[0] != want_sparse:
                failures.append("auto-sparse: parity broken")
            if out[1].get("planner_engine") != "SPADE_TPU":
                failures.append(
                    f"auto-sparse routed "
                    f"{out[1].get('planner_engine')} — AUTO must never "
                    f"pick SPAM below the crossover")

        # ---- the decision is on the trace
        dump = obs.trace_dump("auto-dense")
        routes = [s for s in (dump or {}).get("spans", ())
                  if s["site"] == "planner.route"]
        if not routes or routes[0]["attrs"].get("engine") != "SPAM_TPU":
            failures.append(f"planner.route span missing/wrong: {routes}")

        # ---- unknown engine: structured 400 from the registry
        resp = master.handle(ServiceRequest("fsm", "train", {
            "algorithm": "SPQR", "source": "INLINE",
            "sequences": format_spmf(dense), "support": "0.1"}))
        if resp.data.get("http_status") != "400":
            failures.append(f"unknown engine not a 400: {resp.data}")
        elif json.loads(resp.data.get("supported", "[]")) != \
                sorted(plugins.ALGORITHMS):
            failures.append("400 'supported' list drifted from the "
                            "registry")

        # ---- metric family live with the full vocabulary
        fam = obs.REGISTRY.snapshot().get("fsm_engine_selected_total", {})
        for eng in planner.CONCRETE_ENGINES:
            if f"engine={eng}" not in fam:
                failures.append(f"fsm_engine_selected_total missing "
                                f"seeded engine={eng}")
        if fam.get("engine=SPAM_TPU", 0) < 2:  # direct + auto-dense
            failures.append(f"engine=SPAM_TPU did not count: {fam}")
        if fam.get("engine=SPADE_TPU", 0) < 1:  # auto-sparse
            failures.append(f"engine=SPADE_TPU did not count: {fam}")
    finally:
        master.shutdown()

    if failures:
        print("spam_smoke: FAILED:", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    print(f"spam_smoke: OK (dense density {d_stats.density} -> SPAM_TPU, "
          f"sparse density {s_stats.density} -> SPADE_TPU, oracle "
          f"parity on both shapes, structured 400, "
          f"fsm_engine_selected_total live)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
