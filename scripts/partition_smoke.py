#!/usr/bin/env python
"""Partitioned-mining smoke: pinned 8-virtual-device 2-D mesh mine.

The CI companion to verify_t1.sh for the equivalence-class partition
layer (parallel/partition.py + models/tsr.TsrPartitioned): on the
forced-host 8-device CPU mesh it runs the config-3 kosarak miniature
through the PARTITIONED route (2 partitions x 4-device inner seq rows)
and asserts

- BYTE PARITY with the single-device route (the exact-merge contract);
- the launch-budget-style collectives pin: cross-partition exchanges
  == deepening rounds (ONE per round), while kernel launches run an
  order of magnitude past them — the per-wave full-mesh psum is gone
  from the partitioned path;
- partition balance: the LPT plan's imbalance ratio stays under 2x;
- the fsm_partition_* metric families are LIVE on a registry scrape
  with their label vocabularies seeded.

Usage: scripts/partition_smoke.sh   (pins JAX_PLATFORMS=cpu + 8 devs)
"""

from __future__ import annotations

import sys
import time


def main() -> int:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    from spark_fsm_tpu.data.synth import kosarak_like
    from spark_fsm_tpu.models.tsr import mine_tsr_tpu
    from spark_fsm_tpu.ops import ragged_batch as RB
    from spark_fsm_tpu.parallel.mesh import make_mesh
    from spark_fsm_tpu.utils import obs
    from spark_fsm_tpu.utils.canonical import rules_text

    # pinned planner constants: the collectives/launch counters must be
    # exact on any machine (same posture as bench_smoke)
    RB.set_overhead_calibration(False)
    failures = []
    db = kosarak_like(scale=0.002, fast=True)

    t0 = time.monotonic()
    want = rules_text(mine_tsr_tpu(db, 100, 0.5, max_side=2))
    solo_s = time.monotonic() - t0

    mesh = make_mesh(8)
    stats: dict = {}
    t0 = time.monotonic()
    got = rules_text(mine_tsr_tpu(db, 100, 0.5, max_side=2, mesh=mesh,
                                  partition_parts=2, stats_out=stats))
    part_s = time.monotonic() - t0

    if got != want:
        failures.append("partitioned rules differ from the single-device "
                        "route (exact-merge contract broken)")
    rounds = stats.get("deepening_rounds", 0)
    exch = stats.get("partition_exchanges", -1)
    if exch != rounds or rounds < 1:
        failures.append(f"cross-partition exchanges ({exch}) != deepening "
                        f"rounds ({rounds}) — the per-round contract")
    launches = stats.get("kernel_launches", 0)
    if launches <= 4 * max(1, exch):
        failures.append(f"kernel_launches ({launches}) not >> exchanges "
                        f"({exch}); the pin is meaningless at this shape")
    imb = stats.get("partition_imbalance", 99.0)
    if not (1.0 <= imb < 2.0):
        failures.append(f"partition imbalance ratio out of range: {imb}")
    if stats.get("partition_cross_bytes", 0) <= 0:
        failures.append("partition_cross_bytes not counted")

    text = obs.REGISTRY.render_prometheus()
    for fam in ("fsm_partition_plans_total",
                "fsm_partition_exchange_rounds_total",
                "fsm_partition_cross_bytes_total",
                "fsm_partition_imbalance_ratio",
                "fsm_partition_mines_total"):
        if fam not in text:
            failures.append(f"metric family missing from scrape: {fam}")
    for algo in ("tsr", "spade", "cspade"):
        if f'fsm_partition_mines_total{{algo="{algo}"}}' not in text:
            failures.append(f"fsm_partition_mines_total algo={algo} "
                            "not seeded")

    if failures:
        print("partition_smoke: FAILED:", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    print(f"partition_smoke: 2x4 partitioned mine byte-identical to the "
          f"single-device route ({launches} launches, {exch} exchange "
          f"round(s), imbalance {imb}; walls solo {solo_s:.1f}s / "
          f"partitioned {part_s:.1f}s on timeshared virtual devices — "
          f"shape check, not a perf claim)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
