#!/usr/bin/env python
"""Partition-chaos storm harness (ISSUE 14) — store-outage survival
over a REAL multi-replica fleet, with a jepsen-lite invariant checker.

Topology: one MiniRedis (the shared store) fronted by ONE
:class:`~spark_fsm_tpu.utils.netproxy.NetProxy` PER replica, so the
harness can black-hole, delay, or reset each replica's store link
independently — asymmetric partitions included.  The MiniRedis is
subclassed to SNOOP lease-key writes (uid, token, replica) for the
token-monotonicity invariant.

Phases:

1. **Outage drill** (deterministic, the ISSUE 14 acceptance): submit a
   checkpointed mine to replica A, black-hole the WHOLE store (every
   proxy) mid-mine → A's storeguard proves the outage and the job
   STALLS at a safe point (never a terminal failure); restore A's
   link first → the SAME replica reacquires through the journal-gated
   NX path, replays its write-behind spool, resumes and completes
   with oracle parity, zero duplicated results, spool fully drained.

2. **Randomized storms** (seeded): for each seed, submit a mix of
   quick and checkpointed jobs across the replicas while a seeded
   schedule of faults plays out (per-replica black-hole, global
   black-hole, delay, mid-stream resets).  Then HEAL everything,
   wait for quiescence, and run the invariant checker:

   - every accepted (HTTP 200) job reached EXACTLY ONE terminal
     status (the status log carries exactly one terminal entry);
   - oracle parity on every completed mine (zero duplicated or
     corrupted results — the no-double-commit invariant observed
     from the data itself);
   - lease-token monotonicity per uid: tokens never decrease, and a
     re-SET of an existing token comes from the SAME replica (the
     spool replay's same-token reacquire is the only legal reuse);
   - quiescence: zero journal intents, leases, admission markers, or
     spooled writes left anywhere (spool gauges at 0 on every
     replica);
   - fence-rejection / replay-refusal accounting printed next to the
     verdict (each refusal is a double-commit that did NOT happen).

Usage: scripts/storm_smoke.sh            (one pinned seed — CI)
       python scripts/storm_smoke.py --seeds 5   (the acceptance run)
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import random
import re
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.parse
import urllib.request

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))

BOOT_TIMEOUT_S = 180.0
DRILL_TIMEOUT_S = 300.0
QUIESCE_TIMEOUT_S = 240.0
LEASE_TTL_S = 2.0
RECOVER_EVERY_S = 0.5
STORE_TIMEOUT_S = 1.0


def log(msg):
    print(f"storm_smoke: {msg}", flush=True)


def post(port, endpoint, timeout=60, **params):
    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{port}{endpoint}"
    try:
        with urllib.request.urlopen(url, data=data,
                                    timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read().decode())


def scrape(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=60) as resp:
        return resp.read().decode()


def series_sum(text, family, label_filter=""):
    total, seen = 0.0, False
    for line in text.splitlines():
        m = re.match(rf"^{re.escape(family)}(\{{[^}}]*\}})?\s+(\S+)$", line)
        if m and label_filter in (m.group(1) or ""):
            total += float(m.group(2))
            seen = True
    assert seen, f"{family} missing from /metrics"
    return total


def boot_service(cfg_path, env, name):
    child = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import sys\n"
        f"sys.argv = ['app', '--config', {str(cfg_path)!r}]\n"
        "from spark_fsm_tpu.service.app import main\n"
        "main()\n"
    )
    proc = subprocess.Popen([sys.executable, "-c", child], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    port = replica = None
    deadline = time.time() + BOOT_TIMEOUT_S
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise AssertionError(
                f"replica {name} died at boot (rc={proc.poll()})")
        if line.startswith("cluster replica "):
            replica = line.split()[2]
        if "spark_fsm_tpu service on http://" in line:
            port = int(line.rsplit(":", 1)[1])
            break
    assert port is not None, f"no boot line from {name} within the timeout"
    assert replica is not None, f"no cluster-replica line from {name}"
    # keep draining the pipe for the life of the drill: the replicas
    # log every checkpoint/status/storeguard event at INFO, and an
    # undrained 64KB pipe buffer would eventually block a log write
    # inside the service — a wedge that reads as a lost job
    import threading

    def _drain(stream):
        for _ in stream:
            pass

    threading.Thread(target=_drain, args=(proc.stdout,),
                     daemon=True).start()
    return proc, port, replica


def make_snooping_miniredis():
    """MiniRedis subclass recording every fsm:lease:* SET — the
    token-monotonicity invariant's evidence stream."""
    from test_redis_store import MiniRedis

    class SnoopingMiniRedis(MiniRedis):
        def __init__(self):
            super().__init__()
            self.lease_sets = []  # (uid, token, replica)

        def _dispatch(self, args):
            cmd = args[0].upper()
            if cmd == "SET" and args[1].startswith("fsm:lease:") \
                    and args[1] != "fsm:lease:token":
                try:
                    rec = json.loads(args[2])
                    self.lease_sets.append(
                        (args[1][len("fsm:lease:"):],
                         int(rec.get("token", -1)),
                         str(rec.get("replica", "?"))))
                except (ValueError, TypeError):
                    pass
            return super()._dispatch(args)

    return SnoopingMiniRedis()


class Fleet:
    """2 replicas, each behind its own proxy, over one MiniRedis."""

    def __init__(self):
        from spark_fsm_tpu.utils.netproxy import NetProxy

        self.mini = make_snooping_miniredis()
        log(f"MiniRedis on port {self.mini.port}")
        self.proxies = [NetProxy("127.0.0.1", self.mini.port)
                        for _ in range(2)]
        self.tmp = tempfile.mkdtemp(prefix="storm_smoke_")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = str(REPO) + os.pathsep + \
            env.get("PYTHONPATH", "")
        self.procs, self.ports, self.replicas = [], [], []
        for i, proxy in enumerate(self.proxies):
            cfg_path = os.path.join(self.tmp, f"replica{i}.json")
            with open(cfg_path, "w") as fh:
                json.dump({
                    "fault_injection": True,
                    "service": {"port": 0, "miner_workers": 1,
                                "queue_depth": 16},
                    "store": {"backend": "redis", "host": "127.0.0.1",
                              "port": proxy.port,
                              "timeout_s": STORE_TIMEOUT_S},
                    "cluster": {"enabled": True,
                                "lease_ttl_s": LEASE_TTL_S,
                                "recover_every_s": RECOVER_EVERY_S},
                    "storeguard": {"enabled": True,
                                   "probe_every_s": 0.25,
                                   "down_after": 1,
                                   "spool_max_entries": 4096,
                                   "stall_max_s": 120.0},
                    "observability": {"trace": True,
                                      "spine_flush_spans": 8},
                    "engine": {"fused": "queue"},
                }, fh)
            proc, port, rid = boot_service(cfg_path, env, f"R{i}")
            log(f"replica R{i} {rid} on port {port} (pid {proc.pid}) "
                f"via proxy :{proxy.port}")
            self.procs.append(proc)
            self.ports.append(port)
            self.replicas.append(rid)

    def direct(self):
        """A RESP client straight to the MiniRedis (the omniscient
        observer — never routed through a proxy)."""
        from spark_fsm_tpu.service.resp import RespClient

        return RespClient(port=self.mini.port)

    def heal_all(self):
        for p in self.proxies:
            p.heal()

    def close(self):
        for proc in self.procs:
            if proc.poll() is None:
                proc.kill()
        for p in self.proxies:
            p.close()
        self.mini.close()


# --------------------------------------------------------------- invariants


def check_invariants(fleet, accepted, oracles, phase):
    """The jepsen-lite checker; every violation is a hard failure."""
    from spark_fsm_tpu.service.model import deserialize_patterns
    from spark_fsm_tpu.utils.canonical import diff_patterns, patterns_text

    client = fleet.direct()
    violations = []

    # quiescence: journals/leases/markers settle; spools drain
    deadline = time.time() + QUIESCE_TIMEOUT_S
    leftovers = None
    while time.time() < deadline:
        leftovers = (client.keys("fsm:journal:*")
                     + [k for k in client.keys("fsm:lease:*")
                        if k != "fsm:lease:token"]
                     + client.keys("fsm:admission:*"))
        spooled = 0.0
        try:
            for port in fleet.ports:
                spooled += series_sum(scrape(port),
                                      "fsm_storeguard_spool_entries")
        except Exception:
            spooled = -1.0
        terminal = all(
            client.get(f"fsm:status:{uid}") in ("finished", "failure")
            for uid in accepted)
        if not leftovers and spooled == 0.0 and terminal:
            break
        time.sleep(0.25)
    else:
        violations.append(f"no quiescence: leftovers={leftovers} "
                          f"spooled={spooled}")
        # diagnostics: who owns the stuck uids, and what do the
        # replicas' guards think is happening?
        for key in leftovers or ():
            log(f"  [diag] {key} = {client.get(key)!r}")
        for port in fleet.ports:
            try:
                _, health = post(port, "/admin/health", timeout=45)
                log(f"  [diag] :{port} storeguard="
                    f"{health.get('storeguard')} "
                    f"admission={health.get('admission')}")
            except Exception as exc:
                log(f"  [diag] :{port} health unreachable: {exc}")

    # exactly-once settlement: ONE terminal entry in each status log
    for uid in sorted(accepted):
        st = client.get(f"fsm:status:{uid}")
        if st not in ("finished", "failure"):
            violations.append(f"{uid}: no terminal status ({st!r})")
            continue
        entries = [e.partition(":")[2]
                   for e in client.lrange(f"fsm:status:log:{uid}")]
        terminals = [e for e in entries if e in ("finished", "failure")]
        if len(terminals) != 1:
            violations.append(
                f"{uid}: settled {len(terminals)} times ({entries})")

    # oracle parity on every completed mine (zero dup/corrupt results)
    parity_ok = 0
    for uid, want_text in sorted(oracles.items()):
        if client.get(f"fsm:status:{uid}") != "finished":
            continue
        raw = client.get(f"fsm:pattern:{uid}")
        if raw is None:
            violations.append(f"{uid}: finished but no patterns")
            continue
        got = deserialize_patterns(raw)
        if patterns_text(got) != want_text:
            violations.append(f"{uid}: PARITY VIOLATION")
        else:
            parity_ok += 1

    # lease-token monotonicity: per uid, tokens never decrease, and a
    # token REUSE (the spool replay's same-token reacquire) must come
    # from the same replica that held it
    last = {}
    for uid, token, replica in fleet.mini.lease_sets:
        prev = last.get(uid)
        if prev is not None:
            ptok, prep = prev
            if token < ptok:
                violations.append(
                    f"{uid}: token regressed {ptok} -> {token}")
            if token == ptok and replica != prep:
                violations.append(
                    f"{uid}: token {token} reused across replicas "
                    f"{prep} -> {replica}")
        last[uid] = (token, replica)

    # accounting next to the verdict
    fences = spool_refused = replays = stalls = 0.0
    for port in fleet.ports:
        text = scrape(port)
        fences += series_sum(text, "fsm_lease_fence_rejections_total")
        spool_refused += series_sum(
            text, "fsm_storeguard_replays_total", 'outcome="refused"')
        replays += series_sum(
            text, "fsm_storeguard_replays_total", 'outcome="ok"')
        stalls += series_sum(
            text, "fsm_storeguard_stalls_total", 'outcome="entered"')
    log(f"[{phase}] checked {len(accepted)} accepted jobs: "
        f"parity_ok={parity_ok} replays_ok={int(replays)} "
        f"replays_refused={int(spool_refused)} "
        f"fence_rejections={int(fences)} stalls={int(stalls)} "
        f"lease_sets={len(fleet.mini.lease_sets)}")
    client.close()
    assert not violations, "INVARIANT VIOLATIONS:\n  " + \
        "\n  ".join(violations)


# -------------------------------------------------------------- the drill


def outage_drill(fleet):
    """Phase 1: the deterministic black-hole-the-store acceptance."""
    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.data.synth import synthetic_db
    from spark_fsm_tpu.data.vertical import abs_minsup
    from spark_fsm_tpu.models.oracle import mine_spade
    from spark_fsm_tpu.utils.canonical import patterns_text

    port_a, port_b = fleet.ports
    rep_a = fleet.replicas[0]
    client = fleet.direct()

    # slow every frontier save on A so the drill spans the outage
    code, _ = post(port_a, "/admin/faults", action="arm",
                   site="checkpoint.save", every="1", delay_s="1.0",
                   exc="none")
    assert code == 200, "chaos lab refused the arm"
    db = synthetic_db(seed=41, n_sequences=200, n_items=12,
                      mean_itemsets=3.0, mean_itemset_size=1.3)
    want = patterns_text(mine_spade(db, abs_minsup(0.05, len(db))))
    code, body = post(port_a, "/train", uid="drill",
                      algorithm="SPADE_TPU", source="INLINE",
                      sequences=format_spmf(db), support="0.05",
                      checkpoint="1", checkpoint_every_s="0")
    assert code == 200 and body["status"] == "started", body

    deadline = time.time() + DRILL_TIMEOUT_S
    while time.time() < deadline:
        if client.get("fsm:frontier:drill"):
            break
        time.sleep(0.1)
    assert client.get("fsm:frontier:drill"), "no frontier save seen"

    # BLACK-HOLE the whole store: every replica's link swallowed
    for p in fleet.proxies:
        p.blackhole(True)
    log("store black-holed fleet-wide mid-checkpointed-mine")

    # A must prove the outage and STALL the drill — never fail it.
    # NOTE the admin endpoints stay up during the outage but are SLOW
    # (each store-counter read burns a transport timeout): poll with a
    # generous per-request timeout.
    stalled, sg = False, {}
    deadline = time.time() + DRILL_TIMEOUT_S
    while time.time() < deadline:
        try:
            code, health = post(port_a, "/admin/health", timeout=45)
        except Exception:
            time.sleep(0.25)
            continue
        sg = (health or {}).get("storeguard") or {}
        if sg.get("state") == "down" and sg.get("stalled_jobs", 0) >= 1:
            stalled = True
            break
        time.sleep(0.25)
    assert stalled, f"drill never stalled (storeguard: {sg})"
    assert client.get("fsm:status:drill") not in ("finished", "failure"), \
        "drill reached a terminal status during the outage"
    log(f"outage proven on A: state=down, drill stalled "
        f"(spool {sg.get('spool_entries')} entries)")

    # restore A's link FIRST: the SAME replica must reacquire (journal-
    # gated NX under its own token) and resume; B heals a beat later
    fleet.proxies[0].heal()
    log("healed A's store link (B still black-holed)")
    deadline = time.time() + DRILL_TIMEOUT_S
    reacquired = False
    while time.time() < deadline:
        raw = client.get("fsm:lease:drill")
        if raw and json.loads(raw).get("replica") == rep_a:
            reacquired = True
            break
        st = client.get("fsm:status:drill")
        if st in ("finished", "failure"):
            reacquired = st == "finished"  # resumed+completed already
            break
        time.sleep(0.1)
    assert reacquired, "A never reacquired the drill after the heal"
    fleet.proxies[1].heal()

    deadline = time.time() + DRILL_TIMEOUT_S
    status = None
    while time.time() < deadline:
        code, body = post(port_a, "/status/drill")
        status = body.get("status")
        if status in ("finished", "failure"):
            break
        time.sleep(0.25)
    assert status == "finished", (status, body)
    journal = client.get("fsm:journal:drill")
    # journal intents are enveloped on the wire now — unwrap first
    from spark_fsm_tpu.utils import envelope
    assert journal is None or \
        json.loads(envelope.unwrap(journal)[0] or "{}").get("replica") == rep_a
    code, body = post(port_a, "/get/patterns", uid="drill")
    from spark_fsm_tpu.service.model import deserialize_patterns
    got = patterns_text(deserialize_patterns(body["data"]["patterns"]))
    assert got == want, "oracle parity violated after outage resume"
    # spool fully drained; the stall was entered and resumed on A
    text = scrape(port_a)
    assert series_sum(text, "fsm_storeguard_spool_entries") == 0.0
    assert series_sum(text, "fsm_storeguard_replays_total",
                      'outcome="ok"') >= 1
    assert series_sum(text, "fsm_storeguard_stalls_total",
                      'outcome="resumed"') >= 1
    post(port_a, "/admin/faults", action="disarm", site="checkpoint.save")
    client.close()
    log("outage drill ok: stall -> same-replica resume -> parity, "
        "spool drained")
    return {"drill": want}


# --------------------------------------------------------------- the storm


def storm_round(fleet, seed, accepted, oracles):
    """One seeded randomized fault schedule over live traffic."""
    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.data.synth import synthetic_db
    from spark_fsm_tpu.data.vertical import abs_minsup
    from spark_fsm_tpu.models.oracle import mine_spade
    from spark_fsm_tpu.utils.canonical import patterns_text

    rng = random.Random(seed)
    log(f"storm seed={seed}")

    # job templates: a couple of tiny dataset families with precomputed
    # oracles, mined as quick jobs or checkpointed slow drills
    dbs = []
    for fam in range(2):
        db = synthetic_db(seed=100 + fam, n_sequences=80, n_items=10,
                          mean_itemsets=2.5, mean_itemset_size=1.2)
        dbs.append((format_spmf(db),
                    patterns_text(mine_spade(db,
                                             abs_minsup(0.1, len(db))))))

    shed = 0
    for step in range(8):
        uid = f"storm-{seed}-{step}"
        port = fleet.ports[rng.randrange(len(fleet.ports))]
        text, want = dbs[rng.randrange(len(dbs))]
        params = dict(uid=uid, algorithm="SPADE_TPU", source="INLINE",
                      sequences=text, support="0.1")
        if rng.random() < 0.4:
            params.update(checkpoint="1", checkpoint_every_s="0")
        try:
            code, body = post(port, "/train", timeout=30, **params)
        except Exception as exc:
            log(f"  submit {uid} failed transport-side ({exc}) — "
                f"counts as shed")
            shed += 1
            code = 0
        if code == 200 and body.get("status") == "started":
            accepted.add(uid)
            oracles[uid] = want
        else:
            shed += 1

        # seeded fault event between submits
        roll = rng.random()
        if roll < 0.30:
            victim = rng.randrange(len(fleet.proxies))
            dur = 0.5 + 2.0 * rng.random()
            log(f"  event: black-hole R{victim} for {dur:.1f}s")
            fleet.proxies[victim].blackhole(True)
            time.sleep(dur)
            fleet.proxies[victim].heal()
        elif roll < 0.45:
            dur = 1.0 + 2.0 * rng.random()
            log(f"  event: GLOBAL black-hole for {dur:.1f}s")
            for p in fleet.proxies:
                p.blackhole(True)
            time.sleep(dur)
            fleet.heal_all()
        elif roll < 0.65:
            victim = rng.randrange(len(fleet.proxies))
            d = 0.05 + 0.15 * rng.random()
            log(f"  event: delay R{victim} by {d * 1000:.0f}ms")
            fleet.proxies[victim].delay(d)
            time.sleep(1.0)
            fleet.proxies[victim].heal()
        elif roll < 0.80:
            victim = rng.randrange(len(fleet.proxies))
            n = fleet.proxies[victim].reset_all()
            log(f"  event: reset R{victim} ({n} connections)")
        else:
            time.sleep(0.3 + 0.5 * rng.random())

    fleet.heal_all()
    log(f"  seed {seed}: {len(accepted)} accepted so far, "
        f"{shed} shed this round; healing + quiescing")


def main():
    ap = argparse.ArgumentParser(description="partition-chaos storm "
                                             "harness")
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("SPARKFSM_STORM_SEED",
                                               "7001")))
    ap.add_argument("--seeds", type=int, default=1,
                    help="number of consecutive seeds to storm "
                         "(seed, seed+1, ...); the acceptance run "
                         "uses 5")
    ap.add_argument("--skip-drill", action="store_true")
    args = ap.parse_args()

    fleet = Fleet()
    try:
        if not args.skip_drill:
            oracles = outage_drill(fleet)
            check_invariants(fleet, {"drill"}, oracles, "drill")
        for i in range(args.seeds):
            seed = args.seed + i
            accepted, oracles = set(), {}
            storm_round(fleet, seed, accepted, oracles)
            check_invariants(fleet, accepted, oracles, f"seed {seed}")
    finally:
        fleet.close()
    log("PASS")


if __name__ == "__main__":
    main()
