#!/usr/bin/env python
"""Result-reuse smoke: boot with ``[rescache] enabled``, drive a hit, a
coalesce, and a dominated serve over HTTP, assert parity + live metrics.

The CI companion to obs_smoke/chaos_smoke for the result-reuse tier
(ISSUE 12, service/resultcache.py): it boots the real HTTP service with
the tier on and ONE miner worker, then

- mines a base TSR job cold (the first mine also pays the compile, so
  it reliably occupies the single worker);
- submits an identical pair while the worker is busy: the first queues
  as a coalescing LEADER, the second attaches as a FOLLOWER and is
  delivered by fan-out — byte-identical rules, its own stats/status;
- repeats the request after completion: an EXACT cache hit;
- requests a strictly weaker variant (smaller k): a DOMINATED serve,
  checked byte-identical (canonical text) against a local cold oracle
  (models/tsr.mine_tsr_cpu);
- asserts the fsm_rescache_* metric families are live on /metrics with
  nonzero hit/coalesce/dominated counters, /admin/rescache reports the
  resident entry, and the journal namespace drained (no stuck uids).

Usage: scripts/rescache_smoke.sh   (pins JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import json
import sys
import time
import urllib.parse
import urllib.request


def main() -> int:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from spark_fsm_tpu import config as cfgmod
    from spark_fsm_tpu.data.spmf import format_spmf
    from spark_fsm_tpu.data.synth import synthetic_db
    from spark_fsm_tpu.models.tsr import mine_tsr_cpu
    from spark_fsm_tpu.service.app import serve_background
    from spark_fsm_tpu.service.model import deserialize_rules
    from spark_fsm_tpu.utils.canonical import rules_text

    cfgmod.set_config(cfgmod.parse_config({"rescache": {"enabled": True}}))
    srv = serve_background()
    port = srv.server_port

    def post(ep, **params):
        data = urllib.parse.urlencode(params).encode()
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{ep}",
                                    data=data, timeout=120) as r:
            return r.read().decode()

    def train(uid, text, **params):
        d = {"algorithm": "TSR_TPU", "source": "INLINE",
             "sequences": text, "k": "8", "minconf": "0.4",
             "max_side": "2", "uid": uid}
        d.update(params)
        resp = json.loads(post("/train", **d))
        assert resp["status"] != "failure", resp
        return resp

    def wait(uid, timeout=240.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = json.loads(post(f"/status/{uid}"))
            if st["status"] in ("finished", "failure"):
                return st
            time.sleep(0.05)
        raise TimeoutError(f"job {uid} never finished")

    def stats_of(st):
        return json.loads(st.get("data", {}).get("stats", "{}"))

    failures = []
    try:
        db_a = synthetic_db(seed=71, n_sequences=80, n_items=10,
                            mean_itemsets=3.0, mean_itemset_size=1.3)
        db_b = synthetic_db(seed=72, n_sequences=80, n_items=10,
                            mean_itemsets=3.0, mean_itemset_size=1.3)
        text_a, text_b = format_spmf(db_a), format_spmf(db_b)

        # the blocker pins the single worker (first mine pays the
        # compile); leader + follower land while it runs
        train("rc-blk", text_a)
        train("rc-lead", text_b)
        train("rc-follow", text_b)
        for uid in ("rc-blk", "rc-lead", "rc-follow"):
            st = wait(uid)
            if st["status"] != "finished":
                failures.append(f"{uid} did not finish: {st}")
        st_follow = wait("rc-follow")
        if stats_of(st_follow).get("coalesced_into") != "rc-lead":
            failures.append(
                f"follower was not coalesced onto rc-lead: "
                f"{stats_of(st_follow)}")
        rules_lead = json.loads(post("/get/rules", uid="rc-lead"))
        rules_follow = json.loads(post("/get/rules", uid="rc-follow"))
        if rules_lead["data"].get("rules") != \
                rules_follow["data"].get("rules"):
            failures.append("follower rules differ from leader rules")

        # exact hit after completion
        train("rc-hit", text_b)
        st = wait("rc-hit")
        if stats_of(st).get("served_from_cache") != "exact":
            failures.append(f"repeat request not an exact hit: "
                            f"{stats_of(st)}")
        rules_hit = json.loads(post("/get/rules", uid="rc-hit"))
        if rules_hit["data"].get("rules") != \
                rules_lead["data"].get("rules"):
            failures.append("exact-hit rules differ from the cold run")

        # dominated serve: smaller k, parity vs a local cold oracle
        train("rc-dom", text_b, k="4")
        st = wait("rc-dom")
        if stats_of(st).get("served_from_cache") != "dominated":
            failures.append(f"smaller-k request not served dominated: "
                            f"{stats_of(st)}")
        got = rules_text(deserialize_rules(
            json.loads(post("/get/rules", uid="rc-dom"))["data"]["rules"]))
        want = rules_text(mine_tsr_cpu(db_b, 4, 0.4, max_side=2))
        if got != want:
            failures.append("dominated serve is NOT byte-identical to "
                            "the cold oracle at k=4")

        # live metric families with the drill's counts
        text = post("/metrics")
        for fam, floor in (("fsm_rescache_hits_total", 1),
                           ("fsm_rescache_coalesced_total", 1),
                           ("fsm_rescache_dominated_serves_total", 1),
                           ("fsm_rescache_misses_total", 1),
                           ("fsm_rescache_errors_total", 0),
                           ("fsm_rescache_bytes", 1)):
            vals = [float(line.rsplit(" ", 1)[1])
                    for line in text.splitlines()
                    if line.startswith(fam + " ")
                    or line.startswith(fam + "{")]
            if not vals:
                failures.append(f"/metrics missing family {fam}")
            elif sum(vals) < floor:
                failures.append(f"{fam} = {sum(vals)} < {floor}")

        admin = json.loads(post("/admin/rescache"))
        if not admin.get("enabled") or not admin.get("entries"):
            failures.append(f"/admin/rescache incomplete: {admin}")

        # zero stuck uids: every journal intent settled
        leftover = srv.master.store.keys("fsm:journal:")
        if leftover:
            failures.append(f"journal intents leaked: {leftover}")
    finally:
        srv.master.shutdown()
        srv.shutdown()
    if failures:
        print("rescache_smoke: FAILED:", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    print("rescache_smoke: hit + coalesce + dominated-serve over HTTP "
          "all parity-checked, metric families live, journal drained")
    return 0


if __name__ == "__main__":
    sys.exit(main())
