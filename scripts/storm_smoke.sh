#!/usr/bin/env bash
# Partition-chaos storm smoke (ISSUE 14) — CI entry for
# scripts/storm_smoke.py: the deterministic store-outage drill (black-
# hole mid-checkpointed-mine -> stall -> same-replica resume with
# oracle parity and a drained spool) plus ONE pinned-seed randomized
# fault schedule over a real 2-replica fleet behind per-replica TCP
# proxies, closed by the jepsen-lite invariant checker (exactly-once
# settlement, parity, token monotonicity, quiescence).  Override the
# seed with SPARKFSM_STORM_SEED (or run storm_smoke.py --seeds 5 for
# the multi-seed acceptance sweep); a failure under a new seed is a
# real recovery bug, not flake.  Hard timeout so a wedged fleet fails
# loudly instead of hanging CI.
cd "$(dirname "$0")/.."
exec timeout -k 15 900 env JAX_PLATFORMS=cpu \
    SPARKFSM_STORM_SEED="${SPARKFSM_STORM_SEED:-7001}" \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/storm_smoke.py "$@"
