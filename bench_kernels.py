#!/usr/bin/env python
"""Kernel roofline microbench -> KERNELS.json (SURVEY.md sec 5 tracing row).

Times the two production Pallas kernels at their headline geometries,
computes achieved HBM bandwidth from an explicit traffic model, reports
the fraction of the v5e HBM roofline, and times the jnp fallback paths at
the same geometry — replacing the docstring anecdotes ("~3x over the jnp
path", "45.5 ms") with committed, reproducible numbers.

Traffic models (what the BlockSpecs actually stream from HBM):

- ``pair_supports`` grid (P/P_T, NI/I_T, S/S_B): a parent block is
  re-read once per ITEM TILE and an item block once per PARENT TILE, so
  bytes = P*NI*S*4*(1/I_TILE + 1/P_TILE) + 4*P*NI (out, written once).
  The *minimum useful* bytes (every row read exactly once) is
  (P+NI)*S*4 — the tiling factor between the two is the known cost of
  computing a full pair matrix with bounded VMEM.
- ``rule_supports`` grid (C, S/sb): per candidate per seq step the kernel
  streams km prefix blocks + km suffix blocks, so bytes = C*S*4*2*km
  (+ 8*C out).

Achieved GB/s = model bytes / median wall.  Percent-of-peak uses the v5e
HBM figure (819 GB/s/chip); on other TPU generations re-derive.  The jnp
comparisons run the same candidate workload through the non-Pallas paths
the engines actually fall back to (the dense jnp pair matrix; the
chunked gather evaluator for rules, extrapolated from a timed slice
because the full width would not fit HBM).

Runs ONLY on a real TPU (the numbers are meaningless elsewhere); prints
one JSON line per kernel and writes KERNELS.json unless BENCH_KERNELS_OUT=0.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

from spark_fsm_tpu.utils.probe import tpu_probe

V5E_HBM_GBPS = 819.0  # v5e HBM peak per chip

# v5e VPU throughput for the op-level compute model: (8 x 128) vector
# slots x 4 ALUs x ~1.5 GHz clock.  The clock is derived from the public
# peak (197 bf16 TFLOP/s over 4 MXUs x 128x128 MACs x 2 flops =>
# 197e12 / 131072 ~= 1.5e9); int8's 394 TOP/s gives the same figure.
V5E_VPU_OPS = 8 * 128 * 4 * 1.5e9

# The pair kernel's per-element VPU op count and the grid/traffic model
# live with the kernel (ops/pallas_support.grid_model — the ONE
# definition), so the bench can never model a program the kernel didn't
# run.  V5E_VPU_OPS stays here: it is a hardware figure, not a kernel
# property.


def _roundtrip_s() -> float:
    """One dispatch + 4-byte readback on the current backend — the fence
    cost subtracted from every amortized measurement below."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    x = jnp.zeros((8,), jnp.int32)
    np.asarray(jnp.sum(x))  # compile + warm
    walls = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(jnp.sum(x))
        walls.append(time.perf_counter() - t0)
    return statistics.median(walls)


def _amortized_wall(fn, *, n_iters: int = 10, repeats: int = 3,
                    roundtrip_s: float = 0.0) -> tuple[float, list]:
    """Median per-call device wall of ``fn`` (a dispatch returning a
    device array).

    ``jax.block_until_ready`` does NOT wait for execution on the tunneled
    axon backend (measured: a 45 ms kernel 'completed' in 0.4 ms), so a
    naive per-call timer reads dispatch latency, not kernel wall.  This
    measures N back-to-back dispatches fenced by ONE 4-byte sum readback
    (the device executes dispatches in order; the sum depends on the last
    output), subtracts the separately measured roundtrip, and divides by
    N."""
    import jax.numpy as jnp
    import numpy as np

    np.asarray(jnp.sum(fn()))  # compile + warm + fence
    walls = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = None
        for _ in range(n_iters):
            out = fn()
        np.asarray(jnp.sum(out))
        walls.append(
            max(0.0, time.perf_counter() - t0 - roundtrip_s) / n_iters)
    return statistics.median(walls), [round(w, 4) for w in walls]


def bench_pair_supports() -> dict:
    """Headline SPADE geometry: the [2048 x 384] pair matrix over a
    BMS-WebView-2-shaped sequence axis (77.5k padded to the seq block) —
    the per-wave workload of the classic engine's Pallas path."""
    import jax
    import jax.numpy as jnp

    from spark_fsm_tpu.models.spade_fused import _dense_pair_jnp
    from spark_fsm_tpu.ops import pallas_support as PS

    P, NI, W = 2048, 384, 1
    S = -(-77500 // PS.S_BLOCK) * PS.S_BLOCK  # 77824 (19 x 4096)
    # synthesize ON DEVICE: shipping ~0.8 GB of host randomness through a
    # ~10 MB/s tunnel would take minutes and measure nothing
    k1, k2 = jax.random.split(jax.random.PRNGKey(7))
    # ~6% bit density (a realistic id-list fill for the headline mine)
    bits = jax.jit(lambda k, s: jax.random.bernoulli(
        k, 0.06, s).astype(jnp.uint32), static_argnums=1)
    pt = jax.block_until_ready(bits(k1, (P, W, S)))
    items = jax.block_until_ready(bits(k2, (NI, W, S)))

    rt = _roundtrip_s()
    wall, walls = _amortized_wall(
        lambda: PS.pair_supports(pt, items, NI), roundtrip_s=rt)
    # the default call takes the kernel's ADAPTIVE tiles at this geometry
    # — the grid/traffic/compute model comes from the kernel's OWN
    # model helper (PS.grid_model resolves tiles via effective_tiles,
    # SPARKFSM_PAIR_P_TILE pin included), so the modeled program is the
    # measured one by construction
    gm = PS.grid_model(P, NI, W, S, items_rows=items.shape[0])
    eff_p, eff_i = gm["p_tile"], gm["i_tile"]
    model_bytes = gm["model_bytes"]
    min_bytes = gm["min_useful_bytes"]

    # jnp fallback at the same geometry (the engine's _dense_pair_jnp)
    pt3 = jnp.transpose(pt, (0, 2, 1))        # [P, S, W] engine layout
    items3 = jnp.transpose(items, (0, 2, 1))
    dense = jax.jit(_dense_pair_jnp)
    jnp_wall, _ = _amortized_wall(lambda: dense(pt3, items3),
                                  n_iters=4, roundtrip_s=rt)

    # tile sweep: the evidence behind the default tiles (and the check
    # that no neighboring config leaves real wall time on the table).
    # Every config is feasible at this geometry (i_tile must divide into
    # the allocated NI=384 rows after rounding; s_block must divide
    # S=77824, a multiple of 4096 but not 8192).  Skipped with
    # BENCH_KERNELS_SWEEP=0.  Sweep walls use the same amortized fence
    # as the headline; an unexpected failure records its error.
    sweep = []
    if os.environ.get("BENCH_KERNELS_SWEEP") != "0":
        for ptile, itile, sb in ((8, 128, 4096), (16, 128, 4096),
                                 (32, 128, 4096), (16, 384, 4096),
                                 (32, 384, 4096), (16, 128, 2048)):
            try:
                w, _ = _amortized_wall(
                    lambda: PS.pair_supports(pt, items, NI, s_block=sb,
                                             p_tile=ptile, i_tile=itile),
                    n_iters=8, repeats=3, roundtrip_s=rt)
                sweep.append({"p_tile": ptile, "i_tile": itile,
                              "s_block": sb, "wall_ms": round(w * 1e3, 2)})
            except Exception as exc:
                sweep.append({"p_tile": ptile, "i_tile": itile,
                              "s_block": sb,
                              "error": repr(exc).split("\n")[0][:120]})

    # Op-level compute model: is 46%-of-HBM-peak a tuning failure or the
    # VPU roofline?  Every (parent, item, seq-word) element costs
    # PS.PAIR_VPU_OPS_PER_WORD VPU ops; the theoretical compute-bound
    # wall at the v5e VPU rate decides which roofline binds.
    compute_ops = gm["vpu_ops"]
    compute_wall_s = compute_ops / V5E_VPU_OPS
    hbm_wall_s = model_bytes / (V5E_HBM_GBPS * 1e9)

    # Overhead-decomposed roofline (VERDICT Weak #1: attribute the
    # residual ~8% under the 4-ALU rate, don't hand-wave it):
    # (1) grid-step overhead — sweep PAIRS with IDENTICAL element work
    #     but different step counts isolate the per-step constant
    #     (Mosaic prologue + block DMA turnaround); two independent
    #     pairs cross-check the estimate;
    # (2) the tile landscape — if no swept config beats the default by
    #     more than session noise, whatever remains after subtracting
    #     compute + grid overhead is ISSUE INEFFICIENCY (bounds/scalar
    #     bookkeeping, DMA-overlap edges), not tuning headroom.
    def _steps(ptile, itile, sb):
        return PS.grid_model(P, NI, W, S, s_block=sb, p_tile=ptile,
                             i_tile=itile)["grid_steps"]

    base_steps = gm["grid_steps"]
    by_tile = {(r.get("p_tile"), r.get("i_tile"), r.get("s_block")):
               r.get("wall_ms") for r in sweep if "wall_ms" in r}
    # step-count-isolating pairs: (16,128)v(16,384) = 3x steps at ~same
    # traffic; (8,128)v(32,384) = 12x steps (traffic differs by the
    # non-binding reread term — the cross-check bounds that error)
    per_step_est = []
    for (a, b) in (((16, 128), (16, 384)), ((8, 128), (32, 384))):
        w_many = by_tile.get((a[0], a[1], PS.S_BLOCK))
        w_few = by_tile.get((b[0], b[1], PS.S_BLOCK))
        if w_many and w_few and w_many > w_few:
            d_steps = (_steps(a[0], a[1], PS.S_BLOCK)
                       - _steps(b[0], b[1], PS.S_BLOCK))
            if d_steps > 0:
                per_step_est.append((w_many - w_few) / d_steps)
    per_step_ms = (statistics.median(per_step_est)
                   if per_step_est else None)
    overhead_ms = per_step_ms * base_steps if per_step_ms else 0.0
    wall_ms = wall * 1e3
    compute_ms = compute_wall_s * 1e3
    walls_sorted = sorted(r["wall_ms"] for r in sweep if "wall_ms" in r)

    vpu_model = {
        "ops_per_word": PS.PAIR_VPU_OPS_PER_WORD,
        "total_vpu_ops": int(compute_ops),
        "v5e_vpu_ops_per_s": V5E_VPU_OPS,
        "compute_bound_wall_ms": round(compute_wall_s * 1e3, 2),
        "hbm_bound_wall_ms": round(hbm_wall_s * 1e3, 2),
        "binding_roofline": ("vpu" if compute_wall_s > hbm_wall_s
                             else "hbm"),
        "pct_vpu_roofline": round(100 * compute_wall_s / wall, 1),
        "grid_steps": base_steps,
        "grid_overhead_ms": round(overhead_ms, 2),
        "pct_vpu_roofline_ex_overhead": round(
            100 * compute_ms / max(wall_ms - overhead_ms, 1e-9), 1),
        # the full attribution: wall = VPU compute + per-step grid
        # overhead + residual (issue inefficiency) — each term measured
        # or modeled, none inferred by subtraction alone except the
        # residual, which is exactly the unattributed remainder
        "overhead_decomposition": {
            "wall_ms": round(wall_ms, 2),
            "vpu_compute_ms": round(compute_ms, 2),
            "grid_overhead_ms": round(overhead_ms, 2),
            "residual_ms": round(max(0.0, wall_ms - compute_ms
                                     - overhead_ms), 2),
            "per_step_us_estimates": [round(v * 1e3, 4)
                                      for v in per_step_est],
            "pct_wall": {
                "vpu_compute": round(100 * compute_ms / wall_ms, 1),
                "grid_overhead": round(100 * overhead_ms / wall_ms, 1),
                "residual": round(100 * max(0.0, wall_ms - compute_ms
                                            - overhead_ms) / wall_ms, 1),
            },
        },
    }
    if walls_sorted:
        # the denominator's justification: six tile configs span a FLAT
        # landscape (no config beats the adaptive default by more than
        # session noise), so the residual ~9% under the theoretical
        # 4-ALU rate is issue inefficiency (bounds/scalar bookkeeping,
        # DMA-overlap edges), not a reachable tuning gap.  A VMEM-
        # resident ALU microbench was tried and rejected: its fori_loop
        # scheduling measured 21-53% of peak — loop artifacts, not the
        # kernel's sustained rate — and would have muddied the model.
        vpu_model["tile_landscape_ms"] = {
            "best": walls_sorted[0], "worst": walls_sorted[-1],
            "default": round(wall_ms, 2)}

    return {
        "kernel": "pair_supports (ops/pallas_support.py)",
        "geometry": f"P={P} NI={NI} S={S} W={W} "
                    f"tiles P_T={eff_p} I_T={eff_i} S_B={PS.S_BLOCK} "
                    "(adaptive defaults)",
        "wall_ms": round(wall * 1e3, 2),
        "amortized_walls_s": walls,
        "traffic_model_bytes": int(model_bytes),
        "achieved_GBps": round(model_bytes / wall / 1e9, 1),
        "pct_v5e_hbm_peak": round(100 * model_bytes / wall / 1e9
                                  / V5E_HBM_GBPS, 1),
        "min_useful_bytes": int(min_bytes),
        "effective_GBps_min_bytes": round(min_bytes / wall / 1e9, 1),
        "vpu_model": vpu_model,
        "jnp_wall_ms": round(jnp_wall * 1e3, 2),
        "speedup_vs_jnp": round(jnp_wall / wall, 2),
        "tile_sweep": sweep,
    }


def bench_extend_prune() -> dict:
    """Fused extension-count-prune kernel (ops/pallas_extend.py) at the
    pair kernel's headline geometry: the same [2048 x 384] join matrix,
    with the threshold compare + survivor-mask pack fused into the
    epilogue.  The interesting numbers are the wall DELTA vs the unfused
    pair kernel (the epilogue is ~2e-5 relative VPU work — the model
    says free, this measures it) and the output-traffic shrink: dying
    lanes write zeros that never need a host copy, and the packed mask
    is 1/32 of the sup array."""
    import jax
    import jax.numpy as jnp

    from spark_fsm_tpu.ops import pallas_extend as PE
    from spark_fsm_tpu.ops import pallas_support as PS

    P, NI, W = 2048, 384, 1
    S = -(-77500 // PS.S_BLOCK) * PS.S_BLOCK
    k1, k2 = jax.random.split(jax.random.PRNGKey(11))
    bits = jax.jit(lambda k, s: jax.random.bernoulli(
        k, 0.06, s).astype(jnp.uint32), static_argnums=1)
    pt = jax.block_until_ready(bits(k1, (P, W, S)))
    items = jax.block_until_ready(bits(k2, (NI, W, S)))
    # threshold at a deep-wave prune rate: ~6% fill over 77.8k seqs
    # gives expected pair support ~280; thr=400 kills most lanes, the
    # regime the fusion exists for
    thr = jnp.int32(400)

    rt = _roundtrip_s()
    wall, walls = _amortized_wall(
        lambda: PE.extend_count_prune(pt, items, thr, NI)[0],
        roundtrip_s=rt)
    pair_wall, _ = _amortized_wall(
        lambda: PS.pair_supports(pt, items, NI), roundtrip_s=rt)
    gm = PE.grid_model(P, NI, W, S, items_rows=items.shape[0])
    model_bytes = gm["model_bytes"]

    # survivor accounting at this geometry: how much host-copy traffic
    # the in-kernel prune removes (zeroed sup lanes compress to nothing
    # useful; the engine reads candidates through the mask)
    sup, mask = jax.block_until_ready(
        PE.extend_count_prune(pt, items, thr, NI))
    survivors = int(jnp.sum(
        jnp.sum(jnp.unpackbits(mask.view(jnp.uint8)).astype(jnp.int32))))
    dead_bytes = 4 * (P * NI - survivors)

    return {
        "kernel": "extend_count_prune (ops/pallas_extend.py)",
        "geometry": f"P={P} NI={NI} S={S} W={W} "
                    f"tiles P_T={gm['p_tile']} I_T={gm['i_tile']} "
                    f"S_B={gm['s_block']} thr=400",
        "wall_ms": round(wall * 1e3, 2),
        "amortized_walls_s": walls,
        "traffic_model_bytes": int(model_bytes),
        "achieved_GBps": round(model_bytes / wall / 1e9, 1),
        "pct_v5e_hbm_peak": round(100 * model_bytes / wall / 1e9
                                  / V5E_HBM_GBPS, 1),
        "min_useful_bytes": int(gm["min_useful_bytes"]),
        "vpu_model": {
            "ops_per_word": PE.EXTEND_VPU_OPS_PER_WORD,
            "epilogue_ops_per_lane": PE.EPILOGUE_VPU_OPS_PER_LANE,
            "total_vpu_ops": int(gm["vpu_ops"]),
            "grid_steps": gm["grid_steps"],
        },
        "pair_supports_wall_ms": round(pair_wall * 1e3, 2),
        "fusion_overhead_pct": round(100 * (wall - pair_wall)
                                     / pair_wall, 2),
        "survivor_lanes": survivors,
        "pruned_writeback_bytes": int(dead_bytes),
    }


def bench_rule_supports() -> dict:
    """Headline TSR geometry: full-width (8192-candidate) km=1 launches
    over a Kosarak-shaped sequence axis (990k seqs, single word) against
    the top-M=512 item rows — the per-launch workload of the full-scale
    config-3 mine (38 such launches)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from spark_fsm_tpu.ops import pallas_tsr as PT

    M, C, km = 512, 8192, 1
    sb = PT.seq_block(1, 990_000)
    S = -(-990_000 // sb) * sb
    # on-device synthesis (see bench_pair_supports): p1/s1 are ~2 GB each
    k1, k2 = jax.random.split(jax.random.PRNGKey(9))

    @jax.jit
    def mk(k1, k2):
        p = jax.random.bernoulli(
            k1, 0.01, (M + 1, S // 128, 128)).astype(jnp.uint32)
        s = jax.random.bernoulli(
            k2, 0.5, (M + 1, S // 128, 128)).astype(jnp.uint32)
        # row M = the all-ones pad row (the AND identity for unused slots)
        return (p.at[M].set(jnp.uint32(0xFFFFFFFF)),
                s.at[M].set(jnp.uint32(0xFFFFFFFF)))

    p1, s1 = jax.block_until_ready(mk(k1, k2))
    rng = np.random.default_rng(9)
    xy = jnp.asarray(
        np.stack([rng.integers(0, M, (C, km)),
                  rng.integers(0, M, (C, km))], axis=1).astype(np.int32))

    rt = _roundtrip_s()
    wall, walls = _amortized_wall(
        lambda: PT.rule_supports(p1, s1, xy, km=km, s_block=sb),
        roundtrip_s=rt)
    model_bytes = C * S * 4 * 2 * km + 8 * C

    # jnp fallback: the gather evaluator the engine downgrades to, at its
    # real narrow width; extrapolated to the kernel's C (full width would
    # need C*S*4 = ~32 GB of gathered temps, which is WHY the kernel wins)
    chunk = 256
    xs = xy[:chunk, 0, 0]
    ys = xy[:chunk, 1, 0]
    p1f = p1.reshape(M + 1, -1)
    s1f = s1.reshape(M + 1, -1)

    @jax.jit
    def jnp_eval(p1f, s1f, xs, ys):
        # p1f/s1f MUST be arguments, not closure captures: jit lowers
        # captured device arrays as 4 GB of inline constants, which the
        # tunneled remote compiler then uploads (minutes) before compiling
        a = p1f[xs]                              # [chunk, S/32]
        cc = s1f[ys]
        shifted = a << jnp.uint32(1)             # single word, no carry
        sup = jnp.sum((shifted & cc) != 0, axis=1, dtype=jnp.int32)
        supx = jnp.sum(a != 0, axis=1, dtype=jnp.int32)
        return jnp.stack([sup, supx])

    jnp_wall_chunk, _ = _amortized_wall(
        lambda: jnp_eval(p1f, s1f, xs, ys), roundtrip_s=rt)
    jnp_wall = jnp_wall_chunk * (C / chunk)

    return {
        "kernel": "rule_supports (ops/pallas_tsr.py)",
        "geometry": f"C={C} M={M} S={S} km={km} W=1 sb={sb}",
        "wall_ms": round(wall * 1e3, 2),
        "amortized_walls_s": walls,
        "traffic_model_bytes": int(model_bytes),
        "achieved_GBps": round(model_bytes / wall / 1e9, 1),
        "pct_v5e_hbm_peak": round(100 * model_bytes / wall / 1e9
                                  / V5E_HBM_GBPS, 1),
        "jnp_wall_ms_extrapolated": round(jnp_wall * 1e3, 2),
        "jnp_chunk": chunk,
        "speedup_vs_jnp": round(jnp_wall / wall, 2),
    }


def main() -> None:
    from spark_fsm_tpu.utils.jitcache import enable_compile_cache

    enable_compile_cache()
    reason = tpu_probe(float(os.environ.get("BENCH_TPU_WAIT", "60")))
    if reason:
        sys.exit(f"bench_kernels: needs the real TPU ({reason}); "
                 "roofline numbers are meaningless elsewhere")
    import jax

    if jax.default_backend() != "tpu":
        sys.exit("bench_kernels: backend is not tpu")

    rows = []
    for bench in (bench_pair_supports, bench_extend_prune,
                  bench_rule_supports):
        rows.append(bench())
        print(json.dumps(rows[-1]), flush=True)
    if os.environ.get("BENCH_KERNELS_OUT") != "0":
        out = {
            "ts": round(time.time(), 1),
            "platform": "tpu",
            "hbm_peak_GBps_assumed": V5E_HBM_GBPS,
            "note": ("achieved_GBps divides the BlockSpec traffic model "
                     "by the median wall; pct_v5e_hbm_peak is that over "
                     "the 819 GB/s v5e figure.  Shared-host contention "
                     "swings walls — the per-run walls_s list shows the "
                     "session's spread."),
            "kernels": rows,
        }
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "KERNELS.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(out, fh, indent=2)
            fh.write("\n")
        os.replace(tmp, path)


if __name__ == "__main__":
    main()
